"""Runtime memory probe -> ``MEM_r<N>.json`` (the CI mem-probe job).

Graftlint pass 12 pins peak HBM *statically* from the buffer
assignment of every backend's compiled converge; this probe closes the
loop at runtime, the way ``tools/comm_probe.py`` does for the comm
wall:

- **8-dev dryrun**: every registered jax backend runs a REAL converge
  on the analyzer's 8-device CPU mesh at the analyzer's pinned scale,
  with the PR 6 memory-watermark watcher armed.  Measured peak per
  backend is the allocator's ``memory_stats()`` high-water mark where
  the platform reports one (TPU); platforms without allocator stats
  (CPU) degrade to the executed module's buffer-assignment peak — the
  allocation the runtime actually makes — recorded through the
  watcher's new ``record_converge_peak`` so the
  ``eigentrust_converge_peak_bytes{backend}`` gauge is populated
  either way.
- **2-process ``jax.distributed`` round**: two workers (gloo CPU, 2x4
  mesh) run a real cross-process ``converge_sharded``; each scrapes
  its OWN executable's memory analysis and asserts the per-process
  peak fits the per-shard MEM_INVARIANTS allowance — per-shard peak
  must scale as E/n_shards, the ROADMAP item 1 prerequisite.

Every backend's measured peak is asserted ``<= static budget`` at the
probe scale; any overrun, worker crash, or diverged score exits
non-zero.  The report carries sentinel-shaped ``entries``
(``peak_hbm_bytes`` / ``peak_hbm_bytes_per_shard``, lower-is-better)
so ``tools/perf_sentinel.py`` gates the recorded trajectory.

Run: ``python tools/mem_probe.py [--smoke] [--out MEM_rNN.json]``
"""

from __future__ import annotations

import argparse
import json
import os
import re
import resource
import socket
import subprocess
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

#: Devices per worker process; 2 workers -> the analyzer's 8-way mesh.
LOCAL_DEVICES = 4
N_PROCESSES = 2

#: Probe scale = the analyzer's first compile scale, so the committed
#: budgets apply without re-derivation.
PROBE_PEERS, PROBE_EDGES = 1024, 4096


def _ensure_cpu_mesh() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


def _next_round_path() -> str:
    rounds = [0]
    for p in REPO.glob("MEM_r*.json"):
        m = re.fullmatch(r"MEM_r(\d+)\.json", p.name)
        if m:
            rounds.append(int(m.group(1)))
    return str(REPO / f"MEM_r{max(rounds) + 1:02d}.json")


def _allocator_peak() -> int | None:
    """Summed ``peak_bytes_in_use`` over local devices, or None where
    the platform has no allocator stats (CPU)."""
    import jax

    try:
        stats = [d.memory_stats() for d in jax.local_devices()]
    except Exception:  # noqa: BLE001
        return None
    if not stats or any(s is None for s in stats):
        return None
    return sum(int(s.get("peak_bytes_in_use", 0)) for s in stats)


def _backend_round(name: str, budget) -> dict:
    """One backend: real converge on the 8-dev mesh + peak cross-check."""
    import numpy as np

    from protocol_tpu.analysis.comm.lowering import _graph, build_cases
    from protocol_tpu.analysis.memory.liveness import measured_view
    from protocol_tpu.obs.watchers import MEMORY_WATERMARKS
    from protocol_tpu.trust.backend import get_backend

    # The analyzer's executable for this backend at the probe scale:
    # its buffer assignment is the fallback measured signal, and its
    # dims evaluate the budget.
    case = build_cases(name)[0]
    view, source = measured_view(case)
    dims = case.dims
    static_budget = budget.max_resident(
        dims.get("n", 0), dims.get("edges", 0), dims.get("n_segments", 0),
        dims.get("n_rows", 0), dims.get("n_shards", 1),
    ) + budget.max_transient(
        dims.get("n", 0), dims.get("n_segments", 0), dims.get("n_rows", 0)
    )

    # Run the REAL converge through the trust-backend interface (the
    # node's code path, converge spans included) on the same synthetic
    # graph family the analyzer compiles.
    graph = _graph(PROBE_PEERS, PROBE_EDGES)
    backend = get_backend(name)
    result = backend.converge(graph, alpha=0.1, tol=1e-6, max_iter=8)
    scores = np.asarray(result.scores)
    l1 = float(scores.sum())

    alloc_peak = _allocator_peak()
    measured = alloc_peak if alloc_peak is not None else view["peak_bytes"]
    measured_source = "memory_stats" if alloc_peak is not None else source
    # Populate the per-backend gauge either way (the watcher's span
    # hook already did on allocator-stats platforms; this is the
    # explicit path for the rest).
    MEMORY_WATERMARKS.record_converge_peak(name, measured)

    ok = measured <= static_budget and abs(l1 - 1.0) < 1e-3
    return {
        "backend": name,
        "dims": dims,
        "iterations": int(result.iterations),
        "l1": l1,
        "measured_peak_bytes": int(measured),
        "measured_source": measured_source,
        "static_budget_bytes": static_budget,
        "buffer_assignment": view,
        "ok": bool(ok),
    }


def _worker(process_id: int, coordinator: str, out_path: str) -> int:
    """Distributed worker: one cross-process sharded converge + a
    per-shard peak scrape of its own executable."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={LOCAL_DEVICES}"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    result: dict = {"process_id": process_id, "ok": False}
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=N_PROCESSES,
            process_id=process_id,
        )
    except Exception as exc:  # old jaxlib: no multi-process CPU
        result.update(skipped=True, reason=repr(exc))
        Path(out_path).write_text(json.dumps(result))
        return 0

    import jax.numpy as jnp
    import numpy as np

    from protocol_tpu.analysis.budget import MEM_INVARIANTS
    from protocol_tpu.models.graphs import scale_free
    from protocol_tpu.parallel.mesh import SHARD_AXIS, default_mesh
    from protocol_tpu.parallel.sharded import (
        ShardedTrustProblem,
        _get_runner,
        converge_sharded,
    )

    backend = "tpu-sharded:tpu-csr"
    budget = MEM_INVARIANTS[backend]
    mesh = default_mesh()
    n_shards = mesh.shape[SHARD_AXIS]

    graph = scale_free(PROBE_PEERS, PROBE_EDGES, seed=1)
    prob = ShardedTrustProblem.build(graph, mesh)
    t, iters, resid = converge_sharded(prob, alpha=0.1, tol=1e-6, max_iter=8)
    scores = np.asarray(t)

    run = _get_runner(mesh, prob.n)
    comp = run.lower(
        prob.src, prob.w, prob.row_ptr, prob.t0(), prob.p, prob.dangling,
        jnp.asarray(0.1, jnp.float32), max_iter=8, tol=1e-6,
    ).compile()
    ma = comp.memory_analysis()
    violations: list[str] = []
    if ma is None:
        violations.append("executable exposes no memory analysis")
        per_shard_peak = -1
    else:
        resident = int(ma.argument_size_in_bytes)
        transient = (
            int(ma.temp_size_in_bytes)
            + int(ma.output_size_in_bytes)
            - int(ma.alias_size_in_bytes)
        )
        per_shard_peak = resident + transient
        e_pad = int(prob.src.shape[0])
        allow = budget.max_resident(prob.n, e_pad, 0, 0, n_shards)
        allow += budget.max_transient(prob.n, 0, 0)
        if resident > budget.max_resident(prob.n, e_pad, 0, 0, n_shards):
            violations.append(
                f"per-shard resident {resident} > E/n_shards allowance "
                f"{budget.max_resident(prob.n, e_pad, 0, 0, n_shards):.0f}"
            )
        if per_shard_peak > allow:
            violations.append(
                f"per-shard peak {per_shard_peak} > budget {allow:.0f}"
            )
        result.update(budget_bytes=allow)
    result.update(
        backend=backend,
        n=int(prob.n),
        n_shards=n_shards,
        iterations=int(iters),
        residual=float(resid),
        l1=float(scores.sum()),
        peak_hbm_bytes_per_shard=per_shard_peak,
        violations=violations,
        ok=bool(not violations and abs(float(scores.sum()) - 1.0) < 1e-3),
    )
    Path(out_path).write_text(json.dumps(result))
    return 0 if result["ok"] else 1


def _distributed_round(timeout: float) -> dict:
    coordinator = f"127.0.0.1:{_free_port()}"
    with tempfile.TemporaryDirectory() as tmp:
        outs = [str(Path(tmp) / f"worker{i}.json") for i in range(N_PROCESSES)]
        procs = [
            subprocess.Popen(
                [
                    sys.executable, __file__,
                    "--worker", str(i),
                    "--coordinator", coordinator,
                    "--worker-out", outs[i],
                ],
                cwd=REPO,
            )
            for i in range(N_PROCESSES)
        ]
        rcs = []
        for p in procs:
            try:
                rcs.append(p.wait(timeout=timeout))
            except subprocess.TimeoutExpired:
                p.kill()
                rcs.append(-9)
        workers = []
        for path in outs:
            try:
                workers.append(json.loads(Path(path).read_text()))
            except (OSError, json.JSONDecodeError):
                workers.append({"ok": False, "error": "no worker report"})
    skipped = all(w.get("skipped") for w in workers)
    ok = skipped or (
        all(rc == 0 for rc in rcs) and all(w.get("ok") for w in workers)
    )
    if ok and not skipped:
        resids = [w["residual"] for w in workers]
        if abs(resids[0] - resids[1]) > 1e-9:
            ok = False
            workers.append({"error": f"residual divergence: {resids}"})
    return {
        "mesh": f"{N_PROCESSES}x{LOCAL_DEVICES}",
        "ok": ok,
        "skipped": skipped,
        "return_codes": rcs,
        "workers": workers,
    }


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=None, help="report path (default: next MEM_r<N>.json)")
    ap.add_argument(
        "--smoke", action="store_true",
        help="CI smoke: same scales, distinct metric strings so the "
        "sentinel never cross-compares smoke vs recorded rounds",
    )
    ap.add_argument("--timeout", type=float, default=600.0)
    ap.add_argument("--worker", type=int, default=None, help=argparse.SUPPRESS)
    ap.add_argument("--coordinator", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--worker-out", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.worker is not None:
        return _worker(args.worker, args.coordinator, args.worker_out)

    _ensure_cpu_mesh()
    from protocol_tpu.analysis.budget import MEM_INVARIANTS, NON_JAX_BACKENDS
    from protocol_tpu.obs.watchers import MEMORY_WATERMARKS
    from protocol_tpu.parallel import sharded  # noqa: F401  (declares budgets)
    from protocol_tpu.trust.backend import registered_backends

    tag = "smoke" if args.smoke else "8-dev CPU mesh"
    rounds = []
    for name in registered_backends():
        if name in NON_JAX_BACKENDS:
            continue
        rounds.append(_backend_round(name, MEM_INVARIANTS[name]))

    dist = _distributed_round(args.timeout)

    entries = [
        {
            "metric": (
                f"converge peak HBM bytes ({r['backend']}, {tag}, "
                f"{PROBE_PEERS} peers/{PROBE_EDGES} edges)"
            ),
            "peak_hbm_bytes": r["measured_peak_bytes"],
            "unit": "bytes",
        }
        for r in rounds
    ]
    for w in dist["workers"]:
        if w.get("peak_hbm_bytes_per_shard", -1) > 0 and w["process_id"] == 0:
            entries.append({
                "metric": (
                    f"per-shard converge peak HBM bytes "
                    f"({w['backend']}, 2-process jax.distributed, {tag}, "
                    f"{PROBE_PEERS} peers/{PROBE_EDGES} edges)"
                ),
                "peak_hbm_bytes_per_shard": w["peak_hbm_bytes_per_shard"],
                "unit": "bytes",
            })

    ok = all(r["ok"] for r in rounds) and dist["ok"]
    report = {
        "tool": "mem_probe",
        "ok": ok,
        "scale": {"peers": PROBE_PEERS, "edges": PROBE_EDGES},
        "backends": rounds,
        "distributed": dist,
        "converge_peak_gauge": MEMORY_WATERMARKS.converge_peaks(),
        "ru_maxrss_kb": resource.getrusage(resource.RUSAGE_SELF).ru_maxrss,
        "entries": entries,
    }
    out = args.out or _next_round_path()
    Path(out).write_text(json.dumps(report, indent=2) + "\n")
    status = "OK" if ok else "FAILED"
    worst = max(
        (r["measured_peak_bytes"] / r["static_budget_bytes"] for r in rounds),
        default=0.0,
    )
    print(
        f"mem_probe: {status} — {len(rounds)} backends measured <= static "
        f"budget (worst fill {worst:.1%}), distributed "
        f"{'SKIPPED' if dist['skipped'] else 'OK' if dist['ok'] else 'FAILED'} "
        f"({out})"
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
