"""Offline params/verifier generator — the reference's ``circuit``
binary analog (circuit/src/main.rs:16-106): generates the KZG SRS file
(params-{k}.bin analog), compiles the epoch circuit's proving key from
it, emits the EVM verifier contract artifact (et_verifier.bin analog)
and a sample proof (et_proof.json analog), all into data/.

Usage:  python tools/gen_et_verifier.py [--data-dir data] [--k 15]

A node booted with ``"prover": "plonk", "srs_path": "data/srs-15.bin"``
then serves proofs that verify against the emitted artifact — clients
run them through the in-process EVM (EtVerifierWrapper flow).
"""

from __future__ import annotations

import argparse
import secrets
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", default="data")
    ap.add_argument("--k", type=int, default=15, help="SRS size (2^k points)")
    args = ap.parse_args()
    data = Path(args.data_dir)
    data.mkdir(exist_ok=True)

    from protocol_tpu.zk.kzg import Setup
    from protocol_tpu.zk.proof import PlonkEpochProver, Proof

    srs_path = data / f"srs-{args.k}.bin"
    if srs_path.exists():
        print(f"loading existing SRS {srs_path}")
        srs = Setup.from_bytes(srs_path.read_bytes())
    else:
        t0 = time.time()
        srs = Setup.generate(args.k, seed=secrets.token_bytes(32))
        srs_path.write_bytes(srs.to_bytes())
        print(f"SRS 2^{args.k} generated in {time.time() - t0:.1f}s -> {srs_path}")

    t0 = time.time()
    prover = PlonkEpochProver(srs=srs)
    print(f"keygen in {time.time() - t0:.1f}s")

    t0 = time.time()
    gen, pub, proof = prover.generate_verifier_artifact()
    out = data / "et_verifier.bin"
    out.write_bytes(gen.to_bytes())
    print(
        f"verifier artifact in {time.time() - t0:.1f}s -> {out} "
        f"({len(gen.runtime)} bytes runtime, n_t={gen.n_t})"
    )

    # Sample proof over the dummy statement (et_proof.json analog) —
    # the one generate_verifier_artifact already produced.
    (data / "et_proof.json").write_text(
        Proof(pub_ins=pub, proof=proof).to_raw().to_json()
    )
    from protocol_tpu.zk.evm_verifier import evm_verify

    ok, gas = evm_verify(gen, pub, proof)
    print(f"sample proof verifies on EVM: {ok} (gas {gas})")
    assert ok


if __name__ == "__main__":
    main()
