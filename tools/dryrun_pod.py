"""Pod scale-out dryrun: a multi-process ``jax.distributed`` churned
epoch replay over host-sharded edge partitions (ROADMAP item 1,
PERF.md §20 — the ISSUE 16 acceptance tool).

The parent spawns ``--processes`` workers.  Each worker is one pod
host: it initializes ``jax.distributed`` (gloo CPU collectives,
``--local-devices`` forced host devices), derives the identical
deterministic attestation stream (``models.churn`` sender-centric
epochs), and per epoch

- journals **only the churn rows whose source peer it owns**
  (``parallel.partition`` rendezvous hash) into its own per-host WAL
  shard, fsyncs, and acknowledges them (``acks-h*.jsonl``);
- builds its **local** window plan only (``parallel.pod.PodWindowPlan``
  — reuse/delta/rebuild against the local fingerprint, so churn owned
  by other hosts never forces a rebuild here) and assembles the global
  sharded arrays without moving an edge byte across hosts;
- runs the identical ``converge_sharded`` windowed runner across the
  whole pod (one boundary-completing psum per step) with a warm start
  from the previous fixed point;
- checkpoints its local graph shard + plan (``CheckpointStore``),
  publishes its shard stamp, and host 0 seals the epoch into the pod
  manifest (``node.pod.PodDurability``).

The first executed epoch is also **scraped**: the worker compiles its
own runner AOT and judges the module with the real graftlint passes —
``check_comm_case`` (collective kinds/counts/bytes, donation aliases,
replica-group coverage over the *multi-process* mesh) and
``check_mem_case`` (per-shard resident/transient vs MEM_INVARIANTS,
plus the ``pod_budget_view`` allowance the measured peak must clear).

The parent asserts **per-epoch residuals and score digests are
bit-identical across all workers** (every host holds the replicated
result; the pod either agrees exactly or is broken), aggregates a
reference subprocess (serial full-graph plan build vs per-partition
builds → ``plan_build_seconds`` sentinel series; a single-host run at
1/H scale for the flat-epoch-seconds comparison; a full-scale cold
converge for the L1 correctness pin), and emits sentinel-shaped
``entries`` keyed on ``n_hosts``.

``--chaos-host-loss`` adds the crash-matrix host-loss row: a second
run crashes one worker mid-epoch (``os._exit`` after WAL ack, before
converge — the kill -9 analog), the parent reaps the stuck survivors,
relaunches the whole pod with ``--resume``, and requires (a) zero
acknowledged attestations lost — every acked epoch past the sealed
manifest replays from the host's WAL shard with the exact payload
digest — and (b) a final fixed point **bit-identical** to the
uncrashed control run.

Run::

    python tools/dryrun_pod.py --smoke --out POD_smoke.json
    python tools/dryrun_pod.py --smoke --chaos-host-loss --out POD_smoke.json
    python tools/dryrun_pod.py --peers 20000 --edges 160000 --epochs 4 \
        --chaos-host-loss --round 1 --out POD_r01.json

Exit 0 = every invariant held (or the jax build has no multi-process
CPU collectives: ``skipped``); 1 = divergence, budget violation, or
lost acknowledged data.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import socket
import struct
import subprocess
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

BACKEND = "tpu-sharded:tpu-windowed"

#: L1 bar for pod-warm vs single-host-cold scores (the bench.py
#: ``warm_vs_cold_l1`` doctrine / crash_matrix SCORE_TOL).
SCORE_TOL = 1e-4

#: WAL record header: epoch u32, row u32, out-degree u32, then
#: ``deg`` destinations (u32) and ``deg`` weights (f32).  Genuinely
#: reconstructive — recovery re-derives the row's out-edges from the
#: record alone, no side channel.
_HDR = struct.Struct("<III")


def encode_row(epoch: int, row: int, dst, w) -> bytes:
    import numpy as np

    dst = np.asarray(dst, "<u4")
    return _HDR.pack(epoch, row, dst.size) + dst.tobytes() + (
        np.asarray(w, "<f4").tobytes()
    )


def decode_row(payload: bytes):
    import numpy as np

    epoch, row, deg = _HDR.unpack_from(payload)
    off = _HDR.size
    dst = np.frombuffer(payload, "<u4", deg, off)
    w = np.frombuffer(payload, "<f4", deg, off + 4 * deg)
    return epoch, row, dst, w


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _jsonable(o):
    import numpy as np

    if isinstance(o, np.integer):
        return int(o)
    if isinstance(o, np.floating):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(f"not JSON-serializable: {type(o)}")


# ---------------------------------------------------------------------------
# deterministic stream — every host derives the identical epochs
# ---------------------------------------------------------------------------


def _epoch_rng(seed: int, epoch: int):
    import numpy as np

    # Independent per-epoch seeding (not one carried generator): any
    # host — including one recovering from a crash — regenerates epoch
    # e without replaying epochs < e.
    return np.random.default_rng((seed + 1) * 1_000_003 + epoch)


def bootstrap_graph(args):
    from protocol_tpu.models.graphs import scale_free

    return scale_free(args.peers, args.edges, seed=args.seed).drop_self_edges()


def churn_epoch(cur, epoch: int, args):
    from protocol_tpu.models.churn import churn_cohort_dims, sender_centric_churn

    cohort_size, deg = churn_cohort_dims(cur, args.churn)
    return sender_centric_churn(
        _epoch_rng(args.seed, epoch), cur, cohort_size=cohort_size, deg=deg
    )


# ---------------------------------------------------------------------------
# worker (one pod host)
# ---------------------------------------------------------------------------


def _scrape(podplan, n_edges: int):
    """Compile this process's runner AOT and judge the module with the
    real graftlint comm + memory passes over the multi-process mesh."""
    from dataclasses import asdict
    from functools import partial

    import jax
    import numpy as np
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from protocol_tpu.analysis.budget import COMM_INVARIANTS, MEM_INVARIANTS
    from protocol_tpu.analysis.comm.checker import check_comm_case
    from protocol_tpu.analysis.comm.lowering import (
        CommCase,
        _jaxpr_psums,
        _mem_stats,
    )
    from protocol_tpu.analysis.memory.checker import (
        check_mem_case,
        pod_budget_view,
    )
    from protocol_tpu.parallel.mesh import SHARD_AXIS
    from protocol_tpu.parallel.sharded import _get_windowed_runner

    run = _get_windowed_runner(
        podplan.mesh, podplan.n, podplan.rows_per_shard,
        podplan.table_entries, podplan.interpret,
    )
    alpha = jax.device_put(np.float32(0.1), NamedSharding(podplan.mesh, P()))
    plan_args = (
        podplan.wid, podplan.local, podplan.weight, podplan.seg_end,
        podplan.seg_first, podplan.seg_perm, podplan.dst_ptr,
        podplan.t0(), podplan.p, podplan.dangling, alpha,
    )
    kw = dict(max_iter=4, tol=1e-6)
    comp = run.lower(*plan_args, **kw).compile()
    jaxpr = jax.make_jaxpr(partial(run, **kw))(*plan_args)
    n_shards = podplan.mesh.shape[SHARD_AXIS]
    case = CommCase(
        backend=BACKEND,
        dims={
            "n": podplan.n,
            "edges": n_edges,
            "n_segments": podplan.s_max,
            "n_rows": podplan.rows_per_shard,
            "n_shards": n_shards,
        },
        module_text=comp.as_text(),
        arg_names=(
            "wid", "local", "weight", "seg_end", "seg_first", "seg_perm",
            "dst_ptr", "t0", "p", "dangling", "alpha",
        ),
        jaxpr_psums=_jaxpr_psums(jaxpr),
        mem=_mem_stats(comp),
    )
    comm_findings, comm_record = check_comm_case(COMM_INVARIANTS[BACKEND], case)
    mem_findings, mem_record = check_mem_case(MEM_INVARIANTS[BACKEND], case)
    pod_view = pod_budget_view(
        MEM_INVARIANTS[BACKEND],
        n=podplan.n, edges=n_edges, n_segments=podplan.s_max,
        rows=podplan.rows_per_shard, n_shards=n_shards,
        n_hosts=podplan.n_hosts,
    )
    findings = comm_findings + mem_findings
    peak = mem_record.get("measured", {}).get("peak_bytes")
    return {
        "comm": comm_record,
        "mem": mem_record,
        "pod_budget": pod_view,
        "peak_within_pod_budget": (
            peak is not None and peak <= pod_view["peak_bytes"]
        ),
        "findings": [asdict(f) for f in findings],
        "ok": not findings
        and peak is not None
        and peak <= pod_view["peak_bytes"],
    }


def worker_main(args) -> int:
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={args.local_devices}"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    out = Path(args.worker_out)
    result: dict = {"process_id": args.worker, "ok": False}
    if args.processes > 1:
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
            jax.distributed.initialize(
                coordinator_address=args.coordinator,
                num_processes=args.processes,
                process_id=args.worker,
            )
        except Exception as exc:  # old jaxlib: no multi-process CPU
            result.update(skipped=True, reason=repr(exc))
            out.write_text(json.dumps(result))
            return 0

    import numpy as np

    from protocol_tpu.node.epoch import Epoch
    from protocol_tpu.node.pod import PodDurability
    from protocol_tpu.obs import metrics as obs_metrics
    from protocol_tpu.obs import podtrace as obs_podtrace
    from protocol_tpu.obs.fleet import load_directory, publish_snapshot
    from protocol_tpu.obs.journal import JOURNAL, install_worker_dump_handler
    from protocol_tpu.obs.slo import SLO_ENGINE, install_pod_defaults
    from protocol_tpu.obs.trace import TRACER
    from protocol_tpu.obs.watchers import STRAGGLERS
    from protocol_tpu.parallel.mesh import SHARD_AXIS
    from protocol_tpu.parallel.pod import PodContext, PodWindowPlan
    from protocol_tpu.parallel.sharded import converge_sharded
    from protocol_tpu.trust.graph import TrustGraph

    pod = PodContext.current(seed=args.seed)
    host = pod.host_id
    obs_metrics.POD_HOSTS.set(pod.n_hosts)
    obs_metrics.POD_HOST_ID.set(host)
    pd = PodDurability(args.state_dir, host, pod.n_hosts)
    ack_path = Path(args.state_dir) / f"acks-h{host:03d}.jsonl"

    # Pod observability plumbing (ISSUE 19): per-epoch trace + metric
    # snapshots ride the shared exchange; flight-recorder rings dump
    # per epoch (and on SIGTERM) so a crashed host's last events
    # survive for the parent to collect.
    fleet_dir = Path(args.state_dir) / "fleet"
    flight_dir = Path(args.state_dir) / "flight"
    flight_dir.mkdir(parents=True, exist_ok=True)
    install_worker_dump_handler(flight_dir, "pod")
    straggler_host = min(1, pod.n_hosts - 1) if args.seed_straggler else -1
    if host == 0:
        STRAGGLERS.configure(
            ratio=args.straggler_ratio, k=args.straggler_k, min_seconds=0.1
        )

    # -- recovery (resume runs): newest sealed manifest + own shards --
    start_epoch, scores, plan = 0, None, None
    last_seq = -1
    replayed: dict[int, dict] = {}
    recovery = None
    manifest = None
    if args.resume:
        t_rec = time.perf_counter()
        manifest = pd.load_manifest()
        lost: list[int] = []
        if manifest is not None:
            stamp = pd.my_stamp(manifest)
            snap = pd.checkpoints.load(Epoch(int(manifest["epoch"])))
            scores, plan = snap.scores, snap.plan
            start_epoch = int(manifest["epoch"]) + 1
            last_seq = int(stamp["wal_seq"])
            # The WAL tail past the sealed stamp, digested per epoch.
            tail: dict[int, "hashlib._Hash"] = {}
            tail_counts: dict[int, int] = {}
            for seq, payload in pd.wal.replay(after_seq=last_seq):
                e = _HDR.unpack_from(payload)[0]
                tail.setdefault(e, hashlib.sha256()).update(payload)
                tail_counts[e] = tail_counts.get(e, 0) + 1
                last_seq = seq
            # Zero acknowledged loss: every acked epoch past the
            # manifest must replay from this host's shard bit-exactly.
            acks = []
            if ack_path.exists():
                acks = [
                    json.loads(line)
                    for line in ack_path.read_text().splitlines()
                    if line.strip()
                ]
            for rec in acks:
                if rec["epoch"] <= int(manifest["epoch"]):
                    continue  # inside the checkpoint shard
                got = tail.get(rec["epoch"])
                if got is None or got.hexdigest() != rec["digest"]:
                    lost.append(rec["epoch"])
                else:
                    replayed[rec["epoch"]] = rec
            recovery = {
                "seconds": round(time.perf_counter() - t_rec, 4),
                "manifest_epoch": int(manifest["epoch"]),
                "resume_epoch": start_epoch,
                "wal_tail_records": int(sum(tail_counts.values())),
                "acked_epochs_replayed": sorted(replayed),
                "lost_acked_epochs": lost,
            }
        else:
            recovery = {
                "seconds": round(time.perf_counter() - t_rec, 4),
                "resume_epoch": 0,
                "cold": True,
                "lost_acked_epochs": lost,
            }

    # -- regenerate the deterministic stream up to the resume point --
    cur = bootstrap_graph(args)
    for e in range(1, start_epoch):
        _, cur, _ = churn_epoch(cur, e, args)
    owner = pod.partition.assign_ids(cur.n)
    if manifest is not None:
        # The checkpoint shard must equal the stream-derived local
        # partition column-for-column (recovery is reconstruction, not
        # trust).
        m = owner[cur.src] == host
        g = snap.graph
        recovery["checkpoint_matches_stream"] = bool(
            np.array_equal(g.src, cur.src[m])
            and np.array_equal(g.dst, cur.dst[m])
            and np.array_equal(g.weight, cur.weight[m])
        )

    epochs_detail = []
    stitch_reports: list[dict] = []
    scrape = None
    prev_dims = None
    ok = True
    for e in range(start_epoch, args.epochs):
        rows = None
        owned_count = 0
        if e > 0:
            rows, cur, (ns, nd, nw) = churn_epoch(cur, e, args)
        t_epoch = time.perf_counter()
        with TRACER.epoch(e):
            if e > 0:
                with TRACER.span("wal_flush"):
                    deg = ns.shape[0] // rows.shape[0]
                    owned_idx = np.flatnonzero(owner[rows] == host)
                    owned_count = int(owned_idx.size)
                    payloads = [
                        encode_row(
                            e, int(rows[i]),
                            nd[i * deg:(i + 1) * deg],
                            nw[i * deg:(i + 1) * deg],
                        )
                        for i in owned_idx
                    ]
                    digest = hashlib.sha256(b"".join(payloads)).hexdigest()
                    if e in replayed:
                        # Already durable + acknowledged before the
                        # crash; the recovery audit verified the WAL
                        # shard replays it, so re-journaling would only
                        # duplicate records.  The regenerated stream
                        # must still agree with what was acked — the
                        # reconstruction cross-check.
                        if replayed[e]["digest"] != digest:
                            recovery.setdefault(
                                "replay_stream_mismatch", []
                            ).append(e)
                            ok = False
                    else:
                        for pbytes in payloads:
                            last_seq = pd.wal.append(pbytes, flush=False)
                        pd.wal.flush()
                        with ack_path.open("a") as f:
                            f.write(json.dumps({
                                "epoch": e,
                                "count": len(payloads),
                                "digest": digest,
                                "wal_to": last_seq,
                            }) + "\n")
                            f.flush()
                            os.fsync(f.fileno())
                if args.crash_host == host and args.crash_epoch == e:
                    # kill -9 analog: acked data is on disk, nothing else
                    # is — recovery must replay exactly this epoch's rows.
                    # The flight ring dump is the crash handler's last
                    # act (same contract as the SIGTERM dump handler).
                    JOURNAL.dump(
                        flight_dir / f"flight-pod-h{host:03d}.jsonl",
                        reason=f"crash-epoch-{e}",
                    )
                    os._exit(137)

            with TRACER.span("plan"):
                t_plan = time.perf_counter()
                # time.monotonic (not perf_counter) so the barrier and
                # sync stamps share a clock base with the
                # clock_sync_samples() pairs the stitcher aligns on.
                podplan = PodWindowPlan.build(
                    cur, pod, plan=plan, delta_rows=rows,
                    clock=time.monotonic, wall=time.time,
                )
                plan_update_seconds = time.perf_counter() - t_plan
                plan = podplan.plan

            dims = (podplan.rows_per_shard, podplan.table_entries,
                    podplan.s_max)
            with TRACER.span("converge"):
                if dims != prev_dims:
                    # Eat the jit compile outside the timed region
                    # (bench.py's warm-up policy); recompiles are
                    # counted per epoch.
                    converge_sharded(
                        podplan, alpha=0.1, tol=args.tol,
                        max_iter=args.max_iter, t0=scores,
                    )
                t_conv = time.perf_counter()
                t, iters, resid = converge_sharded(
                    podplan, alpha=0.1, tol=args.tol,
                    max_iter=args.max_iter, t0=scores,
                )
                converge_seconds = time.perf_counter() - t_conv
            scores = np.asarray(t)

            if scrape is None and not args.skip_scrape:
                with TRACER.span("scrape"):
                    scrape = _scrape(podplan, int(cur.nnz))
                ok = ok and scrape["ok"]

            # Durability: local shard checkpoint -> stamp -> host 0 seal.
            with TRACER.span("checkpoint"):
                if host == straggler_host and e > 0:
                    # Seeded straggler rides a LOCAL phase: converge is
                    # collective-synchronized, so a pre-converge sleep
                    # would elongate every host's converge span equally
                    # and produce zero skew.
                    time.sleep(args.straggler_sleep)
                m = owner[cur.src] == host
                lg = TrustGraph(
                    cur.n, cur.src[m], cur.dst[m], cur.weight[m],
                    cur.pre_trusted,
                )
                pd.checkpoints.save(
                    Epoch(e), lg, scores=scores, plan=plan, wal_seq=last_seq
                )
                entry = pd.checkpoints.manifest_entry(Epoch(e))
                sdig = hashlib.sha256(scores.tobytes()).hexdigest()
                pd.publish_shard(
                    e, wal_seq=last_seq, columns=entry["columns"],
                    extra={"scores_sha256": sdig, "residual": float(resid)},
                )
            sealed = None
            if host == 0:
                # Sealing waits on every host's stamp, so it gets its
                # OWN span — folded into `checkpoint` it would read as
                # host-0 checkpoint skew whenever a peer runs late.
                with TRACER.span("seal"):
                    deadline = time.monotonic() + args.seal_timeout
                    while sealed is None and time.monotonic() < deadline:
                        sealed = pd.seal_epoch(e)
                        if sealed is None:
                            time.sleep(0.02)
                ok = ok and sealed is not None

        epoch_seconds = time.perf_counter() - t_epoch
        obs_metrics.POD_OWNED_PEERS.set(int((owner == host).sum()))
        obs_metrics.POD_LOCAL_EDGES.set(podplan.local_edges)
        obs_metrics.POD_PLAN_BUILD_SECONDS.set(podplan.build_seconds)
        obs_metrics.POD_PLAN_REUSED.inc(outcome=podplan.plan_outcome)
        obs_metrics.POD_EPOCH_SECONDS.set(epoch_seconds)
        if sealed is not None:
            obs_metrics.POD_MANIFESTS_SEALED.inc()

        # Pod obs exchange: ship this epoch's span tree + clock-sync
        # burst + barrier probe, refresh the heartbeat snapshot, dump
        # the flight ring (the parent collects the tails), and — host 0
        # — stitch the pod trace once every host has published.
        t_obs = time.perf_counter()
        sync = obs_podtrace.clock_sync_samples()
        if podplan.sync_unix > 0.0:
            sync.append({
                "monotonic": podplan.sync_monotonic,
                "unix": podplan.sync_unix,
            })
        obs_podtrace.publish_epoch_trace(
            fleet_dir, host, e,
            sync=sync,
            barrier={
                "enter_monotonic": podplan.barrier_enter_monotonic,
                "wait_seconds": podplan.barrier_wait_seconds,
            },
        )
        publish_snapshot(fleet_dir, f"h{host:03d}")
        obs_publish_seconds = time.perf_counter() - t_obs

        stitch_summary = None
        if host == 0 and pod.n_hosts > 1:
            obs_deadline = time.monotonic() + args.seal_timeout
            while (
                len(obs_podtrace.directory_hosts(fleet_dir, e)) < pod.n_hosts
                and time.monotonic() < obs_deadline
            ):
                time.sleep(0.02)
            stitched = obs_podtrace.stitch_epoch(
                fleet_dir, e, expected_hosts=pod.n_hosts, graft_into=TRACER
            )
            load_directory(fleet_dir, skip_pid=os.getpid(), max_age_s=30.0)
            if stitched is not None:
                stitch_summary = {
                    "epoch": e,
                    "complete": stitched["complete"],
                    "missing_hosts": stitched["missing_hosts"],
                    "stitch_seconds": stitched["stitch_seconds"],
                    "phase_skew_s": stitched["phase_skew_s"],
                    "barrier_spread_s": stitched["barrier"]["spread_s"],
                    "phase_attribution": stitched["phase_attribution"],
                    "stragglers": stitched.get("stragglers", []),
                }
                stitch_reports.append(stitch_summary)

        epochs_detail.append({
            "epoch": e,
            "seconds": round(epoch_seconds, 4),
            "plan_update_seconds": round(plan_update_seconds, 4),
            "converge_seconds": round(converge_seconds, 4),
            "iterations": int(iters),
            "residual": float(resid),
            "scores_sha256": sdig,
            "plan_outcome": podplan.plan_outcome,
            "local_plan_build_seconds": round(podplan.build_seconds, 4),
            "local_edges": int(podplan.local_edges),
            "owned_rows": owned_count,
            "recompiled": dims != prev_dims,
            "sealed": (sealed is not None) if host == 0 else None,
            "phase_seconds": {
                p: round(d, 4)
                for p, d in obs_podtrace.phase_durations(
                    TRACER.get_trace(e) or {}
                ).items()
            },
            "obs_publish_seconds": round(obs_publish_seconds, 4),
            "stitch": stitch_summary,
        })
        prev_dims = dims

    # End-of-run flight ring dump — the per-host tail the parent ships
    # into the pod artifact (crash paths dump via the crash-exit hook
    # and the SIGTERM handler instead).
    JOURNAL.dump(
        flight_dir / f"flight-pod-h{host:03d}.jsonl", reason="run-end"
    )

    if args.dump_scores and host == 0:
        np.save(args.dump_scores, scores)

    if recovery is not None:
        ok = ok and not recovery["lost_acked_epochs"]
        ok = ok and recovery.get("checkpoint_matches_stream", True)
    ok = ok and abs(float(scores.sum()) - 1.0) < 1e-3

    # -- pod obs verdict (host 0, multi-host pods) --------------------
    pod_obs = None
    if host == 0 and pod.n_hosts > 1 and stitch_reports:
        install_pod_defaults(
            phase_skew_p99_s=args.skew_slo_target, heartbeat_max_age_s=30.0
        )
        slo_doc = SLO_ENGINE.evaluate()
        flagged = sorted(STRAGGLERS.flagged())
        complete_all = all(r["complete"] for r in stitch_reports)
        steady = [r for r in stitch_reports if r["epoch"] > 0]
        attrs = [
            v for r in steady for v in r["phase_attribution"].values()
        ]
        min_attr = min(attrs) if attrs else None
        # Serve through the real node route — the acceptance probe is
        # GET /trace/pod/latest with every host present in the stitch.
        from protocol_tpu.node.server import handle_request

        status_code, body = handle_request("GET", "/trace/pod/latest", None)
        trace_latest = json.loads(body) if status_code == 200 else None
        served_ok = (
            status_code == 200
            and trace_latest is not None
            and sorted(trace_latest.get("hosts", []))
            == list(range(pod.n_hosts))
        )
        # Obs overhead: publish + stitch cost against the steady epoch
        # wall-clock (the <1% acceptance bar; seeded runs are skewed by
        # design, so the bar applies to clean runs only).
        steady_detail = [d for d in epochs_detail if d["epoch"] > 0]
        obs_cost = sum(
            d["obs_publish_seconds"]
            + ((d.get("stitch") or {}).get("stitch_seconds") or 0.0)
            for d in steady_detail
        )
        steady_seconds = sum(d["seconds"] for d in steady_detail)
        overhead_pct = (
            round(100.0 * obs_cost / steady_seconds, 4)
            if steady_detail and steady_seconds
            else None
        )
        pod_obs = {
            "stitch_reports": stitch_reports,
            "stitch_complete": complete_all,
            "min_phase_attribution": min_attr,
            "trace_pod_served": served_ok,
            "trace_latest": trace_latest,
            "obs_overhead_pct": overhead_pct,
            "straggler_flagged": flagged,
            "seeded_straggler": bool(args.seed_straggler),
            "skew_slo": slo_doc["objectives"].get("pod-phase-skew-p99"),
            "slo_ok": slo_doc["ok"],
            "slo": {
                name: {"ok": obj["ok"], "value": obj["value"]}
                for name, obj in slo_doc["objectives"].items()
            },
        }
        if args.seed_straggler:
            pod_obs["seeded_straggler_fired"] = bool(flagged) and not (
                slo_doc["objectives"]
                .get("pod-phase-skew-p99", {})
                .get("ok", True)
            )
        # Gate: a complete served stitch with green SLOs and no
        # straggler is the healthy verdict; a seeded straggler MUST
        # flip it (the CI must-fail leg checks exit-nonzero).
        ok = ok and complete_all and served_ok
        ok = ok and slo_doc["ok"] and not flagged
        if not args.seed_straggler:
            ok = ok and (min_attr is None or min_attr >= 0.9)
            ok = ok and (overhead_pct is None or overhead_pct < 1.0)
        result.update(pod_obs=pod_obs)

    result.update(
        backend=BACKEND,
        n_hosts=pod.n_hosts,
        host_id=host,
        local_devices=len(jax.local_devices()),
        global_devices=len(jax.devices()),
        n_shards=pod.mesh.shape[SHARD_AXIS],
        n=int(cur.n),
        edges=int(cur.nnz),
        owned_peers=int((owner == host).sum()),
        epochs=epochs_detail,
        recovery=recovery,
        scrape=scrape,
        final_scores_sha256=hashlib.sha256(scores.tobytes()).hexdigest(),
        l1=float(scores.sum()),
        ok=bool(ok),
    )
    out.write_text(json.dumps(result, default=_jsonable))
    return 0 if ok else 1


# ---------------------------------------------------------------------------
# reference (single subprocess): serial-vs-partitioned plan build,
# 1/H-scale single host, full-scale cold correctness pin
# ---------------------------------------------------------------------------


def reference_main(args) -> int:
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={args.local_devices}"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from protocol_tpu.ops.gather_window import build_window_plan
    from protocol_tpu.parallel.partition import HostPartition
    from protocol_tpu.parallel.pod import PodContext, PodWindowPlan
    from protocol_tpu.parallel.sharded import converge_sharded

    result: dict = {}
    cur = bootstrap_graph(args)
    w, _ = cur.row_normalized()

    # Serial full build (the PERF.md §11 bottleneck) vs the per-host
    # partitioned builds, run back-to-back in ONE process so neither
    # side pays multi-process core contention: the pod's plan-build
    # critical path is the slowest partition.
    t0 = time.perf_counter()
    build_window_plan(cur.src, cur.dst, w, n=cur.n)
    serial = time.perf_counter() - t0
    owner = HostPartition(args.processes, seed=args.seed).assign_ids(cur.n)
    per_part = []
    for h in range(args.processes):
        m = owner[cur.src] == h
        t0 = time.perf_counter()
        build_window_plan(cur.src[m], cur.dst[m], w[m], n=cur.n)
        per_part.append(round(time.perf_counter() - t0, 4))
    critical = max(per_part)
    result.update(
        serial_plan_build_seconds=round(serial, 4),
        partitioned_plan_build_seconds=per_part,
        plan_build_seconds=critical,
        plan_build_speedup=round(serial / max(critical, 1e-9), 3),
    )

    # Full-scale cold converge of the FINAL churned graph — the
    # correctness pin the pod's warm fixed point must match in L1.
    for e in range(1, args.epochs):
        _, cur, _ = churn_epoch(cur, e, args)
    pod = PodContext.current(seed=args.seed)  # single process
    podplan = PodWindowPlan.build(cur, pod, clock=time.perf_counter)
    t, iters, resid = converge_sharded(
        podplan, alpha=0.1, tol=args.tol, max_iter=args.max_iter
    )
    np.save(args.dump_scores, np.asarray(t))
    result.update(ref_iterations=int(iters), ref_residual=float(resid))
    Path(args.worker_out).write_text(json.dumps(result, default=_jsonable))
    return 0


# ---------------------------------------------------------------------------
# parent orchestration
# ---------------------------------------------------------------------------


def _passthrough(args) -> list[str]:
    return [
        "--peers", str(args.peers), "--edges", str(args.edges),
        "--epochs", str(args.epochs), "--churn", str(args.churn),
        "--processes", str(args.processes),
        "--local-devices", str(args.local_devices),
        "--seed", str(args.seed), "--tol", str(args.tol),
        "--max-iter", str(args.max_iter),
        "--seal-timeout", str(args.seal_timeout),
        "--straggler-sleep", str(args.straggler_sleep),
        "--skew-slo-target", str(args.skew_slo_target),
        "--straggler-ratio", str(args.straggler_ratio),
        "--straggler-k", str(args.straggler_k),
    ] + (["--skip-scrape"] if args.skip_scrape else []) + (
        ["--seed-straggler"] if args.seed_straggler else []
    )


def collect_pod_flight_tails(flight_dir: Path, tail_events: int = 20) -> dict:
    """Per-host flight-recorder tails from the workers' per-epoch ring
    dumps — ``collect_worker_dumps`` semantics (bounded tail, journaled
    into the parent's ring, files consumed), grouped per host by
    staging each dump into its own directory first."""
    from protocol_tpu.obs.journal import collect_worker_dumps

    tails: dict[str, list] = {}
    if not flight_dir.is_dir():
        return tails
    for path in sorted(flight_dir.glob("flight-pod-*.jsonl")):
        hostkey = path.stem.removeprefix("flight-pod-")
        staging = flight_dir / f"collect-{hostkey}"
        staging.mkdir(exist_ok=True)
        path.rename(staging / path.name)
        tails[hostkey] = collect_worker_dumps(
            staging, "pod", tail_events=tail_events
        )
        staging.rmdir()
    return tails


def launch_pod(args, state_dir: Path, out_dir: Path, *, resume=False,
               crash_host=-1, crash_epoch=-1, dump_scores=None):
    """Spawn the pod; returns ``(return codes, worker reports)``.  On a
    crash run the parent reaps the killed worker, gives the survivors a
    grace period (they hang in the gloo collective their peer left),
    then kills them — the host-loss failure mode itself."""
    coordinator = f"127.0.0.1:{_free_port()}"
    outs = [out_dir / f"worker{i}.json" for i in range(args.processes)]
    procs = []
    for i in range(args.processes):
        cmd = [
            sys.executable, __file__, "--worker", str(i),
            "--coordinator", coordinator,
            "--state-dir", str(state_dir),
            "--worker-out", str(outs[i]),
        ] + _passthrough(args)
        if resume:
            cmd.append("--resume")
        if crash_host >= 0:
            cmd += ["--crash-host", str(crash_host),
                    "--crash-epoch", str(crash_epoch)]
        if dump_scores is not None and i == 0:
            cmd += ["--dump-scores", str(dump_scores)]
        procs.append(subprocess.Popen(cmd, cwd=REPO))

    rcs: list[int | None] = [None] * len(procs)
    deadline = time.monotonic() + args.timeout
    expect_crash = crash_host >= 0
    grace_until = None
    while any(rc is None for rc in rcs):
        for i, p in enumerate(procs):
            if rcs[i] is None:
                rcs[i] = p.poll()
        now = time.monotonic()
        if expect_crash and grace_until is None and any(
            rc not in (None, 0) for rc in rcs
        ):
            grace_until = now + 15.0  # survivors are stuck in gloo
        if now > deadline or (grace_until is not None and now > grace_until):
            for i, p in enumerate(procs):
                if rcs[i] is None:
                    p.kill()
                    rcs[i] = -9
            break
        time.sleep(0.2)
    for p in procs:
        p.wait()

    workers = []
    for path in outs:
        try:
            workers.append(json.loads(path.read_text()))
        except (OSError, json.JSONDecodeError):
            workers.append({"ok": False, "error": "no worker report"})
    return rcs, workers


def _bit_identity(workers: list[dict]) -> dict:
    """Per-epoch residual + score-digest agreement across all workers —
    exact equality, not a tolerance: every host holds the replicated
    vector, so the pod either agrees bit-for-bit or is broken."""
    by_epoch: dict[int, list[tuple[float, str]]] = {}
    for wkr in workers:
        for ep in wkr.get("epochs", []):
            by_epoch.setdefault(ep["epoch"], []).append(
                (ep["residual"], ep["scores_sha256"])
            )
    mismatches = {
        e: vals for e, vals in sorted(by_epoch.items())
        if len(set(vals)) != 1
    }
    return {
        "epochs_checked": len(by_epoch),
        "ok": not mismatches and bool(by_epoch),
        "mismatches": {str(e): v for e, v in mismatches.items()},
    }


def chaos_host_loss(args, workdir: Path, control_workers: list[dict]) -> dict:
    """Crash-matrix host-loss row: kill one worker of N mid-epoch
    (after WAL ack, before converge), reap the stuck pod, relaunch with
    ``--resume``, and require zero acked loss + a control-identical
    fixed point."""
    crash_epoch = max(1, args.epochs // 2)
    crash_host = min(1, args.processes - 1)
    state = workdir / "chaos-state"
    state.mkdir(parents=True, exist_ok=True)
    out_crash = workdir / "chaos-crash"
    out_crash.mkdir(exist_ok=True)
    crash_rcs, _ = launch_pod(
        args, state, out_crash, crash_host=crash_host, crash_epoch=crash_epoch
    )
    t0 = time.perf_counter()
    out_resume = workdir / "chaos-resume"
    out_resume.mkdir(exist_ok=True)
    resume_rcs, resume_workers = launch_pod(
        args, state, out_resume, resume=True
    )
    recovery_seconds = time.perf_counter() - t0

    control_digest = {
        w.get("host_id"): w.get("final_scores_sha256") for w in control_workers
    }
    resume_digest = {
        w.get("host_id"): w.get("final_scores_sha256") for w in resume_workers
    }
    identity = _bit_identity(resume_workers)
    lost = [
        w.get("recovery", {}).get("lost_acked_epochs")
        for w in resume_workers
        if isinstance(w.get("recovery"), dict)
    ]
    crashed_recovery = next(
        (
            w.get("recovery")
            for w in resume_workers
            if w.get("host_id") == crash_host
        ),
        None,
    )
    ok = (
        all(rc == 0 for rc in resume_rcs)
        and all(w.get("ok") for w in resume_workers)
        and identity["ok"]
        and all(not x for x in lost)
        and set(control_digest.values()) == set(resume_digest.values())
        and len(set(control_digest.values())) == 1
    )
    return {
        "point": "pod.host-loss",
        "crash_host": crash_host,
        "crash_epoch": crash_epoch,
        "crash_return_codes": crash_rcs,
        "resume_return_codes": resume_rcs,
        "recovery_seconds": round(recovery_seconds, 4),
        "crashed_host_recovery": crashed_recovery,
        "lost_acked": lost,
        "fixed_point_matches_control": set(control_digest.values())
        == set(resume_digest.values()),
        "residual_bit_identity": identity,
        "resume_workers": resume_workers,
        "ok": bool(ok),
    }


def _median(xs):
    xs = sorted(xs)
    return xs[len(xs) // 2] if xs else None


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="POD_smoke.json")
    ap.add_argument("--peers", type=int, default=8192)
    ap.add_argument("--edges", type=int, default=65536)
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--churn", type=float, default=0.01)
    ap.add_argument("--processes", type=int, default=2)
    ap.add_argument("--local-devices", type=int, default=2)
    ap.add_argument("--seed", type=int, default=16)
    ap.add_argument("--tol", type=float, default=1e-6)
    ap.add_argument("--max-iter", type=int, default=60)
    ap.add_argument("--seal-timeout", type=float, default=120.0)
    ap.add_argument("--timeout", type=float, default=1800.0)
    ap.add_argument("--smoke", action="store_true", help="CI scale")
    ap.add_argument("--chaos-host-loss", action="store_true")
    ap.add_argument("--skip-reference", action="store_true")
    ap.add_argument("--skip-scrape", action="store_true")
    ap.add_argument("--round", type=int, default=0)
    ap.add_argument("--workdir", default=None)
    ap.add_argument(
        "--seed-straggler", action="store_true",
        help="inject a sleep in one host's checkpoint phase; the "
             "straggler watcher and skew SLO MUST fire (exit 1)",
    )
    ap.add_argument("--straggler-sleep", type=float, default=0.5)
    ap.add_argument("--skew-slo-target", type=float, default=0.2)
    ap.add_argument("--straggler-ratio", type=float, default=1.5)
    ap.add_argument("--straggler-k", type=int, default=2)
    ap.add_argument(
        "--obs-out", default=None,
        help="also write the OBS_r*.json pod series for perf_sentinel",
    )
    # hidden subprocess plumbing
    ap.add_argument("--worker", type=int, default=None, help=argparse.SUPPRESS)
    ap.add_argument("--reference", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--coordinator", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--state-dir", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--worker-out", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--resume", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--crash-host", type=int, default=-1, help=argparse.SUPPRESS)
    ap.add_argument("--crash-epoch", type=int, default=-1, help=argparse.SUPPRESS)
    ap.add_argument("--dump-scores", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.smoke:
        args.peers = min(args.peers, 2048)
        args.edges = min(args.edges, 16384)
        args.epochs = min(args.epochs, 3)

    if args.worker is not None:
        return worker_main(args)
    if args.reference:
        return reference_main(args)

    import numpy as np

    tmp_ctx = (
        tempfile.TemporaryDirectory()
        if args.workdir is None
        else None
    )
    workdir = Path(args.workdir or tmp_ctx.name)
    workdir.mkdir(parents=True, exist_ok=True)
    try:
        # -- reference subprocess (serial build + correctness pin) ----
        reference = None
        ref_scores = workdir / "ref_scores.npy"
        if not args.skip_reference:
            ref_out = workdir / "reference.json"
            rc = subprocess.run(
                [sys.executable, __file__, "--reference",
                 "--worker-out", str(ref_out),
                 "--dump-scores", str(ref_scores)] + _passthrough(args),
                cwd=REPO, timeout=args.timeout,
            ).returncode
            try:
                reference = json.loads(ref_out.read_text())
            except (OSError, json.JSONDecodeError):
                reference = {"error": f"reference failed rc={rc}"}

        # -- single-host run at 1/H scale (flat-epoch-seconds pin) ----
        single_host = None
        if not args.skip_reference and args.processes > 1:
            sh_args = argparse.Namespace(**vars(args))
            sh_args.processes = 1
            sh_args.seed_straggler = False
            sh_args.peers = max(args.peers // args.processes, 64)
            sh_args.edges = max(args.edges // args.processes, 256)
            sh_state = workdir / "single-host-state"
            sh_state.mkdir(exist_ok=True)
            sh_out = workdir / "single-host"
            sh_out.mkdir(exist_ok=True)
            sh_rcs, sh_workers = launch_pod(sh_args, sh_state, sh_out)
            single_host = {
                "peers": sh_args.peers,
                "edges": sh_args.edges,
                "return_codes": sh_rcs,
                "worker": sh_workers[0] if sh_workers else None,
            }

        # -- the pod itself (control run) -----------------------------
        state = workdir / "pod-state"
        state.mkdir(exist_ok=True)
        out_dir = workdir / "pod"
        out_dir.mkdir(exist_ok=True)
        pod_scores = workdir / "pod_scores.npy"
        rcs, workers = launch_pod(
            args, state, out_dir, dump_scores=pod_scores
        )
        skipped = all(w.get("skipped") for w in workers)
        identity = _bit_identity(workers)
        if skipped and args.seed_straggler:
            # The CI must-fail leg inverts our exit code; a skip-exit-0
            # there would read as "watcher fired" on a jax build that
            # never ran the pod at all.
            print(
                "dryrun_pod: SKIPPED but --seed-straggler expects a "
                "failure — exiting 1 so the must-fail leg stays honest"
            )
            return 1
        flight_tails = collect_pod_flight_tails(state / "flight")

        warm_vs_cold_l1 = None
        if not skipped and ref_scores.exists() and pod_scores.exists():
            warm_vs_cold_l1 = float(
                np.abs(np.load(pod_scores) - np.load(ref_scores)).sum()
            )

        chaos = None
        if args.chaos_host_loss and not skipped and args.processes > 1:
            chaos = chaos_host_loss(args, workdir, workers)
            chaos["flight_tails"] = {
                k: len(v)
                for k, v in collect_pod_flight_tails(
                    workdir / "chaos-state" / "flight"
                ).items()
            }
    finally:
        if tmp_ctx is not None:
            tmp_ctx.cleanup()

    ok = skipped or (
        all(rc == 0 for rc in rcs)
        and all(w.get("ok") for w in workers)
        and identity["ok"]
        and (warm_vs_cold_l1 is None or warm_vs_cold_l1 < SCORE_TOL)
        and (chaos is None or chaos["ok"])
    )

    # -- sentinel-shaped entries (perf_sentinel keys on n_hosts) ------
    scale = f"{args.peers} peers/{args.edges} edges"
    meshs = f"{args.processes}x{args.local_devices}"
    entries: list[dict] = []
    if not skipped and workers and workers[0].get("epochs"):
        w0 = workers[0]
        steady = _median(
            [e["seconds"] for e in w0["epochs"] if e["epoch"] > 0]
        )
        cold = next(
            (e["seconds"] for e in w0["epochs"] if e["epoch"] == 0), None
        )
        sh_epochs = (
            (single_host or {}).get("worker") or {}
        ).get("epochs") or []
        sh_steady = _median(
            [e["seconds"] for e in sh_epochs if e["epoch"] > 0]
        )
        entries.append({
            "metric": (
                f"pod steady-state epoch wall-clock ({scale}, "
                f"{meshs} mesh, {BACKEND})"
            ),
            "value": steady,
            "unit": "seconds",
            "n_hosts": args.processes,
            "cold_epoch_seconds": cold,
            "single_host_steady_epoch_seconds": sh_steady,
            "single_host_scale": (
                f"{single_host['peers']} peers/{single_host['edges']} edges"
                if single_host else None
            ),
            "warm_vs_cold_l1": warm_vs_cold_l1,
            "per_epoch": w0["epochs"],
        })
        if reference and "plan_build_seconds" in reference:
            entries.append({
                "metric": (
                    f"pod plan-build critical path ({scale}, "
                    f"{args.processes} hosts)"
                ),
                "value": reference["plan_build_seconds"],
                "unit": "seconds",
                "n_hosts": args.processes,
                "plan_build_seconds": reference["plan_build_seconds"],
                "serial_plan_build_seconds":
                    reference["serial_plan_build_seconds"],
                "plan_build_speedup": reference["plan_build_speedup"],
                "partitioned_plan_build_seconds":
                    reference["partitioned_plan_build_seconds"],
                "pod_measured_local_build_seconds": [
                    w["epochs"][0]["local_plan_build_seconds"]
                    for w in workers if w.get("epochs")
                ],
            })
        scrape = w0.get("scrape") or {}
        if scrape.get("comm"):
            entries.append({
                "metric": (
                    f"pod per-iteration collective bytes ({scale}, "
                    f"{meshs} mesh)"
                ),
                "value": scrape["comm"]["bytes_per_iter"],
                "comm_bytes_per_iter": scrape["comm"]["bytes_per_iter"],
                "unit": "bytes",
                "n_hosts": args.processes,
                "budget_bytes": scrape["comm"]["budget_bytes"],
            })
        if scrape.get("mem"):
            entries.append({
                "metric": (
                    f"pod per-shard peak HBM ({scale}, {meshs} mesh)"
                ),
                "value": scrape["mem"]["measured"]["peak_bytes"],
                "peak_hbm_bytes_per_shard":
                    scrape["mem"]["measured"]["peak_bytes"],
                "unit": "bytes",
                "n_hosts": args.processes,
                "pod_budget_peak_bytes":
                    scrape["pod_budget"]["peak_bytes"],
            })
        pod_obs = w0.get("pod_obs") or {}
        stitch_reports = pod_obs.get("stitch_reports") or []
        if stitch_reports:
            skews = [
                max(r["phase_skew_s"].values()) if r["phase_skew_s"] else 0.0
                for r in stitch_reports
            ]
            spreads = [
                r["barrier_spread_s"] for r in stitch_reports
                if r.get("barrier_spread_s") is not None
            ]
            stitch_ms = round(
                (_median([r["stitch_seconds"] for r in stitch_reports])
                 or 0.0) * 1e3, 3,
            )
            entries.append({
                "metric": (
                    f"pod trace stitch + phase skew ({scale}, "
                    f"{meshs} mesh)"
                ),
                "value": stitch_ms,
                "unit": "ms",
                "n_hosts": args.processes,
                "stitch_ms": stitch_ms,
                "phase_skew_p99_ms": round(max(skews) * 1e3, 3),
                "barrier_spread_ms": (
                    round((_median(spreads) or 0.0) * 1e3, 3)
                    if spreads else None
                ),
                "obs_overhead_pct": pod_obs.get("obs_overhead_pct"),
                "stitch_complete": pod_obs.get("stitch_complete"),
                "min_phase_attribution":
                    pod_obs.get("min_phase_attribution"),
            })

    report = {
        "tool": "dryrun_pod",
        "round": args.round,
        "backend": BACKEND,
        "mesh": meshs,
        "n_hosts": args.processes,
        "n_cpus": os.cpu_count(),
        "params": {
            "peers": args.peers, "edges": args.edges,
            "epochs": args.epochs, "churn": args.churn,
            "tol": args.tol, "max_iter": args.max_iter,
            "seed": args.seed,
        },
        "ok": bool(ok),
        "skipped": skipped,
        "return_codes": rcs,
        "residual_bit_identity": identity,
        "warm_vs_cold_l1": warm_vs_cold_l1,
        "reference": reference,
        "single_host": single_host,
        "chaos": chaos,
        "entries": entries,
        "flight_tails": flight_tails if not skipped else {},
        "workers": workers,
    }
    Path(args.out).write_text(
        json.dumps(report, indent=2, default=_jsonable) + "\n"
    )
    # The stitched pod trace as its own artifact (CI uploads it), plus
    # the sentinel-walkable OBS series when asked.
    pod_trace_doc = next(
        (
            w["pod_obs"]["trace_latest"]
            for w in workers
            if isinstance(w.get("pod_obs"), dict)
            and w["pod_obs"].get("trace_latest")
        ),
        None,
    )
    if pod_trace_doc is not None:
        Path(args.out).with_name("POD_TRACE_latest.json").write_text(
            json.dumps(pod_trace_doc, indent=2, default=_jsonable) + "\n"
        )
    if args.obs_out and not skipped:
        Path(args.obs_out).write_text(json.dumps({
            "tool": "dryrun_pod",
            "round": args.round,
            "n_hosts": args.processes,
            "entries": [e for e in entries if "stitch_ms" in e],
        }, indent=2, default=_jsonable) + "\n")
    status = (
        "SKIPPED (no multi-process CPU collectives)" if skipped
        else ("OK" if ok else "FAILED")
    )
    print(f"dryrun_pod: {status} — report in {args.out}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
