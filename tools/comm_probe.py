"""2-process ``jax.distributed`` CPU comm smoke (the CI comm-probe job)
— the first executable artifact of the multi-host path (ROADMAP item 3).

Graftlint pass 8 proves the partitioner's collective structure
*statically* under a single-process 8-device mesh; this probe proves
the same structure holds when the mesh actually spans processes.  The
parent spawns two workers; each one:

- initializes ``jax.distributed`` (gloo CPU collectives, 4 local
  devices per process -> a 2x4 = 8-device global mesh, the same shard
  count the static analyzer uses),
- builds the sharded trust problem and runs one real
  ``converge_sharded`` across both processes,
- scrapes its own compiled module (``run.lower(...).compile()``) with
  the pass-8 walker, and
- asserts the **measured collective structure is a subset of the
  static COMM_INVARIANTS budget**: only declared kinds, within
  declared counts, per-iteration bytes within the O(boundary + N)
  allowance, donation alias present.

The parent aggregates both workers into ``COMM_PROBE.json`` (CI
artifact) and exits non-zero on any violation, a diverged score
vector, or a worker crash.  On a jax build without multi-process CPU
collectives the probe reports ``skipped`` and exits 0 — the static
pass still gates.

Run: ``python tools/comm_probe.py [--out COMM_PROBE.json]``
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

#: Defaults: 2 workers x 4 devices -> the analyzer's 8-way mesh.  Both
#: are CLI-tunable (``--processes``/``--local-devices``) so the pod
#: dryrun and CI can run probes of different shapes concurrently — the
#: coordinator port is always picked from a free socket, never fixed.
LOCAL_DEVICES = 4
N_PROCESSES = 2


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker(process_id: int, coordinator: str, out_path: str,
            n_peers: int, n_edges: int,
            n_processes: int = N_PROCESSES,
            local_devices: int = LOCAL_DEVICES) -> int:
    """Worker body: distributed init, one sharded converge, self-scrape."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={local_devices}"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    result: dict = {"process_id": process_id, "ok": False}
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=n_processes,
            process_id=process_id,
        )
    except Exception as exc:  # old jaxlib: no multi-process CPU
        result.update(skipped=True, reason=repr(exc))
        Path(out_path).write_text(json.dumps(result))
        return 0

    import jax.numpy as jnp
    import numpy as np

    from protocol_tpu.analysis.budget import COMM_INVARIANTS
    from protocol_tpu.analysis.comm.hlo_walk import parse_module
    from protocol_tpu.models.graphs import scale_free
    from protocol_tpu.parallel.mesh import SHARD_AXIS, default_mesh
    from protocol_tpu.parallel.sharded import (
        ShardedTrustProblem,
        _get_runner,
        converge_sharded,
    )

    backend = "tpu-sharded:tpu-csr"
    budget = COMM_INVARIANTS[backend]
    mesh = default_mesh()
    n_shards = mesh.shape[SHARD_AXIS]
    result.update(
        backend=backend,
        n_processes=n_processes,
        local_devices=len(jax.local_devices()),
        global_devices=len(jax.devices()),
        n_shards=n_shards,
    )

    graph = scale_free(n_peers, n_edges, seed=1)
    prob = ShardedTrustProblem.build(graph, mesh)
    t, iters, resid = converge_sharded(prob, alpha=0.1, tol=1e-6, max_iter=8)
    scores = np.asarray(t)
    result.update(
        n=int(prob.n),
        iterations=int(iters),
        residual=float(resid),
        l1=float(scores.sum()),
    )

    # Scrape THIS process's lowering — the module the local runtime
    # actually executes — and judge it against the static budget.
    run = _get_runner(mesh, prob.n)
    comp = run.lower(
        prob.src, prob.w, prob.row_ptr, prob.t0(), prob.p, prob.dangling,
        jnp.asarray(0.1, jnp.float32), max_iter=8, tol=1e-6,
    ).compile()
    mod = parse_module(comp.as_text())
    violations: list[str] = []
    counts = mod.kind_counts()
    for kind, count in sorted(counts.items()):
        allowed = budget.allowed_count(kind)
        if allowed == 0:
            violations.append(f"undeclared collective kind {kind} (x{count})")
        elif count > allowed:
            violations.append(f"{kind} x{count} > budget {allowed}")
    measured = mod.total_bytes(per_iteration_only=True)
    allowance = budget.max_bytes(prob.n, 0, n_shards)
    if measured > allowance:
        violations.append(
            f"per-iteration bytes {measured} > budget {allowance:.0f}"
        )
    if 3 not in mod.aliased_params():  # t0 is parameter 3 of the runner
        violations.append(
            f"t0 donation missing from input_output_alias "
            f"{sorted(mod.aliases.items())}"
        )
    if mod.host_calls:
        violations.append(
            f"host round-trips in module: "
            f"{[h.target or h.op for h in mod.host_calls]}"
        )
    result.update(
        collectives=[op.to_dict() for op in mod.collectives],
        bytes_per_iter=measured,
        budget_bytes=allowance,
        input_output_alias={str(k): v for k, v in sorted(mod.aliases.items())},
        violations=violations,
        ok=bool(not violations and abs(float(scores.sum()) - 1.0) < 1e-3),
    )
    Path(out_path).write_text(json.dumps(result))
    return 0 if result["ok"] else 1


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="COMM_PROBE.json", help="report path")
    ap.add_argument("--peers", type=int, default=512)
    ap.add_argument("--edges", type=int, default=4096)
    ap.add_argument(
        "--processes", type=int, default=N_PROCESSES,
        help="worker process count (default 2)",
    )
    ap.add_argument(
        "--local-devices", type=int, default=LOCAL_DEVICES,
        help="forced CPU devices per process (default 4)",
    )
    ap.add_argument("--timeout", type=float, default=600.0)
    ap.add_argument("--worker", type=int, default=None, help=argparse.SUPPRESS)
    ap.add_argument("--coordinator", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--worker-out", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.worker is not None:
        return _worker(
            args.worker, args.coordinator, args.worker_out,
            args.peers, args.edges, args.processes, args.local_devices,
        )

    coordinator = f"127.0.0.1:{_free_port()}"
    with tempfile.TemporaryDirectory() as tmp:
        outs = [
            str(Path(tmp) / f"worker{i}.json") for i in range(args.processes)
        ]
        procs = [
            subprocess.Popen(
                [
                    sys.executable, __file__,
                    "--worker", str(i),
                    "--coordinator", coordinator,
                    "--worker-out", outs[i],
                    "--peers", str(args.peers),
                    "--edges", str(args.edges),
                    "--processes", str(args.processes),
                    "--local-devices", str(args.local_devices),
                ],
                cwd=REPO,
            )
            for i in range(args.processes)
        ]
        rcs = []
        for p in procs:
            try:
                rcs.append(p.wait(timeout=args.timeout))
            except subprocess.TimeoutExpired:
                p.kill()
                rcs.append(-9)
        workers = []
        for path in outs:
            try:
                workers.append(json.loads(Path(path).read_text()))
            except (OSError, json.JSONDecodeError):
                workers.append({"ok": False, "error": "no worker report"})

    skipped = all(w.get("skipped") for w in workers)
    ok = skipped or (
        all(rc == 0 for rc in rcs) and all(w.get("ok") for w in workers)
    )
    # Cross-process agreement: every worker holds the full replicated
    # result; their residuals must match bit-for-bit-ish.
    if ok and not skipped:
        resids = [w["residual"] for w in workers]
        if max(resids) - min(resids) > 1e-9:
            ok = False
            workers.append({"error": f"residual divergence: {resids}"})
    report = {
        "tool": "comm_probe",
        "mesh": f"{args.processes}x{args.local_devices}",
        "ok": ok,
        "skipped": skipped,
        "return_codes": rcs,
        "workers": workers,
    }
    Path(args.out).write_text(json.dumps(report, indent=2) + "\n")
    status = "SKIPPED (no multi-process CPU collectives)" if skipped else (
        "OK" if ok else "FAILED"
    )
    print(f"comm_probe: {status} — report in {args.out}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
