"""Multi-epoch pipelined churn dryrun (the CI epoch-pipe step).

Drives the real :class:`~protocol_tpu.node.pipeline.EpochPipeline` +
Manager warm-start/delta-plan machinery over a synthetic open graph (the
5-peer fixed set cannot exercise convergence depth) for N epochs with
per-epoch edge churn, asserts the ISSUE 5 acceptance shape —

- every warm epoch converged in FEWER iterations than cold epoch 0,
- the warm fixed point matches a cold-start convergence of the final
  graph within tolerance,
- no tick was dropped or superseded (each epoch landed),
- steady-state epochs resolved the window plan by delta, not rebuild,

and writes ``EPOCH_PIPE.json`` with the per-epoch numbers.

Run: ``JAX_PLATFORMS=cpu python tools/epoch_pipe.py [--out FILE]``
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


class _SyntheticGraphManager:
    """Manager facade whose open graph is a synthetic scale-free edge
    list with injected churn — peer "hashes" are row ids, so the
    warm-start remap and dirty-row plumbing run exactly as in
    production, without signing 20k attestations."""

    def __new__(cls, graph):
        from protocol_tpu.node.manager import Manager, ManagerConfig
        from protocol_tpu.trust.graph import TrustGraph

        class _Mgr(Manager):
            def __init__(self, g):
                # 1% EDGE churn touches ~6% of the rows at this avg
                # degree — above the conservative production default,
                # so the dryrun raises the delta/rebuild crossover.
                super().__init__(
                    ManagerConfig(
                        backend="tpu-windowed",
                        prover="commitment",
                        plan_delta_max_churn=0.25,
                    )
                )
                self._graph = g
                self._rng = np.random.default_rng(23)

            def churn(self, fraction: float) -> int:
                g = self._graph
                k = max(1, int(g.nnz * fraction))
                idx = self._rng.choice(g.nnz, k, replace=False)
                dst = g.dst.copy()
                dst[idx] = self._rng.integers(0, g.n, k)
                while (bad := dst[idx] == g.src[idx]).any():
                    dst[idx[bad]] = self._rng.integers(0, g.n, int(bad.sum()))
                w = g.weight.copy()
                w[idx] = self._rng.integers(1, 1000, k).astype(np.float32)
                self._graph = TrustGraph(g.n, g.src, dst, w, g.pre_trusted)
                self._dirty_hashes.update(int(s) for s in np.unique(g.src[idx]))
                return k

            def build_graph(self):
                self._id_order = list(range(self._graph.n))
                return self._graph

        return _Mgr(graph)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="EPOCH_PIPE.json", help="report path")
    ap.add_argument("--peers", type=int, default=20_000)
    ap.add_argument("--edges", type=int, default=120_000)
    ap.add_argument("--churn", type=float, default=0.01)
    ap.add_argument("--epochs", type=int, default=3)
    args = ap.parse_args(argv)

    from protocol_tpu.models.graphs import scale_free
    from protocol_tpu.node.epoch import Epoch
    from protocol_tpu.node.pipeline import EpochPipeline
    from protocol_tpu.obs.metrics import EPOCH_TICKS_DROPPED, PLAN_OUTCOMES
    from protocol_tpu.trust.backend import get_backend

    manager = _SyntheticGraphManager(scale_free(args.peers, args.edges, seed=7))
    per_epoch = []
    delta0 = PLAN_OUTCOMES.value(outcome="delta")
    dropped0 = EPOCH_TICKS_DROPPED.value()
    with EpochPipeline(manager, alpha=0.1, tol=1e-6, max_iter=80) as pipe:
        for k in range(args.epochs):
            churned = manager.churn(args.churn) if k else 0
            t0 = time.perf_counter()
            prepared = pipe.submit(Epoch(k))
            assert pipe.drain(timeout=600), f"epoch {k} did not finish"
            outcome = pipe.outcomes[k]
            assert outcome.error is None, f"epoch {k}: {outcome.error!r}"
            per_epoch.append(
                {
                    "epoch": k,
                    "seconds": round(time.perf_counter() - t0, 4),
                    "iterations": int(outcome.result.iterations),
                    "warm": prepared.t0 is not None,
                    "edges_churned": churned,
                }
            )
    final_scores = manager.last_scores

    # -- acceptance shape ----------------------------------------------
    cold_iters = per_epoch[0]["iterations"]
    assert not per_epoch[0]["warm"], "epoch 0 must be a cold start"
    for entry in per_epoch[1:]:
        assert entry["warm"], f"epoch {entry['epoch']} did not warm start"
        assert entry["iterations"] < cold_iters, (
            f"epoch {entry['epoch']} took {entry['iterations']} iterations, "
            f"not fewer than cold epoch 0's {cold_iters}"
        )
    delta_applies = PLAN_OUTCOMES.value(outcome="delta") - delta0
    assert delta_applies >= args.epochs - 1, (
        f"expected >= {args.epochs - 1} plan delta-applies, saw {delta_applies}"
    )
    dropped = EPOCH_TICKS_DROPPED.value() - dropped0
    assert dropped == 0 and pipe.coalesced == 0, (dropped, pipe.coalesced)

    # Warm path must land on the cold fixed point of the final graph.
    ref = get_backend("tpu-windowed").converge(
        manager.build_graph(), alpha=0.1, tol=1e-6, max_iter=80
    )
    l1 = float(np.abs(final_scores - ref.scores).sum())
    assert l1 <= 1e-4, f"warm fixed point drifted from cold: L1 {l1}"

    report = {
        "peers": args.peers,
        "edges": args.edges,
        "churn": args.churn,
        "cold_iterations": cold_iters,
        "warm_iterations": [e["iterations"] for e in per_epoch[1:]],
        "plan_delta_applies": delta_applies,
        "dropped_ticks": dropped,
        "coalesced_ticks": pipe.coalesced,
        "warm_vs_cold_l1": l1,
        "per_epoch": per_epoch,
    }
    Path(args.out).parent.mkdir(parents=True, exist_ok=True)
    Path(args.out).write_text(json.dumps(report, indent=2) + "\n")
    print(
        f"epoch_pipe: OK — cold {cold_iters} iters, warm "
        f"{report['warm_iterations']}, {int(delta_applies)} delta-applies, "
        f"0 dropped ticks; report at {args.out}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
