"""Multi-epoch async-proving dryrun (the CI prover-pipe step).

Drives the real :class:`~protocol_tpu.node.pipeline.EpochPipeline` +
:class:`~protocol_tpu.prover.plane.ProvingPlane` over a synthetic
churned open graph for N epochs: every epoch's device stage ends at
``converge`` and *enqueues* the fixed-set SNARK onto the proving
plane's bounded queue — the ISSUE 10 acceptance shape —

- every epoch tick's wall-clock excludes prove time (tick ≈ converge;
  the overlap ratio tick/(tick+prove) stays below the bound),
- proof lag stays bounded while the run is in flight and returns to 0
  after the drain,
- zero failed jobs, and every submitted epoch terminates explicitly:
  ``proved`` or ``superseded`` (never a silent drop) with the newest
  epoch always proved,
- pooled proofs verify and carry the worker-side span attribution
  (``prove{power_iterate, circuit_check, snark{msm, ...}}``) grafted
  into the epoch's stored trace,

and writes ``PROVER_PIPE.json`` with the per-epoch numbers.

Run: ``JAX_PLATFORMS=cpu python tools/prover_pipe.py [--out FILE]
[--prover plonk|commitment] [--workers N]``
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def _make_manager(graph, prover: str, zk_backend: str = "native"):
    from protocol_tpu.node.manager import Manager, ManagerConfig
    from protocol_tpu.trust.graph import TrustGraph

    class _Mgr(Manager):
        """Manager facade over a synthetic open graph (the epoch_pipe
        pattern): peer "hashes" are row ids so warm-start and delta
        plumbing run exactly as in production, while the fixed-set
        proof path runs the real statement end to end."""

        def __init__(self, g):
            super().__init__(
                ManagerConfig(
                    backend="tpu-windowed",
                    prover=prover,
                    zk_backend=zk_backend,
                    plan_delta_max_churn=0.25,
                )
            )
            self._graph = g
            self._rng = np.random.default_rng(23)

        def churn(self, fraction: float) -> int:
            """Sender-centric, recency-biased re-attestation (the
            bench.py replay pattern, PERF.md §11): a cohort of
            id-local peers rewrites its whole out-row — the churn
            shape the delta plan's quantized capacity holds device
            shapes stable under (whole-graph random edge rewires
            instead touch most windows and force rebuild/recompile)."""
            g = self._graph
            avg_deg = max(g.nnz / g.n, 1.0)
            cohort = max(1, int(round(fraction * g.nnz / avg_deg)))
            offs = self._rng.exponential(
                scale=max(g.n * 0.02, cohort), size=cohort
            ).astype(np.int64)
            rows = np.unique(g.n - 1 - np.minimum(offs, g.n - 1))
            keep = ~np.isin(g.src, rows.astype(np.int32))
            deg = max(1, int(round(avg_deg)))
            ns = np.repeat(rows.astype(np.int32), deg)
            nd = self._rng.integers(0, g.n, ns.shape[0]).astype(np.int32)
            while (bad := nd == ns).any():
                nd[bad] = self._rng.integers(0, g.n, int(bad.sum()))
            nw = self._rng.integers(1, 1000, ns.shape[0]).astype(np.float32)
            self._graph = TrustGraph(
                g.n,
                np.concatenate([g.src[keep], ns]),
                np.concatenate([g.dst[keep], nd]),
                np.concatenate([g.weight[keep], nw]),
                g.pre_trusted,
            )
            self._dirty_hashes.update(int(r) for r in rows)
            return int(ns.shape[0])

        def build_graph(self):
            self._id_order = list(range(self._graph.n))
            return self._graph

    return _Mgr(graph)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="PROVER_PIPE.json", help="report path")
    ap.add_argument("--peers", type=int, default=20_000)
    ap.add_argument("--edges", type=int, default=120_000)
    ap.add_argument("--churn", type=float, default=0.01)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument(
        "--prover",
        default="plonk",
        choices=("plonk", "commitment"),
        help="proof backend for the enqueued jobs (plonk = the real "
        "k=14 SNARK, the headline overlap; commitment = fast smoke)",
    )
    ap.add_argument(
        "--workers",
        type=int,
        default=1,
        help="prover worker processes (0 = prove inline on the plane's "
        "dispatcher thread)",
    )
    ap.add_argument(
        "--zk-backend",
        default="native",
        choices=("native", "graft"),
        help="proving-kernel backend for the enqueued jobs (zk.graft "
        "knob); proofs are byte-identical either way, and the snark "
        "span must carry msm/ntt attribution regardless",
    )
    ap.add_argument(
        "--max-overlap-ratio",
        type=float,
        default=0.7,
        help="fail if median tick/(tick+prove) exceeds this (the "
        "'epoch excludes prove' acceptance bound)",
    )
    args = ap.parse_args(argv)

    from protocol_tpu.models.graphs import scale_free
    from protocol_tpu.node.epoch import Epoch
    from protocol_tpu.node.pipeline import EpochPipeline
    from protocol_tpu.obs import TRACER
    from protocol_tpu.obs.metrics import PROOF_LAG_EPOCHS
    from protocol_tpu.prover import ProvingPlane, ProvingPlaneConfig

    manager = _make_manager(
        scale_free(args.peers, args.edges, seed=7), args.prover, args.zk_backend
    )
    manager.generate_initial_attestations()
    print(f"prover_pipe: warming {args.prover} prover (keygen/key cache)...")
    manager.warm_prover()

    plane = ProvingPlane(
        ProvingPlaneConfig(workers=args.workers, queue_depth=2),
        on_proved=lambda r: manager.install_proof(r.epoch, r.pub_ins, r.proof),
    ).start()
    cfg = manager.config
    plane.prewarm(
        (cfg.num_neighbours, cfg.num_iter, cfg.initial_score, cfg.scale),
        cfg.prover,
        cfg.srs_path,
    )

    def device_stage(prepared):
        # The node's stage shape: converge, then enqueue the SNARK at
        # tick end (microseconds) — the tick never waits on a prover,
        # and the prove's CPU burst lands in the inter-tick gap rather
        # than time-slicing against this tick's converge.
        with TRACER.epoch(prepared.epoch.number):
            result = manager.converge_prepared(prepared, alpha=0.1, max_iter=80)
            plane.submit(manager.build_proof_job(prepared.epoch))
            return result

    per_epoch = []
    lag_samples = []
    bound = args.workers + 2 + 1  # queue_depth + workers + the in-flight tick
    with EpochPipeline(manager, device_stage=device_stage) as pipe:
        for k in range(args.epochs):
            if k:
                manager.churn(args.churn)
            t0 = time.perf_counter()
            pipe.submit(Epoch(k))
            assert pipe.drain(timeout=600), f"epoch {k} did not finish"
            tick = time.perf_counter() - t0
            outcome = pipe.outcomes[k]
            assert outcome.error is None, f"epoch {k}: {outcome.error!r}"
            lag = PROOF_LAG_EPOCHS.value()
            lag_samples.append(lag)
            assert lag <= bound, f"proof lag {lag} epochs exceeds bound {bound}"
            per_epoch.append(
                {
                    "epoch": k,
                    "tick_seconds": round(tick, 4),
                    "iterations": int(outcome.result.iterations),
                    "proof_lag_epochs_after_tick": lag,
                }
            )
    assert plane.drain(timeout=900), "proving plane did not drain"
    stats = plane.stats()
    plane.close()

    # -- acceptance shape ----------------------------------------------
    assert stats["failed"] == 0, f"failed proof jobs: {stats}"
    for k in range(args.epochs):
        state = stats["states"].get(k, {}).get("state")
        assert state in ("proved", "superseded"), (
            f"epoch {k} ended in {state!r} — every epoch must terminate "
            "explicitly as proved or superseded"
        )
    newest = args.epochs - 1
    assert stats["states"][newest]["state"] == "proved", (
        "the newest epoch must always prove (latest-wins coalescing)"
    )
    assert PROOF_LAG_EPOCHS.value() == 0, "lag must return to 0 after drain"

    prove_seconds = [
        s["prove_seconds"]
        for s in stats["states"].values()
        if s.get("prove_seconds") is not None
    ]
    med_tick = statistics.median(e["tick_seconds"] for e in per_epoch)
    med_prove = statistics.median(prove_seconds)
    overlap_ratio = med_tick / max(med_tick + med_prove, 1e-9)
    assert overlap_ratio <= args.max_overlap_ratio, (
        f"median epoch tick {med_tick:.2f}s vs prove {med_prove:.2f}s: "
        f"overlap ratio {overlap_ratio:.2f} exceeds "
        f"{args.max_overlap_ratio} — prove is not off the epoch path"
    )

    # The grafted attribution must be visible on the stored traces of
    # every proved epoch (it lands when the proof lands).
    grafted = 0
    for k in range(args.epochs):
        trace = TRACER.get_trace(k)
        if trace is None or stats["states"][k]["state"] != "proved":
            continue
        names = [c["name"] for c in trace["children"]]
        assert "prove" in names, f"epoch {k}: no grafted prove span ({names})"
        prove_span = next(c for c in trace["children"] if c["name"] == "prove")
        child_names = [c["name"] for c in prove_span["children"]]
        assert "snark" in child_names, child_names
        if args.prover == "plonk":
            # The deep attribution must survive the zk_backend switch:
            # whichever kernel engine ran (native timers or the graft
            # phase table), the same msm/ntt children hang off snark,
            # tagged with the engine that produced them.
            snark = next(c for c in prove_span["children"] if c["name"] == "snark")
            phases = {
                c["name"]: c.get("attrs", {}).get("engine")
                for c in snark["children"]
            }
            for phase in ("msm", "ntt"):
                assert phase in phases, (
                    f"epoch {k}: snark span lost {phase} attribution "
                    f"under zk_backend={args.zk_backend} ({sorted(phases)})"
                )
            engines = {e for e in phases.values() if e != "host"}
            assert args.zk_backend in engines, (
                f"epoch {k}: no {args.zk_backend}-engine rows on the "
                f"snark span ({phases})"
            )
        grafted += 1
    assert grafted >= 1, "no epoch trace carries the grafted prove tree"

    report = {
        "peers": args.peers,
        "edges": args.edges,
        "churn": args.churn,
        "epochs": args.epochs,
        "prover": args.prover,
        "workers": args.workers,
        "zk_backend": args.zk_backend,
        "median_tick_seconds": round(med_tick, 4),
        "median_prove_seconds": round(med_prove, 4),
        "sync_epoch_estimate_seconds": round(med_tick + med_prove, 4),
        "overlap_ratio": round(overlap_ratio, 4),
        "proofs_completed": stats["completed"],
        "proofs_failed": stats["failed"],
        "proofs_superseded": stats["superseded"],
        "max_proof_lag_epochs": max(lag_samples),
        "grafted_traces": grafted,
        "per_epoch": per_epoch,
        "proof_states": {str(k): v for k, v in stats["states"].items()},
    }
    Path(args.out).parent.mkdir(parents=True, exist_ok=True)
    Path(args.out).write_text(json.dumps(report, indent=2) + "\n")
    print(
        f"prover_pipe: OK — median tick {med_tick:.2f}s with prove "
        f"{med_prove:.2f}s overlapped (ratio {overlap_ratio:.2f}), "
        f"{stats['completed']} proved / {stats['superseded']} superseded / "
        f"0 failed; report at {args.out}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
