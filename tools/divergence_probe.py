"""Differential replay probe: pass 13's runtime half (the
``comm_probe``/``mem_probe`` analog for the determinism wall).

The static legs prove no divergence-feasible *source* reaches a
bit-identity sink; this probe proves the whole replay is a pure
function of the protocol seed by running the 2-process gloo churned
pod replay (``tools/dryrun_pod.py`` workers) **twice under perturbed
schedules** and requiring every sink identical across runs:

- **perturbations** (everything a correct run must be invariant to):
  different ``PYTHONHASHSEED`` (set/dict-salt divergence), different
  ``OMP_NUM_THREADS`` (host-side BLAS/XLA thread partitioning),
  reversed host launch order with a stagger (coordinator rendezvous
  timing), and different fleet-directory scrape interleavings (decoy
  snapshot files created in a different order + a concurrent scraper
  thread merging the directory at a different cadence during the
  replay);
- **asserted identical across runs**: per-host WAL ack digests
  (``acks-h*.jsonl``), checkpoint column sha256s
  (``checkpoints/manifest.json``), every sealed pod manifest and shard
  stamp (full canonical JSON), per-epoch residuals + score digests,
  the final score fixed point (digest AND dumped ``.npy`` bytes), and
  the commitment proof bytes derived from the final scores through the
  real prover path (``zk.proof.PoseidonCommitmentProver``);
- **asserted within each run**: per-epoch cross-host score/residual
  agreement (the pod either agrees bit-for-bit or is broken), and the
  fleet-directory merge reaching the same aggregate regardless of
  scan interleaving.

``--seed-divergence`` is the CI self-check: it perturbs the one knob a
replay is *allowed* to depend on (the protocol seed) in the second
schedule, so every digest leg must trip and the probe must exit 1 —
proving the comparator actually compares.

Run::

    python tools/divergence_probe.py --smoke --out DET_smoke.json
    python tools/divergence_probe.py --peers 4096 --edges 32768 \
        --epochs 3 --round 1 --out DET_r01.json

Exit 0 = every sink bit-identical across both schedules (or the jax
build has no multi-process CPU collectives: ``skipped``); 1 =
divergence.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import subprocess
import sys
import tempfile
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

DRYRUN = REPO / "tools" / "dryrun_pod.py"

#: The two perturbed schedules.  Everything here is a knob a correct
#: replay must be INVARIANT to; the protocol seed (the one legitimate
#: input) is held fixed across both.
SCHEDULES: tuple[dict, ...] = (
    {
        "name": "baseline",
        "hashseed": "1",
        "omp_threads": "2",
        "reverse_launch": False,
        "launch_stagger": 0.0,
        "decoy_order": (0, 1, 2),
        "scrape_interval": 0.05,
    },
    {
        "name": "perturbed",
        "hashseed": "31337",
        "omp_threads": "1",
        "reverse_launch": True,
        "launch_stagger": 0.25,
        "decoy_order": (2, 0, 1),
        "scrape_interval": 0.013,
    },
)


def _sha256_bytes(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _canonical_json_digest(obj) -> str:
    return _sha256_bytes(
        json.dumps(obj, sort_keys=True, separators=(",", ":")).encode()
    )


# ---------------------------------------------------------------------------
# fleet-directory scrape interleaving
# ---------------------------------------------------------------------------

#: Fixed-content decoy snapshots (obs.fleet snapshot schema): only
#: their creation ORDER and the scrape cadence differ per schedule, so
#: any aggregate difference is a scan-order dependence in the merge.
_DECOYS = tuple(
    {
        "version": 1,
        "pid": 900000 + i,
        "source": f"decoy-{i}",
        "taken_unix": 0,
        "metrics": {
            "probe_decoy_total": {
                "kind": "counter",
                "help": "divergence-probe decoy series",
                "labelnames": ["decoy"],
                "samples": [[[str(i)], float(10 * (i + 1))]],
            }
        },
    }
    for i in range(3)
)


def _write_decoys(fleet_dir: Path, order: tuple[int, ...]) -> None:
    fleet_dir.mkdir(parents=True, exist_ok=True)
    for i in order:
        path = fleet_dir / f"fleet-decoy{i}.json"
        path.write_text(json.dumps(_DECOYS[i]) + "\n")
        time.sleep(0.01)  # distinct mtimes: a real creation-order skew


class _Scraper(threading.Thread):
    """Concurrent fleet-directory merge during the replay — the scrape
    interleaving leg.  Owns a private aggregator so two schedules'
    merges never share state."""

    def __init__(self, fleet_dir: Path, interval: float):
        super().__init__(daemon=True)
        from protocol_tpu.obs.fleet import FleetAggregator

        self.fleet_dir = fleet_dir
        self.interval = interval
        self.aggregator = FleetAggregator()
        self.scrapes = 0
        self._halt = threading.Event()

    def run(self) -> None:
        from protocol_tpu.obs.fleet import load_directory

        while not self._halt.is_set():
            load_directory(self.fleet_dir, self.aggregator)
            self.scrapes += 1
            self._halt.wait(self.interval)

    def finish(self) -> dict:
        self._halt.set()
        self.join(timeout=10.0)
        return {
            "scrapes": self.scrapes,
            "sources": self.aggregator.sources(),
            "aggregate_sha256": _canonical_json_digest(
                self.aggregator.snapshots()
            ),
        }


# ---------------------------------------------------------------------------
# one schedule = one full pod replay
# ---------------------------------------------------------------------------


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_cmd(args, i: int, coordinator: str, state: Path, out: Path,
                scores: Path | None, seed: int) -> list[str]:
    cmd = [
        sys.executable, str(DRYRUN),
        "--worker", str(i),
        "--coordinator", coordinator,
        "--state-dir", str(state),
        "--worker-out", str(out),
        "--peers", str(args.peers), "--edges", str(args.edges),
        "--epochs", str(args.epochs), "--churn", str(args.churn),
        "--processes", str(args.processes),
        "--local-devices", str(args.local_devices),
        "--seed", str(seed), "--tol", str(args.tol),
        "--max-iter", str(args.max_iter),
        "--seal-timeout", str(args.seal_timeout),
        "--skip-scrape",
    ]
    if scores is not None and i == 0:
        cmd += ["--dump-scores", str(scores)]
    return cmd


def run_schedule(args, sched: dict, workdir: Path, *, seed: int) -> dict:
    """One perturbed full replay; returns the run record with every
    sink digested."""
    state = workdir / f"state-{sched['name']}"
    out_dir = workdir / f"out-{sched['name']}"
    fleet_dir = workdir / f"fleet-{sched['name']}"
    state.mkdir(parents=True, exist_ok=True)
    out_dir.mkdir(parents=True, exist_ok=True)
    scores_path = workdir / f"scores-{sched['name']}.npy"
    _write_decoys(fleet_dir, sched["decoy_order"])

    env = dict(os.environ)
    env["PYTHONHASHSEED"] = sched["hashseed"]
    env["OMP_NUM_THREADS"] = sched["omp_threads"]

    coordinator = f"127.0.0.1:{_free_port()}"
    outs = [out_dir / f"worker{i}.json" for i in range(args.processes)]
    order = list(range(args.processes))
    if sched["reverse_launch"]:
        order.reverse()

    scraper = _Scraper(fleet_dir, sched["scrape_interval"])
    scraper.start()
    t0 = time.perf_counter()
    procs: dict[int, subprocess.Popen] = {}
    for i in order:
        procs[i] = subprocess.Popen(
            _worker_cmd(args, i, coordinator, state, outs[i], scores_path, seed),
            cwd=REPO, env=env,
        )
        if sched["launch_stagger"]:
            time.sleep(sched["launch_stagger"])

    rcs: list[int | None] = [None] * args.processes
    deadline = time.monotonic() + args.timeout
    while any(rc is None for rc in rcs):
        for i, p in procs.items():
            if rcs[i] is None:
                rcs[i] = p.poll()
        if time.monotonic() > deadline:
            for i, p in procs.items():
                if rcs[i] is None:
                    p.kill()
                    rcs[i] = -9
            break
        time.sleep(0.2)
    for p in procs.values():
        p.wait()
    wall = time.perf_counter() - t0
    fleet = scraper.finish()

    workers = []
    for path in outs:
        try:
            workers.append(json.loads(path.read_text()))
        except (OSError, json.JSONDecodeError):
            workers.append({"ok": False, "error": "no worker report"})

    # -- digest every sink ------------------------------------------------
    acks = {
        p.name: _sha256_bytes(p.read_bytes())
        for p in sorted(state.rglob("acks-h*.jsonl"))
    }
    # Checkpoint manifests + pod manifests + shard stamps: canonical
    # JSON digests keyed by state-relative path.  All of these are
    # deterministic JSON by contract — any wall-clock or pid that leaks
    # in shows up here as a cross-run mismatch.
    manifests = {}
    for p in sorted(state.rglob("*.json")):
        try:
            manifests[str(p.relative_to(state))] = _canonical_json_digest(
                json.loads(p.read_text())
            )
        except (OSError, json.JSONDecodeError):
            manifests[str(p.relative_to(state))] = "unreadable"
    epoch_digests = [
        {
            "epoch": ep["epoch"],
            "residual": ep["residual"],
            "scores_sha256": ep["scores_sha256"],
        }
        for ep in (workers[0].get("epochs") or [])
    ]
    # Cross-host agreement within this run (every host holds the
    # replicated vector — exact equality, not a tolerance).
    by_epoch: dict[int, set] = {}
    for w in workers:
        for ep in w.get("epochs") or []:
            by_epoch.setdefault(ep["epoch"], set()).add(
                (ep["residual"], ep["scores_sha256"])
            )
    cross_host_ok = bool(by_epoch) and all(
        len(v) == 1 for v in by_epoch.values()
    )

    scores_sha = None
    proof = None
    if scores_path.exists():
        scores_sha = _sha256_bytes(scores_path.read_bytes())
        proof = _proof_digest(scores_path)

    return {
        "schedule": {k: v for k, v in sched.items()},
        "seed": seed,
        "return_codes": rcs,
        "workers_ok": [bool(w.get("ok")) for w in workers],
        "skipped": all(w.get("skipped") for w in workers),
        "wall_seconds": round(wall, 4),
        "wal_ack_digests": acks,
        "manifest_digests": manifests,
        "epoch_digests": epoch_digests,
        "cross_host_bit_identity": cross_host_ok,
        "final_scores_sha256": [
            w.get("final_scores_sha256") for w in workers
        ],
        "scores_npy_sha256": scores_sha,
        "proof": proof,
        "fleet": fleet,
    }


def _proof_digest(scores_path: Path) -> dict:
    """Commitment proof bytes over the final fixed point, through the
    real prover path: quantized scores as public inputs, the leading
    rows as witness ops.  A pure function of the replay output — two
    bit-identical replays must produce byte-identical proofs."""
    import numpy as np

    from protocol_tpu.zk.proof import PoseidonCommitmentProver

    scores = np.load(scores_path)
    scale = 1 << 24
    pub_ins = [int(round(float(x) * scale)) for x in scores[:64]]
    ops = [pub_ins[:16], pub_ins[16:32]]
    prover = PoseidonCommitmentProver()
    proof = prover.prove(pub_ins, {"ops": ops})
    return {
        "prover": prover.name,
        "proof_bytes": len(proof),
        "proof_sha256": _sha256_bytes(proof),
        "verified": bool(prover.verify(pub_ins, proof)),
    }


# ---------------------------------------------------------------------------
# cross-run comparison
# ---------------------------------------------------------------------------


def compare_runs(a: dict, b: dict) -> dict:
    """Leg-by-leg equality verdicts across two perturbed replays.
    Every leg must hold; the record names each one so DET_r*.json reads
    as the sentinel-shaped evidence table."""
    legs = {
        "return_codes": a["return_codes"] == b["return_codes"] == [0] * len(
            a["return_codes"]
        ),
        "workers_ok": all(a["workers_ok"]) and all(b["workers_ok"]),
        "wal_ack_digests": (
            a["wal_ack_digests"] == b["wal_ack_digests"]
            and bool(a["wal_ack_digests"])
        ),
        "manifest_digests": (
            a["manifest_digests"] == b["manifest_digests"]
            and bool(a["manifest_digests"])
            and "unreadable" not in a["manifest_digests"].values()
        ),
        "epoch_digests": (
            a["epoch_digests"] == b["epoch_digests"]
            and bool(a["epoch_digests"])
        ),
        "cross_host_bit_identity": (
            a["cross_host_bit_identity"] and b["cross_host_bit_identity"]
        ),
        "final_scores_sha256": (
            a["final_scores_sha256"] == b["final_scores_sha256"]
            and len(set(a["final_scores_sha256"])) == 1
            and a["final_scores_sha256"][0] is not None
        ),
        "scores_npy_bytes": (
            a["scores_npy_sha256"] == b["scores_npy_sha256"]
            and a["scores_npy_sha256"] is not None
        ),
        "proof_bytes": (
            a["proof"] is not None
            and b["proof"] is not None
            and a["proof"]["proof_sha256"] == b["proof"]["proof_sha256"]
            and a["proof"]["verified"]
            and b["proof"]["verified"]
        ),
        "fleet_merge_order_insensitive": (
            a["fleet"]["aggregate_sha256"] == b["fleet"]["aggregate_sha256"]
            and a["fleet"]["sources"] == b["fleet"]["sources"]
            and bool(a["fleet"]["sources"])
        ),
    }
    return {"ok": all(legs.values()), "legs": legs}


# ---------------------------------------------------------------------------
# main
# ---------------------------------------------------------------------------


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="DET_smoke.json")
    ap.add_argument("--peers", type=int, default=4096)
    ap.add_argument("--edges", type=int, default=32768)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--churn", type=float, default=0.01)
    ap.add_argument("--processes", type=int, default=2)
    ap.add_argument("--local-devices", type=int, default=2)
    ap.add_argument("--seed", type=int, default=16)
    ap.add_argument("--tol", type=float, default=1e-6)
    ap.add_argument("--max-iter", type=int, default=60)
    ap.add_argument("--seal-timeout", type=float, default=120.0)
    ap.add_argument("--timeout", type=float, default=1800.0)
    ap.add_argument("--smoke", action="store_true", help="CI scale")
    ap.add_argument(
        "--seed-divergence", action="store_true",
        help="self-check: perturb the protocol seed in schedule 2 so "
        "every digest leg must trip and the probe must exit 1",
    )
    ap.add_argument("--round", type=int, default=0)
    ap.add_argument("--workdir", default=None)
    args = ap.parse_args(argv)

    if args.smoke:
        args.peers = min(args.peers, 2048)
        args.edges = min(args.edges, 16384)
        args.epochs = min(args.epochs, 3)

    tmp_ctx = tempfile.TemporaryDirectory() if args.workdir is None else None
    workdir = Path(args.workdir or tmp_ctx.name)
    workdir.mkdir(parents=True, exist_ok=True)
    try:
        runs = []
        for idx, sched in enumerate(SCHEDULES):
            seed = args.seed + (
                1 if (args.seed_divergence and idx == 1) else 0
            )
            runs.append(run_schedule(args, sched, workdir, seed=seed))
    finally:
        if tmp_ctx is not None:
            tmp_ctx.cleanup()

    skipped = all(r["skipped"] for r in runs)
    comparison = None if skipped else compare_runs(runs[0], runs[1])
    ok = skipped or comparison["ok"]

    scale = f"{args.peers} peers/{args.edges} edges"
    mesh = f"{args.processes}x{args.local_devices}"
    entries: list[dict] = []
    if not skipped:
        entries.append({
            "metric": (
                f"divergence probe full-replay wall-clock ({scale}, "
                f"{mesh} mesh, {args.epochs} epochs, per perturbed "
                "schedule)"
            ),
            "value": round(
                sum(r["wall_seconds"] for r in runs) / len(runs), 4
            ),
            "unit": "seconds",
            "n_hosts": args.processes,
            "per_schedule_seconds": [r["wall_seconds"] for r in runs],
            "legs_checked": (
                sorted(comparison["legs"]) if comparison else []
            ),
        })

    report = {
        "tool": "divergence_probe",
        "round": args.round,
        "mesh": mesh,
        "n_hosts": args.processes,
        "n_cpus": os.cpu_count(),
        "params": {
            "peers": args.peers, "edges": args.edges,
            "epochs": args.epochs, "churn": args.churn,
            "tol": args.tol, "max_iter": args.max_iter,
            "seed": args.seed,
        },
        "seed_divergence_mode": bool(args.seed_divergence),
        "ok": bool(ok),
        "skipped": skipped,
        "comparison": comparison,
        "entries": entries,
        "runs": runs,
    }
    Path(args.out).write_text(json.dumps(report, indent=2) + "\n")
    status = (
        "SKIPPED (no multi-process CPU collectives)" if skipped
        else ("OK" if ok else "FAILED")
    )
    print(f"divergence_probe: {status} — report in {args.out}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
