"""Perf-regression sentinel over the committed bench trajectory.

The repo has recorded every bench round since PR 1 (``BENCH_r*.json``,
``LADDER_r*.json``, since ISSUE 7 the ingest-storm rounds
``INGEST_r*.json``, since ISSUE 9 the multichip comm rounds
``MULTICHIP_r*.json``, since ISSUE 10 the proving-plane rounds
``PROVER_r*.json``, since ISSUE 11 the fleet-observability rounds
``OBS_r*.json``, since ISSUE 14 the crash-matrix rounds
``CHAOS_r*.json``, since ISSUE 15 the memory-probe rounds
``MEM_r*.json``, since ISSUE 16 the pod scale-out rounds
``POD_r*.json``, since ISSUE 18 the divergence-probe rounds
``DET_r*.json``, and since ISSUE 20 the zk kernel rounds
``MSM_r*.json``) but nothing ever *read* the series — a PR could
halve headline throughput and no gate would notice.  This tool closes
the loop: it parses the recorded rounds into per-metric series
(headline convergence seconds, cold/steady-state epoch seconds, plan
build seconds, sigs/s, power-iters/s, p99 admission latency,
per-iteration collective bytes), optionally
folds in a fresh bench entry, and exits non-zero when the newest value
regresses more than ``--threshold`` against the best value the repo has
ever recorded.

Series are keyed by the exact ``metric`` string plus the field name,
so differently-shaped runs (CI smoke vs the recorded 1M-peer rounds)
never get compared against each other; a fresh entry with no matching
history is reported as ``no-baseline`` and cannot fail the gate.
Multi-host entries additionally key on ``n_hosts`` (``[n_hosts=N]``
suffix for N > 1): a 2-host pod number and a single-host number for
the same metric string are different series by construction —
single-host entries keep their historical keys unsuffixed.

Directionality: ``*seconds*`` metrics regress upward, throughput
metrics (``*/s``, ``*per_sec*``) regress downward.

Run (CI ``perf-sentinel`` job)::

    python tools/perf_sentinel.py --out SENTINEL.json
    python tools/perf_sentinel.py --fresh FRESH.json --threshold 0.10

Exit code 0 = no regression; 1 = regression (details in SENTINEL.json
and on stderr); 2 = usage/parse error.
"""

from __future__ import annotations

import argparse
import glob as globlib
import json
import re
import sys
from pathlib import Path
from typing import Any, Iterator

#: Named numeric fields lifted from a bench entry into their own
#: series: field -> lower_is_better.
_FIELDS = {
    "value": None,  # direction inferred from unit/metric
    "plan_seconds": True,
    "cold_epoch_seconds": True,
    "steady_state_epoch_seconds": True,
    "sigs_per_s": False,
    "power_iters_per_sec": False,
    "p99_admission_ms": True,
    # Proving-plane rounds (PROVER_r*.json): submit→proved tail latency
    # and sustained proof throughput under the churned epoch replay.
    "p99_proof_lag_ms": True,
    "sustained_proofs_per_s": False,
    # Fleet-observability rounds (OBS_r*.json): end-to-end freshness
    # (attestation accepted → proof landed for the including epoch) and
    # the lineage+SLO instrumentation overhead against the steady-state
    # epoch — a regressing observability plane fails the gate like any
    # other hot path.
    "freshness_p99_ms": True,
    "obs_overhead_pct": True,
    # Pod-observability rounds (OBS_r*.json with n_hosts > 1, ISSUE
    # 19): host-0 stitch latency for the pod epoch trace and the
    # worst clock-aligned per-phase host skew — a pod that starts
    # dragging a phase regresses this series before it trips the SLO.
    "stitch_ms": True,
    "phase_skew_p99_ms": True,
    # Pass-8 comm scrape (MULTICHIP/LADDER rounds): per-iteration
    # collective wire volume of the sharded composites — a partitioner
    # surprise that inflates traffic regresses this series upward.
    "comm_bytes_per_iter": True,
    # Crash-matrix rounds (CHAOS_r*.json): median kill -9 → serving
    # recovery wall-clock, and the WAL's fsync cost as a percentage of
    # the steady epoch — a slower recovery or a heavier log both
    # regress the durability plane.
    "recovery_seconds": True,
    "wal_overhead_pct": True,
    # Pass-12 memory scrape (MEM_r*/MULTICHIP rounds): measured peak
    # device bytes of the converge executables, total and per shard —
    # a silently materialized O(E) temporary or a replicated edge
    # operand regresses these series upward before it fails the wall.
    "peak_hbm_bytes": True,
    "peak_hbm_bytes_per_shard": True,
    # Pod scale-out rounds (POD_r*.json): the pod's plan-build critical
    # path (max per-host partition build — the PERF.md §11 serial
    # bottleneck, attacked by host-sharding) and its speedup over the
    # serial full-graph build.
    "plan_build_seconds": True,
    "plan_build_speedup": False,
    # ZK kernel rounds (MSM_r*.json, ISSUE 20): Pippenger MSM and NTT
    # throughput per zk_backend/size (the proving plane's inner loops)
    # and the full epoch prove wall — the metric string carries the
    # backend, so a graft-lowering regression and a native-runtime
    # regression are separate series.
    "msm_points_per_s": False,
    "ntt_butterflies_per_s": False,
    "prove_seconds": True,
}


def _series_key(entry: dict[str, Any], fld: str) -> str:
    """``<metric> :: <field>`` plus an ``[n_hosts=N]`` marker for
    multi-host entries — pod rounds never collide with a single-host
    series for the same metric string, while ``n_hosts: 1`` (and
    legacy entries without the field) keep their historical keys."""
    key = f"{entry['metric']} :: {fld}"
    n_hosts = entry.get("n_hosts")
    if isinstance(n_hosts, int) and n_hosts > 1:
        key += f" [n_hosts={n_hosts}]"
    return key


def _lower_is_better(field: str, entry: dict[str, Any]) -> bool | None:
    fixed = _FIELDS.get(field)
    if fixed is not None:
        return fixed
    unit = str(entry.get("unit", ""))
    metric = str(entry.get("metric", ""))
    if "second" in unit or "seconds" in metric:
        return True
    if re.search(r"(/s\b|per_sec|per second)", unit + " " + metric):
        return False
    return None  # unknown: not gated


def _is_non_bench_artifact(obj: Any) -> bool:
    """True for sibling CI artifacts that are not bench rounds — the
    graftlint report (ANALYSIS*.json: "tool" + "findings") and the
    sanitizer wall (SANITIZER*.json: "sanflags"/"mode" + "runs").
    They may land in the history directory (or an over-broad --glob);
    the sentinel skips them instead of mining them for numbers."""
    if not isinstance(obj, dict):
        return False
    if "tool" in obj and "findings" in obj:
        return True
    return "runs" in obj and ("sanflags" in obj or "mode" in obj)


def _entries(obj: Any) -> Iterator[dict[str, Any]]:
    """Every bench entry inside one parsed JSON document: driver
    records ({"parsed": {...}}), ladder reports ({"ladder": [...]}),
    ingest-storm reports ({"entries": [...]}), bare entries, or lists
    of any of those."""
    if isinstance(obj, list):
        for item in obj:
            yield from _entries(item)
        return
    if not isinstance(obj, dict):
        return
    if "parsed" in obj and isinstance(obj["parsed"], dict):
        yield from _entries(obj["parsed"])
        return
    if "ladder" in obj and isinstance(obj["ladder"], list):
        yield from _entries(obj["ladder"])
        return
    if "entries" in obj and isinstance(obj["entries"], list):
        yield from _entries(obj["entries"])
        return
    if "metric" in obj:
        yield obj


def _round_of(path: Path, obj: Any) -> int:
    if isinstance(obj, dict) and isinstance(obj.get("n"), int):
        return obj["n"]
    m = re.search(r"_r(\d+)", path.name)
    return int(m.group(1)) if m else 0


def collect_series(paths: list[Path]) -> dict[str, list[dict[str, Any]]]:
    """{series key: [{round, value, lower_is_better, source}, ...]}
    sorted by round.  A series key is ``<metric string> :: <field>``."""
    series: dict[str, list[dict[str, Any]]] = {}
    for path in sorted(paths):
        try:
            obj = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError) as exc:
            print(f"perf_sentinel: skipping {path}: {exc}", file=sys.stderr)
            continue
        if _is_non_bench_artifact(obj):
            print(
                f"perf_sentinel: ignoring non-bench artifact {path.name}",
                file=sys.stderr,
            )
            continue
        rnd = _round_of(path, obj)
        for entry in _entries(obj):
            for fld in _FIELDS:
                val = entry.get(fld)
                if not isinstance(val, (int, float)) or isinstance(val, bool):
                    continue
                direction = _lower_is_better(fld, entry)
                if direction is None:
                    continue
                key = _series_key(entry, fld)
                series.setdefault(key, []).append(
                    {
                        "round": rnd,
                        "value": float(val),
                        "lower_is_better": direction,
                        "source": path.name,
                    }
                )
    for points in series.values():
        points.sort(key=lambda p: (p["round"], p["source"]))
    return series


def evaluate(
    series: dict[str, list[dict[str, Any]]],
    fresh: dict[str, float] | None,
    threshold: float,
) -> dict[str, Any]:
    """Per-series verdict: the candidate (the fresh value when one
    matches, else the newest recorded round) against the best recorded
    value.  ``regressed`` iff candidate is worse than best by more
    than ``threshold`` (relative)."""
    report: dict[str, Any] = {"series": {}, "regressions": []}
    fresh = fresh or {}
    seen_fresh: set[str] = set()
    for key, points in sorted(series.items()):
        lower = points[0]["lower_is_better"]
        values = [p["value"] for p in points]
        best = min(values) if lower else max(values)
        candidate = fresh.get(key)
        source = "fresh"
        if candidate is None:
            candidate = points[-1]["value"]
            source = points[-1]["source"]
        else:
            seen_fresh.add(key)
        if lower:
            delta = (candidate - best) / best if best > 0 else 0.0
        else:
            delta = (best - candidate) / best if best > 0 else 0.0
        regressed = delta > threshold
        row = {
            "best": best,
            "candidate": candidate,
            "candidate_source": source,
            "rounds": len(points),
            "lower_is_better": lower,
            "delta_vs_best": round(delta, 4),
            "status": "REGRESSED" if regressed else "ok",
        }
        report["series"][key] = row
        if regressed:
            report["regressions"].append(key)
    for key in sorted(set(fresh) - seen_fresh):
        report["series"][key] = {
            "best": None,
            "candidate": fresh[key],
            "candidate_source": "fresh",
            "rounds": 0,
            "status": "no-baseline",
        }
    report["ok"] = not report["regressions"]
    return report


def load_fresh(path: Path) -> dict[str, float]:
    """Flatten a fresh bench document into {series key: value}."""
    obj = json.loads(path.read_text())
    out: dict[str, float] = {}
    for entry in _entries(obj):
        for fld in _FIELDS:
            val = entry.get(fld)
            if not isinstance(val, (int, float)) or isinstance(val, bool):
                continue
            if _lower_is_better(fld, entry) is None:
                continue
            out[_series_key(entry, fld)] = float(val)
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--history",
        default=None,
        help="directory holding the recorded BENCH_r*/LADDER_r* rounds "
        "(default: the repo root this script lives in)",
    )
    ap.add_argument(
        "--glob",
        action="append",
        default=None,
        help="history filename glob(s); default: BENCH_r*.json, "
        "LADDER_r*.json, INGEST_r*.json, MULTICHIP_r*.json, "
        "PROVER_r*.json, OBS_r*.json, CHAOS_r*.json, MEM_r*.json, "
        "POD_r*.json, DET_r*.json, and MSM_r*.json",
    )
    ap.add_argument(
        "--fresh",
        default=None,
        help="JSON file with a fresh bench entry (bench.py output) to "
        "gate against the recorded best",
    )
    ap.add_argument(
        "--threshold",
        type=float,
        default=0.15,
        help="relative regression tolerance vs the best recorded value "
        "(default 0.15 = 15%%)",
    )
    ap.add_argument("--out", default="SENTINEL.json", help="report path")
    args = ap.parse_args(argv)

    root = Path(args.history) if args.history else Path(__file__).resolve().parent.parent
    patterns = args.glob or [
        "BENCH_r*.json",
        "LADDER_r*.json",
        "INGEST_r*.json",
        "MULTICHIP_r*.json",
        "PROVER_r*.json",
        "OBS_r*.json",
        "CHAOS_r*.json",
        "MEM_r*.json",
        "POD_r*.json",
        "DET_r*.json",
        "MSM_r*.json",
    ]
    paths = [
        Path(p) for pat in patterns for p in globlib.glob(str(root / pat))
    ]
    if not paths:
        print(f"perf_sentinel: no history matches {patterns} under {root}", file=sys.stderr)
        return 2

    series = collect_series(paths)
    fresh = None
    if args.fresh:
        try:
            fresh = load_fresh(Path(args.fresh))
        except (OSError, json.JSONDecodeError, KeyError) as exc:
            print(f"perf_sentinel: bad --fresh file: {exc}", file=sys.stderr)
            return 2

    report = evaluate(series, fresh, args.threshold)
    report["threshold"] = args.threshold
    report["history_files"] = sorted(p.name for p in paths)
    Path(args.out).write_text(json.dumps(report, indent=2) + "\n")

    for key, row in report["series"].items():
        print(f"  [{row['status']:>11}] {key}: best={row['best']} "
              f"candidate={row['candidate']} ({row['candidate_source']})")
    if report["regressions"]:
        print(
            f"perf_sentinel: {len(report['regressions'])} metric(s) regressed "
            f">{args.threshold:.0%} vs the best recorded value:",
            file=sys.stderr,
        )
        for key in report["regressions"]:
            row = report["series"][key]
            print(
                f"  {key}: best {row['best']} -> {row['candidate']} "
                f"(+{row['delta_vs_best']:.1%}, {row['candidate_source']})",
                file=sys.stderr,
            )
        return 1
    print(f"perf_sentinel: OK — {len(report['series'])} series within "
          f"{args.threshold:.0%} of their best ({args.out} written)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
