"""Generate METRICS.md — the auto-generated metric catalog.

The registry (``protocol_tpu/obs/metrics.py``) is the single source of
truth for every metric the node emits; this tool renders it as a
markdown table (name, type, labels, help) so the catalog in the repo
can never drift silently: ``tests/test_obs_fleet.py`` regenerates the
document in-memory and fails when the committed METRICS.md differs —
an emitted-but-undocumented metric (or a stale doc row) fails tier-1,
not a reviewer's memory.

Run: ``python tools/gen_metrics_md.py [--check]``
(``--check`` exits non-zero instead of rewriting, for CI.)
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

HEADER = """\
# Metric catalog

Auto-generated from the registry in `protocol_tpu/obs/metrics.py` by
`tools/gen_metrics_md.py` — do not edit by hand; regenerate after any
metric change (`tests/test_obs_fleet.py::TestMetricsCatalogDoc` fails
on drift).  Every metric is served at `GET /metrics` (Prometheus
exposition format); `GET /metrics/fleet` serves the same series merged
across worker/sibling processes with a `process` label.

| Metric | Type | Labels | Help |
|---|---|---|---|
"""


def _escape_cell(text: str) -> str:
    return text.replace("|", "\\|").replace("\n", " ")


def metrics_markdown() -> str:
    """The catalog document, rendered from the live registry."""
    # Importing the metrics module registers the full catalog; the
    # repo convention keeps every metric declaration there (worker
    # and analyzer modules reuse those objects), so one import sees
    # everything the node can emit.
    from protocol_tpu.obs.metrics import METRICS

    rows = []
    for metric in sorted(METRICS.collect(), key=lambda m: m.name):
        labels = ", ".join(metric.labelnames) if metric.labelnames else "—"
        rows.append(
            f"| `{metric.name}` | {metric.kind} | {labels} "
            f"| {_escape_cell(metric.help)} |"
        )
    return HEADER + "\n".join(rows) + "\n"


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--out",
        default=str(Path(__file__).resolve().parent.parent / "METRICS.md"),
    )
    ap.add_argument(
        "--check",
        action="store_true",
        help="exit 1 if the committed catalog differs (CI mode)",
    )
    args = ap.parse_args(argv)
    doc = metrics_markdown()
    out = Path(args.out)
    if args.check:
        current = out.read_text() if out.exists() else ""
        if current != doc:
            print(
                f"gen_metrics_md: {out} is stale — regenerate with "
                "`python tools/gen_metrics_md.py`",
                file=sys.stderr,
            )
            return 1
        print(f"gen_metrics_md: {out} is current")
        return 0
    out.write_text(doc)
    print(f"gen_metrics_md: wrote {out} ({doc.count(chr(10)) - 10} metrics)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
