"""Multi-chip dry run + pass-8 comm scrape -> ``MULTICHIP_r<N>.json``.

The driver's ``dryrun_multichip`` entry (``__graft_entry__.py``) proves
the sharded path *computes* correctly on an n-device mesh; this tool
runs the same dual-kernel dry run and additionally records what the
run *communicates*: for each sharded composite it scrapes the compiled
module with the graftlint pass-8 walker and persists the per-epoch
collective table — kind, replica groups, per-iteration byte volume —
next to the correctness verdict.  The ``entries`` list is shaped for
``tools/perf_sentinel.py``, which tracks ``comm_bytes_per_iter`` as a
per-metric series: a PR that silently inflates wire traffic (a
partitioner surprise at a new jax pin, a resharding regression) now
moves a recorded number, not just a lint bit.

Self-provisions the mesh exactly like the driver entry: without enough
real devices it re-execs itself on a virtual CPU mesh.

Run: ``python tools/dryrun_multichip.py [--devices 8] [--out PATH]``
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def _next_round_path() -> str:
    """``MULTICHIP_r<N>.json`` with N following the highest recorded
    multichip round (same convention as bench.py's ladder rounds)."""
    rounds = [0]
    for p in REPO.glob("MULTICHIP_r*.json"):
        m = re.fullmatch(r"MULTICHIP_r(\d+)\.json", p.name)
        if m:
            rounds.append(int(m.group(1)))
    return str(REPO / f"MULTICHIP_r{max(rounds) + 1:02d}.json")


def _scrape(backend: str, lowered) -> dict:
    """Pass-8 walk + pass-12 buffer-assignment view of one compiled
    runner module."""
    from protocol_tpu.analysis.comm.hlo_walk import parse_module

    compiled = lowered.compile()
    mod = parse_module(compiled.as_text())
    out = {
        "collectives": [op.to_dict() for op in mod.collectives],
        "bytes_per_iter": mod.total_bytes(per_iteration_only=True),
        "input_output_alias": {
            str(k): v for k, v in sorted(mod.aliases.items())
        },
        "host_round_trips": len(mod.host_calls),
    }
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 - absent on some runtimes
        ma = None
    if ma is not None:
        # memory_analysis is the PER-DEVICE view: under the mesh this
        # is the per-shard footprint the MEM_INVARIANTS budgets pin.
        out["peak_hbm_bytes_per_shard"] = int(
            ma.argument_size_in_bytes
            + ma.output_size_in_bytes
            - ma.alias_size_in_bytes
            + ma.temp_size_in_bytes
        )
    return out


def _body(n_devices: int, n_peers: int, n_edges: int, iters: int) -> dict:
    import jax.numpy as jnp
    import numpy as np

    from protocol_tpu.models.graphs import scale_free
    from protocol_tpu.parallel.mesh import default_mesh
    from protocol_tpu.parallel.sharded import (
        ShardedTrustProblem,
        ShardedWindowPlan,
        _get_runner,
        _get_windowed_runner,
        converge_sharded,
    )

    mesh = default_mesh(n_devices)
    graph = scale_free(n_peers, n_edges, seed=1)
    alpha = jnp.asarray(0.1, jnp.float32)

    prob = ShardedTrustProblem.build(graph, mesh)
    t, iters_run, resid = converge_sharded(
        prob, alpha=0.1, tol=1e-6, max_iter=iters
    )
    scores = np.asarray(t)
    assert scores.shape == (graph.n,)
    assert abs(float(scores.sum()) - 1.0) < 1e-3

    swp = ShardedWindowPlan.build(graph, mesh)
    tw, _, _ = converge_sharded(swp, alpha=0.1, tol=1e-6, max_iter=iters)
    drift = float(np.abs(np.asarray(tw) - scores).sum())
    assert drift < 1e-4, f"windowed sharded kernel drifted from csr: {drift}"

    csr_run = _get_runner(mesh, prob.n)
    comm = {
        "tpu-sharded:tpu-csr": _scrape(
            "tpu-sharded:tpu-csr",
            csr_run.lower(
                prob.src, prob.w, prob.row_ptr, prob.t0(), prob.p,
                prob.dangling, alpha, max_iter=iters, tol=1e-6,
            ),
        )
    }
    win_run = _get_windowed_runner(
        mesh, swp.n, swp.rows_per_shard, swp.table_entries, swp.interpret
    )
    comm["tpu-sharded:tpu-windowed"] = _scrape(
        "tpu-sharded:tpu-windowed",
        win_run.lower(
            swp.wid, swp.local, swp.weight, swp.seg_end, swp.seg_first,
            swp.seg_perm, swp.dst_ptr, swp.t0(), swp.p, swp.dangling,
            alpha, max_iter=iters, tol=1e-6,
        ),
    )

    entries = [
        {
            "metric": (
                f"per-iteration collective bytes ({backend}, "
                f"{n_devices}-dev mesh, {graph.n} peers/{n_edges} edges)"
            ),
            "comm_bytes_per_iter": scraped["bytes_per_iter"],
            "unit": "bytes",
        }
        for backend, scraped in comm.items()
    ]
    # Pass-12 series: per-process (= per-shard) converge peaks, so a
    # PR that inflates the per-host footprint moves a recorded number.
    entries += [
        {
            "metric": (
                f"per-shard converge peak HBM bytes ({backend}, "
                f"{n_devices}-dev mesh, {graph.n} peers/{n_edges} edges)"
            ),
            "peak_hbm_bytes_per_shard": scraped["peak_hbm_bytes_per_shard"],
            "unit": "bytes",
        }
        for backend, scraped in comm.items()
        if "peak_hbm_bytes_per_shard" in scraped
    ]
    return {
        "n_devices": n_devices,
        "rc": 0,
        "ok": True,
        "skipped": False,
        "n_peers": graph.n,
        "n_edges": int(n_edges),
        "iterations": int(iters_run),
        "residual": float(resid),
        "windowed_vs_csr_l1": drift,
        "comm": comm,
        "entries": entries,
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--peers", type=int, default=512)
    ap.add_argument("--edges", type=int, default=4096)
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument(
        "--out",
        default=None,
        help="report path (default: MULTICHIP_r<N>.json, next round)",
    )
    args = ap.parse_args(argv)
    out = args.out or _next_round_path()

    import jax

    if len(jax.devices()) < args.devices:
        # Not enough real devices — re-exec on a virtual CPU mesh (the
        # __graft_entry__.dryrun_multichip doctrine: the env var alone
        # is not enough when a site hook pins the platform, so the
        # child also overrides jax_platforms before backend init).
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        flag = f"--xla_force_host_platform_device_count={args.devices}"
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" in flags:
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", flag, flags
            )
        else:
            flags = (flags + " " + flag).strip()
        env["XLA_FLAGS"] = flags
        env["PROTOCOL_TPU_MULTICHIP_CHILD"] = "1"
        proc = subprocess.run(
            [
                sys.executable, __file__,
                "--devices", str(args.devices),
                "--peers", str(args.peers),
                "--edges", str(args.edges),
                "--iters", str(args.iters),
                "--out", out,
            ],
            env=env,
            cwd=REPO,
        )
        return proc.returncode

    if os.environ.get("PROTOCOL_TPU_MULTICHIP_CHILD"):
        jax.config.update("jax_platforms", "cpu")

    report = _body(args.devices, args.peers, args.edges, args.iters)
    Path(out).write_text(json.dumps(report, indent=2) + "\n")
    bytes_by_backend = {
        b: c["bytes_per_iter"] for b, c in report["comm"].items()
    }
    print(
        f"dryrun_multichip: {args.devices} devices, n={report['n_peers']}, "
        f"{report['iterations']} iters, residual {report['residual']:.2e}, "
        f"windowed drift {report['windowed_vs_csr_l1']:.2e}, "
        f"collective bytes/iter {bytes_by_backend} — OK ({out})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
