"""One-epoch node dryrun + observability scrape (the CI obs-dryrun job).

Boots a real node (commitment prover, tpu-sparse open-graph backend) on
a loopback port, lets exactly one epoch tick land, then scrapes the
observability surface over the actual HTTP socket:

- ``GET /metrics``  -> ``METRICS_scrape.txt`` (Prometheus text format)
- ``GET /trace/latest`` -> ``TRACE_epoch0.json`` (the epoch's span tree)

and asserts the ISSUE 4 acceptance shape: the metrics parse as
Prometheus samples, the residual histogram count equals the iteration
gauge, and the span tree roots at ``epoch_tick`` with the canonical
phase children.  Exit code 0 iff everything held.

Run: ``JAX_PLATFORMS=cpu python tools/obs_dryrun.py [--out-dir DIR]``
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


async def _http_get(port: int, path: str) -> tuple[str, str]:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"GET {path} HTTP/1.1\r\nhost: dryrun\r\n\r\n".encode())
    await writer.drain()
    response = (await reader.read()).decode()
    writer.close()
    head, _, body = response.partition("\r\n\r\n")
    return head, body


async def _dryrun(out_dir: Path, epoch_interval: int, timeout_s: float) -> int:
    from protocol_tpu.node.config import ProtocolConfig
    from protocol_tpu.node.server import Node
    from protocol_tpu.obs import TRACER, configure_logging

    configure_logging()
    cfg = ProtocolConfig(
        epoch_interval=epoch_interval,
        endpoint=((127, 0, 0, 1), 0),
        prover="commitment",
        trust_backend="tpu-sparse",
    )
    node = Node.from_config(cfg)
    await node.start()
    port = node._server.sockets[0].getsockname()[1]
    print(f"obs_dryrun: node on 127.0.0.1:{port}, interval {epoch_interval}s")

    # Wait for the first epoch tick to complete (its trace appearing is
    # the completion signal — the tree is stored at tick end).
    waited = 0.0
    while TRACER.latest_epoch() is None:
        if waited > timeout_s:
            print("obs_dryrun: no epoch tick within timeout", file=sys.stderr)
            await node.stop()
            return 1
        await asyncio.sleep(0.25)
        waited += 0.25

    metrics_head, metrics_body = await _http_get(port, "/metrics")
    trace_head, trace_body = await _http_get(port, "/trace/latest")
    latest = TRACER.latest_epoch()
    _, trace_by_number = await _http_get(port, f"/trace/{latest}")
    drift_head, drift_body = await _http_get(port, "/scores/drift")
    flight_head, flight_body = await _http_get(port, "/debug/flight")
    await node.stop()

    assert "200 OK" in metrics_head, metrics_head
    assert "text/plain; version=0.0.4" in metrics_head, metrics_head
    assert "200 OK" in trace_head, trace_head
    assert "200 OK" in drift_head, drift_head
    assert "200 OK" in flight_head, flight_head

    # /trace/latest must be BYTE-identical to /trace/<epoch> for the
    # newest epoch — same serialized tree, not a re-render.
    assert trace_body == trace_by_number, (
        f"/trace/latest diverges from /trace/{latest}"
    )

    # -- acceptance shape ----------------------------------------------
    samples: dict[str, float] = {}
    for line in metrics_body.strip().splitlines():
        if line.startswith("#"):
            continue
        name, _, value = line.rpartition(" ")
        samples[name] = float(value)
    iterations = samples["eigentrust_convergence_iterations"]
    residual_count = samples["eigentrust_convergence_residual_count"]
    epochs = samples["eigentrust_epochs_total"]
    assert epochs >= 1, f"no epochs counted: {epochs}"
    # One observation per iteration per epoch.
    assert residual_count >= iterations >= 1, (residual_count, iterations)

    # Admission-plane backpressure surface (ISSUE 7): the queue-depth
    # gauges and shed counters must be scrapeable from boot — HELP/TYPE
    # advertised and the per-stage sample rows materialized at zero —
    # so a dashboard can alert on shed>0 without waiting for traffic.
    for name, kind in (
        ("eigentrust_ingest_queue_depth", "gauge"),
        ("eigentrust_ingest_shed_total", "counter"),
        ("eigentrust_ingest_verify_batches_total", "counter"),
        ("eigentrust_ingest_worker_restarts_total", "counter"),
    ):
        assert f"# TYPE {name} {kind}" in metrics_body, name
        assert f"# HELP {name} " in metrics_body, name
    for stage in ("submit", "verify"):
        key = f'eigentrust_ingest_queue_depth{{stage="{stage}"}}'
        assert key in samples, key
    assert 'eigentrust_ingest_shed_total{stage="submit"}' in samples, samples.keys()
    assert samples['eigentrust_ingest_shed_total{stage="submit"}'] == 0, (
        "dryrun shed ingest traffic with no load"
    )
    assert "eigentrust_ingest_admission_seconds_count" in samples

    tree = json.loads(trace_body)
    assert tree["name"] == "epoch_tick", tree["name"]
    child_names = [c["name"] for c in tree["children"]]
    assert "prove" in child_names and "converge" in child_names, child_names

    # Span-tree integrity: every span closed (an orphaned span would
    # serialize duration_s=None) and every span's end >= start.
    def check_spans(node, path="epoch_tick"):
        dur = node["duration_s"]
        assert dur is not None, f"orphaned (never-closed) span: {path}"
        assert dur >= 0, f"span end < start at {path}: {dur}"
        assert node["start_offset_s"] >= 0, (path, node["start_offset_s"])
        for child in node["children"]:
            check_spans(child, f"{path}/{child['name']}")

    check_spans(tree)

    # Drift endpoint: one epoch has landed, so the monitor has a
    # summary (no previous fixed point yet -> l1/linf are null).
    drift = json.loads(drift_body)
    assert drift.get("epoch") == tree["attrs"]["epoch"], drift
    assert "stalled" in drift, drift

    # Flight recorder: the tail must replay the tick's event sequence
    # — spans (incl. the epoch root) and the plan/converge phases.
    flight = [json.loads(line) for line in flight_body.splitlines() if line]
    kinds = {e["kind"] for e in flight}
    assert "span" in kinds, kinds
    span_names = {e.get("name") for e in flight if e["kind"] == "span"}
    assert "epoch_tick" in span_names and "converge" in span_names, span_names

    out_dir.mkdir(parents=True, exist_ok=True)
    (out_dir / "METRICS_scrape.txt").write_text(metrics_body)
    (out_dir / "TRACE_epoch0.json").write_text(json.dumps(tree, indent=2) + "\n")
    (out_dir / "FLIGHT_tail.jsonl").write_text(flight_body)
    print(
        f"obs_dryrun: OK — epoch {tree['attrs']['epoch']}, "
        f"{int(iterations)} iterations, {int(residual_count)} residuals, "
        f"phases {child_names}, {len(flight)} flight events; "
        f"artifacts in {out_dir}/"
    )
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--out-dir", default=".", help="artifact directory (default: cwd)"
    )
    ap.add_argument(
        "--epoch-interval", type=int, default=2, help="epoch length, seconds"
    )
    ap.add_argument(
        "--timeout", type=float, default=120.0, help="max wait for the tick"
    )
    args = ap.parse_args(argv)
    return asyncio.run(
        _dryrun(Path(args.out_dir), args.epoch_interval, args.timeout)
    )


if __name__ == "__main__":
    sys.exit(main())
