"""One-epoch node dryrun + observability scrape (the CI obs-dryrun job).

Boots a real node (commitment prover, tpu-sparse open-graph backend) on
a loopback port, POSTs a handful of signed attestations through the
admission plane (lineage-sampled at 1:1), lets epoch ticks land until
their lineage completes end-to-end, then scrapes the observability
surface over the actual HTTP socket:

- ``GET /metrics``  -> ``METRICS_scrape.txt`` (Prometheus text format)
- ``GET /trace/latest`` -> ``TRACE_epoch0.json`` (the epoch's span tree)
- ``GET /timeline/latest`` -> ``TIMELINE_latest.json`` (the epoch's
  joined record: watermarks, phases, proof lifecycle, freshness)
- ``GET /slo`` -> ``SLO.json`` (every objective green, or exit 1)
- ``GET /healthz`` (ok/degraded verdict with component state)
- ``GET /metrics/fleet`` (the process-labeled fleet-merged scrape)

and asserts the ISSUE 4 + ISSUE 11 acceptance shapes: metrics parse,
residual count == iterations, span tree roots at ``epoch_tick``,
end-to-end freshness observed (``stage="proof_landed"``), the timeline
joins phase + proof fragments, every SLO objective holds, and the
fleet scrape carries ``process`` labels.  ``--seed-slo-violation``
registers an objective that cannot pass — the run must then FAIL,
which is the CI self-check that a regressing objective fails the
build.  Exit code 0 iff everything held.

Run: ``JAX_PLATFORMS=cpu python tools/obs_dryrun.py [--out-dir DIR]``
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


async def _http_get(port: int, path: str) -> tuple[str, str]:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"GET {path} HTTP/1.1\r\nhost: dryrun\r\n\r\n".encode())
    await writer.drain()
    response = (await reader.read()).decode()
    writer.close()
    head, _, body = response.partition("\r\n\r\n")
    return head, body


async def _http_post(port: int, path: str, payload: bytes) -> tuple[str, str]:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(
        (
            f"POST {path} HTTP/1.1\r\nhost: dryrun\r\n"
            f"content-length: {len(payload)}\r\n\r\n"
        ).encode()
        + payload
    )
    await writer.drain()
    response = (await reader.read()).decode()
    writer.close()
    head, _, body = response.partition("\r\n\r\n")
    return head, body


async def _dryrun(
    out_dir: Path,
    epoch_interval: int,
    timeout_s: float,
    seed_slo_violation: bool = False,
) -> int:
    from protocol_tpu.node.attestation import AttestationData
    from protocol_tpu.node.config import ProtocolConfig
    from protocol_tpu.node.server import Node
    from protocol_tpu.obs import TRACER, configure_logging
    from protocol_tpu.obs.metrics import FRESHNESS_SECONDS

    configure_logging()
    cfg = ProtocolConfig(
        epoch_interval=epoch_interval,
        endpoint=((127, 0, 0, 1), 0),
        prover="commitment",
        trust_backend="tpu-sparse",
        # Sample every accepted attestation so the handful POSTed below
        # all carry lineage through to proof_landed.
        lineage_sample_every=1,
    )
    node = Node.from_config(cfg)
    await node.start()
    if seed_slo_violation:
        from protocol_tpu.obs.slo import seed_violation

        seed_violation()
        print("obs_dryrun: seeded an always-violating SLO objective")
    port = node._server.sockets[0].getsockname()[1]
    print(f"obs_dryrun: node on 127.0.0.1:{port}, interval {epoch_interval}s")

    # Feed the admission plane a few real signed attestations (the
    # node's own boot-time self-attestations, re-POSTed over the
    # socket) so lineage sampling has an end-to-end stream to follow.
    posted = 0
    for att in list(node.manager.attestations.values()):
        payload = AttestationData.from_attestation(att).to_bytes()
        head, body = await _http_post(port, "/attestation", payload)
        verdict = json.loads(body)
        assert verdict["accepted"], verdict
        posted += 1
    print(f"obs_dryrun: posted {posted} attestations through the plane")

    # Wait until (a) an epoch tick landed AND (b) the posted lineage
    # completed end-to-end (its including epoch's proof landed) — up
    # to two ticks when the first boundary races the POSTs.
    def freshness_done() -> bool:
        return FRESHNESS_SECONDS.count(stage="proof_landed") >= 1

    waited = 0.0
    while TRACER.latest_epoch() is None or not freshness_done():
        if waited > timeout_s:
            print(
                "obs_dryrun: no epoch tick / lineage completion within "
                f"timeout (traced={TRACER.epochs()}, "
                f"proof_landed={FRESHNESS_SECONDS.count(stage='proof_landed')})",
                file=sys.stderr,
            )
            await node.stop()
            return 1
        await asyncio.sleep(0.25)
        waited += 0.25

    metrics_head, metrics_body = await _http_get(port, "/metrics")
    trace_head, trace_body = await _http_get(port, "/trace/latest")
    latest = TRACER.latest_epoch()
    _, trace_by_number = await _http_get(port, f"/trace/{latest}")
    drift_head, drift_body = await _http_get(port, "/scores/drift")
    flight_head, flight_body = await _http_get(port, "/debug/flight")
    timeline_head, timeline_body = await _http_get(port, "/timeline/latest")
    _, timeline_by_number = await _http_get(port, f"/timeline/{latest}")
    slo_head, slo_body = await _http_get(port, "/slo")
    health_head, health_body = await _http_get(port, "/healthz")
    fleet_head, fleet_body = await _http_get(port, "/metrics/fleet")
    await node.stop()

    assert "200 OK" in metrics_head, metrics_head
    assert "text/plain; version=0.0.4" in metrics_head, metrics_head
    assert "200 OK" in trace_head, trace_head
    assert "200 OK" in drift_head, drift_head
    assert "200 OK" in flight_head, flight_head

    # /trace/latest must be BYTE-identical to /trace/<epoch> for the
    # newest epoch — same serialized tree, not a re-render.
    assert trace_body == trace_by_number, (
        f"/trace/latest diverges from /trace/{latest}"
    )

    # -- acceptance shape ----------------------------------------------
    samples: dict[str, float] = {}
    for line in metrics_body.strip().splitlines():
        if line.startswith("#"):
            continue
        name, _, value = line.rpartition(" ")
        samples[name] = float(value)
    iterations = samples["eigentrust_convergence_iterations"]
    residual_count = samples["eigentrust_convergence_residual_count"]
    epochs = samples["eigentrust_epochs_total"]
    assert epochs >= 1, f"no epochs counted: {epochs}"
    # One observation per iteration per epoch.
    assert residual_count >= iterations >= 1, (residual_count, iterations)

    # Admission-plane backpressure surface (ISSUE 7): the queue-depth
    # gauges and shed counters must be scrapeable from boot — HELP/TYPE
    # advertised and the per-stage sample rows materialized at zero —
    # so a dashboard can alert on shed>0 without waiting for traffic.
    for name, kind in (
        ("eigentrust_ingest_queue_depth", "gauge"),
        ("eigentrust_ingest_shed_total", "counter"),
        ("eigentrust_ingest_verify_batches_total", "counter"),
        ("eigentrust_ingest_worker_restarts_total", "counter"),
    ):
        assert f"# TYPE {name} {kind}" in metrics_body, name
        assert f"# HELP {name} " in metrics_body, name
    for stage in ("submit", "verify"):
        key = f'eigentrust_ingest_queue_depth{{stage="{stage}"}}'
        assert key in samples, key
    assert 'eigentrust_ingest_shed_total{stage="submit"}' in samples, samples.keys()
    assert samples['eigentrust_ingest_shed_total{stage="submit"}'] == 0, (
        "dryrun shed ingest traffic with no load"
    )
    assert "eigentrust_ingest_admission_seconds_count" in samples

    tree = json.loads(trace_body)
    assert tree["name"] == "epoch_tick", tree["name"]
    child_names = [c["name"] for c in tree["children"]]
    assert "prove" in child_names and "converge" in child_names, child_names

    # Span-tree integrity: every span closed (an orphaned span would
    # serialize duration_s=None) and every span's end >= start.
    def check_spans(node, path="epoch_tick"):
        dur = node["duration_s"]
        assert dur is not None, f"orphaned (never-closed) span: {path}"
        assert dur >= 0, f"span end < start at {path}: {dur}"
        assert node["start_offset_s"] >= 0, (path, node["start_offset_s"])
        for child in node["children"]:
            check_spans(child, f"{path}/{child['name']}")

    check_spans(tree)

    # Drift endpoint: one epoch has landed, so the monitor has a
    # summary (no previous fixed point yet -> l1/linf are null).
    drift = json.loads(drift_body)
    assert drift.get("epoch") == tree["attrs"]["epoch"], drift
    assert "stalled" in drift, drift

    # Flight recorder: the tail must replay the tick's event sequence
    # — spans (incl. the epoch root) and the plan/converge phases.
    flight = [json.loads(line) for line in flight_body.splitlines() if line]
    kinds = {e["kind"] for e in flight}
    assert "span" in kinds, kinds
    span_names = {e.get("name") for e in flight if e["kind"] == "span"}
    assert "epoch_tick" in span_names and "converge" in span_names, span_names

    # -- fleet-plane surfaces (ISSUE 11) --------------------------------
    assert "200 OK" in timeline_head, timeline_head
    assert "200 OK" in slo_head, slo_head
    assert "200 OK" in fleet_head, fleet_head
    assert "text/plain; version=0.0.4" in fleet_head, fleet_head

    # Timeline: the joined epoch record — phase durations from the
    # span tree, the ingest watermark from the host stage, the proof
    # lifecycle, and /timeline/latest ≡ /timeline/<epoch>.
    assert timeline_body == timeline_by_number, "timeline latest diverges"
    timeline = json.loads(timeline_body)
    assert timeline["epoch"] == latest, timeline
    assert "phases" in timeline and "converge" in timeline["phases"], timeline
    assert timeline.get("graph", {}).get("peers", 0) >= 1, timeline
    assert timeline.get("proof", {}).get("state") == "proved", timeline
    assert timeline.get("converge", {}).get("iterations", 0) >= 1, timeline

    # End-to-end freshness: the POSTed lineage completed, so every hop
    # histogram has samples and proof_landed is the headline.
    for stage in ("admitted", "verified", "applied", "included", "proof_landed"):
        key = f'eigentrust_freshness_seconds_count{{stage="{stage}"}}'
        assert samples.get(key, 0) >= 1, (stage, key)

    # SLO engine: every objective evaluated and green (a seeded
    # violation flips this and the dryrun exits 1 — the CI self-check
    # that the gate can fail).
    slo = json.loads(slo_body)
    objectives = slo.get("objectives", {})
    for required in (
        "freshness-p99",
        "proof-lag-p99",
        "epoch-cadence",
        "shed-rate",
        "residual-stall",
    ):
        assert required in objectives, (required, sorted(objectives))
    violating = sorted(k for k, o in objectives.items() if not o["ok"])
    slo_ok = bool(slo.get("ok")) and not violating

    # Health: the node just served an epoch, so the verdict is ok (or
    # degraded only by an SLO violation when one was seeded).
    health = json.loads(health_body)
    assert health["status"] in ("ok", "degraded"), health
    assert "200 OK" in health_head, health_head
    assert health["components"]["epoch"]["latest"] == latest, health
    if not seed_slo_violation:
        assert health["status"] == "ok", health

    # Fleet scrape: one coherent exposition with per-process labels —
    # the node process at minimum (spawn workers / jax.distributed
    # siblings add their own process rows when present).
    assert 'process="node"' in fleet_body, fleet_body[:400]
    fleet_names = {
        line.split("{", 1)[0]
        for line in fleet_body.splitlines()
        if line and not line.startswith("#")
    }
    assert "eigentrust_epochs_total" in fleet_names

    out_dir.mkdir(parents=True, exist_ok=True)
    (out_dir / "METRICS_scrape.txt").write_text(metrics_body)
    (out_dir / "TRACE_epoch0.json").write_text(json.dumps(tree, indent=2) + "\n")
    (out_dir / "FLIGHT_tail.jsonl").write_text(flight_body)
    (out_dir / "TIMELINE_latest.json").write_text(
        json.dumps(timeline, indent=2) + "\n"
    )
    (out_dir / "SLO.json").write_text(json.dumps(slo, indent=2) + "\n")
    landed = samples.get(
        'eigentrust_freshness_seconds_count{stage="proof_landed"}', 0
    )
    print(
        f"obs_dryrun: epoch {tree['attrs']['epoch']}, "
        f"{int(iterations)} iterations, {int(residual_count)} residuals, "
        f"phases {child_names}, {len(flight)} flight events, "
        f"{int(landed)} lineage completions, "
        f"health={health['status']}; artifacts in {out_dir}/"
    )
    if not slo_ok:
        print(
            f"obs_dryrun: SLO VIOLATION — objectives {violating} not met "
            "(see SLO.json)",
            file=sys.stderr,
        )
        return 1
    print("obs_dryrun: OK — all SLO objectives green")
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--out-dir", default=".", help="artifact directory (default: cwd)"
    )
    ap.add_argument(
        "--epoch-interval", type=int, default=2, help="epoch length, seconds"
    )
    ap.add_argument(
        "--timeout", type=float, default=120.0, help="max wait for the tick"
    )
    ap.add_argument(
        "--seed-slo-violation",
        action="store_true",
        help="register an always-violating SLO objective; the dryrun "
        "must then exit non-zero (the CI gate self-check)",
    )
    args = ap.parse_args(argv)
    return asyncio.run(
        _dryrun(
            Path(args.out_dir),
            args.epoch_interval,
            args.timeout,
            seed_slo_violation=args.seed_slo_violation,
        )
    )


if __name__ == "__main__":
    sys.exit(main())
