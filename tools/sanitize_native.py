"""Sanitizer wall for the native runtime (ISSUE 8 satellite).

Builds ``native/`` with compiler sanitizers into a side directory
(``native/sanitize/<mode>``), points the Python loaders at it via
``PROTOCOL_TPU_NATIVE_DIR``, and runs the native-touching workload
under the instrumented libraries:

- ``--mode asan`` — AddressSanitizer + UBSan over the native-touching
  test subset (field/runtime parity suites) plus the parallel driver.
- ``--mode tsan`` — ThreadSanitizer over the parallel driver: the
  OpenMP batch-verify region, the MSM/NTT parallel regions, and the
  relaxed-atomic phase-timer table (PR 6) hammered from concurrent
  Python threads.  ``native/tsan.supp`` suppresses the known libgomp
  runtime-internal reports (GCC's libgomp is not TSAN-instrumented;
  its barrier/teardown internals are runtime noise, not our code) —
  every suppression is enumerated in the report.

Reports land in ``SANITIZER.json`` (the CI artifact): build/run exit
codes, every sanitizer report captured via ``log_path``, and the
suppression list in force.  Exit 0 iff the build succeeded, the
workload passed, and no unsuppressed report fired.

Run::

    python tools/sanitize_native.py --mode asan --out SANITIZER.json
    python tools/sanitize_native.py --mode tsan --out SANITIZER_tsan.json
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
NATIVE = REPO / "native"

#: Native-touching test subset for the ASAN/UBSAN leg: the ctypes
#: parity suites that drive every exported symbol with real data.
ASAN_TESTS = [
    "tests/test_native_field.py",
    "tests/test_native_runtime.py",
]

MODES = {
    "asan": {
        "sanout": "sanitize/asan",
        "sanflags": "-fsanitize=address,undefined -fno-sanitize-recover=undefined",
        "libs": ["libasan.so", "libubsan.so"],
    },
    "tsan": {
        "sanout": "sanitize/tsan",
        "sanflags": "-fsanitize=thread",
        "libs": ["libtsan.so"],
    },
}


def _preload_paths(libs: list[str], cxx: str) -> list[str]:
    out = []
    for lib in libs:
        p = subprocess.run(
            [cxx, f"-print-file-name={lib}"], capture_output=True, text=True
        ).stdout.strip()
        if p and p != lib and Path(p).exists():
            out.append(str(Path(p).resolve()))
    return out


def _build(mode: dict) -> int:
    return subprocess.run(
        [
            "make",
            "-C",
            str(NATIVE),
            "sanitized",
            f"SANOUT={mode['sanout']}",
            f"SANFLAGS={mode['sanflags']}",
        ],
    ).returncode


def _driver() -> None:
    """The parallel workload (runs in the instrumented subprocess):
    hammer every OpenMP region and the relaxed-atomic phase timers
    from concurrent threads."""
    import threading

    from protocol_tpu.crypto import calculate_message_hash
    from protocol_tpu.crypto import native as cnative
    from protocol_tpu.crypto.eddsa import sign
    from protocol_tpu.node.bootstrap import FIXED_SET, keyset_from_raw
    from protocol_tpu.zk import native as zknative
    from protocol_tpu.zk.bn254 import GENERATOR

    assert cnative.available(), "instrumented libprotocol_native failed to load"
    assert zknative.available(), "instrumented libzk_runtime failed to load"

    sks, pks = keyset_from_raw(FIXED_SET)
    rows = [[200 + (i % 190), 200 - (i % 190), 200, 200, 200] for i in range(64)]
    _, msgs = calculate_message_hash(pks, rows)
    sigs = [sign(sks[i % len(sks)], pks[i % len(pks)], m) for i, m in enumerate(msgs)]

    def batch_verify():
        ok = cnative.eddsa_verify_batch(
            [s.big_r.x for s in sigs],
            [s.big_r.y for s in sigs],
            [s.s for s in sigs],
            [pks[i % len(pks)].point.x for i in range(len(sigs))],
            [pks[i % len(pks)].point.y for i in range(len(sigs))],
            msgs,
        )
        assert all(ok), "batch verify rejected a valid signature"

    def zk_hot_loops():
        # MSM + NTT parallel regions, ~2^10 scale so TSAN finishes fast.
        n = 1 << 10
        scalars = [(i * 2654435761 + 1) % zknative.R for i in range(n)]
        points = [GENERATOR.mul((i % 7) + 1) for i in range(64)] * (n // 64)
        zknative.msm(scalars, points)
        root = pow(5, (zknative.R - 1) // n, zknative.R)
        zknative.ntt(scalars, root)
        zknative.batch_inv(scalars[: 1 << 8])

    def phase_timers():
        # The PR 6 relaxed-atomic table, read while the hot loops write.
        for _ in range(200):
            zknative.phase_stats()
            zknative.reset_phase_stats()

    failures: list[BaseException] = []

    def run(fn):
        def wrapped():
            try:
                fn()
            except BaseException as exc:  # noqa: BLE001 - re-raised below
                failures.append(exc)

        return wrapped

    threads = (
        [threading.Thread(target=run(batch_verify)) for _ in range(2)]
        + [threading.Thread(target=run(zk_hot_loops)) for _ in range(2)]
        + [threading.Thread(target=run(phase_timers))]
    )
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if failures:
        raise failures[0]
    print("sanitize driver: all parallel regions exercised")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mode", choices=sorted(MODES), default="asan")
    ap.add_argument("--out", default="SANITIZER.json")
    ap.add_argument(
        "--driver", action="store_true", help=argparse.SUPPRESS
    )  # internal: the instrumented child process
    args = ap.parse_args(argv)

    if args.driver:
        sys.path.insert(0, str(REPO))  # invoked as a script from tools/
        _driver()
        return 0

    mode = MODES[args.mode]
    cxx = os.environ.get("CXX", "g++")
    report: dict = {"mode": args.mode, "sanflags": mode["sanflags"]}

    build_rc = _build(mode)
    report["build_rc"] = build_rc
    if build_rc != 0:
        report["ok"] = False
        Path(args.out).write_text(json.dumps(report, indent=2) + "\n")
        print(f"sanitize_native: build failed (rc={build_rc})", file=sys.stderr)
        return 1

    log_dir = REPO / f"sanitize-logs-{args.mode}"
    log_dir.mkdir(exist_ok=True)
    for old in glob.glob(str(log_dir / "*")):
        os.unlink(old)
    log_prefix = str(log_dir / "report")

    env = dict(os.environ)
    env["PROTOCOL_TPU_NATIVE_DIR"] = str(NATIVE / mode["sanout"])
    env["JAX_PLATFORMS"] = "cpu"
    preloads = _preload_paths(mode["libs"], cxx)
    if preloads:
        env["LD_PRELOAD"] = ":".join(
            preloads + [p for p in env.get("LD_PRELOAD", "").split(":") if p]
        )
    report["preload"] = preloads
    suppressions = NATIVE / "tsan.supp"
    if args.mode == "asan":
        # Python itself "leaks" interned state by design; leak checking
        # the interpreter drowns real reports.
        env["ASAN_OPTIONS"] = f"detect_leaks=0:log_path={log_prefix}"
        env["UBSAN_OPTIONS"] = f"print_stacktrace=1:log_path={log_prefix}"
    else:
        env["TSAN_OPTIONS"] = (
            f"suppressions={suppressions}:log_path={log_prefix}:exitcode=66"
        )
        report["suppressions"] = (
            suppressions.read_text().splitlines() if suppressions.exists() else []
        )

    runs: list[dict] = []
    if args.mode == "asan":
        runs.append(
            {
                "name": "native-test-subset",
                "cmd": [sys.executable, "-m", "pytest", "-q", *ASAN_TESTS],
            }
        )
    runs.append(
        {
            "name": "parallel-driver",
            "cmd": [sys.executable, str(Path(__file__)), "--driver"],
        }
    )

    ok = True
    report["runs"] = []
    for run in runs:
        rc = subprocess.run(run["cmd"], cwd=REPO, env=env).returncode
        report["runs"].append({"name": run["name"], "rc": rc})
        ok = ok and rc == 0

    reports = []
    for path in sorted(glob.glob(log_prefix + "*")):
        text = Path(path).read_text()
        reports.append(
            {
                "file": Path(path).name,
                "summary": [
                    line
                    for line in text.splitlines()
                    if line.startswith(("SUMMARY:", "WARNING:", "ERROR:"))
                ][:10],
                "text": text[:20000],
            }
        )
    report["reports"] = reports
    report["ok"] = ok and not reports
    Path(args.out).write_text(json.dumps(report, indent=2) + "\n")
    status = "clean" if report["ok"] else "FINDINGS"
    print(
        f"sanitize_native[{args.mode}]: {status} — "
        f"{len(reports)} report file(s), runs="
        + ", ".join(f"{r['name']}:{r['rc']}" for r in report["runs"])
        + f" ({args.out} written)"
    )
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
