"""Crash matrix: kill -9 the node at every registered fault point and
prove recovery (the ISSUE 14 acceptance tool).

For each fault point in the chaos registry (protocol_tpu/chaos/) the
matrix runs the churned attestation replay in a child process with a
deterministic schedule that **crashes the process at that point**
(``os._exit`` — the kill -9 analog: no flush, no atexit), restarts it
against the same directories, and asserts the recovery invariants:

- **no acknowledged attestation lost** — every apply the parent saw
  acked before the crash is present in the recovered cache;
- **same fixed point** — after feeding the rest of the stream, the
  recovered run's converged scores match an uncrashed control run
  within convergence tolerance (arXiv:1603.00589's start-independence
  is what makes the warm recovered seed safe);
- **proofs still land** post-recovery;
- recovery is bounded (``recovery_seconds`` recorded per entry).

Two torn-write entries ride along (``wal.append`` / ``checkpoint.write``
with the torn fault: a truncated record/snapshot reaches disk and the
process dies), and a **double-crash** entry kills the restarted child
*during recovery itself* (``wal.replay``).  A separate ``--node`` phase
boots the real daemon over HTTP, SIGKILLs it after an epoch, restarts
it with a delayed replay schedule, and asserts ``/healthz`` walks
``recovering`` → ``ok`` with the WAL metrics populated and all SLOs
re-green.

The workload: P synthetic peers attesting over K neighbours with a
recency-biased churned sender mix (the bench doctrine), driven through
the REAL Manager → WAL → CheckpointStore → converge(+warm start/plan
cache) → commitment-prove path.  Synthetic peers use a fast injective
pk-hash stand-in (Poseidon on 200k+ synthetic keys is pure-Python
minutes and irrelevant to durability); the 5-member fixed set keeps
its real Poseidon hashes and real signatures.

Run::

    python tools/crash_matrix.py --smoke --out CHAOS_smoke.json
    python tools/crash_matrix.py --out CHAOS_r01.json     # recorded round

Exit 0 = every entry recovered clean; 1 = any invariant violated.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import subprocess
import sys
import time
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

#: Synthetic peer keys: y = base + i (injective, never the null point,
#: trivially detected by the fast-hash override in the child).
SYNTH_Y_BASE = 1 << 40

#: Convergence-tolerance bar for recovered-vs-control scores (L1).
SCORE_TOL = 1e-4


# ---------------------------------------------------------------------------
# deterministic workload stream (parent side)
# ---------------------------------------------------------------------------


def build_stream(peers: int, events: int, neighbours: int, seed: int):
    """``events`` wire-encoded synthetic attestations with a
    recency-biased churned sender mix (bench.py's cohort doctrine)."""
    import numpy as np

    from protocol_tpu.crypto import field
    from protocol_tpu.node.attestation import AttestationData

    rng = np.random.default_rng(seed)
    zero = field.to_le_bytes(0)
    out: list[str] = []
    for _ in range(events):
        # Recency bias: a hot cohort of senders re-attests often.
        if rng.random() < 0.7:
            sender = int(rng.integers(0, max(1, peers // 10)))
        else:
            sender = int(rng.integers(0, peers))
        nbr_ids = rng.choice(peers, size=neighbours, replace=False)
        scores = rng.integers(1, 1000, size=neighbours)
        data = AttestationData(
            sig_r_x=zero,
            sig_r_y=zero,
            sig_s=zero,
            pk=(
                field.to_le_bytes(sender + 1),
                field.to_le_bytes(SYNTH_Y_BASE + sender),
            ),
            neighbours=[
                (
                    field.to_le_bytes(int(j) + 1),
                    field.to_le_bytes(SYNTH_Y_BASE + int(j)),
                )
                for j in nbr_ids
            ],
            scores=[field.to_le_bytes(int(s)) for s in scores],
        )
        out.append(data.to_bytes().hex())
    return out


def sender_of(wire_hex: str) -> str:
    """Sender pk bytes (x‖y) — the parent's cache key for ack tracking."""
    return wire_hex[96 * 2 : 160 * 2]


def digest_of(wire_hex: str) -> str:
    return hashlib.sha256(bytes.fromhex(wire_hex)).hexdigest()


# ---------------------------------------------------------------------------
# child worker (runs the real Manager/WAL/CheckpointStore under chaos)
# ---------------------------------------------------------------------------


def worker_main(args) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np  # noqa: F401

    from protocol_tpu.crypto.eddsa import PublicKey
    from protocol_tpu.node.attestation import AttestationData
    from protocol_tpu.node.checkpoint import CheckpointStore
    from protocol_tpu.node.epoch import Epoch
    from protocol_tpu.node.ethereum import ChainEventSource
    from protocol_tpu.node.manager import Manager, ManagerConfig
    from protocol_tpu.node.wal import AttestationWAL, recover
    from protocol_tpu import chaos

    class MatrixManager(Manager):
        """Real Manager with a fast injective pk-hash stand-in for the
        synthetic peer keys (durability does not depend on WHICH
        injective id keys a peer's rows; the fixed set keeps real
        Poseidon hashes so the proof path is untouched)."""

        def _pk_hash(self, pk: PublicKey) -> int:
            y = pk.point.y
            if SYNTH_Y_BASE <= y < SYNTH_Y_BASE + (1 << 32):
                return ((y - SYNTH_Y_BASE) << 64) | pk.point.x
            return super()._pk_hash(pk)

    class StubRpc:
        """Tiny in-process RPC backend so the matrix workload exercises
        the rpc.* fault points through the real ChainEventSource."""

        def __init__(self):
            self.head = 0

        def block_number(self):
            self.head += 1
            return self.head

        def get_logs(self, address, from_block, to_block, topic0):
            return []

    base = Path(args.dir)
    manager = MatrixManager(
        ManagerConfig(
            backend=args.backend, prover="commitment", check_circuit=False
        )
    )
    manager.generate_initial_attestations()
    store = CheckpointStore(base / "checkpoints")
    wal = None
    if args.wal:
        wal = AttestationWAL(base / "checkpoints" / "wal", fsync=args.fsync)
    recovery = recover(manager, store, wal)
    rpc_source = ChainEventSource(StubRpc(), "0x" + "11" * 20)

    out = sys.stdout
    print(json.dumps({"ready": True, "recovery": recovery}), file=out, flush=True)
    for line in sys.stdin:
        cmd = json.loads(line)
        op = cmd["op"]
        if op == "apply_batch":
            t0 = time.perf_counter()
            n = 0
            for wire_hex in cmd["items"]:
                wire = bytes.fromhex(wire_hex)
                k = cmd["neighbours"]
                att = AttestationData.from_bytes(wire, k).to_attestation(k)
                manager.apply_verified(att, raw=wire, flush=False)
                n += 1
            manager.flush_wal()
            print(
                json.dumps(
                    {"ok": True, "applied": n, "seconds": time.perf_counter() - t0}
                ),
                file=out,
                flush=True,
            )
        elif op == "epoch":
            number = cmd["number"]
            t0 = time.perf_counter()
            result = manager.converge_epoch(Epoch(number), alpha=0.1, max_iter=80)
            store.save(
                Epoch(number),
                manager.last_graph,
                result.scores,
                None,
                plan=manager.window_plan,
                peer_hashes=manager.last_peer_hashes,
                wal_seq=manager.checkpoint_watermark(),
                attestations=manager.snapshot_attestations(),
            )
            if chaos.ACTIVE:
                chaos.fire("checkpoint.post_save")
            if wal is not None:
                floor = store.retained_wal_floor()
                if floor is not None:
                    wal.truncate_through(floor)
            print(
                json.dumps(
                    {
                        "ok": True,
                        "iterations": int(result.iterations),
                        "peers": int(manager.last_graph.n),
                        "seconds": time.perf_counter() - t0,
                    }
                ),
                file=out,
                flush=True,
            )
        elif op == "prove":
            manager.calculate_proofs(Epoch(cmd["number"]))
            print(json.dumps({"ok": True, "proved": True}), file=out, flush=True)
        elif op == "rpc":
            list(rpc_source.replay(from_block=0, to_block=rpc_source._block_number()))
            print(json.dumps({"ok": True}), file=out, flush=True)
        elif op == "state":
            scores = {}
            if manager.last_scores is not None and manager.last_peer_hashes:
                scores = {
                    str(h): float(s)
                    for h, s in zip(manager.last_peer_hashes, manager.last_scores)
                }
            cache = {}
            for h, att in manager.attestations.items():
                wire = AttestationData.from_attestation(att).to_bytes()
                cache[str(h)] = hashlib.sha256(wire).hexdigest()
            print(
                json.dumps(
                    {
                        "ok": True,
                        "cache": cache,
                        "scores": scores,
                        "proofs": sorted(e.number for e in manager.cached_proofs),
                        "hits": chaos.hits(),
                    }
                ),
                file=out,
                flush=True,
            )
        elif op == "exit":
            print(json.dumps({"ok": True}), file=out, flush=True)
            return 0
    return 0


# ---------------------------------------------------------------------------
# parent-side child driver
# ---------------------------------------------------------------------------


class Child:
    def __init__(self, workdir: Path, args, chaos_spec: dict | None, wal=True):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PROTOCOL_TPU_CHAOS", None)
        if chaos_spec is not None:
            env["PROTOCOL_TPU_CHAOS"] = json.dumps(chaos_spec)
        cmd = [
            sys.executable,
            str(Path(__file__).resolve()),
            "--worker",
            "--dir",
            str(workdir),
            "--backend",
            args.backend,
        ]
        if not wal:
            cmd.append("--no-wal")
        if not args.fsync:
            cmd.append("--no-fsync")
        workdir.mkdir(parents=True, exist_ok=True)
        self._stderr = open(workdir / "stderr.log", "a")
        self.proc = subprocess.Popen(
            cmd,
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=self._stderr,
            text=True,
            env=env,
            cwd=str(ROOT),
        )
        self.ready = self._read()

    def _read(self) -> dict | None:
        line = self.proc.stdout.readline()
        if not line:
            return None
        return json.loads(line)

    def call(self, **cmd) -> dict | None:
        """One command round-trip; None = the child died (crashed)."""
        try:
            self.proc.stdin.write(json.dumps(cmd) + "\n")
            self.proc.stdin.flush()
        except (BrokenPipeError, OSError):
            return None
        return self._read()

    def close(self) -> int:
        try:
            self.call(op="exit")
        except Exception:  # noqa: BLE001
            pass
        try:
            self.proc.stdin.close()
        except OSError:
            pass
        rc = self.proc.wait(timeout=60)
        self._stderr.close()
        return rc


def run_stream(child: Child, stream, args, acked: list | None = None):
    """Feed the full workload; returns (crashed_at_op | None, timing).
    ``acked`` collects indices of acknowledged apply batches."""
    batch, batch_idx, op_idx = [], 0, 0
    apply_seconds = 0.0
    epoch_seconds: list[float] = []
    epoch_no = 0
    per_epoch = max(1, len(stream) // max(1, args.epochs))
    for i, wire_hex in enumerate(stream):
        batch.append(wire_hex)
        end_of_epoch = (i + 1) % per_epoch == 0 or i + 1 == len(stream)
        if len(batch) >= args.batch or end_of_epoch:
            ack = child.call(
                op="apply_batch", items=batch, neighbours=args.neighbours
            )
            if ack is None:
                return f"apply_batch:{batch_idx}", (apply_seconds, epoch_seconds)
            apply_seconds += ack["seconds"]
            if acked is not None:
                acked.append(batch_idx)
            batch, batch_idx = [], batch_idx + 1
        if end_of_epoch:
            for op in (
                {"op": "rpc"},
                {"op": "epoch", "number": epoch_no},
                {"op": "prove", "number": epoch_no},
            ):
                ack = child.call(**op)
                if ack is None:
                    return f"{op['op']}:{epoch_no}", (apply_seconds, epoch_seconds)
                if op["op"] == "epoch":
                    epoch_seconds.append(ack["seconds"])
            epoch_no += 1
        op_idx += 1
    return None, (apply_seconds, epoch_seconds)


def batch_bounds(stream, args):
    """[(batch_idx, [event indices])] mirroring run_stream's batching."""
    out, batch, idx = [], [], 0
    per_epoch = max(1, len(stream) // max(1, args.epochs))
    for i in range(len(stream)):
        batch.append(i)
        if len(batch) >= args.batch or (i + 1) % per_epoch == 0 or i + 1 == len(stream):
            out.append((idx, batch))
            batch, idx = [], idx + 1
    return out


def expected_cache(stream, args, upto_batch: int) -> dict[str, str]:
    """Per-sender last acked digest after ``upto_batch`` batches."""
    out: dict[str, str] = {}
    for idx, events in batch_bounds(stream, args):
        if idx >= upto_batch:
            break
        for i in events:
            out[sender_of(stream[i])] = digest_of(stream[i])
    return out


# ---------------------------------------------------------------------------
# matrix entries
# ---------------------------------------------------------------------------


def run_entry(args, stream, point, spec_fault, control, tmp: Path, crash_in_recovery=False):
    """One matrix row: crash the workload at ``point``, restart, verify."""
    entry = {"point": point, "fault": spec_fault.get("kind", "crash"), "ok": False}
    workdir = tmp / point.replace(".", "_") / spec_fault.get("kind", "crash")
    workdir.mkdir(parents=True, exist_ok=True)

    first_spec = {"seed": args.seed, "faults": [] if crash_in_recovery else [spec_fault]}
    acked: list[int] = []
    child = Child(workdir, args, first_spec)
    if child.ready is None:
        entry["error"] = "child failed to boot"
        return entry
    if crash_in_recovery:
        # Phase 0 for the recovery-crash entry: land some state, then
        # die at a late apply so the NEXT boot has a tail to replay.
        mid_spec = {
            "seed": args.seed,
            "faults": [{"point": "ingest.pre_apply", "kind": "crash", "after": control["hits"]["ingest.pre_apply"] * 3 // 4}],
        }
        child.close()
        child = Child(workdir, args, mid_spec)
        if child.ready is None:
            entry["error"] = "phase-0 child failed to boot"
            return entry
    crashed_at, _ = run_stream(child, stream, args, acked)
    from protocol_tpu import chaos as chaos_mod

    if crashed_at is None:
        entry["error"] = f"fault at {point} never crashed the child"
        child.close()
        return entry
    rc = child.proc.wait(timeout=60)
    if rc != chaos_mod.CRASH_EXIT_CODE:
        entry["error"] = f"child exited rc={rc}, expected chaos crash"
        return entry
    entry["crashed_at"] = crashed_at
    entry["acked_batches"] = len(acked)

    if crash_in_recovery:
        # Restart WITH a schedule that kills the replay mid-recovery,
        # then restart again clean: recovery must itself be crash-safe.
        crash_child = Child(workdir, args, {"seed": args.seed, "faults": [spec_fault]})
        mid_rc = None
        if crash_child.ready is None:
            mid_rc = crash_child.proc.wait(timeout=60)
        else:  # replay too short to hit the scheduled point — still fine
            crash_child.close()
        entry["recovery_crash_rc"] = mid_rc

    # Clean restart: recovery must find every acked attestation.
    resumed = Child(workdir, args, None)
    if resumed.ready is None:
        entry["error"] = "resumed child failed to boot"
        return entry
    recovery = resumed.ready["recovery"]
    entry["recovery"] = recovery
    state = resumed.call(op="state")
    want = expected_cache(stream, args, upto_batch=len(acked))
    # Senders the parent saw acked must be in the recovered cache with
    # the last-acked digest — OR a newer one from the written-but-
    # unacked in-flight tail (both are on disk; neither was lost).
    later: dict[str, list[str]] = {}
    for idx, events in batch_bounds(stream, args):
        if idx >= len(acked):
            for i in events:
                later.setdefault(sender_of(stream[i]), []).append(digest_of(stream[i]))
    cache_by_sender = dict(state["cache"])
    lost = []
    for sender, digest in want.items():
        h = sender_hash_str(sender)
        got = cache_by_sender.get(h)
        if got is None or (got != digest and got not in later.get(sender, ())):
            lost.append(sender[:16])
    entry["lost_attestations"] = len(lost)

    # Feed the remainder, converge the final epoch, compare to control.
    tail_start = sum(len(ev) for idx, ev in batch_bounds(stream, args) if idx < len(acked))
    tail = stream[tail_start:]
    crashed2, _ = run_stream(resumed, tail, args)
    if crashed2 is not None:
        entry["error"] = f"resumed child crashed at {crashed2}"
        return entry
    final = resumed.call(op="state")
    resumed.close()
    entry["score_l1"] = score_l1(control["state"]["scores"], final["scores"])
    entry["proofs_landed"] = len(final["proofs"])
    cache_match = final["cache"] == control["state"]["cache"]
    entry["cache_matches_control"] = cache_match
    entry["ok"] = (
        not lost
        and cache_match
        and entry["score_l1"] <= SCORE_TOL
        and entry["proofs_landed"] >= 1
    )
    if not entry["ok"] and "error" not in entry:
        entry["error"] = "invariant violated (see fields)"
    return entry


def sender_hash_str(sender_hex: str) -> str:
    """Parent-side mirror of MatrixManager's fast synthetic pk hash."""
    from protocol_tpu.crypto import field

    raw = bytes.fromhex(sender_hex)
    x = field.from_le_bytes(raw[:32])
    y = field.from_le_bytes(raw[32:])
    return str(((y - SYNTH_Y_BASE) << 64) | x)


def score_l1(a: dict, b: dict) -> float:
    keys = set(a) | set(b)
    return float(sum(abs(a.get(k, 0.0) - b.get(k, 0.0)) for k in keys))


# ---------------------------------------------------------------------------
# node-level phase: /healthz walks recovering → ok across kill -9
# ---------------------------------------------------------------------------


def http_get(port: int, path: str, timeout=2.0):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read().decode()
    finally:
        conn.close()


def node_phase(args, tmp: Path) -> dict:
    """Boot the real daemon, accept an attestation, kill -9 after a
    checkpointed epoch, restart with a slowed replay, and assert the
    /healthz walk + WAL metrics + green SLOs."""
    import socket

    from protocol_tpu.crypto import calculate_message_hash, field
    from protocol_tpu.crypto.eddsa import sign
    from protocol_tpu.node.attestation import Attestation, AttestationData
    from protocol_tpu.node.bootstrap import FIXED_SET, keyset_from_raw

    entry: dict = {"point": "node.restart", "fault": "sigkill", "ok": False}
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    ckpt = tmp / "node" / "checkpoints"
    cfg = {
        "epoch_interval": 4,
        "endpoint": [[127, 0, 0, 1], port],
        "trust_backend": "tpu-csr",
        "prover": "commitment",
        "checkpoint_dir": str(ckpt),
        "ingest_plane": True,
        "ingest_workers": 0,
    }
    cfg_path = tmp / "node" / "config.json"
    cfg_path.parent.mkdir(parents=True, exist_ok=True)
    cfg_path.write_text(json.dumps(cfg))

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PROTOCOL_TPU_CHAOS", None)

    def boot(extra_env=None):
        e = dict(env)
        if extra_env:
            e.update(extra_env)
        return subprocess.Popen(
            [sys.executable, "-m", "protocol_tpu.node.server", "--config", str(cfg_path)],
            env=e,
            cwd=str(ROOT),
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )

    def wait_http(path, pred, deadline=90.0, interval=0.2):
        t0 = time.time()
        while time.time() - t0 < deadline:
            try:
                status, body = http_get(port, path)
                if pred(status, body):
                    return True, body
            except OSError:
                pass
            time.sleep(interval)
        return False, ""

    proc = boot()
    try:
        ok, _ = wait_http("/status", lambda s, b: s == 200)
        if not ok:
            entry["error"] = "node never served /status"
            return entry
        # Real signed fixed-set attestations through POST /attestation.
        import http.client

        sks, pks = keyset_from_raw(FIXED_SET)

        def post_att(sender: int, scores: list[int]) -> bool:
            _, msgs = calculate_message_hash(pks, [scores])
            sig = sign(sks[sender], pks[sender], msgs[0])
            att = Attestation(
                sig=sig, pk=pks[sender], neighbours=list(pks), scores=scores
            )
            payload = AttestationData.from_attestation(att).to_bytes()
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
            try:
                conn.request("POST", "/attestation", body=payload)
                resp = conn.getresponse()
                return resp.status == 200 and json.loads(
                    resp.read().decode()
                ).get("accepted", False)
            finally:
                conn.close()

        if not post_att(0, [217, 183, 200, 200, 200]):
            entry["error"] = "attestation not accepted"
            return entry
        # Wait for a checkpointed epoch...
        ok, _ = wait_http(
            "/metrics",
            lambda s, b: s == 200
            and any(
                line.startswith("eigentrust_checkpoint_saves_total")
                and float(line.split()[-1]) >= 1
                for line in b.splitlines()
            ),
        )
        if not ok:
            entry["error"] = "no checkpoint before kill"
            return entry
        # ...then land one MORE accepted attestation past the snapshot
        # (it lives only in the WAL) and kill -9 before the next tick.
        if not post_att(1, [190, 210, 200, 200, 200]):
            entry["error"] = "post-checkpoint attestation not accepted"
            return entry
    finally:
        proc.kill()  # SIGKILL — the point of the exercise
        proc.wait(timeout=30)

    # Restart with a slowed WAL replay so the recovering window is
    # scrapeable, and record the /healthz walk.
    slow = {
        "seed": 0,
        "faults": [
            {"point": "wal.replay", "kind": "delay", "delay_s": args.replay_delay_s}
        ],
    }
    proc = boot({"PROTOCOL_TPU_CHAOS": json.dumps(slow)})
    walk: list[str] = []
    try:
        t0 = time.time()
        deadline = 120.0
        while time.time() - t0 < deadline:
            try:
                status, body = http_get(port, "/healthz")
            except OSError:
                time.sleep(0.05)
                continue
            obj = json.loads(body)
            state = obj.get("components", {}).get("recovery", {}).get("state")
            if not walk or walk[-1] != state:
                walk.append(state)
            if (
                state == "ok"
                and obj.get("status") == "ok"
                and obj["components"]["recovery"].get("wal_replayed", 0) >= 1
            ):
                break
            time.sleep(0.05)
        entry["healthz_walk"] = walk
        _, metrics = http_get(port, "/metrics")
        wal_replayed = recovery_s = 0.0
        for line in metrics.splitlines():
            if line.startswith("eigentrust_wal_replayed_total"):
                wal_replayed = float(line.split()[-1])
            if line.startswith("eigentrust_recovery_seconds"):
                recovery_s = float(line.split()[-1])
        _, slo = http_get(port, "/slo")
        entry["wal_replayed"] = wal_replayed
        entry["recovery_seconds"] = recovery_s
        entry["slo_ok"] = bool(json.loads(slo).get("ok"))
        entry["ok"] = (
            walk[-1:] == ["ok"]
            and "recovering" in walk
            and wal_replayed >= 1
            and recovery_s > 0
            and entry["slo_ok"]
        )
        if not entry["ok"]:
            entry["error"] = f"healthz walk {walk}, slo_ok={entry.get('slo_ok')}"
    finally:
        proc.kill()
        proc.wait(timeout=30)
    return entry


def pod_phase(args, tmp: Path) -> dict:
    """Host-loss row (ISSUE 16): kill one process of an N-host pod
    mid-epoch and prove pod recovery.  Delegates to
    ``tools/dryrun_pod.py --chaos-host-loss`` — a control pod run, a
    run that ``os._exit``\\ s one worker after its WAL ack but before
    converge, and a full-pod ``--resume`` that must replay the dead
    host's WAL shard with zero acknowledged loss and reconverge to a
    fixed point **bit-identical** to the control run's."""
    entry: dict = {"point": "pod.host-loss", "fault": "kill -9 host 1 of 2"}
    out = tmp / "pod_chaos.json"
    t0 = time.perf_counter()
    proc = subprocess.run(
        [
            sys.executable,
            str(ROOT / "tools" / "dryrun_pod.py"),
            "--smoke",
            "--chaos-host-loss",
            "--skip-reference",
            "--out", str(out),
        ],
        cwd=ROOT,
        timeout=3000,
    )
    entry["seconds"] = round(time.perf_counter() - t0, 3)
    try:
        report = json.loads(out.read_text())
    except (OSError, json.JSONDecodeError) as exc:
        entry.update(ok=False, error=f"no dryrun_pod report: {exc!r}")
        return entry
    chaos = report.get("chaos") or {}
    entry.update(
        skipped=report.get("skipped", False),
        crash_host=chaos.get("crash_host"),
        crash_epoch=chaos.get("crash_epoch"),
        recovery_seconds=chaos.get("recovery_seconds"),
        lost_attestations=sum(
            len(x) for x in chaos.get("lost_acked", []) if x
        ),
        fixed_point_matches_control=chaos.get("fixed_point_matches_control"),
        residual_bit_identity=(chaos.get("residual_bit_identity") or {}).get("ok"),
        # A jax build without multi-process CPU collectives skips the
        # row without failing the matrix — same policy as comm_probe.
        ok=bool(report.get("skipped") or (proc.returncode == 0 and chaos.get("ok"))),
    )
    if not entry["ok"]:
        entry["error"] = f"dryrun_pod rc={proc.returncode}, chaos={chaos.get('ok')}"
    return entry


# ---------------------------------------------------------------------------
# main
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--worker", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--dir", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--backend", default="tpu-windowed")
    ap.add_argument("--peers", type=int, default=2000)
    ap.add_argument("--events", type=int, default=6000)
    ap.add_argument("--neighbours", type=int, default=8)
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--seed", type=int, default=14)
    ap.add_argument("--smoke", action="store_true", help="CI scale")
    ap.add_argument("--no-wal", dest="wal", action="store_false", default=True)
    ap.add_argument("--no-fsync", dest="fsync", action="store_false", default=True)
    ap.add_argument("--skip-node-phase", action="store_true")
    ap.add_argument("--skip-pod-phase", action="store_true")
    ap.add_argument("--replay-delay-s", type=float, default=0.4)
    ap.add_argument("--round", type=int, default=1)
    ap.add_argument("--out", default="CHAOS_smoke.json")
    ap.add_argument("--workdir", default=None)
    args = ap.parse_args(argv)

    if args.worker:
        return worker_main(args)

    if args.smoke:
        # CI scale: small stream, native converge (no per-shape jit
        # compiles — the durability invariants are backend-independent;
        # the recorded rounds run the windowed backend).
        args.peers, args.events, args.epochs = 120, 600, 3
        args.backend = "native-cpu"

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from protocol_tpu import chaos as chaos_mod

    # Importing the node tree registers every fault point.
    import protocol_tpu.node.checkpoint  # noqa: F401
    import protocol_tpu.node.ethereum  # noqa: F401
    import protocol_tpu.node.server  # noqa: F401
    import protocol_tpu.node.wal  # noqa: F401

    registry = chaos_mod.registry()
    import tempfile

    tmp = Path(args.workdir) if args.workdir else Path(tempfile.mkdtemp(prefix="chaos_"))
    stream = build_stream(args.peers, args.events, args.neighbours, args.seed)

    # -- control: counting-mode run, full stream, no faults -------------
    t_control = time.perf_counter()
    control_child = Child(tmp / "control", args, {"seed": args.seed, "faults": []})
    crashed, (apply_s, epoch_s) = run_stream(control_child, stream, args)
    assert crashed is None, f"control run crashed at {crashed}"
    control = {"state": control_child.call(op="state")}
    control["hits"] = control["state"]["hits"]
    control_child.close()
    control_seconds = time.perf_counter() - t_control

    # -- WAL overhead: same stream without the WAL ----------------------
    nowal_child = Child(tmp / "nowal", args, None, wal=False)
    _, (apply_nowal, _) = run_stream(nowal_child, stream, args)
    nowal_child.close()
    per_event_overhead = max(0.0, (apply_s - apply_nowal)) / max(1, len(stream))
    events_per_epoch = len(stream) / max(1, args.epochs)
    mean_epoch_s = sum(epoch_s) / max(1, len(epoch_s))
    wal_overhead_pct = (
        100.0 * per_event_overhead * events_per_epoch / mean_epoch_s
        if mean_epoch_s
        else 0.0
    )

    # -- the matrix ------------------------------------------------------
    hits = control["hits"]
    # wal.replay only fires on a RESTART's recovery — the control run
    # (one boot, fresh dirs) never replays; its dedicated double-crash
    # entry below exercises it.
    not_exercised = sorted(
        p for p in registry if hits.get(p, 0) == 0 and p != "wal.replay"
    )
    entries = []
    for point in sorted(registry):
        if hits.get(point, 0) == 0 and point != "wal.replay":
            continue
        if point == "wal.replay":
            # Double-crash: the schedule kills the RESTARTED child
            # during its own recovery replay, then a third boot must
            # still recover clean — recovery is itself crash-safe.
            fault = {"point": point, "kind": "crash", "after": 2}
            entries.append(
                run_entry(args, stream, point, fault, control, tmp, crash_in_recovery=True)
            )
            continue
        fault = {"point": point, "kind": "crash", "after": max(1, hits[point] // 2)}
        entries.append(run_entry(args, stream, point, fault, control, tmp))
    # Torn-write rows: a truncated record / snapshot reaches disk and
    # the process dies (then_crash arms the next fired point).
    for point in ("wal.append", "checkpoint.write"):
        if hits.get(point, 0) == 0:
            continue
        fault = {
            "point": point,
            "kind": "torn",
            "at": 24,
            "after": max(1, hits[point] // 2),
        }
        entries.append(run_entry(args, stream, f"{point}", fault, control, tmp))

    if not args.skip_node_phase:
        entries.append(node_phase(args, tmp))
    if not args.skip_pod_phase:
        entries.append(pod_phase(args, tmp))

    recoveries = [
        e["recovery"]["seconds"] for e in entries if isinstance(e.get("recovery"), dict)
    ]
    recoveries += [e["recovery_seconds"] for e in entries if "recovery_seconds" in e]
    recovery_seconds = sorted(recoveries)[len(recoveries) // 2] if recoveries else None
    ok = all(e.get("ok") for e in entries) and not not_exercised

    scale = f"{args.peers} peers/{args.events} events, {args.backend}"
    report = {
        "n": args.round,
        "tool": "crash_matrix",
        "scale": {
            "peers": args.peers,
            "events": args.events,
            "neighbours": args.neighbours,
            "epochs": args.epochs,
            "backend": args.backend,
        },
        "registry": registry,
        "control": {
            "seconds": round(control_seconds, 3),
            "apply_seconds": round(apply_s, 3),
            "apply_seconds_no_wal": round(apply_nowal, 3),
            "mean_epoch_seconds": round(mean_epoch_s, 4),
            "hits": hits,
            "proofs": control["state"]["proofs"],
        },
        "wal_overhead": {
            "per_event_us": round(per_event_overhead * 1e6, 2),
            "pct_of_epoch": round(wal_overhead_pct, 3),
        },
        "not_exercised": not_exercised,
        "entries_detail": entries,
        # Sentinel-shaped series (tools/perf_sentinel.py walks these).
        "entries": [
            {
                "metric": f"crash-matrix recovery ({scale})",
                "recovery_seconds": recovery_seconds,
                "wal_overhead_pct": round(wal_overhead_pct, 3),
            }
        ],
        "ok": ok,
    }
    Path(args.out).write_text(json.dumps(report, indent=2) + "\n")
    for e in entries:
        status = "OK " if e.get("ok") else "FAIL"
        print(
            f"  [{status}] {e['point']} ({e.get('fault')}): "
            f"crashed_at={e.get('crashed_at', 'sigkill')} "
            f"recovery={e.get('recovery', {}).get('seconds', e.get('recovery_seconds'))}s "
            f"lost={e.get('lost_attestations', '-')} l1={e.get('score_l1', '-')}"
            + (f"  ERROR: {e['error']}" if "error" in e else "")
        )
    if not_exercised:
        print(f"crash_matrix: points never exercised by the workload: {not_exercised}", file=sys.stderr)
    print(
        f"crash_matrix: {'OK' if ok else 'FAILED'} — {len(entries)} entries, "
        f"median recovery {recovery_seconds}s, WAL overhead "
        f"{report['wal_overhead']['pct_of_epoch']}% of the epoch ({args.out})"
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
