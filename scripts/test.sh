#!/usr/bin/env bash
# Full test suite (the reference's scripts/test.sh: cargo test --all).
#
# `--tier1` runs the driver's gate exactly: CPU platform, everything not
# marked slow — which includes the interpret-mode windowed-pipeline
# equivalence tests (tests/test_windowed_pipeline.py, PERF.md §7-8).
#
# `--slow` is the scripted cadence entry for the PROTOCOL_TPU_SLOW_TESTS
# tier (VERDICT weak #10): the full PLONK epoch e2e and the real fold
# proof, which are skipped by default.  Run it on every change to zk/ or
# native/, and at minimum once per round before recording BENCH/LADDER
# numbers — it is the only continuous exercise of the headline proving
# path.  Expect ~10 min single-core (PERF.md §2-3).
set -euo pipefail
cd "$(dirname "$0")/.."

if [[ "${1:-}" == "--tier1" ]]; then
    shift
    exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' "$@"
fi
if [[ "${1:-}" == "--slow" ]]; then
    shift
    # The slow tier is env-gated (pytest.mark.skipif on
    # PROTOCOL_TPU_SLOW_TESTS), so this runs the full suite with the
    # gate open — the 5 default skips (epoch PLONK e2e, fold proof,
    # verifier artifact regen) execute alongside everything else.
    exec env PROTOCOL_TPU_SLOW_TESTS=1 JAX_PLATFORMS=cpu \
        python -m pytest tests/ -q "$@"
fi
python -m pytest tests/ -q "$@"
