#!/usr/bin/env bash
# Full test suite (the reference's scripts/test.sh: cargo test --all).
#
# `--tier1` runs the driver's gate exactly: CPU platform, everything not
# marked slow — which includes the interpret-mode windowed-pipeline
# equivalence tests (tests/test_windowed_pipeline.py, PERF.md §7).
set -euo pipefail
cd "$(dirname "$0")/.."

if [[ "${1:-}" == "--tier1" ]]; then
    shift
    exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' "$@"
fi
python -m pytest tests/ -q "$@"
