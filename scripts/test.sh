#!/usr/bin/env bash
# Full test suite (the reference's scripts/test.sh: cargo test --all).
set -euo pipefail
cd "$(dirname "$0")/.."
python -m pytest tests/ -q "$@"
