#!/usr/bin/env bash
# Lint wall (the reference's fmt-check.sh + clippy.sh analog,
# .github/workflows/test.yml:32-37).  Runs the full ruff+mypy wall when
# the tools exist; always runs the bytecode-compile floor so even
# tool-less images (like the build image) get a syntax/structure gate —
# and always runs graftlint (`python -m protocol_tpu.analysis`), the
# jaxpr/AST invariant analyzer that hard-gates every trust backend's
# access-pattern contract (PERF.md §9).
set -euo pipefail
cd "$(dirname "$0")/.."

python -m compileall -q protocol_tpu tests tools bench bench.py __graft_entry__.py

# graftlint: pass 1 traces every registered backend's step to a jaxpr
# and checks its declared KERNEL_INVARIANTS budget; passes 2-6 are the
# AST ruleset over protocol_tpu/; pass 7 is the whole-program
# concurrency analyzer (thread-root discovery, shared-state guard
# inference, lock-order cycles, blocking/native-under-lock) with its
# enumerated waiver table; pass 8 is the SPMD-lowering comm analyzer
# (compiles every backend under the 8-device CPU mesh and checks the
# partitioner's collectives/bytes/aliasing against COMM_INVARIANTS,
# sharded composites at two problem scales); pass 11 is the durability
# ruleset (non-atomic state writes in node/, chaos fault points inside
# jit/shard_map bodies); pass 12 is the static peak-HBM analyzer
# (reads the buffer assignment of the same executables pass 8
# compiles and gates MEM_INVARIANTS: per-shard resident scaling as
# E/n_shards, an N-linear transient allowance in which an O(E) live
# temporary is inexpressible, donation-reduces-peak, host-staging
# caps, plus the edge-materialization and cache-growth AST rules over
# node/ and ingest/); pass 13 is the determinism analyzer (the AST
# divergence walk over node/, parallel/, ingest/, prover/ and models/
# — set-order-to-state, unsorted-dirscan, hash-ordering, unseeded-rng,
# clock-in-digest — plus the HLO leg riding the pass-8/12 lowerings:
# no scatter without unique_indices, no reduce-precision on the f32
# path, and every backend compiled twice with a canonicalized
# module-text diff so compile-time entropy itself is gated).  Any
# error-severity finding — including an unwaived
# concurrency/comm/memory/determinism finding or a STALE waiver in any
# table — fails here.  Emits ANALYSIS.json (uploaded as a CI artifact;
# the concurrency, comm, memory, and determinism sections carry the
# root inventory, guard map, lock graph, per-backend collective/byte,
# resident/transient and scatter/recompile-drift tables, and waiver
# lists).
python -m protocol_tpu.analysis --output ANALYSIS.json

# Trees held to the hard format/type gates: the convergence-kernel,
# backend, mesh-parallel, node, analyzer, observability, crypto, zk,
# admission-plane, and proving-plane code.  crypto/ and zk/ were
# promoted from informational with the analyzer work; obs/ joined with
# the telemetry subsystem (ISSUE 4); ingest/ with the admission plane
# (ISSUE 7); prover/ with the async proving plane (ISSUE 10) — the
# whole admission + proving + serving + instrumentation path sits
# behind the same wall as the kernels.
HARD_TREES="protocol_tpu/ops protocol_tpu/trust protocol_tpu/parallel protocol_tpu/node protocol_tpu/analysis protocol_tpu/obs protocol_tpu/crypto protocol_tpu/zk protocol_tpu/ingest protocol_tpu/prover protocol_tpu/chaos"

if command -v ruff >/dev/null 2>&1; then
    ruff check .
    # Hard gate on the kernel/backend/proving trees; informational elsewhere.
    ruff format --check $HARD_TREES
    ruff format --check . || echo "lint: format drift outside $HARD_TREES (informational)" >&2
else
    echo "lint: ruff not installed; ran compileall + analysis floor only" >&2
fi
if command -v mypy >/dev/null 2>&1; then
    mypy $HARD_TREES
    mypy protocol_tpu || echo "lint: mypy findings outside $HARD_TREES (informational)" >&2
else
    echo "lint: mypy not installed; skipped type gate" >&2
fi
