#!/usr/bin/env bash
# Lint wall (the reference's fmt-check.sh + clippy.sh analog,
# .github/workflows/test.yml:32-37).  Runs the full ruff+mypy wall when
# the tools exist; always runs the bytecode-compile floor so even
# tool-less images (like the build image) get a syntax/structure gate.
set -euo pipefail
cd "$(dirname "$0")/.."

python -m compileall -q protocol_tpu tests tools bench bench.py __graft_entry__.py

# Trees held to the hard format/type gates: the convergence-kernel,
# backend, mesh-parallel, and node code the fused-pipeline work
# (PERF.md §7-8) touches.  The rest of the tree stays informational
# until it is brought up to the wall.
HARD_TREES="protocol_tpu/ops protocol_tpu/trust protocol_tpu/parallel protocol_tpu/node"

if command -v ruff >/dev/null 2>&1; then
    ruff check .
    # Hard gate on the kernel/backend trees; informational elsewhere.
    ruff format --check $HARD_TREES
    ruff format --check . || echo "lint: format drift outside $HARD_TREES (informational)" >&2
else
    echo "lint: ruff not installed; ran compileall floor only" >&2
fi
if command -v mypy >/dev/null 2>&1; then
    mypy $HARD_TREES
    mypy protocol_tpu || echo "lint: mypy findings outside $HARD_TREES (informational)" >&2
else
    echo "lint: mypy not installed; skipped type gate" >&2
fi
