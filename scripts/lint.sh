#!/usr/bin/env bash
# Lint wall (the reference's fmt-check.sh + clippy.sh analog,
# .github/workflows/test.yml:32-37).  Runs the full ruff+mypy wall when
# the tools exist; always runs the bytecode-compile floor so even
# tool-less images (like the build image) get a syntax/structure gate.
set -euo pipefail
cd "$(dirname "$0")/.."

python -m compileall -q protocol_tpu tests tools bench.py __graft_entry__.py

if command -v ruff >/dev/null 2>&1; then
    ruff check .
    ruff format --check .
else
    echo "lint: ruff not installed; ran compileall floor only" >&2
fi
if command -v mypy >/dev/null 2>&1; then
    mypy protocol_tpu
else
    echo "lint: mypy not installed; skipped type gate" >&2
fi
