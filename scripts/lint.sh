#!/usr/bin/env bash
# Lint wall (the reference's fmt-check.sh + clippy.sh analog,
# .github/workflows/test.yml:32-37).  Runs the full ruff+mypy wall when
# the tools exist; always runs the bytecode-compile floor so even
# tool-less images (like the build image) get a syntax/structure gate.
set -euo pipefail
cd "$(dirname "$0")/.."

python -m compileall -q protocol_tpu tests tools bench bench.py __graft_entry__.py

if command -v ruff >/dev/null 2>&1; then
    ruff check .
    # The format and type gates are informational until first exercised
    # on a ruff/mypy-equipped machine (this build image has neither, so
    # they have never run against this tree).  Flip them to hard gates
    # by removing the trailing `|| ...` once the tree is formatted.
    ruff format --check . || echo "lint: format drift (informational)" >&2
else
    echo "lint: ruff not installed; ran compileall floor only" >&2
fi
if command -v mypy >/dev/null 2>&1; then
    mypy protocol_tpu || echo "lint: mypy findings (informational)" >&2
else
    echo "lint: mypy not installed; skipped type gate" >&2
fi
