#!/usr/bin/env bash
# Build the native runtime from source (the reference's build.sh analog).
# No binaries are committed; the Python loaders also rebuild on demand.
set -euo pipefail
cd "$(dirname "$0")/.."
make -C native
