"""Peer→host partition properties (ISSUE 16, pod scale-out).

The rendezvous-hash partition is the pod's only membership agreement
mechanism — every host computes it independently, so the properties
below are load-bearing protocol invariants, not implementation trivia:

- **determinism**: two processes (here: two instances) with the same
  ``(n_hosts, seed)`` assign every key identically, including keys
  folded from arbitrary-width Poseidon hashes;
- **balance**: per-host buckets stay within ±20% of ``n/n_hosts`` at
  realistic sizes (an unbalanced partition silently serializes the
  pod's plan-build critical path back toward the single-host wall);
- **minimal remap**: a host join moves ≈ ``1/(n_hosts+1)`` of the keys
  (all of them TO the joiner); a host leave moves exactly the leaver's
  keys and nothing else — surviving hosts' window plans stay valid;
- **churn locality**: the bench.py sender-centric churn stream is
  partition-local — every churned row is dirty on exactly one host,
  and the per-host edge partitions tile the edge set exactly.
"""

import numpy as np
import pytest

from protocol_tpu.models import scale_free
from protocol_tpu.models.churn import churn_cohort_dims, sender_centric_churn
from protocol_tpu.ops.gather_window import partition_delta
from protocol_tpu.parallel.partition import (
    MASK64,
    HostPartition,
    keys_from_hashes,
    mix64,
    remap_fraction,
)


class TestDeterminism:
    def test_identical_across_instances(self):
        keys = np.random.default_rng(0).integers(
            0, 1 << 63, 50_000, dtype=np.uint64
        )
        a = HostPartition(5, seed=3).assign(keys)
        b = HostPartition(5, seed=3).assign(keys)
        assert np.array_equal(a, b)
        assert a.dtype == np.int32
        assert a.min() >= 0 and a.max() < 5

    def test_assign_ids_matches_assign_on_arange(self):
        part = HostPartition(4, seed=1)
        n = 10_000
        assert np.array_equal(
            part.assign_ids(n), part.assign(np.arange(n, dtype=np.uint64))
        )

    def test_seed_namespaces_the_partition(self):
        keys = np.arange(20_000, dtype=np.uint64)
        a = HostPartition(4, seed=0).assign(keys)
        b = HostPartition(4, seed=1).assign(keys)
        # Different salt chains: assignments must differ somewhere (a
        # seed that did nothing would collide test and production pods).
        assert not np.array_equal(a, b)

    def test_wide_hash_folding(self):
        # Poseidon field elements are ~254-bit Python ints; folding
        # masks to 64 bits, so two hashes equal mod 2^64 get one owner.
        wide = [(7 << 200) | 12345, (3 << 150) | 12345, 12345]
        keys = keys_from_hashes(wide)
        assert keys.dtype == np.uint64
        assert np.array_equal(keys, np.full(3, 12345, np.uint64))
        owners = HostPartition(8, seed=2).assign(keys)
        assert len(set(owners.tolist())) == 1

    def test_single_host_owns_everything(self):
        owners = HostPartition(1).assign_ids(1000)
        assert np.array_equal(owners, np.zeros(1000, np.int32))

    def test_invalid_pod_size_rejected(self):
        with pytest.raises(ValueError):
            HostPartition(0)

    def test_mix64_reference_vector(self):
        # splitmix64(0) first output — the published reference value;
        # pins the mixer against accidental constant/shift edits.
        out = mix64(np.asarray([0], np.uint64))[0]
        assert int(out) == 0xE220A8397B1DCDAF


class TestBalance:
    @pytest.mark.parametrize("n_hosts", [2, 4, 8])
    def test_buckets_within_20_percent(self, n_hosts):
        n = 100_000
        counts = np.bincount(
            HostPartition(n_hosts, seed=16).assign_ids(n), minlength=n_hosts
        )
        expect = n / n_hosts
        assert counts.min() >= 0.8 * expect, counts
        assert counts.max() <= 1.2 * expect, counts


class TestMinimalRemap:
    def test_join_moves_one_over_h_plus_1(self):
        keys = np.arange(200_000, dtype=np.uint64)
        before = HostPartition(4, seed=16).assign(keys)
        after = HostPartition(5, seed=16).assign(keys)
        moved = before != after
        # Every mover lands on the new host — rendezvous only ever
        # reassigns keys whose new candidate wins.
        assert np.all(after[moved] == 4)
        frac = remap_fraction(before, after)
        assert abs(frac - 1 / 5) < 0.02, frac

    def test_leave_moves_only_the_leavers_keys(self):
        keys = np.arange(100_000, dtype=np.uint64)
        before = HostPartition(5, seed=16).assign(keys)
        after = HostPartition(4, seed=16).assign(keys)
        survivors = before != 4
        # Removing a candidate never changes the argmax among the
        # rest: surviving hosts keep every key — their window plans
        # stay byte-identical across the membership change.
        assert np.array_equal(before[survivors], after[survivors])
        assert np.all(after[~survivors] < 4)

    def test_modulo_baseline_is_worse(self):
        # The property HRW buys: a modulo partition moves ~H/(H+1) of
        # the keys on the same join (here 4/5), ~4x the HRW remap.
        keys = np.arange(50_000, dtype=np.uint64)
        frac = remap_fraction(keys % 4, keys % 5)
        assert frac > 0.7

    def test_remap_fraction_edge_cases(self):
        assert remap_fraction(np.array([]), np.array([])) == 0.0
        with pytest.raises(ValueError):
            remap_fraction(np.zeros(3), np.zeros(4))


class TestChurnLocality:
    """The bench.py sender-centric churn stream against the partition:
    the exact claim the pod dryrun's steady-state relies on."""

    def _graph(self):
        return scale_free(2048, 16384, seed=16).drop_self_edges()

    def test_churned_rows_partition_local(self):
        g = self._graph()
        cohort_size, deg = churn_cohort_dims(g, 0.01)
        rng = np.random.default_rng(16)
        rows, g2, _ = sender_centric_churn(
            rng, g, cohort_size=cohort_size, deg=deg
        )
        part = HostPartition(4, seed=16)
        owner = part.assign_ids(g2.n)
        g2 = g2.drop_self_edges()
        w, _ = g2.row_normalized()
        seen_rows, seen_edges = [], 0
        for h in range(4):
            owned, lsrc, ldst, lw = partition_delta(
                rows, g2.src, g2.dst, w, owner, h
            )
            # Every local edge's source belongs to this host.
            assert np.all(owner[lsrc] == h)
            assert lsrc.shape == ldst.shape == lw.shape
            seen_rows.append(owned)
            seen_edges += lsrc.shape[0]
        # The per-host dirty rows tile the churn cohort exactly: each
        # row dirty on exactly one host.
        tiled = np.sort(np.concatenate(seen_rows))
        assert np.array_equal(tiled, np.sort(np.unique(rows)))
        # And the edge partitions tile the edge set exactly.
        assert seen_edges == g2.nnz

    def test_no_hint_forces_fingerprint_revalidation(self):
        g = self._graph()
        w, _ = g.row_normalized()
        owner = HostPartition(2, seed=16).assign_ids(g.n)
        owned, lsrc, _, _ = partition_delta(None, g.src, g.dst, w, owner, 0)
        assert owned is None
        assert np.all(owner[lsrc] == 0)

    def test_churn_stream_is_deterministic(self):
        g = self._graph()
        cohort_size, deg = churn_cohort_dims(g, 0.01)
        r1, g1, (ns1, nd1, nw1) = sender_centric_churn(
            np.random.default_rng(7), g, cohort_size=cohort_size, deg=deg
        )
        r2, g2, (ns2, nd2, nw2) = sender_centric_churn(
            np.random.default_rng(7), g, cohort_size=cohort_size, deg=deg
        )
        assert np.array_equal(r1, r2)
        assert np.array_equal(ns1, ns2)
        assert np.array_equal(nd1, nd2)
        assert np.array_equal(nw1, nw2)
        assert np.array_equal(g1.src, g2.src)
        # Row i's fresh out-row is the [i*deg, (i+1)*deg) slice — the
        # WAL journaling contract the pod dryrun encodes per host.
        assert np.array_equal(ns1, np.repeat(r1.astype(np.int32), deg))
        assert not np.any(nd1 == ns1)  # no self-edges survive resampling
