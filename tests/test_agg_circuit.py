"""Aggregation-fold circuit tests: transcript-chip parity with the
native transcript, an end-to-end fold over real member PLONK proofs
(constraint-checked in the default suite; really proved + EVM-verified
in the slow tier), and tampered-member negatives.
"""

import os

import pytest

from protocol_tpu.crypto import field
from protocol_tpu.zk import plonk
from protocol_tpu.zk.agg_circuit import (
    PoseidonTranscriptChip,
    prepare_fold,
    synthesize_fold,
    verify_fold,
)
from protocol_tpu.zk.aggregator import Snark, finalize
from protocol_tpu.zk.cs import ConstraintSystem
from protocol_tpu.zk.gadgets import PoseidonChip, StdGate
from protocol_tpu.zk.kzg import Setup
from protocol_tpu.zk.transcript import PoseidonTranscript

P = field.MODULUS


class TestTranscriptChip:
    def test_matches_native_transcript(self):
        cs = ConstraintSystem()
        std = StdGate(cs)
        chip = PoseidonTranscriptChip(std, PoseidonChip(cs))
        native = PoseidonTranscript()

        seq = [3, 1 << 100, P - 2, 7, 9, 11, 13, 17]
        for v in seq[:3]:
            chip.common_scalar(std.witness(v))
            native.common_scalar(v)
        c1 = chip.squeeze_challenge()
        n1 = native.squeeze_challenge()
        assert std.cell_value(c1) == n1
        # Chained squeeze with more absorption in between.
        for v in seq[3:]:
            chip.common_scalar(std.witness(v))
            native.common_scalar(v)
        c2 = chip.squeeze_challenge()
        n2 = native.squeeze_challenge()
        assert std.cell_value(c2) == n2
        # Back-to-back squeeze (re-absorbed challenge chains).
        assert std.cell_value(chip.squeeze_challenge()) == native.squeeze_challenge()
        cs.assert_satisfied()


def _member_snarks(n=2, seed=b"agg"):
    """Two small mul-add member proofs sharing one SRS (any PLONK
    proofs aggregate; the epoch statement is just bigger)."""
    from tests.test_plonk import _mul_add_circuit

    srs = Setup.generate(6, seed=seed)
    snarks = []
    for i in range(n):
        cs = _mul_add_circuit()
        pk = plonk.compile_circuit(cs, srs=srs)
        proof = plonk.prove(pk, cs, [17], seed=b"m%d" % i, transcript="poseidon")
        snarks.append(Snark(vk=pk.vk, instances=[17], proof=proof))
    return snarks


BITS = 16  # test-tier batching width; production default is 128


class TestFoldCircuit:
    def test_fold_constraints_and_native_agreement(self):
        snarks = _member_snarks()
        stmt = prepare_fold(snarks, challenge_bits=BITS)
        # The real (untruncated) accumulator also pairs correctly.
        assert finalize(stmt.accumulator, snarks[0].vk)
        cs = synthesize_fold(stmt)
        cs.assert_satisfied()

    def test_tampered_member_pair_unsatisfiable(self):
        snarks = _member_snarks()
        stmt = prepare_fold(snarks, challenge_bits=BITS)
        # Claim a wrong deferred pair for member 0: shift B.
        stmt.members[0].b = stmt.members[0].b.add(stmt.members[0].a)
        with pytest.raises((AssertionError, ValueError)):
            cs = synthesize_fold(stmt)
            cs.assert_satisfied()

    def test_tampered_challenge_unsatisfiable(self):
        snarks = _member_snarks()
        stmt = prepare_fold(snarks, challenge_bits=BITS)
        stmt.members[0].challenge = (stmt.members[0].challenge + 1) % P
        with pytest.raises(AssertionError):
            cs = synthesize_fold(stmt)
            cs.assert_satisfied()


@pytest.mark.skipif(
    not os.environ.get("PROTOCOL_TPU_SLOW_TESTS"),
    reason="fold proof is a k~16 circuit (~1 min); set PROTOCOL_TPU_SLOW_TESTS=1",
)
class TestFoldProof:
    def test_fold_proof_roundtrip(self):
        snarks = _member_snarks()
        stmt = prepare_fold(snarks, challenge_bits=BITS)
        cs = synthesize_fold(stmt)
        pk = plonk.compile_circuit(cs)
        proof = plonk.prove(pk, cs, stmt.public_inputs(), transcript="poseidon")
        assert verify_fold(pk.vk, snarks, proof, challenge_bits=BITS)
        # A different member set must not verify against this proof.
        other = _member_snarks(seed=b"agg2")
        assert not verify_fold(pk.vk, other, proof, challenge_bits=BITS)
