"""Concurrency stress + lock-witness cross-check (ISSUE 8).

Three layers:

- :class:`TestLockWitnessUnit` — the witness runtime in isolation:
  wrapped allocation, order-edge recording, contention histograms,
  watched attribute writes, and each cross-check violation class
  (cycle, runtime-only edge, bare cross-thread write of a
  statically-guarded attribute).
- :class:`TestRealFixRegressions` — targeted hammers for the real
  findings pass 7 surfaced and this PR fixed: the ingest plane's
  verdict tallies, the pipeline's coalesce counter and started flag,
  and the manager's warm-start scores/peer-hashes pair (a torn read
  maps scores onto the wrong peers).
- :class:`TestConcurrencyStress` — the acceptance stress: a real
  manager + epoch pipeline + ingest plane churned for three epochs
  while scrapers hammer /metrics and /debug/flight, all under
  lock-witness mode; asserts zero witness violations against the
  statically inferred guard map / lock-order graph and no deadlock
  within the timeout.  Smoke scale — runs in the tier-1 suite.
"""

import threading
import time
from pathlib import Path
from types import SimpleNamespace

import numpy as np
import pytest

from protocol_tpu.analysis.concurrency import build_static_model
from protocol_tpu.analysis.concurrency.checker import StaticConcurrencyModel
from protocol_tpu.analysis.concurrency.witness import LockWitness
from protocol_tpu.crypto import calculate_message_hash
from protocol_tpu.crypto.eddsa import sign
from protocol_tpu.ingest import IngestPlane, IngestPlaneConfig
from protocol_tpu.ingest.ratelimit import RateLimitConfig
from protocol_tpu.node.attestation import Attestation
from protocol_tpu.node.bootstrap import FIXED_SET, keyset_from_raw
from protocol_tpu.node.epoch import Epoch
from protocol_tpu.node.manager import Manager, ManagerConfig
from protocol_tpu.node.pipeline import EpochPipeline
from protocol_tpu.node.server import handle_request
from protocol_tpu.obs import prometheus_text
from protocol_tpu.obs import metrics as obs_metrics

SKS, PKS = keyset_from_raw(FIXED_SET)


def make_att(i: int, sender: int = 0) -> Attestation:
    """Unique validly-signed attestation #i (scores sum to SCALE)."""
    d = i % 190
    scores = [200 + d, 200 - d, 200, 200, 200]
    _, msgs = calculate_message_hash(PKS, [scores])
    sig = sign(SKS[sender], PKS[sender], msgs[0])
    return Attestation(sig=sig, pk=PKS[sender], neighbours=list(PKS), scores=scores)


@pytest.fixture(scope="module")
def static_model() -> StaticConcurrencyModel:
    """The analyzer's guard map + lock-order graph for the real tree —
    the witness cross-checks observations against this."""
    return build_static_model()


# ---------------------------------------------------------------------------
# witness unit tests
# ---------------------------------------------------------------------------


class TestLockWitnessUnit:
    def test_install_wraps_and_uninstall_restores(self):
        orig = threading.Lock
        w = LockWitness()
        with w:
            assert threading.Lock is not orig
            lock = threading.Lock()
            with lock:
                pass
        assert threading.Lock is orig
        assert len(w.report()["locks"]) == 1

    def test_order_edges_and_waits_recorded(self):
        w = LockWitness()
        with w:
            a = threading.Lock()
            b = threading.Lock()
            for _ in range(3):
                with a:
                    with b:
                        pass
        rep = w.report()
        assert len(rep["locks"]) == 2
        (edge, count), = rep["order_edges"].items()
        assert count == 3

    def test_contention_histogram_feeds_metrics(self):
        before = sum(n for _, n in obs_metrics.LOCK_WAIT_SECONDS.samples())
        w = LockWitness()
        with w:
            lock = threading.Lock()
            with lock:
                pass
        after = sum(n for _, n in obs_metrics.LOCK_WAIT_SECONDS.samples())
        assert after > before

    def test_watched_writes_record_thread_and_guards(self):
        w = LockWitness()
        with w:
            lock = threading.Lock()

            class Box:
                def __init__(self):
                    self.val = 0

            box = Box()
            w.watch(box, ["val"])
            with lock:
                box.val = 1
            box.val = 2
        writes = w.writes[("Box", "val")]
        assert len(writes) == 2
        assert len(writes[0][1]) == 1  # under the lock
        assert writes[1][1] == ()  # bare

    def test_cross_check_passes_on_consistent_run(self):
        w = LockWitness()
        with w:
            a = threading.Lock()
            with a:
                pass
        static = StaticConcurrencyModel()
        assert w.cross_check(static) == []

    def test_cross_check_flags_cycle(self):
        w = LockWitness()
        with w:
            a = threading.Lock()
            b = threading.Lock()
            with a:
                with b:
                    pass
            with b:
                with a:
                    pass
        static = StaticConcurrencyModel()
        violations = w.cross_check(static)
        assert any("cyclic" in v for v in violations)

    def test_cross_check_flags_runtime_only_edge(self):
        w = LockWitness()
        with w:
            a = threading.Lock()
            b = threading.Lock()
            with a:
                with b:
                    pass
        static = StaticConcurrencyModel(
            lock_sites={"T._a": a._site, "T._b": b._site},
            order_edges=set(),  # static graph has no a->b edge
        )
        violations = w.cross_check(static)
        assert any("T._a -> T._b" in v for v in violations)

    def test_cross_check_flags_bare_crossthread_write_of_guarded_attr(self):
        w = LockWitness()
        with w:
            guard = threading.Lock()

            class State:
                def __init__(self):
                    self.x = 0

            s = State()
            w.watch(s, ["x"])
            with guard:
                s.x = 1  # main thread: correctly guarded

            def rogue():
                s.x = 2  # second thread: bare

            t = threading.Thread(target=rogue)
            t.start()
            t.join()
        static = StaticConcurrencyModel(
            guard_map={("State", "x"): frozenset({"State._g"})},
            lock_sites={"State._g": guard._site},
        )
        violations = w.cross_check(static)
        assert any("State.x" in v for v in violations)


# ---------------------------------------------------------------------------
# regression hammers for the fixed real findings
# ---------------------------------------------------------------------------


class TestRealFixRegressions:
    def test_plane_tallies_consistent_under_concurrent_verdicts(self):
        """accepted/shed/rejections are resolved from three thread
        roots; the totals must balance exactly (pass-7 finding:
        unguarded-rmw on IngestPlane.accepted/shed)."""
        manager = Manager(ManagerConfig(prover="commitment"))
        plane = IngestPlane(
            manager,
            IngestPlaneConfig(
                workers=0,
                batch_size=8,
                submit_queue_max=4096,
                rate=RateLimitConfig(rate=1e6, burst=1e6),
            ),
        )
        n_per_thread, n_threads = 40, 4
        with plane:
            def submitter(tid: int):
                for i in range(n_per_thread):
                    # Half the traffic is replays (same i across tids).
                    plane.submit(make_att(i if tid % 2 else 1000 + tid * 100 + i))

            threads = [
                threading.Thread(target=submitter, args=(t,))
                for t in range(n_threads)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert plane.drain(timeout=30)
            stats = plane.stats()
        total = stats["accepted"] + stats["shed"] + sum(
            stats["rejections"].values()
        )
        assert total == n_per_thread * n_threads
        assert stats["pending"] == 0

    def test_pipeline_counters_balance_under_concurrent_submit(self):
        """completed + coalesced == submitted, even with submit racing
        from two threads against a deliberately slow device stage
        (pass-7 findings: unguarded-rmw on coalesced, check-then-act
        on _started)."""
        manager = SimpleNamespace(
            prepare_epoch=lambda epoch: SimpleNamespace(epoch=epoch)
        )
        pipe = EpochPipeline(
            manager,  # type: ignore[arg-type]
            queue_depth=1,
            device_stage=lambda prepared: time.sleep(0.005),
        )
        n_per_thread, n_threads = 25, 2
        with pipe:
            def submitter(tid: int):
                for i in range(n_per_thread):
                    pipe.submit(Epoch(tid * 1000 + i))

            threads = [
                threading.Thread(target=submitter, args=(t,))
                for t in range(n_threads)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert pipe.drain(timeout=30)
        assert pipe.completed + pipe.coalesced == n_per_thread * n_threads
        assert pipe.completed >= 1

    def test_warm_state_pair_never_tears(self):
        """_warm_t0 must read (last_scores, last_peer_hashes) as a
        matched pair: both published states map peer 10 -> 0.25 and
        peer 20 -> 0.75, so any torn combination shows up as a
        different seed (pass-7 finding: the converge_prepared publish
        racing prepare_epoch's warm remap)."""
        m = Manager(ManagerConfig(prover="commitment"))
        state_a = (np.array([0.25, 0.75]), [10, 20])
        state_b = (np.array([0.75, 0.25]), [20, 10])
        with m._state_lock:
            m.last_scores, m.last_peer_hashes = state_a
        stop = threading.Event()

        def flipper():
            flip = False
            while not stop.is_set():
                with m._state_lock:
                    m.last_scores, m.last_peer_hashes = (
                        state_b if flip else state_a
                    )
                flip = not flip

        t = threading.Thread(target=flipper)
        t.start()
        try:
            for _ in range(2000):
                t0 = m._warm_t0([10, 20])
                np.testing.assert_allclose(t0, [0.25, 0.75])
        finally:
            stop.set()
            t.join()


# ---------------------------------------------------------------------------
# sanitizer-wall configuration pins
# ---------------------------------------------------------------------------


class TestSanitizerWallConfig:
    REPO = Path(__file__).resolve().parent.parent

    def test_tsan_suppressions_stay_narrow(self):
        """`race:libgomp.so` matches any report whose stack passes
        through libgomp and was verified to mask a seeded race inside a
        parallel-region body — only the `called_from_lib` form is
        allowed (PERF.md §14)."""
        supp = (self.REPO / "native" / "tsan.supp").read_text()
        entries = [
            line for line in supp.splitlines() if line and not line.startswith("#")
        ]
        assert entries == ["called_from_lib:libgomp.so"], entries
        assert not any(e.startswith("race:") for e in entries)

    def test_sanitize_tool_targets_exist(self):
        import sys

        sys.path.insert(0, str(self.REPO / "tools"))
        import sanitize_native

        for rel in sanitize_native.ASAN_TESTS:
            assert (self.REPO / rel).exists(), rel
        assert set(sanitize_native.MODES) == {"asan", "tsan"}
        assert (self.REPO / "native" / "Makefile").read_text().count("sanitized:")

    def test_native_dir_env_override_respected(self, monkeypatch, tmp_path):
        """PROTOCOL_TPU_NATIVE_DIR points both loaders at the
        instrumented build (the sanitizer wall's selection mechanism)."""
        import importlib

        monkeypatch.setenv("PROTOCOL_TPU_NATIVE_DIR", str(tmp_path))
        import protocol_tpu.crypto.native as cn
        import protocol_tpu.zk.native as zn

        try:
            cn2 = importlib.reload(cn)
            zn2 = importlib.reload(zn)
            assert cn2._NATIVE_DIR == tmp_path
            assert zn2._NATIVE_DIR == tmp_path
        finally:
            monkeypatch.delenv("PROTOCOL_TPU_NATIVE_DIR")
            importlib.reload(cn)
            importlib.reload(zn)


# ---------------------------------------------------------------------------
# the acceptance stress
# ---------------------------------------------------------------------------


class TestConcurrencyStress:
    N_EPOCHS = 3

    def test_node_paths_under_witness_no_violations(self, static_model):
        """Scrapes + ingest + a churned pipelined epoch run, all
        concurrent, under lock-witness mode: zero cross-check
        violations, no deadlock, consistent tallies."""
        witness = LockWitness()
        errors: list[BaseException] = []
        with witness:
            manager = Manager(
                ManagerConfig(backend="tpu-windowed", prover="commitment")
            )
            manager.generate_initial_attestations()
            plane = IngestPlane(
                manager,
                IngestPlaneConfig(
                    workers=0,
                    batch_size=8,
                    submit_queue_max=4096,
                    rate=RateLimitConfig(rate=1e6, burst=1e6),
                ),
            )
            pipe = EpochPipeline(manager, alpha=0.1)
            # Watch exactly the attrs the analyzer inferred as guarded
            # on these classes — the static->runtime contract.
            for obj in (manager, pipe, plane):
                attrs = [
                    attr
                    for (cls, attr) in static_model.guard_map
                    if cls == type(obj).__name__
                ]
                witness.watch(obj, attrs)

            stop = threading.Event()

            def guarded(fn):
                def run():
                    try:
                        fn()
                    except BaseException as exc:  # noqa: BLE001
                        errors.append(exc)

                return run

            def scraper():
                while not stop.is_set():
                    prometheus_text()
                    handle_request("GET", "/metrics", manager)
                    handle_request("GET", "/debug/flight?n=50", manager)
                    handle_request("GET", "/status", manager)

            def ingester():
                i = 0
                while not stop.is_set():
                    plane.submit(make_att(i, sender=i % len(PKS)))
                    i += 1
                    time.sleep(0.001)

            workers = [
                threading.Thread(target=guarded(scraper), name=f"scrape-{i}")
                for i in range(2)
            ] + [threading.Thread(target=guarded(ingester), name="ingest-load")]
            with plane, pipe:
                for t in workers:
                    t.start()
                try:
                    for n in range(self.N_EPOCHS):
                        pipe.submit(Epoch(n + 1))
                        assert pipe.drain(timeout=120), "pipeline deadlock"
                finally:
                    stop.set()
                    for t in workers:
                        t.join(timeout=10)
                assert plane.drain(timeout=30), "ingest plane deadlock"
            violations = witness.cross_check(static_model)
        assert errors == [], errors
        assert violations == [], violations
        assert pipe.completed == self.N_EPOCHS
        for n in range(self.N_EPOCHS):
            assert pipe.outcomes[n + 1].error is None
        stats = plane.stats()
        assert stats["pending"] == 0
        assert stats["accepted"] >= 1
        report = witness.report()
        # The node's own locks were witnessed (allocation sites inside
        # the repo), and contention was exported through the metric.
        assert any(
            "protocol_tpu/" in site for site in report["locks"]
        ), report["locks"]
        assert sum(n for _, n in obs_metrics.LOCK_WAIT_SECONDS.samples()) > 0

    def test_witness_observed_guarded_writes(self, static_model):
        """The cross-check exercised real data: the stress run above is
        only meaningful if watched writes actually happened.  Re-run a
        minimal epoch under the witness and assert the manager's
        guarded publishes were observed."""
        witness = LockWitness()
        with witness:
            manager = Manager(
                ManagerConfig(backend="tpu-sparse", prover="commitment")
            )
            manager.generate_initial_attestations()
            attrs = [
                attr
                for (cls, attr) in static_model.guard_map
                if cls == "Manager"
            ]
            witness.watch(manager, attrs)
            manager.converge_epoch(Epoch(1), alpha=0.1)
            violations = witness.cross_check(static_model)
        assert violations == []
        observed = {attr for (cls, attr) in witness.writes if cls == "Manager"}
        assert "last_scores" in observed
        assert "last_peer_hashes" in observed
