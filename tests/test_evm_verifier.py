"""Contract-level verification tests — the reference's tier-3c ladder
step (circuit.rs:622-689: generate verifier, deploy into an embedded
executor, verify calldata), run through the generated EVM verifier and
the EtVerifierWrapper analog."""

import pytest

from protocol_tpu.crypto.poseidon import permute
from protocol_tpu.evm import EVM
from protocol_tpu.zk import plonk
from protocol_tpu.zk.cs import ConstraintSystem
from protocol_tpu.zk.evm_verifier import (
    GeneratedVerifier,
    evm_verify,
    generate_evm_verifier,
    generate_wrapper,
    infer_n_t,
)
from protocol_tpu.zk.gadgets import PoseidonChip, StdGate


def _mul_add_setup():
    cs = ConstraintSystem()
    std = StdGate(cs)
    out = std.add(std.mul(std.witness(3), std.witness(4)), std.witness(5))
    inst = cs.column("instance", "instance")
    cs.copy(cs.assign(inst, 0, 17), out)
    pk = plonk.compile_circuit(cs)
    proof = plonk.prove(pk, cs, [17], seed=b"t", transcript="keccak")
    gen = generate_evm_verifier(pk.vk, infer_n_t(pk.vk, proof), 1)
    return pk, proof, gen


class TestEvmVerifier:
    def test_valid_proof_accepted_with_gas(self):
        _pk, proof, gen = _mul_add_setup()
        ok, gas = evm_verify(gen, [17], proof)
        assert ok
        assert 100_000 < gas < 2_000_000  # plausible verifier cost

    def test_rejections(self):
        _pk, proof, gen = _mul_add_setup()
        assert not evm_verify(gen, [18], proof)[0]  # wrong instance
        for off in (3, len(proof) // 2, len(proof) - 17):
            bad = bytearray(proof)
            bad[off] ^= 1
            assert not evm_verify(gen, [17], bytes(bad))[0]
        assert not evm_verify(gen, [17], proof[:-32])[0]  # truncated
        assert not evm_verify(gen, [17], proof + b"\0" * 32)[0]  # extended

    def test_poseidon_transcript_proof_rejected(self):
        """A proof from the wrong Fiat-Shamir backend must not verify
        on the EVM (different challenge derivation)."""
        cs = ConstraintSystem()
        std = StdGate(cs)
        out = std.add(std.mul(std.witness(3), std.witness(4)), std.witness(5))
        inst = cs.column("instance", "instance")
        cs.copy(cs.assign(inst, 0, 17), out)
        pk = plonk.compile_circuit(cs)
        _pk2, kproof, gen = _mul_add_setup()
        pproof = plonk.prove(pk, cs, [17], seed=b"t")
        # Rejected either by challenge mismatch (same length) or by the
        # CALLDATASIZE check (different length) — never accepted.
        assert not evm_verify(gen, [17], pproof)[0]

    def test_matches_python_verifier_gas_free(self):
        """The EVM verdict agrees with the Python keccak verifier."""
        pk, proof, gen = _mul_add_setup()
        assert plonk.verify(pk.vk, [17], proof, transcript="keccak")
        assert evm_verify(gen, [17], proof)[0]

    def test_lookup_circuit_on_evm(self):
        from protocol_tpu.zk.chips import RangeCheckChip

        cs = ConstraintSystem()
        std = StdGate(cs)
        rng = RangeCheckChip(cs, word_bits=4)
        x = std.witness(13)
        rng.assert_word(x)
        y = std.witness(200)
        rng.assert_range(y, 2)
        inst = cs.column("instance", "instance")
        cs.copy(cs.assign(inst, 0, 13), x)
        pk = plonk.compile_circuit(cs)
        proof = plonk.prove(pk, cs, [13], seed=b"t", transcript="keccak")
        gen = generate_evm_verifier(pk.vk, infer_n_t(pk.vk, proof), 1)
        assert evm_verify(gen, [13], proof)[0]
        bad = bytearray(proof)
        bad[100] ^= 1
        assert not evm_verify(gen, [13], bytes(bad))[0]

    def test_poseidon_circuit_on_evm(self):
        cs = ConstraintSystem()
        std = StdGate(cs)
        pos = PoseidonChip(cs)
        outs = pos.permute([std.witness(i + 1) for i in range(5)])
        expected = permute([1, 2, 3, 4, 5])
        inst = cs.column("instance", "instance")
        cs.copy(cs.assign(inst, 0, expected[0]), outs[0])
        pk = plonk.compile_circuit(cs)
        proof = plonk.prove(pk, cs, [expected[0]], seed=b"x", transcript="keccak")
        gen = generate_evm_verifier(pk.vk, infer_n_t(pk.vk, proof), 1)
        assert evm_verify(gen, [expected[0]], proof)[0]

    def test_artifact_roundtrip(self):
        _pk, proof, gen = _mul_add_setup()
        restored = GeneratedVerifier.from_bytes(gen.to_bytes())
        assert restored.runtime == gen.runtime and restored.n_t == gen.n_t
        assert evm_verify(restored, [17], proof)[0]


class TestWrapper:
    def test_missing_verifier_message(self):
        evm = EVM()
        w = evm.deploy_runtime(generate_wrapper(0xDEAD))
        r = evm.call(w, b"\0" * 64)
        assert not r.success
        assert b"verifier-missing" in r.returndata

    def test_failed_verification_message(self):
        _pk, proof, gen = _mul_add_setup()
        evm = EVM()
        verifier = evm.deploy_runtime(gen.runtime)
        w = evm.deploy_runtime(generate_wrapper(verifier))
        bad = bytearray(proof)
        bad[3] ^= 1
        r = evm.call(w, gen.calldata([17], bytes(bad)), gas=500_000_000)
        assert not r.success
        assert b"verification-failed" in r.returndata
