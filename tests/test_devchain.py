"""Chain-integration tests on the in-process dev chain — the rebuild's
analog of the reference's Anvil tests (client/src/lib.rs:185-260,
client/src/utils.rs:169-206): deploy the registry and verifier
contracts, attest through the client's chain backend, replay the event
log through the node's event source, and verify a served proof
on-chain through the EtVerifierWrapper.

No Ethereum node or web3 exists in this image; the dev chain runs on
the repo's own EVM (evm/devchain.py), so every line of the event
source's replay/stream/decode and the client's chain-mode attest/verify
actually executes.
"""

import asyncio
from pathlib import Path

import pytest

from protocol_tpu.crypto import field
from protocol_tpu.crypto.keccak import keccak256
from protocol_tpu.evm.devchain import (
    ATTESTATION_CREATED_TOPIC,
    VERIFIED_TOPIC,
    DevChain,
    encode_attest_calldata,
    et_wrapper_runtime,
)
from protocol_tpu.node.ethereum import ChainEventSource, DevChainRpc

DATA = Path(__file__).resolve().parent.parent / "data"
P = field.MODULUS


def _station_chain():
    chain = DevChain()
    addr = chain.deploy_attestation_station()
    return chain, addr


class TestAttestationStationContract:
    def test_attest_batch_emits_and_stores(self):
        chain, addr = _station_chain()
        sender, about, key = 0xA11CE, 0xB0B, 0xDEAD
        val = b"some attestation payload bytes"
        r = chain.transact(
            addr, encode_attest_calldata([(about, key, val)]), sender
        )
        assert r.success and len(r.logs) == 1
        lg = chain.logs[0]
        assert lg.topics == [ATTESTATION_CREATED_TOPIC, sender, about, key]
        assert int.from_bytes(lg.data[32:64], "big") == len(val)
        assert lg.data[64 : 64 + len(val)] == val
        # Solidity-shaped nested mapping slot holds keccak(val).
        h1 = keccak256(sender.to_bytes(32, "big") + (0).to_bytes(32, "big"))
        h2 = keccak256(about.to_bytes(32, "big") + h1)
        slot = int.from_bytes(keccak256(key.to_bytes(32, "big") + h2), "big")
        assert chain.evm.storage[addr][slot] == int.from_bytes(keccak256(val), "big")

    def test_bad_selector_reverts(self):
        chain, addr = _station_chain()
        assert not chain.transact(addr, b"\x00\x01\x02\x03", 1).success
        assert chain.block_number == 1  # reverted tx does not mine

    def test_batch_padding_does_not_leak_previous_val(self):
        """A shorter val after a longer one must emit zero ABI padding,
        not residue from the previous iteration's memory."""
        chain, addr = _station_chain()
        r = chain.transact(
            addr,
            encode_attest_calldata([(1, 2, b"A" * 40), (3, 4, b"B" * 5)]),
            9,
        )
        assert r.success
        second = chain.logs[1].data
        assert second[64:69] == b"B" * 5
        assert second[69:96] == b"\0" * 27  # padding, not b"A" residue

    def test_call_is_ephemeral(self):
        """eth_call semantics: a query never mutates storage or mines."""
        chain, addr = _station_chain()
        before_blocks = chain.block_number
        chain.call(addr, encode_attest_calldata([(7, 8, b"query-only")]))
        assert chain.block_number == before_blocks
        assert chain.evm.storage.get(addr, {}) == {}
        assert chain.logs == []


class TestEventSourceOverDevChain:
    def test_client_attest_node_replay_roundtrip(self):
        """The reference flow: client signs + submits on-chain; a node
        replays the event log from block 0 and accepts the attestation
        (client/src/lib.rs:185-221 + server/src/main.rs:139-143)."""
        from protocol_tpu.client.client import DevChainBackend, EigenTrustClient
        from protocol_tpu.node.attestation import AttestationData
        from protocol_tpu.node.manager import Manager, ManagerConfig
        from tests.test_client import bootstrap_nodes, make_config

        chain, addr = _station_chain()
        cfg = make_config(
            None, event_fixture=None, as_address=f"0x{addr:040x}"
        )
        client = EigenTrustClient(cfg, bootstrap_nodes(), chain=DevChainBackend(chain))
        sent = client.attest()

        source = ChainEventSource(DevChainRpc(chain), cfg.as_address)
        events = list(source.replay())
        assert len(events) == 1
        ev = events[0]
        assert ev.key == sent.key and ev.val == sent.val
        # creator is the dev account, not the zero placeholder
        assert int(ev.creator, 16) == DevChainBackend.SENDER

        mgr = Manager(ManagerConfig(prover="commitment"))
        att = AttestationData.from_bytes(
            ev.val, mgr.config.num_neighbours
        ).to_attestation(mgr.config.num_neighbours)
        mgr.add_attestation(att)
        assert len(mgr.attestations) == 1

    def test_stream_polls_new_blocks(self):
        from protocol_tpu.evm.devchain import encode_attest_calldata

        chain, addr = _station_chain()
        source = ChainEventSource(DevChainRpc(chain), f"0x{addr:040x}")

        async def scenario():
            got = []
            stream = source.stream(poll_interval=0.01)

            async def consume():
                async for ev in stream:
                    got.append(ev)
                    if len(got) >= 2:
                        return

            chain.transact(addr, encode_attest_calldata([(1, 2, b"one")]), 7)
            task = asyncio.create_task(consume())
            await asyncio.sleep(0.05)
            chain.transact(addr, encode_attest_calldata([(3, 4, b"two")]), 7)
            await asyncio.wait_for(task, timeout=5)
            return got

        got = asyncio.run(scenario())
        assert [e.val for e in got] == [b"one", b"two"]


class TestOnChainVerify:
    def test_wrapper_verifies_committed_proof(self):
        """Deploy the committed verifier + wrapper on the dev chain and
        run the client's chain-mode verify — the reference's on-chain
        proof check against committed artifacts
        (client/src/lib.rs:223-260)."""
        from protocol_tpu.client.client import (
            DevChainBackend,
            EigenTrustClient,
        )
        from protocol_tpu.zk.evm_verifier import GeneratedVerifier
        from protocol_tpu.zk.proof import ProofRaw
        from tests.test_client import bootstrap_nodes, make_config

        gen = GeneratedVerifier.from_bytes((DATA / "et_verifier.bin").read_bytes())
        raw = ProofRaw.from_json((DATA / "et_proof.json").read_text())

        chain = DevChain()
        verifier = chain.deploy_runtime(gen.runtime)
        wrapper = chain.deploy_runtime(et_wrapper_runtime(verifier))

        cfg = make_config(
            None,
            event_fixture=None,
            et_verifier_wrapper_address=f"0x{wrapper:040x}",
        )
        client = EigenTrustClient(cfg, bootstrap_nodes(), chain=DevChainBackend(chain))
        assert client.use_chain()
        assert client.verify(raw)
        # The wrapper emitted Verified(msg.sender).
        assert any(
            lg.topics[:2] == [VERIFIED_TOPIC, DevChainBackend.SENDER]
            for lg in chain.logs
        )
        # Tampered public input reverts the wrapper -> False.
        bad = ProofRaw(
            pub_ins=[bytes([raw.pub_ins[0][0] ^ 1]) + raw.pub_ins[0][1:]]
            + raw.pub_ins[1:],
            proof=raw.proof,
            backend=raw.backend,
        )
        assert not client.verify(bad)
