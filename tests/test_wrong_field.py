"""In-circuit wrong-field integer + emulated-Fq ECC chip tests.

Tier-2 doctrine (SURVEY.md §4): every chip wraps in a minimal circuit
and is checked by the MockProver analog (assert_satisfied) against the
native implementations (zk/rns.py, zk/bn254.py), with tampered-witness
negatives.  Mirrors the reference's inline chip tests for
integer/mod.rs and ecc/mod.rs.
"""

import random

import pytest

from protocol_tpu.crypto import field
from protocol_tpu.zk.bn254 import G1
from protocol_tpu.zk.cs import ConstraintSystem
from protocol_tpu.zk.gadgets import StdGate
from protocol_tpu.zk.rns import FQ_MODULUS, WrongFieldInteger, compose
from protocol_tpu.zk.wrong_field import AssignedInteger, EccChip, IntegerChip

P = field.MODULUS


def _chips():
    cs = ConstraintSystem()
    std = StdGate(cs)
    integer = IntegerChip(cs, std)
    return cs, std, integer


class TestIntegerChip:
    def test_witness_roundtrip_and_range(self):
        cs, std, chip = _chips()
        v = random.Random(1).randrange(FQ_MODULUS)
        a = chip.witness(v)
        assert a.value(std) == v
        cs.assert_satisfied()

    def test_add_sub_mul_div_match_native(self):
        cs, std, chip = _chips()
        rng = random.Random(2)
        for _ in range(3):
            x, y = rng.randrange(FQ_MODULUS), rng.randrange(1, FQ_MODULUS)
            a, b = chip.witness(x), chip.witness(y)
            assert chip.add(a, b).value(std) == (x + y) % FQ_MODULUS
            assert chip.sub(a, b).value(std) == (x - y) % FQ_MODULUS
            assert chip.mul(a, b).value(std) == (x * y) % FQ_MODULUS
            expected_div = x * pow(y, -1, FQ_MODULUS) % FQ_MODULUS
            assert chip.div(a, b).value(std) == expected_div
            # Native half agrees (rns.py is the spec).
            wa, wb = WrongFieldInteger.from_value(x), WrongFieldInteger.from_value(y)
            assert wa.mul(wb).result.value() == (x * y) % FQ_MODULUS
        cs.assert_satisfied()

    def test_tampered_mul_result_unsatisfiable(self):
        cs, std, chip = _chips()
        a = chip.witness(1234567)
        b = chip.witness(7654321)
        r = chip.mul(a, b)
        # Corrupt the low result limb in the trace.
        cell = r.limbs[0]
        cs.trace[cell.column][cell.row] = (cs.value(cell.column, cell.row) + 1) % P
        with pytest.raises(AssertionError):
            cs.assert_satisfied()

    def test_tampered_quotient_unsatisfiable(self):
        cs, std, chip = _chips()
        a = chip.witness(FQ_MODULUS - 2)
        b = chip.witness(FQ_MODULUS - 3)
        chip.mul(a, b)
        # The mul quotient limbs are the first witnesses after the
        # operands; scan the std_a column for a row whose perturbation
        # breaks satisfaction without touching the result limbs.
        col = std.a
        rows = sorted(cs.trace[col])
        tampered = False
        for row in rows:
            orig = cs.trace[col][row]
            cs.trace[col][row] = (orig + 1) % P
            try:
                cs.assert_satisfied()
            except AssertionError:
                tampered = True
                cs.trace[col][row] = orig
                break
            cs.trace[col][row] = orig
        assert tampered, "no witness perturbation was caught"

    def test_non_canonical_limb_rejected(self):
        """A limb ≥ 2^68 must fail its range lookup."""
        cs, std, chip = _chips()
        big = (1 << 68) + 5
        cells = [std.witness(v) for v in (big, 0, 0, 0)]
        with pytest.raises(AssertionError):
            chip.from_limb_cells(cells)
            cs.assert_satisfied()


class TestEccChip:
    def _ecc(self):
        cs, std, integer = _chips()
        return cs, std, EccChip(cs, std, integer)

    def test_add_double_match_native(self):
        cs, std, ecc = self._ecc()
        g = G1(1, 2)
        p2 = g.mul(5)
        q2 = g.mul(11)
        a = ecc.witness(p2.x, p2.y)
        b = ecc.witness(q2.x, q2.y)
        s = ecc.add_incomplete(a, b)
        expect = p2.add(q2)
        assert s.values(std) == (expect.x, expect.y)
        d = ecc.double(a)
        expect2 = p2.add(p2)
        assert d.values(std) == (expect2.x, expect2.y)
        cs.assert_satisfied()

    def test_off_curve_point_rejected(self):
        cs, std, ecc = self._ecc()
        with pytest.raises(AssertionError):
            ecc.witness(3, 5)  # not on y² = x³ + 3
            cs.assert_satisfied()

    def test_scalar_mul_matches_native(self):
        cs, std, ecc = self._ecc()
        g = G1(1, 2)
        base = g.mul(7)
        k = 0xB7  # 8-bit scalar keeps the trace small
        scalar = ecc.std.witness(k)
        out = ecc.scalar_mul(ecc.witness(base.x, base.y), scalar, n_bits=8)
        expect = base.mul(k)
        assert out.values(std) == (expect.x, expect.y)
        cs.assert_satisfied()

    def test_scalar_mul_tampered_bit_unsatisfiable(self):
        cs, std, ecc = self._ecc()
        g = G1(1, 2)
        base = g.mul(3)
        scalar = ecc.std.witness(0x5)
        ecc.scalar_mul(ecc.witness(base.x, base.y), scalar, n_bits=4)
        bit_col = ecc.b2n.bit
        row = min(cs.trace[bit_col])
        cs.trace[bit_col][row] = 1 - cs.trace[bit_col][row]
        with pytest.raises(AssertionError):
            cs.assert_satisfied()
