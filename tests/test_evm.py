"""EVM interpreter tests: opcode semantics, gas accounting sanity,
Bn254 precompiles, and a wrapper-style staticcall flow (the execution
profile the reference exercises through revm, verifier/mod.rs:117-134)."""

import pytest

from protocol_tpu.crypto.keccak import keccak256
from protocol_tpu.evm import EVM, Precompiles, asm
from protocol_tpu.zk.bn254 import G1, GENERATOR
from protocol_tpu.zk.fields import G2_GENERATOR

FQ = 21888242871839275222246405745257275088696311157297823662689037894645226208583
FR = 21888242871839275222246405745257275088548364400416034343698204186575808495617


def run(code: bytes, calldata: bytes = b"", gas: int = 10_000_000):
    evm = EVM()
    addr = evm.deploy_runtime(code)
    return evm.call(addr, calldata, gas)


def ret_word(*pre) -> tuple:
    """asm suffix: store top of stack at mem[0] and return 32 bytes."""
    return (*pre, 0, "MSTORE", 32, 0, "RETURN")


class TestInterpreter:
    def test_arithmetic(self):
        r = run(asm(*ret_word(7, 5, "ADD")))
        assert r.success and int.from_bytes(r.returndata, "big") == 12

    def test_mulmod(self):
        r = run(asm(*ret_word(FR, 3, FR - 1, "MULMOD")))
        # (FR-1)*3 mod FR = FR-3
        assert int.from_bytes(r.returndata, "big") == FR - 3

    def test_sub_order(self):
        r = run(asm(*ret_word(3, 10, "SUB")))
        assert int.from_bytes(r.returndata, "big") == 7  # 10 - 3

    def test_calldataload(self):
        r = run(
            asm(*ret_word(0, "CALLDATALOAD")),
            calldata=(99).to_bytes(32, "big"),
        )
        assert int.from_bytes(r.returndata, "big") == 99

    def test_keccak(self):
        r = run(asm(0xAB, 0, "MSTORE8", 1, 0, "KECCAK256", 0, "MSTORE", 32, 0, "RETURN"))
        assert r.returndata == keccak256(b"\xab")

    def test_jump_loop(self):
        # sum 1..5 with a loop: i in slot counter on stack
        code = asm(
            0,  # acc
            5,  # i
            ("label", "loop"),
            "DUP1",
            "ISZERO",
            ("ref", "end"),
            "JUMPI",  # if i == 0 goto end
            "DUP1",  # [acc, i, i]
            "SWAP2",  # [i, i, acc]
            "ADD",  # [i, acc+i]
            "SWAP1",  # [acc', i]
            1,
            "SWAP1",
            "SUB",  # [acc', i-1]
            ("ref", "loop"),
            "JUMP",
            ("label", "end"),
            "POP",
            0,
            "MSTORE",
            32,
            0,
            "RETURN",
        )
        r = run(code)
        assert r.success, r.error
        assert int.from_bytes(r.returndata, "big") == 15

    def test_revert_propagates(self):
        r = run(asm(0, 0, "REVERT"))
        assert not r.success and r.error == "revert"

    def test_out_of_gas(self):
        r = run(asm(*ret_word(7, 5, "ADD")), gas=4)
        assert not r.success and "gas" in r.error

    def test_bad_jump_rejected(self):
        r = run(asm(4, "JUMP", "STOP", "STOP"))
        assert not r.success and "jump" in r.error

    def test_gas_metered(self):
        r = run(asm(*ret_word(7, 5, "ADD")))
        assert 0 < r.gas_used < 100


class TestPrecompiles:
    def test_ec_add(self):
        g2 = GENERATOR.double()
        data = (
            GENERATOR.x.to_bytes(32, "big")
            + GENERATOR.y.to_bytes(32, "big")
            + GENERATOR.x.to_bytes(32, "big")
            + GENERATOR.y.to_bytes(32, "big")
        )
        ok, out, gas = Precompiles.run(0x06, data)
        assert ok and gas == 150
        assert int.from_bytes(out[:32], "big") == g2.x
        assert int.from_bytes(out[32:], "big") == g2.y

    def test_ec_mul(self):
        data = (
            GENERATOR.x.to_bytes(32, "big")
            + GENERATOR.y.to_bytes(32, "big")
            + (5).to_bytes(32, "big")
        )
        ok, out, gas = Precompiles.run(0x07, data)
        g5 = GENERATOR.mul(5)
        assert ok and int.from_bytes(out[:32], "big") == g5.x

    def test_ec_add_rejects_off_curve(self):
        data = (1).to_bytes(32, "big") + (1).to_bytes(32, "big") + bytes(64)
        ok, _, _ = Precompiles.run(0x06, data)
        assert not ok

    def test_modexp_inverse(self):
        # a^(FR-2) mod FR == a^-1
        a = 12345
        data = (
            (32).to_bytes(32, "big") * 3
            + a.to_bytes(32, "big")
            + (FR - 2).to_bytes(32, "big")
            + FR.to_bytes(32, "big")
        )
        ok, out, gas = Precompiles.run(0x05, data)
        assert ok
        inv = int.from_bytes(out, "big")
        assert a * inv % FR == 1

    def test_pairing_check_via_evm(self):
        """e(G, H)·e(−G, H) == 1 through the 0x08 precompile."""

        def g2_words(q):
            return (
                q.x.coeffs[1].to_bytes(32, "big")
                + q.x.coeffs[0].to_bytes(32, "big")
                + q.y.coeffs[1].to_bytes(32, "big")
                + q.y.coeffs[0].to_bytes(32, "big")
            )

        neg_g = GENERATOR.neg()
        data = (
            GENERATOR.x.to_bytes(32, "big")
            + GENERATOR.y.to_bytes(32, "big")
            + g2_words(G2_GENERATOR)
            + neg_g.x.to_bytes(32, "big")
            + neg_g.y.to_bytes(32, "big")
            + g2_words(G2_GENERATOR)
        )
        ok, out, gas = Precompiles.run(0x08, data)
        assert ok and int.from_bytes(out, "big") == 1
        assert gas == 45000 + 34000 * 2

    def test_pairing_nondegenerate(self):
        """e(G, H) != 1 alone."""

        def g2_words(q):
            return (
                q.x.coeffs[1].to_bytes(32, "big")
                + q.x.coeffs[0].to_bytes(32, "big")
                + q.y.coeffs[1].to_bytes(32, "big")
                + q.y.coeffs[0].to_bytes(32, "big")
            )

        data = (
            GENERATOR.x.to_bytes(32, "big")
            + GENERATOR.y.to_bytes(32, "big")
            + g2_words(G2_GENERATOR)
        )
        ok, out, _ = Precompiles.run(0x08, data)
        assert ok and int.from_bytes(out, "big") == 0


class TestStaticcallFlow:
    def test_wrapper_staticcalls_inner(self):
        """EtVerifierWrapper-style: outer contract forwards calldata to
        an inner contract via STATICCALL and returns its result."""
        evm = EVM()
        # Inner: returns calldata[0..32] + 1.
        inner = evm.deploy_runtime(
            asm(*ret_word(1, 0, "CALLDATALOAD", "ADD"))
        )
        # Outer: copy calldata to memory, staticcall inner, return its word.
        outer = evm.deploy_runtime(
            asm(
                "CALLDATASIZE", 0, 0, "CALLDATACOPY",
                32, 0, "CALLDATASIZE", 0, inner, "GAS", "STATICCALL",
                ("ref", "ok"), "JUMPI",
                0, 0, "REVERT",
                ("label", "ok"),
                32, 0, "RETURN",
            )
        )
        r = evm.call(outer, (41).to_bytes(32, "big"))
        assert r.success, r.error
        assert int.from_bytes(r.returndata, "big") == 42

    def test_staticcall_enforces_read_only(self):
        """SSTORE inside a STATICCALL frame must fail the inner call
        (real EVM static-context semantics); the outer frame observes
        success=0 and the store never lands."""
        evm = EVM()
        inner = evm.deploy_runtime(asm(7, 1, "SSTORE", *ret_word(1)))
        outer = evm.deploy_runtime(
            asm(
                *ret_word(32, 0, 0, 0, inner, "GAS", "STATICCALL"),
            )
        )
        r = evm.call(outer, b"")
        assert r.success, r.error
        assert int.from_bytes(r.returndata, "big") == 0  # inner call failed
        assert evm.storage.get(inner, {}).get(1) is None

    def test_sstore_allowed_outside_static(self):
        evm = EVM()
        addr = evm.deploy_runtime(asm(7, 1, "SSTORE", *ret_word(1)))
        r = evm.call(addr, b"")
        assert r.success, r.error
        assert evm.storage[addr][1] == 7

    def test_modexp_oversize_consumes_forwarded_gas(self):
        """A failing precompile consumes the gas forwarded to it — the
        STATICCALL returns 0 and gas_used reflects the forwarded gas,
        not the precompile's (zero) metered cost."""
        big = 2000  # > 1024-byte length cap -> precompile failure
        calldata = big.to_bytes(32, "big") * 3
        evm = EVM()
        outer = evm.deploy_runtime(
            asm(
                "CALLDATASIZE", 0, 0, "CALLDATACOPY",
                *ret_word(32, 0, "CALLDATASIZE", 0, 0x05, 50_000, "STATICCALL"),
            )
        )
        r = evm.call(outer, calldata, gas=1_000_000)
        assert r.success, r.error
        assert int.from_bytes(r.returndata, "big") == 0  # precompile failed
        # Forwarded 50k consumed (plus base costs), far above the
        # metered-cost-only floor.
        assert r.gas_used > 50_000

    def test_modexp_unpaid_pow_never_runs(self):
        """Cost check happens before the pow: a huge exponentiation with
        a tiny gas limit returns failure with the true cost reported."""
        blen = elen = mlen = 1024
        head = b"".join(x.to_bytes(32, "big") for x in (blen, elen, mlen))
        body = b"\xff" * (blen + elen + mlen)
        ok, out, gas = Precompiles.run(0x05, head + body, gas_limit=100)
        assert not ok and out == b"" and gas > 100

    def test_staticcall_precompile_from_bytecode(self):
        """ecMul via STATICCALL from inside a contract."""
        code = asm(
            # mem[0:64] = G, mem[64] = 3
            GENERATOR.x, 0, "MSTORE",
            GENERATOR.y, 32, "MSTORE",
            3, 64, "MSTORE",
            # staticcall(gas, 0x07, 0, 96, 0, 64)
            64, 0, 96, 0, 0x07, "GAS", "STATICCALL",
            "POP",
            64, 0, "RETURN",
        )
        r = run(code)
        assert r.success, r.error
        g3 = GENERATOR.mul(3)
        assert int.from_bytes(r.returndata[:32], "big") == g3.x
