"""Auxiliary subsystems: checkpoint/resume, telemetry, /status
(SURVEY.md §5)."""

import json

import numpy as np

from protocol_tpu.models.graphs import erdos_renyi
from protocol_tpu.node.checkpoint import CheckpointStore
from protocol_tpu.node.epoch import Epoch
from protocol_tpu.node.manager import Manager, ManagerConfig
from protocol_tpu.node.server import handle_request
from protocol_tpu.utils.telemetry import TELEMETRY, Telemetry


class TestCheckpointStore:
    def test_save_load_roundtrip(self, tmp_path):
        store = CheckpointStore(tmp_path)
        g = erdos_renyi(100, avg_degree=4.0, seed=1)
        scores = np.linspace(0, 1, 100)
        store.save(Epoch(5), g, scores)

        snap = store.load_latest()
        assert snap.epoch == Epoch(5)
        assert snap.graph.n == g.n
        np.testing.assert_array_equal(snap.graph.src, g.src)
        np.testing.assert_array_equal(snap.graph.weight, g.weight)
        np.testing.assert_array_equal(snap.graph.pre_trusted, g.pre_trusted)
        np.testing.assert_allclose(snap.scores, scores)

    def test_latest_tracks_manifest(self, tmp_path):
        store = CheckpointStore(tmp_path)
        g = erdos_renyi(20, seed=2)
        store.save(Epoch(1), g)
        store.save(Epoch(3), g)
        store.save(Epoch(2), g)  # out-of-order write
        assert store.load_latest().epoch == Epoch(2)  # manifest wins

    def test_prune_keeps_recent(self, tmp_path):
        store = CheckpointStore(tmp_path, keep=2)
        g = erdos_renyi(10, seed=3)
        for e in range(5):
            store.save(Epoch(e), g)
        assert sorted(store.epochs()) == [3, 4]

    def test_empty_dir(self, tmp_path):
        assert CheckpointStore(tmp_path).load_latest() is None

    def test_scores_optional(self, tmp_path):
        store = CheckpointStore(tmp_path)
        store.save(Epoch(0), erdos_renyi(10, seed=4))
        assert store.load_latest().scores is None

    def test_node_restores_proof_from_checkpoint(self, tmp_path):
        """Restart path: a new node serves the checkpointed proof before
        any epoch has run (SURVEY.md §5 checkpoint/resume doctrine)."""
        import asyncio

        from protocol_tpu.node.config import ProtocolConfig
        from protocol_tpu.node.server import Node
        from protocol_tpu.zk.proof import ProofRaw

        m = Manager(ManagerConfig(prover="commitment"))
        m.generate_initial_attestations()
        m.calculate_proofs(Epoch(41))
        store = CheckpointStore(tmp_path)
        store.save(
            Epoch(41),
            m.build_graph(),
            None,
            m.get_proof(Epoch(41)).to_raw().to_json(),
        )

        async def scenario():
            cfg = ProtocolConfig(
                epoch_interval=3600,
                endpoint=((127, 0, 0, 1), 0),
                checkpoint_dir=str(tmp_path),
                prover="commitment",
            )
            node = Node.from_config(cfg)
            await node.start()
            status, body = handle_request("GET", "/score", node.manager)
            await node.stop()
            return status, body

        status, body = asyncio.run(scenario())
        assert status == 200
        assert ProofRaw.from_json(body).to_proof().pub_ins == m.get_proof(Epoch(41)).pub_ins


class TestTelemetry:
    def test_timer_and_counter(self):
        t = Telemetry()
        with t.timer("work"):
            pass
        with t.timer("work"):
            pass
        t.count("items", 3)
        snap = t.snapshot()
        assert snap["timers"]["work"]["count"] == 2
        assert snap["counters"]["items"] == 3

    def test_timer_records_on_exception(self):
        t = Telemetry()
        try:
            with t.timer("boom"):
                raise RuntimeError
        except RuntimeError:
            pass
        assert t.timers["boom"].count == 1

    def test_status_endpoint(self):
        TELEMETRY.reset()
        m = Manager(ManagerConfig(prover="commitment"))
        m.generate_initial_attestations()
        m.calculate_proofs(Epoch(9))
        status, body = handle_request("GET", "/status", m)
        assert status == 200
        obj = json.loads(body)
        assert obj["attestations"] == 5
        assert obj["cached_proofs"] == 1
        assert obj["latest_epoch"] == 9
        assert obj["backend"] == "native-cpu"
