"""Pod trace stitching (ISSUE 19): clock-offset estimation, the
multi-host stitcher (skewed/drifting clocks, out-of-order arrival,
partial stitches), exact skew math, the straggler watcher, and the pod
SLO objectives."""

from __future__ import annotations

import json

import pytest

from protocol_tpu.obs.journal import JOURNAL
from protocol_tpu.obs.metrics import POD_STRAGGLER
from protocol_tpu.obs.podtrace import (
    POD_TRACES,
    PodTraceStore,
    clock_sync_samples,
    compute_phase_skew,
    directory_epochs,
    directory_hosts,
    estimate_offset,
    phase_durations,
    publish_epoch_trace,
    stitch_epoch,
)
from protocol_tpu.obs.slo import SLOEngine, pod_objectives
from protocol_tpu.obs.trace import Tracer
from protocol_tpu.obs.watchers import StragglerWatcher


def _trace(start_monotonic: float, duration: float, phases: dict[str, tuple]):
    """A serialized epoch_tick tree: {phase: (start_offset_s, dur_s)}."""
    return {
        "name": "epoch_tick",
        "span_id": 1,
        "start_monotonic": start_monotonic,
        "start_offset_s": 0.0,
        "duration_s": duration,
        "attrs": {},
        "children": [
            {
                "name": name,
                "span_id": i + 2,
                "start_offset_s": off,
                "duration_s": dur,
                "attrs": {},
                "children": [],
            }
            for i, (name, (off, dur)) in enumerate(phases.items())
        ],
    }


def _sync(offset: float, base: float = 100.0, n: int = 3):
    """Exact sync samples for a host whose unix = monotonic + offset."""
    return [
        {"monotonic": base + i, "unix": base + i + offset} for i in range(n)
    ]


# ---------------------------------------------------------------------------
# clock offset estimation
# ---------------------------------------------------------------------------


class TestClockOffset:
    def test_exact_offset_recovered(self):
        assert estimate_offset(_sync(4_999_000.25)) == pytest.approx(
            4_999_000.25
        )

    def test_median_absorbs_preempted_pair(self):
        # One pair split by an 80s "preemption" between the clock
        # reads; the median ignores it (the NTP-filter argument).
        samples = _sync(5.0, n=2) + [{"monotonic": 200.0, "unix": 285.0}]
        assert estimate_offset(samples) == pytest.approx(5.0)

    def test_empty_and_malformed_samples(self):
        assert estimate_offset([]) is None
        assert estimate_offset([{"monotonic": 1.0}, "junk"]) is None

    def test_live_samples_pair_real_clocks(self):
        samples = clock_sync_samples(3)
        assert len(samples) == 3
        off = estimate_offset(samples)
        assert off is not None and off != 0.0


# ---------------------------------------------------------------------------
# exact skew + attribution math
# ---------------------------------------------------------------------------


class TestSkewMath:
    def test_max_minus_median(self):
        skew = compute_phase_skew({"plan": {0: 1.0, 1: 2.0, 2: 6.0}})
        assert skew == {"plan": pytest.approx(4.0)}

    def test_two_host_median_is_mean(self):
        skew = compute_phase_skew({"converge": {0: 0.1, 1: 0.5}})
        assert skew == {"converge": pytest.approx(0.2)}

    def test_single_host_phase_skipped(self):
        assert compute_phase_skew({"checkpoint": {0: 3.0}}) == {}

    def test_phase_durations_first_match_depth_first(self):
        t = _trace(0.0, 2.0, {"plan": (0.0, 0.5), "converge": (0.5, 1.0)})
        # A nested duplicate must not shadow the first (depth-first) hit.
        t["children"][0]["children"] = [
            {"name": "converge", "start_offset_s": 0.1, "duration_s": 9.9,
             "attrs": {}, "children": []}
        ]
        assert phase_durations(t) == {
            "plan": pytest.approx(0.5),
            "converge": pytest.approx(1.0),
        }


# ---------------------------------------------------------------------------
# publish + directory scan
# ---------------------------------------------------------------------------


class TestPublish:
    def test_publish_without_stored_trace_returns_none(self, tmp_path):
        t = Tracer()
        assert publish_epoch_trace(tmp_path, 0, 7, tracer=t) is None

    def test_round_trip_and_directory_scan(self, tmp_path):
        path = publish_epoch_trace(
            tmp_path, 3, 12,
            trace=_trace(10.0, 1.0, {"plan": (0.0, 1.0)}),
            sync=_sync(5.0),
        )
        assert path is not None and path.name == "podtrace-h003-e000012.json"
        rec = json.loads(path.read_text())
        assert rec["host"] == 3 and rec["epoch"] == 12
        assert directory_hosts(tmp_path, 12) == [3]
        assert directory_epochs(tmp_path) == [12]


# ---------------------------------------------------------------------------
# the stitcher
# ---------------------------------------------------------------------------


def _publish_pair(tmp_path, *, skew=0.0):
    """Two hosts with wildly different monotonic bases and clock
    offsets whose wall-time roots sit 0.1s apart; host 1's checkpoint
    runs ``skew`` seconds longer."""
    publish_epoch_trace(
        tmp_path, 0, 5,
        trace=_trace(1000.0, 2.0, {
            "plan": (0.0, 0.5), "converge": (0.5, 1.0),
            "checkpoint": (1.5, 0.3),
        }),
        sync=_sync(4_999_000.0, base=990.0),
        barrier={"enter_monotonic": 1000.5, "wait_seconds": 0.04},
    )
    publish_epoch_trace(
        tmp_path, 1, 5,
        trace=_trace(50.0, 1.9, {
            "plan": (0.0, 0.5), "converge": (0.5, 1.0),
            "checkpoint": (1.5, 0.3 + skew),
        }),
        sync=_sync(4_999_950.1, base=40.0),
        barrier={"enter_monotonic": 50.7, "wait_seconds": 0.01},
    )


class TestStitcher:
    def test_skewed_clocks_align_exactly(self, tmp_path):
        _publish_pair(tmp_path, skew=0.4)
        store = PodTraceStore()
        s = stitch_epoch(
            tmp_path, 5, expected_hosts=2, store=store,
            straggler_watcher=StragglerWatcher(),
        )
        assert s["complete"] and s["hosts"] == [0, 1]
        # Offsets recovered exactly despite disjoint monotonic bases.
        assert s["clock_offsets_s"]["0"] == pytest.approx(4_999_000.0)
        assert s["clock_offsets_s"]["1"] == pytest.approx(4_999_950.1)
        # host0 root lands at wall 5_000_000.0, host1 at +0.1.
        assert s["start_unix"] == pytest.approx(5_000_000.0)
        assert s["children"][1]["start_offset_s"] == pytest.approx(0.1)
        # Checkpoint skew: max 0.7 - median(mean of 0.3, 0.7) = 0.2.
        assert s["phase_skew_s"]["checkpoint"] == pytest.approx(0.2)
        assert s["phase_skew_s"]["converge"] == pytest.approx(0.0)
        # Barrier arrivals: 0.5 vs 0.1 + 0.7 -> spread 0.3.
        assert s["barrier"]["spread_s"] == pytest.approx(0.3)
        assert s["barrier"]["waits_s"] == {"0": 0.04, "1": 0.01}
        # Attribution: (0.5 + 1.0 + 0.3[+skew]) / root.
        assert s["phase_attribution"]["0"] == pytest.approx(0.9, abs=1e-3)
        assert store.get(5)["epoch"] == 5

    def test_drifting_clock_sample_noise_filtered(self, tmp_path):
        _publish_pair(tmp_path)
        # Corrupt host 1's file with one preempted sync pair; the
        # median keeps the stitch exact.
        path = tmp_path / "podtrace-h001-e000005.json"
        rec = json.loads(path.read_text())
        rec["clock_sync"].append({"monotonic": 40.0, "unix": 4_999_999_999.0})
        path.write_text(json.dumps(rec))
        s = stitch_epoch(tmp_path, 5, store=PodTraceStore(),
                         straggler_watcher=StragglerWatcher())
        assert s["clock_offsets_s"]["1"] == pytest.approx(4_999_950.1)

    def test_out_of_order_and_numeric_host_sort(self, tmp_path):
        # Arrival order 10, 2, 0 — the stitch must sort hosts
        # numerically (lexically "10" < "2").
        for host in (10, 2, 0):
            publish_epoch_trace(
                tmp_path, host, 3,
                trace=_trace(100.0 * host + 1.0, 1.0, {"plan": (0.0, 1.0)}),
                sync=_sync(-100.0 * host, base=100.0 * host + 0.5),
            )
        s = stitch_epoch(tmp_path, 3, store=PodTraceStore(),
                         straggler_watcher=StragglerWatcher())
        assert s["hosts"] == [0, 2, 10]
        assert [c["attrs"]["host"] for c in s["children"]] == [0, 2, 10]

    def test_missing_host_partial_stitch(self, tmp_path):
        _publish_pair(tmp_path)
        store = PodTraceStore()
        s = stitch_epoch(tmp_path, 5, expected_hosts=3, store=store,
                         straggler_watcher=StragglerWatcher())
        assert not s["complete"]
        assert s["missing_hosts"] == [2]
        assert store.last_missing_hosts() == 1

    def test_no_records_returns_none(self, tmp_path):
        assert stitch_epoch(tmp_path, 9) is None

    def test_degraded_record_without_sync_still_lands(self, tmp_path):
        _publish_pair(tmp_path)
        path = tmp_path / "podtrace-h001-e000005.json"
        rec = json.loads(path.read_text())
        rec["clock_sync"] = []
        path.write_text(json.dumps(rec))
        s = stitch_epoch(tmp_path, 5, store=PodTraceStore(),
                         straggler_watcher=StragglerWatcher())
        assert s["hosts"] == [0, 1]
        assert s["children"][1]["attrs"].get("clock_degraded") is True
        assert "1" not in s["clock_offsets_s"]

    def test_graft_parks_until_local_epoch_closes(self, tmp_path):
        # The stitching host's own epoch root may still be open (or not
        # yet stored) when the stitch lands — the pod_stitch span must
        # ride Tracer.graft's parking and attach once the root closes.
        _publish_pair(tmp_path)
        t = Tracer()
        s = stitch_epoch(tmp_path, 5, store=PodTraceStore(),
                         straggler_watcher=StragglerWatcher(), graft_into=t)
        assert s is not None
        assert t.get_trace(5) is None  # parked, nothing stored yet
        with t.epoch(5):
            pass
        names = [c["name"] for c in t.get_trace(5)["children"]]
        assert "pod_stitch" in names


# ---------------------------------------------------------------------------
# stitched-trace store ring
# ---------------------------------------------------------------------------


class TestPodTraceRoute:
    """GET /trace/pod through the node router — the serve half of the
    stitch exchange ("any host can answer")."""

    class _FakeConfig:
        def __init__(self, fleet_dir):
            self.fleet_dir = str(fleet_dir)

    class _FakeNode:
        def __init__(self, fleet_dir):
            self.config = TestPodTraceRoute._FakeConfig(fleet_dir)

    def test_latest_prefers_newer_published_epoch(self, tmp_path):
        """A host whose local store lags the exchange (it was not the
        tick-time stitcher) must serve the newest PUBLISHED epoch as
        latest, stitching it on demand — not its stale store entry."""
        from protocol_tpu.node.server import handle_request

        POD_TRACES.reset()
        try:
            POD_TRACES.put(7, {"epoch": 7, "hosts": [0], "missing_hosts": []})
            for host in (0, 1):
                publish_epoch_trace(
                    tmp_path,
                    host,
                    9,
                    trace=_trace(
                        1000.0 + host,
                        1.0,
                        {"converge": (0.0, 0.5 + 0.2 * host)},
                    ),
                    sync=_sync(5_000.0, base=1000.0 + host),
                )
            status, body = handle_request(
                "GET",
                "/trace/pod/latest",
                None,
                node=self._FakeNode(tmp_path),
            )
            assert status == 200
            doc = json.loads(body)
            assert doc["epoch"] == 9
            assert doc["hosts"] == [0, 1]
        finally:
            POD_TRACES.reset()

    def test_store_only_serves_without_node(self):
        """The dryrun probe path: manager=None, node=None — the route
        answers from the stitch store alone."""
        from protocol_tpu.node.server import handle_request

        POD_TRACES.reset()
        try:
            POD_TRACES.put(3, {"epoch": 3, "hosts": [0, 1], "missing_hosts": []})
            status, body = handle_request("GET", "/trace/pod/latest", None)
            assert status == 200
            assert json.loads(body)["epoch"] == 3
            status, _ = handle_request("GET", "/trace/pod/99", None)
            assert status == 404
        finally:
            POD_TRACES.reset()

    def test_no_epochs_anywhere_404s(self):
        from protocol_tpu.node.server import handle_request

        POD_TRACES.reset()
        status, body = handle_request("GET", "/trace/pod/latest", None)
        assert status == 404
        assert "no pod epochs" in body


class TestPodTraceStore:
    def test_ring_eviction(self):
        store = PodTraceStore(keep_epochs=3)
        for e in range(5):
            store.put(e, {"epoch": e, "missing_hosts": []})
        assert store.epochs() == [2, 3, 4]
        assert store.latest_epoch() == 4
        assert store.get(0) is None

    def test_last_missing_tracks_newest_stitch(self):
        store = PodTraceStore()
        assert store.last_missing_hosts() is None
        store.put(1, {"missing_hosts": [2, 3]})
        assert store.last_missing_hosts() == 2
        store.put(2, {"missing_hosts": []})
        assert store.last_missing_hosts() == 0


# ---------------------------------------------------------------------------
# straggler watcher
# ---------------------------------------------------------------------------


class TestStragglerWatcher:
    def test_k_consecutive_epochs_flag(self):
        w = StragglerWatcher(ratio=1.5, k=2, min_seconds=0.05)
        slow = {"checkpoint": {0: 0.1, 1: 0.1, 2: 0.5}}
        r1 = w.observe(1, slow)
        assert r1["exceeded"] == {2: ["checkpoint"]} and not r1["flagged"]
        r2 = w.observe(2, slow)
        assert r2["flagged"] == [2]
        assert w.flagged() == {2: {"epoch": 2, "phases": ["checkpoint"],
                                   "streak": 2}}
        assert POD_STRAGGLER.value(host="2") == 1.0

    def test_clean_epoch_resets_streak_and_unflags(self):
        w = StragglerWatcher(ratio=1.5, k=2, min_seconds=0.05)
        slow = {"plan": {0: 0.1, 1: 0.9}}
        clean = {"plan": {0: 0.1, 1: 0.1}}
        w.observe(1, slow)
        w.observe(2, clean)  # streak broken before k
        w.observe(3, slow)
        assert w.observe(4, slow)["flagged"] == [1]
        w.observe(5, clean)
        assert w.flagged() == {}
        assert POD_STRAGGLER.value(host="1") == 0.0

    def test_min_seconds_floor_ignores_tiny_phases(self):
        w = StragglerWatcher(ratio=1.5, k=1, min_seconds=0.05)
        # 3x the median but only 2ms over it: microsecond-scale jitter.
        r = w.observe(1, {"plan": {0: 0.001, 1: 0.003}})
        assert r["exceeded"] == {} and not r["flagged"]

    def test_flag_journals_anomaly(self):
        w = StragglerWatcher(ratio=1.5, k=1, min_seconds=0.05)
        w.observe(7, {"converge": {0: 0.1, 1: 0.8}})
        events = [
            e for e in JOURNAL.tail(50)
            if e.get("kind") == "anomaly" and e.get("what") == "pod-straggler"
        ]
        assert events and events[-1]["host"] == 1

    def test_missing_host_keeps_streak(self):
        w = StragglerWatcher(ratio=1.5, k=2, min_seconds=0.05)
        w.observe(1, {"plan": {0: 0.1, 1: 0.9}})
        # Host 1 vanished (partial stitch) — its streak must survive.
        w.observe(2, {"plan": {0: 0.1}})
        assert w.observe(3, {"plan": {0: 0.1, 1: 0.9}})["flagged"] == [1]


# ---------------------------------------------------------------------------
# pod SLO objectives
# ---------------------------------------------------------------------------


class TestPodSLO:
    def test_stitch_completeness_reads_store(self):
        engine = SLOEngine()
        for obj in pod_objectives():
            engine.register(obj)
        POD_TRACES.put(1, {"missing_hosts": [3]})
        try:
            doc = engine.evaluate()
            comp = doc["objectives"]["pod-stitch-completeness"]
            assert comp["value"] == 1 and not comp["ok"]
            POD_TRACES.put(2, {"missing_hosts": []})
            doc = engine.evaluate()
            assert doc["objectives"]["pod-stitch-completeness"]["ok"]
        finally:
            POD_TRACES.reset()

    def test_skew_objective_trips_on_seeded_skew(self):
        from protocol_tpu.obs.metrics import POD_PHASE_SKEW_SECONDS

        engine = SLOEngine()
        for obj in pod_objectives(phase_skew_p99_s=0.2):
            engine.register(obj)
        POD_PHASE_SKEW_SECONDS.observe(0.3, phase="checkpoint")
        doc = engine.evaluate()
        skew = doc["objectives"]["pod-phase-skew-p99"]
        assert skew["value"] is not None and skew["value"] > 0.2
        assert not skew["ok"] and not doc["ok"]
