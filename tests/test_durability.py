"""Durability wall (ISSUE 14): WAL semantics, checkpoint integrity +
epoch-by-epoch fallback, deterministic boot recovery, the RPC retry
wall, and the admission plane's durability contract.

The invariants this suite pins:

- crash-after-append-before-checkpoint replays exactly once; a torn
  record (crash mid-append) drops ONLY the unacknowledged tail;
- segment rotation + post-checkpoint truncation bound WAL disk, and
  truncation respects the *oldest retained* snapshot (fallback must
  still find its records);
- a torn/corrupt/truncated snapshot — at any byte — never crashes the
  loader: ``load`` raises the typed :class:`SnapshotCorrupt`,
  ``load_latest`` falls back to the newest valid epoch, counted and
  journaled;
- recovery is idempotent across double restarts;
- the chain event stream retries ``block_number``/``get_logs`` with
  backoff + jitter + per-call timeout, resumes from the persisted
  block cursor, and counts ``eigentrust_rpc_retries_total{op}`` —
  driven by the ``rpc.get_logs`` chaos fault point.
"""

from __future__ import annotations

import asyncio
import json
import time

import numpy as np
import pytest

from protocol_tpu import chaos
from protocol_tpu.crypto import calculate_message_hash
from protocol_tpu.crypto.eddsa import sign
from protocol_tpu.node.attestation import Attestation, AttestationData
from protocol_tpu.node.bootstrap import FIXED_SET, keyset_from_raw
from protocol_tpu.node.checkpoint import CheckpointStore, SnapshotCorrupt
from protocol_tpu.node.epoch import Epoch
from protocol_tpu.node.ethereum import ChainEventSource, RetryPolicy
from protocol_tpu.node.manager import Manager, ManagerConfig
from protocol_tpu.node.wal import (
    AttestationWAL,
    decode_payload,
    encode_payload,
    recover,
)
from protocol_tpu.obs import metrics as obs_metrics
from protocol_tpu.trust.graph import TrustGraph

SKS, PKS = keyset_from_raw(FIXED_SET)


@pytest.fixture(autouse=True)
def _reset_chaos():
    yield
    chaos.reset()


def make_att(i: int, sender: int = 0) -> Attestation:
    """Unique validly-signed attestation #i (scores sum to SCALE)."""
    d = i % 190
    scores = [200 + d, 200 - d, 200, 200, 200]
    _, msgs = calculate_message_hash(PKS, [scores])
    sig = sign(SKS[sender], PKS[sender], msgs[0])
    return Attestation(sig=sig, pk=PKS[sender], neighbours=list(PKS), scores=scores)


def wire(att: Attestation) -> bytes:
    return AttestationData.from_attestation(att).to_bytes()


def make_manager() -> Manager:
    return Manager(ManagerConfig(prover="commitment"))


def small_graph(n: int = 4, seed: int = 3) -> TrustGraph:
    rng = np.random.default_rng(seed)
    e = 3 * n
    src = rng.integers(0, n, e).astype(np.int32)
    dst = (src + 1 + rng.integers(0, n - 1, e).astype(np.int32)) % n
    pre = np.zeros(n, bool)
    pre[0] = True
    return TrustGraph(
        n, src, dst.astype(np.int32), rng.random(e).astype(np.float32), pre
    )


def cache_digests(manager: Manager) -> dict[int, tuple[int, ...]]:
    """Comparable view of the attestation cache: sender hash -> scores."""
    return {h: tuple(a.scores) for h, a in manager.attestations.items()}


class TestWALSemantics:
    def test_append_replay_roundtrip(self, tmp_path):
        wal = AttestationWAL(tmp_path)
        payloads = [encode_payload(5, wire(make_att(i, i))) for i in range(3)]
        seqs = [wal.append(p) for p in payloads]
        assert seqs == [1, 2, 3]
        replayed = list(wal.replay())
        assert [s for s, _ in replayed] == [1, 2, 3]
        assert [p for _, p in replayed] == payloads
        n, wire_bytes = decode_payload(replayed[0][1])
        assert n == 5
        att = AttestationData.from_bytes(wire_bytes, n).to_attestation(n)
        assert att.scores == make_att(0, 0).scores

    def test_replay_after_seq_skips_prefix(self, tmp_path):
        wal = AttestationWAL(tmp_path)
        for i in range(4):
            wal.append(encode_payload(5, wire(make_att(i, i % 5))))
        assert [s for s, _ in wal.replay(after_seq=2)] == [3, 4]

    def test_crash_after_append_before_checkpoint_replays_exactly_once(
        self, tmp_path
    ):
        m1 = make_manager()
        m1.wal = AttestationWAL(tmp_path / "wal")
        att = make_att(7, 2)
        assert m1.apply_verified(att).accepted
        # "Crash": abandon the process state; a fresh manager recovers
        # from disk alone.
        replayed0 = obs_metrics.WAL_REPLAYED.value()
        m2 = make_manager()
        report = recover(m2, None, AttestationWAL(tmp_path / "wal"))
        assert report["wal_replayed"] == 1
        assert obs_metrics.WAL_REPLAYED.value() - replayed0 == 1
        assert cache_digests(m2)[att.pk.hash()] == tuple(att.scores)

    def test_torn_tail_drops_only_the_tail_record(self, tmp_path):
        wal = AttestationWAL(tmp_path)
        for i in range(3):
            wal.append(encode_payload(5, wire(make_att(i, i))))
        wal.close()
        seg = sorted(tmp_path.glob("wal_*.seg"))[0]
        data = seg.read_bytes()
        seg.write_bytes(data[:-7])  # crash mid-write of record 3
        wal2 = AttestationWAL(tmp_path)
        assert [s for s, _ in wal2.replay()] == [1, 2]
        assert wal2.dropped_tail == 1
        # New appends continue past the highest VALID seq.
        assert wal2.append(encode_payload(5, wire(make_att(9, 4)))) == 3

    def test_mid_log_bitflip_stops_that_segment_conservatively(self, tmp_path):
        wal = AttestationWAL(tmp_path)
        for i in range(3):
            wal.append(encode_payload(5, wire(make_att(i, i))))
        wal.close()
        seg = sorted(tmp_path.glob("wal_*.seg"))[0]
        data = bytearray(seg.read_bytes())
        # Flip a byte inside record 2's payload (header is 8+16 bytes,
        # record 1 spans 16+payload): aim well into the middle.
        data[len(data) // 2] ^= 0xFF
        seg.write_bytes(bytes(data))
        replayed = [s for s, _ in AttestationWAL(tmp_path).replay()]
        assert replayed in ([1], [1, 2]), replayed  # never a corrupt record

    def test_segment_rotation_and_truncation_bound_disk(self, tmp_path):
        wal = AttestationWAL(tmp_path, segment_max_bytes=256)
        for i in range(10):
            wal.append(encode_payload(5, wire(make_att(i, i % 5))))
        assert wal.segment_count() > 2, "tiny segments must rotate"
        removed = wal.truncate_through(8)
        assert removed >= 1
        survivors = [s for s, _ in wal.replay(after_seq=8)]
        assert survivors == [9, 10], "records past the floor must survive"
        # Only whole segments at or below the floor were dropped.
        assert all(s <= 8 or s in (9, 10) for s, _ in wal.replay())

    def test_watermark_excludes_unapplied_records(self, tmp_path):
        wal = AttestationWAL(tmp_path)
        s1 = wal.append(b"a", flush=False)
        s2 = wal.append(b"b", flush=False)
        assert wal.applied_watermark() == s1 - 1, "both still pending"
        wal.mark_applied(s1)
        assert wal.applied_watermark() == s2 - 1, "s2 still pending"
        wal.mark_applied(s2)
        assert wal.applied_watermark() == s2

    def test_applied_watermark_tracks_pending(self, tmp_path):
        wal = AttestationWAL(tmp_path)
        s1 = wal.append(b"a")
        assert wal.applied_watermark() == s1 - 1
        wal.mark_applied(s1)
        assert wal.applied_watermark() == s1

    def test_wal_error_rejects_instead_of_accepting(self, tmp_path):
        m = make_manager()
        m.wal = AttestationWAL(tmp_path)
        chaos.configure(
            {
                "seed": 1,
                "faults": [{"point": "ingest.pre_apply", "kind": "io-error"}],
            }
        )
        results = m.add_attestations_bulk([make_att(1, 0)])
        assert not results[0].accepted
        assert results[0].reason == "wal-error"
        chaos.reset()
        assert m.add_attestations_bulk([make_att(1, 0)])[0].accepted


class TestCheckpointIntegrity:
    def _save(self, store, number, wal_seq=None, scores=True):
        g = small_graph(seed=number)
        store.save(
            Epoch(number),
            g,
            np.ones(g.n) / g.n if scores else None,
            None,
            peer_hashes=list(range(100, 100 + g.n)) if scores else None,
            wal_seq=wal_seq,
        )
        return g

    def test_manifest_carries_digests_and_wal_seq(self, tmp_path):
        store = CheckpointStore(tmp_path)
        self._save(store, 1, wal_seq=17)
        manifest = json.loads((tmp_path / "manifest.json").read_text())
        entry = manifest["epochs"]["1"]
        assert entry["wal_seq"] == 17
        assert set(entry["columns"]) >= {"n", "src", "dst", "weight", "scores"}
        snap = store.load(Epoch(1))
        assert snap.wal_seq == 17

    def test_bit_flip_detected_and_falls_back(self, tmp_path):
        store = CheckpointStore(tmp_path)
        self._save(store, 1)
        self._save(store, 2)
        path = tmp_path / "epoch_2.npz"
        data = bytearray(path.read_bytes())
        data[len(data) // 2] ^= 0x01
        path.write_bytes(bytes(data))
        with pytest.raises(SnapshotCorrupt):
            store.load(Epoch(2))
        fallbacks0 = obs_metrics.CHECKPOINT_FALLBACKS.value()
        snap = store.load_latest()
        assert snap is not None and snap.epoch == Epoch(1)
        assert obs_metrics.CHECKPOINT_FALLBACKS.value() - fallbacks0 == 1

    def test_truncation_at_every_region_never_raises(self, tmp_path):
        store = CheckpointStore(tmp_path)
        self._save(store, 1)
        self._save(store, 2)
        path = tmp_path / "epoch_2.npz"
        pristine = path.read_bytes()
        step = max(1, len(pristine) // 23)
        for cut in range(0, len(pristine), step):
            path.write_bytes(pristine[:cut])
            snap = store.load_latest()  # must fall back, never raise
            assert snap is not None and snap.epoch == Epoch(1), cut
        path.write_bytes(pristine)
        assert store.load_latest().epoch == Epoch(2)

    def test_byte_flips_at_every_region_detected(self, tmp_path):
        store = CheckpointStore(tmp_path)
        self._save(store, 1)
        self._save(store, 2)
        path = tmp_path / "epoch_2.npz"
        pristine = path.read_bytes()
        step = max(1, len(pristine) // 23)
        pristine_snap = store.load(Epoch(2))
        for off in range(0, len(pristine), step):
            data = bytearray(pristine)
            data[off] ^= 0xA5
            path.write_bytes(bytes(data))
            snap = store.load_latest()
            assert snap is not None, off
            if snap.epoch == Epoch(2):
                # A flip in non-semantic zip metadata (timestamps,
                # member names' extra fields) can leave the DATA
                # intact — legal, as long as what loads is exactly
                # the pristine content, never silent corruption.
                assert np.array_equal(snap.graph.src, pristine_snap.graph.src), off
                assert np.array_equal(snap.graph.weight, pristine_snap.graph.weight), off
                assert np.array_equal(snap.scores, pristine_snap.scores), off
            else:
                assert snap.epoch == Epoch(1), off
        path.write_bytes(pristine)

    def test_corrupt_manifest_degrades_to_directory_scan(self, tmp_path):
        store = CheckpointStore(tmp_path)
        self._save(store, 1)
        self._save(store, 3)
        (tmp_path / "manifest.json").write_text("{not json")
        snap = store.load_latest()
        assert snap is not None and snap.epoch == Epoch(3)

    def test_all_snapshots_corrupt_is_a_cold_start(self, tmp_path):
        store = CheckpointStore(tmp_path)
        self._save(store, 1)
        self._save(store, 2)
        for p in tmp_path.glob("epoch_*.npz"):
            p.write_bytes(b"garbage")
        assert store.load_latest() is None

    def test_corrupt_proof_degrades_to_none(self, tmp_path):
        store = CheckpointStore(tmp_path)
        g = small_graph()
        store.save(Epoch(1), g, None, '{"fake": "proof"}')
        (tmp_path / "epoch_1.proof.json").write_text('{"tampered": 1}')
        snap = store.load(Epoch(1))
        assert snap.proof_json is None  # digest mismatch, journaled

    def test_garbage_plan_sidecar_degrades_to_rebuild(self, tmp_path):
        store = CheckpointStore(tmp_path)
        self._save(store, 1)
        (tmp_path / "epoch_1.plan.npz").write_bytes(b"\x00" * 40)
        snap = store.load(Epoch(1))
        assert snap.plan is None

    def test_legacy_manifest_without_digests_still_loads(self, tmp_path):
        store = CheckpointStore(tmp_path)
        self._save(store, 4)
        (tmp_path / "manifest.json").write_text('{"latest_epoch": 4}')
        snap = store.load_latest()
        assert snap is not None and snap.epoch == Epoch(4)
        assert snap.wal_seq is None

    def test_block_cursor_roundtrip_and_survives_save(self, tmp_path):
        store = CheckpointStore(tmp_path)
        assert store.block_cursor() is None
        store.save_block_cursor(42)
        assert store.block_cursor() == 42
        self._save(store, 1)
        assert store.block_cursor() == 42, "save must not clobber the cursor"
        assert json.loads((tmp_path / "manifest.json").read_text())[
            "latest_epoch"
        ] == 1

    def test_retained_wal_floor_is_minimum_over_kept_epochs(self, tmp_path):
        store = CheckpointStore(tmp_path, keep=2)
        self._save(store, 1, wal_seq=10)
        self._save(store, 2, wal_seq=20)
        assert store.retained_wal_floor() == 10
        self._save(store, 3, wal_seq=30)  # epoch 1 pruned
        assert store.retained_wal_floor() == 20

    def test_prune_drops_manifest_entries_with_snapshots(self, tmp_path):
        store = CheckpointStore(tmp_path, keep=2)
        for k in (1, 2, 3):
            self._save(store, k, wal_seq=k * 10)
        manifest = json.loads((tmp_path / "manifest.json").read_text())
        assert sorted(manifest["epochs"]) == ["2", "3"]


class TestRecovery:
    def _converged_manager(self, tmp_path, n_atts=3):
        m = make_manager()
        store = CheckpointStore(tmp_path / "ckpt")
        m.wal = AttestationWAL(tmp_path / "ckpt" / "wal")
        for i in range(n_atts):
            assert m.apply_verified(make_att(i, i)).accepted
        result = m.converge_epoch(Epoch(0), alpha=0.1)
        store.save(
            Epoch(0),
            m.last_graph,
            result.scores,
            None,
            peer_hashes=m.last_peer_hashes,
            wal_seq=m.checkpoint_watermark(),
            attestations=m.snapshot_attestations(),
        )
        floor = store.retained_wal_floor()
        if floor is not None:
            m.wal.truncate_through(floor)
        return m, store

    def test_full_recovery_cycle(self, tmp_path):
        m1, store = self._converged_manager(tmp_path)
        late = make_att(99, 4)  # accepted after the checkpoint
        assert m1.apply_verified(late).accepted
        # kill -9: nothing but the disk survives.
        m2 = make_manager()
        report = recover(m2, store, AttestationWAL(tmp_path / "ckpt" / "wal"))
        assert report["checkpoint_epoch"] == 0
        assert report["wal_replayed"] >= 1
        assert cache_digests(m2) == cache_digests(m1)
        assert m2.last_scores is not None, "warm state restored"
        assert m2.wal is not None, "wal attached for new appends"
        assert obs_metrics.RECOVERY_SECONDS.value() > 0

    def test_recovery_survives_corrupt_latest_snapshot(self, tmp_path):
        m1 = make_manager()
        store = CheckpointStore(tmp_path / "ckpt")
        m1.wal = AttestationWAL(tmp_path / "ckpt" / "wal")
        for epoch in range(2):
            assert m1.apply_verified(make_att(epoch, epoch)).accepted
            result = m1.converge_epoch(Epoch(epoch), alpha=0.1)
            store.save(
                Epoch(epoch),
                m1.last_graph,
                result.scores,
                None,
                peer_hashes=m1.last_peer_hashes,
                wal_seq=m1.checkpoint_watermark(),
                attestations=m1.snapshot_attestations(),
            )
            floor = store.retained_wal_floor()
            if floor is not None:
                m1.wal.truncate_through(floor)
        # Tear the latest snapshot: fallback to epoch 0 + WAL replay
        # must still reconstruct the exact cache.
        path = tmp_path / "ckpt" / "epoch_1.npz"
        path.write_bytes(path.read_bytes()[: 40])
        m2 = make_manager()
        report = recover(m2, store, AttestationWAL(tmp_path / "ckpt" / "wal"))
        assert report["checkpoint_epoch"] == 0
        assert report["checkpoint_fallbacks"] == 1
        assert cache_digests(m2) == cache_digests(m1), (
            "fallback + WAL replay lost accepted attestations"
        )

    def test_recovery_is_idempotent_across_double_restart(self, tmp_path):
        m1, store = self._converged_manager(tmp_path)
        m1.apply_verified(make_att(50, 3))
        m2 = make_manager()
        recover(m2, store, AttestationWAL(tmp_path / "ckpt" / "wal"))
        m3 = make_manager()
        report3 = recover(m3, store, AttestationWAL(tmp_path / "ckpt" / "wal"))
        assert cache_digests(m3) == cache_digests(m2) == cache_digests(m1)
        # The second restart replays the same tail (nothing newly
        # checkpointed in between) — and lands in the same state.
        assert report3["wal_replayed"] >= 1

    def test_recovered_fixed_point_matches_uncrashed_control(self, tmp_path):
        m1, store = self._converged_manager(tmp_path)
        m1.apply_verified(make_att(123, 4))
        control = m1.converge_epoch(Epoch(1), alpha=0.1)
        # Crash instead of converging epoch 1; recover and converge.
        m2 = make_manager()
        recover(m2, store, AttestationWAL(tmp_path / "ckpt" / "wal"))
        recovered = m2.converge_epoch(Epoch(1), alpha=0.1)
        l1 = float(np.abs(recovered.scores - control.scores).sum())
        assert l1 <= 1e-4, f"recovered fixed point drifted: L1 {l1}"

    def test_healthz_walks_recovering_to_ok(self, tmp_path):
        from protocol_tpu.node.config import ProtocolConfig
        from protocol_tpu.node.server import Node, node_health

        cfg = ProtocolConfig()
        cfg.checkpoint_dir = str(tmp_path / "ckpt")
        node = Node.from_config(cfg)
        node._recovery = {"state": "recovering"}
        status, body = node_health(node)
        assert status == 200
        assert "recovering" in body["degraded"]
        assert body["components"]["recovery"]["state"] == "recovering"
        node._recovery = {"state": "ok", "wal_replayed": 5, "seconds": 0.1}
        status, body = node_health(node)
        assert "recovering" not in body["degraded"]
        assert body["components"]["recovery"]["wal_replayed"] == 5


class _FlakyRpc:
    """Stub RPC backend: a fixed head, no logs — the chaos schedule
    injects the failures."""

    def __init__(self, head: int = 9):
        self.head = head
        self.calls: list[tuple] = []

    def block_number(self) -> int:
        return self.head

    def get_logs(self, address, from_block, to_block, topic0):
        self.calls.append((from_block, to_block))
        return []


class TestRpcRetryWall:
    def _drive(self, source, cursor, advances, seconds=1.5):
        async def run():
            agen = source.stream(
                poll_interval=0.01, cursor=cursor, on_advance=advances.append
            )
            try:
                await asyncio.wait_for(agen.__anext__(), timeout=seconds)
            except (StopAsyncIteration, asyncio.TimeoutError):
                pass
            finally:
                await agen.aclose()

        asyncio.run(run())

    def test_get_logs_failures_retry_and_recover(self):
        chaos.configure(
            {
                "seed": 1,
                "faults": [{"point": "rpc.get_logs", "kind": "rpc-error", "times": 2}],
            }
        )
        rpc = _FlakyRpc()
        source = ChainEventSource(
            rpc, "0x" + "11" * 20, retry=RetryPolicy(base_s=0.01, cap_s=0.05)
        )
        retries0 = obs_metrics.RPC_RETRIES.value(op="get_logs")
        advances: list[int] = []
        self._drive(source, None, advances)
        assert obs_metrics.RPC_RETRIES.value(op="get_logs") - retries0 == 2
        assert advances and advances[0] == rpc.head + 1
        assert rpc.calls[0] == (0, rpc.head), "replay still starts at block 0"

    def test_cursor_resumes_where_replay_left_off(self):
        rpc = _FlakyRpc()
        source = ChainEventSource(
            rpc, "0x" + "11" * 20, retry=RetryPolicy(base_s=0.01, cap_s=0.05)
        )
        advances: list[int] = []
        self._drive(source, 5, advances)
        assert rpc.calls[0] == (5, rpc.head), "cursor must skip replayed blocks"

    def test_hung_call_times_out_as_retry(self):
        class _HungRpc(_FlakyRpc):
            def __init__(self):
                super().__init__()
                self.slow = True

            def block_number(self) -> int:
                if self.slow:
                    self.slow = False
                    time.sleep(0.3)
                return self.head

        rpc = _HungRpc()
        source = ChainEventSource(
            rpc,
            "0x" + "11" * 20,
            retry=RetryPolicy(base_s=0.01, cap_s=0.05, timeout_s=0.05),
        )
        retries0 = obs_metrics.RPC_RETRIES.value(op="block_number")
        advances: list[int] = []
        self._drive(source, None, advances)
        assert obs_metrics.RPC_RETRIES.value(op="block_number") - retries0 >= 1
        assert advances, "the stream must recover after the timeout"


class TestPlaneDurability:
    def test_accepted_verdict_means_record_on_disk(self, tmp_path):
        from protocol_tpu.ingest import IngestPlane, IngestPlaneConfig

        manager = make_manager()
        manager.wal = AttestationWAL(tmp_path)
        plane = IngestPlane(manager, IngestPlaneConfig(workers=0))
        with plane:
            future = plane.submit(make_att(3, 1))
            result = future.result(timeout=30)
            assert result.accepted
            # The durability contract: the verdict implies the record
            # is already on disk (fresh WAL handle = what a restart
            # would see).
            records = list(AttestationWAL(tmp_path).replay())
            assert len(records) == 1
            n, wire_bytes = decode_payload(records[0][1])
            assert AttestationData.from_bytes(wire_bytes, n).to_attestation(
                n
            ).scores == make_att(3, 1).scores
