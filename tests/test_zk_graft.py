"""zk.graft parity suite (the jit MSM/NTT proving backend).

The acceptance oracle for the graft backend is bit-for-bit parity:
every kernel result must equal the native/python engines exactly —
same canonical field bytes, same curve points, same proof bytes — so
the ``zk_backend`` knob is pure execution selection.  This file pins
that oracle across the edge cases (zero scalars, identity points,
n=1, non-power-of-two batches padded up, max-field-element scalars,
NTT round-trips), the dispatch ladder's length-mismatch regression,
the attribution bridge, and the analyzer's zk coverage.

Compile discipline: the non-slow tests reuse a small set of kernel
shapes (the persistent compilation cache in conftest.py makes repeat
runs cheap); the wide sweeps and the compile-heavy analyzer legs are
``slow``.
"""

from __future__ import annotations

import functools

import numpy as np
import pytest

from protocol_tpu.crypto.field import MODULUS as R
from protocol_tpu.utils.limbs import to_limbs_fast
from protocol_tpu.zk import graft as zk_graft
from protocol_tpu.zk import kzg, plonk
from protocol_tpu.zk import native as zk_native
from protocol_tpu.zk.bn254 import G1, GENERATOR, IDENTITY
from protocol_tpu.zk.graft import use_zk_backend

RNG = np.random.default_rng(20)


def _rand_scalar(rng) -> int:
    return int.from_bytes(rng.bytes(32), "little") % R


def _rand_points(rng, n: int) -> list[G1]:
    return [GENERATOR.mul(_rand_scalar(rng) or 1) for _ in range(n)]


def _ref_msm(scalars: list[int], points: list[G1]) -> G1:
    """Exact affine reference: sum of per-term double-and-add."""
    return functools.reduce(
        G1.add, (p.mul(s % R) for s, p in zip(scalars, points)), IDENTITY
    )


class TestBackendKnob:
    def test_default_is_native(self):
        assert zk_graft.zk_backend() == "native"

    def test_context_flips_and_restores(self):
        with use_zk_backend("graft"):
            assert zk_graft.zk_backend() == "graft"
            with use_zk_backend("native"):
                assert zk_graft.zk_backend() == "native"
            assert zk_graft.zk_backend() == "graft"
        assert zk_graft.zk_backend() == "native"

    def test_unknown_backend_rejected(self):
        with pytest.raises(ValueError):
            with use_zk_backend("cuda"):
                pass


class TestFieldParity:
    @pytest.mark.parametrize("which", ["fr", "fq"])
    def test_mulmod_matches_python(self, which):
        from protocol_tpu.zk.graft import field as gf

        F = gf.FR if which == "fr" else gf.FQ
        mulmod = gf.mulmod_fr if which == "fr" else gf.mulmod_fq
        rng = np.random.default_rng(7)
        edge = [0, 1, 2, F.p - 1, F.p - 2, (1 << 255) % F.p]
        avals = edge + [int.from_bytes(rng.bytes(32), "little") % F.p
                        for _ in range(10)]
        bvals = list(reversed(avals))
        am = gf.ints_to_limbs([F.to_mont_int(a) for a in avals])
        bm = gf.ints_to_limbs([F.to_mont_int(b) for b in bvals])
        got = gf.limbs_to_ints(np.asarray(mulmod(am, bm)))
        expected = [F.to_mont_int(a * b % F.p)
                    for a, b in zip(avals, bvals)]
        assert got == expected


class TestNTTParity:
    K = 8  # 256-point domain: every stage shape compiles in seconds

    def test_fft_matches_native_and_roundtrips(self):
        d = plonk.Domain(self.K)
        rng = np.random.default_rng(11)
        vals = [int.from_bytes(rng.bytes(32), "little") % R
                for _ in range(d.n)]
        vals[0] = 0
        vals[1] = R - 1
        reference = d.fft(list(vals))  # native lib or python fallback
        with use_zk_backend("graft"):
            assert d.fft(list(vals)) == reference
            assert d.ifft(reference) == vals  # inverse(NTT(x)) == x

    def test_ntt_limbs_dispatch_parity(self):
        d = plonk.Domain(self.K)
        rng = np.random.default_rng(13)
        vals = [int.from_bytes(rng.bytes(32), "little") % R
                for _ in range(d.n)]
        native_out = d.ntt_limbs(to_limbs_fast(vals), d.omega, False)
        with use_zk_backend("graft"):
            graft_out = d.ntt_limbs(to_limbs_fast(vals), d.omega, False)
        assert np.array_equal(native_out, graft_out)

    def test_non_power_of_two_rejected(self):
        arr = to_limbs_fast([1, 2, 3])
        with pytest.raises(ValueError):
            zk_graft.ntt_limbs(arr, plonk.Domain(2).omega, False)


class TestMSMParity:
    def test_edge_case_batch_pads_to_pow2(self):
        """n=33 (padded to 64) with a zero scalar, a max-field-element
        scalar, an identity point, and a duplicated point — all through
        the public kzg dispatch."""
        rng = np.random.default_rng(17)
        n = 33
        scalars = [_rand_scalar(rng) for _ in range(n)]
        points = _rand_points(rng, n)
        scalars[0] = 0
        scalars[1] = R - 1
        points[2] = IDENTITY
        points[4] = points[3]
        reference = _ref_msm(scalars, points)
        with use_zk_backend("graft"):
            assert kzg.msm(scalars, points) == reference
        assert kzg.msm(scalars, points) == reference  # native/python leg

    def test_single_term_and_zero_scalars(self):
        rng = np.random.default_rng(19)
        p = _rand_points(rng, 1)[0]
        s = _rand_scalar(rng)
        with use_zk_backend("graft"):
            assert kzg.msm([s], [p]) == p.mul(s)
            assert kzg.msm([0], [p]) == IDENTITY
            assert kzg.msm([], []) == IDENTITY

    def test_duplicate_points_hit_add_collision(self):
        """Equal points in one bucket exercise the P==Q doubling patch
        inside the complete Jacobian add."""
        rng = np.random.default_rng(23)
        p = _rand_points(rng, 1)[0]
        with use_zk_backend("graft"):
            assert kzg.msm([3, 3], [p, p]) == p.mul(6)


class TestLengthMismatch:
    """Regression: ``msm`` used to silently truncate
    ``points[: len(scalars)]`` — now every layer raises."""

    def test_kzg_msm_raises(self):
        pts = _rand_points(np.random.default_rng(3), 3)
        with pytest.raises(ValueError, match="length mismatch"):
            kzg.msm([1, 2], pts)

    def test_graft_msm_raises(self):
        pts = _rand_points(np.random.default_rng(4), 2)
        with pytest.raises(ValueError, match="length mismatch"):
            zk_graft.msm([1, 2, 3], pts)

    def test_native_msm_raises(self):
        pts = _rand_points(np.random.default_rng(5), 2)
        with pytest.raises(ValueError, match="length mismatch"):
            zk_native.msm([1, 2, 3], pts)

    def test_native_msm_limbs_raises(self):
        scalars = np.zeros((2, 4), dtype=np.uint64)
        point_limbs = np.zeros((3, 8), dtype=np.uint64)
        with pytest.raises(ValueError, match="length mismatch"):
            zk_native.msm_limbs(scalars, point_limbs)


class TestCommitBatch:
    def test_batch_matches_serial_commits(self):
        srs = kzg.Setup.generate(4, seed=b"graft-test-srs")
        rng = np.random.default_rng(29)
        polys = [
            np.asarray(
                to_limbs_fast(
                    [int.from_bytes(rng.bytes(32), "little") % R
                     for _ in range(ln)]
                )
            )
            for ln in (4, 7, 16)
        ]
        serial = [srs.commit_limbs(p) for p in polys]
        assert srs.commit_batch(polys) == serial


class TestAttribution:
    def test_graft_phase_table_counts_ntt(self):
        zk_graft.reset_phase_stats()
        d = plonk.Domain(6)
        with use_zk_backend("graft"):
            d.fft([1] * d.n)
        stats = zk_graft.phase_stats()
        assert stats["ntt"]["calls"] >= 1
        assert stats["ntt"]["seconds"] > 0

    def test_attribution_bridges_graft_engine_rows(self):
        """The dual-engine _ProveAttribution attaches graft phase rows
        as engine-tagged children of the enclosing span — the same
        ``snark -> {msm, ntt}`` shape the native timers feed."""
        from protocol_tpu.obs import TRACER

        zk_graft.reset_phase_stats()
        with TRACER.span("snark") as sp:
            att = plonk._ProveAttribution()
            d = plonk.Domain(6)
            with att.stage("quotient"), use_zk_backend("graft"):
                d.fft([2] * d.n)
            att.attach()
        children = {
            (c.name, c.attrs.get("engine")) for c in sp.children
        }
        assert ("ntt", "graft") in children, children
        assert ("quotient", "host") in children, children


class TestAnalyzerCoverage:
    def test_zk_registry_and_budget_tables_agree(self):
        from protocol_tpu.analysis import (
            COMM_INVARIANTS,
            KERNEL_INVARIANTS,
            MEM_INVARIANTS,
        )
        from protocol_tpu.analysis.zk_lowering import (
            ensure_budgets,
            zk_kernel_names,
        )

        names = set(ensure_budgets())
        assert names == set(zk_graft.registered_zk_kernels())
        assert names == set(zk_kernel_names())
        assert names <= set(KERNEL_INVARIANTS)
        assert names <= set(COMM_INVARIANTS)
        assert names <= set(MEM_INVARIANTS)

    def test_zk_jaxpr_pass_clean(self):
        """Pass 1 over the zk kernels (trace-only — no compile):
        every kernel checked, zero findings."""
        from protocol_tpu.analysis.invariants import run_jaxpr_pass
        from protocol_tpu.analysis.zk_lowering import register

        names = register()
        findings, meta = run_jaxpr_pass(backends=names)
        assert [f.render() for f in findings] == []
        for name in names:
            assert meta[name]["status"] == "checked", meta[name]


@pytest.mark.slow
class TestSlowParitySweep:
    def test_msm_sweep_matches_reference(self):
        rng = np.random.default_rng(31)
        for n in (2, 3, 7, 16, 100):
            scalars = [_rand_scalar(rng) for _ in range(n)]
            points = _rand_points(rng, n)
            if n >= 3:
                scalars[0] = 0
                scalars[1] = R - 1
                points[2] = IDENTITY
            reference = _ref_msm(scalars, points)
            with use_zk_backend("graft"):
                assert zk_graft.msm(scalars, points) == reference, n

    def test_zk_compile_passes_clean(self):
        """Passes 8/12/13 over the zk kernels (the ``graftlint --zk``
        leg): real compiles at two scales, buffer-assignment memory
        checks, and the double-compile drift wall — zero findings."""
        from protocol_tpu.analysis.comm.checker import run_comm_pass
        from protocol_tpu.analysis.determinism.checker import (
            run_determinism_pass,
        )
        from protocol_tpu.analysis.memory.checker import run_memory_pass
        from protocol_tpu.analysis.zk_lowering import register

        names = register()
        for runner in (run_comm_pass, run_memory_pass, run_determinism_pass):
            findings, section = runner(backends=names)
            assert [f.render() for f in findings] == [], runner.__name__
            for name in names:
                status = section["backends"][name]["status"]
                assert status == "checked", (runner.__name__, name, status)


@pytest.mark.slow
class TestProveByteParity:
    """A full PLONK prove under ``zk_backend='graft'`` must verify and
    match the native proof byte-for-byte (statement-seeded blinding
    keeps both paths on the same transcript)."""

    @staticmethod
    def _manager(n: int, zk_backend: str):
        from protocol_tpu.node.bootstrap import FIXED_SET
        from protocol_tpu.node.manager import Manager, ManagerConfig

        mgr = Manager(
            ManagerConfig(
                prover="plonk",
                num_neighbours=n,
                num_iter=1,
                fixed_set=list(FIXED_SET[:n]),
                zk_backend=zk_backend,
            )
        )
        mgr.generate_initial_attestations()
        return mgr

    def _prove_pair(self, n: int):
        from protocol_tpu.node.epoch import Epoch
        from protocol_tpu.prover import prove_job

        native_job = self._manager(n, "native").build_proof_job(Epoch(1))
        graft_job = self._manager(n, "graft").build_proof_job(Epoch(1))
        assert native_job.zk_backend == "native"
        assert graft_job.zk_backend == "graft"
        # The knob is execution selection only: identical statements.
        from protocol_tpu.prover import job_seed

        assert job_seed(native_job) == job_seed(graft_job)
        return prove_job(native_job), prove_job(graft_job)

    def test_full_k14_statement_proof_bytes_identical(self):
        """The acceptance statement: the full 5-peer (k=14 circuit)
        epoch prove, both backends, byte-compared."""
        native, graft = self._prove_pair(5)
        assert native.pub_ins == graft.pub_ins
        assert native.proof == graft.proof

    def test_small_statement_proof_bytes_identical(self):
        native, graft = self._prove_pair(2)
        assert native.pub_ins == graft.pub_ins
        assert native.proof == graft.proof
        # Attribution survives the backend switch: the graft prove's
        # snark span carries graft-engine msm/ntt children.
        snark = next(
            c for c in graft.spans["children"] if c["name"] == "snark"
        )
        rows = {
            (c["name"], c.get("attrs", {}).get("engine"))
            for c in snark["children"]
        }
        assert ("msm", "graft") in rows, rows
        assert ("ntt", "graft") in rows, rows
