"""Incremental epoch engine (ISSUE 5): warm-start convergence, delta
WindowPlan updates, and the double-buffered host/device epoch pipeline.

Covers the acceptance properties:

- warm-start and cold-start reach the same fixed point within tolerance
  under random churn, including peer join/leave, on every backend rung;
- ``WindowPlan.apply_delta`` produces a plan identical in layout
  semantics to a from-scratch rebuild of the same graph (same edge
  multiset, same invariants, same device Cᵀt) while keeping the device
  array shapes (no recompile) and chaining fingerprint lineage;
- the manager's dirty-row tracking, plan-cache handoff (delta outcome
  metric), and checkpointed warm-start state survive a restart;
- the pipeline overlaps host/device stages behind a bounded queue and
  coalesces — never drops — ticks under backpressure.
"""

import time

import numpy as np
import pytest

from protocol_tpu.models.graphs import erdos_renyi, scale_free
from protocol_tpu.node.checkpoint import CheckpointStore
from protocol_tpu.node.epoch import Epoch
from protocol_tpu.node.manager import Manager, ManagerConfig
from protocol_tpu.node.pipeline import EpochPipeline
from protocol_tpu.obs.metrics import PLAN_OUTCOMES
from protocol_tpu.ops.gather_window import (
    ROW,
    PlanDeltaError,
    WindowPlan,
    build_window_plan,
    try_plan_delta,
)
from protocol_tpu.trust.backend import get_backend
from protocol_tpu.trust.graph import TrustGraph


def l1(a, b) -> float:
    return float(np.abs(np.asarray(a) - np.asarray(b)).sum())


def churn_graph(g: TrustGraph, fraction: float, rng, n_new: int = 0):
    """Rewire ``fraction``·E edges to random destinations/weights and
    optionally grow the peer set; returns ``(graph, changed_rows)``."""
    n = g.n + n_new
    k = max(1, int(g.nnz * fraction))
    idx = rng.choice(g.nnz, k, replace=False)
    dst = g.dst.copy()
    dst[idx] = rng.integers(0, n, k)
    while (bad := dst[idx] == g.src[idx]).any():
        dst[idx[bad]] = rng.integers(0, n, int(bad.sum()))
    w = g.weight.copy()
    w[idx] = rng.integers(1, 1000, k).astype(np.float32)
    pre = g.pre_trusted
    if n_new and pre is not None:
        pre = np.concatenate([pre, np.zeros(n_new, bool)])
    return TrustGraph(n, g.src, dst, w, pre), np.unique(g.src[idx])


def edge_multiset(src, dst, w):
    a = np.stack(
        [
            np.asarray(src, np.int64),
            np.asarray(dst, np.int64),
            np.asarray(w, np.float32).view(np.int32).astype(np.int64),
        ],
        axis=1,
    )
    return a[np.lexsort(a.T[::-1])]


class TestWarmStartFixedPoint:
    """Warm and cold starts land on the same fixed point — the property
    that makes warm starting free of correctness risk."""

    @pytest.mark.parametrize(
        "backend", ["tpu-csr", "tpu-windowed", "tpu-sharded:tpu-windowed"]
    )
    def test_same_fixed_point_under_churn(self, backend):
        rng = np.random.default_rng(41)
        g = scale_free(1800, 11000, seed=3)
        b = get_backend(backend)
        prev = b.converge(g, alpha=0.1, tol=1e-6, max_iter=80)
        g2, rows = churn_graph(g.drop_self_edges(), 0.02, rng)
        if hasattr(b, "delta_rows"):
            b.delta_rows = rows
        warm = b.converge(g2, alpha=0.1, tol=1e-6, max_iter=80, t0=prev.scores)
        cold = get_backend(backend).converge(g2, alpha=0.1, tol=1e-6, max_iter=80)
        assert l1(warm.scores, cold.scores) <= 1e-5
        assert warm.iterations < cold.iterations

    def test_same_fixed_point_with_join_and_leave(self):
        """Peers join (n grows) and leave (their edges vanish): the
        warm seed is renormalized over the survivors and still reaches
        the cold fixed point."""
        rng = np.random.default_rng(42)
        g = scale_free(1500, 9000, seed=5).drop_self_edges()
        b = get_backend("tpu-windowed")
        prev = b.converge(g, alpha=0.1, tol=1e-7, max_iter=80)
        # Leave: drop every edge touching 30 peers; join: 64 new peers
        # with edges in both directions.
        gone = rng.choice(g.n, 30, replace=False)
        keep = ~(np.isin(g.src, gone) | np.isin(g.dst, gone))
        n2 = g.n + 64
        new_src = rng.integers(g.n, n2, 200).astype(np.int32)
        new_dst = rng.integers(0, g.n, 200).astype(np.int32)
        g2 = TrustGraph(
            n2,
            np.concatenate([g.src[keep], new_src, new_dst]),
            np.concatenate([g.dst[keep], new_dst, new_src]),
            np.concatenate(
                [g.weight[keep], np.ones(400, np.float32) * 7.0]
            ),
            np.concatenate([g.pre_trusted, np.zeros(64, bool)])
            if g.pre_trusted is not None
            else None,
        ).drop_self_edges()
        # Remap the old fixed point onto the new id space (ids are
        # stable here; the survivors keep their score, joiners start 0).
        t0 = np.zeros(n2)
        t0[: g.n] = np.maximum(prev.scores, 0.0)
        t0[gone] = 0.0
        warm = b.converge(g2, alpha=0.1, tol=1e-7, max_iter=80, t0=t0 / t0.sum())
        cold = get_backend("tpu-windowed").converge(
            g2, alpha=0.1, tol=1e-7, max_iter=80
        )
        assert l1(warm.scores, cold.scores) <= 1e-5

    def test_degenerate_seed_falls_back_to_cold(self):
        g = erdos_renyi(400, avg_degree=5.0, seed=6)
        b = get_backend("tpu-csr")
        bad = np.zeros(400)  # zero mass: unusable seed
        res = b.converge(g, alpha=0.1, tol=1e-7, max_iter=50, t0=bad)
        cold = get_backend("tpu-csr").converge(g, alpha=0.1, tol=1e-7, max_iter=50)
        assert l1(res.scores, cold.scores) <= 1e-6
        short = np.ones(17)  # mis-shaped seed
        res2 = b.converge(g, alpha=0.1, tol=1e-7, max_iter=50, t0=short)
        assert l1(res2.scores, cold.scores) <= 1e-6


class TestApplyDelta:
    def _normalized(self, g):
        g = g.drop_self_edges()
        w, _ = g.row_normalized()
        return g, w

    def _churned_rows(self, g, w, rng, rows_n=25):
        """Whole-row replacement delta in the normalized domain."""
        rows = rng.choice(g.n, rows_n, replace=False)
        ns, nd, nw = [], [], []
        for r in rows:
            deg = int(rng.integers(1, 6))
            tgt = rng.choice(g.n, deg, replace=False)
            ww = rng.random(deg)
            ww /= ww.sum()
            ns += [r] * deg
            nd += list(tgt)
            nw += list(ww)
        keep = ~np.isin(g.src, rows)
        full = (
            np.concatenate([g.src[keep], np.array(ns, np.int32)]),
            np.concatenate([g.dst[keep], np.array(nd, np.int32)]),
            np.concatenate([w[keep], np.array(nw, np.float32)]),
        )
        return rows, np.array(ns, np.int32), np.array(nd, np.int32), np.array(
            nw, np.float32
        ), full

    def test_delta_matches_rebuild_layout_semantics(self):
        """The acceptance property: a delta-updated plan is identical
        in layout semantics to a from-scratch rebuild of the same graph
        — same edge multiset, same layout invariants, and the same
        device Cᵀt bit pattern."""
        import jax.numpy as jnp

        from protocol_tpu.ops.gather_window import windowed_ct

        rng = np.random.default_rng(7)
        g, w = self._normalized(scale_free(2200, 14000, seed=9))
        plan = build_window_plan(g.src, g.dst, w, n=g.n)
        rows, ns, nd, nw, full = self._churned_rows(g, w, rng)
        p2 = plan.replace_rows(rows, ns, nd, nw, fingerprint="post")
        ref = build_window_plan(*full, n=g.n)

        # Same edge multiset...
        assert (
            edge_multiset(*p2.recovered_edges()) == edge_multiset(*full)
        ).all()
        assert p2.n_edges == ref.n_edges == full[0].shape[0]
        # ...same layout invariants...
        live = p2.seg_end.astype(np.int64)[: p2.n_segments]
        assert (np.diff(live) > 0).all()
        rows_of = live // ROW
        expect_first = np.empty(len(live), bool)
        expect_first[0] = True
        expect_first[1:] = rows_of[1:] != rows_of[:-1]
        np.testing.assert_array_equal(p2.seg_first[: p2.n_segments], expect_first)
        assert sorted(p2.seg_perm.tolist()) == list(range(p2.seg_capacity))
        assert int(p2.dst_ptr[-1]) == p2.n_segments
        # ...and the same device product as the rebuilt plan.
        t = rng.random(g.n).astype(np.float32)

        def ct(pl):
            return np.asarray(
                windowed_ct(
                    *[jnp.asarray(getattr(pl, k)) for k in pl._CORE],
                    jnp.asarray(t),
                    n_rows=pl.n_rows,
                    table_entries=pl.table_entries,
                    interpret=True,
                )
            )

        np.testing.assert_allclose(ct(p2), ct(ref), atol=1e-6)

    def test_delta_keeps_device_shapes(self):
        """Steady-state churn must not change any device array shape —
        a shape change recompiles the whole convergence kernel."""
        rng = np.random.default_rng(8)
        g, w = self._normalized(scale_free(2200, 14000, seed=9))
        plan = build_window_plan(g.src, g.dst, w, n=g.n)
        cur, cw = g, w
        p = plan
        for i in range(5):
            rows, ns, nd, nw, full = self._churned_rows(cur, cw, rng, rows_n=15)
            p = p.replace_rows(rows, ns, nd, nw, fingerprint=f"fp{i}")
            cur = TrustGraph(cur.n, full[0], full[1], full[2], cur.pre_trusted)
            cw = full[2]
            for k in WindowPlan._CORE:
                assert getattr(p, k).shape == getattr(plan, k).shape, (i, k)
        assert len(p.lineage) == 5
        assert p.lineage[0] == plan.fingerprint

    def test_n_growth_and_new_windows(self):
        rng = np.random.default_rng(10)
        g, w = self._normalized(scale_free(1200, 8000, seed=11))
        plan = build_window_plan(g.src, g.dst, w, n=g.n)
        # Joiners far beyond the old table: new windows, bigger dst_ptr.
        ns = np.array([2500, 2500, 3, 2047], np.int32)
        nd = np.array([1, 2500 + 1, 2500, 5], np.int32)
        nw = np.array([0.5, 0.5, 1.0, 1.0], np.float32)
        p2 = plan.apply_delta((ns, nd, nw), None, n=2600, fingerprint="grown")
        assert p2.n == 2600
        assert p2.dst_ptr.shape == (2601,)
        assert p2.table_entries >= 2600
        full = (
            np.concatenate([g.src, ns]),
            np.concatenate([g.dst, nd]),
            np.concatenate([w, nw]),
        )
        assert (
            edge_multiset(*p2.recovered_edges()) == edge_multiset(*full)
        ).all()

    def test_delete_absent_edge_and_shrink_raise(self):
        g, w = self._normalized(scale_free(900, 5000, seed=12))
        plan = build_window_plan(g.src, g.dst, w, n=g.n)
        missing = (np.array([int(g.src[0])]), np.array([(int(g.dst[0]) + 1) % g.n]))
        deleted = ~np.isin(
            np.arange(g.nnz), np.nonzero((g.src == g.src[0]) & (g.dst == missing[1][0]))[0]
        )
        if not deleted.all():  # the "absent" pair happens to exist: pick another
            missing = (np.array([g.n - 1]), np.array([g.n - 1]))
        with pytest.raises(PlanDeltaError):
            plan.apply_delta(None, missing, fingerprint="x")
        with pytest.raises(PlanDeltaError):
            plan.apply_delta(None, None, n=g.n - 1, fingerprint="x")

    def test_overflow_falls_back_via_try_plan_delta(self):
        """A delta bigger than the spare-row headroom returns None from
        try_plan_delta — the caller rebuilds instead of corrupting."""
        g, w = self._normalized(erdos_renyi(500, avg_degree=4.0, seed=13))
        plan = build_window_plan(g.src, g.dst, w, n=g.n, spare_rows=0)
        rng = np.random.default_rng(14)
        # Blow out one window far past its padding AND the (zero) spare.
        rows = np.arange(0, 400, dtype=np.int64)
        ns = np.repeat(rows, 600).astype(np.int32)
        nd = rng.integers(0, 500, ns.shape[0]).astype(np.int32)
        nw = np.full(ns.shape[0], 1e-3, np.float32)
        keep = ~np.isin(g.src, rows)
        full_src = np.concatenate([g.src[keep], ns])
        full_dst = np.concatenate([g.dst[keep], nd])
        full_w = np.concatenate([w[keep], nw])
        out = try_plan_delta(
            plan, full_src, full_dst, full_w, n=g.n, rows=rows, fingerprint="of"
        )
        assert out is None

    def test_stale_hint_tripwire(self):
        """An incomplete churn hint (edge counts disagree) must never
        produce a plan stamped with the new fingerprint."""
        g, w = self._normalized(scale_free(900, 5000, seed=15))
        plan = build_window_plan(g.src, g.dst, w, n=g.n)
        # New graph deletes row 0's edges too, but the hint only names
        # row 1 — the delta cannot represent the target graph.
        keep = ~np.isin(g.src, [0, 1])
        out = try_plan_delta(
            plan,
            g.src[keep],
            g.dst[keep],
            w[keep],
            n=g.n,
            rows=np.array([1]),
            fingerprint="stale",
        )
        assert out is None

    def test_plan_v3_roundtrips_with_lineage(self, tmp_path):
        g, w = self._normalized(scale_free(900, 5000, seed=16))
        plan = build_window_plan(g.src, g.dst, w, n=g.n)
        p2 = plan.apply_delta(
            (np.array([3], np.int32), np.array([7], np.int32), np.array([0.5], np.float32)),
            None,
            fingerprint="child",
        )
        store = CheckpointStore(tmp_path)
        store.save(Epoch(2), erdos_renyi(20, seed=1), plan=p2)
        snap = store.load_latest()
        assert snap.plan is not None
        assert snap.plan.lineage == (plan.fingerprint,)
        assert snap.plan.n_edges == p2.n_edges
        assert snap.plan.n_data_rows == p2.n_data_rows
        for k in WindowPlan._CORE + WindowPlan._HOST:
            np.testing.assert_array_equal(getattr(snap.plan, k), getattr(p2, k))


class TestManagerWarmState:
    def _manager(self, **kw):
        m = Manager(
            ManagerConfig(backend="tpu-windowed", prover="commitment", **kw)
        )
        m.generate_initial_attestations()
        return m

    def test_second_epoch_warm_starts_and_persists(self, tmp_path):
        m = self._manager()
        r1 = m.converge_epoch(Epoch(1), alpha=0.1)
        assert m.last_scores is not None and m.last_peer_hashes is not None
        assert len(m.last_peer_hashes) == len(r1.scores)
        prep = m.prepare_epoch(Epoch(2))
        assert prep.t0 is not None
        assert prep.t0.sum() == pytest.approx(1.0, rel=1e-6)
        # Remapped onto the same peer set: the warm seed IS the scores.
        np.testing.assert_allclose(prep.t0, r1.scores, atol=1e-9)
        r2 = m.converge_prepared(prep, alpha=0.1)
        np.testing.assert_allclose(r2.scores, r1.scores, rtol=1e-5)

    def test_warm_start_disabled_by_config(self):
        m = self._manager(warm_start=False)
        m.converge_epoch(Epoch(1), alpha=0.1)
        assert m.prepare_epoch(Epoch(2)).t0 is None

    def test_warm_t0_remaps_joins_and_leaves(self):
        m = self._manager()
        m.last_peer_hashes = [10, 20, 30]
        m.last_scores = np.array([0.5, 0.3, 0.2])
        # Peer 20 departed, peer 40 joined.
        t0 = m._warm_t0([10, 40, 30])
        np.testing.assert_allclose(t0, [0.5 / 0.7, 0.0, 0.2 / 0.7])
        # No overlap at all -> cold.
        assert m._warm_t0([7, 8]) is None

    def test_dirty_rows_feed_plan_delta(self):
        from tests.test_node import make_attestation

        m = self._manager(plan_delta_max_churn=1.0)
        m.converge_epoch(Epoch(1), alpha=0.1)
        assert not m._dirty_hashes  # consumed by the successful epoch
        plan1 = m.window_plan
        # Sender 0 re-attests with a different split: its row is dirty.
        att = make_attestation(sender_idx=0, scores=[400, 300, 150, 150, 0])
        m.add_attestation(att)
        assert m._dirty_hashes
        prep = m.prepare_epoch(Epoch(2))
        assert prep.delta_rows is not None and prep.delta_rows.size == 1
        before = PLAN_OUTCOMES.value(outcome="delta")
        m.converge_prepared(prep, alpha=0.1)
        assert PLAN_OUTCOMES.value(outcome="delta") == before + 1
        assert m.window_plan is not plan1
        assert plan1.fingerprint in m.window_plan.lineage
        assert not m._dirty_hashes

    def test_churn_threshold_disables_delta(self):
        from tests.test_node import make_attestation

        m = self._manager(plan_delta_max_churn=0.0)
        m.converge_epoch(Epoch(1), alpha=0.1)
        m.add_attestation(
            make_attestation(sender_idx=0, scores=[400, 300, 150, 150, 0])
        )
        assert m.prepare_epoch(Epoch(2)).delta_rows is None

    def test_checkpoint_restores_warm_state(self, tmp_path):
        m = self._manager()
        r1 = m.converge_epoch(Epoch(1), alpha=0.1)
        store = CheckpointStore(tmp_path)
        store.save(
            Epoch(1),
            m.last_graph,
            r1.scores,
            plan=m.window_plan,
            peer_hashes=m.last_peer_hashes,
        )
        snap = store.load_latest()
        assert snap.peer_hashes == m.last_peer_hashes
        # A fresh manager (reboot) seeded from the snapshot warm starts.
        m2 = self._manager()
        m2.last_scores = snap.scores
        m2.last_peer_hashes = snap.peer_hashes
        m2.window_plan = snap.plan
        prep = m2.prepare_epoch(Epoch(2))
        assert prep.t0 is not None
        np.testing.assert_allclose(prep.t0, r1.scores, atol=1e-9)


class TestEpochPipeline:
    def _manager(self):
        m = Manager(ManagerConfig(backend="tpu-sparse", prover="commitment"))
        m.generate_initial_attestations()
        return m

    def test_sequential_epochs_warm_start(self):
        m = self._manager()
        with EpochPipeline(m, alpha=0.1) as pipe:
            pipe.submit(Epoch(1))
            assert pipe.drain(60)
            pipe.submit(Epoch(2))
            assert pipe.drain(60)
        o1, o2 = pipe.outcomes[1], pipe.outcomes[2]
        assert o1.error is None and o2.error is None
        assert o2.result.iterations <= o1.result.iterations
        assert pipe.coalesced == 0 and pipe.completed == 2

    def test_backpressure_coalesces_instead_of_dropping(self):
        from protocol_tpu.obs import metrics as obs_metrics

        m = self._manager()

        def slow_stage(prepared):
            time.sleep(0.5)
            return m.converge_prepared(prepared, alpha=0.1)

        before = obs_metrics.EPOCH_TICKS_COALESCED.value()
        with EpochPipeline(m, device_stage=slow_stage, queue_depth=1) as pipe:
            for k in range(1, 6):
                pipe.submit(Epoch(k))
                time.sleep(0.05)
            assert pipe.drain(60)
        assert pipe.coalesced >= 1
        # Every tick is accounted for: it either ran or was coalesced.
        assert pipe.completed + pipe.coalesced == 5
        # The NEWEST epoch always lands (coalescing supersedes, never
        # drops the head of the line).
        assert 5 in pipe.outcomes
        assert (
            obs_metrics.EPOCH_TICKS_COALESCED.value() - before == pipe.coalesced
        )

    def test_device_failure_does_not_kill_the_pipeline(self):
        m = self._manager()
        calls = []

        def flaky_stage(prepared):
            calls.append(prepared.epoch.number)
            if prepared.epoch.number == 1:
                raise RuntimeError("prover exploded")
            return m.converge_prepared(prepared, alpha=0.1)

        with EpochPipeline(m, device_stage=flaky_stage) as pipe:
            pipe.submit(Epoch(1))
            assert pipe.drain(60)
            pipe.submit(Epoch(2))
            assert pipe.drain(60)
        assert isinstance(pipe.outcomes[1].error, RuntimeError)
        assert pipe.outcomes[2].error is None
        assert calls == [1, 2]

    def test_failed_epoch_keeps_dirty_accounting(self):
        """A failed device stage must not clear the dirty set — the
        next epoch's delta hint still covers the missed churn."""
        from tests.test_node import make_attestation

        m = Manager(
            ManagerConfig(
                backend="tpu-windowed", prover="commitment", plan_delta_max_churn=1.0
            )
        )
        m.generate_initial_attestations()
        m.converge_epoch(Epoch(1), alpha=0.1)
        m.add_attestation(
            make_attestation(sender_idx=1, scores=[0, 500, 300, 100, 100])
        )
        dirty = set(m._dirty_hashes)
        assert dirty
        prep = m.prepare_epoch(Epoch(2))
        # Simulate the device stage dying before converge finished.
        assert m._dirty_hashes == dirty
        # The retry (next epoch) still sees the churn.
        prep3 = m.prepare_epoch(Epoch(3))
        assert prep3.delta_rows is not None
        m.converge_prepared(prep3, alpha=0.1)
        assert not m._dirty_hashes


class TestPipelinedNode:
    def test_node_ticks_through_pipeline(self):
        """Full node wiring: `"epoch_pipeline": true` routes epoch
        boundaries through the double-buffered engine; the second tick
        warm starts and the shutdown drains in-flight work."""
        import asyncio

        from protocol_tpu.node.config import ProtocolConfig
        from protocol_tpu.node.server import Node
        from protocol_tpu.obs import TRACER

        async def scenario():
            cfg = ProtocolConfig(
                epoch_interval=1,
                endpoint=((127, 0, 0, 1), 0),
                prover="commitment",
                trust_backend="tpu-sparse",
                epoch_pipeline=True,
            )
            node = Node.from_config(cfg)
            await node.start()
            assert node._pipeline is not None
            deadline = 60.0
            while node._pipeline.completed < 2 and deadline > 0:
                await asyncio.sleep(0.2)
                deadline -= 0.2
            await node.stop()
            return node

        node = asyncio.run(scenario())
        assert node._pipeline.completed >= 2
        assert node.manager.last_scores is not None  # warm state advanced
        assert TRACER.latest_epoch() is not None

    def test_config_parses_pipeline_fields(self):
        from protocol_tpu.node.config import ProtocolConfig

        cfg = ProtocolConfig.from_json(
            '{"epoch_pipeline": true, "warm_start": false, '
            '"plan_delta_max_churn": 0.2}'
        )
        assert cfg.epoch_pipeline is True
        assert cfg.warm_start is False
        assert cfg.plan_delta_max_churn == 0.2
        base = ProtocolConfig.from_json("{}")
        assert base.epoch_pipeline is False and base.warm_start is True


class TestBenchEpochs:
    @pytest.mark.slow
    def test_epochs_entry_smoke(self):
        import bench

        entry = bench.epochs_entry(
            epochs=3, churn=0.02, n_peers=4000, n_edges=24000, max_iter=40
        )
        assert entry["steady_state_epoch_seconds"] > 0
        assert entry["cold_epoch_seconds"] > 0
        assert entry["iterations_saved_by_warm_start"] > 0
        assert entry["warm_vs_cold_l1"] < 1e-4
        assert entry["plan_outcomes"]["delta"] >= 1
        assert len(entry["per_epoch"]) == 2


class TestRecompileTracker:
    """ISSUE 6: PR 5's stable-shape guarantee, *watched* instead of
    asserted — steady-state delta epochs must report zero jit cache
    misses; a shape-changing epoch must report exactly one."""

    def test_steady_state_delta_epochs_report_zero_recompiles(self):
        from protocol_tpu.obs import metrics as obs_metrics
        from protocol_tpu.obs.watchers import RECOMPILES

        rng = np.random.default_rng(7)
        g = scale_free(1500, 9000, seed=11).drop_self_edges()
        b = get_backend("tpu-windowed")
        prev = b.converge(g, alpha=0.1, tol=1e-6, max_iter=60)  # cold compile
        scores = prev.scores
        cur = g
        counter_before = obs_metrics.JIT_RECOMPILES.value(fn="converge_windowed")
        for k in range(3):
            cur, rows = churn_graph(cur, 0.01, rng)
            b.delta_rows = rows
            snap = RECOMPILES.snapshot()
            res = b.converge(cur, alpha=0.1, tol=1e-6, max_iter=60, t0=scores)
            misses = RECOMPILES.observe(snap, steady_state=True, epoch=k)
            assert misses == {}, (
                f"steady-state delta epoch {k} recompiled: {misses}"
            )
            scores = res.scores
        # The delta epochs really took the delta path (shape-stable).
        assert obs_metrics.JIT_RECOMPILES.value(fn="converge_windowed") == (
            counter_before
        )

    def test_shape_changing_epoch_reports_exactly_one(self):
        from protocol_tpu.obs import metrics as obs_metrics
        from protocol_tpu.obs.watchers import RECOMPILES

        # A peer-count no other test in this module uses: guaranteed
        # novel device shapes for converge_windowed.
        g = scale_free(1777, 9300, seed=23).drop_self_edges()
        b = get_backend("tpu-windowed")
        counter_before = obs_metrics.JIT_RECOMPILES.value(fn="converge_windowed")
        snap = RECOMPILES.snapshot()
        b.converge(g, alpha=0.1, tol=1e-6, max_iter=30)
        misses = RECOMPILES.observe(snap, steady_state=False, epoch=0)
        assert misses.get("converge_windowed") == 1, misses
        assert obs_metrics.JIT_RECOMPILES.value(fn="converge_windowed") == (
            counter_before + 1
        )

    def test_registered_entry_points(self):
        from protocol_tpu.obs.watchers import RECOMPILES

        names = RECOMPILES.registered()
        for fn in ("converge_csr", "converge_sparse", "converge_windowed"):
            assert fn in names, names
