"""Pod scale-out plumbing (ISSUE 16): the single-process halves of the
multi-host path — partition plan build, the sharded durability plane,
the multi-host analyzer extensions, and the sentinel keying.

The genuinely multi-process claims (bit-identical residuals across
hosts, host-loss recovery to a control-identical fixed point, flat
steady-state epoch seconds) are driven by ``tools/dryrun_pod.py`` and
the crash-matrix ``pod.host-loss`` row; this file pins everything that
can be checked in one process:

- a 1-host pod's ``PodWindowPlan`` is **byte-identical** to the
  single-host ``ShardedWindowPlan`` — the pod builder is a
  generalization, not a fork (same runner cache key, same arrays);
- the pod delta path resolves churn against the *local* plan and
  produces the same partition arrays as a cold rebuild;
- ``PodDurability`` seals only complete stamp sets, recovery reads the
  newest *sealed* manifest (torn pod states unrepresentable);
- ``pod_budget_view`` divides the resident edge term by the global
  shard count; the replica-group-coverage rule rejects per-host
  subgroup collectives; ``_warm_t0``'s vectorized remap matches the
  per-peer definition; pod sentinel series never collide with the
  single-host history.
"""

import json
import sys
import threading
import time
import types
from pathlib import Path

import numpy as np
import pytest

from protocol_tpu.analysis import COMM_INVARIANTS, MEM_INVARIANTS
from protocol_tpu.analysis.comm.checker import check_comm_case
from protocol_tpu.analysis.comm.hlo_walk import replica_group_sizes
from protocol_tpu.analysis.comm.lowering import CommCase
from protocol_tpu.analysis.memory.checker import pod_budget_view
from protocol_tpu.models import scale_free
from protocol_tpu.models.churn import churn_cohort_dims, sender_centric_churn
from protocol_tpu.node.manager import Manager
from protocol_tpu.node.pod import PodDurability
from protocol_tpu.parallel.mesh import default_mesh
from protocol_tpu.parallel.partition import HostPartition
from protocol_tpu.parallel.pod import PodContext, PodWindowPlan
from protocol_tpu.parallel.sharded import ShardedWindowPlan

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))

import perf_sentinel

pytestmark = pytest.mark.allow_transfer

SHARDED_ARRAYS = (
    "wid", "local", "weight", "seg_end", "seg_first", "seg_perm",
    "dst_ptr", "p", "dangling",
)


def _graph(n=1024, e=8192, seed=16):
    return scale_free(n, e, seed=seed)


def _pod1():
    """A 1-host pod over the test mesh — ``PodContext.current`` under
    a single process, which is exactly what it resolves to."""
    return PodContext.current(seed=16)


class TestPodPlanBuild:
    def test_single_host_pod_matches_sharded_plan(self):
        g = _graph()
        pod = _pod1()
        pp = PodWindowPlan.build(g, pod, clock=time.perf_counter)
        sp = ShardedWindowPlan.build(g, default_mesh())
        assert (pp.n, pp.rows_per_shard, pp.s_max, pp.table_entries) == (
            sp.n, sp.rows_per_shard, sp.s_max, sp.table_entries
        )
        for name in SHARDED_ARRAYS:
            a, b = np.asarray(getattr(pp, name)), np.asarray(getattr(sp, name))
            assert np.array_equal(a, b), name
        assert pp.plan.fingerprint == sp.plan.fingerprint
        assert pp.host_id == 0 and pp.n_hosts == 1
        assert np.array_equal(pp.owner, np.zeros(g.n, np.int32))
        assert pp.local_edges == g.drop_self_edges().nnz
        assert pp.plan_outcome == "rebuild" and pp.build_seconds > 0

    def test_plan_reuse_and_delta_outcomes(self):
        g = _graph()
        pod = _pod1()
        cold = PodWindowPlan.build(g, pod)
        # Same graph + cached plan: fingerprint revalidates, no build.
        warm = PodWindowPlan.build(g, pod, plan=cold.plan)
        assert warm.plan_outcome == "reuse"
        assert warm.build_seconds == 0.0
        # Churn one epoch and resolve with the hint: delta path, and
        # the partition arrays match a cold rebuild of the new graph.
        cohort_size, deg = churn_cohort_dims(g, 0.01)
        rows, g2, _ = sender_centric_churn(
            np.random.default_rng(16), g, cohort_size=cohort_size, deg=deg
        )
        delta = PodWindowPlan.build(g2, pod, plan=cold.plan, delta_rows=rows)
        rebuilt = PodWindowPlan.build(g2, pod)
        assert delta.plan_outcome == "delta"
        assert delta.plan.fingerprint == rebuilt.plan.fingerprint
        for name in SHARDED_ARRAYS:
            a = np.asarray(getattr(delta, name))
            b = np.asarray(getattr(rebuilt, name))
            assert np.array_equal(a, b), name

    @pytest.mark.slow
    def test_single_host_pod_converge_bit_identical(self):
        """The pod plan through the real runner: same cache key, same
        arrays — the scores must be bit-identical to the single-host
        sharded windowed backend (the multi-process version of this
        claim is the dryrun's cross-host residual identity check)."""
        from protocol_tpu.parallel.sharded import converge_sharded

        g = _graph(512, 4096)
        sp = ShardedWindowPlan.build(g, default_mesh())
        s_ref, it_ref, _ = converge_sharded(sp, max_iter=30)
        pp = PodWindowPlan.build(g, _pod1())
        s_pod, it_pod, _ = converge_sharded(pp, max_iter=30)
        assert it_pod == it_ref
        assert np.array_equal(np.asarray(s_pod), np.asarray(s_ref))

    def test_t0_is_a_fresh_copy(self):
        pp = PodWindowPlan.build(_graph(256, 2048), _pod1())
        t0 = pp.t0()
        assert t0 is not pp.p
        assert np.array_equal(np.asarray(t0), np.asarray(pp.p))


class TestPodDurability:
    def _pod(self, root, host, n=2):
        return PodDurability(root, host, n, fsync=False)

    def test_host_id_validation(self, tmp_path):
        with pytest.raises(ValueError):
            PodDurability(tmp_path, 2, 2)

    def test_seal_requires_complete_stamp_set(self, tmp_path):
        h0, h1 = self._pod(tmp_path, 0), self._pod(tmp_path, 1)
        h0.publish_shard(3, wal_seq=7, columns={"graph": "aa"})
        assert h0.seal_epoch(3) is None  # host 1 not published yet
        assert h0.load_manifest() is None
        h1.publish_shard(3, wal_seq=9, columns={"graph": "bb"})
        manifest = h0.seal_epoch(3)
        assert manifest is not None and manifest["epoch"] == 3
        loaded = h1.load_manifest()
        assert loaded == manifest
        assert h1.my_stamp(loaded)["wal_seq"] == 9
        assert h0.my_stamp(loaded)["columns"] == {"graph": "aa"}

    def test_recovery_reads_newest_sealed_not_newest_published(self, tmp_path):
        h0, h1 = self._pod(tmp_path, 0), self._pod(tmp_path, 1)
        for e in (1, 2):
            h0.publish_shard(e, wal_seq=e, columns={})
            h1.publish_shard(e, wal_seq=e, columns={})
            h0.seal_epoch(e)
        # Crash between publish and seal at epoch 3: host 0 stamped,
        # host 1 (and the seal) never happened.
        h0.publish_shard(3, wal_seq=3, columns={})
        manifest = h1.load_manifest()
        assert manifest is not None and manifest["epoch"] == 2
        # Partial epoch-3 state is invisible — every host rolls back
        # to the same epoch.
        assert h0.load_manifest()["epoch"] == 2

    def test_stamps_are_atomic_no_tmp_litter(self, tmp_path):
        h0 = self._pod(tmp_path, 0, n=1)
        h0.publish_shard(1, wal_seq=0, columns={"a": "b"})
        h0.seal_epoch(1)
        assert not list(tmp_path.glob("manifests/*.tmp"))
        stamp = json.loads(
            (tmp_path / "manifests" / "shard-e00000001-h000.json").read_text()
        )
        assert stamp["n_hosts"] == 1

    def test_wal_and_checkpoints_shard_per_host(self, tmp_path):
        h0, h1 = self._pod(tmp_path, 0), self._pod(tmp_path, 1)
        s0 = h0.wal.append(b"host0-att", flush=True)
        s1 = h1.wal.append(b"host1-att", flush=True)
        # Each host replays only its own shard, and the sequence
        # counters are per-shard (independent WALs, not one log).
        assert [p for _, p in h0.wal.replay()] == [b"host0-att"]
        assert [p for _, p in h1.wal.replay()] == [b"host1-att"]
        assert s0 == s1
        assert (tmp_path / "host-000" / "wal").is_dir()
        assert (tmp_path / "host-001" / "checkpoints").is_dir()


class TestPodAnalyzers:
    def test_pod_budget_view_divides_edges_by_global_shards(self):
        # CSR composite: raw edge arrays, so the resident edge term
        # divides by the GLOBAL shard count directly.
        budget = MEM_INVARIANTS["tpu-sharded:tpu-csr"]
        dims = dict(n=4096, edges=1 << 20, n_segments=0, rows=0)
        one = pod_budget_view(budget, n_shards=8, n_hosts=1, **dims)
        pod = pod_budget_view(budget, n_shards=32, n_hosts=4, **dims)
        assert pod["n_hosts"] == 4 and pod["n_shards"] == 32
        # 4x the shards: the edge-resident term shrinks, the O(N)
        # replicated terms don't — per-shard peak strictly drops.
        assert pod["resident_bytes"] < one["resident_bytes"]
        assert pod["transient_bytes"] == one["transient_bytes"]
        assert pod["peak_bytes"] == pod["resident_bytes"] + pod["transient_bytes"]

    def test_pod_budget_view_windowed_scales_with_per_host_plan(self):
        # Windowed composite: edge residency lives in the plan's
        # vreg-rows, so the pod division shows up through the per-host
        # plan dims (a host's plan over E/H edges has ~rows/H rows).
        budget = MEM_INVARIANTS["tpu-sharded:tpu-windowed"]
        assert budget.resident_edge_bytes == 0.0
        one = pod_budget_view(
            budget, n=4096, edges=1 << 20, n_segments=2048, rows=512,
            n_shards=8, n_hosts=1,
        )
        pod = pod_budget_view(
            budget, n=4096, edges=1 << 20, n_segments=512, rows=128,
            n_shards=32, n_hosts=4,
        )
        assert pod["peak_bytes"] < one["peak_bytes"]

    def test_replica_group_sizes_parsing(self):
        assert replica_group_sizes("{{0,1,2,3},{4,5,6,7}}") == [4, 4]
        assert replica_group_sizes("{{0,1,2,3,4,5,6,7}}") == [8]
        assert replica_group_sizes("{}") == []
        assert replica_group_sizes("") == []

    def _case(self, groups: str) -> CommCase:
        text = (
            "HloModule jit_run, is_scheduled=true\n"
            "%all-reduce.4 = f32[512]{0} all-reduce(f32[512]{0} %c.2), "
            f"channel_id=1, replica_groups={groups}, "
            "use_global_device_ids=true, to_apply=%region_1.205, "
            'metadata={op_name="jit(run)/jit(main)/while/body/'
            'jit(shmap_body)/psum2" source_file="/repo/parallel/sharded.py" '
            "source_line=171}\n"
        )
        return CommCase(
            backend="tpu-sharded:tpu-windowed",
            dims={"n": 512, "edges": 4096, "n_shards": 4, "n_segments": 1024},
            module_text=text,
            arg_names=("t0",),
            jaxpr_psums=1,
        )

    def test_per_host_subgroup_psum_is_rejected(self):
        budget = COMM_INVARIANTS["tpu-sharded:tpu-windowed"]
        assert budget.require_full_replica_group
        findings, _ = check_comm_case(budget, self._case("{{0,1},{2,3}}"))
        rules = [f.rule for f in findings]
        assert "replica-group-coverage" in rules

    def test_full_mesh_group_passes(self):
        budget = COMM_INVARIANTS["tpu-sharded:tpu-windowed"]
        for groups in ("{{0,1,2,3}}", "{}"):
            findings, _ = check_comm_case(budget, self._case(groups))
            assert "replica-group-coverage" not in [f.rule for f in findings], groups


class TestWarmT0Remap:
    """The vectorized searchsorted remap (PERF.md §20) against the
    per-peer definition it replaced."""

    def _warm(self, scores, hashes, id_order):
        m = types.SimpleNamespace(
            _state_lock=threading.Lock(),
            last_scores=scores,
            last_peer_hashes=hashes,
        )
        return Manager._warm_t0(m, id_order)

    def test_matches_per_peer_remap(self):
        rng = np.random.default_rng(16)
        prev_hashes = [int(h) for h in rng.integers(1, 1 << 62, 300)]
        scores = rng.random(300).astype(np.float64)
        survivors = prev_hashes[:200]
        joined = [int(h) for h in rng.integers(1 << 62, 1 << 63, 100)]
        id_order = survivors + joined
        rng.shuffle(id_order)
        got = self._warm(scores, prev_hashes, id_order)
        ref = np.array(
            [
                max(scores[prev_hashes.index(h)], 0.0) if h in set(survivors) else 0.0
                for h in id_order
            ]
        )
        ref /= ref.sum()
        assert got is not None
        np.testing.assert_allclose(got, ref, rtol=1e-12)
        assert abs(got.sum() - 1.0) < 1e-9

    def test_cold_start_cases(self):
        assert self._warm(None, None, [1, 2]) is None
        assert self._warm(np.array([]), [], [1, 2]) is None
        # Zero overlap: every score drops out -> cold start.
        assert self._warm(np.array([0.5, 0.5]), [10, 11], [20, 21]) is None
        # Negative garbage clamps to zero rather than poisoning the seed.
        got = self._warm(np.array([-1.0, 0.5]), [10, 11], [10, 11])
        np.testing.assert_allclose(got, [0.0, 1.0])


class TestPodSentinelKeys:
    def test_multi_host_entries_get_their_own_series(self):
        single = {"metric": "pod steady-state epoch wall-clock", "n_hosts": 1}
        pod = {"metric": "pod steady-state epoch wall-clock", "n_hosts": 2}
        legacy = {"metric": "pod steady-state epoch wall-clock"}
        k1 = perf_sentinel._series_key(single, "value")
        k2 = perf_sentinel._series_key(pod, "value")
        k3 = perf_sentinel._series_key(legacy, "value")
        # n_hosts=1 and legacy entries share the historical key; pods
        # fork their own series instead of gating against it.
        assert k1 == k3 == "pod steady-state epoch wall-clock :: value"
        assert k2 == "pod steady-state epoch wall-clock :: value [n_hosts=2]"

    def test_plan_build_fields_gate(self):
        assert perf_sentinel._FIELDS["plan_build_seconds"] is True
        assert perf_sentinel._FIELDS["plan_build_speedup"] is False
