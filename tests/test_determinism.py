"""Determinism wall (ISSUE 18): graftlint pass 13 + divergence probe.

Covers: the five pass-13 AST rules fire on minimal positive snippets
and stay quiet on the blessed idioms (``sorted(...)``, seeded RNGs,
timing deltas into metrics), the HLO leg's canonicalizer cancels SSA
renumbering while structural drift still trips
``hlo-nondeterministic-compile``, every seeded determinism fixture is
registered and fails the CLI, the real tree is clean modulo the one
enumerated waiver, a dead determinism waiver is itself a gate error,
the two real divergence sources this pass found (checkpoint glob
order, dedup ``hash()`` shard key) stay fixed, the probe's comparator
discriminates leg by leg, and the committed DET_r01.json round feeds
the perf sentinel as a lower-is-better multi-host series.
"""

import json
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))

import divergence_probe
import perf_sentinel

from protocol_tpu.analysis.__main__ import main as analysis_main
from protocol_tpu.analysis.determinism import (
    DET_AST_RULES,
    DET_TREES,
    DET_WAIVERS,
    canonicalize_hlo,
    check_recompile,
    diff_canonical,
    scan_det_source,
    scan_module_text,
)
from protocol_tpu.analysis.determinism import checker as det_checker
from protocol_tpu.analysis.determinism.ast_walk import run_det_ast_pass
from protocol_tpu.analysis.fixtures import FIXTURES

REPO = Path(__file__).resolve().parent.parent

DET_FIXTURES = {
    "set-order-to-state": "det-ast",
    "unsorted-dirscan": "det-ast",
    "hash-ordering": "det-ast",
    "unseeded-rng": "det-ast",
    "clock-in-digest": "det-ast",
    "hlo-nondeterministic-compile": "det-hlo",
}


def _scan(code: str, rel: str = "protocol_tpu/node/_snippet.py"):
    return scan_det_source(code, rel)


def _rules(code: str, rel: str = "protocol_tpu/node/_snippet.py"):
    return [f.rule for f in _scan(code, rel)]


# ---------------------------------------------------------------------------
# rule: set-order-to-state
# ---------------------------------------------------------------------------


class TestSetOrderToState:
    def test_list_of_set_fires(self):
        code = (
            "def seal(live):\n"
            "    live = set(live)\n"
            "    return list(live)\n"
        )
        assert _rules(code) == ["set-order-to-state"]

    def test_comprehension_over_set_fires(self):
        code = (
            "def columns(peers):\n"
            "    alive = {p for p in peers}\n"
            "    return [p * 2 for p in alive]\n"
        )
        assert _rules(code) == ["set-order-to-state"]

    def test_sum_over_set_fires(self):
        code = (
            "def residual(scores):\n"
            "    pending = set(scores)\n"
            "    return sum(pending)\n"
        )
        assert _rules(code) == ["set-order-to-state"]

    def test_np_asarray_of_set_fires(self):
        code = (
            "import numpy as np\n"
            "def column(live):\n"
            "    live = frozenset(live)\n"
            "    return np.asarray(list(live))\n"
        )
        assert "set-order-to-state" in _rules(code)

    def test_accumulating_loop_over_set_fires(self):
        code = (
            "def order(ids):\n"
            "    live = ids | {0}\n"
            "    out = []\n"
            "    for i in live:\n"
            "        out.append(i)\n"
            "    return out\n"
        )
        # `ids | {0}` is set-ish through the BinOp only when a side is
        # known set-ish; make it explicit:
        code = code.replace("ids | {0}", "set(ids) | {0}")
        assert _rules(code) == ["set-order-to-state"]

    def test_sorted_set_is_quiet(self):
        code = (
            "def seal(live):\n"
            "    live = set(live)\n"
            "    return sorted(live)\n"
        )
        assert _rules(code) == []

    def test_sorted_genexp_over_set_is_quiet(self):
        # The manager.py idiom: sorted(<genexp over set>).
        code = (
            "def seal(stale):\n"
            "    stale = set(stale)\n"
            "    return sorted(s.key for s in stale)\n"
        )
        assert _rules(code) == []

    def test_order_insensitive_consumers_are_quiet(self):
        code = (
            "def stats(live):\n"
            "    live = set(live)\n"
            "    return len(live), min(live), max(live), any(live), all(live)\n"
        )
        assert _rules(code) == []

    def test_membership_loop_is_quiet(self):
        code = (
            "def check(live, want):\n"
            "    live = set(live)\n"
            "    for i in live:\n"
            "        print(i)\n"
            "    return want in live\n"
        )
        assert _rules(code) == []

    def test_dict_keys_iteration_is_quiet(self):
        # dicts preserve insertion order — not a hash-order source.
        code = (
            "def cols(table):\n"
            "    out = []\n"
            "    for k in table.keys():\n"
            "        out.append(k)\n"
            "    return out\n"
        )
        assert _rules(code) == []


# ---------------------------------------------------------------------------
# rule: unsorted-dirscan
# ---------------------------------------------------------------------------


class TestUnsortedDirscan:
    def test_listdir_fires(self):
        code = (
            "import os\n"
            "def segments(wal_dir):\n"
            "    return os.listdir(wal_dir)\n"
        )
        assert _rules(code) == ["unsorted-dirscan"]

    @pytest.mark.parametrize("call", ["glob('*.npz')", "rglob('*.json')", "iterdir()"])
    def test_path_scan_methods_fire(self, call):
        code = (
            "def epochs(root):\n"
            f"    return list(root.{call})\n"
        )
        assert _rules(code) == ["unsorted-dirscan"]

    def test_sorted_listdir_is_quiet(self):
        code = (
            "import os\n"
            "def segments(wal_dir):\n"
            "    return sorted(os.listdir(wal_dir))\n"
        )
        assert _rules(code) == []

    def test_sorted_glob_is_quiet(self):
        code = (
            "def epochs(root):\n"
            "    return sorted(root.glob('epoch_*.npz'))\n"
        )
        assert _rules(code) == []


# ---------------------------------------------------------------------------
# rule: hash-ordering
# ---------------------------------------------------------------------------


class TestHashOrdering:
    def test_builtin_hash_fires(self):
        code = (
            "def shard(sender, n):\n"
            "    return hash(sender) % n\n"
        )
        assert _rules(code) == ["hash-ordering"]

    def test_id_fires(self):
        code = (
            "def key(obj):\n"
            "    return id(obj)\n"
        )
        assert _rules(code) == ["hash-ordering"]

    def test_method_hash_is_quiet(self):
        # pk.hash() is the curve point's own digest, not builtin hash().
        code = (
            "def key(pk, n):\n"
            "    return pk.hash() % n\n"
        )
        assert _rules(code) == []


# ---------------------------------------------------------------------------
# rule: unseeded-rng
# ---------------------------------------------------------------------------


class TestUnseededRng:
    @pytest.mark.parametrize(
        "expr",
        [
            "random.random()",
            "random.shuffle(xs)",
            "np.random.permutation(8)",
            "np.random.randint(0, 8)",
            "random.Random()",
            "np.random.default_rng()",
        ],
    )
    def test_unseeded_draws_fire(self, expr):
        code = (
            "import random\n"
            "import numpy as np\n"
            "def churn(xs):\n"
            f"    return {expr}\n"
        )
        assert _rules(code) == ["unseeded-rng"]

    @pytest.mark.parametrize(
        "expr",
        [
            "np.random.default_rng(7)",
            "random.Random(7)",
            "rng.permutation(8)",
            "rng.integers(0, 8)",
        ],
    )
    def test_seeded_streams_are_quiet(self, expr):
        code = (
            "import random\n"
            "import numpy as np\n"
            "def churn(rng):\n"
            f"    return {expr}\n"
        )
        assert _rules(code) == []


# ---------------------------------------------------------------------------
# rule: clock-in-digest
# ---------------------------------------------------------------------------


class TestClockInDigest:
    def test_clock_through_binding_into_update_fires(self):
        code = (
            "import hashlib, time\n"
            "def seal(h):\n"
            "    stamp = time.time()\n"
            "    h.update(str(stamp).encode())\n"
        )
        assert _rules(code) == ["clock-in-digest"]

    def test_pid_directly_into_sha256_fires(self):
        code = (
            "import hashlib, os\n"
            "def token():\n"
            "    return hashlib.sha256(str(os.getpid()).encode())\n"
        )
        assert _rules(code) == ["clock-in-digest"]

    def test_clock_bound_to_seedish_name_fires(self):
        code = (
            "import time\n"
            "def job():\n"
            "    job_seed = time.time_ns()\n"
            "    return job_seed\n"
        )
        assert _rules(code) == ["clock-in-digest"]

    def test_timing_deltas_into_metrics_are_quiet(self):
        code = (
            "import time\n"
            "def bench(fn, metrics):\n"
            "    t0 = time.perf_counter()\n"
            "    fn()\n"
            "    wall = time.perf_counter() - t0\n"
            "    metrics['wall_seconds'] = wall\n"
            "    return wall\n"
        )
        assert _rules(code) == []

    def test_returned_timestamp_is_quiet(self):
        # epoch.py idiom: a wall-clock *observation* returned to the
        # caller is not a digest/seed sink.
        code = (
            "import time\n"
            "def now_unix():\n"
            "    return int(time.time())\n"
        )
        assert _rules(code) == []

    def test_taint_does_not_leak_across_functions(self):
        code = (
            "import hashlib, time\n"
            "def a():\n"
            "    stamp = time.time()\n"
            "    return stamp\n"
            "def b(stamp_text):\n"
            "    other = 'static'\n"
            "    return hashlib.sha256(other.encode())\n"
        )
        assert _rules(code) == []


# ---------------------------------------------------------------------------
# the HLO leg
# ---------------------------------------------------------------------------

_MODULE_A = """\
HloModule converge.0
ENTRY %main.1 {
  %param.3 = f32[64]{0} parameter(0)  // arg shard
  %add.17 = f32[64]{0} add(%param.3, %param.3)
  ROOT %mul.29 = f32[64]{0} multiply(%add.17, %param.3)
}
"""

#: Same structure, different per-process SSA numbering + comments.
_MODULE_A_RENUMBERED = """\
HloModule converge.0
ENTRY %main.7 {
  %param.9 = f32[64]{0} parameter(0)  /* other naming counter */
  %add.101 = f32[64]{0} add(%param.9, %param.9)
  ROOT %mul.4 = f32[64]{0} multiply(%add.101, %param.9)
}
"""

#: Structurally different: an extra fused add the renamer cannot hide.
_MODULE_B = """\
HloModule converge.0
ENTRY %main.1 {
  %param.3 = f32[64]{0} parameter(0)
  %add.17 = f32[64]{0} add(%param.3, %param.3)
  %add.18 = f32[64]{0} add(%add.17, %param.3)
  ROOT %mul.29 = f32[64]{0} multiply(%add.18, %param.3)
}
"""


class TestHloLeg:
    def test_renumbering_cancels_under_canonicalization(self):
        assert canonicalize_hlo(_MODULE_A) == canonicalize_hlo(_MODULE_A_RENUMBERED)
        assert diff_canonical(_MODULE_A, _MODULE_A_RENUMBERED) is None
        assert check_recompile("tpu-dense", _MODULE_A, _MODULE_A_RENUMBERED) == []

    def test_structural_drift_fires(self):
        findings = check_recompile("tpu-dense", _MODULE_A, _MODULE_B)
        assert [f.rule for f in findings] == ["hlo-nondeterministic-compile"]
        assert findings[0].severity == "error"
        assert findings[0].backend == "tpu-dense"
        assert "drift" in findings[0].message

    def test_scatter_without_unique_indices_fires(self):
        text = (
            "%scatter.5 = f32[64]{0} scatter(%operand, %idx, %upd), "
            "to_apply=%add_f32\n"
        )
        findings, stats = scan_module_text("tpu-sparse", text)
        assert [f.rule for f in findings] == ["hlo-nondeterministic-scatter"]
        assert stats == {"scatter_ops": 1, "reduce_precision_ops": 0}

    def test_scatter_with_unique_indices_is_quiet(self):
        text = (
            "%scatter.5 = f32[64]{0} scatter(%operand, %idx, %upd), "
            "unique_indices=true, to_apply=%add_f32\n"
        )
        findings, stats = scan_module_text("tpu-sparse", text)
        assert findings == []
        assert stats["scatter_ops"] == 1

    def test_reduce_precision_fires(self):
        text = "%rp.2 = f32[64]{0} reduce-precision(%add.1), exponent_bits=8\n"
        findings, stats = scan_module_text("tpu-dense", text)
        assert [f.rule for f in findings] == ["hlo-reduce-precision"]
        assert stats["reduce_precision_ops"] == 1


# ---------------------------------------------------------------------------
# seeded fixtures + CLI gate
# ---------------------------------------------------------------------------


class TestSeededFixtures:
    def test_all_six_registered(self):
        for name, kind in DET_FIXTURES.items():
            assert name in FIXTURES, name
            assert FIXTURES[name].kind == kind
            assert FIXTURES[name].rule == name

    def test_cli_exits_nonzero_on_det_ast_fixture(self, tmp_path):
        out = tmp_path / "fixture.json"
        rc = analysis_main(["--fixture", "unseeded-rng", "--output", str(out)])
        assert rc == 1
        report = json.loads(out.read_text())
        assert report["findings"][0]["rule"] == "unseeded-rng"
        assert report["findings"][0]["pass"] == "determinism"

    def test_cli_exits_nonzero_on_det_hlo_fixture(self, tmp_path):
        out = tmp_path / "fixture.json"
        rc = analysis_main(
            ["--fixture", "hlo-nondeterministic-compile", "--output", str(out)]
        )
        assert rc == 1
        report = json.loads(out.read_text())
        assert report["findings"][0]["rule"] == "hlo-nondeterministic-compile"
        assert report["findings"][0]["pass"] == "determinism"


# ---------------------------------------------------------------------------
# the real tree + waiver doctrine
# ---------------------------------------------------------------------------


class TestRealTree:
    def test_det_trees_are_clean_modulo_the_enumerated_waiver(self):
        findings, n_files = run_det_ast_pass()
        assert n_files > 20  # the five trees are really being walked
        live, waived, stale = det_checker._apply_waivers(findings)
        assert live == [], [
            f"{f.file}:{f.line} {f.rule}: {f.message}" for f in live
        ]
        assert [w["symbol"] for w in waived] == ["random.Random"]
        assert waived[0]["file"] == "protocol_tpu/node/ethereum.py"
        assert stale == []

    def test_waiver_table_is_enumerated_not_patterned(self):
        assert len(DET_WAIVERS) == 1
        w = DET_WAIVERS[0]
        assert w.rule in DET_AST_RULES
        assert w.reason  # every waiver carries its rationale

    def test_dead_det_waiver_is_error(self, monkeypatch):
        from protocol_tpu.analysis.concurrency.waivers import Waiver

        dead = Waiver(
            rule="hlo-nondeterministic-scatter", file="gone.py",
            symbol="ghost", reason="the scatter this waived was segmented",
        )
        monkeypatch.setattr(det_checker, "DET_WAIVERS", (dead,))
        live, waived, stale = det_checker._apply_waivers([])
        assert live == [] and waived == []
        assert [s["symbol"] for s in stale] == ["ghost"]
        findings, section = det_checker.run_determinism_pass(backends=[])
        assert [f.rule for f in findings] == ["stale-waiver"]
        assert findings[0].severity == "error"
        assert section["stale_waivers"][0]["symbol"] == "ghost"

    def test_subset_run_does_not_stale_ast_waivers(self):
        # backends=[] never evaluates the AST leg, so the real (AST-rule)
        # waiver must not be judged stale there.
        findings, section = det_checker.run_determinism_pass(backends=[])
        assert findings == []
        assert section["stale_waivers"] == []


# ---------------------------------------------------------------------------
# regressions: the divergence sources pass 13 found and fixed
# ---------------------------------------------------------------------------


class TestFoundAndFixed:
    def test_checkpoint_epochs_sorted_despite_creation_order(self, tmp_path):
        """checkpoint.epochs() fed prune order and boot-time latest()
        from raw glob order — inode-history-dependent.  Now sorted."""
        from protocol_tpu.node.checkpoint import CheckpointStore

        store = CheckpointStore(tmp_path / "ckpt")
        # Reverse creation order so a naive glob would plausibly return
        # [10, 2]; the contract is numeric order regardless of history.
        (tmp_path / "ckpt" / "epoch_10.npz").touch()
        (tmp_path / "ckpt" / "epoch_2.npz").touch()
        assert store.epochs() == [2, 10]

    def test_dedup_shard_key_is_the_stable_mix_not_builtin_hash(self):
        """The dedup shard key was ``hash((x, y)) % n`` — stable in
        today's CPython but an implementation detail.  Now a splitmix
        mix, pinned here against an independent reimplementation."""
        from protocol_tpu.ingest.dedup import ShardedDedupCache, _shard_index

        mask = (1 << 64) - 1

        def reference(sender, n):
            x, y = sender
            acc = (int(x) * 0x9E3779B97F4A7C15 + int(y)) & mask
            acc ^= acc >> 31
            acc = (acc * 0xBF58476D1CE4E5B9) & mask
            acc ^= acc >> 27
            return acc % n

        senders = [(0, 0), (1, 2), (2, 1), (2**255 - 19, 7), (17, 2**200)]
        for sender in senders:
            got = _shard_index(sender, 16)
            assert 0 <= got < 16
            assert got == reference(sender, 16), sender

        # The cache still routes consistently: a digest admitted once is
        # a duplicate on the second admit through the same shard.
        cache = ShardedDedupCache(n_shards=4)
        assert cache.admit((1, 2), b"d" * 32) is None
        assert cache.admit((1, 2), b"d" * 32) == "duplicate"

    def test_shard_key_spreads(self):
        from protocol_tpu.ingest.dedup import _shard_index

        hits = {_shard_index((i, i + 1), 16) for i in range(256)}
        assert len(hits) == 16  # all shards reachable


# ---------------------------------------------------------------------------
# the runtime probe (unit level — the full replay runs in CI)
# ---------------------------------------------------------------------------


def _run_record(**over) -> dict:
    base = {
        "return_codes": [0, 0],
        "workers_ok": [True, True],
        "wal_ack_digests": {"h000/acks-h000.jsonl": "a1", "h001/acks-h001.jsonl": "a2"},
        "manifest_digests": {"h000/manifest.json": "m1"},
        "epoch_digests": [{"epoch": 0, "residual": 0.5, "scores_sha256": "s0"}],
        "cross_host_bit_identity": True,
        "final_scores_sha256": ["fs", "fs"],
        "scores_npy_sha256": "npy",
        "proof": {
            "prover": "poseidon-commitment",
            "proof_bytes": 32,
            "proof_sha256": "pf",
            "verified": True,
        },
        "fleet": {"scrapes": 9, "sources": ["decoy-0"], "aggregate_sha256": "fl"},
    }
    base.update(over)
    return base


class TestCompareRuns:
    def test_identical_runs_pass_every_leg(self):
        verdict = divergence_probe.compare_runs(_run_record(), _run_record())
        assert verdict["ok"] is True
        assert set(verdict["legs"]) == {
            "return_codes", "workers_ok", "wal_ack_digests",
            "manifest_digests", "epoch_digests", "cross_host_bit_identity",
            "final_scores_sha256", "scores_npy_bytes", "proof_bytes",
            "fleet_merge_order_insensitive",
        }
        assert all(verdict["legs"].values())

    def test_wal_digest_drift_trips_exactly_that_leg(self):
        b = _run_record(
            wal_ack_digests={"h000/acks-h000.jsonl": "XX", "h001/acks-h001.jsonl": "a2"}
        )
        verdict = divergence_probe.compare_runs(_run_record(), b)
        assert verdict["ok"] is False
        bad = [k for k, v in verdict["legs"].items() if not v]
        assert bad == ["wal_ack_digests"]

    def test_proof_drift_trips_the_proof_leg(self):
        b = _run_record(proof={
            "prover": "poseidon-commitment", "proof_bytes": 32,
            "proof_sha256": "OTHER", "verified": True,
        })
        verdict = divergence_probe.compare_runs(_run_record(), b)
        assert verdict["legs"]["proof_bytes"] is False

    def test_cross_host_disagreement_trips_its_leg(self):
        b = _run_record(cross_host_bit_identity=False)
        verdict = divergence_probe.compare_runs(_run_record(), b)
        assert verdict["legs"]["cross_host_bit_identity"] is False

    def test_empty_digests_never_vacuously_pass(self):
        # A probe that collected nothing must not report bit-identity.
        a = _run_record(wal_ack_digests={})
        verdict = divergence_probe.compare_runs(a, _run_record(wal_ack_digests={}))
        assert verdict["legs"]["wal_ack_digests"] is False

    def test_unreadable_manifest_fails_the_manifest_leg(self):
        a = _run_record(manifest_digests={"h000/manifest.json": "unreadable"})
        verdict = divergence_probe.compare_runs(a, a)
        assert verdict["legs"]["manifest_digests"] is False


class TestProbePlumbing:
    def test_canonical_json_digest_is_key_order_insensitive(self):
        a = divergence_probe._canonical_json_digest({"a": 1, "b": [2, 3]})
        b = divergence_probe._canonical_json_digest({"b": [2, 3], "a": 1})
        assert a == b
        c = divergence_probe._canonical_json_digest({"a": 1, "b": [3, 2]})
        assert a != c  # list order is real data

    def test_decoy_write_order_does_not_change_the_merge(self, tmp_path):
        """The scrape-interleaving leg's foundation: two fleet dirs with
        the same snapshots written in different orders aggregate to the
        same canonical digest."""
        from protocol_tpu.obs.fleet import FleetAggregator, load_directory

        dir_a, dir_b = tmp_path / "a", tmp_path / "b"
        divergence_probe._write_decoys(dir_a, (0, 1, 2))
        divergence_probe._write_decoys(dir_b, (2, 0, 1))
        digests = []
        for d in (dir_a, dir_b):
            agg = FleetAggregator()
            load_directory(d, agg)
            digests.append(
                divergence_probe._canonical_json_digest(agg.snapshots())
            )
        assert digests[0] == digests[1]

    def test_schedules_really_perturb(self):
        a, b = divergence_probe.SCHEDULES
        assert a["hashseed"] != b["hashseed"]
        assert a["omp_threads"] != b["omp_threads"]
        assert a["reverse_launch"] != b["reverse_launch"]
        assert a["decoy_order"] != b["decoy_order"]
        assert a["scrape_interval"] != b["scrape_interval"]


# ---------------------------------------------------------------------------
# the committed round + perf sentinel plumbing
# ---------------------------------------------------------------------------


class TestCommittedRound:
    def test_det_r01_shows_bit_identity_under_perturbation(self):
        report = json.loads((REPO / "DET_r01.json").read_text())
        assert report["ok"] is True
        assert report["skipped"] is False
        assert report["seed_divergence_mode"] is False
        assert report["n_hosts"] == 2
        legs = report["comparison"]["legs"]
        assert len(legs) == 10 and all(legs.values()), legs
        entry = report["entries"][0]
        assert entry["unit"] == "seconds"
        assert entry["n_hosts"] == 2
        assert len(entry["per_schedule_seconds"]) == len(
            divergence_probe.SCHEDULES
        )

    def test_det_report_is_not_skipped_as_artifact(self):
        report = json.loads((REPO / "DET_r01.json").read_text())
        assert not perf_sentinel._is_non_bench_artifact(report)

    def test_det_entry_is_lower_is_better_multi_host(self):
        report = json.loads((REPO / "DET_r01.json").read_text())
        entry = report["entries"][0]
        assert perf_sentinel._lower_is_better("value", entry) is True
        assert perf_sentinel._series_key(entry, "value").endswith("[n_hosts=2]")

    def test_committed_det_round_feeds_the_gate(self, tmp_path):
        out = tmp_path / "SENTINEL.json"
        rc = perf_sentinel.main(["--history", str(REPO), "--out", str(out)])
        assert rc == 0
        report = json.loads(out.read_text())
        assert "DET_r01.json" in report["history_files"]
        det_series = [k for k in report["series"] if "divergence probe" in k]
        assert det_series, sorted(report["series"])
        assert any(k.endswith("[n_hosts=2]") for k in det_series)


# ---------------------------------------------------------------------------
# pass wiring
# ---------------------------------------------------------------------------


class TestPassWiring:
    def test_det_trees_cover_the_state_planes(self):
        assert DET_TREES == ("node", "parallel", "ingest", "prover", "models")
        for tree in DET_TREES:
            assert (REPO / "protocol_tpu" / tree).is_dir()

    def test_empty_subset_run_still_writes_the_section_shape(self):
        # Narrow smoke of the section contract; the full HLO leg runs
        # in test_analysis.py's module-scoped real_report.
        findings, section = det_checker.run_determinism_pass(backends=[])
        assert findings == []
        assert section["backends"] == {}
        assert "waived" in section and "stale_waivers" in section
