"""Wrong-field RNS integers, Bn254 G1, and the Poseidon transcript
(parity with circuit/src/integer/, ecc/native.rs and
verifier/transcript/native.rs test coverage)."""

import random

import pytest

from protocol_tpu.crypto import field
from protocol_tpu.zk.bn254 import G1, GENERATOR, GROUP_ORDER, IDENTITY, is_on_curve
from protocol_tpu.zk.rns import (
    FQ_MODULUS,
    WrongFieldInteger,
    compose,
    decompose,
)
from protocol_tpu.zk.transcript import PoseidonRead, PoseidonWrite

rng = random.Random(21)


class TestRns:
    def test_decompose_compose_roundtrip(self):
        for _ in range(20):
            v = rng.randrange(FQ_MODULUS)
            assert compose(decompose(v)) == v

    def test_limb_width(self):
        limbs = decompose(FQ_MODULUS - 1)
        assert len(limbs) == 4
        assert all(limb < 1 << 68 for limb in limbs)

    @pytest.mark.parametrize("op", ["add", "sub", "mul", "div"])
    def test_reduction_witnesses(self, op):
        for _ in range(10):
            a = WrongFieldInteger.from_value(rng.randrange(FQ_MODULUS))
            b = WrongFieldInteger.from_value(rng.randrange(1, FQ_MODULUS))
            witness = getattr(a, op)(b)
            assert witness.check(a, b), op
            # result matches direct modular arithmetic
            expect = {
                "add": (a.value() + b.value()) % FQ_MODULUS,
                "sub": (a.value() - b.value()) % FQ_MODULUS,
                "mul": (a.value() * b.value()) % FQ_MODULUS,
                "div": a.value() * pow(b.value(), -1, FQ_MODULUS) % FQ_MODULUS,
            }[op]
            assert witness.result.value() == expect

    def test_forged_witness_rejected(self):
        a = WrongFieldInteger.from_value(123)
        b = WrongFieldInteger.from_value(456)
        w = a.mul(b)
        forged = type(w)(
            result=WrongFieldInteger.from_value(w.result.value() + 1),
            quotient=w.quotient,
            op="mul",
        )
        assert not forged.check(a, b)

    def test_fr_limbs_fit_scalar_field(self):
        v = WrongFieldInteger.from_value(FQ_MODULUS - 1)
        assert all(x < field.MODULUS for x in v.to_fr_limbs())


class TestBn254G1:
    def test_generator_on_curve(self):
        assert is_on_curve(GENERATOR)

    def test_group_order(self):
        assert GENERATOR.mul(GROUP_ORDER) == IDENTITY

    def test_add_double_consistency(self):
        p2 = GENERATOR.double()
        assert p2 == GENERATOR.add(GENERATOR)
        p3 = p2.add(GENERATOR)
        assert p3 == GENERATOR.mul(3)
        assert is_on_curve(p3)

    def test_inverse(self):
        p = GENERATOR.mul(77)
        assert p.add(p.neg()) == IDENTITY

    def test_scalar_mul_matches_addition_chain(self):
        acc = IDENTITY
        for k in range(8):
            assert acc == GENERATOR.mul(k)
            acc = acc.add(GENERATOR)

    def test_identity_laws(self):
        p = GENERATOR.mul(5)
        assert IDENTITY.add(p) == p
        assert p.add(IDENTITY) == p
        assert IDENTITY.double() == IDENTITY


class TestPoseidonTranscript:
    def test_prover_verifier_challenge_agreement(self):
        w = PoseidonWrite()
        p1 = GENERATOR.mul(42)
        w.write_point(p1)
        c1_prover = w.squeeze_challenge()
        w.write_scalar(12345)
        c2_prover = w.squeeze_challenge()
        proof = w.finalize()

        r = PoseidonRead(proof)
        assert r.read_point() == p1
        assert r.squeeze_challenge() == c1_prover
        assert r.read_scalar() == 12345
        assert r.squeeze_challenge() == c2_prover

    def test_transcript_binds_messages(self):
        w1, w2 = PoseidonWrite(), PoseidonWrite()
        w1.write_scalar(1)
        w2.write_scalar(2)
        assert w1.squeeze_challenge() != w2.squeeze_challenge()

    def test_successive_challenges_differ(self):
        w = PoseidonWrite()
        w.write_scalar(9)
        assert w.squeeze_challenge() != w.squeeze_challenge()

    def test_off_curve_point_rejected(self):
        w = PoseidonWrite()
        with pytest.raises(ValueError, match="not on curve"):
            w.write_point(G1(5, 5))

    def test_truncated_proof_rejected(self):
        w = PoseidonWrite()
        w.write_scalar(7)
        proof = w.finalize()
        r = PoseidonRead(proof[:16])
        with pytest.raises(ValueError, match="exhausted"):
            r.read_scalar()


class TestAggregation:
    """KZG accumulation (zk.aggregator) — the working analog of the
    reference's unfinished aggregator (verifier/aggregator.rs): k
    proofs batch-verify with one pairing check."""

    @classmethod
    def setup_class(cls):
        from protocol_tpu.zk import plonk
        from protocol_tpu.zk.cs import ConstraintSystem
        from protocol_tpu.zk.gadgets import StdGate
        from protocol_tpu.zk.kzg import Setup

        srs = Setup.generate(8, seed=b"agg-test")

        def circuit(a, b, c, pub):
            cs = ConstraintSystem()
            std = StdGate(cs)
            out = std.add(std.mul(std.witness(a), std.witness(b)), std.witness(c))
            inst = cs.column("instance", "instance")
            cs.copy(cs.assign(inst, 0, pub), out)
            return cs

        cls.pk = plonk.compile_circuit(circuit(3, 4, 5, 17), srs=srs)
        cls.snarks = []
        from protocol_tpu.zk.aggregator import Snark

        for seed, (a, b, c) in [(b"1", (3, 4, 5)), (b"2", (2, 8, 3)), (b"3", (6, 6, 6))]:
            pub = a * b + c
            proof = plonk.prove(cls.pk, circuit(a, b, c, pub), [pub], seed=seed)
            cls.snarks.append(Snark(cls.pk.vk, [pub], proof))

    def test_batch_of_three_verifies(self):
        from protocol_tpu.zk.aggregator import aggregate_verify

        assert aggregate_verify(self.snarks)

    def test_single_also_verifies(self):
        from protocol_tpu.zk.aggregator import aggregate_verify

        assert aggregate_verify(self.snarks[:1])

    def test_wrong_instance_member_rejects_batch(self):
        from protocol_tpu.zk.aggregator import Snark, aggregate_verify

        bad = Snark(self.pk.vk, [20], self.snarks[1].proof)
        assert not aggregate_verify([self.snarks[0], bad, self.snarks[2]])

    def test_tampered_member_rejects_batch(self):
        from protocol_tpu.zk.aggregator import Snark, aggregate_verify

        t = bytearray(self.snarks[2].proof)
        t[40] ^= 1
        bad = Snark(self.pk.vk, self.snarks[2].instances, bytes(t))
        assert not aggregate_verify([self.snarks[0], self.snarks[1], bad])

    def test_accumulator_roundtrip(self):
        from protocol_tpu.zk.aggregator import Accumulator, accumulate, finalize

        acc = accumulate(self.snarks)
        assert acc is not None
        restored = Accumulator.from_bytes(acc.to_bytes())
        assert finalize(restored, self.pk.vk)

    def test_deferred_pairing_matches_direct(self):
        from protocol_tpu.zk import plonk
        from protocol_tpu.zk.fields import pairing_check

        s = self.snarks[0]
        pair = plonk.verify_deferred(s.vk, s.instances, s.proof)
        assert pair is not None
        b, a = pair
        assert pairing_check([(b, s.vk.srs.g2), (a.neg(), s.vk.srs.tau_g2)])
        assert plonk.verify(s.vk, s.instances, s.proof)
