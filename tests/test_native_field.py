"""Adversarial-value tests for the native field engines: the IFMA
radix-52 lazy-reduction paths (native/zk_ifma.cpp) and the scalar CIOS
path must agree with exact Python arithmetic at the field boundaries
(0, 1, p-1, p-2, single-bit limbs, 2^52-boundary patterns), not just on
random values.
"""

import random

from protocol_tpu.crypto.field import MODULUS as R
from protocol_tpu.zk import native
from protocol_tpu.zk.plonk import omega, _py_ntt

EDGE = [
    0,
    1,
    2,
    R - 1,
    R - 2,
    (1 << 52) - 1,
    1 << 52,
    (1 << 104) - 1,
    (1 << 208) + ((1 << 52) - 1),
    (1 << 253) + 1,
    R >> 1,
]


class TestFieldBoundaries:
    def test_vec_mul_edge_values(self):
        pairs = [(a, b) for a in EDGE for b in EDGE]
        a = [p[0] for p in pairs]
        b = [p[1] for p in pairs]
        # Pad to a multiple of 8 so the IFMA path covers every pair.
        while len(a) % 8:
            a.append(3)
            b.append(5)
        got = native.vec_mul(a, b)
        assert got == [(x * y) % R for x, y in zip(a, b)]

    def test_ntt_edge_coefficients(self):
        random.seed(7)
        k = 5
        n = 1 << k
        vals = (EDGE * ((n // len(EDGE)) + 1))[:n]
        w = omega(k)
        got = native.ntt(list(vals), w, inverse=False)
        assert got == _py_ntt(list(vals), w, False)
        back = native.ntt(list(got), pow(w, R - 2, R), inverse=True)
        assert back == vals

    def test_scale_add_edge_values(self):
        from protocol_tpu.utils.limbs import from_limbs, to_limbs
        import numpy as np

        lib = native._load()
        for s in (0, 1, R - 1, (1 << 52), R >> 1):
            acc_vals = (EDGE * 2)[:16]
            p_vals = list(reversed((EDGE * 2)[:16]))
            acc = to_limbs(acc_vals)
            pl_ = to_limbs(p_vals)
            sl = to_limbs([s])
            lib.zk_scale_add(native._ptr(acc), native._ptr(pl_), native._ptr(sl), 16)
            got = from_limbs(acc)
            assert got == [(a + s * p) % R for a, p in zip(acc_vals, p_vals)]
