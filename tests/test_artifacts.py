"""Committed deployment-artifact pinning tests.

The reference ships its proving artifacts in data/ (params-14.bin, the
et_verifier.bin contract, et_proof.json) and its client test verifies
the committed proof against the committed verifier byte-for-byte
(client/src/lib.rs:223-260).  These tests pin this repo's equivalents —
data/srs-15.bin, data/et_verifier.bin, data/et_proof.json — so the
wire format cannot drift between rounds without a deliberate
regeneration (tools/gen_et_verifier.py).
"""

import json
import os
from pathlib import Path

import pytest

from protocol_tpu.crypto import field
from protocol_tpu.zk.evm_verifier import GeneratedVerifier, evm_verify
from protocol_tpu.zk.proof import ProofRaw

DATA = Path(__file__).resolve().parent.parent / "data"
P = field.MODULUS


class TestCommittedArtifacts:
    def test_committed_proof_verifies_on_committed_verifier(self):
        gen = GeneratedVerifier.from_bytes((DATA / "et_verifier.bin").read_bytes())
        proof = ProofRaw.from_json((DATA / "et_proof.json").read_text()).to_proof()
        ok, gas = evm_verify(gen, proof.pub_ins, proof.proof)
        assert ok and gas > 0

    def test_committed_proof_tamper_rejected(self):
        gen = GeneratedVerifier.from_bytes((DATA / "et_verifier.bin").read_bytes())
        proof = ProofRaw.from_json((DATA / "et_proof.json").read_text()).to_proof()
        bad_ins = [(proof.pub_ins[0] + 1) % P] + proof.pub_ins[1:]
        assert not evm_verify(gen, bad_ins, proof.proof)[0]
        bad = bytearray(proof.proof)
        bad[7] ^= 1
        assert not evm_verify(gen, proof.pub_ins, bytes(bad))[0]

    def test_srs_artifact_well_formed(self):
        """srs-15.bin parses, has 2^15 G1 powers, and its first powers
        are pairing-consistent: e(g1[1], g2) == e(g1[0], tau_g2)."""
        from protocol_tpu.zk.fields import pairing_check
        from protocol_tpu.zk.kzg import Setup

        srs = Setup.from_bytes((DATA / "srs-15.bin").read_bytes())
        assert srs.k == 15 and len(srs.g1_powers) == 1 << 15
        assert pairing_check(
            [(srs.g1_powers[1], srs.g2), (srs.g1_powers[0].neg(), srs.tau_g2)]
        )

    def test_artifact_sizes_pinned(self):
        """Shape parity with the reference's committed artifacts:
        params-14.bin is 2,097,412 bytes; srs-15.bin carries the same
        2MB G1 ladder (plus the G2 pair)."""
        assert (DATA / "srs-15.bin").stat().st_size == 2_097_420
        assert (DATA / "et_verifier.bin").stat().st_size > 10_000


@pytest.mark.skipif(
    not os.environ.get("PROTOCOL_TPU_SLOW_TESTS"),
    reason="keygen from the committed SRS (~14 s cold) + one epoch prove (~8 s); "
    "set PROTOCOL_TPU_SLOW_TESTS=1",
)
class TestNodeServesCommittedFormat:
    def test_fresh_epoch_proof_verifies_on_committed_verifier(self):
        """A node booted on the committed SRS serves proofs the
        committed on-chain verifier accepts — the round-trip the
        reference's client test drives against Anvil."""
        from protocol_tpu.node.epoch import Epoch
        from protocol_tpu.node.manager import Manager, ManagerConfig

        mgr = Manager(
            ManagerConfig(prover="plonk", srs_path=str(DATA / "srs-15.bin"))
        )
        mgr.generate_initial_attestations()
        mgr.calculate_proofs(Epoch(2))
        proof = mgr.cached_proofs[Epoch(2)]
        gen = GeneratedVerifier.from_bytes((DATA / "et_verifier.bin").read_bytes())
        ok, gas = evm_verify(gen, proof.pub_ins, proof.proof)
        assert ok and gas > 0
