"""Perf-regression sentinel (ISSUE 6): the committed BENCH_r*.json
trajectory is finally *read* — the sentinel gates on per-metric trend
deltas against the best recorded value."""

import json
import shutil
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))

import perf_sentinel

REPO = Path(__file__).resolve().parent.parent


def _record(n: int, value: float, metric: str = "headline seconds") -> dict:
    return {
        "n": n,
        "parsed": {"metric": metric, "value": value, "unit": "seconds"},
    }


def _write_rounds(path: Path, values: list[float], **kw) -> None:
    for i, v in enumerate(values, start=1):
        (path / f"BENCH_r{i:02d}.json").write_text(json.dumps(_record(i, v, **kw)))


class TestRealSeries:
    def test_committed_bench_series_passes(self, tmp_path):
        """Acceptance: exit 0 on the real BENCH_r01..r05 series."""
        out = tmp_path / "SENTINEL.json"
        rc = perf_sentinel.main(["--history", str(REPO), "--out", str(out)])
        assert rc == 0
        report = json.loads(out.read_text())
        assert report["ok"] is True
        assert any("BENCH_r01.json" in f for f in report["history_files"])
        # The headline series was parsed across all five rounds.
        headline = [k for k in report["series"] if "convergence wall-clock" in k]
        assert headline and report["series"][headline[0]]["rounds"] == 5


class TestIngestSeries:
    def test_ingest_storm_rounds_feed_the_gate(self, tmp_path):
        """ISSUE 7: INGEST_r*.json is in the default globs, its
        ``entries`` list is walked, and sigs_per_s gates downward /
        p99_admission_ms upward."""
        for i, (sigs, p99) in enumerate([(1000.0, 50.0), (400.0, 200.0)], start=1):
            (tmp_path / f"INGEST_r{i:02d}.json").write_text(
                json.dumps(
                    {
                        "n": i,
                        "bench": "ingest_storm",
                        "entries": [
                            {
                                "metric": "ingest-storm accepted sigs/s (honest)",
                                "sigs_per_s": sigs,
                                "p99_admission_ms": p99,
                            }
                        ],
                    }
                )
            )
        out = tmp_path / "SENTINEL.json"
        rc = perf_sentinel.main(["--history", str(tmp_path), "--out", str(out)])
        assert rc == 1  # r02 regressed both directions vs r01
        report = json.loads(out.read_text())
        assert set(report["regressions"]) == {
            "ingest-storm accepted sigs/s (honest) :: sigs_per_s",
            "ingest-storm accepted sigs/s (honest) :: p99_admission_ms",
        }

    def test_committed_ingest_round_passes(self, tmp_path):
        out = tmp_path / "SENTINEL.json"
        rc = perf_sentinel.main(["--history", str(REPO), "--out", str(out)])
        assert rc == 0
        report = json.loads(out.read_text())
        assert any("INGEST_r01.json" in f for f in report["history_files"])
        assert any("sigs_per_s" in k for k in report["series"])


class TestProverSeries:
    def test_prover_storm_rounds_feed_the_gate(self, tmp_path):
        """ISSUE 10: PROVER_r*.json is in the default globs, its
        ``entries`` list is walked, and steady_state_epoch_seconds /
        p99_proof_lag_ms gate upward, sustained_proofs_per_s
        downward."""
        for i, (steady, lag, pps) in enumerate(
            [(6.0, 12000.0, 0.1), (12.0, 40000.0, 0.03)], start=1
        ):
            (tmp_path / f"PROVER_r{i:02d}.json").write_text(
                json.dumps(
                    {
                        "n": i,
                        "entries": [
                            {
                                "metric": "steady-state epoch with async plane",
                                "value": steady,
                                "unit": "seconds",
                                "steady_state_epoch_seconds": steady,
                            },
                            {
                                "metric": "proving-plane proof latency",
                                "p99_proof_lag_ms": lag,
                                "sustained_proofs_per_s": pps,
                            },
                        ],
                    }
                )
            )
        out = tmp_path / "SENTINEL.json"
        rc = perf_sentinel.main(["--history", str(tmp_path), "--out", str(out)])
        assert rc == 1  # r02 regressed all three directions vs r01
        report = json.loads(out.read_text())
        assert {
            "proving-plane proof latency :: p99_proof_lag_ms",
            "proving-plane proof latency :: sustained_proofs_per_s",
            "steady-state epoch with async plane :: steady_state_epoch_seconds",
        } <= set(report["regressions"])

    def test_committed_prover_round_feeds_the_gate(self, tmp_path):
        out = tmp_path / "SENTINEL.json"
        rc = perf_sentinel.main(["--history", str(REPO), "--out", str(out)])
        assert rc == 0
        report = json.loads(out.read_text())
        assert any("PROVER_r01.json" in f for f in report["history_files"])
        assert any("p99_proof_lag_ms" in k for k in report["series"])


class TestSyntheticRegression:
    def test_regressed_latest_round_fails(self, tmp_path):
        """Acceptance: exit non-zero on a synthetically regressed
        fixture — the newest round is >threshold worse than the best."""
        for f in REPO.glob("BENCH_r0*.json"):
            shutil.copy(f, tmp_path / f.name)
        rec = json.loads((REPO / "BENCH_r05.json").read_text())
        rec["n"] = 6
        rec["parsed"]["value"] = rec["parsed"]["value"] * 3
        (tmp_path / "BENCH_r06.json").write_text(json.dumps(rec))
        out = tmp_path / "SENTINEL.json"
        rc = perf_sentinel.main(["--history", str(tmp_path), "--out", str(out)])
        assert rc == 1
        report = json.loads(out.read_text())
        assert report["ok"] is False
        assert len(report["regressions"]) == 1
        row = report["series"][report["regressions"][0]]
        assert row["status"] == "REGRESSED"
        assert row["candidate_source"] == "BENCH_r06.json"

    def test_within_threshold_wobble_passes(self, tmp_path):
        _write_rounds(tmp_path, [10.0, 8.0, 8.5])  # 6.25% above best
        rc = perf_sentinel.main(
            ["--history", str(tmp_path), "--out", str(tmp_path / "s.json")]
        )
        assert rc == 0

    def test_threshold_is_configurable(self, tmp_path):
        _write_rounds(tmp_path, [10.0, 8.0, 8.5])
        rc = perf_sentinel.main(
            [
                "--history", str(tmp_path),
                "--threshold", "0.05",
                "--out", str(tmp_path / "s.json"),
            ]
        )
        assert rc == 1

    def test_higher_is_better_metrics_gate_downward(self, tmp_path):
        for i, sigs in enumerate([3000.0, 3554.0, 1000.0], start=1):
            rec = {
                "n": i,
                "parsed": {
                    "metric": "sustained ingest",
                    "value": 1.0,
                    "unit": "seconds",
                    "sigs_per_s": sigs,
                },
            }
            (tmp_path / f"BENCH_r{i:02d}.json").write_text(json.dumps(rec))
        out = tmp_path / "s.json"
        rc = perf_sentinel.main(["--history", str(tmp_path), "--out", str(out)])
        assert rc == 1
        report = json.loads(out.read_text())
        assert any("sigs_per_s" in k for k in report["regressions"])


class TestFreshRun:
    def test_fresh_entry_gates_against_recorded_best(self, tmp_path):
        _write_rounds(tmp_path, [10.0, 8.0, 8.1])
        fresh = {"metric": "headline seconds", "value": 12.0, "unit": "seconds"}
        (tmp_path / "FRESH.json").write_text(json.dumps(fresh))
        rc = perf_sentinel.main(
            [
                "--history", str(tmp_path),
                "--fresh", str(tmp_path / "FRESH.json"),
                "--out", str(tmp_path / "s.json"),
            ]
        )
        assert rc == 1
        report = json.loads((tmp_path / "s.json").read_text())
        row = report["series"][report["regressions"][0]]
        assert row["candidate_source"] == "fresh" and row["candidate"] == 12.0

    def test_fresh_smoke_scale_never_compared(self, tmp_path):
        """A differently-shaped fresh run (CI smoke) has a different
        metric string — it must report as no-baseline, never gate."""
        _write_rounds(tmp_path, [10.0, 8.0])
        fresh = {
            "metric": "smoke-scale convergence (tpu-csr)",
            "value": 999.0,
            "unit": "seconds",
        }
        (tmp_path / "FRESH.json").write_text(json.dumps(fresh))
        rc = perf_sentinel.main(
            [
                "--history", str(tmp_path),
                "--fresh", str(tmp_path / "FRESH.json"),
                "--out", str(tmp_path / "s.json"),
            ]
        )
        assert rc == 0
        report = json.loads((tmp_path / "s.json").read_text())
        smoke = [k for k in report["series"] if "smoke-scale" in k]
        assert report["series"][smoke[0]]["status"] == "no-baseline"

    def test_richer_epoch_metrics_are_tracked(self, tmp_path):
        """cold/steady-state epoch seconds and plan seconds from an
        epochs-mode bench entry become their own gated series."""
        entry = {
            "metric": "steady-state epoch wall-clock",
            "value": 5.82,
            "unit": "seconds",
            "cold_epoch_seconds": 7.37,
            "steady_state_epoch_seconds": 5.82,
            "plan_seconds": 2.5,
        }
        (tmp_path / "BENCH_r01.json").write_text(
            json.dumps({"n": 1, "parsed": entry})
        )
        regressed = dict(entry, steady_state_epoch_seconds=9.0, value=9.0)
        (tmp_path / "BENCH_r02.json").write_text(
            json.dumps({"n": 2, "parsed": regressed})
        )
        out = tmp_path / "s.json"
        rc = perf_sentinel.main(["--history", str(tmp_path), "--out", str(out)])
        assert rc == 1
        report = json.loads(out.read_text())
        assert any(
            "steady_state_epoch_seconds" in k for k in report["regressions"]
        )
        assert any("plan_seconds" in k for k in report["series"])


class TestNoHistory:
    def test_missing_history_is_usage_error(self, tmp_path):
        rc = perf_sentinel.main(
            ["--history", str(tmp_path), "--out", str(tmp_path / "s.json")]
        )
        assert rc == 2


class TestSiblingArtifactsIgnored:
    """ISSUE 8: SANITIZER/ANALYSIS artifacts living beside the bench
    rounds (or caught by an over-broad --glob) are skipped gracefully —
    never mined for numbers, never a parse failure."""

    def _sanitizer_doc(self) -> dict:
        return {
            "mode": "asan",
            "sanflags": "-fsanitize=address,undefined",
            "build_rc": 0,
            "runs": [{"name": "native-test-subset", "rc": 0}],
            "reports": [],
            "ok": True,
        }

    def _analysis_doc(self) -> dict:
        return {
            "version": 1,
            "tool": "protocol_tpu.analysis (graftlint)",
            "summary": {"error": 0},
            "findings": [
                {"pass": "concurrency", "rule": "unguarded-rmw", "line": 42}
            ],
            "concurrency": {"roots": [], "findings": 0},
            # ISSUE 9: the report grew a pass-8 comm section whose
            # per-scale records carry bytes_per_iter numbers — still an
            # analysis artifact, still never mined.
            "comm": {
                "backends": {
                    "tpu-sharded:tpu-csr": {
                        "status": "checked",
                        "scales": [{"bytes_per_iter": 4096}],
                    }
                }
            },
        }

    def test_artifacts_beside_rounds_do_not_pollute_series(self, tmp_path):
        _write_rounds(tmp_path, [10.0, 9.5])
        (tmp_path / "SANITIZER_asan_r01.json").write_text(
            json.dumps(self._sanitizer_doc())
        )
        (tmp_path / "ANALYSIS_r01.json").write_text(
            json.dumps(self._analysis_doc())
        )
        out = tmp_path / "s.json"
        rc = perf_sentinel.main(
            ["--history", str(tmp_path), "--glob", "*_r*.json", "--out", str(out)]
        )
        assert rc == 0
        report = json.loads(out.read_text())
        assert report["ok"] is True
        # Exactly the bench series — nothing mined from the artifacts.
        assert list(report["series"]) == ["headline seconds :: value"]
        assert report["series"]["headline seconds :: value"]["rounds"] == 2

    def test_artifact_only_history_is_empty_not_crash(self, tmp_path):
        (tmp_path / "SANITIZER_tsan_r01.json").write_text(
            json.dumps(self._sanitizer_doc() | {"mode": "tsan"})
        )
        out = tmp_path / "s.json"
        rc = perf_sentinel.main(
            ["--history", str(tmp_path), "--glob", "*_r*.json", "--out", str(out)]
        )
        assert rc == 0
        assert json.loads(out.read_text())["series"] == {}

    def test_committed_sanitizer_rounds_are_ignored_by_defaults(self):
        """The repo now commits SANITIZER_*_r01.json next to BENCH_r*;
        the default-glob run must not pick them up."""
        series = perf_sentinel.collect_series(
            [REPO / "SANITIZER_asan_r01.json", REPO / "SANITIZER_tsan_r01.json"]
        )
        assert series == {}


class TestCommBytesSeries:
    """ISSUE 9: MULTICHIP_r*.json is in the default globs and its
    pass-8 comm scrape feeds a ``comm_bytes_per_iter`` series that
    gates UPWARD — a partitioner surprise that inflates per-iteration
    wire traffic is a regression like any wall-clock."""

    METRIC = "per-iteration collective bytes (tpu-sharded:tpu-csr, 8-dev mesh)"

    def _multichip(self, n: int, bytes_per_iter: float) -> dict:
        return {
            "n": n,
            "n_devices": 8,
            "ok": True,
            "comm": {
                "tpu-sharded:tpu-csr": {"bytes_per_iter": bytes_per_iter}
            },
            "entries": [
                {
                    "metric": self.METRIC,
                    "comm_bytes_per_iter": bytes_per_iter,
                    "unit": "bytes",
                }
            ],
        }

    def test_stable_comm_bytes_pass(self, tmp_path):
        for i in (1, 2):
            (tmp_path / f"MULTICHIP_r{i:02d}.json").write_text(
                json.dumps(self._multichip(i, 2048.0))
            )
        out = tmp_path / "s.json"
        rc = perf_sentinel.main(["--history", str(tmp_path), "--out", str(out)])
        assert rc == 0
        report = json.loads(out.read_text())
        key = f"{self.METRIC} :: comm_bytes_per_iter"
        assert report["series"][key]["rounds"] == 2
        assert report["series"][key]["lower_is_better"] is True

    def test_inflated_comm_bytes_fail(self, tmp_path):
        for i, b in ((1, 2048.0), (2, 4096.0)):  # 2x wire out of nowhere
            (tmp_path / f"MULTICHIP_r{i:02d}.json").write_text(
                json.dumps(self._multichip(i, b))
            )
        out = tmp_path / "s.json"
        rc = perf_sentinel.main(["--history", str(tmp_path), "--out", str(out)])
        assert rc == 1
        report = json.loads(out.read_text())
        assert report["regressions"] == [
            f"{self.METRIC} :: comm_bytes_per_iter"
        ]

    def test_legacy_multichip_rounds_yield_no_series(self):
        """The pre-ISSUE-9 MULTICHIP_r01..r05 records (rc/ok/tail only)
        are in the default globs but carry no metric entries — they
        must contribute nothing rather than break the parse."""
        series = perf_sentinel.collect_series([REPO / "MULTICHIP_r01.json"])
        assert series == {}

    def test_committed_multichip_round_feeds_the_gate(self, tmp_path):
        """The ISSUE 9 recorded round (MULTICHIP_r06+) is picked up by
        the default-glob run as a comm_bytes_per_iter series."""
        out = tmp_path / "SENTINEL.json"
        rc = perf_sentinel.main(["--history", str(REPO), "--out", str(out)])
        assert rc == 0
        report = json.loads(out.read_text())
        assert any("MULTICHIP_r06.json" in f for f in report["history_files"])
        assert any("comm_bytes_per_iter" in k for k in report["series"])


class TestObsFreshnessSeries:
    def test_obs_rounds_feed_the_gate(self, tmp_path):
        """ISSUE 11: OBS_r*.json is in the default globs, its
        ``entries`` list is walked, and freshness_p99_ms /
        obs_overhead_pct gate upward."""
        for i, (fresh, overhead) in enumerate(
            [(9000.0, 0.2), (30000.0, 2.5)], start=1
        ):
            (tmp_path / f"OBS_r{i:02d}.json").write_text(
                json.dumps(
                    {
                        "n": i,
                        "entries": [
                            {
                                "metric": "end-to-end freshness (200k/2M churned)",
                                "value": fresh,
                                "unit": "ms p99 accepted-to-proven",
                                "freshness_p99_ms": fresh,
                            },
                            {
                                "metric": "lineage+SLO overhead",
                                "obs_overhead_pct": overhead,
                            },
                        ],
                    }
                )
            )
        out = tmp_path / "SENTINEL.json"
        rc = perf_sentinel.main(["--history", str(tmp_path), "--out", str(out)])
        assert rc == 1  # r02 regressed both series vs r01
        report = json.loads(out.read_text())
        assert {
            "end-to-end freshness (200k/2M churned) :: freshness_p99_ms",
            "lineage+SLO overhead :: obs_overhead_pct",
        } <= set(report["regressions"])

    def test_committed_obs_round_feeds_the_gate(self, tmp_path):
        out = tmp_path / "SENTINEL.json"
        rc = perf_sentinel.main(["--history", str(REPO), "--out", str(out)])
        assert rc == 0
        report = json.loads(out.read_text())
        assert any("OBS_r01.json" in f for f in report["history_files"])
        assert any("freshness_p99_ms" in k for k in report["series"])


class TestPodObsSeries:
    def test_pod_obs_rounds_gate_with_n_hosts_key(self, tmp_path):
        """ISSUE 19: pod OBS rounds carry ``n_hosts`` so the stitch /
        skew series never collide with the single-host freshness
        series; stitch_ms and phase_skew_p99_ms gate upward."""
        for i, (stitch, skew) in enumerate([(0.5, 8.0), (4.0, 60.0)], start=1):
            (tmp_path / f"OBS_r{i:02d}.json").write_text(
                json.dumps(
                    {
                        "n": i,
                        "tool": "dryrun_pod",
                        "n_hosts": 2,
                        "entries": [
                            {
                                "metric": "pod trace stitch + phase skew",
                                "value": stitch,
                                "unit": "ms",
                                "n_hosts": 2,
                                "stitch_ms": stitch,
                                "phase_skew_p99_ms": skew,
                            }
                        ],
                    }
                )
            )
        out = tmp_path / "SENTINEL.json"
        rc = perf_sentinel.main(["--history", str(tmp_path), "--out", str(out)])
        assert rc == 1  # r02 regressed both pod series vs r01
        report = json.loads(out.read_text())
        assert {
            "pod trace stitch + phase skew :: stitch_ms [n_hosts=2]",
            "pod trace stitch + phase skew :: phase_skew_p99_ms [n_hosts=2]",
        } <= set(report["regressions"])

    def test_committed_pod_obs_round_feeds_the_gate(self, tmp_path):
        out = tmp_path / "SENTINEL.json"
        rc = perf_sentinel.main(["--history", str(REPO), "--out", str(out)])
        assert rc == 0
        report = json.loads(out.read_text())
        assert any("OBS_r02.json" in f for f in report["history_files"])
        assert any(
            "stitch_ms [n_hosts=2]" in k for k in report["series"]
        )


class TestChaosRecoverySeries:
    def test_chaos_rounds_feed_the_gate(self, tmp_path):
        """ISSUE 14: CHAOS_r*.json is in the default globs, its
        ``entries`` list is walked, and recovery_seconds /
        wal_overhead_pct gate upward (a slower kill -9 recovery or a
        heavier WAL both regress the durability plane)."""
        for i, (recovery, overhead) in enumerate(
            [(0.8, 1.5), (4.0, 9.0)], start=1
        ):
            (tmp_path / f"CHAOS_r{i:02d}.json").write_text(
                json.dumps(
                    {
                        "n": i,
                        "tool": "crash_matrix",
                        "entries": [
                            {
                                "metric": "crash-matrix recovery (2000 peers)",
                                "recovery_seconds": recovery,
                                "wal_overhead_pct": overhead,
                            }
                        ],
                    }
                )
            )
        out = tmp_path / "SENTINEL.json"
        rc = perf_sentinel.main(["--history", str(tmp_path), "--out", str(out)])
        assert rc == 1  # r02 regressed both series vs r01
        report = json.loads(out.read_text())
        assert {
            "crash-matrix recovery (2000 peers) :: recovery_seconds",
            "crash-matrix recovery (2000 peers) :: wal_overhead_pct",
        } <= set(report["regressions"])

    def test_committed_chaos_round_feeds_the_gate(self, tmp_path):
        out = tmp_path / "SENTINEL.json"
        rc = perf_sentinel.main(["--history", str(REPO), "--out", str(out)])
        assert rc == 0
        report = json.loads(out.read_text())
        assert any("CHAOS_r01.json" in f for f in report["history_files"])
        assert any("recovery_seconds" in k for k in report["series"])


class TestMemPeakSeries:
    def test_mem_rounds_feed_the_gate(self, tmp_path):
        """ISSUE 15: MEM_r*.json is in the default globs, its
        ``entries`` list is walked, and peak_hbm_bytes /
        peak_hbm_bytes_per_shard gate upward (a silently materialized
        O(E) temporary or a replicated edge operand moves a recorded
        number before it trips the static wall)."""
        for i, (peak, per_shard) in enumerate(
            [(500_000, 60_000), (900_000, 140_000)], start=1
        ):
            (tmp_path / f"MEM_r{i:02d}.json").write_text(
                json.dumps(
                    {
                        "n": i,
                        "tool": "mem_probe",
                        "entries": [
                            {
                                "metric": "converge peak HBM bytes (tpu-csr)",
                                "peak_hbm_bytes": peak,
                                "unit": "bytes",
                            },
                            {
                                "metric": (
                                    "per-shard converge peak HBM bytes "
                                    "(tpu-sharded:tpu-csr)"
                                ),
                                "peak_hbm_bytes_per_shard": per_shard,
                                "unit": "bytes",
                            },
                        ],
                    }
                )
            )
        out = tmp_path / "SENTINEL.json"
        rc = perf_sentinel.main(["--history", str(tmp_path), "--out", str(out)])
        assert rc == 1  # r02 regressed both series vs r01
        report = json.loads(out.read_text())
        assert {
            "converge peak HBM bytes (tpu-csr) :: peak_hbm_bytes",
            "per-shard converge peak HBM bytes (tpu-sharded:tpu-csr) "
            ":: peak_hbm_bytes_per_shard",
        } <= set(report["regressions"])

    def test_stable_mem_rounds_pass(self, tmp_path):
        for i in (1, 2):
            (tmp_path / f"MEM_r{i:02d}.json").write_text(
                json.dumps(
                    {
                        "n": i,
                        "tool": "mem_probe",
                        "entries": [
                            {
                                "metric": "converge peak HBM bytes (x)",
                                "peak_hbm_bytes": 500_000,
                                "unit": "bytes",
                            }
                        ],
                    }
                )
            )
        rc = perf_sentinel.main(
            ["--history", str(tmp_path), "--out", str(tmp_path / "S.json")]
        )
        assert rc == 0

    def test_committed_mem_round_feeds_the_gate(self, tmp_path):
        out = tmp_path / "SENTINEL.json"
        rc = perf_sentinel.main(["--history", str(REPO), "--out", str(out)])
        assert rc == 0
        report = json.loads(out.read_text())
        assert any("MEM_r01.json" in f for f in report["history_files"])
        assert any("peak_hbm_bytes" in k for k in report["series"])
        assert any("peak_hbm_bytes_per_shard" in k for k in report["series"])

    def test_mem_probe_report_is_not_skipped_as_artifact(self):
        """MEM rounds carry "tool" but no "findings", so the
        non-bench-artifact filter must NOT skip them — the COMM_PROBE
        parity the ISSUE names."""
        report = {"tool": "mem_probe", "ok": True, "entries": []}
        assert not perf_sentinel._is_non_bench_artifact(report)


class TestZkKernelSeries:
    def test_msm_rounds_feed_the_gate(self, tmp_path):
        """ISSUE 20: MSM_r*.json is in the default globs, its
        ``entries`` list is walked, and msm_points_per_s /
        ntt_butterflies_per_s gate downward while prove_seconds gates
        upward — per backend/size, since the metric string carries
        both."""
        rounds = [
            (50_000.0, 2_000_000.0, 8.0),
            (20_000.0, 800_000.0, 14.0),  # all three regressed
        ]
        for i, (msm, ntt, prove) in enumerate(rounds, start=1):
            (tmp_path / f"MSM_r{i:02d}.json").write_text(
                json.dumps(
                    {
                        "n": i,
                        "entries": [
                            {
                                "metric": "zk msm throughput (native, n=2^14, bn254 G1)",
                                "msm_points_per_s": msm,
                                "unit": "points/s",
                            },
                            {
                                "metric": "zk ntt throughput (native, n=2^14, fr)",
                                "ntt_butterflies_per_s": ntt,
                                "unit": "butterflies/s",
                            },
                            {
                                "metric": "plonk epoch prove wall (native, 5 peers)",
                                "prove_seconds": prove,
                                "unit": "seconds",
                            },
                        ],
                    }
                )
            )
        out = tmp_path / "SENTINEL.json"
        rc = perf_sentinel.main(["--history", str(tmp_path), "--out", str(out)])
        assert rc == 1
        report = json.loads(out.read_text())
        assert {
            "zk msm throughput (native, n=2^14, bn254 G1) :: msm_points_per_s",
            "zk ntt throughput (native, n=2^14, fr) :: ntt_butterflies_per_s",
            "plonk epoch prove wall (native, 5 peers) :: prove_seconds",
        } <= set(report["regressions"])

    def test_backend_series_never_cross_compare(self, tmp_path):
        """A slow graft round beside a fast native round is two
        different series (the backend is in the metric string), so
        neither regresses the other."""
        (tmp_path / "MSM_r01.json").write_text(
            json.dumps(
                {
                    "n": 1,
                    "entries": [
                        {
                            "metric": "zk msm throughput (native, n=2^10, bn254 G1)",
                            "msm_points_per_s": 100_000.0,
                            "unit": "points/s",
                        }
                    ],
                }
            )
        )
        (tmp_path / "MSM_r02.json").write_text(
            json.dumps(
                {
                    "n": 2,
                    "entries": [
                        {
                            "metric": "zk msm throughput (graft, n=2^10, bn254 G1)",
                            "msm_points_per_s": 150.0,
                            "unit": "points/s",
                        }
                    ],
                }
            )
        )
        rc = perf_sentinel.main(
            ["--history", str(tmp_path), "--out", str(tmp_path / "S.json")]
        )
        assert rc == 0

    def test_committed_msm_round_feeds_the_gate(self, tmp_path):
        out = tmp_path / "SENTINEL.json"
        rc = perf_sentinel.main(["--history", str(REPO), "--out", str(out)])
        assert rc == 0
        report = json.loads(out.read_text())
        assert any("MSM_r01.json" in f for f in report["history_files"])
        assert any("msm_points_per_s" in k for k in report["series"])
        assert any("ntt_butterflies_per_s" in k for k in report["series"])
