"""graftlint pass 8 (protocol_tpu.analysis.comm) — the ISSUE 9
acceptance suite.

Covers: the comm pass runs clean on the real tree with every
registered backend covered; the sharded composites are judged at TWO
problem scales whose byte budgets provably cannot absorb an O(E)
collective; donation survives all the way into the compiled module's
``input_output_alias`` table for every donating backend (the PR 3
regression pin, now at the executable level); the jaxpr-psum vs
lowered-all-reduce cross-check holds; the HLO walker parses the text
format correctly on hostile snippets; and dead comm/concurrency
waivers fail the gate (``stale-waiver``).

The seeded comm fixtures themselves are exercised by the parametrized
``tests/test_analysis.py::TestViolationFixtures`` (rule + file:line
against the ``# VIOLATION:`` markers) — this file pins their
registration and the CLI plumbing.
"""

import json

import pytest

from protocol_tpu.analysis import COMM_INVARIANTS, NON_JAX_BACKENDS
from protocol_tpu.analysis.__main__ import main as analysis_main
from protocol_tpu.analysis.comm import run_comm_pass
from protocol_tpu.analysis.comm.hlo_walk import parse_module, shape_bytes
from protocol_tpu.analysis.fixtures import FIXTURES
from protocol_tpu.trust.backend import registered_backends

#: Parameter index of the donated ``t0`` in each backend's converge
#: entry point — the regression pin for the PR 3 donation work, now
#: asserted against the compiled module, not the jaxpr.
DONATED_T0_PARAM = {
    "tpu-sparse": 3,
    "tpu-csr": 3,
    "tpu-windowed": 7,
    "tpu-sharded:tpu-csr": 3,
    "tpu-sharded:tpu-windowed": 7,
}


@pytest.fixture(scope="module")
def comm_report():
    """One full pass-8 run (module-scoped: compiles all six backends,
    the sharded pair at two scales)."""
    findings, section = run_comm_pass()
    return findings, section


class TestRealTree:
    def test_comm_pass_clean(self, comm_report):
        findings, _ = comm_report
        assert [f.render() for f in findings] == []

    def test_every_registered_backend_covered(self, comm_report):
        _, section = comm_report
        for name in registered_backends():
            assert name in section["backends"], name
            status = section["backends"][name]["status"]
            expected = "skipped" if name in NON_JAX_BACKENDS else "checked"
            assert status == expected, (name, status)

    def test_sharded_composites_checked_at_two_scales(self, comm_report):
        _, section = comm_report
        for name in ("tpu-sharded:tpu-csr", "tpu-sharded:tpu-windowed"):
            scales = section["backends"][name]["scales"]
            assert len(scales) == 2, name
            ns = [s["dims"]["n"] for s in scales]
            es = [s["dims"]["edges"] for s in scales]
            assert ns[1] == 2 * ns[0], ns  # N doubles...
            assert es[1] > 3.5 * es[0], es  # ...while E quadruples

    def test_exactly_one_psum_lowered_per_sharded_step(self, comm_report):
        """The pass-1 promise (psum_count=1) holds at the executable:
        one all-reduce, full replica group, inside the while body."""
        _, section = comm_report
        for name in ("tpu-sharded:tpu-csr", "tpu-sharded:tpu-windowed"):
            for scale in section["backends"][name]["scales"]:
                assert scale["jaxpr_psums"] == 1, (name, scale["scale"])
                assert scale["lowered_all_reduces"] == 1
                (op,) = [
                    c for c in scale["collectives"] if c["per_iteration"]
                ]
                assert op["kind"] == "all-reduce"
                assert op["replica_groups"] == "{{0,1,2,3,4,5,6,7}}"

    def test_single_device_backends_have_no_wire(self, comm_report):
        _, section = comm_report
        for name in ("tpu-dense", "tpu-sparse", "tpu-csr", "tpu-windowed"):
            for scale in section["backends"][name]["scales"]:
                assert scale["collectives"] == [], name
                assert scale["host_round_trips"] == [], name
                assert scale["bytes_per_iter"] == 0

    def test_byte_budget_is_o_boundary_plus_n_never_o_e(self, comm_report):
        """The ISSUE 9 acceptance: at BOTH scales, measured collective
        bytes fit the linear budget AND an O(E) collective (4 bytes/f32
        per edge) would NOT fit — the budget cannot absorb edge-scaled
        traffic at either scale, so no constant-padding can hide an
        O(E) lowering.  Measured volume itself must track N linearly
        across the scales."""
        _, section = comm_report
        for name in ("tpu-sharded:tpu-csr", "tpu-sharded:tpu-windowed"):
            scales = section["backends"][name]["scales"]
            for s in scales:
                assert s["bytes_per_iter"] <= s["budget_bytes"], (name, s)
                o_e_volume = 4 * s["dims"]["edges"]
                assert o_e_volume > s["budget_bytes"], (
                    f"{name} at {s['scale']}: the byte budget "
                    f"({s['budget_bytes']:.0f}) could absorb an O(E) "
                    f"all-reduce ({o_e_volume}) — tighten bytes_n/const"
                )
            ratio = scales[1]["bytes_per_iter"] / scales[0]["bytes_per_iter"]
            n_ratio = scales[1]["dims"]["n"] / scales[0]["dims"]["n"]
            assert ratio == pytest.approx(n_ratio), (name, ratio)

    def test_donation_survives_lowering(self, comm_report):
        """t0's donation materializes in the compiled module's
        input_output_alias for converge_sparse/csr/windowed and both
        sharded composites (the sharded runners donate since ISSUE 9)."""
        _, section = comm_report
        for name, param in DONATED_T0_PARAM.items():
            for scale in section["backends"][name]["scales"]:
                aliased = set(scale["input_output_alias"].values())
                assert param in aliased, (
                    f"{name}: t0 (param {param}) not in alias table "
                    f"{scale['input_output_alias']} at {scale['scale']}"
                )

    def test_budget_table_matches_registry(self):
        from protocol_tpu.analysis.zk_lowering import ensure_budgets

        zk_names = set(ensure_budgets())
        declared = set(COMM_INVARIANTS)
        registered = {
            n for n in registered_backends() if n not in NON_JAX_BACKENDS
        }
        assert declared == registered | zk_names

    def test_no_stale_comm_waivers(self, comm_report):
        _, section = comm_report
        assert section["stale_waivers"] == []


class TestRegistryGate:
    def test_undeclared_comm_budget_is_error(self):
        findings, section = run_comm_pass(backends=["tpu-quantum"])
        assert section["backends"]["tpu-quantum"]["status"] == "undeclared"
        assert [(f.rule, f.severity) for f in findings] == [
            ("undeclared-comm-budget", "error")
        ]


class TestFixturePlumbing:
    def test_comm_fixtures_registered(self):
        comm = {n for n, f in FIXTURES.items() if f.kind == "comm"}
        assert comm == {
            "surprise-all-gather",
            "comm-bytes-over-budget",
            "host-round-trip",
            "alias-dropped",
            "psum-lowering-mismatch",
        }

    def test_cli_exits_nonzero_on_comm_fixture(self, tmp_path):
        out = tmp_path / "fixture.json"
        rc = analysis_main(["--fixture", "alias-dropped", "--output", str(out)])
        assert rc == 1
        report = json.loads(out.read_text())
        assert report["findings"][0]["rule"] == "alias-dropped"
        assert report["findings"][0]["pass"] == "comm"


class TestHloWalk:
    """Parser units on hostile snippets (no compile)."""

    def test_shape_bytes(self):
        assert shape_bytes("f32[512]{0}") == 2048
        assert shape_bytes("f32[512,128]{1,0}") == 512 * 128 * 4
        assert shape_bytes("pred[1024]{0}") == 1024
        assert shape_bytes("f32[]") == 4
        assert shape_bytes("(f32[8]{0}, s32[])") == 36
        assert shape_bytes("token[]") == 0

    def test_collective_parse_with_metadata(self):
        text = (
            "HloModule jit_run, is_scheduled=true\n"
            "%all-reduce.4 = f32[512]{0} all-reduce(f32[512]{0} %call.2), "
            "channel_id=1, replica_groups={{0,1,2,3,4,5,6,7}}, "
            "use_global_device_ids=true, to_apply=%region_1.205, "
            'metadata={op_name="jit(run)/jit(main)/while/body/'
            'jit(shmap_body)/psum2" source_file="/repo/parallel/sharded.py" '
            "source_line=171}\n"
        )
        mod = parse_module(text)
        (op,) = mod.collectives
        assert op.kind == "all-reduce"
        assert op.bytes == 2048
        assert op.per_iteration
        assert op.replica_groups == "{{0,1,2,3,4,5,6,7}}"
        assert op.file == "/repo/parallel/sharded.py"
        assert op.line == 171

    def test_all_gather_bytes_use_output_shape(self):
        text = (
            "HloModule m\n"
            "%all-gather.1 = f32[16]{0} all-gather(f32[2]{0} %param), "
            "channel_id=1, replica_groups={{0,1}}, dimensions={0}\n"
        )
        (op,) = parse_module(text).collectives
        assert op.bytes == 64  # result f32[16], not operand f32[2]
        assert not op.per_iteration  # no while in (absent) op_name

    def test_async_start_done_counted_once(self):
        text = (
            "HloModule m\n"
            "%ar-start = f32[8]{0} all-reduce-start(f32[8]{0} %x), channel_id=1\n"
            "%ar-done = f32[8]{0} all-reduce-done(f32[8]{0} %ar-start)\n"
        )
        mod = parse_module(text)
        assert mod.kind_counts() == {"all-reduce": 1}

    def test_host_callback_flagged_device_custom_call_ignored(self):
        text = (
            "HloModule m\n"
            "%cc.1 = (f32[]) custom-call(s64[] %c, f32[8]{0} %x), "
            'custom_call_target="xla_python_cpu_callback", '
            "api_version=API_VERSION_STATUS_RETURNING\n"
            "%cc.2 = f32[8]{0} custom-call(f32[8]{0} %x), "
            'custom_call_target="TopK"\n'
        )
        mod = parse_module(text)
        assert [h.target for h in mod.host_calls] == ["xla_python_cpu_callback"]

    def test_alias_table_multi_pair(self):
        text = (
            "HloModule jit_f, is_scheduled=true, input_output_alias="
            "{ {0}: (3, {}, may-alias), {1}: (4, {}) }, "
            "entry_computation_layout={(f32[4]{0})->f32[4]{0}}\n"
        )
        mod = parse_module(text)
        assert mod.aliases == {0: 3, 1: 4}
        assert mod.aliased_params() == {3, 4}

    def test_no_alias_table(self):
        assert parse_module("HloModule jit_f, is_scheduled=true\n").aliases == {}


class TestStaleWaivers:
    """A dead waiver fails the gate in every run that evaluates its
    table — concurrency (pass 7) and comm (pass 8) alike."""

    def test_dead_concurrency_waiver_is_error(self):
        from protocol_tpu.analysis.concurrency.checker import (
            analyze_models,
            build_program_model,
        )
        from protocol_tpu.analysis.concurrency.waivers import Waiver

        dead = Waiver(
            rule="unguarded-rmw", file="gone.py", symbol="Ghost.attr",
            reason="the bug this waived was fixed",
        )
        findings, section, _ = analyze_models(
            build_program_model({"protocol_tpu/node/_x.py": "x = 1\n"}),
            (dead,),
        )
        assert [f.rule for f in findings] == ["stale-waiver"]
        assert all(f.severity == "error" for f in findings)
        assert section["stale_waivers"] == [
            {"symbol": "Ghost.attr", "rule": "unguarded-rmw",
             "reason": "the bug this waived was fixed"}
        ]

    def test_dead_comm_waiver_is_error(self, monkeypatch):
        from protocol_tpu.analysis.comm import checker as comm_checker
        from protocol_tpu.analysis.concurrency.waivers import Waiver

        dead = Waiver(
            rule="comm-bytes-budget", file="gone.py", symbol="ghost",
            reason="fixed",
        )
        monkeypatch.setattr(comm_checker, "COMM_WAIVERS", (dead,))
        live, waived, stale = comm_checker._apply_waivers([])
        assert live == [] and waived == []
        assert [s["symbol"] for s in stale] == ["ghost"]
        # and the pass turns it into an error finding:
        findings, section = comm_checker.run_comm_pass(backends=[])
        assert [f.rule for f in findings] == ["stale-waiver"]
        assert findings[0].severity == "error"


class TestManagerCommWarning:
    """Config-time pin check: a configured sharded backend without a
    COMM_INVARIANTS entry warns at Manager construction (mirror of the
    per-converge unpinned-kernel-budget warning)."""

    def _manager(self, backend):
        from protocol_tpu.node.manager import Manager, ManagerConfig

        return Manager(ManagerConfig(backend=backend, prover="commitment"))

    def test_pinned_sharded_backend_is_quiet(self, caplog):
        import logging

        with caplog.at_level(logging.WARNING, logger="protocol_tpu.node.manager"):
            self._manager("tpu-sharded:tpu-windowed")
        assert "COMM_INVARIANTS" not in caplog.text

    def test_unpinned_sharded_backend_warns(self, caplog, monkeypatch):
        import logging

        from protocol_tpu.analysis.budget import COMM_INVARIANTS as table
        from protocol_tpu.parallel import sharded  # noqa: F401  (declares)

        monkeypatch.delitem(table, "tpu-sharded:tpu-csr")
        with caplog.at_level(logging.WARNING, logger="protocol_tpu.node.manager"):
            self._manager("tpu-sharded")
        assert "COMM_INVARIANTS" in caplog.text

    def test_single_device_backend_never_comm_warns(self, caplog):
        import logging

        with caplog.at_level(logging.WARNING, logger="protocol_tpu.node.manager"):
            self._manager("tpu-csr")
        assert "COMM_INVARIANTS" not in caplog.text
