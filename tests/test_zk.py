"""ZK layer tests, following the reference's MockProver ladder
(SURVEY.md §4 tier 2-3): every gadget gets a positive and a tampered
negative check; the full EigenTrust circuit is checked against the
native kernel's output as its public instance."""

import pytest

from protocol_tpu.crypto import calculate_message_hash, field
from protocol_tpu.crypto.babyjubjub import B8, Point
from protocol_tpu.crypto.eddsa import SecretKey, sign
from protocol_tpu.crypto.poseidon import permute
from protocol_tpu.node.attestation import Attestation
from protocol_tpu.node.bootstrap import FIXED_SET, keyset_from_raw
from protocol_tpu.trust.native import power_iterate
from protocol_tpu.zk.circuit import EigenTrustCircuit, prove_epoch_statement
from protocol_tpu.zk.cs import ConstraintSystem
from protocol_tpu.zk.eddsa import EddsaChipset
from protocol_tpu.zk.gadgets import (
    Bits2NumChip,
    EdwardsChip,
    LessEqChip,
    PoseidonChip,
    PoseidonSpongeChip,
    SetChip,
    StdGate,
)

P = field.MODULUS


def fresh():
    cs = ConstraintSystem()
    return cs, StdGate(cs)


class TestStdGate:
    def test_add_mul_sub(self):
        cs, std = fresh()
        x, y = std.witness(7), std.witness(5)
        assert std.cell_value(std.add(x, y)) == 12
        assert std.cell_value(std.sub(x, y)) == 2
        assert std.cell_value(std.mul(x, y)) == 35
        assert std.cell_value(std.mul_add(x, y, std.witness(3))) == 38
        cs.assert_satisfied()

    def test_tampered_mul_fails(self):
        cs, std = fresh()
        out = std.mul(std.witness(3), std.witness(4))
        cs.trace[out.column][out.row] = 13  # lie about the product
        assert cs.verify()

    def test_is_zero_both_branches(self):
        cs, std = fresh()
        assert std.cell_value(std.is_zero(std.witness(0))) == 1
        assert std.cell_value(std.is_zero(std.witness(9))) == 0
        cs.assert_satisfied()

    def test_is_equal_select_and(self):
        cs, std = fresh()
        t = std.is_equal(std.witness(4), std.witness(4))
        f = std.is_equal(std.witness(4), std.witness(5))
        assert std.cell_value(t) == 1 and std.cell_value(f) == 0
        sel = std.select(t, std.witness(10), std.witness(20))
        assert std.cell_value(sel) == 10
        land = std.logical_and(t, f)
        assert std.cell_value(land) == 0
        cs.assert_satisfied()

    def test_select_non_boolean_cond_fails(self):
        cs, std = fresh()
        std.select(std.witness(2), std.witness(1), std.witness(0))
        assert cs.verify()  # booleanity violated

    def test_inverse(self):
        cs, std = fresh()
        inv = std.inverse(std.witness(1234))
        assert std.cell_value(inv) == field.inv(1234)
        cs.assert_satisfied()

    def test_inverse_of_zero_unsatisfiable(self):
        cs, std = fresh()
        std.inverse(std.witness(0))
        assert cs.verify()

    def test_constant_binding(self):
        cs, std = fresh()
        c = std.constant(42)
        assert std.cell_value(c) == 42
        cs.assert_satisfied()
        cs.trace[c.column][c.row] = 43
        assert cs.verify()


class TestBits2Num:
    def test_decompose_and_recompose(self):
        cs, std = fresh()
        b2n = Bits2NumChip(cs)
        bits = b2n.decompose(std.witness(0b1011001), 8)
        assert [cs.value(b.column, b.row) for b in bits] == [1, 0, 0, 1, 1, 0, 1, 0]
        cs.assert_satisfied()

    def test_value_too_wide_fails(self):
        cs, std = fresh()
        Bits2NumChip(cs).decompose(std.witness(300), 8)
        assert cs.verify()  # 300 needs 9 bits

    def test_flipped_bit_fails(self):
        cs, std = fresh()
        bits = Bits2NumChip(cs).decompose(std.witness(6), 4)
        cs.trace[bits[0].column][bits[0].row] = 1
        assert cs.verify()

    def test_adversarial_acc_shift_fails(self):
        """Soundness: shifting every accumulator cell by a constant and
        forging the bits must be caught by the init-row constraint."""
        cs, std = fresh()
        b2n = Bits2NumChip(cs)
        bits = b2n.decompose(std.witness(6), 4)
        delta = (6 - 15) % P
        first_row = bits[0].row
        for r in range(first_row, first_row + 4):
            cs.trace[b2n.bit][r] = 1
        for r in range(first_row, first_row + 5):
            cs.trace[b2n.acc][r] = (cs.trace[b2n.acc].get(r, 0) + delta) % P
        # restore final acc to match the copied value cell
        cs.trace[b2n.acc][first_row + 4] = 6
        assert cs.verify(), "forged decomposition must not satisfy"


class TestLessEq:
    def test_le_holds(self):
        cs, std = fresh()
        chip = LessEqChip(cs, std, Bits2NumChip(cs))
        chip.assert_le(std.witness(100), std.witness(200))
        chip.assert_le(std.witness(200), std.witness(200))
        cs.assert_satisfied()

    def test_gt_fails(self):
        cs, std = fresh()
        LessEqChip(cs, std, Bits2NumChip(cs)).assert_le(
            std.witness(201), std.witness(200)
        )
        assert cs.verify()

    def test_wraparound_operand_fails(self):
        """Soundness: a near-modulus operand must not pass via mod-P
        wraparound of the shifted difference."""
        from protocol_tpu.crypto.babyjubjub import SUBORDER

        cs, std = fresh()
        LessEqChip(cs, std, Bits2NumChip(cs)).assert_le(
            std.witness(P - 1), std.witness(SUBORDER)
        )
        assert cs.verify(), "P-1 <= SUBORDER must not be satisfiable"


class TestSetChip:
    def test_membership(self):
        cs, std = fresh()
        chip = SetChip(std)
        items = [std.witness(v) for v in (5, 9, 11)]
        chip.assert_member(std.witness(9), items)
        assert std.cell_value(chip.is_member(std.witness(11), items)) == 1
        assert std.cell_value(chip.is_member(std.witness(10), items)) == 0
        cs.assert_satisfied()

    def test_non_member_assert_fails(self):
        cs, std = fresh()
        SetChip(std).assert_member(std.witness(3), [std.witness(1), std.witness(2)])
        assert cs.verify()


class TestPoseidonChip:
    def test_permute_matches_native(self):
        cs, std = fresh()
        chip = PoseidonChip(cs)
        inputs = [std.witness(v) for v in (0, 1, 2, 3, 4)]
        out = chip.permute(inputs)
        native = permute([0, 1, 2, 3, 4])
        assert [cs.value(c.column, c.row) for c in out] == native
        cs.assert_satisfied()

    def test_tampered_round_fails(self):
        cs, std = fresh()
        chip = PoseidonChip(cs)
        out = chip.permute([std.witness(v) for v in (0, 1, 2, 3, 4)])
        cs.trace[out[0].column][out[0].row - 30] += 1  # corrupt a mid round
        assert cs.verify()

    def test_sponge_matches_native(self):
        from protocol_tpu.crypto.poseidon import PoseidonSponge

        cs, std = fresh()
        chip = PoseidonSpongeChip(cs, std, PoseidonChip(cs))
        values = list(range(1, 11))
        out = chip.squeeze([std.witness(v) for v in values])
        native = PoseidonSponge()
        native.update(values)
        assert cs.value(out.column, out.row) == native.squeeze()
        cs.assert_satisfied()


class TestEdwardsChip:
    def test_scalar_mul_matches_native(self):
        cs, std = fresh()
        chip = EdwardsChip(cs)
        k = 0xDEADBEEFCAFE1234567
        native = B8.mul_scalar(k).affine()
        one = std.constant(1)
        out = chip.scalar_mul((std.constant(B8.x), std.constant(B8.y), one), std.witness(k))
        zinv = field.inv(cs.value(out[2].column, out[2].row))
        x = field.mul(cs.value(out[0].column, out[0].row), zinv)
        y = field.mul(cs.value(out[1].column, out[1].row), zinv)
        assert (x, y) == (native.x, native.y)
        cs.assert_satisfied()

    def test_scalar_mismatch_fails(self):
        cs, std = fresh()
        chip = EdwardsChip(cs)
        one = std.constant(1)
        sc = std.witness(99)
        chip.scalar_mul((std.constant(B8.x), std.constant(B8.y), one), sc)
        cs.trace[sc.column][sc.row] = 98  # claimed scalar differs from bits
        assert cs.verify()

    def test_scalar_plus_p_aliasing_rejected_in_strict_mode(self):
        """Soundness: a bit pattern encoding scalar+P recomposes to the
        same field element but multiplies by a different integer; the
        strict (< P) check must reject the forged ladder."""
        from protocol_tpu.zk.gadgets import LessEqChip

        cs, std = fresh()
        chip = EdwardsChip(cs)
        b2n = Bits2NumChip(cs)
        lessq = LessEqChip(cs, std, b2n)
        one = std.constant(1)
        k = 12345
        sc = std.witness(k)
        out = chip.scalar_mul(
            (std.constant(B8.x), std.constant(B8.y), one),
            sc,
            n_bits=254,
            strict=True,
            std=std,
            lessq=lessq,
        )
        cs.assert_satisfied()

        # Forge the whole region as an honest ladder for k+P: rebuild a
        # second strict scalar_mul whose *witness* value is k+P but whose
        # copy target claims k.
        cs2, std2 = fresh()
        chip2 = EdwardsChip(cs2)
        b2n2 = Bits2NumChip(cs2)
        lessq2 = LessEqChip(cs2, std2, b2n2)
        one2 = std2.constant(1)
        sc2 = std2.witness(k + P)  # witness() stores the raw int mod P...
        # emulate the adversary: assign the cell value k (mod P) but run
        # the ladder over the k+P bit pattern by patching the stored
        # value before synthesis
        cs2.trace[sc2.column][sc2.row] = k + P  # un-reduced alias
        chip2.scalar_mul(
            (std2.constant(B8.x), std2.constant(B8.y), one2),
            sc2,
            n_bits=254,
            strict=True,
            std=std2,
            lessq=lessq2,
        )
        cs2.trace[sc2.column][sc2.row] = k  # the claimed canonical scalar
        assert cs2.verify(), "k+P bit pattern must not satisfy strict mode"

    def test_add_points_matches_native(self):
        cs, std = fresh()
        chip = EdwardsChip(cs)
        one = std.constant(1)
        p1 = B8.mul_scalar(5).affine()
        p2 = B8.mul_scalar(7).affine()
        native = p1.projective().add(p2.projective()).affine()
        out = chip.add_points(
            (std.constant(p1.x), std.constant(p1.y), one),
            (std.constant(p2.x), std.constant(p2.y), one),
        )
        zinv = field.inv(cs.value(out[2].column, out[2].row))
        assert field.mul(cs.value(out[0].column, out[0].row), zinv) == native.x
        assert field.mul(cs.value(out[1].column, out[1].row), zinv) == native.y
        cs.assert_satisfied()


class TestEddsaChipset:
    def _chipset(self, cs, std):
        return EddsaChipset(
            cs, std, EdwardsChip(cs), PoseidonChip(cs), Bits2NumChip(cs)
        )

    def test_valid_signature(self):
        cs, std = fresh()
        sk = SecretKey.random()
        pk = sk.public()
        m = 123456789
        sig = sign(sk, pk, m)
        self._chipset(cs, std).verify(
            (std.witness(pk.point.x), std.witness(pk.point.y)),
            (std.witness(sig.big_r.x), std.witness(sig.big_r.y)),
            std.witness(sig.s),
            std.witness(m),
        )
        cs.assert_satisfied()

    def test_wrong_message_fails(self):
        cs, std = fresh()
        sk = SecretKey.random()
        pk = sk.public()
        sig = sign(sk, pk, 111)
        self._chipset(cs, std).verify(
            (std.witness(pk.point.x), std.witness(pk.point.y)),
            (std.witness(sig.big_r.x), std.witness(sig.big_r.y)),
            std.witness(sig.s),
            std.witness(222),
        )
        assert cs.verify()

    def test_oversized_s_fails(self):
        from protocol_tpu.crypto.babyjubjub import SUBORDER

        cs, std = fresh()
        sk = SecretKey.random()
        pk = sk.public()
        sig = sign(sk, pk, 5)
        self._chipset(cs, std).verify(
            (std.witness(pk.point.x), std.witness(pk.point.y)),
            (std.witness(sig.big_r.x), std.witness(sig.big_r.y)),
            std.witness(sig.s + SUBORDER + 1),
            std.witness(5),
        )
        assert cs.verify()


def build_attestations(scores_rows):
    sks, pks = keyset_from_raw(FIXED_SET)
    _, messages = calculate_message_hash(pks, scores_rows)
    atts = []
    for sk, pk, msg, row in zip(sks, pks, messages, scores_rows):
        atts.append(
            Attestation(sig=sign(sk, pk, msg), pk=pk, neighbours=list(pks), scores=row)
        )
    return atts


class TestEigenTrustCircuit:
    def test_full_circuit_against_native(self):
        """The tier-3 check (circuit.rs:488-554): the native kernel's
        output is the satisfied circuit's public instance."""
        scores_rows = [[200] * 5 for _ in range(5)]
        atts = build_attestations(scores_rows)
        pub = power_iterate([1000] * 5, scores_rows, 10, 1000)
        cs = prove_epoch_statement(atts, pub)
        stats = cs.stats()
        assert stats["rows"] > 5000  # non-trivial statement

    def test_wrong_instance_fails(self):
        scores_rows = [[200] * 5 for _ in range(5)]
        atts = build_attestations(scores_rows)
        pub = power_iterate([1000] * 5, scores_rows, 10, 1000)
        pub[0] = field.add(pub[0], 1)
        with pytest.raises(AssertionError, match="not satisfied"):
            prove_epoch_statement(atts, pub)

    def test_tampered_ops_fails_signature(self):
        """Changing a score after signing breaks the message hash →
        EdDSA constraints fail."""
        scores_rows = [[200] * 5 for _ in range(5)]
        atts = build_attestations(scores_rows)
        atts[0].scores[1] = 300
        atts[0].scores[2] = 100  # keep row sum for the trust math
        pub = power_iterate(
            [1000] * 5, [a.scores for a in atts], 10, 1000
        )
        with pytest.raises(AssertionError, match="not satisfied"):
            prove_epoch_statement(atts, pub)

    def test_heterogeneous_scores(self):
        scores_rows = [
            [0, 300, 100, 300, 300],
            [200, 0, 300, 200, 300],
            [500, 100, 0, 300, 100],
            [300, 300, 300, 0, 100],
            [250, 250, 250, 250, 0],
        ]
        atts = build_attestations(scores_rows)
        pub = power_iterate([1000] * 5, scores_rows, 10, 1000)
        cs = prove_epoch_statement(atts, pub)
        assert not cs.verify()
