"""C++ runtime parity tests: the native library must be bit-identical
to the Python crypto layer (the rebuild's native↔python duality,
SURVEY.md §1)."""

import random

import pytest

from protocol_tpu.crypto import native as cnative
from protocol_tpu.crypto import field
from protocol_tpu.crypto.eddsa import SecretKey, sign
from protocol_tpu.crypto.poseidon import permute

pytestmark = pytest.mark.skipif(
    not cnative.available(), reason="native library unavailable (no compiler?)"
)


class TestPoseidonParity:
    def test_batch_matches_python(self):
        rng = random.Random(11)
        inputs = [
            [rng.randrange(field.MODULUS) for _ in range(5)] for _ in range(8)
        ]
        outs = cnative.poseidon_permute_batch(inputs)
        for row, out in zip(inputs, outs):
            assert out == permute(row)

    def test_edge_values(self):
        inputs = [[0, 0, 0, 0, 0], [field.MODULUS - 1] * 5, [1, 0, field.MODULUS - 1, 2, 3]]
        outs = cnative.poseidon_permute_batch(inputs)
        for row, out in zip(inputs, outs):
            assert out == permute(row)

    def test_pk_hash_batch(self):
        sks = [SecretKey.random() for _ in range(4)]
        pks = [sk.public() for sk in sks]
        hashes = cnative.pk_hash_batch(
            [pk.point.x for pk in pks], [pk.point.y for pk in pks]
        )
        assert hashes == [pk.hash() for pk in pks]


class TestEddsaParity:
    def test_batch_verify_mixed(self):
        sks = [SecretKey.random() for _ in range(5)]
        pks = [sk.public() for sk in sks]
        msgs = [100 + i for i in range(5)]
        sigs = [sign(sk, pk, m) for sk, pk, m in zip(sks, pks, msgs)]
        # Corrupt #1 (message) and #3 (s).
        msgs_in = list(msgs)
        msgs_in[1] += 1
        s_in = [sig.s for sig in sigs]
        s_in[3] = field.add(s_in[3], 1)
        ok = cnative.eddsa_verify_batch(
            [s.big_r.x for s in sigs],
            [s.big_r.y for s in sigs],
            s_in,
            [pk.point.x for pk in pks],
            [pk.point.y for pk in pks],
            msgs_in,
        )
        assert ok.tolist() == [True, False, True, False, True]

    def test_oversized_s_rejected(self):
        from protocol_tpu.crypto.babyjubjub import SUBORDER

        sk = SecretKey.random()
        pk = sk.public()
        sig = sign(sk, pk, 7)
        ok = cnative.eddsa_verify_batch(
            [sig.big_r.x], [sig.big_r.y], [sig.s + SUBORDER + 1],
            [pk.point.x], [pk.point.y], [7],
        )
        assert not ok[0]


class TestBulkIngest:
    def test_bulk_matches_single(self):
        from protocol_tpu.node.manager import Manager
        from tests.test_node import make_attestation

        good = make_attestation(0)
        bad_sig = make_attestation(1)
        bad_sig.sig = sign(SecretKey.random(), SecretKey.random().public(), 1)
        bad_sum = make_attestation(2, scores=[1, 0, 0, 0, 0])

        m = Manager()
        results = m.add_attestations_bulk([good, bad_sig, bad_sum])
        # IngestResult truthiness mirrors acceptance; rejections carry
        # the structural/signature reason the metric is labelled with.
        assert [bool(r) for r in results] == [True, False, False]
        assert [r.reason for r in results] == [
            None,
            "bad-signature",
            "non-conserving-scores",
        ]
        assert len(m.attestations) == 1
