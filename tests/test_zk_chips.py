"""Range-lookup, Merkle-path and Rescue-Prime chip tests (parity with
gadgets/range.rs, merkle_tree/mod.rs, rescue_prime/mod.rs test
coverage)."""

from protocol_tpu.crypto import field
from protocol_tpu.crypto.merkle import MerkleTree, Path
from protocol_tpu.crypto.poseidon import rescue_prime_permute
from protocol_tpu.zk.chips import MerklePathChip, RangeCheckChip, RescuePrimeChip
from protocol_tpu.zk.cs import ConstraintSystem
from protocol_tpu.zk.gadgets import PoseidonChip, StdGate

P = field.MODULUS


def fresh():
    cs = ConstraintSystem()
    return cs, StdGate(cs)


class TestRangeCheck:
    def test_word_in_range(self):
        cs, std = fresh()
        chip = RangeCheckChip(cs)
        chip.assert_word(std.witness(255))
        chip.assert_word(std.witness(0))
        cs.assert_satisfied()

    def test_word_out_of_range(self):
        cs, std = fresh()
        RangeCheckChip(cs).assert_word(std.witness(256))
        assert cs.verify()

    def test_running_sum_range(self):
        cs, std = fresh()
        chip = RangeCheckChip(cs)
        chip.assert_range(std.witness(0xABCDEF), 3)  # < 2^24
        cs.assert_satisfied()

    def test_running_sum_too_wide(self):
        cs, std = fresh()
        RangeCheckChip(cs).assert_range(std.witness(1 << 24), 3)
        assert cs.verify()

    def test_two_widths_coexist_soundly(self):
        """Regression: a second chip with a different word size must get
        its own table, not silently share the first one's."""
        cs, std = fresh()
        RangeCheckChip(cs, word_bits=16).assert_word(std.witness(300))
        RangeCheckChip(cs, word_bits=8).assert_word(std.witness(300))
        failures = cs.verify()
        assert failures and "rng8" in failures[0].gate

    def test_forged_words_fail(self):
        cs, std = fresh()
        chip = RangeCheckChip(cs)
        chip.assert_range(std.witness(77), 2)
        # Shift every acc cell: init gate must catch it.
        rows = sorted(cs.selectors["rng8_sum"])
        for r in rows + [rows[-1] + 1]:
            cs.trace[chip.acc][r] = (cs.trace[chip.acc].get(r, 0) + 5) % P
        cs.trace[chip.acc][rows[-1] + 1] = 77
        assert cs.verify()


class TestMerklePathChip:
    def _tree_and_path(self):
        leaves = [7, 11, 13, 17, 19, 23, 29, 31]
        tree = MerkleTree.build(leaves, 3)
        return tree, Path.find(tree, 13)

    def test_valid_path(self):
        tree, path = self._tree_and_path()
        cs, std = fresh()
        chip = MerklePathChip(cs, std, PoseidonChip(cs))
        pairs = [
            (std.witness(left), std.witness(right))
            for left, right in path.pairs[:-1]
        ]
        chip.verify_path(std.witness(13), pairs, std.witness(tree.root))
        cs.assert_satisfied()

    def test_wrong_value_fails(self):
        tree, path = self._tree_and_path()
        cs, std = fresh()
        chip = MerklePathChip(cs, std, PoseidonChip(cs))
        pairs = [
            (std.witness(left), std.witness(right))
            for left, right in path.pairs[:-1]
        ]
        chip.verify_path(std.witness(14), pairs, std.witness(tree.root))
        assert cs.verify()

    def test_wrong_root_fails(self):
        tree, path = self._tree_and_path()
        cs, std = fresh()
        chip = MerklePathChip(cs, std, PoseidonChip(cs))
        pairs = [
            (std.witness(left), std.witness(right))
            for left, right in path.pairs[:-1]
        ]
        chip.verify_path(std.witness(13), pairs, std.witness(tree.root + 1))
        assert cs.verify()

    def test_tampered_sibling_fails(self):
        tree, path = self._tree_and_path()
        cs, std = fresh()
        chip = MerklePathChip(cs, std, PoseidonChip(cs))
        tampered = [list(p) for p in path.pairs[:-1]]
        tampered[1][0] += 1
        pairs = [
            (std.witness(left), std.witness(right)) for left, right in tampered
        ]
        chip.verify_path(std.witness(13), pairs, std.witness(tree.root))
        assert cs.verify()


class TestRescuePrimeChip:
    def test_permute_matches_native(self):
        cs, std = fresh()
        chip = RescuePrimeChip(cs)
        inputs = [std.witness(v) for v in (0, 1, 2, 3, 4)]
        out = chip.permute(inputs)
        native = rescue_prime_permute([0, 1, 2, 3, 4])
        assert [cs.value(c.column, c.row) for c in out] == native
        cs.assert_satisfied()

    def test_tampered_mid_witness_fails(self):
        cs, std = fresh()
        chip = RescuePrimeChip(cs)
        chip.permute([std.witness(v) for v in (5, 6, 7, 8, 9)])
        rows = sorted(cs.selectors["rp5_round"])
        cs.trace[chip.mid[2]][rows[3]] += 1
        assert cs.verify()
