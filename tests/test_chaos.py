"""Fault-injection framework (protocol_tpu/chaos/) — ISSUE 14.

Covers: the disabled default (one module-attribute read, engine never
touched), declarative registry enumeration, deterministic trigger
semantics (after / times / seeded p), every fault kind (crash via a
subprocess — the in-process tests can't survive ``os._exit`` — delay,
io-error, rpc-error, torn writes through ``corrupt`` and
``wrap_file``), counting mode, and env-var configuration.
"""

from __future__ import annotations

import errno
import io
import json
import subprocess
import sys
import time

import pytest

from protocol_tpu import chaos


@pytest.fixture(autouse=True)
def _reset_chaos():
    yield
    chaos.reset()


class TestDisabledDefault:
    def test_inactive_by_default(self):
        assert chaos.ACTIVE is False

    def test_fire_without_schedule_is_noop(self):
        # The guarded call shape: sites never even reach fire() when
        # inactive, but an unguarded call must still be harmless.
        chaos.fire("wal.post_append")

    def test_registry_lists_declared_points(self):
        # Importing the node modules registers their fault points.
        import protocol_tpu.node.checkpoint  # noqa: F401
        import protocol_tpu.node.ethereum  # noqa: F401
        import protocol_tpu.node.server  # noqa: F401
        import protocol_tpu.node.wal  # noqa: F401

        points = chaos.registry()
        for expected in (
            "wal.append",
            "wal.post_append",
            "wal.replay",
            "checkpoint.write",
            "checkpoint.pre_rename",
            "checkpoint.post_save",
            "ingest.pre_apply",
            "epoch.post_converge",
            "prover.pre_enqueue",
            "rpc.get_logs",
            "rpc.block_number",
        ):
            assert expected in points, expected


class TestTriggers:
    def test_after_fires_on_exact_hit(self):
        chaos.configure(
            {"seed": 1, "faults": [{"point": "p", "kind": "io-error", "after": 3}]}
        )
        chaos.fire("p")
        chaos.fire("p")
        with pytest.raises(OSError) as exc:
            chaos.fire("p")
        assert exc.value.errno == errno.ENOSPC
        chaos.fire("p")  # hit 4: past the schedule

    def test_times_fires_on_first_n_hits(self):
        chaos.configure(
            {"seed": 1, "faults": [{"point": "p", "kind": "rpc-error", "times": 2}]}
        )
        for _ in range(2):
            with pytest.raises(chaos.ChaosRpcError):
                chaos.fire("p")
        chaos.fire("p")  # hit 3 passes

    def test_probability_is_seed_deterministic(self):
        def draws(seed: int) -> list[bool]:
            chaos.configure(
                {"seed": seed, "faults": [{"point": "p", "kind": "io-error", "p": 0.5}]}
            )
            out = []
            for _ in range(32):
                try:
                    chaos.fire("p")
                    out.append(False)
                except OSError:
                    out.append(True)
            return out

        a, b = draws(7), draws(7)
        assert a == b, "same seed must replay the same schedule"
        assert any(a) and not all(a), "p=0.5 over 32 hits should mix"
        assert draws(8) != a, "a different seed should re-roll"

    def test_custom_errno(self):
        chaos.configure(
            {
                "seed": 1,
                "faults": [{"point": "p", "kind": "io-error", "errno": "EIO"}],
            }
        )
        with pytest.raises(OSError) as exc:
            chaos.fire("p")
        assert exc.value.errno == errno.EIO

    def test_delay_sleeps(self):
        chaos.configure(
            {"seed": 1, "faults": [{"point": "p", "kind": "delay", "delay_s": 0.05}]}
        )
        t0 = time.perf_counter()
        chaos.fire("p")
        assert time.perf_counter() - t0 >= 0.04

    def test_counting_mode_tracks_hits_without_faults(self):
        chaos.configure({"seed": 0, "faults": []})
        assert chaos.ACTIVE
        chaos.fire("a")
        chaos.fire("a")
        chaos.fire("b")
        assert chaos.hits() == {"a": 2, "b": 1}


class TestTornWrites:
    def test_corrupt_truncates_at_byte_k(self):
        chaos.configure(
            {
                "seed": 1,
                "faults": [
                    {
                        "point": "w",
                        "kind": "torn",
                        "at": 4,
                        "after": 1,
                        "then_crash": False,
                    }
                ],
            }
        )
        assert chaos.corrupt("w", b"0123456789") == b"0123"
        # Only the scheduled hit tears; the next write is whole.
        assert chaos.corrupt("w", b"0123456789") == b"0123456789"

    def test_wrap_file_drops_past_k(self):
        chaos.configure(
            {
                "seed": 1,
                "faults": [
                    {"point": "w", "kind": "torn", "at": 6, "then_crash": False}
                ],
            }
        )
        buf = io.BytesIO()
        f = chaos.wrap_file("w", buf)
        f.write(b"0123")
        f.write(b"456789")  # claims success, silently drops past byte 6
        assert buf.getvalue() == b"012345"

    def test_wrap_file_without_schedule_passes_through(self):
        chaos.configure({"seed": 1, "faults": []})
        buf = io.BytesIO()
        assert chaos.wrap_file("w", buf) is buf


class TestCrash:
    def _run(self, spec: dict, body: str) -> int:
        code = (
            "from protocol_tpu import chaos\n"
            f"chaos.configure({spec!r})\n" + body
        )
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            timeout=120,
            cwd=str(__import__("pathlib").Path(__file__).resolve().parent.parent),
        )
        return proc.returncode

    def test_crash_exits_with_chaos_code(self):
        rc = self._run(
            {"seed": 1, "faults": [{"point": "p", "kind": "crash", "after": 2}]},
            "chaos.fire('p')\nchaos.fire('p')\nprint('unreachable')\n",
        )
        assert rc == chaos.CRASH_EXIT_CODE

    def test_torn_then_crash_arms_next_fire(self):
        rc = self._run(
            {"seed": 1, "faults": [{"point": "w", "kind": "torn", "at": 2}]},
            "out = chaos.corrupt('w', b'abcdef')\n"
            "assert out == b'ab', out\n"
            "chaos.fire('x')\nprint('unreachable')\n",
        )
        assert rc == chaos.CRASH_EXIT_CODE

    def test_env_var_configures(self):
        spec = json.dumps(
            {"seed": 1, "faults": [{"point": "p", "kind": "crash"}]}
        )
        import os

        proc = subprocess.run(
            [
                sys.executable,
                "-c",
                "from protocol_tpu import chaos\nchaos.fire('p')\n",
            ],
            env={**os.environ, "PROTOCOL_TPU_CHAOS": spec},
            capture_output=True,
            timeout=120,
            cwd=str(__import__("pathlib").Path(__file__).resolve().parent.parent),
        )
        assert proc.returncode == chaos.CRASH_EXIT_CODE
