"""Node layer tests: attestation codec, manager validation, epoch, errors,
request handler — mirroring server/src tests (SURVEY.md §4 tier 6)."""

import asyncio
import json

import numpy as np
import pytest

from protocol_tpu.crypto import calculate_message_hash, field
from protocol_tpu.crypto.eddsa import PublicKey, SecretKey, Signature, sign
from protocol_tpu.node.attestation import Attestation, AttestationData
from protocol_tpu.node.bootstrap import (
    FIXED_SET,
    INITIAL_SCORE,
    NUM_NEIGHBOURS,
    keyset_from_raw,
    read_bootstrap_csv,
)
from protocol_tpu.node.config import ProtocolConfig
from protocol_tpu.node.epoch import Epoch
from protocol_tpu.node.errors import EigenError, EigenErrorCode
from protocol_tpu.node.ethereum import AttestationCreatedEvent, FixtureEventSource
from protocol_tpu.node.manager import Manager, ManagerConfig
from protocol_tpu.node.server import handle_request
from protocol_tpu.zk.proof import ProofRaw


class TestEpoch:
    def test_display(self):
        assert str(Epoch(123)) == "Epoch(123)"

    def test_next_previous(self):
        assert Epoch(1).next() == Epoch(2)
        assert Epoch(1).previous() == Epoch(0)

    def test_be_bytes(self):
        assert Epoch(0).to_be_bytes() == bytes(8)
        assert Epoch.from_be_bytes(Epoch(77).to_be_bytes()) == Epoch(77)

    def test_current(self):
        import time

        interval = 10
        assert Epoch.current_epoch(interval).number == int(time.time()) // interval

    def test_secs_until_next(self):
        secs = Epoch.secs_until_next_epoch(10)
        assert 0 < secs <= 10


class TestErrors:
    def test_codes_stable_both_directions(self):
        # server/src/error.rs:27-56
        expected = {
            EigenErrorCode.INVALID_BOOTSTRAP_PUBKEY: 0,
            EigenErrorCode.PROVING_ERROR: 1,
            EigenErrorCode.VERIFICATION_ERROR: 2,
            EigenErrorCode.CONNECTION_ERROR: 3,
            EigenErrorCode.LISTEN_ERROR: 4,
            EigenErrorCode.ATTESTATION_NOT_FOUND: 5,
            EigenErrorCode.PROOF_NOT_FOUND: 6,
            EigenErrorCode.INVALID_ATTESTATION: 7,
            EigenErrorCode.UNKNOWN: 255,
        }
        for code, value in expected.items():
            assert code.value == value
            assert EigenErrorCode.from_u8(value) == code
        assert EigenErrorCode.from_u8(99) == EigenErrorCode.UNKNOWN


def make_attestation(sender_idx=0, scores=None):
    sks, pks = keyset_from_raw(FIXED_SET)
    scores = scores or [200] * NUM_NEIGHBOURS
    _, msgs = calculate_message_hash(pks, [scores])
    sig = sign(sks[sender_idx], pks[sender_idx], msgs[0])
    return Attestation(sig=sig, pk=pks[sender_idx], neighbours=list(pks), scores=scores)


class TestAttestationCodec:
    def test_roundtrip(self):
        att = make_attestation()
        data = AttestationData.from_attestation(att)
        raw = data.to_bytes()
        # Fixed layout: 32 bytes × (3 sig + 2 pk + 2N neighbours + N scores)
        assert len(raw) == 32 * (5 + 3 * NUM_NEIGHBOURS)
        decoded = AttestationData.from_bytes(raw, NUM_NEIGHBOURS).to_attestation(NUM_NEIGHBOURS)
        assert decoded.pk == att.pk
        assert decoded.sig == att.sig
        assert decoded.neighbours == att.neighbours
        assert decoded.scores == att.scores

    def test_zero_attestation_decodes(self):
        # attestation.rs:143-168: all-zero payload is representable.
        raw = bytes(32 * (5 + 3 * NUM_NEIGHBOURS))
        att = AttestationData.from_bytes(raw, NUM_NEIGHBOURS).to_attestation(NUM_NEIGHBOURS)
        assert att.pk == PublicKey.null()
        assert att.scores == [0] * NUM_NEIGHBOURS

    def test_misaligned_rejected(self):
        with pytest.raises(ValueError):
            AttestationData.from_bytes(b"\x00" * 31, NUM_NEIGHBOURS)

    def test_short_scores_zero_filled(self):
        att = make_attestation()
        data = AttestationData.from_attestation(att)
        data.scores = data.scores[:2]
        decoded = data.to_attestation(NUM_NEIGHBOURS)
        assert decoded.scores[2:] == [0] * (NUM_NEIGHBOURS - 2)


class TestManager:
    def test_add_valid_attestation(self):
        m = Manager()
        m.add_attestation(make_attestation())
        assert len(m.attestations) == 1

    def test_reject_wrong_group(self):
        """Rejections come back as IngestResult reason codes — the same
        shape as the bulk path (ISSUE 7 satellite), not exceptions."""
        m = Manager()
        att = make_attestation()
        att.neighbours = list(reversed(att.neighbours))
        result = m.add_attestation(att)
        assert (result.accepted, result.reason) == (False, "group-mismatch")
        assert len(m.attestations) == 0

    def test_reject_outsider_sender(self):
        m = Manager()
        att = make_attestation()
        outsider = SecretKey.random()
        _, msgs = calculate_message_hash(att.neighbours, [att.scores])
        att.sig = sign(outsider, outsider.public(), msgs[0])
        att.pk = outsider.public()
        assert m.add_attestation(att).reason == "sender-not-in-group"

    def test_reject_non_conserving_scores(self):
        """A validly-signed row not summing to SCALE would poison every
        epoch proof (conservation gate); rejected at ingest."""
        m = Manager()
        att = make_attestation(scores=[999, 0, 0, 0, 0])
        assert m.add_attestation(att).reason == "non-conserving-scores"

    def test_reject_bad_signature(self):
        m = Manager()
        att = make_attestation()
        att.sig = Signature(att.sig.big_r, field.add(att.sig.s, 1))
        assert m.add_attestation(att).reason == "bad-signature"

    def test_single_and_bulk_verdicts_identical(self):
        m = Manager()
        good, bad = make_attestation(), make_attestation()
        bad.sig = Signature(bad.sig.big_r, field.add(bad.sig.s, 1))
        single = [m.add_attestation(good), Manager().add_attestation(bad)]
        bulk = Manager().add_attestations_bulk([good, bad])
        assert [(r.accepted, r.reason) for r in single] == [
            (r.accepted, r.reason) for r in bulk
        ]

    def test_get_attestation(self):
        m = Manager()
        att = make_attestation()
        m.add_attestation(att)
        assert m.get_attestation(att.pk) is att
        with pytest.raises(EigenError):
            m.get_attestation(SecretKey.random().public())

    def test_should_calculate_proof(self):
        """manager/mod.rs:246-262: initial attestations converge to the
        initial scores."""
        m = Manager(ManagerConfig(prover="commitment"))
        m.generate_initial_attestations()
        epoch = Epoch(0)
        m.calculate_proofs(epoch)
        proof = m.get_proof(epoch)
        assert proof.pub_ins == [INITIAL_SCORE] * NUM_NEIGHBOURS
        assert m.prover.verify(proof.pub_ins, proof.proof)

    def test_get_last_proof(self):
        m = Manager(ManagerConfig(prover="commitment"))
        m.generate_initial_attestations()
        with pytest.raises(EigenError):
            m.get_last_proof()
        m.calculate_proofs(Epoch(3))
        m.calculate_proofs(Epoch(7))
        assert m.get_last_proof() is m.get_proof(Epoch(7))

    def test_open_graph_and_epoch_convergence(self):
        m = Manager(ManagerConfig(backend="tpu-sparse"))
        m.generate_initial_attestations()
        graph = m.build_graph()
        assert graph.n == NUM_NEIGHBOURS
        assert graph.nnz == NUM_NEIGHBOURS**2  # all uniform scores incl self
        res = m.converge_epoch(Epoch(1), alpha=0.1)
        # Symmetric uniform graph → uniform trust.
        np.testing.assert_allclose(res.scores, [1 / NUM_NEIGHBOURS] * NUM_NEIGHBOURS, rtol=1e-4)

    def test_window_plan_cached_across_epochs(self):
        """Both windowed backends (single-device and sharded) surface
        their WindowPlan through the manager cache, so stable graphs
        build it once and checkpoints can persist it."""
        for backend in ("tpu-windowed", "tpu-sharded:tpu-windowed"):
            m = Manager(ManagerConfig(backend=backend, prover="commitment"))
            m.generate_initial_attestations()
            res1 = m.converge_epoch(Epoch(1), alpha=0.1)
            assert m.window_plan is not None, backend
            plan = m.window_plan
            res2 = m.converge_epoch(Epoch(2), alpha=0.1)
            assert m.window_plan is plan, backend  # fingerprint hit
            np.testing.assert_allclose(res1.scores, res2.scores, rtol=1e-6)


class TestHandleRequest:
    def _ready_manager(self):
        m = Manager(ManagerConfig(prover="commitment"))
        m.generate_initial_attestations()
        m.calculate_proofs(Epoch(0))
        return m

    def test_unknown_route_404(self):
        # main.rs:196-213
        status, body = handle_request("GET", "/non_existing_route", Manager())
        assert (status, body) == (404, "InvalidRequest")

    def test_score_query(self):
        # main.rs:215-237
        m = self._ready_manager()
        status, body = handle_request("GET", "/score", m)
        assert status == 200
        raw = ProofRaw.from_json(body)
        assert raw.to_proof().pub_ins == [INITIAL_SCORE] * NUM_NEIGHBOURS

    def test_score_without_proof_400(self):
        status, body = handle_request("GET", "/score", Manager())
        assert (status, body) == (400, "InvalidQuery")

    def test_post_rejected(self):
        status, _ = handle_request("POST", "/score", self._ready_manager())
        assert status == 404


class TestProofRawJson:
    def test_roundtrip(self):
        raw = ProofRaw(pub_ins=[field.to_le_bytes(5)], proof=b"\x01\x02")
        again = ProofRaw.from_json(raw.to_json())
        assert again.pub_ins == raw.pub_ins and again.proof == raw.proof
        # serde shape: integer arrays
        obj = json.loads(raw.to_json())
        assert isinstance(obj["pub_ins"][0], list) and isinstance(obj["proof"], list)


class TestConfigAndFixtures:
    def test_protocol_config_parses_reference_shape(self):
        cfg = ProtocolConfig.load("data/protocol-config.json")
        assert cfg.epoch_interval == 10
        assert cfg.host == "0.0.0.0" and cfg.port == 3000
        assert cfg.trust_backend == "native-cpu"

    def test_prover_config_parsed(self):
        cfg = ProtocolConfig.from_json(
            '{"prover": "plonk", "srs_path": "/tmp/srs.bin"}'
        )
        assert cfg.prover == "plonk" and cfg.srs_path == "/tmp/srs.bin"
        # A node proves real SNARKs by default, like the reference
        # (manager/mod.rs:170-214).
        assert ProtocolConfig.from_json("{}").prover == "plonk"
        assert ProtocolConfig.from_json('{"prover": "commitment"}').prover == "commitment"

    def test_unknown_prover_rejected(self):
        import pytest

        with pytest.raises(ValueError, match="unknown prover"):
            Manager(ManagerConfig(prover="Plonk"))

    def test_bootstrap_csv(self):
        nodes = read_bootstrap_csv("data/bootstrap-nodes.csv")
        assert [n.name for n in nodes] == ["Alice", "Bob", "Charlie", "Chuck", "Craig"]
        assert nodes[0].secret_key().public() == keyset_from_raw(FIXED_SET)[1][0]

    def test_event_fixture_roundtrip(self, tmp_path):
        att = make_attestation()
        payload = AttestationData.from_attestation(att).to_bytes()
        ev = AttestationCreatedEvent(
            creator="0x" + "11" * 20, about="0x" + "00" * 20, key=bytes(32), val=payload
        )
        path = tmp_path / "events.jsonl"
        path.write_text(ev.to_json() + "\n")
        events = list(FixtureEventSource(path).replay())
        assert len(events) == 1
        decoded = AttestationData.from_bytes(events[0].val, NUM_NEIGHBOURS).to_attestation(
            NUM_NEIGHBOURS
        )
        assert decoded.pk == att.pk


class TestNodeEndToEnd:
    def test_http_server_serves_score(self):
        """Full socket-level drive: boot the node, query /score."""
        from protocol_tpu.node.config import ProtocolConfig
        from protocol_tpu.node.server import Node

        async def scenario():
            cfg = ProtocolConfig(
                epoch_interval=3600, endpoint=((127, 0, 0, 1), 0), prover="commitment"
            )
            node = Node.from_config(cfg)
            await node.start()
            node.manager.calculate_proofs(Epoch(0))
            port = node._server.sockets[0].getsockname()[1]

            reader, writer = await asyncio.open_connection("127.0.0.1", port)
            writer.write(b"GET /score HTTP/1.1\r\nhost: x\r\n\r\n")
            await writer.drain()
            response = await reader.read()
            writer.close()
            await node.stop()
            return response.decode()

        response = asyncio.run(scenario())
        head, _, body = response.partition("\r\n\r\n")
        assert "200 OK" in head
        raw = ProofRaw.from_json(body)
        assert raw.to_proof().pub_ins == [INITIAL_SCORE] * NUM_NEIGHBOURS
