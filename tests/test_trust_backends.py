"""jit-vs-native equivalence for every trust kernel (SURVEY.md §4 tier 3)
and cross-backend consistency, including the sharded mesh path (tier 5)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from protocol_tpu.crypto.eddsa import SecretKey
from protocol_tpu.models.graphs import erdos_renyi, scale_free, sybil_mass, sybil_stress
from protocol_tpu.ops.dense import converge_dense, filter_and_normalize, set_converge_dense
from protocol_tpu.parallel.mesh import default_mesh
from protocol_tpu.parallel.sharded import ShardedTrustProblem, converge_sharded
from protocol_tpu.trust.backend import get_backend
from protocol_tpu.trust.graph import TrustGraph
from protocol_tpu.trust.native import EigenTrustSet, Opinion, power_iterate_rational
from protocol_tpu.crypto.eddsa import Signature


def unsigned_opinion(pks, scores):
    """Set tests that don't exercise signatures use a zero signature."""
    return Opinion(sig=Signature.new(0, 0, 0), message_hash=0, scores=list(zip(pks, scores)))


class TestDenseKernel:
    def test_matches_exact_rational(self):
        """converge_dense on the row-stochastic matrix equals native()'s
        unscaled rational result (circuit.rs:425-470 equivalence)."""
        rng = np.random.default_rng(3)
        n, iters, scale = 7, 10, 1000
        # Random rows summing to SCALE.
        ops = np.zeros((n, n), dtype=np.int64)
        for i in range(n):
            cuts = np.sort(rng.integers(0, scale + 1, n - 1))
            parts = np.diff(np.concatenate([[0], cuts, [scale]]))
            ops[i] = parts
            ops[i, i] = 0
            ops[i] = ops[i] * scale // max(ops[i].sum(), 1)
            ops[i, (i + 1) % n] += scale - ops[i].sum()
        init = [1000] * n

        exact = power_iterate_rational(init, ops.tolist(), iters, scale)
        c_t = jnp.asarray((ops.T / scale).astype(np.float32))
        out = converge_dense(c_t, jnp.asarray(np.array(init, np.float32)), iters)
        np.testing.assert_allclose(
            np.asarray(out), [float(x) for x in exact], rtol=2e-4
        )

    def test_jit_static_iters(self):
        # Explicit staging: eager jnp constructors (eye/ones) build from
        # host scalars, which the module's transfer guard rejects.
        c = jnp.asarray(np.eye(4, dtype=np.float32))
        s = jnp.asarray(np.ones(4, np.float32))
        assert converge_dense(c, s, 3).shape == (4,)


class TestSetKernelVectorized:
    def _scenario(self, seed=0):
        s = EigenTrustSet(num_neighbours=6, num_iterations=20, initial_score=1000)
        pks = [SecretKey.random().public() for _ in range(4)]
        for pk in pks[:3]:
            s.add_member(pk)
        from protocol_tpu.crypto.eddsa import PublicKey

        null = PublicKey.null()
        padded = pks[:3] + [null, null, null]
        # Mixed scenario: valid rows, a mismatched pk (pks[3] in slot 5),
        # a self-score, and one zero-sum opinion.
        s.update_op(pks[0], unsigned_opinion([pks[0], pks[1], pks[2], null, null, pks[3]], [10, 10, 0, 0, 10, 5]))
        s.update_op(pks[1], unsigned_opinion(padded, [0, 0, 30, 0, 0, 0]))
        s.update_op(pks[2], unsigned_opinion(padded, [0, 0, 0, 0, 0, 0]))
        return s

    def test_filter_matches_native(self):
        s = self._scenario()
        ops, match, valid, credits = s.to_arrays()
        stochastic = np.asarray(
            filter_and_normalize(jnp.asarray(ops), jnp.asarray(match), jnp.asarray(valid))
        )

        filtered_set, filtered_ops = s.filter_peers()
        for i, (pk, _) in enumerate(filtered_set):
            if pk.is_null():
                assert np.all(stochastic[i] == 0)
                continue
            native_scores = np.array(
                [float(score) for _, score in filtered_ops[pk].scores]
            )
            expected = native_scores / native_scores.sum()
            np.testing.assert_allclose(stochastic[i], expected, rtol=1e-6)

    def test_converge_matches_native(self):
        s = self._scenario()
        ops, match, valid, credits = s.to_arrays()
        stochastic = filter_and_normalize(
            jnp.asarray(ops), jnp.asarray(match), jnp.asarray(valid)
        )
        out = np.asarray(
            set_converge_dense(stochastic, jnp.asarray(credits.astype(np.float32)), 20)
        )
        exact = s.converge_rational()
        # Native raw scores grow by INITIAL_SCORE^20; compare normalized.
        expected = np.array([float(x / 1000**20) for x in exact])
        np.testing.assert_allclose(out, expected, rtol=1e-4, atol=1e-7)


class TestSparseBackend:
    def test_matches_dense_backend(self):
        g = erdos_renyi(200, avg_degree=6.0, seed=1)
        dense = get_backend("tpu-dense").converge(g, alpha=0.1, tol=1e-9, max_iter=80)
        sparse = get_backend("tpu-sparse").converge(g, alpha=0.1, tol=1e-9, max_iter=80)
        np.testing.assert_allclose(sparse.scores, dense.scores, rtol=1e-3, atol=1e-8)

    def test_matches_exact_native_backend(self):
        g = erdos_renyi(40, avg_degree=4.0, seed=2)
        exact = get_backend("native-cpu").converge(g, alpha=0.15, tol=0, max_iter=25)
        sparse = get_backend("tpu-sparse").converge(g, alpha=0.15, tol=0, max_iter=25)
        np.testing.assert_allclose(sparse.scores, exact.scores, rtol=1e-3, atol=1e-7)

    def test_l1_normalized(self):
        g = scale_free(500, 4000, seed=3)
        res = get_backend("tpu-sparse").converge(g, alpha=0.1)
        assert res.scores.sum() == pytest.approx(1.0, rel=1e-5)
        assert (res.scores >= 0).all()

    def test_fixed_iter_mode(self):
        g = erdos_renyi(100, seed=4)
        res = get_backend("tpu-sparse").converge(g, alpha=0.1, tol=0, max_iter=7)
        assert res.iterations == 7


class TestCsrBackend:
    def test_matches_sparse_backend(self):
        g = scale_free(800, 6400, seed=9)
        sparse = get_backend("tpu-sparse").converge(g, alpha=0.1, tol=1e-9, max_iter=60)
        csr = get_backend("tpu-csr").converge(g, alpha=0.1, tol=1e-9, max_iter=60)
        np.testing.assert_allclose(csr.scores, sparse.scores, rtol=1e-3, atol=1e-8)

    def test_matches_exact_native(self):
        g = erdos_renyi(40, avg_degree=4.0, seed=2)
        exact = get_backend("native-cpu").converge(g, alpha=0.15, tol=0, max_iter=25)
        csr = get_backend("tpu-csr").converge(g, alpha=0.15, tol=0, max_iter=25)
        np.testing.assert_allclose(csr.scores, exact.scores, rtol=1e-3, atol=1e-7)

    def test_row_ptr_construction(self):
        g = erdos_renyi(50, avg_degree=3.0, seed=12).drop_self_edges().sorted_by_dst()
        rp = g.row_ptr_by_dst()
        assert rp.shape == (51,)
        assert rp[0] == 0 and rp[-1] == g.nnz
        for j in range(50):
            assert (g.dst[rp[j] : rp[j + 1]] == j).all()


class TestShardedBackend:
    def test_mesh_has_8_devices(self):
        assert len(jax.devices()) == 8  # conftest virtual CPU mesh

    def test_matches_sparse_backend(self):
        g = scale_free(1000, 8000, seed=5)
        sparse = get_backend("tpu-sparse").converge(g, alpha=0.1, tol=1e-9, max_iter=60)
        sharded = get_backend("tpu-sharded").converge(g, alpha=0.1, tol=1e-9, max_iter=60)
        np.testing.assert_allclose(sharded.scores, sparse.scores, rtol=1e-3, atol=1e-8)

    def test_explicit_small_mesh(self):
        mesh = default_mesh(4)
        g = erdos_renyi(300, seed=6)
        res = get_backend("tpu-sharded", mesh=mesh).converge(g, alpha=0.1)
        assert res.scores.shape == (300,)
        assert res.scores.sum() == pytest.approx(1.0, rel=1e-5)

    def test_sharded_problem_padding(self):
        # nnz not divisible by the mesh size must zero-pad cleanly.
        g = erdos_renyi(50, avg_degree=3.1, seed=7)
        problem = ShardedTrustProblem.build(g, default_mesh(8))
        assert problem.src.shape[0] % 8 == 0
        t, it, resid = converge_sharded(problem, alpha=0.2, max_iter=30)
        assert np.asarray(t).sum() == pytest.approx(1.0, rel=1e-5)


class TestSybilDamping:
    def test_damping_bounds_collective(self):
        """BASELINE config 5 semantics: pre-trust damping caps the trust
        mass a closed sybil collective can capture."""
        g = sybil_stress(2000, 16000, sybil_fraction=0.3, seed=8)
        masses = []
        for alpha in (0.01, 0.2, 0.5):
            res = get_backend("tpu-sparse").converge(g, alpha=alpha, max_iter=80)
            masses.append(sybil_mass(res.scores, g.n, 0.3))
        assert masses[0] > masses[1] > masses[2]
        assert masses[2] < 0.2


class TestBackendRegistry:
    def test_unknown_backend(self):
        with pytest.raises(ValueError, match="unknown trust backend"):
            get_backend("gpu-magic")

    def test_all_named_backends_construct(self):
        for name in ("native-cpu", "tpu-dense", "tpu-sparse", "tpu-sharded"):
            assert get_backend(name).name == name


class TestBenchLadder:
    def test_ladder_smoke(self):
        """All five BASELINE.md configs execute and report: shape check
        at 1/1000 scale (bench.py --ladder is the real run)."""
        import bench

        entries = bench.ladder(scale_div=1000, iters=6)
        assert [e["config"][:2] for e in entries] == ["1-", "2-", "3-", "4-", "5-"]
        curve = entries[-1]["sybil_mass_curve"]
        masses = [p["sybil_mass"] for p in curve]
        assert masses == sorted(masses, reverse=True)  # damping squeezes the clique


class TestTransferGuard:
    """This module runs under ``jax.transfer_guard("disallow")``
    (conftest): implicit transfers in any backend path fail loudly
    here, so a hidden per-iteration host sync can't land silently."""

    def test_implicit_transfers_rejected_here(self):
        step = jax.jit(lambda a: a * 2)
        with pytest.raises(Exception, match="Disallowed host-to-device"):
            step(np.arange(3.0, dtype=np.float32))

    @pytest.mark.allow_transfer
    def test_marker_opts_out(self):
        step = jax.jit(lambda a: a * 2)
        out = np.asarray(step(np.arange(3.0, dtype=np.float32)))
        np.testing.assert_allclose(out, [0.0, 2.0, 4.0])


class TestWindowedGather:
    def test_bucketed_gather_matches_direct(self):
        """The windowed Pallas gather (interpret mode on CPU; PERF.md
        §1 documents the TPU compilation envelope it is built for)
        reproduces w * t[src] under the bucket permutation."""
        import numpy as np

        from protocol_tpu.ops.gather_window import bucket_by_window, gather_windowed

        rng = np.random.default_rng(5)
        n, e = 1 << 13, 1 << 15
        src = rng.integers(0, n, e).astype(np.int32)
        w = rng.random(e, dtype=np.float32)
        t = rng.random(n, dtype=np.float32)

        b = bucket_by_window(src, w, table_size=n)
        out = np.asarray(
            gather_windowed(
                jnp.asarray(b["wid"]),
                jnp.asarray(t),
                jnp.asarray(b["local"]),
                jnp.asarray(b["weight"]),
                n_rows=b["n_rows"],
                interpret=True,
            )
        ).reshape(-1)
        expect = w[b["order"]] * t[src[b["order"]]]
        np.testing.assert_allclose(out[b["out_pos"]], expect, rtol=1e-6)
        # Padding slots carry zero weight, so the bucketed sum matches.
        np.testing.assert_allclose(out.sum(), (w * t[src]).sum(), rtol=1e-4)
