"""graftlint pass 12 (protocol_tpu.analysis.memory) — the ISSUE 15
acceptance suite.

Covers: the memory pass runs clean on the real tree with every
registered backend covered; the sharded composites are judged at TWO
problem scales whose committed budgets provably cannot absorb a
4 B/edge live temporary at either scale (the COMM_INVARIANTS pinning
trick applied to liveness); donation materializes as buffer aliasing
for every donating backend; per-shard resident budgets cannot absorb
a replicated edge operand; the conservative live-range walker and the
buffer-assignment view agree in shape on hostile snippets; dead
memory waivers fail the gate (``stale-waiver``); the pass-12 AST
rules fire/stay-quiet on minimal snippets and the fixed Manager
caches are ring-bounded (the first analyzer run's real findings).

The seeded pass-12 fixtures themselves are exercised by the
parametrized ``tests/test_analysis.py::TestViolationFixtures`` (rule +
file:line against the ``# VIOLATION:`` markers) — this file pins their
registration and the CLI plumbing.
"""

import json

import pytest

from protocol_tpu.analysis import MEM_INVARIANTS, NON_JAX_BACKENDS
from protocol_tpu.analysis.__main__ import main as analysis_main
from protocol_tpu.analysis.ast_rules import run_mem_ast_pass, scan_source
from protocol_tpu.analysis.fixtures import FIXTURES
from protocol_tpu.analysis.memory import run_memory_pass
from protocol_tpu.analysis.memory.liveness import (
    largest_temp_site,
    live_range_peak,
    measured_view,
)
from protocol_tpu.trust.backend import registered_backends

#: Backends whose converge donates its f32[N] seed (the pass-12
#: donation-reduces-peak contract; dense re-feeds its own carry).
DONATING_BACKENDS = (
    "tpu-sparse",
    "tpu-csr",
    "tpu-windowed",
    "tpu-sharded:tpu-csr",
    "tpu-sharded:tpu-windowed",
)


@pytest.fixture(scope="module")
def mem_report():
    """One full pass-12 run (module-scoped; the compiled cases are
    shared with pass 8 through the lowering memo)."""
    findings, section = run_memory_pass()
    return findings, section


class TestRealTree:
    def test_memory_pass_clean(self, mem_report):
        findings, _ = mem_report
        assert [f.render() for f in findings] == []

    def test_every_registered_backend_covered(self, mem_report):
        _, section = mem_report
        for name in registered_backends():
            assert name in section["backends"], name
            status = section["backends"][name]["status"]
            expected = "skipped" if name in NON_JAX_BACKENDS else "checked"
            assert status == expected, (name, status)

    def test_sharded_composites_checked_at_two_scales(self, mem_report):
        _, section = mem_report
        for name in ("tpu-sharded:tpu-csr", "tpu-sharded:tpu-windowed"):
            scales = section["backends"][name]["scales"]
            assert len(scales) == 2, name
            ns = [s["dims"]["n"] for s in scales]
            es = [s["dims"]["edges"] for s in scales]
            assert ns[1] == 2 * ns[0], ns  # N doubles...
            assert es[1] > 3.5 * es[0], es  # ...while E quadruples

    def test_budgets_cannot_absorb_4_bytes_per_edge(self, mem_report):
        """The ISSUE 15 acceptance: at EVERY compiled scale of EVERY
        backend, measured resident and transient fit their allowances
        AND the slack in each component is below a 4 B/edge live
        buffer — so an extra edge-sized temporary (or a replicated
        edge operand) trips the gate no matter which component it
        lands in, and no padded constant can hide it."""
        _, section = mem_report
        for name, rec in section["backends"].items():
            if rec.get("status") != "checked":
                continue
            for s in rec["scales"]:
                o_e = 4 * s["dims"]["edges"]
                m = s["measured"]
                for comp, budget_key in (
                    ("resident_bytes", "budget_resident_bytes"),
                    ("transient_bytes", "budget_transient_bytes"),
                ):
                    assert m[comp] <= s[budget_key], (name, s["scale"], comp)
                    slack = s[budget_key] - m[comp]
                    assert slack < o_e, (
                        f"{name} at {s['scale']}: {comp} slack "
                        f"{slack:.0f} could absorb a 4 B/edge buffer "
                        f"({o_e}) — tighten the budget"
                    )

    def test_per_shard_resident_cannot_absorb_replication(self, mem_report):
        """The shard-replicated-edges contract: the per-shard resident
        allowance is small enough that holding the FULL edge slice on
        one device (instead of E/n_shards) busts it."""
        _, section = mem_report
        for name in ("tpu-sharded:tpu-csr",):
            for s in section["backends"][name]["scales"]:
                e_bytes = 8 * s["dims"]["edges"]  # src + w, full graph
                replicated = (
                    s["measured"]["resident_bytes"]
                    + e_bytes * (s["dims"]["n_shards"] - 1) / s["dims"]["n_shards"]
                )
                assert replicated > s["budget_resident_bytes"], (name, s)

    def test_per_shard_transient_tracks_n_not_e(self, mem_report):
        """Across the 4x edge growth the sharded transient must grow
        by no more than the budget's N/n_segments-linear coefficients
        (the replicated score vectors and per-shard segment tables) —
        the measured fact the no-edge-coefficient model rests on: at
        this step E quadrupled while the growth fits tn*dN + ts*dS."""
        _, section = mem_report
        for name in ("tpu-sharded:tpu-csr", "tpu-sharded:tpu-windowed"):
            rec = section["backends"][name]
            scales = rec["scales"]
            t1 = scales[0]["measured"]["transient_bytes"]
            t2 = scales[1]["measured"]["transient_bytes"]
            dn = scales[1]["dims"]["n"] - scales[0]["dims"]["n"]
            ds = scales[1]["dims"].get("n_segments", 0) - scales[0][
                "dims"
            ].get("n_segments", 0)
            linear_growth = (
                rec["budget"]["transient_n"] * dn
                + rec["budget"]["transient_segments"] * ds
            )
            assert t2 - t1 <= linear_growth, (name, t1, t2, linear_growth)
            # ...whereas a per-shard 4 B/edge transient would have had
            # to grow with the edge slice on top of that.
            de = scales[1]["dims"]["edges"] - scales[0]["dims"]["edges"]
            per_shard_o_e = 4 * de / scales[0]["dims"]["n_shards"]
            assert t2 - t1 < linear_growth + per_shard_o_e

    def test_donation_reduces_peak(self, mem_report):
        """Every donating backend's buffer assignment aliases at least
        the 4*N seed bytes — the executable-level half of the PR 3/9
        donation pins."""
        _, section = mem_report
        for name in DONATING_BACKENDS:
            for s in section["backends"][name]["scales"]:
                m = s["measured"]
                assert m.get("alias_bytes", 0) >= 4 * s["dims"]["n"], (name, s)

    def test_no_host_transfers_in_any_converge(self, mem_report):
        _, section = mem_report
        for name, rec in section["backends"].items():
            if rec.get("status") != "checked":
                continue
            for s in rec["scales"]:
                assert s["host_transfers"] == [], (name, s["scale"])

    def test_budget_table_matches_registry(self):
        from protocol_tpu.analysis.zk_lowering import ensure_budgets

        zk_names = set(ensure_budgets())
        declared = set(MEM_INVARIANTS)
        registered = {
            n for n in registered_backends() if n not in NON_JAX_BACKENDS
        }
        assert declared == registered | zk_names

    def test_waiver_table_live_not_stale(self, mem_report):
        """The hash-memo waiver is live (the rule really fires on
        Manager._hash_cache, which is bounded by the peer set) and no
        waiver is stale."""
        _, section = mem_report
        assert section["stale_waivers"] == []
        assert [w["symbol"] for w in section["waived"]] == [
            "Manager._hash_cache"
        ]

    def test_buffer_assignment_source_used(self, mem_report):
        """On this runtime the primary view is the compiler's buffer
        assignment, not the conservative fallback."""
        _, section = mem_report
        for name, rec in section["backends"].items():
            if rec.get("status") != "checked":
                continue
            for s in rec["scales"]:
                assert s["source"] == "buffer-assignment", name


class TestRegistryGate:
    def test_undeclared_mem_budget_is_error(self):
        findings, section = run_memory_pass(backends=["tpu-quantum"])
        assert section["backends"]["tpu-quantum"]["status"] == "undeclared"
        assert [(f.rule, f.severity) for f in findings] == [
            ("undeclared-mem-budget", "error")
        ]


class TestFixturePlumbing:
    def test_mem_fixtures_registered(self):
        mem = {n for n, f in FIXTURES.items() if f.kind in ("mem", "mem-ast")}
        assert mem == {
            "o-e-live-temporary",
            "donation-peak-doubled",
            "shard-replicated-edges",
            "host-staging-over-cap",
            "host-materialization-of-edges",
            "unbounded-cache-growth",
        }

    def test_cli_exits_nonzero_on_mem_fixture(self, tmp_path):
        out = tmp_path / "fixture.json"
        rc = analysis_main(
            ["--fixture", "donation-peak-doubled", "--output", str(out)]
        )
        assert rc == 1
        report = json.loads(out.read_text())
        assert report["findings"][0]["rule"] == "donation-peak-doubled"
        assert report["findings"][0]["pass"] == "memory"


class TestLivenessWalk:
    """Walker units on hostile snippets (no compile)."""

    MODULE = (
        "HloModule jit_f, is_scheduled=true\n"
        "\n"
        "%fused_computation (p: f32[512]) -> f32[512] {\n"
        "  %p = f32[512]{0} parameter(0)\n"
        "  ROOT %m = f32[512]{0} multiply(f32[512]{0} %p, f32[512]{0} %p)\n"
        "}\n"
        "\n"
        "ENTRY %main (a: f32[1024], b: f32[1024]) -> f32[] {\n"
        "  %a = f32[1024]{0} parameter(0)\n"
        "  %b = f32[1024]{0} parameter(1)\n"
        '  %big = f32[4096]{0} broadcast(f32[1024]{0} %a), metadata={op_name="jit(f)/bc" source_file="/repo/x.py" source_line=7}\n'
        "  %s = f32[] reduce(f32[4096]{0} %big, f32[] %b)\n"
        "  ROOT %r = f32[] add(f32[] %s, f32[] %s)\n"
        "}\n"
    )

    def test_live_range_peak_counts_temps_not_params(self):
        peak = live_range_peak(self.MODULE)
        # entry: big (16384) + s (4) live together; fused adds m (2048).
        assert peak >= 16384 + 4
        assert peak < 16384 + 4096 + 4096  # parameters excluded

    def test_largest_temp_site_with_metadata(self):
        site = largest_temp_site(self.MODULE)
        assert site is not None
        assert site.bytes == 16384
        assert site.op == "broadcast"
        assert site.file == "/repo/x.py"
        assert site.line == 7

    def test_measured_view_prefers_buffer_assignment(self):
        from protocol_tpu.analysis.comm.lowering import CommCase

        case = CommCase(
            backend="x", dims={}, module_text=self.MODULE, arg_names=(),
            mem={
                "argument_bytes": 100,
                "output_bytes": 20,
                "alias_bytes": 10,
                "temp_bytes": 50,
            },
        )
        view, source = measured_view(case)
        assert source == "buffer-assignment"
        assert view["resident_bytes"] == 100
        assert view["transient_bytes"] == 60
        assert view["peak_bytes"] == 160

    def test_measured_view_falls_back_to_live_range_walk(self):
        from protocol_tpu.analysis.comm.lowering import CommCase

        case = CommCase(
            backend="x", dims={}, module_text=self.MODULE, arg_names=(),
            mem=None,
        )
        view, source = measured_view(case)
        assert source == "live-range-walk"
        # resident estimate = the largest computation's parameters.
        assert view["resident_bytes"] == 8192
        assert view["transient_bytes"] == live_range_peak(self.MODULE)


class TestStaleWaivers:
    """A dead memory waiver fails the gate in every run that evaluates
    the table — the cross-table staleness parity of ISSUE 15."""

    def test_dead_mem_waiver_is_error(self, monkeypatch):
        from protocol_tpu.analysis.concurrency.waivers import Waiver
        from protocol_tpu.analysis.memory import checker as mem_checker

        dead = Waiver(
            rule="o-e-live-temporary", file="gone.py", symbol="ghost",
            reason="the leak this waived was fixed",
        )
        monkeypatch.setattr(mem_checker, "MEM_WAIVERS", (dead,))
        live, waived, stale = mem_checker._apply_waivers([])
        assert live == [] and waived == []
        assert [s["symbol"] for s in stale] == ["ghost"]
        findings, section = mem_checker.run_memory_pass(backends=[])
        assert [f.rule for f in findings] == ["stale-waiver"]
        assert findings[0].severity == "error"

    def test_all_four_tables_enforce_staleness(self):
        """Concurrency, comm, memory, and determinism waiver tables all
        turn a dead entry into an error — no table rots silently."""
        from protocol_tpu.analysis.comm import checker as comm_checker
        from protocol_tpu.analysis.concurrency.checker import (
            analyze_models,
            build_program_model,
        )
        from protocol_tpu.analysis.concurrency.waivers import Waiver
        from protocol_tpu.analysis.determinism import checker as det_checker
        from protocol_tpu.analysis.memory import checker as mem_checker

        dead = Waiver(rule="x", file="gone.py", symbol="ghost", reason="r")
        conc, _, _ = analyze_models(
            build_program_model({"protocol_tpu/node/_x.py": "x = 1\n"}),
            (dead,),
        )
        assert [f.rule for f in conc] == ["stale-waiver"]
        for checker in (comm_checker, mem_checker, det_checker):
            live, _, stale = checker._apply_waivers([])
            # the committed tables have no dead entries...
            assert [s for s in stale if s["symbol"] == "ghost"] == []


def _scan(rel: str, code: str):
    return scan_source(code, rel, mem_rules=True)


class TestHostMaterializationRule:
    """Pass 12: no edge-scale host materialization on the epoch loop's
    critical path (file-scoped like passes 6/9)."""

    def test_np_asarray_on_edge_array_fires(self):
        findings = _scan(
            "protocol_tpu/node/pipeline.py",
            "import numpy as np\n"
            "def device_stage(plan):\n"
            "    return np.asarray(plan.seg_dst)\n",
        )
        assert [f.rule for f in findings] == ["host-materialization-of-edges"]
        assert findings[0].line == 3

    def test_device_get_and_tolist_fire(self):
        findings = _scan(
            "protocol_tpu/node/epoch.py",
            "import jax\n"
            "def tick(graph):\n"
            "    a = jax.device_get(graph.src)\n"
            "    b = graph.edge_weights.tolist()\n"
            "    return a, b\n",
        )
        assert [f.rule for f in findings] == [
            "host-materialization-of-edges"
        ] * 2
        assert [f.line for f in findings] == [3, 4]

    def test_n_sized_materialization_is_fine(self):
        """Scores and peer columns are O(N) — the rule only fences
        edge-scale names."""
        findings = _scan(
            "protocol_tpu/node/pipeline.py",
            "import numpy as np\n"
            "def device_stage(result):\n"
            "    return np.asarray(result.scores)\n",
        )
        assert findings == []

    def test_same_code_outside_epoch_loop_files_is_fine(self):
        """Plan build (manager.py) materializes edge arrays on the
        host legitimately — the rule is epoch-loop-file-scoped."""
        findings = _scan(
            "protocol_tpu/node/manager.py",
            "import numpy as np\n"
            "def build_graph(src, dst, w):\n"
            "    return np.asarray(src), np.asarray(dst), np.asarray(w)\n",
        )
        assert [
            f for f in findings if f.rule == "host-materialization-of-edges"
        ] == []

    def test_rule_off_without_mem_pass(self):
        findings = scan_source(
            "import numpy as np\n"
            "def device_stage(plan):\n"
            "    return np.asarray(plan.seg_dst)\n",
            "protocol_tpu/node/pipeline.py",
        )
        assert findings == []


class TestUnboundedCacheGrowthRule:
    """Pass 12: cache-named attributes of long-lived node classes must
    evict, bound, or rotate."""

    GROWING = (
        "class Server:\n"
        "    def __init__(self):\n"
        "        self._proof_cache = {}\n"
        "    def put(self, epoch, proof):\n"
        "        self._proof_cache[epoch] = proof\n"
    )

    def test_growing_cache_fires(self):
        findings = _scan("protocol_tpu/node/server2.py", self.GROWING)
        assert [f.rule for f in findings] == ["unbounded-cache-growth"]
        assert findings[0].line == 3
        assert "Server._proof_cache" in findings[0].message

    def test_pop_eviction_quiets(self):
        findings = _scan(
            "protocol_tpu/node/server2.py",
            self.GROWING
            + "    def evict(self):\n"
            + "        while len(self._proof_cache) > 4:\n"
            + "            self._proof_cache.pop(min(self._proof_cache))\n",
        )
        assert findings == []

    def test_del_eviction_quiets(self):
        findings = _scan(
            "protocol_tpu/node/server2.py",
            self.GROWING
            + "    def evict(self, k):\n"
            + "        del self._proof_cache[k]\n",
        )
        assert findings == []

    def test_generation_rotation_quiets(self):
        """The dedup-cache shape: reassignment outside __init__ is a
        rotation, not growth."""
        findings = _scan(
            "protocol_tpu/ingest/dedup2.py",
            self.GROWING
            + "    def advance_epoch(self):\n"
            + "        self._proof_cache = {}\n",
        )
        assert findings == []

    def test_non_cache_names_are_exempt(self):
        findings = _scan(
            "protocol_tpu/node/state.py",
            "class Store:\n"
            "    def __init__(self):\n"
            "        self.attestations = {}\n"
            "    def put(self, k, v):\n"
            "        self.attestations[k] = v\n",
        )
        assert findings == []

    def test_read_only_cache_is_exempt(self):
        """A cache filled once in __init__ and only read never grows."""
        findings = _scan(
            "protocol_tpu/node/memo.py",
            "class Memo:\n"
            "    def __init__(self, pairs):\n"
            "        self._hash_cache = {}\n"
            "    def get(self, k):\n"
            "        return self._hash_cache.get(k)\n",
        )
        assert findings == []

    def test_outside_long_lived_trees_is_exempt(self):
        findings = _scan("protocol_tpu/obs/cacheish.py", self.GROWING)
        # scan_source arms the rules, but run_mem_ast_pass only walks
        # node/ + ingest/; at the pass level obs/ is out of scope.
        # The visitor itself is tree-agnostic, so this still fires —
        # scope is enforced by the pass walker:
        from protocol_tpu.analysis.ast_rules import MEM_AST_TREES

        assert MEM_AST_TREES == ("node", "ingest")
        assert [f.rule for f in findings] == ["unbounded-cache-growth"]

    def test_real_tree_only_waived_finding(self):
        """After the cached_proofs/cached_results fixes, the only
        pass-12 AST finding on the real tree is the (waived) pk-hash
        memo — the clean-real-tree half of the acceptance."""
        findings, n_files = run_mem_ast_pass()
        assert n_files > 15
        assert [(f.rule, "Manager._hash_cache" in f.message) for f in findings] == [
            ("unbounded-cache-growth", True)
        ]


class TestManagerCacheBounds:
    """Regression tests for the first analyzer run's real findings:
    Manager.cached_results held a full f32[N] fixed point per epoch
    forever (4 MB/epoch at 1M peers), Manager.cached_proofs a SNARK
    per epoch forever.  Both now ring-evict."""

    def _manager(self):
        from protocol_tpu.node.manager import Manager, ManagerConfig

        m = Manager(ManagerConfig(prover="commitment", backend="tpu-sparse"))
        m.generate_initial_attestations()
        return m

    def test_cached_results_ring_bounded(self, monkeypatch):
        import protocol_tpu.node.manager as manager_mod
        from protocol_tpu.node.epoch import Epoch

        monkeypatch.setattr(manager_mod, "RESULT_CACHE_EPOCHS", 2)
        m = self._manager()
        for e in range(4):
            m.converge_epoch(Epoch(e), alpha=0.1)
        assert len(m.cached_results) == 2
        assert sorted(e.number for e in m.cached_results) == [2, 3]

    def test_cached_proofs_ring_bounded(self, monkeypatch):
        import protocol_tpu.node.manager as manager_mod
        from protocol_tpu.node.epoch import Epoch
        from protocol_tpu.zk.proof import Proof

        monkeypatch.setattr(manager_mod, "PROOF_CACHE_EPOCHS", 3)
        m = self._manager()
        for e in range(6):
            m.cache_proof(Epoch(e), Proof(pub_ins=[e], proof=b"p%d" % e))
        assert sorted(e.number for e in m.cached_proofs) == [3, 4, 5]
        # latest_proof still serves the newest surviving epoch.
        assert m.cached_proofs[max(m.cached_proofs, key=lambda e: e.number)]

    def test_install_proof_routes_through_ring(self, monkeypatch):
        import protocol_tpu.node.manager as manager_mod

        monkeypatch.setattr(manager_mod, "PROOF_CACHE_EPOCHS", 1)
        m = self._manager()
        m.install_proof(1, [1], b"a")
        m.install_proof(2, [2], b"b")
        assert [e.number for e in m.cached_proofs] == [2]
