"""Fleet observability plane (ISSUE 11): attestation lineage, epoch
timelines, cross-process metric aggregation, the SLO engine, /healthz,
and the generated metric catalog."""

from __future__ import annotations

import json
import os
import threading
import time

import pytest

from protocol_tpu.node.epoch import Epoch
from protocol_tpu.node.manager import Manager, ManagerConfig
from protocol_tpu.obs import TIMELINE, TRACER, Tracer
from protocol_tpu.obs.fleet import (
    FleetAggregator,
    fleet_prometheus_text,
    load_directory,
    publish_snapshot,
    registry_snapshot,
)
from protocol_tpu.obs.lineage import LINEAGE, LineageTracker
from protocol_tpu.obs.metrics import FRESHNESS_SECONDS, METRICS
from protocol_tpu.obs.slo import (
    SLOEngine,
    SLObjective,
    default_objectives,
    seed_violation,
)
from protocol_tpu.obs.timeline import TimelineRegistry


def _manager(prover: str = "commitment") -> Manager:
    mgr = Manager(ManagerConfig(prover=prover))
    mgr.generate_initial_attestations()
    return mgr


# ---------------------------------------------------------------------------
# Lineage tracker
# ---------------------------------------------------------------------------


class TestLineageTracker:
    def test_sampling_period(self):
        t = LineageTracker(sample_every=4)
        ids = [t.maybe_begin() for _ in range(8)]
        assert sum(1 for lid in ids if lid) == 2
        assert len(t) == 2

    def test_unsampled_path_builds_no_state(self):
        """The zero-allocation contract: with sampling off (or between
        samples) the tracker builds NO per-item state — no entries, no
        epoch cohorts, and the returned ID is the shared int 0."""
        t = LineageTracker(sample_every=0)
        for _ in range(1000):
            assert t.maybe_begin() == 0
        assert len(t) == 0
        assert t.snapshot()["live"] == 0
        assert t.ids_for_epoch(1) == ()
        # Marks and drops against unsampled IDs are no-ops.
        t.mark(0, "applied")
        t.drop(0)
        assert len(t) == 0

    def test_full_lifecycle_observes_each_stage(self):
        t = LineageTracker(sample_every=1)
        before = {
            s: FRESHNESS_SECONDS.count(stage=s)
            for s in ("admitted", "applied", "included", "converged", "proof_landed")
        }
        lid = t.maybe_begin()
        assert lid
        t.mark(lid, "admitted")
        t.mark(lid, "verified")
        t.mark(lid, "applied")
        assert t.bind_epoch(7) == (lid,)
        assert t.ids_for_epoch(7) == (lid,)
        assert t.ids_for_epoch(6) == ()
        t.epoch_converged(7)
        e2e = t.epoch_proved(7)
        assert len(e2e) == 1 and e2e[0] >= 0
        assert len(t) == 0  # completed entries evict
        for stage, n0 in before.items():
            assert FRESHNESS_SECONDS.count(stage=stage) == n0 + 1, stage

    def test_later_proof_completes_earlier_cohorts(self):
        """Supersede semantics: epoch 9's proof covers the cohort bound
        to epoch 8 (whose own proof was displaced)."""
        t = LineageTracker(sample_every=1)
        a = t.maybe_begin()
        t.mark(a, "applied")
        t.bind_epoch(8)
        b = t.maybe_begin()
        t.mark(b, "applied")
        t.bind_epoch(9)
        assert set(t.ids_for_epoch(9)) == {a, b}
        assert len(t.epoch_proved(9)) == 2

    def test_drop_on_rejection(self):
        t = LineageTracker(sample_every=1)
        lid = t.maybe_begin()
        t.drop(lid, reason="rejected")
        assert len(t) == 0
        t.mark(lid, "applied")  # late mark on a dropped entry: no-op
        assert t.bind_epoch(1) == ()

    def test_capacity_eviction_is_bounded(self):
        t = LineageTracker(sample_every=1, max_entries=4)
        for _ in range(10):
            t.maybe_begin()
        assert len(t) == 4


# ---------------------------------------------------------------------------
# Lineage across the spawn boundary
# ---------------------------------------------------------------------------


class TestLineageSpawnBoundary:
    def test_proof_job_carries_lineage_ids(self):
        mgr = _manager()
        t = LINEAGE
        t.configure(1)
        try:
            lid = t.maybe_begin()
            t.mark(lid, "applied")
            t.bind_epoch(5)
            job = mgr.build_proof_job(Epoch(5))
            assert job.lineage == (lid,)
            assert all(isinstance(x, int) for x in job.lineage)
        finally:
            t.configure(0)
            t.reset()

    def test_unsampled_job_lineage_is_empty(self):
        LINEAGE.configure(0)
        LINEAGE.reset()
        mgr = _manager()
        job = mgr.build_proof_job(Epoch(5))
        assert job.lineage == ()

    def test_lineage_and_seed_are_independent(self):
        """Sampling must never perturb proof bytes: job_seed ignores
        the lineage payload."""
        from dataclasses import replace

        from protocol_tpu.prover import job_seed

        mgr = _manager()
        job = mgr.build_proof_job(Epoch(6))
        assert job_seed(job) == job_seed(replace(job, lineage=(1, 2, 3)))

    def test_spawned_worker_echoes_lineage_and_ships_metrics(self):
        """The spawn-boundary round trip: a pooled worker returns the
        flat lineage tuple AND its own registry snapshot (pid differs
        from the parent's)."""
        from protocol_tpu.prover.workers import ProverPool

        mgr = _manager()
        from dataclasses import replace

        job = replace(mgr.build_proof_job(Epoch(7)), lineage=(11, 23))
        pool = ProverPool(workers=1)
        try:
            result = pool.prove(job)
        finally:
            pool.close()
        assert result.lineage == (11, 23)
        assert result.metrics is not None
        assert result.metrics["pid"] != os.getpid()
        assert result.metrics["source"] == f"prover-{result.metrics['pid']}"
        # The worker's own span-fed histograms rode back with the proof.
        assert "eigentrust_phase_seconds" in result.metrics["metrics"]


# ---------------------------------------------------------------------------
# Tracer.graft early-arrival parking under concurrent epochs
# ---------------------------------------------------------------------------


class TestGraftConcurrentEpochs:
    def test_parked_grafts_land_per_epoch_with_two_open_roots(self):
        """Two epochs' root spans are open concurrently (pipelined
        ticks) while proofs for BOTH arrive early: each graft parks,
        and each lands under its own epoch when that trace stores."""
        tracer = Tracer()
        ready = threading.Barrier(3)
        release = {7: threading.Event(), 8: threading.Event()}

        def run_epoch(n: int):
            with tracer.epoch(n):
                ready.wait(timeout=10)
                release[n].wait(timeout=10)

        threads = [
            threading.Thread(target=run_epoch, args=(n,)) for n in (7, 8)
        ]
        for th in threads:
            th.start()
        ready.wait(timeout=10)
        # Both roots are open: neither trace is stored yet, so both
        # grafts must park (graft returns False) instead of dropping.
        assert tracer.graft(7, {"name": "prove", "children": []}) is False
        assert tracer.graft(8, {"name": "prove", "children": []}) is False
        # Close epoch 8 FIRST — out of submission order, like a fast
        # prove beating a cold-compile tick.
        release[8].set()
        threads[1].join(timeout=10)
        release[7].set()
        threads[0].join(timeout=10)
        for n in (7, 8):
            trace = tracer.get_trace(n)
            assert trace is not None
            names = [c["name"] for c in trace["children"]]
            assert names.count("prove") == 1, (n, names)

    def test_graft_for_evicted_epoch_is_dropped_not_parked(self):
        tracer = Tracer(keep_epochs=2)
        for n in (1, 2, 3):
            with tracer.epoch(n):
                pass
        assert tracer.graft(1, {"name": "prove"}) is False
        # Epoch 1 was ring-evicted; its pending-graft slot must not
        # grow unboundedly either.
        assert 1 not in tracer._pending_grafts


# ---------------------------------------------------------------------------
# Timeline registry
# ---------------------------------------------------------------------------


class TestTimelineRegistry:
    def test_merge_semantics_one_level_deep(self):
        tl = TimelineRegistry()
        tl.record(4, proof={"state": "queued", "submitted_unix": 1.0})
        tl.record(4, proof={"state": "proved"}, converge={"iterations": 3})
        rec = tl.get(4)
        assert rec["proof"] == {"state": "proved", "submitted_unix": 1.0}
        assert rec["converge"]["iterations"] == 3
        assert rec["epoch"] == 4

    def test_ring_bound_evicts_oldest(self):
        tl = TimelineRegistry(keep_epochs=3)
        for n in range(6):
            tl.record(n, x=n)
        assert tl.epochs() == [3, 4, 5]
        assert tl.latest_epoch() == 5
        assert tl.latest()["x"] == 5

    def test_seconds_since_last_tick(self):
        tl = TimelineRegistry()
        assert tl.seconds_since_last_tick() is None
        tl.record(1, tick_ended_unix=time.time() - 5.0)
        since = tl.seconds_since_last_tick()
        assert since is not None and 4.0 < since < 30.0

    def test_epoch_root_span_close_feeds_global_timeline(self):
        epoch = 987_654_001
        with TRACER.epoch(epoch):
            with TRACER.span("converge"):
                pass
        rec = TIMELINE.get(epoch)
        assert rec is not None
        assert "converge" in rec["phases"]
        assert rec["tick_seconds"] >= 0
        assert rec["error"] is False


# ---------------------------------------------------------------------------
# Fleet aggregation
# ---------------------------------------------------------------------------


class TestFleetAggregation:
    def test_snapshot_skips_empty_and_carries_pid(self):
        snap = registry_snapshot()
        assert snap["pid"] == os.getpid()
        assert snap["version"] == 1
        # skip_empty: only touched metrics ship.
        for entry in snap["metrics"].values():
            assert entry.get("samples") or entry.get("hist")

    def test_render_merges_with_process_labels(self):
        agg = FleetAggregator()
        reg_worker = type(METRICS)()  # fresh registry as "the worker"
        c = reg_worker.counter("eigentrust_test_fleet_total", "t", ("kind",))
        c.inc(3, kind="x")
        h = reg_worker.histogram("eigentrust_test_fleet_seconds", "t", buckets=(1.0,))
        h.observe(0.5)
        agg.ingest("worker-1", registry_snapshot(reg_worker, source="worker-1"))
        text = fleet_prometheus_text(aggregator=agg)
        assert (
            'eigentrust_test_fleet_total{kind="x",process="worker-1"} 3' in text
        )
        assert 'process="node"' in text
        assert (
            'eigentrust_test_fleet_seconds_count{process="worker-1"} 1' in text
        )

    def test_reingest_same_source_never_double_counts(self):
        agg = FleetAggregator()
        reg = type(METRICS)()
        c = reg.counter("eigentrust_test_refleet_total", "t")
        c.inc(5)
        snap = registry_snapshot(reg, source="w")
        agg.ingest("w", snap)
        agg.ingest("w", registry_snapshot(reg, source="w"))  # re-ship
        text = fleet_prometheus_text(aggregator=agg)
        assert 'eigentrust_test_refleet_total{process="w"} 5' in text
        assert text.count("eigentrust_test_refleet_total{") == 1

    def test_directory_exchange_round_trip(self, tmp_path):
        reg = type(METRICS)()
        reg.counter("eigentrust_test_dir_total", "t").inc(2)
        path = publish_snapshot(tmp_path, "A", reg)
        assert path.exists()
        # A half-written sibling must not break the merge.
        (tmp_path / "fleet-B.json").write_text("{not json")
        agg = FleetAggregator()
        ingested = load_directory(tmp_path, agg)
        assert ingested == ["proc-A"]
        text = fleet_prometheus_text(aggregator=agg)
        assert 'eigentrust_test_dir_total{process="proc-A"} 2' in text

    def test_directory_skips_own_pid(self, tmp_path):
        publish_snapshot(tmp_path, "self")
        agg = FleetAggregator()
        assert load_directory(tmp_path, agg, skip_pid=os.getpid()) == []


class TestFleetStaleness:
    """Snapshot TTL (ISSUE 19): a silently dead host's last snapshot
    must drop out of the merge and surface as stale, not be trusted
    forever."""

    def _decoy(self, tmp_path, name: str, age_s: float, now: float):
        """A snapshot whose taken_unix is ``age_s`` in the fake past."""
        reg = type(METRICS)()
        reg.counter(f"eigentrust_test_stale_{name}_total", "t").inc(1)
        path = publish_snapshot(tmp_path, name, reg)
        snap = json.loads(path.read_text())
        snap["taken_unix"] = now - age_s
        path.write_text(json.dumps(snap))

    def test_decoy_stale_snapshot_evicted(self, tmp_path):
        from protocol_tpu.obs.metrics import FLEET_STALE_SOURCES

        now = 1_000_000.0
        self._decoy(tmp_path, "dead", age_s=120.0, now=now)
        self._decoy(tmp_path, "live", age_s=3.0, now=now)
        agg = FleetAggregator()
        ingested = load_directory(
            tmp_path, agg, max_age_s=30.0, clock=lambda: now
        )
        assert ingested == ["proc-live"]
        assert agg.sources() == ["proc-live"]
        assert agg.stale() == {"proc-dead": pytest.approx(120.0)}
        assert FLEET_STALE_SOURCES.value() == 1.0
        text = fleet_prometheus_text(aggregator=agg)
        assert "eigentrust_test_stale_live_total" in text
        assert "eigentrust_test_stale_dead_total" not in text
        agg.reset()
        assert FLEET_STALE_SOURCES.value() == 0.0

    def test_fresh_reingest_clears_stale_mark(self, tmp_path):
        now = 1_000_000.0
        self._decoy(tmp_path, "flappy", age_s=120.0, now=now)
        agg = FleetAggregator()
        load_directory(tmp_path, agg, max_age_s=30.0, clock=lambda: now)
        assert "proc-flappy" in agg.stale()
        self._decoy(tmp_path, "flappy", age_s=1.0, now=now)  # came back
        load_directory(tmp_path, agg, max_age_s=30.0, clock=lambda: now)
        assert agg.stale() == {}
        assert agg.sources() == ["proc-flappy"]
        agg.reset()

    def test_no_ttl_keeps_old_snapshots(self, tmp_path):
        # Worker pools publish once and exit; without a TTL the old
        # keep-forever behavior must hold.
        now = 1_000_000.0
        self._decoy(tmp_path, "old", age_s=9_999.0, now=now)
        agg = FleetAggregator()
        assert load_directory(tmp_path, agg) == ["proc-old"]
        assert agg.stale() == {}
        agg.reset()


# ---------------------------------------------------------------------------
# SLO engine
# ---------------------------------------------------------------------------


class TestSLOEngine:
    def test_ok_and_violation_verdicts(self):
        engine = SLOEngine()
        value = {"v": 1.0}
        engine.register(
            SLObjective(
                name="test-max",
                description="d",
                target=2.0,
                value_fn=lambda: value["v"],
            )
        )
        out = engine.evaluate()
        assert out["ok"] and out["objectives"]["test-max"]["ok"]
        value["v"] = 3.0
        out = engine.evaluate()
        assert not out["ok"]
        assert out["objectives"]["test-max"]["value"] == 3.0

    def test_no_data_counts_as_ok(self):
        engine = SLOEngine()
        engine.register(
            SLObjective(
                name="test-none", description="d", target=1.0, value_fn=lambda: None
            )
        )
        assert engine.evaluate()["ok"]

    def test_min_direction(self):
        engine = SLOEngine()
        engine.register(
            SLObjective(
                name="test-min",
                description="d",
                target=5.0,
                direction="min",
                value_fn=lambda: 4.0,
            )
        )
        assert not engine.evaluate()["ok"]

    def test_burn_rate_and_transition_counter(self):
        from protocol_tpu.obs.metrics import SLO_VIOLATIONS

        engine = SLOEngine()
        value = {"v": 0.0}
        engine.register(
            SLObjective(
                name="test-burn",
                description="d",
                target=1.0,
                value_fn=lambda: value["v"],
                window=4,
            )
        )
        v0 = SLO_VIOLATIONS.value(objective="test-burn")
        engine.evaluate()  # ok
        value["v"] = 9.0
        engine.evaluate()  # violating (transition)
        engine.evaluate()  # still violating (no new transition)
        out = engine.evaluate()
        assert SLO_VIOLATIONS.value(objective="test-burn") == v0 + 1
        assert out["objectives"]["test-burn"]["burn_rate"] == 0.75

    def test_value_fn_exception_is_no_data(self):
        engine = SLOEngine()
        engine.register(
            SLObjective(
                name="test-raise",
                description="d",
                target=1.0,
                value_fn=lambda: 1 / 0,
            )
        )
        out = engine.evaluate()
        assert out["ok"]
        assert out["objectives"]["test-raise"]["value"] is None

    def test_default_objective_set(self):
        names = {o.name for o in default_objectives(epoch_interval_s=10)}
        assert {
            "freshness-p99",
            "proof-lag-p99",
            "epoch-cadence",
            "shed-rate",
            "residual-stall",
            "score-drift-linf",
        } <= names
        cadence = next(
            o for o in default_objectives(epoch_interval_s=10)
            if o.name == "epoch-cadence"
        )
        assert cadence.target == 30.0

    def test_seeded_violation_always_fails(self):
        engine = SLOEngine()
        seed_violation(engine)
        out = engine.evaluate()
        assert not out["ok"]
        assert not out["objectives"]["seeded-violation"]["ok"]

    def test_histogram_quantile(self):
        reg = type(METRICS)()
        h = reg.histogram("eigentrust_test_q", "t", buckets=(1.0, 2.0, 4.0))
        assert h.quantile(0.99) is None
        for v in (0.5, 1.5, 1.5, 3.0):
            h.observe(v)
        q50 = h.quantile(0.5)
        assert 1.0 <= q50 <= 2.0
        assert h.quantile(1.0) == 4.0
        h.observe(100.0)  # lands in +Inf: quantile clamps to last bound
        assert h.quantile(1.0) == 4.0


# ---------------------------------------------------------------------------
# Server surfaces: /healthz, /timeline, /slo
# ---------------------------------------------------------------------------


class TestServerSurfaces:
    def test_healthz_without_node_reports_from_globals(self):
        from protocol_tpu.node.server import handle_request

        status, body = handle_request("GET", "/healthz", _manager())
        health = json.loads(body)
        assert status in (200, 503)
        assert health["status"] in ("ok", "degraded", "failed")
        assert "epoch" in health["components"]

    def test_healthz_degraded_before_first_epoch_then_ok(self):
        from protocol_tpu.node.server import node_health

        TIMELINE.reset()
        status, health = node_health(None)
        assert status == 200
        assert health["status"] == "degraded"
        assert "no-epoch-yet" in health["degraded"]
        TIMELINE.record(1, tick_ended_unix=time.time())
        status, health = node_health(None)
        assert health["status"] == "ok"
        TIMELINE.reset()

    def test_healthz_failed_when_epoch_loop_stalls(self):
        from protocol_tpu.node.config import ProtocolConfig
        from protocol_tpu.node.server import Node, node_health

        TIMELINE.reset()
        TIMELINE.record(1, tick_ended_unix=time.time() - 1000.0)
        node = Node.from_config(
            ProtocolConfig(epoch_interval=2, prover="commitment")
        )
        status, health = node_health(node)
        assert status == 503
        assert health["status"] == "failed"
        assert "epoch-loop-stalled" in health["problems"]
        TIMELINE.reset()

    def test_timeline_endpoint(self):
        from protocol_tpu.node.server import handle_request

        TIMELINE.record(41, phases={"converge": 0.5})
        mgr = _manager()
        status, body = handle_request("GET", "/timeline/41", mgr)
        assert status == 200
        assert json.loads(body)["phases"]["converge"] == 0.5
        status, body = handle_request("GET", "/timeline/latest", mgr)
        assert status == 200
        status, _ = handle_request("GET", "/timeline/999999999", mgr)
        assert status == 404
        status, _ = handle_request("GET", "/timeline/nope", mgr)
        assert status == 400
        TIMELINE.reset()

    def test_slo_endpoint_evaluates(self):
        from protocol_tpu.node.server import handle_request
        from protocol_tpu.obs.slo import SLO_ENGINE

        SLO_ENGINE.register(
            SLObjective(
                name="test-endpoint",
                description="d",
                target=1.0,
                value_fn=lambda: 0.5,
            )
        )
        try:
            status, body = handle_request("GET", "/slo", _manager())
            assert status == 200
            doc = json.loads(body)
            assert doc["objectives"]["test-endpoint"]["ok"]
        finally:
            SLO_ENGINE.unregister("test-endpoint")

    def test_fleet_scrape_endpoint(self):
        from protocol_tpu.node.server import handle_request

        status, body = handle_request("GET", "/metrics/fleet", _manager())
        assert status == 200
        assert 'process="node"' in body


# ---------------------------------------------------------------------------
# Worker flight-recorder dumps (spawn-boundary post-mortems)
# ---------------------------------------------------------------------------


class TestWorkerFlightDumps:
    def test_collect_reads_journals_and_deletes(self, tmp_path):
        from protocol_tpu.obs.journal import JOURNAL, collect_worker_dumps

        dump = tmp_path / "flight-prover-123.jsonl"
        events = [{"kind": "span", "name": "msm", "seq": i} for i in range(30)]
        dump.write_text("".join(json.dumps(e) + "\n" for e in events))
        recovered = collect_worker_dumps(tmp_path, pool="prover", tail_events=5)
        assert len(recovered) == 6  # tail_events + the dump marker slot
        assert recovered[-1]["name"] == "msm"
        assert not dump.exists()
        tail = JOURNAL.tail(5)
        assert any(e["kind"] == "worker-flight-tail" for e in tail)

    def test_collect_empty_or_missing_dir(self, tmp_path):
        from protocol_tpu.obs.journal import collect_worker_dumps

        assert collect_worker_dumps(None, pool="x") == []
        assert collect_worker_dumps(tmp_path / "absent", pool="x") == []

    def test_worker_init_installs_sigterm_handler(self, tmp_path):
        """The worker-bootstrap half, exercised in-process: install the
        handler, then invoke it the way signal delivery would (in a
        child fork so os._exit doesn't kill the test runner)."""
        import signal
        import subprocess
        import sys

        code = f"""
import json, os, signal, sys
sys.path.insert(0, {json.dumps(str(tmp_path.parent))!s})
sys.path.insert(0, {json.dumps(os.getcwd())})
from protocol_tpu.obs.journal import JOURNAL, install_worker_dump_handler
install_worker_dump_handler({json.dumps(str(tmp_path))}, pool="prover")
JOURNAL.record("test-event", n=1)
os.kill(os.getpid(), signal.SIGTERM)
"""
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, timeout=60
        )
        assert proc.returncode == 143, proc.stderr.decode()
        dumps = list(tmp_path.glob("flight-prover-*.jsonl"))
        assert len(dumps) == 1
        lines = [json.loads(x) for x in dumps[0].read_text().splitlines()]
        assert any(e.get("kind") == "test-event" for e in lines)
        assert lines[-1]["kind"] == "journal-dump"


# ---------------------------------------------------------------------------
# Metric catalog doc (METRICS.md)
# ---------------------------------------------------------------------------


class TestMetricsCatalogDoc:
    def test_committed_catalog_matches_registry(self):
        """METRICS.md is generated from the registry; any emitted-but-
        undocumented metric (or stale row) fails here.  Regenerate with
        `python tools/gen_metrics_md.py`."""
        import pathlib
        import sys

        root = pathlib.Path(__file__).resolve().parent.parent
        sys.path.insert(0, str(root / "tools"))
        try:
            from gen_metrics_md import metrics_markdown
        finally:
            sys.path.pop(0)
        committed = (root / "METRICS.md").read_text()
        assert committed == metrics_markdown(), (
            "METRICS.md is stale — run `python tools/gen_metrics_md.py`"
        )

    def test_every_registered_metric_documented(self):
        import pathlib

        root = pathlib.Path(__file__).resolve().parent.parent
        committed = (root / "METRICS.md").read_text()
        for metric in METRICS.collect():
            assert f"`{metric.name}`" in committed, (
                f"metric {metric.name} emitted but not documented in METRICS.md"
            )


# ---------------------------------------------------------------------------
# End-to-end: lineage through the ingest plane
# ---------------------------------------------------------------------------


class TestLineageThroughIngestPlane:
    def test_accepted_attestation_walks_the_stages(self):
        from protocol_tpu.ingest import IngestPlane, IngestPlaneConfig

        mgr = _manager()
        LINEAGE.configure(1)
        LINEAGE.reset()
        before = FRESHNESS_SECONDS.count(stage="applied")
        atts = list(mgr.attestations.values())
        try:
            with IngestPlane(mgr, IngestPlaneConfig(workers=0)) as plane:
                future = plane.submit(atts[0])
                result = future.result(timeout=30)
                assert result.accepted, result
                assert plane.drain(timeout=30)
            assert FRESHNESS_SECONDS.count(stage="applied") == before + 1
            snap = LINEAGE.snapshot()
            assert snap["by_stage"].get("applied") == 1
            # The epoch absorbs it; its proof completes it.
            bound = LINEAGE.bind_epoch(12)
            assert len(bound) == 1
            assert len(LINEAGE.epoch_proved(12)) == 1
        finally:
            LINEAGE.configure(0)
            LINEAGE.reset()

    def test_rejected_attestation_drops_lineage(self):
        from protocol_tpu.ingest import IngestPlane, IngestPlaneConfig

        mgr = _manager()
        LINEAGE.configure(1)
        LINEAGE.reset()
        atts = list(mgr.attestations.values())
        try:
            with IngestPlane(mgr, IngestPlaneConfig(workers=0)) as plane:
                # Same digest twice: the second dies in dedup.
                plane.submit(atts[0]).result(timeout=30)
                result = plane.submit(atts[0]).result(timeout=30)
                assert not result.accepted
                assert plane.drain(timeout=30)
            snap = LINEAGE.snapshot()
            assert snap["live"] == 1  # only the accepted one survives
        finally:
            LINEAGE.configure(0)
            LINEAGE.reset()
