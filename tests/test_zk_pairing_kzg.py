"""Pairing, native proving runtime, and KZG commitment tests.

Covers the curve/commitment layer the reference gets from halo2curves +
halo2's KZG backend (circuit/src/utils.rs:198-321): the Bn254 tower and
ate pairing, the C++ NTT/MSM/eval kernels (parity against pure Python),
and commit/open/verify round trips.
"""

import random

import pytest

from protocol_tpu.crypto.field import MODULUS as R
from protocol_tpu.zk import native
from protocol_tpu.zk.bn254 import G1, GENERATOR, IDENTITY, is_on_curve
from protocol_tpu.zk.fields import (
    FQ2,
    FQP,
    G2_GENERATOR,
    g2_in_subgroup,
    g2_is_on_curve,
    pairing,
    pairing_check,
)
from protocol_tpu.zk.kzg import Setup, _eval_poly, _msm_python, msm

rnd = random.Random(0xE1)


# -- tower ------------------------------------------------------------


def test_fq2_arithmetic():
    from protocol_tpu.zk.rns import FQ_MODULUS as Q

    # (3 + 5u)(7 + 11u) = 21 + 68u + 55u^2 = (21 - 55) + 68u  (u^2 = -1)
    a = FQ2([3, 5])
    b = FQ2([7, 11])
    assert (a * b).coeffs == [(21 - 55) % Q, 68]


def test_fqp_inverse_roundtrip():
    x = FQP([rnd.randrange(1 << 60) for _ in range(12)])
    assert x * x.inv() == FQP.one()


def test_fq2_inverse_roundtrip():
    x = FQ2([rnd.randrange(1 << 60), rnd.randrange(1 << 60)])
    assert (x * x.inv()).coeffs == [1, 0]


# -- G2 ---------------------------------------------------------------


def test_g2_generator_on_curve_and_in_subgroup():
    assert g2_is_on_curve(G2_GENERATOR)
    assert g2_in_subgroup(G2_GENERATOR)


def test_g2_group_laws():
    p2 = G2_GENERATOR.double()
    assert g2_is_on_curve(p2)
    assert G2_GENERATOR.add(G2_GENERATOR) == p2
    assert G2_GENERATOR.mul(5) == p2.add(p2).add(G2_GENERATOR)
    assert G2_GENERATOR.add(G2_GENERATOR.neg()).is_identity()


# -- pairing ----------------------------------------------------------


def test_pairing_non_degenerate():
    e = pairing(G2_GENERATOR, GENERATOR)
    assert e != FQP.one()
    assert e.pow(R) == FQP.one()


def test_pairing_bilinearity():
    a, b = 1234567, 987654321
    e = pairing(G2_GENERATOR, GENERATOR)
    assert pairing(G2_GENERATOR.mul(b), GENERATOR.mul(a)) == e.pow(a * b % R)
    assert pairing(G2_GENERATOR, GENERATOR.mul(a)) == e.pow(a)


def test_pairing_check_product():
    # e(5G, H) * e(-5G, H) == 1 ; replacing -5 with -4 must fail.
    g5 = GENERATOR.mul(5)
    assert pairing_check([(g5, G2_GENERATOR), (g5.neg(), G2_GENERATOR)])
    assert not pairing_check(
        [(g5, G2_GENERATOR), (GENERATOR.mul(4).neg(), G2_GENERATOR)]
    )


def test_pairing_identity_inputs():
    assert pairing(G2_GENERATOR, IDENTITY) == FQP.one()


# -- native runtime ---------------------------------------------------

needs_native = pytest.mark.skipif(
    not native.available(), reason="zk native runtime not built"
)


@needs_native
def test_ntt_roundtrip_and_evaluation():
    n = 64
    root = pow(7, (R - 1) // n, R)
    vals = [rnd.randrange(R) for _ in range(n)]
    f = native.ntt(list(vals), root)
    assert native.ntt(f, pow(root, -1, R), inverse=True) == vals
    # The forward transform evaluates at root^i.
    x = pow(root, 5, R)
    assert f[5] == sum(c * pow(x, i, R) for i, c in enumerate(vals)) % R


@needs_native
def test_batch_inv_with_zeros():
    a = [rnd.randrange(1, R) for _ in range(17)] + [0]
    inv = native.batch_inv(a)
    assert inv[-1] == 0
    assert all(x * y % R == 1 for x, y in zip(a[:-1], inv[:-1]))


@needs_native
def test_msm_native_matches_python():
    pts = [GENERATOR.mul(rnd.randrange(1, 10_000)) for _ in range(64)] + [IDENTITY]
    scs = [rnd.randrange(R) for _ in range(65)]
    assert native.msm(scs, pts) == _msm_python(scs, pts)


@needs_native
def test_srs_powers_native():
    tau = 987654321987654321
    powers = native.srs_g1_powers(tau, 32)
    for i in (0, 1, 13, 31):
        assert powers[i] == GENERATOR.mul(pow(tau, i, R))


def test_msm_python_small():
    pts = [GENERATOR, GENERATOR.mul(2)]
    assert _msm_python([3, 4], pts) == GENERATOR.mul(11)
    assert _msm_python([], []) == IDENTITY


# -- KZG --------------------------------------------------------------


@pytest.fixture(scope="module")
def setup_k6():
    return Setup.generate(6)


def test_kzg_srs_structure(setup_k6):
    s = setup_k6
    assert len(s.g1_powers) == 64
    assert s.g1_powers[0] == GENERATOR
    assert all(is_on_curve(p) for p in s.g1_powers[:4])
    # tau consistency across G1/G2: e(tau G1, G2) == e(G1, tau G2)
    assert pairing(s.g2, s.g1_powers[1]) == pairing(s.tau_g2, GENERATOR)


def test_kzg_commit_open_verify(setup_k6):
    s = setup_k6
    coeffs = [rnd.randrange(R) for _ in range(64)]
    c = s.commit(coeffs)
    z = rnd.randrange(R)
    y, w = s.open(coeffs, z)
    assert y == _eval_poly(coeffs, z)
    assert s.verify(c, z, y, w)
    assert not s.verify(c, z, (y + 1) % R, w)
    assert not s.verify(c, (z + 1) % R, y, w)


def test_kzg_linearity(setup_k6):
    """com(f + g) == com(f) + com(g) — the homomorphism the batch
    opening argument relies on."""
    s = setup_k6
    f = [rnd.randrange(R) for _ in range(32)]
    g = [rnd.randrange(R) for _ in range(32)]
    fg = [(a + b) % R for a, b in zip(f, g)]
    assert s.commit(fg) == s.commit(f).add(s.commit(g))


def test_kzg_serialization_roundtrip(setup_k6):
    s = setup_k6
    s2 = Setup.from_bytes(s.to_bytes())
    assert s2.k == s.k
    assert s2.g1_powers == s.g1_powers
    assert s2.g2 == s.g2 and s2.tau_g2 == s.tau_g2


def test_kzg_shrink(setup_k6):
    s = setup_k6
    s5 = s.shrink(5)
    assert s5.g1_powers == s.g1_powers[:32]
    coeffs = [rnd.randrange(R) for _ in range(32)]
    c = s5.commit(coeffs)
    z = rnd.randrange(R)
    y, w = s5.open(coeffs, z)
    assert s5.verify(c, z, y, w)


def test_msm_dispatcher(setup_k6):
    scs = [rnd.randrange(R) for _ in range(40)]
    pts = setup_k6.g1_powers[:40]
    assert msm(scs, pts) == _msm_python(scs, pts)
