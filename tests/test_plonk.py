"""PLONK prover/verifier tests — the tier-3 analog of the reference's
real-proving ladder (circuit.rs:556-620 prove_and_verify): every proof
is checked end-to-end through the KZG pairing, with tampered-proof and
wrong-instance negatives.

The full 5-peer epoch statement (k=14; ~8.4 s proving + ~13 s cold
keygen, amortized by the on-disk key cache) runs when
PROTOCOL_TPU_SLOW_TESTS=1; the default suite exercises the same
machinery (chunked permutation, rotation gates, fixed columns,
blinding) on smaller circuits, and drives one real 2-peer epoch →
PLONK → EVM-verify flow through the Manager.
"""

import os

import pytest

from protocol_tpu.crypto import field
from protocol_tpu.crypto.poseidon import permute
from protocol_tpu.zk import plonk
from protocol_tpu.zk.cs import ConstraintSystem
from protocol_tpu.zk.gadgets import Bits2NumChip, PoseidonChip, StdGate

P = field.MODULUS


def _mul_add_circuit():
    """out = 3*4 + 5, bound to the public instance."""
    cs = ConstraintSystem()
    std = StdGate(cs)
    x, y, c5 = std.witness(3), std.witness(4), std.witness(5)
    out = std.add(std.mul(x, y), c5)
    inst = cs.column("instance", "instance")
    cs.copy(cs.assign(inst, 0, 17), out)
    cs.assert_satisfied()
    return cs


class TestSymTracing:
    def test_trace_matches_direct_eval(self):
        """A traced gate evaluated symbolically at scalar values must
        match the constraint system's own row evaluation."""
        cs = _mul_add_circuit()
        pk = plonk.compile_circuit(cs)
        # All gates must trace to pure-arithmetic Syms.
        for spec in pk.vk.gates:
            assert spec.constraints
            for con in spec.constraints:
                assert con.deg >= 1

    def test_linearize_roundtrip(self):
        s = (plonk.Sym.col(0) * plonk.Sym.col(1) - plonk.Sym.const(7)) * plonk.Sym.col(
            0, 1
        )
        vals = {(0, 0): 3, (1, 0): 5, (0, 1): 11}
        direct = plonk.sym_eval(s, lambda sl, r: vals[(sl, r)])
        assert direct == (3 * 5 - 7) * 11 % P
        code, pool = [], {}
        depth = plonk.linearize(s, {0: 0, 1: 1}, pool, code)
        assert depth <= 4 and code


class TestPlonkSmall:
    def test_roundtrip(self):
        cs = _mul_add_circuit()
        pk = plonk.compile_circuit(cs)
        proof = plonk.prove(pk, cs, [17], seed=b"t")
        assert plonk.verify(pk.vk, [17], proof)

    def test_wrong_instance_rejected(self):
        cs = _mul_add_circuit()
        pk = plonk.compile_circuit(cs)
        proof = plonk.prove(pk, cs, [17], seed=b"t")
        assert not plonk.verify(pk.vk, [18], proof)

    def test_tampered_proof_rejected(self):
        cs = _mul_add_circuit()
        pk = plonk.compile_circuit(cs)
        proof = plonk.prove(pk, cs, [17], seed=b"t")
        for off in (0, len(proof) // 2, len(proof) - 1):
            bad = bytearray(proof)
            bad[off] ^= 1
            assert not plonk.verify(pk.vk, [17], bytes(bad))

    def test_truncated_and_extended_proofs_rejected(self):
        cs = _mul_add_circuit()
        pk = plonk.compile_circuit(cs)
        proof = plonk.prove(pk, cs, [17], seed=b"t")
        assert not plonk.verify(pk.vk, [17], proof[:-32])
        assert not plonk.verify(pk.vk, [17], proof + b"\x00" * 32)

    def test_blinding_changes_proof_not_validity(self):
        """Two proofs of the same statement with different blinding
        randomness differ byte-wise but both verify (the zk property's
        observable half)."""
        cs = _mul_add_circuit()
        pk = plonk.compile_circuit(cs)
        p1 = plonk.prove(pk, cs, [17], seed=b"a")
        p2 = plonk.prove(pk, cs, [17], seed=b"b")
        assert p1 != p2
        assert plonk.verify(pk.vk, [17], p1) and plonk.verify(pk.vk, [17], p2)

    def test_forged_witness_unsatisfying_trace(self):
        """A trace that satisfies the mock checker is provable; one that
        doesn't produces a proof the verifier rejects (the quotient
        division leaves a non-vanishing remainder)."""
        cs = ConstraintSystem()
        std = StdGate(cs)
        x, y = std.witness(3), std.witness(4)
        out = std.mul(x, y)
        inst = cs.column("instance", "instance")
        cs.copy(cs.assign(inst, 0, 12), out)
        pk = plonk.compile_circuit(cs)
        # Corrupt the witness after keygen: claim 3*4 = 13.
        cs2 = ConstraintSystem()
        std2 = StdGate(cs2)
        x2, y2 = std2.witness(3), std2.witness(4)
        r = std2.row(
            {std2.a: x2, std2.b: y2, std2.c: 13}, {"s_ab": 1, "sc": P - 1}
        )
        from protocol_tpu.zk.cs import Cell

        cs2.copy(cs2.assign(cs2.column("instance", "instance"), 0, 13), Cell(std2.c, r))
        assert cs2.verify()  # mock checker catches it
        proof = plonk.prove(pk, cs2, [13], seed=b"t")
        assert not plonk.verify(pk.vk, [13], proof)


class TestPlonkPoseidon:
    """Rotation gates, fixed round-constant columns, multi-chunk
    permutation."""

    def test_poseidon_circuit_roundtrip(self):
        cs = ConstraintSystem()
        std = StdGate(cs)
        pos = PoseidonChip(cs)
        ins = [std.witness(i + 1) for i in range(5)]
        outs = pos.permute(ins)
        expected = permute([1, 2, 3, 4, 5])
        inst = cs.column("instance", "instance")
        cs.copy(cs.assign(inst, 0, expected[0]), outs[0])
        cs.assert_satisfied()
        pk = plonk.compile_circuit(cs)
        assert len(pk.vk.chunks) >= 2  # chunked permutation exercised
        proof = plonk.prove(pk, cs, [expected[0]], seed=b"x")
        assert plonk.verify(pk.vk, [expected[0]], proof)
        assert not plonk.verify(pk.vk, [(expected[0] + 1) % P], proof)

    def test_bits2num_rotation_gate(self):
        cs = ConstraintSystem()
        std = StdGate(cs)
        b2n = Bits2NumChip(cs)
        val = std.witness(0b101101)
        bits = b2n.decompose(val, 8)
        assert [cs.value(b.column, b.row) for b in bits[:6]] == [1, 0, 1, 1, 0, 1]
        inst = cs.column("instance", "instance")
        cs.copy(cs.assign(inst, 0, 0b101101), val)
        cs.assert_satisfied()
        pk = plonk.compile_circuit(cs)
        proof = plonk.prove(pk, cs, [0b101101], seed=b"y")
        assert plonk.verify(pk.vk, [0b101101], proof)
        assert not plonk.verify(pk.vk, [0b101100], proof)


class TestPlonkLookup:
    """The lookup argument (Halo2-style A'/S' + grand product) proving
    RangeCheckChip circuits under the real SNARK."""

    def _range_circuit(self):
        from protocol_tpu.zk.chips import RangeCheckChip

        cs = ConstraintSystem()
        std = StdGate(cs)
        rng = RangeCheckChip(cs, word_bits=4)
        x = std.witness(13)
        rng.assert_word(x)
        y = std.witness(200)
        rng.assert_range(y, 2)
        inst = cs.column("instance", "instance")
        cs.copy(cs.assign(inst, 0, 13), x)
        cs.assert_satisfied()
        return cs

    def test_range_lookup_roundtrip(self):
        cs = self._range_circuit()
        pk = plonk.compile_circuit(cs)
        assert len(pk.vk.lookups) == 1
        proof = plonk.prove(pk, cs, [13], seed=b"lk")
        assert plonk.verify(pk.vk, [13], proof)
        assert not plonk.verify(pk.vk, [14], proof)
        bad = bytearray(proof)
        bad[100] ^= 1
        assert not plonk.verify(pk.vk, [13], bytes(bad))

    def test_out_of_table_witness_unprovable(self):
        from protocol_tpu.zk.chips import RangeCheckChip

        cs = self._range_circuit()
        pk = plonk.compile_circuit(cs)
        cs2 = ConstraintSystem()
        std2 = StdGate(cs2)
        rng2 = RangeCheckChip(cs2, word_bits=4)
        x2 = std2.witness(21)  # 21 >= 16: not in the 4-bit table
        r = cs2.alloc_rows(1)
        cs2.copy(cs2.assign(rng2.word, r, 21), x2)
        cs2.enable(rng2._sel_word, r)
        y2 = std2.witness(200)
        rng2.assert_range(y2, 2)
        cs2.copy(cs2.assign(cs2.column("instance", "instance"), 0, 21), x2)
        with pytest.raises(AssertionError, match="not in table"):
            plonk.prove(pk, cs2, [21], seed=b"bad")

    def test_table_forces_domain_growth(self):
        """A 2^8 table in a tiny circuit still compiles (k grows to fit
        the table rows)."""
        from protocol_tpu.zk.chips import RangeCheckChip

        cs = ConstraintSystem()
        std = StdGate(cs)
        rng = RangeCheckChip(cs, word_bits=8)
        x = std.witness(250)
        rng.assert_word(x)
        inst = cs.column("instance", "instance")
        cs.copy(cs.assign(inst, 0, 250), x)
        pk = plonk.compile_circuit(cs)
        assert pk.vk.n >= 257
        proof = plonk.prove(pk, cs, [250], seed=b"t8")
        assert plonk.verify(pk.vk, [250], proof)


class TestDomain:
    def test_fft_roundtrip(self):
        d = plonk.Domain(5)
        coeffs = [i * 31 + 7 for i in range(20)]
        evals = d.fft(coeffs)
        back = d.ifft(evals)
        assert back[:20] == [c % P for c in coeffs]
        assert all(c == 0 for c in back[20:])

    def test_lagrange_eval_matches_poly(self):
        k = 4
        d = plonk.Domain(k)
        vals = {0: 5, 3: 11, 7: 2}
        dense = [0] * d.n
        for i, v in vals.items():
            dense[i] = v
        coeffs = d.ifft(dense)
        x = 0x1234567
        from protocol_tpu.zk.kzg import _eval_poly

        assert plonk._lagrange_eval(vals, x, k) == _eval_poly(coeffs, x)


class TestEpochProofSmall:
    """Default-suite flagship-path coverage: a real epoch → PLONK →
    EVM-verify roundtrip through the Manager at the smallest viable
    statement (2 peers, 1 iteration).  Keygen hits the on-disk key
    cache after the first run."""

    def test_manager_epoch_plonk_evm_roundtrip(self):
        from protocol_tpu.node.bootstrap import FIXED_SET
        from protocol_tpu.node.epoch import Epoch
        from protocol_tpu.node.manager import Manager, ManagerConfig
        from protocol_tpu.utils.telemetry import TELEMETRY
        from protocol_tpu.zk.evm_verifier import evm_verify

        mgr = Manager(
            ManagerConfig(
                prover="plonk",
                num_neighbours=2,
                num_iter=1,
                fixed_set=list(FIXED_SET[:2]),
            )
        )
        mgr.generate_initial_attestations()
        epoch = Epoch(1)
        mgr.calculate_proofs(epoch)
        proof = mgr.cached_proofs[epoch]
        assert mgr.prover.name == "plonk-kzg"
        assert mgr.prover.verify(proof.pub_ins, proof.proof)
        # Proving time must land in telemetry (the reference's
        # "Proving time" print, circuit/src/utils.rs:305-321).
        assert TELEMETRY.snapshot()["timers"]["epoch.prove"]["count"] >= 1
        # On-chain leg: generate the EVM verifier for this circuit and
        # verify the epoch proof on it (the epoch proof pins the
        # quotient-chunk count, so no extra sample prove is needed).
        from protocol_tpu.zk.evm_verifier import generate_evm_verifier, infer_n_t

        vk = mgr.prover.vk
        gen = generate_evm_verifier(vk, infer_n_t(vk, proof.proof), 2)
        ok, gas = evm_verify(gen, proof.pub_ins, proof.proof)
        assert ok and gas > 0
        bad = [(proof.pub_ins[0] + 1) % P] + proof.pub_ins[1:]
        assert not evm_verify(gen, bad, proof.proof)[0]


@pytest.mark.skipif(
    not os.environ.get("PROTOCOL_TPU_SLOW_TESTS"),
    reason="full 5-peer epoch proof: ~8.4 s prove + ~13 s cold keygen; "
    "set PROTOCOL_TPU_SLOW_TESTS=1",
)
class TestEpochProof:
    def test_epoch_statement_real_proof(self):
        from protocol_tpu.crypto import calculate_message_hash
        from protocol_tpu.crypto.eddsa import sign
        from protocol_tpu.node.attestation import Attestation
        from protocol_tpu.node.bootstrap import FIXED_SET, keyset_from_raw
        from protocol_tpu.trust.native import power_iterate
        from protocol_tpu.zk.circuit import prove_epoch_statement

        sks, pks = keyset_from_raw(FIXED_SET)
        rows = [[200] * 5 for _ in range(5)]
        _, messages = calculate_message_hash(pks, rows)
        atts = [
            Attestation(sig=sign(sk, pk, m), pk=pk, neighbours=list(pks), scores=r)
            for sk, pk, m, r in zip(sks, pks, messages, rows)
        ]
        pub = power_iterate([1000] * 5, rows, 10, 1000)
        cs = prove_epoch_statement(atts, pub)
        pk = plonk.compile_circuit(cs)
        assert pk.vk.k == 14  # same circuit size class as the reference
        proof = plonk.prove(pk, cs, pub, seed=b"epoch")
        assert plonk.verify(pk.vk, pub, proof)
        bad = list(pub)
        bad[0] = (bad[0] + 1) % P
        assert not plonk.verify(pk.vk, bad, proof)

    def test_manager_with_plonk_prover(self):
        """Node integration: a Manager configured with the PLONK
        backend serves a real SNARK from calculate_proofs (the
        reference's boot keygen + epoch proving flow,
        server/src/main.rs:70-83, manager/mod.rs:170-214)."""
        from protocol_tpu.node.epoch import Epoch
        from protocol_tpu.node.manager import Manager, ManagerConfig

        mgr = Manager(ManagerConfig(prover="plonk"))
        mgr.generate_initial_attestations()
        epoch = Epoch(1)
        mgr.calculate_proofs(epoch)
        proof = mgr.cached_proofs[epoch]
        assert mgr.prover.name == "plonk-kzg"
        assert mgr.prover.verify(proof.pub_ins, proof.proof)
        assert not mgr.prover.verify(
            [(proof.pub_ins[0] + 1) % P] + proof.pub_ins[1:], proof.proof
        )


class TestAggregationSurface:
    """Node-reachable proof aggregation (manager.aggregate_proofs +
    GET /aggregate): the reference left its aggregator unwired; here
    batch verification is a served feature."""

    def test_commitment_prover_rejects_aggregation(self):
        from protocol_tpu.node.epoch import Epoch
        from protocol_tpu.node.errors import EigenError
        from protocol_tpu.node.manager import Manager, ManagerConfig
        from protocol_tpu.node.server import handle_request

        mgr = Manager(ManagerConfig(prover="commitment"))
        mgr.generate_initial_attestations()
        mgr.calculate_proofs(Epoch(1))
        with pytest.raises(EigenError):
            mgr.aggregate_proofs([Epoch(1)])
        status, _ = handle_request("GET", "/aggregate?epochs=1", mgr)
        assert status == 400

    def test_aggregate_bad_queries(self):
        from protocol_tpu.node.manager import Manager, ManagerConfig
        from protocol_tpu.node.server import handle_request

        mgr = Manager(ManagerConfig(prover="commitment"))
        assert handle_request("GET", "/aggregate", mgr)[0] == 400
        assert handle_request("GET", "/aggregate?epochs=", mgr)[0] == 400
        assert handle_request("GET", "/aggregate?epochs=x", mgr)[0] == 400
        assert handle_request("GET", "/aggregate?epochs=9", mgr)[0] == 400  # no proof


@pytest.mark.skipif(
    not os.environ.get("PROTOCOL_TPU_SLOW_TESTS"),
    reason="aggregating two real epoch SNARKs proves twice (~10 s); "
    "set PROTOCOL_TPU_SLOW_TESTS=1",
)
class TestAggregationSurfaceSlow:
    def test_manager_aggregates_two_epochs(self):
        import json

        from protocol_tpu.node.bootstrap import FIXED_SET
        from protocol_tpu.node.epoch import Epoch
        from protocol_tpu.node.manager import Manager, ManagerConfig
        from protocol_tpu.node.server import handle_request

        mgr = Manager(
            ManagerConfig(
                prover="plonk",
                num_neighbours=2,
                num_iter=1,
                fixed_set=list(FIXED_SET[:2]),
            )
        )
        mgr.generate_initial_attestations()
        mgr.calculate_proofs(Epoch(3))
        mgr.calculate_proofs(Epoch(7))
        ok, acc = mgr.aggregate_proofs([Epoch(3), Epoch(7)])
        assert ok and acc is not None

        status, body = handle_request("GET", "/aggregate?epochs=3,7", mgr)
        obj = json.loads(body)
        assert status == 200 and obj["ok"] and obj["epochs"] == [3, 7]
        assert len(bytes.fromhex(obj["accumulator"])) == 128

        # A tampered cached proof must fail the batch.
        proof = mgr.cached_proofs[Epoch(7)]
        bad = bytearray(proof.proof)
        bad[11] ^= 1
        mgr.cached_proofs[Epoch(7)] = type(proof)(
            pub_ins=proof.pub_ins, proof=bytes(bad)
        )
        ok2, _ = mgr.aggregate_proofs([Epoch(3), Epoch(7)])
        assert not ok2
