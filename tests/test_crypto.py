"""Crypto layer tests: golden vectors baked from the reference's inline
test modules (SURVEY.md §4 tier 1)."""

import pytest

from protocol_tpu.crypto import babyjubjub as bjj
from protocol_tpu.crypto import calculate_message_hash, field
from protocol_tpu.crypto.blake512 import blake512
from protocol_tpu.crypto.eddsa import PublicKey, SecretKey, Signature, sign, verify
from protocol_tpu.crypto.merkle import MerkleTree, Path
from protocol_tpu.crypto.poseidon import (
    POSEIDON_10,
    PoseidonSponge,
    permute,
    rescue_prime_permute,
)
from protocol_tpu.utils.codec import b58decode, b58encode, to_short


class TestField:
    def test_roundtrip_bytes(self):
        v = 0x1234567890ABCDEF << 128
        assert field.from_le_bytes(field.to_le_bytes(v)) == v

    def test_non_canonical_rejected(self):
        bad = (field.MODULUS).to_bytes(32, "little")
        with pytest.raises(ValueError):
            field.from_le_bytes(bad)

    def test_wide_reduction(self):
        wide = b"\xff" * 64
        assert field.from_wide_bytes(wide) == int.from_bytes(wide, "little") % field.MODULUS

    def test_inv(self):
        a = 123456789
        assert field.mul(a, field.inv(a)) == 1
        with pytest.raises(ZeroDivisionError):
            field.inv(0)

    def test_bits_lsb_first(self):
        assert field.to_bits(b"\x05")[:4] == [True, False, True, False]


class TestPoseidon:
    def test_permute_5x5_golden(self):
        # circuit/src/poseidon/native/mod.rs:108-134
        out = permute([0, 1, 2, 3, 4])
        expected = [
            0x299C867DB6C1FDD79DCEFA40E4510B9837E60EBB1CE0663DBAA525DF65250465,
            0x1148AAEF609AA338B27DAFD89BB98862D8BB2B429ACEAC47D86206154FFE053D,
            0x24FEBB87FED7462E23F6665FF9A0111F4044C38EE1672C1AC6B0637D34F24907,
            0x0EB08F6D809668A981C186BEAF6110060707059576406B248E5D9CF6E78B3D3E,
            0x07748BC6877C9B82C8B98666EE9D0626EC7F5BE4205F79EE8528EF1C4A376FC7,
        ]
        assert out == expected

    def test_permute_10x5_shape(self):
        out = permute(list(range(10)), POSEIDON_10)
        assert len(out) == 10 and all(0 <= x < field.MODULUS for x in out)

    def test_rescue_prime_golden(self):
        # circuit/src/rescue_prime/native/mod.rs:68-96 (matter-labs vectors)
        out = rescue_prime_permute([0, 1, 2, 3, 4])
        expected = [
            0x1A06EA09AF4D8D61F991846F001DED4056FEAFCEF55F1E9C4FD18100B8C7654F,
            0x2F66D057B2BD9692F51E072013B8F320C5E6D7081070FFE7CA357E18E5FAECF4,
            0x177ABF3B6A2E903ADF4C71F18F744B55B39C487A9A4FD1A1D4AEE381B99F357B,
            0x1271BFA104C298EFACCC1680BE1B6E36CBF2C87EA789F2F79F7742BC16992235,
            0x040F785ABFAD4DA68331F9C884343FA6EECB07060EBCD96117862ACEBAE5C3AC,
        ]
        assert out == expected

    def test_sponge_chunks(self):
        # Sponge over 10 elements = two chunk-permutes; must differ from a
        # single-block hash and be deterministic.
        s = PoseidonSponge()
        s.update(list(range(10)))
        h1 = s.squeeze()
        s2 = PoseidonSponge()
        s2.update(list(range(5)))
        s2.update(list(range(5, 10)))
        assert h1 == s2.squeeze()

    def test_sponge_empty_asserts(self):
        with pytest.raises(AssertionError):
            PoseidonSponge().squeeze()


class TestBlake512:
    def test_one_zero_byte(self):
        # BLAKE SHA-3 submission test vector (single 0x00 byte).
        assert blake512(b"\x00").hex() == (
            "97961587f6d970faba6d2478045de6d1fabd09b61ae50932054d52bc29d31be4"
            "ff9102b9f69e2bbdb83be13d4b9c06091e5fa0b48bd081b634058be0ec49beb3"
        )

    def test_144_zero_bytes(self):
        # Two-block vector from the submission (exercises the counter).
        assert blake512(bytes(144)).hex() == (
            "313717d608e9cf758dcb1eb0f0c3cf9fc150b2d500fb33f51c52afc99d358a2f"
            "1374b8a38bba7974e7f6ef79cab16f22ce1e649d6e01ad9589c213045d545dde"
        )

    def test_length_111_boundary(self):
        # 111 bytes mod 128: both padding bits share one byte; just check
        # it digests without error and differs from neighbours.
        assert blake512(bytes(111)) != blake512(bytes(112))


class TestBabyJubJub:
    # circuit/src/edwards/native.rs:95-247 vectors.
    PX = 17777552123799933955779906779655732241715742912184938656739573121738514868268
    PY = 2626589144620713026669568689430873010625803728049924121243784502389097019475

    def test_add_same_point(self):
        p = bjj.Point(self.PX, self.PY).projective()
        r = p.add(p).affine()
        assert r.x == 6890855772600357754907169075114257697580319025794532037257385534741338397365
        assert r.y == 4338620300185947561074059802482547481416142213883829469920100239455078257889

    def test_add_different_points(self):
        p = bjj.Point(self.PX, self.PY).projective()
        q = bjj.Point(
            16540640123574156134436876038791482806971768689494387082833631921987005038935,
            20819045374670962167435360035096875258406992893633759881276124905556507972311,
        ).projective()
        r = p.add(q).affine()
        assert r.x == 7916061937171219682591368294088513039687205273691143098332585753343424131937
        assert r.y == 14035240266687799601661095864649209771790948434046947201833777492504781204499

    def test_mul_scalar(self):
        p = bjj.Point(self.PX, self.PY)
        r3 = p.mul_scalar(3).affine()
        via_add = p.projective().add(p.projective()).add(p.projective()).affine()
        assert r3 == via_add
        assert r3.x == 19372461775513343691590086534037741906533799473648040012278229434133483800898
        assert r3.y == 9458658722007214007257525444427903161243386465067105737478306991484593958249
        n = 14035240266687799601661095864649209771790948434046947201833777492504781204499
        r = p.mul_scalar(n).affine()
        assert r.x == 17070357974431721403481313912716834497662307308519659060910483826664480189605
        assert r.y == 4014745322800118607127020275658861516666525056516280575712425373174125159339

    def test_generators_on_curve(self):
        assert bjj.is_on_curve(bjj.B8)
        assert bjj.is_on_curve(bjj.G)
        assert bjj.B8.mul_scalar(bjj.SUBORDER).affine() == bjj.Point(0, 1)


# The reference's hard-coded bootstrap identities
# (server/src/manager/mod.rs:40-69).
FIXED_SET = [
    ("2L9bbXNEayuRMMbrWFynPtgkrXH1iBdfryRH9Soa8M67", "9rBeBVtbN2MkHDTpeAouqkMWNFJC6Bxb6bXH9jUueWaF"),
    ("ARVqgNQtnV4JTKqgajGEpuapYEnWz93S5vwRDoRYWNh8", "2u1LC2JmKwkzUccS9hd5yS2DUUGTuYQ8MA7y28A9SgQY"),
    ("phhPpTLWJbC4RM39Ww3e6wWvZnVkk86iNAXyA1tRAHJ", "93aMkAqd7AY4c3m6ij6RuBzw3F9QYhQsAMnkKF2Ck2R8"),
    ("Bp3FqLd6Man9h7xujkbYDdhyF42F2dX871SJHvo3xsnU", "AUUqgGTvqzPetRMQdTrQ1xHnwz2BHDxPTi85wL4WYQaK"),
    ("AKo18M6YSE1dQQuXt4HfWNrXA6dKXBVkWVghEi6827u1", "ArT8Kk13Heai2UPbMbrqs3RuVm4XXFN2pVHttUnKpDoV"),
]
PUBLIC_KEY_HASHES = [
    "92tZdMN2SjXbT9byaHHt7hDDNXUphjwRt5UB3LDbgSmR",
    "8uFaYMkkACmnUBRZyA9JbWVjP1KN1BA53wcfKHhGE3kg",
    "DqVjJk7pBjnLXGVsCdD8SVQZLF3SZyypCB6SBJobwUMc",
    "tbXeMMQDSs3XuKUJuzJyU2jTzr66iWtHaMb2eKiqUFM",
    "Gz4dAnn3ex5Pq2vZQyJ94EqDdxpFaY74GJDFuuALvD6b",
]


class TestEddsa:
    def test_fixed_set_public_key_hashes(self):
        """End-to-end parity: the bs58 pk-hashes of the reference's
        FIXED_SET must reproduce its PUBLIC_KEYS table exactly."""
        for (sk0, sk1), expected in zip(FIXED_SET, PUBLIC_KEY_HASHES):
            pk = SecretKey.from_bs58(sk0, sk1).public()
            assert b58encode(field.to_le_bytes(pk.hash())) == expected

    def test_sign_and_verify(self):
        sk = SecretKey.random()
        pk = sk.public()
        m = 123456789012345678901234567890
        sig = sign(sk, pk, m)
        assert verify(sig, pk, m)

    def test_invalid_big_r(self):
        sk = SecretKey.random()
        pk = sk.public()
        m = 123456789012345678901234567890
        sig = sign(sk, pk, m)
        different_r = permute([0, 1, 1, 0, 0])[0]
        bad = Signature(bjj.B8.mul_scalar(different_r).affine(), sig.s)
        assert not verify(bad, pk, m)

    def test_invalid_s(self):
        sk = SecretKey.random()
        pk = sk.public()
        m = 123456789012345678901234567890
        sig = sign(sk, pk, m)
        assert not verify(Signature(sig.big_r, field.add(sig.s, 1)), pk, m)

    def test_invalid_pk(self):
        sk1, sk2 = SecretKey.random(), SecretKey.random()
        m = 123456789012345678901234567890
        sig = sign(sk1, sk1.public(), m)
        assert not verify(sig, sk2.public(), m)

    def test_invalid_message(self):
        sk = SecretKey.random()
        pk = sk.public()
        sig = sign(sk, pk, 123456789012345678901234567890)
        assert not verify(sig, pk, 123456789012345678901234567890123123)

    def test_oversized_s_rejected(self):
        sk = SecretKey.random()
        pk = sk.public()
        m = 42
        sig = sign(sk, pk, m)
        assert not verify(Signature(sig.big_r, sig.s + bjj.SUBORDER + 1), pk, m)

    def test_secret_key_roundtrip(self):
        sk = SecretKey.random()
        assert SecretKey.from_raw(sk.to_raw()) == sk
        pk = sk.public()
        assert PublicKey.from_raw(pk.to_raw()) == pk


class TestMessageHash:
    def test_shape_and_determinism(self):
        pks = [SecretKey.random().public() for _ in range(5)]
        scores = [[100, 200, 300, 400, 0] for _ in range(2)]
        pks_hash, messages = calculate_message_hash(pks, scores)
        assert len(messages) == 2
        assert messages[0] == messages[1]
        pks_hash2, messages2 = calculate_message_hash(pks, [scores[0]])
        assert pks_hash2 == pks_hash and messages2[0] == messages[0]

    def test_differs_on_scores(self):
        pks = [SecretKey.random().public() for _ in range(3)]
        _, m1 = calculate_message_hash(pks, [[1, 2, 3]])
        _, m2 = calculate_message_hash(pks, [[1, 2, 4]])
        assert m1[0] != m2[0]


class TestBase58:
    def test_roundtrip(self):
        for data in [b"", b"\x00\x00abc", bytes(range(32))]:
            assert b58decode(b58encode(data)) == data

    def test_known_value(self):
        # Classic bitcoin-alphabet vector.
        assert b58encode(b"hello world") == "StV1DL6CwTryKyV"
        assert b58decode("StV1DL6CwTryKyV") == b"hello world"
        assert b58encode(b"\x00\x00a") == "112g"
        # Reference secrets decode to exactly 32 canonical bytes.
        assert len(b58decode("2L9bbXNEayuRMMbrWFynPtgkrXH1iBdfryRH9Soa8M67")) == 32

    def test_invalid_character(self):
        with pytest.raises(ValueError):
            b58decode("0OIl")


class TestMerkle:
    def test_build_and_path(self):
        # merkle_tree/native.rs:115-140
        import random

        rng = random.Random(7)
        leaves = [rng.randrange(field.MODULUS) for _ in range(9)]
        value = leaves[4]
        tree = MerkleTree.build(leaves, 4)
        path = Path.find(tree, value)
        assert path.verify()
        assert path.pairs[tree.height][0] == tree.root

    def test_tampered_path_fails(self):
        leaves = [1, 2, 3, 4]
        tree = MerkleTree.build(leaves, 2)
        path = Path.find(tree, 3)
        path.pairs[0] = (path.pairs[0][0], path.pairs[0][1] + 1)
        assert not path.verify()
