"""Async proving plane (ISSUE 10): lifecycle state machine, crash
recovery, supersede-under-backpressure, and in-process/pooled proof
bit-equality."""

from __future__ import annotations

import os
import pickle
import time

import pytest

from protocol_tpu.node.bootstrap import FIXED_SET
from protocol_tpu.node.epoch import Epoch
from protocol_tpu.node.manager import Manager, ManagerConfig
from protocol_tpu.prover import (
    CRASH_MARKER,
    ProofJob,
    ProvingPlane,
    ProvingPlaneConfig,
    crash_once_marker,
    job_seed,
    prove_job,
)
from protocol_tpu.prover.jobs import prover_for


def _manager(prover: str = "commitment", n: int | None = None) -> Manager:
    cfg = (
        ManagerConfig(prover=prover)
        if n is None
        else ManagerConfig(
            prover=prover,
            num_neighbours=n,
            num_iter=1,
            fixed_set=list(FIXED_SET[:n]),
        )
    )
    mgr = Manager(cfg)
    mgr.generate_initial_attestations()
    return mgr


class TestProofJob:
    def test_job_is_flat_and_picklable(self):
        mgr = _manager()
        job = mgr.build_proof_job(Epoch(1))
        clone = pickle.loads(pickle.dumps(job))
        assert clone == job
        assert all(isinstance(x, int) for row in job.ops for x in row)
        assert len(job.sigs) == len(job.pks) == len(job.ops) == 5

    def test_seed_binds_the_statement(self):
        mgr = _manager()
        j1 = mgr.build_proof_job(Epoch(1))
        j2 = mgr.build_proof_job(Epoch(1))
        assert job_seed(j1) == job_seed(j2)
        # A different epoch or a perturbed score row changes the seed.
        assert job_seed(j1) != job_seed(mgr.build_proof_job(Epoch(2)))
        rows = [list(r) for r in j1.ops]
        rows[0][0] += 1
        perturbed = ProofJob(
            epoch=j1.epoch,
            ops=tuple(tuple(r) for r in rows),
            sigs=j1.sigs,
            pks=j1.pks,
            params=j1.params,
            prover=j1.prover,
        )
        assert job_seed(j1) != job_seed(perturbed)

    def test_prove_job_spans_carry_attribution(self):
        mgr = _manager()
        result = prove_job(mgr.build_proof_job(Epoch(3)))
        names = [c["name"] for c in result.spans["children"]]
        assert names == ["power_iterate", "circuit_check", "snark"]
        assert result.spans["name"] == "prove"
        assert result.prove_seconds > 0


class TestLifecycle:
    def test_submit_to_proved(self):
        from protocol_tpu.obs.metrics import PROOF_LAG_EPOCHS

        mgr = _manager()
        landed = []
        with ProvingPlane(
            ProvingPlaneConfig(workers=0),
            on_proved=lambda r: landed.append(r.epoch),
        ) as plane:
            status = plane.submit(mgr.build_proof_job(Epoch(4)))
            assert status.state in ("queued", "proving", "proved")
            assert plane.drain(timeout=30)
            final = plane.status(4)
            assert final.state == "proved"
            assert final.prove_seconds > 0
            assert final.lag_seconds >= final.prove_seconds * 0.5
            assert landed == [4]
            assert PROOF_LAG_EPOCHS.value() == 0
            assert plane.stats()["completed"] == 1

    def test_supersede_keeps_newest_never_drops_silently(self):
        from protocol_tpu.obs.metrics import PROOFS_SUPERSEDED

        mgr = _manager()
        superseded0 = PROOFS_SUPERSEDED.value()
        jobs = {
            k: mgr.build_proof_job(Epoch(k)).__class__(
                **{**mgr.build_proof_job(Epoch(k)).__dict__, "chaos": "sleep:0.4"}
            )
            for k in range(1, 5)
        }
        with ProvingPlane(ProvingPlaneConfig(workers=0, queue_depth=1)) as plane:
            for k in range(1, 5):
                plane.submit(jobs[k])
            assert plane.drain(timeout=60)
            states = {k: plane.status(k).state for k in range(1, 5)}
        # Epoch 1 went straight to a dispatcher; 2 and 3 were displaced
        # from the one-slot queue by their successors; 4 (the newest)
        # must prove.  Nothing may be missing or failed.
        assert states[4] == "proved", states
        assert all(s in ("proved", "superseded") for s in states.values()), states
        assert "superseded" in states.values(), states
        sup = [k for k, s in states.items() if s == "superseded"]
        assert 4 not in sup
        assert PROOFS_SUPERSEDED.value() - superseded0 == len(sup)
        for k in sup:
            assert plane.status(k).reason.startswith("superseded-by-")

    def test_queue_never_blocks_submit(self):
        mgr = _manager()
        with ProvingPlane(ProvingPlaneConfig(workers=0, queue_depth=1)) as plane:
            t0 = time.perf_counter()
            for k in range(1, 8):
                job = mgr.build_proof_job(Epoch(k))
                plane.submit(
                    job.__class__(**{**job.__dict__, "chaos": "sleep:0.3"})
                )
            submit_wall = time.perf_counter() - t0
            assert submit_wall < 0.5, submit_wall  # 7 submits, ~0 blocking
            assert plane.drain(timeout=60)

    def test_undrained_close_resolves_stragglers(self):
        mgr = _manager()
        plane = ProvingPlane(ProvingPlaneConfig(workers=0, queue_depth=2)).start()
        for k in (1, 2, 3):
            job = mgr.build_proof_job(Epoch(k))
            plane.submit(job.__class__(**{**job.__dict__, "chaos": "sleep:0.5"}))
        plane.close(drain=False)
        states = {k: plane.status(k).state for k in (1, 2, 3) if plane.status(k)}
        assert states, "lifecycle lost the queued epochs"
        assert all(
            s in ("proved", "failed", "superseded") for s in states.values()
        ), states


class TestCrashRecovery:
    def test_crash_once_retries_to_proved(self, tmp_path):
        from protocol_tpu.obs.metrics import PROVER_WORKER_RESTARTS

        mgr = _manager()
        restarts0 = PROVER_WORKER_RESTARTS.value()
        job = mgr.build_proof_job(Epoch(6))
        job = job.__class__(
            **{
                **job.__dict__,
                "chaos": crash_once_marker(str(tmp_path / "crash.flag")),
            }
        )
        with ProvingPlane(
            ProvingPlaneConfig(workers=1, max_retries=1, prove_timeout_s=120)
        ) as plane:
            gen0 = plane.pool.generation
            plane.submit(job)
            assert plane.drain(timeout=120)
            status = plane.status(6)
            assert status.state == "proved", status
            # The crash rebuilt the executor exactly once (generation
            # guard) and counted a restart.
            assert plane.pool.generation == gen0 + 1
        assert PROVER_WORKER_RESTARTS.value() - restarts0 == 1
        assert (tmp_path / "crash.flag").exists()

    def test_crash_past_retries_fails_with_reason(self):
        from protocol_tpu.obs.metrics import PROOFS_FAILED

        mgr = _manager()
        failed0 = PROOFS_FAILED.value()
        job = mgr.build_proof_job(Epoch(7))
        job = job.__class__(**{**job.__dict__, "chaos": CRASH_MARKER})
        with ProvingPlane(
            ProvingPlaneConfig(workers=1, max_retries=1, prove_timeout_s=120)
        ) as plane:
            plane.submit(job)
            assert plane.drain(timeout=120)
            status = plane.status(7)
            assert status.state == "failed"
            assert status.reason == "prover-crashed"
            assert plane.stats()["failed"] == 1
        assert PROOFS_FAILED.value() - failed0 == 1


class TestBitEquality:
    def test_commitment_sync_inline_and_pooled_identical(self):
        mgr = _manager()
        mgr.calculate_proofs(Epoch(9))
        sync_proof = mgr.cached_proofs[Epoch(9)]
        inline = prove_job(mgr.build_proof_job(Epoch(9)))
        assert inline.proof == sync_proof.proof
        assert list(inline.pub_ins) == list(sync_proof.pub_ins)
        with ProvingPlane(
            ProvingPlaneConfig(workers=1, prove_timeout_s=120),
            on_proved=lambda r: mgr.install_proof(r.epoch, r.pub_ins, r.proof),
        ) as plane:
            plane.submit(mgr.build_proof_job(Epoch(10)))
            assert plane.drain(timeout=120)
        pooled = mgr.cached_proofs[Epoch(10)]
        # Epoch 10's pooled proof must equal its in-process equivalent.
        assert pooled.proof == prove_job(mgr.build_proof_job(Epoch(10))).proof

    def test_plonk_sync_equals_pooled_path_prove(self):
        """The deterministic-seed contract on the real SNARK: the
        manager's synchronous prove and the plane's job prove are
        byte-identical for the same statement (smallest viable
        statement; keygen hits the on-disk key cache)."""
        mgr = _manager(prover="plonk", n=2)
        mgr.calculate_proofs(Epoch(11))
        sync_proof = mgr.cached_proofs[Epoch(11)]
        result = prove_job(mgr.build_proof_job(Epoch(11)))
        assert result.proof == sync_proof.proof
        assert list(result.pub_ins) == list(sync_proof.pub_ins)
        snark = next(
            c for c in result.spans["children"] if c["name"] == "snark"
        )
        assert {"msm", "witness_gen"} <= {c["name"] for c in snark["children"]}

    @pytest.mark.skipif(
        not os.environ.get("PROTOCOL_TPU_SLOW_TESTS"),
        reason="spawned-worker PLONK prove (~30 s: child key-cache load "
        "+ prove); set PROTOCOL_TPU_SLOW_TESTS=1",
    )
    def test_plonk_pooled_identical_across_process_boundary(self):
        mgr = _manager(prover="plonk", n=2)
        mgr.warm_prover()  # parent writes the disk key cache first
        inline = prove_job(mgr.build_proof_job(Epoch(12)))
        with ProvingPlane(
            ProvingPlaneConfig(workers=1, prove_timeout_s=600),
            on_proved=lambda r: mgr.install_proof(r.epoch, r.pub_ins, r.proof),
        ) as plane:
            cfg = mgr.config
            plane.prewarm(
                (cfg.num_neighbours, cfg.num_iter, cfg.initial_score, cfg.scale),
                cfg.prover,
                cfg.srs_path,
            )
            plane.submit(mgr.build_proof_job(Epoch(12)))
            assert plane.drain(timeout=600)
        assert mgr.cached_proofs[Epoch(12)].proof == inline.proof


class TestProverCache:
    def test_prover_cached_per_params(self):
        p1 = prover_for((5, 10, 1000, 1000), "commitment", None)
        p2 = prover_for((5, 10, 1000, 1000), "commitment", None)
        p3 = prover_for((2, 1, 1000, 1000), "commitment", None)
        assert p1 is p2
        assert p1 is not p3


class TestProofRoute:
    def test_proof_endpoint_serves_proof_and_lifecycle(self):
        import json

        from protocol_tpu.node.server import handle_request

        mgr = _manager()
        with ProvingPlane(
            ProvingPlaneConfig(workers=0),
            on_proved=lambda r: mgr.install_proof(r.epoch, r.pub_ins, r.proof),
        ) as plane:
            plane.submit(mgr.build_proof_job(Epoch(20)))
            assert plane.drain(timeout=30)
            status, body = handle_request("GET", "/proof/20", mgr, plane)
            obj = json.loads(body)
            assert status == 200 and obj["state"] == "proved"
            assert obj["epoch"] == 20 and obj["proof"]
            status, body = handle_request("GET", "/proof/latest", mgr, plane)
            assert status == 200 and json.loads(body)["epoch"] == 20
            status, body = handle_request("GET", "/proof/999", mgr, plane)
            assert status == 404
            status, _ = handle_request("GET", "/proof/abc", mgr, plane)
            assert status == 400

    def test_proof_endpoint_without_plane(self):
        import json

        from protocol_tpu.node.server import handle_request

        mgr = _manager()
        mgr.calculate_proofs(Epoch(21))
        status, body = handle_request("GET", "/proof/21", mgr)
        assert status == 200 and json.loads(body)["state"] == "proved"
        status, _ = handle_request("GET", "/proof/5", mgr)
        assert status == 404


class TestTraceGraft:
    def test_graft_into_stored_trace(self):
        from protocol_tpu.obs.trace import Tracer

        tracer = Tracer()
        with tracer.epoch(1):
            with tracer.span("converge"):
                pass
        assert tracer.graft(1, {"name": "prove", "children": []})
        names = [c["name"] for c in tracer.get_trace(1)["children"]]
        assert names == ["converge", "prove"]
        # Under a named parent, depth-first.
        assert tracer.graft(1, {"name": "snark"}, parent_name="prove")
        prove = tracer.get_trace(1)["children"][1]
        assert prove["children"][0]["name"] == "snark"

    def test_early_graft_pends_until_trace_stores(self):
        from protocol_tpu.obs.trace import Tracer

        tracer = Tracer()
        # The async proof lands while epoch 2's root span is still
        # open (cold-compile tick): the graft parks and applies when
        # the trace stores.
        assert not tracer.graft(2, {"name": "prove", "children": []})
        with tracer.epoch(2):
            pass
        names = [c["name"] for c in tracer.get_trace(2)["children"]]
        assert names == ["prove"]

    def test_graft_for_evicted_epoch_is_dropped(self):
        from protocol_tpu.obs.trace import Tracer

        tracer = Tracer(keep_epochs=2)
        for k in (1, 2, 3):
            with tracer.epoch(k):
                pass
        assert not tracer.graft(1, {"name": "prove"})
        assert tracer.get_trace(1) is None
