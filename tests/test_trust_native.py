"""Exact native trust-kernel tests, mirroring the reference's scenario
suite (circuit/src/native.rs:237-628, circuit/src/circuit.rs tests)."""

from fractions import Fraction

import pytest

from protocol_tpu.crypto import calculate_message_hash, field
from protocol_tpu.crypto.eddsa import PublicKey, SecretKey, sign
from protocol_tpu.trust.native import (
    EigenTrustSet,
    Opinion,
    fraction_to_field,
    power_iterate,
    power_iterate_rational,
)

NUM_NEIGHBOURS = 6
NUM_ITERATIONS = 20
INITIAL_SCORE = 1000


def sign_opinion(sk, pk, pks, scores):
    """Build a signed Opinion (native.rs:247-258 test helper)."""
    _, hashes = calculate_message_hash(pks, [scores])
    sig = sign(sk, pk, hashes[0])
    return Opinion(sig=sig, message_hash=hashes[0], scores=list(zip(pks, scores)))


def make_set():
    return EigenTrustSet(
        num_neighbours=NUM_NEIGHBOURS,
        num_iterations=NUM_ITERATIONS,
        initial_score=INITIAL_SCORE,
    )


def keys(n):
    sks = [SecretKey.random() for _ in range(n)]
    return sks, [sk.public() for sk in sks]


def pad_pks(pks):
    return pks + [PublicKey.null()] * (NUM_NEIGHBOURS - len(pks))


class TestPowerIterate:
    def test_uniform_preserves_initial_scores(self):
        """The server's initial-attestation config: every peer gives
        IS/N to everyone; converged pub_ins equal the initial scores
        (server/src/manager/mod.rs:246-262)."""
        n, it, scale = 5, 10, 1000
        ops = [[200] * n for _ in range(n)]
        init = [1000] * n
        out = power_iterate(init, ops, it, scale)
        assert out == [1000] * n

    def test_field_matches_rational_image(self):
        """The field result is the Fr image of the exact rational result
        for arbitrary SCALE-summing score rows."""
        n, it, scale = 5, 10, 1000
        ops = [
            [0, 300, 100, 300, 300],
            [200, 0, 300, 200, 300],
            [500, 100, 0, 300, 100],
            [300, 300, 300, 0, 100],
            [250, 250, 250, 250, 0],
        ]
        init = [1000] * n
        exact = power_iterate_rational(init, ops, it, scale)
        via_field = power_iterate(init, ops, it, scale)
        assert [fraction_to_field(x) for x in exact] == via_field
        # Total score is conserved (the circuit's Σs == N·IS constraint,
        # circuit.rs:380-418).
        assert sum(exact) == n * 1000

    def test_shape_asserts(self):
        with pytest.raises(AssertionError):
            power_iterate([1, 2], [[1]], 1, 1000)


class TestEigenTrustSet:
    def test_add_member_twice_panics(self):
        s = make_set()
        _, pks = keys(1)
        s.add_member(pks[0])
        with pytest.raises(AssertionError):
            s.add_member(pks[0])

    def test_one_member_converge_panics(self):
        s = make_set()
        _, pks = keys(1)
        s.add_member(pks[0])
        with pytest.raises((AssertionError, ZeroDivisionError)):
            s.converge()

    def test_two_members_no_opinions(self):
        s = make_set()
        _, pks = keys(2)
        s.add_member(pks[0])
        s.add_member(pks[1])
        out = s.converge_rational()
        # Empty opinions redistribute evenly: each trusts the other
        # fully, so mass swaps symmetrically; raw scores grow by a factor
        # of INITIAL_SCORE per iteration (no unscaling in converge,
        # native.rs:111-133).
        expected = INITIAL_SCORE * Fraction(INITIAL_SCORE) ** NUM_ITERATIONS
        assert out[0] == out[1] == expected

    def test_two_members_with_opinions(self):
        s = make_set()
        sks, pks = keys(2)
        s.add_member(pks[0])
        s.add_member(pks[1])
        padded = pad_pks(pks)
        s.update_op(pks[0], sign_opinion(sks[0], pks[0], padded, [0, INITIAL_SCORE, 0, 0, 0, 0]))
        s.update_op(pks[1], sign_opinion(sks[1], pks[1], padded, [INITIAL_SCORE, 0, 0, 0, 0, 0]))
        out = s.converge_rational()
        assert sum(out) == 2 * INITIAL_SCORE * Fraction(INITIAL_SCORE) ** NUM_ITERATIONS

    def test_three_members_with_opinions(self):
        s = make_set()
        sks, pks = keys(3)
        for pk in pks:
            s.add_member(pk)
        padded = pad_pks(pks)
        scores = [
            [0, 300, 700, 0, 0, 0],
            [600, 0, 400, 0, 0, 0],
            [600, 400, 0, 0, 0, 0],
        ]
        for sk, pk, row in zip(sks, pks, scores):
            s.update_op(pk, sign_opinion(sk, pk, padded, row))
        out = s.converge_rational()
        # Rows are normalized to credits=1000 each, so total mass is
        # multiplied by INITIAL_SCORE per iteration.
        assert sum(out) == 3 * INITIAL_SCORE * Fraction(INITIAL_SCORE) ** NUM_ITERATIONS
        # Field image sanity.
        assert s.converge() == [fraction_to_field(x) for x in out]

    def test_three_members_two_opinions(self):
        s = make_set()
        sks, pks = keys(3)
        for pk in pks:
            s.add_member(pk)
        padded = pad_pks(pks)
        s.update_op(pks[0], sign_opinion(sks[0], pks[0], padded, [0, 300, 700, 0, 0, 0]))
        s.update_op(pks[1], sign_opinion(sks[1], pks[1], padded, [600, 0, 400, 0, 0, 0]))
        out = s.converge_rational()
        assert len(out) == NUM_NEIGHBOURS
        assert all(x >= 0 for x in out)

    def test_quit_member_reconverges(self):
        s = make_set()
        sks, pks = keys(3)
        for pk in pks:
            s.add_member(pk)
        padded = pad_pks(pks)
        scores = [
            [0, 300, 700, 0, 0, 0],
            [600, 0, 400, 0, 0, 0],
            [600, 400, 0, 0, 0, 0],
        ]
        for sk, pk, row in zip(sks, pks, scores):
            s.update_op(pk, sign_opinion(sk, pk, padded, row))
        s.converge()
        s.remove_member(pks[1])
        out = s.converge_rational()
        assert out[1] == 0  # removed slot carries no score

    def test_filter_peers(self):
        """The native.rs:573-627 scenario: mismatched, null and self
        entries are filtered; every valid peer ends with an opinion."""
        sks, pks = keys(4)  # pk1, pk2, pk3, pk8
        sk1, sk2, sk3, _ = sks
        pk1, pk2, pk3, pk8 = pks

        s = make_set()
        for pk in (pk1, pk2, pk3):
            s.add_member(pk)

        null = PublicKey.null()
        op1 = sign_opinion(sk1, pk1, [pk1, pk2, pk3, null, null, pk8], [10, 10, 0, 0, 10, 0])
        op2 = sign_opinion(sk2, pk2, [pk1, pk2, pk3, null, null, null], [0, 0, 30, 0, 0, 0])
        op3 = sign_opinion(sk3, pk3, [pk1, pk2, pk3, null, null, null], [10, 0, 0, 0, 0, 0])
        s.update_op(pk1, op1)
        s.update_op(pk2, op2)
        s.update_op(pk3, op3)

        filtered_set, filtered_ops = s.filter_peers()
        n_valid = sum(1 for pk, _ in filtered_set if not pk.is_null())
        assert n_valid == len(filtered_ops) == 3
        # Peer1's self-score and the score at the empty slot are gone.
        scores1 = [score for _, score in filtered_ops[pk1].scores]
        assert scores1 == [0, 10, 0, 0, 0, 0]
        # Peer3's opinion only scored pk1.
        scores3 = [score for _, score in filtered_ops[pk3].scores]
        assert scores3 == [10, 0, 0, 0, 0, 0]

    def test_zero_sum_opinion_redistributes(self):
        s = make_set()
        sks, pks = keys(3)
        for pk in pks:
            s.add_member(pk)
        padded = pad_pks(pks)
        # Peer1 scores only itself → nullified → zero-sum → redistributed
        # evenly to the other two valid peers.
        s.update_op(pks[0], sign_opinion(sks[0], pks[0], padded, [1000, 0, 0, 0, 0, 0]))
        _, filtered_ops = s.filter_peers()
        scores = [score for _, score in filtered_ops[pks[0]].scores]
        assert scores == [0, 1, 1, 0, 0, 0]
