"""Test configuration: force an 8-device virtual CPU mesh.

Sharded-backend tests exercise real `jax.sharding.Mesh` layouts without
TPU hardware, per SURVEY.md §4 rebuild test doctrine (tier 5).  The TPU
tunnel's site hook force-selects its platform via
``jax.config.update("jax_platforms", ...)`` at interpreter start, so
setting the env var is not enough — override the config before any
backend initializes.  bench.py is what runs on the real chip.
"""

import os
import pathlib

# Keep the PLONK keygen cache repo-local: tests must not write pickles
# into (or silently reuse stale keys from) the developer's home cache.
os.environ.setdefault(
    "PROTOCOL_TPU_CACHE",
    str(pathlib.Path(__file__).resolve().parent.parent / ".cache" / "protocol_tpu"),
)

_platform = os.environ.get("PROTOCOL_TPU_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", _platform)

# The zk.graft proving kernels are large traced programs (an EC add
# inlines 16 Montgomery multiplies); XLA:CPU takes tens of seconds per
# (shape, kernel) pair.  Persist compiled executables next to the
# keygen cache so repeat test runs pay compilation once per machine.
_jax_cache = pathlib.Path(os.environ["PROTOCOL_TPU_CACHE"]) / "jax"
_jax_cache.mkdir(parents=True, exist_ok=True)
jax.config.update("jax_compilation_cache_dir", str(_jax_cache))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

#: Modules run under ``jax.transfer_guard("disallow")``: the
#: backend-equivalence suites, where an implicit host transfer means a
#: per-call (or worse, per-iteration) sync hiding in a hot path — the
#: runtime counterpart of the analyzer's bare-sync/host-op AST rules.
#: Explicit staging (``jnp.asarray``/``device_put``/``device_get``)
#: stays legal; tests that legitimately rely on implicit transfers opt
#: out with ``@pytest.mark.allow_transfer``.
_TRANSFER_GUARDED = {"test_trust_backends", "test_windowed_pipeline"}


@pytest.fixture(autouse=True)
def _no_implicit_transfers(request):
    module = getattr(request.node, "module", None)
    name = getattr(module, "__name__", "").rpartition(".")[2]
    if name not in _TRANSFER_GUARDED or request.node.get_closest_marker(
        "allow_transfer"
    ):
        yield
        return
    with jax.transfer_guard("disallow"):
        yield
