"""Test configuration: force an 8-device virtual CPU mesh.

Sharded-backend tests exercise real `jax.sharding.Mesh` layouts without
TPU hardware, per SURVEY.md §4 rebuild test doctrine (tier 5).  Must run
before the first `import jax` anywhere in the test session.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
