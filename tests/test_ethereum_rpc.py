"""JSON-RPC transport fixture for ``_Web3Rpc`` (node/ethereum.py) —
the last no-cover transport path, exercised without web3 in the image
(VERDICT item #10).

A recorded ``eth_getLogs`` / ``eth_blockNumber`` response (hex-string
wire shape, as JSON-RPC returns it) is served through a stub ``web3``
module that mimics web3.py's response normalization (HexBytes — a bytes
subclass — for topics and data).  The tests cover exactly what the real
transport must get right: the HexBytes→int topic normalization in
``_Web3Rpc._Log``, the get_logs query shape (fromBlock/toBlock/address
checksum/topic filter), block_number, and the decode path shared with
every other RPC backend.
"""

import sys
import types

import pytest

from protocol_tpu.node.ethereum import (
    ATTESTATION_CREATED_TOPIC,
    Web3EventSource,
    _Web3Rpc,
    have_web3,
)

CONTRACT = "0x" + "ab" * 20
CREATOR = 0x1234567890ABCDEF1234567890ABCDEF12345678
ABOUT = 0xFEDCBA0987654321FEDCBA0987654321FEDCBA09
KEY = bytes.fromhex("05" * 32)
VAL = bytes(range(96))  # 5-neighbour attestation payloads are ~this size


def _abi_dynamic_bytes(val: bytes) -> bytes:
    """ABI encoding of one dynamic ``bytes`` argument: offset word,
    length word, payload padded to a 32-byte boundary."""
    pad = (-len(val)) % 32
    return (
        (32).to_bytes(32, "big") + len(val).to_bytes(32, "big") + val + b"\x00" * pad
    )


#: The recorded JSON-RPC responses, in wire shape (lowercase hex
#: strings) — what an ``eth_getLogs`` result entry for one
#: AttestationCreated event and an ``eth_blockNumber`` call look like.
RECORDED = {
    "eth_blockNumber": "0x10",
    "eth_getLogs": [
        {
            "topics": [
                ATTESTATION_CREATED_TOPIC,
                "0x" + f"{CREATOR:064x}",
                "0x" + f"{ABOUT:064x}",
                "0x" + KEY.hex(),
            ],
            "data": "0x" + _abi_dynamic_bytes(VAL).hex(),
        }
    ],
}


class _HexBytes(bytes):
    """web3.py returns HexBytes (a bytes subclass) for topics/data."""


def _fake_web3_module(recorded: dict, queries: list) -> types.ModuleType:
    """A web3 stub replaying the recorded responses: hex-string wire
    values are normalized to HexBytes exactly like web3.py does, and
    every get_logs query is captured for shape assertions."""

    class _Eth:
        @property
        def block_number(self):
            return int(recorded["eth_blockNumber"], 16)

        def get_logs(self, query):
            queries.append(dict(query))
            return [
                {
                    "topics": [
                        _HexBytes(bytes.fromhex(t[2:])) for t in log["topics"]
                    ],
                    "data": _HexBytes(bytes.fromhex(log["data"][2:])),
                }
                for log in recorded["eth_getLogs"]
            ]

    class Web3:
        class HTTPProvider:
            def __init__(self, url):
                self.url = url

        def __init__(self, provider):
            self.provider = provider
            self.eth = _Eth()

        @staticmethod
        def to_checksum_address(addr):
            # EIP-55 casing is cosmetic for the stub; byte identity is
            # what the query-shape assertions check.
            return addr

    mod = types.ModuleType("web3")
    mod.Web3 = Web3
    return mod


@pytest.fixture
def rpc_fixture(monkeypatch):
    queries: list = []
    monkeypatch.setitem(sys.modules, "web3", _fake_web3_module(RECORDED, queries))
    return queries


class TestWeb3RpcFixture:
    def test_replay_decodes_recorded_logs(self, rpc_fixture):
        source = Web3EventSource("http://node:8545", CONTRACT)
        events = list(source.replay(from_block=0))
        assert len(events) == 1
        ev = events[0]
        assert ev.creator == f"0x{CREATOR:040x}"
        assert ev.about == f"0x{ABOUT:040x}"
        assert ev.key == KEY
        assert ev.val == VAL

    def test_get_logs_query_shape(self, rpc_fixture):
        source = Web3EventSource("http://node:8545", CONTRACT)
        list(source.replay(from_block=7, to_block=12))
        (query,) = rpc_fixture
        assert query["fromBlock"] == 7
        assert query["toBlock"] == 12
        assert query["address"] == CONTRACT
        # One-element topic filter pinned to the AttestationCreated
        # topic0 — anything broader would replay foreign events.
        assert query["topics"] == [ATTESTATION_CREATED_TOPIC]

    def test_open_ended_replay_omits_to_block(self, rpc_fixture):
        source = Web3EventSource("http://node:8545", CONTRACT)
        list(source.replay(from_block=0))
        (query,) = rpc_fixture
        assert "toBlock" not in query

    def test_block_number_normalizes(self, rpc_fixture):
        rpc = _Web3Rpc("http://node:8545")
        assert rpc.block_number() == 16

    def test_log_topic_normalization(self, rpc_fixture):
        """web3's HexBytes topics become plain ints on the _Log shim —
        the contract ChainEventSource._decode relies on."""
        rpc = _Web3Rpc("http://node:8545")
        logs = rpc.get_logs(
            address=int(CONTRACT, 16),
            from_block=0,
            to_block=None,
            topic0=int(ATTESTATION_CREATED_TOPIC, 16),
        )
        (log,) = logs
        assert all(isinstance(t, int) for t in log.topics)
        assert log.topics[0] == int(ATTESTATION_CREATED_TOPIC, 16)
        assert log.topics[1] == CREATOR
        assert isinstance(log.data, bytes)

    def test_without_web3_raises_actionable_error(self):
        if have_web3():  # pragma: no cover - image carries no web3
            pytest.skip("real web3 installed; the gated path is live")
        with pytest.raises(RuntimeError, match="web3.py is not installed"):
            Web3EventSource("http://node:8545", CONTRACT)
