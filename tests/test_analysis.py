"""graftlint (protocol_tpu.analysis) — the ISSUE 3 acceptance suite.

Covers: every seeded violation fixture fires exactly its rule with the
right ``file:line`` (resolved against the ``# VIOLATION:`` markers in
``analysis/fixtures.py``), the CLI exits non-zero on fixtures and zero
on the real tree, every registered jax backend carries >= 3 checked
invariants (with the one-random-gather budget pinned on the windowed
rungs), an undeclared backend is itself a gate failure, and the AST
ruleset fires/stays-quiet on minimal positive/negative snippets.
"""

import json
import re
from pathlib import Path

import pytest

import protocol_tpu.analysis.fixtures as fixtures_mod
from protocol_tpu.analysis import KERNEL_INVARIANTS, NON_JAX_BACKENDS
from protocol_tpu.analysis.__main__ import main as analysis_main
from protocol_tpu.analysis.ast_rules import scan_file
from protocol_tpu.analysis.fixtures import FIXTURES, run_fixture
from protocol_tpu.analysis.invariants import run_jaxpr_pass
from protocol_tpu.trust.backend import registered_backends

FIXTURES_PATH = Path(fixtures_mod.__file__)

#: The acceptance floor applies to every backend on the ladder.
ACCEPTANCE_BACKENDS = (
    "tpu-dense",
    "tpu-sparse",
    "tpu-csr",
    "tpu-windowed",
    "tpu-sharded:tpu-csr",
    "tpu-sharded:tpu-windowed",
)


def _marker_lines() -> dict[str, int]:
    """``# VIOLATION: <name>`` marker -> 1-based line in fixtures.py."""
    out: dict[str, int] = {}
    for i, line in enumerate(FIXTURES_PATH.read_text().splitlines(), start=1):
        m = re.search(r"# VIOLATION: ([\w-]+)", line)
        if m:
            out[m.group(1)] = i
    return out


class TestViolationFixtures:
    """Each seeded violation fires its rule — and only its rule — with
    the marked ``file:line``."""

    @pytest.mark.parametrize("name", sorted(FIXTURES))
    def test_fires_expected_rule_at_marked_line(self, name):
        fixture = FIXTURES[name]
        findings = run_fixture(name)
        errors = [f for f in findings if f.severity == "error"]
        assert errors, f"fixture {name} produced no error finding"
        assert {f.rule for f in errors} == {fixture.rule}
        finding = errors[0]
        if fixture.marker is None:
            return
        if fixture.kind in ("ast", "concurrency", "mem-ast", "det-ast"):
            # String-sourced fixtures carry their violating code as a
            # source string (so the repo-wide passes never see it); the
            # finding anchors inside that string at the marker line.
            source, rel_path = fixture.build()
            marker_line = next(
                i
                for i, line in enumerate(source.splitlines(), start=1)
                if f"# VIOLATION: {fixture.marker}" in line
            )
            assert finding.file == rel_path
            assert finding.line == marker_line
            return
        assert finding.file is not None and finding.file.endswith("fixtures.py")
        assert finding.line == _marker_lines()[fixture.marker], (
            f"{name}: finding anchored at {finding.file}:{finding.line}, "
            f"marker at line {_marker_lines()[fixture.marker]}"
        )

    def test_cli_exits_nonzero_on_fixture(self, tmp_path):
        out = tmp_path / "fixture.json"
        rc = analysis_main(["--fixture", "extra-gather", "--output", str(out)])
        assert rc == 1
        report = json.loads(out.read_text())
        assert report["summary"]["error"] == 1
        assert report["findings"][0]["rule"] == "gather-budget"

    def test_cli_rejects_unknown_fixture(self, tmp_path):
        rc = analysis_main(
            ["--fixture", "bogus", "--output", str(tmp_path / "x.json")]
        )
        assert rc == 2


@pytest.fixture(scope="module")
def real_report(tmp_path_factory):
    """One full all-pass run over the real tree (module-scoped: the
    jaxpr pass traces all six backends).  Wall time rides along under
    ``_wall_s`` for the analyzer self-budget test."""
    import time

    out = tmp_path_factory.mktemp("analysis") / "ANALYSIS.json"
    t0 = time.perf_counter()
    rc = analysis_main(["--output", str(out)])
    wall = time.perf_counter() - t0
    report = json.loads(out.read_text())
    report["_wall_s"] = wall
    return rc, report


class TestRealTree:
    def test_gate_passes_on_real_tree(self, real_report):
        rc, report = real_report
        assert report["summary"]["error"] == 0, report["findings"]
        assert rc == 0

    def test_every_registered_backend_covered(self, real_report):
        _, report = real_report
        for name in registered_backends():
            assert name in report["backends"], f"{name} missing from report"
            status = report["backends"][name]["status"]
            expected = "skipped" if name in NON_JAX_BACKENDS else "checked"
            assert status == expected, (name, status)

    def test_acceptance_backends_have_three_invariants(self, real_report):
        _, report = real_report
        for name in ACCEPTANCE_BACKENDS:
            checked = report["backends"][name]["invariants_checked"]
            assert checked >= 3, f"{name}: only {checked} invariants checked"

    def test_windowed_one_random_gather_budget_enforced(self, real_report):
        """ISSUE 3 acceptance: the one-random-gather budget for the
        windowed rungs is the analyzer's, not only the unit test's."""
        _, report = real_report
        for name in ("tpu-windowed", "tpu-sharded:tpu-windowed"):
            (gb,) = report["backends"][name]["budget"]["gather_budgets"]
            assert gb["dim"] == "n_segments"
            assert gb["max_random"] == 1
            assert gb["boundary_sorted"] is True

    def test_ast_pass_scanned_the_tree(self, real_report):
        _, report = real_report
        assert report["summary"]["files_scanned"] > 50


class TestRegistryGate:
    def test_undeclared_backend_is_error(self):
        """A backend name with no KERNEL_INVARIANTS entry fails the
        gate — adding a rung without pinning it is itself a finding."""
        findings, meta = run_jaxpr_pass(backends=["tpu-quantum"])
        assert meta["tpu-quantum"]["status"] == "undeclared"
        assert any(
            f.rule == "undeclared-backend" and f.severity == "error"
            for f in findings
        )

    def test_table_matches_registry(self):
        """Every declared kernel budget belongs to a live jax entry
        point: a trust-registry backend or a zk.graft proving kernel
        (whose budgets register at kernel-module import)."""
        from protocol_tpu.analysis.zk_lowering import ensure_budgets

        zk_names = set(ensure_budgets())
        declared = set(KERNEL_INVARIANTS)
        registered = {
            n for n in registered_backends() if n not in NON_JAX_BACKENDS
        }
        assert declared == registered | zk_names


class TestBudgetRules:
    """Direct rule coverage the seeded fixtures don't reach."""

    def test_sized_random_budget_fires_on_second_random_pass(self):
        """The windowed acceptance invariant: a second random
        n_segments-sized pass trips `random-gather-budget` even when
        the global gather budget would tolerate it."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from protocol_tpu.analysis import GatherBudget, KernelBudget
        from protocol_tpu.analysis.invariants import TraceCase, check_case

        x = jnp.asarray(np.arange(32.0, dtype=np.float32))
        idx = jnp.asarray(np.array([3, 1, 2, 5, 4, 0], np.int32))

        def step(x, idx):
            return x[idx] + x[idx + 1]  # two random (6,)-sized passes

        jaxpr = jax.make_jaxpr(step)(x, idx)
        budget = KernelBudget(
            backend="unit",
            max_random_gathers=8,
            gather_budgets=(
                GatherBudget(dim="n_segments", max_total=8, max_random=1),
            ),
        )
        findings = check_case(
            budget, TraceCase("unit", jaxpr, dims={"n_segments": 6})
        )
        assert {f.rule for f in findings} == {"random-gather-budget"}

    def test_psum_count_mismatch_fires(self):
        from protocol_tpu.analysis import KernelBudget
        from protocol_tpu.analysis.invariants import (
            TRACE_BUILDERS,
            _synthetic_graph,
            check_case,
        )

        case = TRACE_BUILDERS["tpu-sharded:tpu-csr"](_synthetic_graph())
        budget = KernelBudget(
            backend="unit", max_random_gathers=99, max_scatters=99, psum_count=0
        )
        findings = check_case(budget, case)
        assert "psum-count" in {f.rule for f in findings}


class TestConcurrencyPass:
    """Pass 7: the whole-program concurrency analyzer (ISSUE 8)."""

    def test_real_tree_zero_unwaived_findings(self, real_report):
        _, report = real_report
        conc = [f for f in report["findings"] if f["pass"] == "concurrency"]
        assert conc == [], conc

    def test_waivers_enumerated_and_live(self, real_report):
        """Every waiver is visible in the report AND still matches a
        live finding — a fixed bug must take its waiver with it (zero
        silent suppressions, zero stale entries)."""
        from protocol_tpu.analysis.concurrency import WAIVERS

        _, report = real_report
        section = report["concurrency"]
        assert section["stale_waivers"] == [], section["stale_waivers"]
        matched = {w["symbol"] for w in section["waived"]}
        assert {w.symbol for w in WAIVERS} == matched

    def test_roots_cover_known_threads(self, real_report):
        """The root inventory finds the node's actual execution roots:
        the pipeline device worker, the journal writer, the ingest
        stage threads, the HTTP handler tree, and the signal handler."""
        _, report = real_report
        roots = {r["name"] for r in report["concurrency"]["roots"]}
        for expected in (
            "thread:epoch-pipeline-device",
            "thread:flight-recorder",
            "thread:ingest-admission",
            "http-handler",
            "signal-handler",
            "asyncio-task",
            "executor-submit",
            "main",
        ):
            assert expected in roots, (expected, sorted(roots))

    def test_guard_map_covers_fixed_state(self, real_report):
        """The attributes fixed in this PR are inferred as guarded —
        the static half of the witness cross-check."""
        _, report = real_report
        guarded = report["concurrency"]["guarded_attrs"]
        for attr, lock in (
            ("Manager._dirty_hashes", "Manager._state_lock"),
            ("Manager.last_scores", "Manager._state_lock"),
            ("Manager.last_peer_hashes", "Manager._state_lock"),
            ("Manager.window_plan", "Manager._state_lock"),
            ("EpochPipeline.coalesced", "EpochPipeline._cv"),
            ("EpochPipeline._started", "EpochPipeline._cv"),
            ("IngestPlane.accepted", "IngestPlane._cv"),
            ("IngestPlane.shed", "IngestPlane._cv"),
            ("MemoryWatermarkWatcher._enabled", "MemoryWatermarkWatcher._probe_lock"),
            ("FlightRecorder._writer", "FlightRecorder._io_lock"),
        ):
            assert guarded.get(attr) == [lock], (attr, guarded.get(attr))

    def test_analyzer_self_budget(self, real_report):
        """Full-tree graftlint (all passes, backends traced AND
        compiled) stays under 120 s — the gate must remain cheap enough
        to run hard on every lint.  Pass 8 raised the floor: it
        XLA-compiles all six backends (the two Pallas-interpret
        windowed compiles dominate at ~25 s), measured ~45 s total on
        the 1-core container.  The 12-pass run (ISSUE 15) added no
        compile cost: pass 12 reads the buffer assignment of the SAME
        executables through the lowering memo (measured ~41 s total).
        Pass 13 (ISSUE 18) scans those memoized module texts for free
        but adds one FRESH first-scale recompile per backend for the
        compile-drift diff (~25 s, the interpret-mode windowed rungs
        again) — measured ~66 s total, still well inside the
        ceiling."""
        _, report = real_report
        assert report["_wall_s"] < 120.0, report["_wall_s"]

    # -- precision negatives -------------------------------------------

    def test_readonly_reference_not_flagged(self):
        """A never-reassigned reference to a thread-safe object (the
        bounded-queue pattern) needs no guard."""
        from protocol_tpu.analysis.concurrency import analyze_sources

        src = (
            "import queue\nimport threading\n\n\n"
            "class Plane:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._queue = queue.Queue(maxsize=4)\n\n"
            "    def producer(self):\n"
            "        with self._lock:\n"
            "            self._queue.put_nowait(1)\n\n"
            "    def consumer(self):\n"
            "        return self._queue.get(timeout=0.05)\n\n\n"
            "def run():\n"
            "    p = Plane()\n"
            "    threading.Thread(target=p.producer).start()\n"
            "    threading.Thread(target=p.consumer).start()\n"
        )
        assert analyze_sources({"protocol_tpu/node/_x.py": src}) == []

    def test_confined_tree_is_quiet(self):
        """The same RMW that fires in node/ is policy-quiet in the
        still-confined trees (evm/ test drivers) — and since the
        prover pool (ISSUE 10) it fires in zk/ too: PR 8's recorded
        'revisit at prover pool' executed, zk/ left the confined
        list."""
        from protocol_tpu.analysis.concurrency import analyze_sources

        src = (
            "import threading\n\n\n"
            "class Hits:\n"
            "    def __init__(self):\n"
            "        self.n = 0\n\n"
            "    def work(self):\n"
            "        self.n += 1\n\n\n"
            "def run():\n"
            "    h = Hits()\n"
            "    threading.Thread(target=h.work, name='a').start()\n"
            "    threading.Thread(target=h.work, name='b').start()\n"
        )
        assert analyze_sources({"protocol_tpu/evm/_x.py": src}) == []
        assert analyze_sources({"protocol_tpu/zk/_x.py": src}) != []
        assert analyze_sources({"protocol_tpu/node/_x.py": src}) != []

    def test_bounded_put_under_lock_ok(self):
        from protocol_tpu.analysis.concurrency import analyze_sources

        src = (
            "import queue\nimport threading\n\n\n"
            "class Stage:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._queue = queue.Queue(maxsize=4)\n\n"
            "    def push(self, item):\n"
            "        with self._lock:\n"
            "            self._queue.put(item, timeout=0.05)\n"
        )
        assert analyze_sources({"protocol_tpu/node/_x.py": src}) == []

    def test_locked_helper_inherits_guard(self):
        """A helper only ever called under the lock inherits the guard
        (the journal's _rotate_locked pattern must not false-positive)."""
        from protocol_tpu.analysis.concurrency import analyze_sources

        src = (
            "import threading\n\n\n"
            "class Store:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.state = 0\n\n"
            "    def mutate(self):\n"
            "        with self._lock:\n"
            "            self.state += 1\n"
            "            self._bump_locked()\n\n"
            "    def _bump_locked(self):\n"
            "        self.state += 1\n\n\n"
            "def run():\n"
            "    s = Store()\n"
            "    threading.Thread(target=s.mutate).start()\n"
            "    threading.Thread(target=s.mutate).start()\n"
        )
        assert analyze_sources({"protocol_tpu/node/_x.py": src}) == []

    def test_consistent_lock_order_no_cycle(self):
        from protocol_tpu.analysis.concurrency import analyze_sources

        src = (
            "import threading\n\n\n"
            "class Transfer:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "        self._b = threading.Lock()\n\n"
            "    def ab(self):\n"
            "        with self._a:\n"
            "            with self._b:\n"
            "                pass\n\n"
            "    def ab2(self):\n"
            "        with self._a:\n"
            "            with self._b:\n"
            "                pass\n"
        )
        assert analyze_sources({"protocol_tpu/node/_x.py": src}) == []

    def test_transitive_lock_cycle_through_call(self):
        """A cycle built through a call made under a held lock is still
        a cycle — the order graph follows same-class calls."""
        from protocol_tpu.analysis.concurrency import analyze_sources

        src = (
            "import threading\n\n\n"
            "class Transfer:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "        self._b = threading.Lock()\n\n"
            "    def ab(self):\n"
            "        with self._a:\n"
            "            self.take_b()\n\n"
            "    def take_b(self):\n"
            "        with self._b:\n"
            "            pass\n\n"
            "    def ba(self):\n"
            "        with self._b:\n"
            "            with self._a:\n"
            "                pass\n"
        )
        findings = analyze_sources({"protocol_tpu/node/_x.py": src})
        assert [f.rule for f in findings] == ["lock-order-cycle"]

    def test_concurrency_section_in_report(self, real_report):
        _, report = real_report
        section = report["concurrency"]
        assert section["classes_analyzed"] > 40
        # zk/ left the confined list at the prover pool (ISSUE 10);
        # its surviving findings are enumerated, stale-tested waivers.
        assert "protocol_tpu/zk/" not in section["confined_trees"]
        assert "protocol_tpu/evm/" in section["confined_trees"]
        assert any("zk/" in w["file"] for w in section["waived"])
        assert section["findings"] == 0


def _scan(tmp_path: Path, rel: str, code: str):
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(code)
    return scan_file(path, tmp_path)


class TestAstRules:
    def test_np_asarray_in_jit(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/ops/x.py",
            "import jax\nimport numpy as np\n"
            "@jax.jit\ndef f(x):\n    return np.asarray(x)\n",
        )
        assert [f.rule for f in findings] == ["host-op-in-jit"]
        assert findings[0].line == 5

    def test_item_and_float_in_jit(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/ops/x.py",
            "from functools import partial\nimport jax\n"
            "@partial(jax.jit, static_argnames=('n',))\n"
            "def f(x, n):\n"
            "    a = x.item()\n"
            "    b = float(x)\n"
            "    c = float(3.5)\n"
            "    return a + b + c\n",
        )
        assert [f.rule for f in findings] == ["host-op-in-jit"] * 2
        assert [f.line for f in findings] == [5, 6]

    def test_host_ops_outside_jit_are_fine(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/ops/x.py",
            "import numpy as np\ndef f(x):\n    return float(np.asarray(x))\n",
        )
        assert findings == []

    def test_import_time_jnp_in_hot_tree(self, tmp_path):
        code = "import jax.numpy as jnp\nTABLE = jnp.zeros(4)\nDT = jnp.float32\n"
        hot = _scan(tmp_path, "protocol_tpu/ops/y.py", code)
        assert [f.rule for f in hot] == ["import-time-jnp"]
        assert hot[0].line == 2
        cold = _scan(tmp_path, "protocol_tpu/zk/y.py", code)
        assert cold == []

    def test_jnp_inside_function_is_fine(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/ops/y.py",
            "import jax.numpy as jnp\ndef f():\n    return jnp.zeros(4)\n",
        )
        assert findings == []

    def test_bare_sync(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/trust/z.py",
            "import jax\ndef f(x):\n"
            "    jax.device_get(x)\n"
            "    x.block_until_ready()\n"
            "    y = jax.device_get(x)\n"
            "    return y\n",
        )
        assert [f.rule for f in findings] == ["bare-sync"] * 2
        assert [f.line for f in findings] == [3, 4]

    def test_real_tree_is_clean(self, real_report):
        _, report = real_report
        ast_errors = [
            f for f in report["findings"] if f["pass"] == "ast" and f["severity"] == "error"
        ]
        assert ast_errors == []


class TestObservabilityBoundaryRules:
    """Pass 3: clocks/logging are host-boundary-only (ISSUE 4)."""

    def test_clock_in_jit(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/trust/x.py",
            "import time\nimport jax\n"
            "@jax.jit\ndef f(x):\n    t0 = time.perf_counter()\n    return x\n",
        )
        assert [f.rule for f in findings] == ["host-clock-in-jit"]
        assert findings[0].line == 5

    def test_bare_perf_counter_in_jit(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/trust/x.py",
            "from time import perf_counter\nimport jax\n"
            "@jax.jit\ndef f(x):\n    return x, perf_counter()\n",
        )
        assert [f.rule for f in findings] == ["host-clock-in-jit"]

    def test_span_in_jit(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/trust/x.py",
            "import jax\nfrom protocol_tpu.obs import TRACER\n"
            "@jax.jit\ndef f(x):\n"
            "    with TRACER.span('inner'):\n        return x * 2\n",
        )
        assert [f.rule for f in findings] == ["host-clock-in-jit"]

    def test_logging_and_print_in_jit(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/trust/x.py",
            "import jax\nimport logging\nlog = logging.getLogger(__name__)\n"
            "@jax.jit\ndef f(x):\n"
            "    log.warning('x=%s', x)\n"
            "    print(x)\n"
            "    return x\n",
        )
        assert [f.rule for f in findings] == ["logging-in-jit"] * 2
        assert [f.line for f in findings] == [6, 7]

    def test_shard_map_body_is_traced(self, tmp_path):
        """The sharded per-shard steps are shard_map-decorated, not
        @jit-decorated — the rule must reach them too."""
        findings = _scan(
            tmp_path,
            "protocol_tpu/parallel/x.py",
            "from functools import partial\nimport jax\n"
            "try:\n    _shard_map = jax.shard_map\n"
            "except AttributeError:\n    _shard_map = None\n"
            "def make(mesh):\n"
            "    @partial(_shard_map, mesh=mesh)\n"
            "    def step(t):\n"
            "        import time\n"
            "        return t, time.monotonic()\n"
            "    return step\n",
        )
        rules = [f.rule for f in findings]
        # the import inside parallel/ also trips the kernel-tree rule
        assert "host-clock-in-jit" in rules

    def test_nested_function_inherits_jit_context(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/trust/x.py",
            "import time\nimport jax\n"
            "@jax.jit\ndef f(x):\n"
            "    def inner(y):\n        return time.time(), y\n"
            "    return inner(x)\n",
        )
        assert [f.rule for f in findings] == ["host-clock-in-jit"]

    def test_clock_in_kernel_tree_outside_jit(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/ops/x.py",
            "import time\ndef probe(x):\n    return time.monotonic(), x\n",
        )
        assert [f.rule for f in findings] == ["clock-in-kernel-tree"] * 2
        assert [f.line for f in findings] == [1, 3]

    def test_host_boundary_timing_in_node_tree_is_fine(self, tmp_path):
        """node/ and trust/ wrap kernels in spans/timers at the host
        boundary — legal; only traced bodies and kernel trees are
        fenced."""
        findings = _scan(
            tmp_path,
            "protocol_tpu/node/x.py",
            "import time\nimport logging\nlog = logging.getLogger(__name__)\n"
            "def tick():\n"
            "    t0 = time.perf_counter()\n"
            "    log.info('tick took %s', time.perf_counter() - t0)\n",
        )
        assert findings == []


class TestJournalBoundaryRule:
    """Pass 5: flight-recorder writes are host-boundary-only (ISSUE 6)."""

    def test_journal_write_in_jit(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/trust/x.py",
            "import jax\nfrom protocol_tpu.obs.journal import JOURNAL\n"
            "@jax.jit\ndef f(x):\n"
            "    JOURNAL.record('iter', x=x)\n"
            "    return x * 2\n",
        )
        assert [f.rule for f in findings] == ["journal-write-in-jit"]
        assert findings[0].file == "protocol_tpu/trust/x.py"
        assert findings[0].line == 5

    def test_journal_dump_in_shard_map_body(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/trust/x.py",
            "import jax\nfrom protocol_tpu.obs.journal import JOURNAL\n"
            "from jax.experimental.shard_map import shard_map\n"
            "@shard_map\ndef step(x):\n"
            "    JOURNAL.dump('/tmp/x')\n"
            "    return x\n",
        )
        assert [f.rule for f in findings] == ["journal-write-in-jit"]
        assert findings[0].line == 6

    def test_instance_journal_receiver_detected(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/node/x.py",
            "import jax\n"
            "@jax.jit\ndef f(self, x):\n"
            "    self._journal.record('iter')\n"
            "    return x\n",
        )
        assert [f.rule for f in findings] == ["journal-write-in-jit"]

    def test_journal_write_at_host_boundary_is_fine(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/node/x.py",
            "from protocol_tpu.obs.journal import JOURNAL\n"
            "def tick():\n"
            "    JOURNAL.record('epoch', n=1)\n",
        )
        assert findings == []

    def test_unrelated_record_method_is_fine(self, tmp_path):
        """Only journal-shaped receivers are fenced — e.g. a metrics
        recorder or audio ``record()`` API must not trip the rule."""
        findings = _scan(
            tmp_path,
            "protocol_tpu/trust/x.py",
            "import jax\n"
            "@jax.jit\ndef f(stats, x):\n"
            "    stats.record(x)\n"
            "    return x\n",
        )
        assert findings == []

    def test_seeded_fixture_registered(self):
        assert "journal-write-in-jit" in FIXTURES
        assert FIXTURES["journal-write-in-jit"].kind == "ast"


class TestEpochLoopIngestRule:
    """Pass 6: the epoch loop neither verifies signatures nor blocks on
    an unbounded queue put (ISSUE 7)."""

    def test_sync_verify_in_epoch_loop_file(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/node/epoch.py",
            "def tick(manager, att):\n"
            "    return manager.add_attestation(att)\n",
        )
        assert [f.rule for f in findings] == ["blocking-ingest-in-epoch-loop"]
        assert findings[0].file == "protocol_tpu/node/epoch.py"
        assert findings[0].line == 2

    def test_unbounded_put_in_pipeline_file(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/node/pipeline.py",
            "import queue\nQ = queue.Queue()\n"
            "def submit(prepared):\n"
            "    Q.put(prepared)\n",
        )
        assert [f.rule for f in findings] == ["blocking-ingest-in-epoch-loop"]
        assert findings[0].line == 4

    def test_bounded_puts_and_put_nowait_are_fine(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/node/pipeline.py",
            "import queue\n"
            "from protocol_tpu.obs import metrics as obs_metrics\n"
            "Q = queue.Queue(maxsize=1)\n"
            "def submit(prepared):\n"
            "    Q.put_nowait(prepared)\n"
            "    Q.put(prepared, timeout=0.05)\n"
            "    Q.put(prepared, block=False)\n"
            "    obs_metrics.PIPELINE_QUEUE_DEPTH.set(Q.qsize())\n",
        )
        assert findings == []

    def test_same_code_outside_epoch_loop_files_is_fine(self, tmp_path):
        """The rule is file-scoped: the admission plane itself (and any
        other module) verifies and enqueues freely."""
        findings = _scan(
            tmp_path,
            "protocol_tpu/ingest/plane.py",
            "import queue\nQ = queue.Queue()\n"
            "def run(manager, att):\n"
            "    Q.put(att)\n"
            "    return manager.add_attestations_bulk([att])\n",
        )
        assert findings == []

    def test_eddsa_verify_call_detected(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/node/epoch.py",
            "from protocol_tpu.crypto import native as cnative\n"
            "def tick(sig):\n"
            "    return cnative.eddsa_verify_batch([sig], [], [], [], [], [])\n",
        )
        assert [f.rule for f in findings] == ["blocking-ingest-in-epoch-loop"]

    def test_seeded_fixture_registered(self):
        assert "blocking-ingest-in-epoch-loop" in FIXTURES
        assert FIXTURES["blocking-ingest-in-epoch-loop"].kind == "ast"

    def test_real_epoch_loop_files_are_clean(self):
        from protocol_tpu.analysis.ast_rules import EPOCH_LOOP_FILES

        root = FIXTURES_PATH.resolve().parents[2]
        for rel in EPOCH_LOOP_FILES:
            findings = scan_file(root / rel, root)
            assert findings == [], (rel, findings)


class TestEpochLoopProveRule:
    """Pass 9: the epoch loop never proves synchronously (ISSUE 10) —
    a SNARK on the epoch path belongs in the proving plane's queue."""

    def test_calculate_proofs_in_epoch_loop_file(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/node/pipeline.py",
            "def device_stage(manager, prepared):\n"
            "    manager.calculate_proofs(prepared.epoch)\n",
        )
        assert [f.rule for f in findings] == ["blocking-prove-in-epoch-loop"]
        assert findings[0].file == "protocol_tpu/node/pipeline.py"
        assert findings[0].line == 2

    def test_plonk_prove_in_epoch_loop_file(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/node/epoch.py",
            "from protocol_tpu.zk import plonk\n"
            "def tick(pk, cs, pub):\n"
            "    return plonk.prove(pk, cs, pub)\n",
        )
        assert [f.rule for f in findings] == ["blocking-prove-in-epoch-loop"]
        assert findings[0].line == 3

    def test_aggregator_calls_flagged(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/node/epoch.py",
            "def tick(manager, epochs):\n"
            "    return manager.aggregate_proofs(epochs)\n",
        )
        assert [f.rule for f in findings] == ["blocking-prove-in-epoch-loop"]

    def test_plane_submit_is_fine(self, tmp_path):
        """The sanctioned shape: enqueue a ProofJob, never prove."""
        findings = _scan(
            tmp_path,
            "protocol_tpu/node/pipeline.py",
            "def device_stage(manager, plane, prepared):\n"
            "    plane.submit(manager.build_proof_job(prepared.epoch))\n"
            "    return prepared\n",
        )
        assert findings == []

    def test_same_code_outside_epoch_loop_files_is_fine(self, tmp_path):
        """File-scoped: the proving plane and the node's sequential
        tick (server.py) prove freely."""
        findings = _scan(
            tmp_path,
            "protocol_tpu/prover/plane.py",
            "def run(manager, epoch):\n"
            "    manager.calculate_proofs(epoch)\n",
        )
        assert findings == []

    def test_seeded_fixture_registered(self):
        assert "blocking-prove-in-epoch-loop" in FIXTURES
        assert FIXTURES["blocking-prove-in-epoch-loop"].kind == "ast"

    def test_real_epoch_loop_files_are_clean_of_prove(self):
        from protocol_tpu.analysis.ast_rules import EPOCH_LOOP_FILES

        root = FIXTURES_PATH.resolve().parents[2]
        for rel in EPOCH_LOOP_FILES:
            findings = scan_file(root / rel, root)
            assert [
                f for f in findings if f.rule == "blocking-prove-in-epoch-loop"
            ] == [], rel


class TestUnobservedQueueRule:
    """Pass 10 (ISSUE 11): every bounded queue constructed in
    protocol_tpu/ must have a queue-depth gauge write in the same
    file — backpressure must be scrapeable, not guessed."""

    def test_bounded_queue_without_gauge_fires(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/ingest/stage.py",
            "import queue\n"
            "class Stage:\n"
            "    def __init__(self):\n"
            "        self._q = queue.Queue(maxsize=4)\n",
        )
        assert [f.rule for f in findings] == ["unobserved-queue"]
        assert findings[0].file == "protocol_tpu/ingest/stage.py"
        assert findings[0].line == 4

    def test_positional_bound_fires_too(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/node/stage.py",
            "from queue import Queue\nQ = Queue(16)\n",
        )
        assert [f.rule for f in findings] == ["unobserved-queue"]
        assert findings[0].line == 2

    def test_depth_gauge_write_in_file_quiets_the_rule(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/ingest/stage.py",
            "import queue\n"
            "from protocol_tpu.obs import metrics as obs_metrics\n"
            "class Stage:\n"
            "    def __init__(self):\n"
            "        self._q = queue.Queue(maxsize=4)\n"
            "    def push(self, item):\n"
            "        self._q.put_nowait(item)\n"
            "        obs_metrics.INGEST_QUEUE_DEPTH.set(\n"
            "            self._q.qsize(), stage='submit')\n",
        )
        assert findings == []

    def test_gauge_registration_with_queue_depth_name_quiets(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/node/stage.py",
            "import queue\n"
            "from protocol_tpu.obs.metrics import METRICS\n"
            "DEPTH = METRICS.gauge('eigentrust_stage_queue_depth', 'd')\n"
            "Q = queue.Queue(maxsize=4)\n",
        )
        assert findings == []

    def test_unbounded_queues_and_rings_are_exempt(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/obs/ring.py",
            "import collections\nimport queue\n"
            "UNBOUNDED = queue.Queue()\n"
            "ALSO_UNBOUNDED = queue.Queue(maxsize=0)\n"
            "NEGATIVE = queue.Queue(maxsize=-1)\n"
            "RING = collections.deque(maxlen=64)\n",
        )
        assert findings == []

    def test_seeded_fixture_registered(self):
        assert "unobserved-queue" in FIXTURES
        assert FIXTURES["unobserved-queue"].kind == "ast"

    def test_real_tree_queue_files_are_clean(self):
        """The real bounded-queue constructors (ingest plane, epoch
        pipeline) all register depth gauges — the rule stays quiet on
        the live tree."""
        root = FIXTURES_PATH.resolve().parents[2]
        for rel in (
            "protocol_tpu/ingest/plane.py",
            "protocol_tpu/node/pipeline.py",
            "protocol_tpu/prover/plane.py",
            "protocol_tpu/obs/journal.py",
        ):
            findings = scan_file(root / rel, root)
            assert [
                f for f in findings if f.rule == "unobserved-queue"
            ] == [], rel


class TestNonAtomicStateWriteRule:
    """Pass 11 (ISSUE 14): durable node state must go through the
    checkpoint store's _atomic_write helper (tmp + fsync + rename) or
    carry fsync discipline in the same function (the WAL's shape) —
    a bare open()+write in node/ can be torn by a crash mid-write."""

    def test_bare_open_write_in_node_fires(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/node/cursor.py",
            "import json\n"
            "def persist(path, cursor):\n"
            "    with open(path, 'w') as f:\n"
            "        json.dump({'cursor': cursor}, f)\n",
        )
        assert [f.rule for f in findings] == ["non-atomic-state-write"]
        assert findings[0].file == "protocol_tpu/node/cursor.py"
        assert findings[0].line == 3

    def test_write_text_and_write_bytes_fire(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/node/state.py",
            "def a(p):\n"
            "    p.write_text('x')\n"
            "def b(p):\n"
            "    p.write_bytes(b'x')\n",
        )
        assert [f.rule for f in findings] == [
            "non-atomic-state-write",
            "non-atomic-state-write",
        ]
        assert [f.line for f in findings] == [2, 4]

    def test_module_scope_write_fires(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/node/boot.py",
            "open('/tmp/state', 'wb').write(b'x')\n",
        )
        assert [f.rule for f in findings] == ["non-atomic-state-write"]

    def test_atomic_write_helper_is_exempt(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/node/store.py",
            "import os, tempfile\n"
            "def _atomic_write(dest, write_fn, mode):\n"
            "    fd, tmp = tempfile.mkstemp()\n"
            "    with os.fdopen(fd, mode) as f:\n"
            "        write_fn(f)\n"
            "        os.fsync(f.fileno())\n"
            "    os.replace(tmp, dest)\n",
        )
        assert findings == []

    def test_fsync_discipline_in_same_function_is_exempt(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/node/seglog.py",
            "import os\n"
            "def open_segment(path):\n"
            "    f = open(path, 'wb')\n"
            "    f.write(b'MAGIC')\n"
            "    f.flush()\n"
            "    os.fsync(f.fileno())\n"
            "    return f\n",
        )
        assert findings == []

    def test_reads_are_fine(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/node/reader.py",
            "def load(path):\n"
            "    with open(path) as f:\n"
            "        a = f.read()\n"
            "    with open(path, 'rb') as f:\n"
            "        return a, f.read()\n",
        )
        assert findings == []

    def test_same_code_outside_node_tree_is_fine(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/obs/export.py",
            "def dump(path, text):\n"
            "    with open(path, 'w') as f:\n"
            "        f.write(text)\n",
        )
        assert findings == []

    def test_seeded_fixture_registered(self):
        assert "non-atomic-state-write" in FIXTURES
        assert FIXTURES["non-atomic-state-write"].kind == "ast"

    def test_real_node_tree_is_clean(self):
        """checkpoint.py routes through _atomic_write, wal.py fsyncs
        what it opens — the rule stays quiet on the live tree."""
        root = FIXTURES_PATH.resolve().parents[2]
        for path in sorted((root / "protocol_tpu" / "node").glob("*.py")):
            findings = scan_file(path, root)
            assert [
                f for f in findings if f.rule == "non-atomic-state-write"
            ] == [], path.name


class TestFaultPointInJitRule:
    """Pass 11 (ISSUE 14): chaos hooks are host-boundary-only — inside
    traced code they fire once at trace time and the schedule silently
    stops covering the point (the pass 3/5 host-callback doctrine)."""

    def test_chaos_fire_in_jit_fires(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/trust/kern.py",
            "import jax\n"
            "from protocol_tpu import chaos\n"
            "@jax.jit\n"
            "def step(t):\n"
            "    chaos.fire('epoch.post_converge')\n"
            "    return t * 2.0\n",
        )
        assert [f.rule for f in findings] == ["fault-point-in-jit"]
        assert findings[0].line == 5

    def test_chaos_corrupt_in_shard_map_fires(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/parallel/kern.py",
            "from functools import partial\n"
            "from jax.experimental.shard_map import shard_map\n"
            "from protocol_tpu import chaos\n"
            "@partial(shard_map, mesh=None, in_specs=None, out_specs=None)\n"
            "def step(t):\n"
            "    data = chaos.corrupt('wal.append', t)\n"
            "    return data\n",
        )
        assert "fault-point-in-jit" in [f.rule for f in findings]

    def test_host_boundary_chaos_is_fine(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/node/tick.py",
            "from protocol_tpu import chaos\n"
            "def epoch_tick(epoch):\n"
            "    if chaos.ACTIVE:\n"
            "        chaos.fire('epoch.post_converge')\n"
            "    return epoch\n",
        )
        assert findings == []

    def test_unrelated_fire_methods_are_fine(self, tmp_path):
        findings = _scan(
            tmp_path,
            "protocol_tpu/trust/kern.py",
            "import jax\n"
            "@jax.jit\n"
            "def step(t, trigger):\n"
            "    trigger.fire(t)\n"
            "    return t\n",
        )
        assert findings == []

    def test_seeded_fixture_registered(self):
        assert "fault-point-in-jit" in FIXTURES
        assert FIXTURES["fault-point-in-jit"].kind == "ast"

    def test_real_tree_is_clean_of_chaos_in_jit(self):
        from protocol_tpu.analysis.ast_rules import run_ast_pass

        findings, _ = run_ast_pass()
        assert [f for f in findings if f.rule == "fault-point-in-jit"] == []
