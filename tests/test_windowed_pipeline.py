"""Fused fixed-slot pipeline (PERF.md §7-8) — interpret-mode
equivalence of the ``tpu-windowed`` backend (single-device and sharded
across the 8-device CPU mesh) against ``tpu-csr``/``native-cpu``,
``bucket_by_window`` layout properties including the single-pass
boundary bridge, the one-random-gather acceptance bound, and WindowPlan
persistence/versioning through the checkpoint store.

Everything runs under the conftest CPU platform: the Pallas kernel
executes in interpret mode (the identical lowered computation, minus
Mosaic codegen), which is the test doctrine PERF.md §6 establishes for
the windowed gather.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from protocol_tpu.analysis.jaxpr_walk import collect_gathers
from protocol_tpu.models.graphs import erdos_renyi, scale_free
from protocol_tpu.node.checkpoint import CheckpointStore
from protocol_tpu.node.epoch import Epoch
from protocol_tpu.ops.gather_window import (
    PLAN_VERSION,
    ROW,
    WINDOW,
    WindowPlan,
    bucket_by_window,
    build_window_plan,
    graph_fingerprint,
    power_step_windowed,
)
from protocol_tpu.trust.backend import WindowedJaxBackend, get_backend
from protocol_tpu.trust.graph import TrustGraph


def drop_out_edges(g: TrustGraph, peers) -> TrustGraph:
    """Make ``peers`` dangling by removing every edge they send."""
    keep = ~np.isin(g.src, np.asarray(peers, dtype=np.int32))
    return TrustGraph(g.n, g.src[keep], g.dst[keep], g.weight[keep], g.pre_trusted)


def l1(a, b) -> float:
    return float(np.abs(np.asarray(a) - np.asarray(b)).sum())


class TestWindowedBackendEquivalence:
    """Acceptance: tpu-windowed matches tpu-csr to ≤1e-5 L1 in CPU
    interpret mode, including dangling rows and non-aligned N."""

    def test_matches_csr_erdos_renyi_non_aligned(self):
        # 773 peers: not divisible by WINDOW (table padding in play),
        # plus forced dangling rows (out-edge-free peers).
        g = drop_out_edges(erdos_renyi(773, avg_degree=5.0, seed=1), [0, 17, 772])
        csr = get_backend("tpu-csr").converge(g, alpha=0.1, tol=1e-9, max_iter=60)
        win = get_backend("tpu-windowed").converge(g, alpha=0.1, tol=1e-9, max_iter=60)
        assert l1(win.scores, csr.scores) <= 1e-5
        assert win.backend == "tpu-windowed"
        assert win.scores.sum() == pytest.approx(1.0, rel=1e-5)

    def test_matches_csr_scale_free(self):
        g = scale_free(1500, 9000, seed=2)
        csr = get_backend("tpu-csr").converge(g, alpha=0.1, tol=1e-9, max_iter=60)
        win = get_backend("tpu-windowed").converge(g, alpha=0.1, tol=1e-9, max_iter=60)
        assert l1(win.scores, csr.scores) <= 1e-5

    def test_matches_csr_multi_window(self):
        # n > WINDOW so the kernel resolves across several table windows.
        g = drop_out_edges(scale_free(3 * WINDOW + 137, 20000, seed=3), [5, 2048])
        csr = get_backend("tpu-csr").converge(g, alpha=0.15, tol=0, max_iter=30)
        win = get_backend("tpu-windowed").converge(g, alpha=0.15, tol=0, max_iter=30)
        assert l1(win.scores, csr.scores) <= 1e-5
        assert win.iterations == 30  # fixed-iter mode drives the same driver

    def test_matches_exact_native(self):
        g = erdos_renyi(40, avg_degree=4.0, seed=2)
        exact = get_backend("native-cpu").converge(g, alpha=0.15, tol=0, max_iter=25)
        win = get_backend("tpu-windowed").converge(g, alpha=0.15, tol=0, max_iter=25)
        assert l1(win.scores, exact.scores) <= 1e-5

    def test_plan_reuse_and_rebuild(self):
        g = erdos_renyi(600, avg_degree=5.0, seed=4)
        backend = WindowedJaxBackend()
        backend.converge(g, alpha=0.1, max_iter=10)
        plan_first = backend.last_plan
        backend.converge(g, alpha=0.1, max_iter=10)
        assert backend.last_plan is plan_first  # fingerprint hit: no rebuild
        g2 = erdos_renyi(600, avg_degree=5.0, seed=5)
        backend.converge(g2, alpha=0.1, max_iter=10)
        assert backend.last_plan is not plan_first  # graph changed: rebuilt

    def test_registry_constructs_windowed(self):
        assert get_backend("tpu-windowed").name == "tpu-windowed"


class TestBucketByWindowProperties:
    def _random_edges(self, seed, n=3000, e=20000):
        rng = np.random.default_rng(seed)
        src = rng.integers(0, n, e).astype(np.int32)
        dst = rng.integers(0, n, e).astype(np.int32)
        w = rng.random(e, dtype=np.float32)
        return n, src, dst, w

    def test_order_out_pos_round_trip(self):
        """``order``/``out_pos`` reconstruct the input edge list exactly:
        slot out_pos[k] carries edge order[k]'s window-local src and
        weight, every other slot is zero-weight padding."""
        n, src, dst, w = self._random_edges(7)
        b = bucket_by_window(src, w, table_size=n)
        order, out_pos = b["order"], b["out_pos"]
        assert sorted(order.tolist()) == list(range(len(src)))  # permutation
        local = b["local"].reshape(-1)
        weight = b["weight"].reshape(-1)
        np.testing.assert_array_equal(local[out_pos], src[order] % WINDOW)
        np.testing.assert_array_equal(weight[out_pos], w[order])
        pad = np.ones(weight.shape[0], bool)
        pad[out_pos] = False
        assert not weight[pad].any()
        # Each slot's global src index re-derives from wid + local.
        wid = np.repeat(b["wid"], WINDOW)
        np.testing.assert_array_equal(
            (wid[out_pos].astype(np.int64) * WINDOW + local[out_pos]), src[order]
        )

    def test_rejects_out_of_range_src(self):
        n, src, dst, w = self._random_edges(8, n=500, e=100)
        bad = src.copy()
        bad[3] = 500  # == table_size
        with pytest.raises(ValueError, match="src index"):
            bucket_by_window(bad, w, table_size=500)
        neg = src.copy()
        neg[0] = -1
        with pytest.raises(ValueError, match="src index"):
            bucket_by_window(neg, w, table_size=500)

    def test_rejects_out_of_range_dst(self):
        n, src, dst, w = self._random_edges(9, n=500, e=100)
        bad = dst.copy()
        bad[5] = 700
        with pytest.raises(ValueError, match="dst index"):
            bucket_by_window(src, w, table_size=500, dst=bad, n_dst=500)
        with pytest.raises(ValueError, match="n_dst"):
            bucket_by_window(src, w, table_size=500, dst=dst)

    def test_segment_plan_reduces_exactly(self):
        """Adjacent-run differencing over the row-local prefix sum —
        the device's exact recipe (``bridge_partials``) emulated in
        f64 — reproduces the direct per-dst sum of w·x[src] on random
        graphs: the bucket-order boundary table plus the one dst
        permutation is a faithful reduction plan."""
        n, src, dst, w = self._random_edges(10)
        b = bucket_by_window(src, w, table_size=n, dst=dst, n_dst=n)
        rng = np.random.default_rng(11)
        x = rng.random(n).astype(np.float32)
        contrib = np.zeros(b["n_rows"] * WINDOW, np.float64)
        contrib[b["out_pos"]] = (w[b["order"]].astype(np.float64)
                                 * x[src[b["order"]]].astype(np.float64))
        # Row-local inclusive prefix, exactly like the device step.
        rowcum = np.cumsum(contrib.reshape(b["n_rows"], ROW), axis=1).reshape(-1)
        seg_end = b["seg_end"].astype(np.int64)
        ends = rowcum[seg_end]
        prev = np.where(b["seg_first"], 0.0, np.concatenate([[0.0], ends[:-1]]))
        partial = (ends - prev)[b["seg_perm"]]
        ptr = b["dst_ptr"].astype(np.int64)
        per_dst = np.add.reduceat(
            np.concatenate([partial, [0.0]]), np.minimum(ptr[:-1], len(partial))
        )
        per_dst[ptr[:-1] == ptr[1:]] = 0.0
        expect = np.zeros(n)
        np.add.at(expect, dst, w.astype(np.float64) * x[src].astype(np.float64))
        np.testing.assert_allclose(per_dst, expect, rtol=1e-5, atol=1e-12)

    def test_segment_plan_layout_invariants(self):
        """Bucket-order invariants the single-pass bridge relies on:
        strictly increasing run ends (the boundary read streams), a
        row-leading flag exactly at vreg-row changes (so the shifted
        differencing never crosses a row), and a true permutation."""
        n, src, dst, w = self._random_edges(12)
        b = bucket_by_window(src, w, table_size=n, dst=dst, n_dst=n)
        seg_end, seg_first = b["seg_end"], b["seg_first"]
        assert (np.diff(seg_end.astype(np.int64)) > 0).all()
        rows = seg_end // ROW
        expect_first = np.empty(len(seg_end), bool)
        expect_first[0] = True
        expect_first[1:] = rows[1:] != rows[:-1]
        np.testing.assert_array_equal(seg_first, expect_first)
        assert sorted(b["seg_perm"].tolist()) == list(range(b["n_segments"]))
        assert int(b["dst_ptr"][-1]) == b["n_segments"]


class TestSinglePassBoundary:
    """ISSUE 2 acceptance: per-iteration boundary random volume in
    ``power_step_windowed`` is ONE n_segments-sized random gather."""

    def test_one_random_segment_gather_in_step(self):
        g = scale_free(1500, 9000, seed=2).drop_self_edges()
        w, dangling = g.row_normalized()
        plan = build_window_plan(g.src, g.dst, w, n=g.n)
        p = g.pre_trust_vector()
        args = plan.device_args() + (
            jnp.asarray(p),
            jnp.asarray(p),
            jnp.asarray(dangling.astype(np.float32)),
            jax.device_put(np.float32(0.1)),
        )
        jaxpr = jax.make_jaxpr(
            lambda *a: power_step_windowed(
                *a,
                n_rows=plan.n_rows,
                table_entries=plan.table_entries,
                interpret=True,
            )
        )(*args)
        # The shared recursive walker (protocol_tpu.analysis.jaxpr_walk)
        # — the analyzer gate counts gathers with exactly this traversal.
        gathers = collect_gathers(jaxpr.jaxpr)
        # Device segment tables run at padded capacity (>= n_segments
        # live runs) so per-epoch deltas keep the compiled shape.
        s = plan.seg_capacity
        assert s != plan.n + 1  # keep the rowsum gathers distinguishable
        seg_sized = [e for e in gathers if e.outvars[0].aval.shape[:1] == (s,)]
        random_seg = [
            e for e in seg_sized if not e.params.get("indices_are_sorted")
        ]
        # Exactly two n_segments-sized gathers: the 2-wide boundary
        # read, declared sorted+unique (bucket-order ends are strictly
        # increasing — it streams), and the single dst permutation —
        # the one random pass the tentpole allows.
        assert len(seg_sized) == 2
        assert len(random_seg) == 1
        (boundary,) = [e for e in seg_sized if e.params.get("indices_are_sorted")]
        assert boundary.outvars[0].aval.shape == (s, 2)  # hi/lo interleaved
        assert boundary.params.get("unique_indices")


class TestWindowPlanCheckpoint:
    def _plan(self, seed=12, n=900):
        g = scale_free(n, 5000, seed=seed).drop_self_edges()
        w, _ = g.row_normalized()
        return build_window_plan(g.src, g.dst, w, n=g.n)

    def test_round_trips_through_store(self, tmp_path):
        plan = self._plan()
        g = erdos_renyi(30, seed=13)
        store = CheckpointStore(tmp_path)
        store.save(Epoch(9), g, plan=plan)
        snap = store.load_latest()
        assert snap.plan is not None
        assert snap.plan.fingerprint == plan.fingerprint
        assert snap.plan.version == PLAN_VERSION
        assert (snap.plan.n, snap.plan.n_rows) == (plan.n, plan.n_rows)
        assert (snap.plan.table_entries, snap.plan.n_segments) == (
            plan.table_entries,
            plan.n_segments,
        )
        for k in WindowPlan._CORE:
            np.testing.assert_array_equal(getattr(snap.plan, k), getattr(plan, k))
        # Checkpoints persist only the core arrays (order/out_pos are
        # test/diagnostic-only and E-sized).
        assert snap.plan.order is None and snap.plan.out_pos is None

    def test_stale_plan_version_rejected_and_tolerated(self, tmp_path):
        """A v1-era sidecar (no ``version`` key, pre-interleave arrays)
        must not rehydrate: ``from_arrays`` raises, and the store
        degrades to ``plan=None`` so the next converge rebuilds."""
        plan = self._plan()
        g = erdos_renyi(30, seed=13)
        store = CheckpointStore(tmp_path)
        store.save(Epoch(3), g, plan=plan)
        # Rewrite the sidecar as an old-format plan: strip the version
        # stamp (v1 files never had one).
        arrays = plan.to_arrays(core_only=True)
        del arrays["version"]
        np.savez(tmp_path / "epoch_3.plan.npz", **arrays)
        with np.load(tmp_path / "epoch_3.plan.npz") as z:
            with pytest.raises(ValueError, match="stale"):
                WindowPlan.from_arrays(z)
        snap = store.load_latest()
        assert snap.plan is None  # graph snapshot still served
        assert snap.graph.n == g.n

    def test_stale_version_plan_triggers_rebuild(self):
        """A fingerprint-valid plan carrying an old layout version is
        rebuilt, not fed to the device."""
        g = scale_free(900, 5000, seed=12).drop_self_edges()
        w, _ = g.row_normalized()
        plan = build_window_plan(g.src, g.dst, w, n=g.n)
        stale = dataclasses.replace(plan, version=1)
        backend = WindowedJaxBackend(plan=stale)
        backend.converge(g, alpha=0.1, tol=1e-9, max_iter=5)
        assert backend.last_plan is not stale
        assert backend.last_plan.version == PLAN_VERSION

    def test_restored_plan_skips_rebuild(self, tmp_path, monkeypatch):
        g = scale_free(900, 5000, seed=12).drop_self_edges()
        w, _ = g.row_normalized()
        plan = build_window_plan(g.src, g.dst, w, n=g.n)
        store = CheckpointStore(tmp_path)
        store.save(Epoch(1), g, plan=plan)
        restored = store.load_latest().plan

        import protocol_tpu.trust.backend as backend_mod

        def boom(*a, **k):  # a fingerprint hit must not reconstruct
            raise AssertionError("plan rebuilt despite checkpoint restore")

        monkeypatch.setattr(backend_mod, "build_window_plan", boom)
        backend = WindowedJaxBackend(plan=restored)
        res = backend.converge(g, alpha=0.1, tol=1e-9, max_iter=40)
        csr = get_backend("tpu-csr").converge(g, alpha=0.1, tol=1e-9, max_iter=40)
        assert l1(res.scores, csr.scores) <= 1e-5

    def test_prune_removes_plan_files(self, tmp_path):
        store = CheckpointStore(tmp_path, keep=1)
        g = erdos_renyi(30, seed=14)
        plan = self._plan()
        store.save(Epoch(1), g, plan=plan)
        store.save(Epoch(2), g, plan=plan)
        assert not (tmp_path / "epoch_1.plan.npz").exists()
        assert (tmp_path / "epoch_2.plan.npz").exists()

    def test_no_plan_is_fine(self, tmp_path):
        store = CheckpointStore(tmp_path)
        store.save(Epoch(4), erdos_renyi(10, seed=15))
        assert store.load_latest().plan is None

    def test_fingerprint_sensitivity(self):
        g = scale_free(700, 4000, seed=16).drop_self_edges()
        w, _ = g.row_normalized()
        fp = graph_fingerprint(g.n, g.src, g.dst, w)
        assert fp == graph_fingerprint(g.n, g.src, g.dst, w)  # deterministic
        w2 = w.copy()
        w2[0] += 0.5
        assert fp != graph_fingerprint(g.n, g.src, g.dst, w2)
        assert fp != graph_fingerprint(g.n + 1, g.src, g.dst, w)


class TestShardedWindowedBackend:
    """ISSUE 2 acceptance: ``converge_sharded`` exposes a working
    ``tpu-windowed`` kernel matching ``converge_csr`` within renorm
    tolerance on the 8-device CPU mesh — with dangling rows,
    shard-straddling dst rows, and non-aligned N."""

    def _graph(self):
        # Non-WINDOW-aligned N across several table windows, enough
        # edges that the window rows span multiple shards (>64 data
        # vreg-rows), and forced dangling peers.
        g = scale_free(2 * WINDOW + 901, 70_000, seed=31)
        return drop_out_edges(g, [3, 700, 2948])

    def test_matches_csr_on_8_device_mesh(self):
        g = self._graph()
        csr = get_backend("tpu-csr").converge(g, alpha=0.1, tol=1e-9, max_iter=40)
        shw = get_backend("tpu-sharded:tpu-windowed").converge(
            g, alpha=0.1, tol=1e-9, max_iter=40
        )
        assert l1(shw.scores, csr.scores) <= 1e-5
        assert shw.backend == "tpu-sharded:tpu-windowed"
        assert shw.scores.sum() == pytest.approx(1.0, rel=1e-5)

    def test_partition_straddles_shards(self):
        """The row partition genuinely spreads data over several shards
        and splits at least one destination's runs across a shard cut —
        the case the psum must complete."""
        from protocol_tpu.parallel.mesh import default_mesh
        from protocol_tpu.parallel.sharded import ShardedWindowPlan

        swp = ShardedWindowPlan.build(self._graph(), default_mesh())
        dst_ptr = np.asarray(swp.dst_ptr)  # (n_shards, n+1)
        runs_per_shard = dst_ptr[:, -1]
        assert (runs_per_shard > 0).sum() >= 2, runs_per_shard
        per_dst_per_shard = np.diff(dst_ptr, axis=1)  # (n_shards, n)
        straddling = ((per_dst_per_shard > 0).sum(axis=0) >= 2).sum()
        assert straddling > 0
        # Every shard's rebased run ends stay inside its row slice.
        seg_end = np.asarray(swp.seg_end).reshape(len(runs_per_shard), -1)
        assert seg_end.min() >= 0
        assert seg_end.max() < swp.rows_per_shard * ROW

    def test_explicit_small_mesh(self):
        from protocol_tpu.parallel.mesh import default_mesh

        g = scale_free(600, 4000, seed=33)
        res = get_backend("tpu-sharded", mesh=default_mesh(4), kernel="tpu-windowed")
        out = res.converge(g, alpha=0.1, max_iter=20)
        assert out.scores.shape == (600,)
        assert out.scores.sum() == pytest.approx(1.0, rel=1e-5)

    def test_restored_plan_skips_rebuild(self, monkeypatch):
        """A fingerprint-valid WindowPlan seeds the sharded build — the
        checkpoint-restore path shared with the single-device backend."""
        import protocol_tpu.parallel.sharded as sharded_mod

        g = self._graph().drop_self_edges()
        w, _ = g.row_normalized()
        plan = build_window_plan(g.src, g.dst, w, n=g.n)

        def boom(*a, **k):
            raise AssertionError("plan rebuilt despite valid seed")

        monkeypatch.setattr(sharded_mod, "build_window_plan", boom)
        backend = get_backend("tpu-sharded:tpu-windowed")
        backend.plan = plan
        res = backend.converge(g, alpha=0.1, tol=1e-9, max_iter=20)
        assert backend.last_plan is plan
        csr = get_backend("tpu-csr").converge(g, alpha=0.1, tol=1e-9, max_iter=20)
        assert l1(res.scores, csr.scores) <= 1e-5

    def test_rejects_unknown_kernel(self):
        with pytest.raises(ValueError, match="unknown sharded kernel"):
            get_backend("tpu-sharded:bogus")
        with pytest.raises(ValueError, match="unknown trust backend"):
            get_backend("tpu-csr:tpu-windowed")
