"""Fused fixed-slot pipeline (PERF.md §7) — interpret-mode equivalence
of the ``tpu-windowed`` backend against ``tpu-csr``/``native-cpu``,
``bucket_by_window`` layout properties, and WindowPlan persistence
through the checkpoint store.

Everything runs under the conftest CPU platform: the Pallas kernel
executes in interpret mode (the identical lowered computation, minus
Mosaic codegen), which is the test doctrine PERF.md §6 establishes for
the windowed gather.
"""

import numpy as np
import pytest

from protocol_tpu.models.graphs import erdos_renyi, scale_free
from protocol_tpu.node.checkpoint import CheckpointStore
from protocol_tpu.node.epoch import Epoch
from protocol_tpu.ops.gather_window import (
    WINDOW,
    WindowPlan,
    bucket_by_window,
    build_window_plan,
    graph_fingerprint,
)
from protocol_tpu.trust.backend import WindowedJaxBackend, get_backend
from protocol_tpu.trust.graph import TrustGraph


def drop_out_edges(g: TrustGraph, peers) -> TrustGraph:
    """Make ``peers`` dangling by removing every edge they send."""
    keep = ~np.isin(g.src, np.asarray(peers, dtype=np.int32))
    return TrustGraph(g.n, g.src[keep], g.dst[keep], g.weight[keep], g.pre_trusted)


def l1(a, b) -> float:
    return float(np.abs(np.asarray(a) - np.asarray(b)).sum())


class TestWindowedBackendEquivalence:
    """Acceptance: tpu-windowed matches tpu-csr to ≤1e-5 L1 in CPU
    interpret mode, including dangling rows and non-aligned N."""

    def test_matches_csr_erdos_renyi_non_aligned(self):
        # 773 peers: not divisible by WINDOW (table padding in play),
        # plus forced dangling rows (out-edge-free peers).
        g = drop_out_edges(erdos_renyi(773, avg_degree=5.0, seed=1), [0, 17, 772])
        csr = get_backend("tpu-csr").converge(g, alpha=0.1, tol=1e-9, max_iter=60)
        win = get_backend("tpu-windowed").converge(g, alpha=0.1, tol=1e-9, max_iter=60)
        assert l1(win.scores, csr.scores) <= 1e-5
        assert win.backend == "tpu-windowed"
        assert win.scores.sum() == pytest.approx(1.0, rel=1e-5)

    def test_matches_csr_scale_free(self):
        g = scale_free(1500, 9000, seed=2)
        csr = get_backend("tpu-csr").converge(g, alpha=0.1, tol=1e-9, max_iter=60)
        win = get_backend("tpu-windowed").converge(g, alpha=0.1, tol=1e-9, max_iter=60)
        assert l1(win.scores, csr.scores) <= 1e-5

    def test_matches_csr_multi_window(self):
        # n > WINDOW so the kernel resolves across several table windows.
        g = drop_out_edges(scale_free(3 * WINDOW + 137, 20000, seed=3), [5, 2048])
        csr = get_backend("tpu-csr").converge(g, alpha=0.15, tol=0, max_iter=30)
        win = get_backend("tpu-windowed").converge(g, alpha=0.15, tol=0, max_iter=30)
        assert l1(win.scores, csr.scores) <= 1e-5
        assert win.iterations == 30  # fixed-iter mode drives the same driver

    def test_matches_exact_native(self):
        g = erdos_renyi(40, avg_degree=4.0, seed=2)
        exact = get_backend("native-cpu").converge(g, alpha=0.15, tol=0, max_iter=25)
        win = get_backend("tpu-windowed").converge(g, alpha=0.15, tol=0, max_iter=25)
        assert l1(win.scores, exact.scores) <= 1e-5

    def test_plan_reuse_and_rebuild(self):
        g = erdos_renyi(600, avg_degree=5.0, seed=4)
        backend = WindowedJaxBackend()
        backend.converge(g, alpha=0.1, max_iter=10)
        plan_first = backend.last_plan
        backend.converge(g, alpha=0.1, max_iter=10)
        assert backend.last_plan is plan_first  # fingerprint hit: no rebuild
        g2 = erdos_renyi(600, avg_degree=5.0, seed=5)
        backend.converge(g2, alpha=0.1, max_iter=10)
        assert backend.last_plan is not plan_first  # graph changed: rebuilt

    def test_registry_constructs_windowed(self):
        assert get_backend("tpu-windowed").name == "tpu-windowed"


class TestBucketByWindowProperties:
    def _random_edges(self, seed, n=3000, e=20000):
        rng = np.random.default_rng(seed)
        src = rng.integers(0, n, e).astype(np.int32)
        dst = rng.integers(0, n, e).astype(np.int32)
        w = rng.random(e, dtype=np.float32)
        return n, src, dst, w

    def test_order_out_pos_round_trip(self):
        """``order``/``out_pos`` reconstruct the input edge list exactly:
        slot out_pos[k] carries edge order[k]'s window-local src and
        weight, every other slot is zero-weight padding."""
        n, src, dst, w = self._random_edges(7)
        b = bucket_by_window(src, w, table_size=n)
        order, out_pos = b["order"], b["out_pos"]
        assert sorted(order.tolist()) == list(range(len(src)))  # permutation
        local = b["local"].reshape(-1)
        weight = b["weight"].reshape(-1)
        np.testing.assert_array_equal(local[out_pos], src[order] % WINDOW)
        np.testing.assert_array_equal(weight[out_pos], w[order])
        pad = np.ones(weight.shape[0], bool)
        pad[out_pos] = False
        assert not weight[pad].any()
        # Each slot's global src index re-derives from wid + local.
        wid = np.repeat(b["wid"], WINDOW)
        np.testing.assert_array_equal(
            (wid[out_pos].astype(np.int64) * WINDOW + local[out_pos]), src[order]
        )

    def test_rejects_out_of_range_src(self):
        n, src, dst, w = self._random_edges(8, n=500, e=100)
        bad = src.copy()
        bad[3] = 500  # == table_size
        with pytest.raises(ValueError, match="src index"):
            bucket_by_window(bad, w, table_size=500)
        neg = src.copy()
        neg[0] = -1
        with pytest.raises(ValueError, match="src index"):
            bucket_by_window(neg, w, table_size=500)

    def test_rejects_out_of_range_dst(self):
        n, src, dst, w = self._random_edges(9, n=500, e=100)
        bad = dst.copy()
        bad[5] = 700
        with pytest.raises(ValueError, match="dst index"):
            bucket_by_window(src, w, table_size=500, dst=bad, n_dst=500)
        with pytest.raises(ValueError, match="n_dst"):
            bucket_by_window(src, w, table_size=500, dst=dst)

    def test_segment_plan_reduces_exactly(self):
        """The static two-level plan is a partition of the slots: summing
        contributions by segment and then by ``dst_ptr`` range equals the
        direct per-dst sum of w·x[src]."""
        n, src, dst, w = self._random_edges(10)
        b = bucket_by_window(src, w, table_size=n, dst=dst, n_dst=n)
        rng = np.random.default_rng(11)
        x = rng.random(n).astype(np.float32)
        contrib = np.zeros(b["n_rows"] * WINDOW, np.float64)
        contrib[b["out_pos"]] = (w[b["order"]].astype(np.float64)
                                 * x[src[b["order"]]].astype(np.float64))
        cum = np.concatenate([[0.0], np.cumsum(contrib)])
        partial = cum[b["seg_end"].astype(np.int64) + 1] - cum[b["seg_start"].astype(np.int64)]
        ptr = b["dst_ptr"].astype(np.int64)
        per_dst = np.add.reduceat(
            np.concatenate([partial, [0.0]]), np.minimum(ptr[:-1], len(partial))
        )
        per_dst[ptr[:-1] == ptr[1:]] = 0.0
        expect = np.zeros(n)
        np.add.at(expect, dst, w.astype(np.float64) * x[src].astype(np.float64))
        np.testing.assert_allclose(per_dst, expect, rtol=1e-5, atol=1e-12)
        # Segments never span a vreg-row (the device prefix sum resets
        # per row), and runs are dst-sorted by construction.
        assert (b["seg_start"] // WINDOW == b["seg_end"] // WINDOW).all()
        assert (b["seg_start"] <= b["seg_end"]).all()


class TestWindowPlanCheckpoint:
    def _plan(self, seed=12, n=900):
        g = scale_free(n, 5000, seed=seed).drop_self_edges()
        w, _ = g.row_normalized()
        return build_window_plan(g.src, g.dst, w, n=g.n)

    def test_round_trips_through_store(self, tmp_path):
        plan = self._plan()
        g = erdos_renyi(30, seed=13)
        store = CheckpointStore(tmp_path)
        store.save(Epoch(9), g, plan=plan)
        snap = store.load_latest()
        assert snap.plan is not None
        assert snap.plan.fingerprint == plan.fingerprint
        assert (snap.plan.n, snap.plan.n_rows) == (plan.n, plan.n_rows)
        assert (snap.plan.table_entries, snap.plan.n_segments) == (
            plan.table_entries,
            plan.n_segments,
        )
        for k in WindowPlan._CORE:
            np.testing.assert_array_equal(getattr(snap.plan, k), getattr(plan, k))
        # Checkpoints persist only the core arrays (order/out_pos are
        # test/diagnostic-only and E-sized).
        assert snap.plan.order is None and snap.plan.out_pos is None

    def test_restored_plan_skips_rebuild(self, tmp_path, monkeypatch):
        g = scale_free(900, 5000, seed=12).drop_self_edges()
        w, _ = g.row_normalized()
        plan = build_window_plan(g.src, g.dst, w, n=g.n)
        store = CheckpointStore(tmp_path)
        store.save(Epoch(1), g, plan=plan)
        restored = store.load_latest().plan

        import protocol_tpu.trust.backend as backend_mod

        def boom(*a, **k):  # a fingerprint hit must not reconstruct
            raise AssertionError("plan rebuilt despite checkpoint restore")

        monkeypatch.setattr(backend_mod, "build_window_plan", boom)
        backend = WindowedJaxBackend(plan=restored)
        res = backend.converge(g, alpha=0.1, tol=1e-9, max_iter=40)
        csr = get_backend("tpu-csr").converge(g, alpha=0.1, tol=1e-9, max_iter=40)
        assert l1(res.scores, csr.scores) <= 1e-5

    def test_prune_removes_plan_files(self, tmp_path):
        store = CheckpointStore(tmp_path, keep=1)
        g = erdos_renyi(30, seed=14)
        plan = self._plan()
        store.save(Epoch(1), g, plan=plan)
        store.save(Epoch(2), g, plan=plan)
        assert not (tmp_path / "epoch_1.plan.npz").exists()
        assert (tmp_path / "epoch_2.plan.npz").exists()

    def test_no_plan_is_fine(self, tmp_path):
        store = CheckpointStore(tmp_path)
        store.save(Epoch(4), erdos_renyi(10, seed=15))
        assert store.load_latest().plan is None

    def test_fingerprint_sensitivity(self):
        g = scale_free(700, 4000, seed=16).drop_self_edges()
        w, _ = g.row_normalized()
        fp = graph_fingerprint(g.n, g.src, g.dst, w)
        assert fp == graph_fingerprint(g.n, g.src, g.dst, w)  # deterministic
        w2 = w.copy()
        w2[0] += 0.5
        assert fp != graph_fingerprint(g.n, g.src, g.dst, w2)
        assert fp != graph_fingerprint(g.n + 1, g.src, g.dst, w)
