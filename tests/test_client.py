"""Client tests including the full protocol loop over a fixture chain:
attest → node ingest → epoch convergence → fetch proof → verify
(the Anvil-less analog of client/src/lib.rs:165-240, SURVEY.md §4 tier 6)."""

import asyncio
import json
from pathlib import Path

import pytest

from protocol_tpu.client.cli import main as cli_main
from protocol_tpu.client.client import ClientConfig, EigenTrustClient, abi_encode_attest
from protocol_tpu.node.attestation import AttestationData
from protocol_tpu.node.bootstrap import NUM_NEIGHBOURS, read_bootstrap_csv
from protocol_tpu.node.manager import Manager
from protocol_tpu.node.epoch import Epoch

DATA = Path(__file__).resolve().parents[1] / "data"


def make_config(tmp_path, **overrides):
    cfg = ClientConfig.load(DATA / "client-config.json")
    if tmp_path is not None:
        cfg.event_fixture = str(tmp_path / "events.jsonl")
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return cfg


def bootstrap_nodes():
    return read_bootstrap_csv(DATA / "bootstrap-nodes.csv")


class TestClientAttest:
    def test_attestation_is_node_valid(self, tmp_path):
        """The client's signed attestation passes the manager's full
        validation (the should_add_attestation analog)."""
        cfg = make_config(tmp_path)
        client = EigenTrustClient(cfg, bootstrap_nodes())
        att = client.build_attestation()
        assert Manager().add_attestation(att).accepted
        assert att.scores == [300, 100, 100, 300, 200]

    def test_attest_writes_fixture_event(self, tmp_path):
        cfg = make_config(tmp_path)
        client = EigenTrustClient(cfg, bootstrap_nodes())
        event = client.attest()
        lines = Path(cfg.event_fixture).read_text().strip().splitlines()
        assert len(lines) == 1
        decoded = AttestationData.from_bytes(event.val, NUM_NEIGHBOURS)
        att = decoded.to_attestation(NUM_NEIGHBOURS)
        assert att.pk == client.build_attestation().pk

    def test_full_protocol_loop(self, tmp_path):
        """attest → node ingests fixture → epoch proof → /score fetch →
        client-side verification."""
        from protocol_tpu.node.config import ProtocolConfig
        from protocol_tpu.node.server import Node

        cfg = make_config(tmp_path)
        client = EigenTrustClient(cfg, bootstrap_nodes())
        client.attest()

        async def scenario():
            node_cfg = ProtocolConfig(
                epoch_interval=3600,
                endpoint=((127, 0, 0, 1), 0),
                event_fixture=cfg.event_fixture,
                prover="commitment",
            )
            node = Node.from_config(node_cfg)
            await node.start()
            # start() pre-fills uniform initial attestations; wait until
            # the fixture stream has replaced Alice's row (polling is
            # 0.5s; a fixed sleep would be timing-flaky).
            alice_hash = client.build_attestation().pk.hash()
            for _ in range(100):
                att = node.manager.attestations.get(alice_hash)
                if att is not None and att.scores == [300, 100, 100, 300, 200]:
                    break
                await asyncio.sleep(0.1)
            else:
                raise AssertionError("fixture event was not ingested")
            node.manager.calculate_proofs(Epoch(0))
            port = node._server.sockets[0].getsockname()[1]
            cfg.server_url = f"http://127.0.0.1:{port}"
            loop = asyncio.get_running_loop()
            proof_raw = await loop.run_in_executor(None, client.fetch_proof)
            await node.stop()
            # Alice's attested row must be in the proof witness.
            payload = json.loads(proof_raw.proof[32:].decode())
            return proof_raw, payload

        proof_raw, payload = asyncio.run(scenario())
        assert client.verify(proof_raw)
        assert [300, 100, 100, 300, 200] in payload["ops"]


class TestAbiEncoding:
    def test_attest_calldata_layout(self):
        data = abi_encode_attest("0x" + "11" * 20, b"\x22" * 32, b"\xab\xcd")
        # head: array offset, length 1, element offset
        assert data[:32] == (0x20).to_bytes(32, "big")
        assert data[32:64] == (1).to_bytes(32, "big")
        assert data[96:128].endswith(b"\x11" * 20)  # about address
        assert data[128:160] == b"\x22" * 32  # key
        assert data[192:224] == (2).to_bytes(32, "big")  # bytes length
        assert data[224:226] == b"\xab\xcd"


class TestCli:
    def _data_dir(self, tmp_path):
        d = tmp_path / "data"
        d.mkdir()
        for name in ("client-config.json", "bootstrap-nodes.csv"):
            (d / name).write_text((DATA / name).read_text())
        return d

    def test_show(self, tmp_path, capsys):
        cli_main(["--data-dir", str(self._data_dir(tmp_path)), "show"])
        out = capsys.readouterr().out
        assert json.loads(out)["ops"] == [300, 100, 100, 300, 200]

    def test_update_score(self, tmp_path, capsys):
        d = self._data_dir(tmp_path)
        cli_main(["--data-dir", str(d), "update", "score", "Bob 777"])
        cfg = ClientConfig.load(d / "client-config.json")
        assert cfg.ops[1] == 777

    def test_update_score_rejects_negative(self, tmp_path):
        # u128 parse semantics (client/src/main.rs:167-170).
        with pytest.raises(SystemExit, match="Failed to parse score"):
            cli_main(["--data-dir", str(self._data_dir(tmp_path)), "update", "score", "Bob -5"])

    def test_update_score_unknown_name(self, tmp_path):
        with pytest.raises(SystemExit, match="Invalid neighbour name"):
            cli_main(["--data-dir", str(self._data_dir(tmp_path)), "update", "score", "Mallory 1"])

    def test_update_bad_field(self, tmp_path):
        with pytest.raises(SystemExit, match="Invalid config field"):
            cli_main(["--data-dir", str(self._data_dir(tmp_path)), "update", "nope", "x"])

    def test_update_missing_value(self, tmp_path):
        with pytest.raises(SystemExit, match="provide the update data"):
            cli_main(["--data-dir", str(self._data_dir(tmp_path)), "update", "score"])

    def test_update_address_validated(self, tmp_path):
        d = self._data_dir(tmp_path)
        with pytest.raises(SystemExit, match="Failed to parse address"):
            cli_main(["--data-dir", str(d), "update", "as_address", "nothex"])
        cli_main(["--data-dir", str(d), "update", "as_address", "0x" + "ab" * 20])
        assert ClientConfig.load(d / "client-config.json").as_address == "0x" + "ab" * 20

    def test_update_node_url_validated(self, tmp_path):
        with pytest.raises(SystemExit, match="Failed to parse node url"):
            cli_main(["--data-dir", str(self._data_dir(tmp_path)), "update", "node_url", "ftp://x"])

    def test_update_sk_validated(self, tmp_path):
        with pytest.raises(SystemExit, match="expected 2 bs58 values"):
            cli_main(["--data-dir", str(self._data_dir(tmp_path)), "update", "sk", "only-one"])

    def test_unknown_identity_rejected_for_signing_commands(self, tmp_path):
        d = self._data_dir(tmp_path)
        cfg = ClientConfig.load(d / "client-config.json")
        cfg.secret_key = ("1111", "2222")
        cfg.save(d / "client-config.json")
        with pytest.raises(SystemExit, match="not in bootstrap-nodes.csv"):
            cli_main(["--data-dir", str(d), "attest"])
        # Config-repair commands still work with a bad identity...
        cli_main(["--data-dir", str(d), "show"])
        # ...including update sk back to a bootstrap identity.
        nodes = read_bootstrap_csv(d / "bootstrap-nodes.csv")
        cli_main(["--data-dir", str(d), "update", "sk", f"{nodes[1].sk0},{nodes[1].sk1}"])
        assert ClientConfig.load(d / "client-config.json").secret_key == (
            nodes[1].sk0,
            nodes[1].sk1,
        )

    def test_update_sk_rejects_non_bootstrap_key(self, tmp_path):
        d = self._data_dir(tmp_path)
        with pytest.raises(SystemExit, match="not one of the bootstrap identities"):
            cli_main(["--data-dir", str(d), "update", "sk", "1111,2222"])
