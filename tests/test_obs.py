"""Observability subsystem (ISSUE 4): trace spans, metrics registry,
exporters, the /metrics and /trace/<epoch> endpoints, and the
zero-hot-path-sync contracts (residual carry adds no gathers/callbacks;
instrumented backends are bit-identical to uninstrumented ones under a
transfer guard)."""

import json
import logging
import re
import threading

import numpy as np
import pytest

import jax

from protocol_tpu.models.graphs import erdos_renyi
from protocol_tpu.node.epoch import Epoch
from protocol_tpu.node.manager import Manager, ManagerConfig
from protocol_tpu.node.server import handle_request
from protocol_tpu.obs import METRICS, TRACER, metrics_json, prometheus_text
from protocol_tpu.obs import metrics as obs_metrics
from protocol_tpu.obs.metrics import MetricsRegistry
from protocol_tpu.obs.trace import SpanContextFilter, Tracer, configure_logging
from protocol_tpu.trust.backend import get_backend


# ---------------------------------------------------------------------------
# Trace spans
# ---------------------------------------------------------------------------


class TestTracer:
    def test_span_nesting_and_serialization(self):
        tracer = Tracer()
        with tracer.epoch(3):
            with tracer.span("prove"):
                with tracer.span("snark"):
                    pass
            with tracer.span("converge", backend="tpu-csr"):
                pass
        tree = tracer.get_trace(3)
        assert tree["name"] == "epoch_tick"
        assert tree["attrs"]["epoch"] == 3
        assert [c["name"] for c in tree["children"]] == ["prove", "converge"]
        (snark,) = tree["children"][0]["children"]
        assert snark["name"] == "snark"
        assert snark["duration_s"] >= 0
        assert tree["children"][1]["attrs"]["backend"] == "tpu-csr"

    def test_trace_survives_tick_exception(self):
        tracer = Tracer()
        with pytest.raises(RuntimeError):
            with tracer.epoch(9):
                with tracer.span("prove"):
                    raise RuntimeError("boom")
        tree = tracer.get_trace(9)
        assert tree is not None and tree["attrs"]["error"] is True

    def test_epoch_ring_evicts_oldest(self):
        tracer = Tracer(keep_epochs=2)
        for e in (1, 2, 3):
            with tracer.epoch(e):
                pass
        assert tracer.epochs() == [2, 3]
        assert tracer.get_trace(1) is None
        assert tracer.latest_epoch() == 3

    def test_threads_have_independent_span_stacks(self):
        tracer = Tracer()
        seen = {}

        def tick(epoch):
            with tracer.epoch(epoch):
                with tracer.span(f"work_{epoch}"):
                    pass
            seen[epoch] = tracer.get_trace(epoch)

        threads = [threading.Thread(target=tick, args=(e,)) for e in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for e in range(4):
            assert [c["name"] for c in seen[e]["children"]] == [f"work_{e}"]

    def test_span_close_hook_feeds_phase_histogram(self):
        before = obs_metrics.PHASE_SECONDS.count(phase="unit_phase")
        with TRACER.span("unit_phase"):
            pass
        assert obs_metrics.PHASE_SECONDS.count(phase="unit_phase") == before + 1


# ---------------------------------------------------------------------------
# Metrics registry
# ---------------------------------------------------------------------------


class TestMetricsRegistry:
    def test_counter_gauge_histogram(self):
        reg = MetricsRegistry()
        c = reg.counter("c_total", "help", labelnames=("reason",))
        c.inc(reason="a")
        c.inc(2, reason="a")
        c.inc(reason="b")
        assert c.value(reason="a") == 3 and c.value(reason="b") == 1
        g = reg.gauge("g")
        g.set(7.5)
        assert g.value() == 7.5
        h = reg.histogram("h", buckets=(1.0, 10.0))
        for v in (0.5, 5.0, 50.0):
            h.observe(v)
        snap = h.snapshot()[()]
        assert snap["count"] == 3 and snap["sum"] == 55.5
        # cumulative buckets: le=1 -> 1, le=10 -> 2, le=+Inf -> 3
        assert snap["buckets"] == [1, 2, 3]

    def test_counters_are_monotonic(self):
        reg = MetricsRegistry()
        c = reg.counter("c_total")
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_label_mismatch_rejected(self):
        reg = MetricsRegistry()
        c = reg.counter("c_total", labelnames=("reason",))
        with pytest.raises(ValueError):
            c.inc()
        with pytest.raises(ValueError):
            c.inc(reason="x", extra="y")

    def test_registration_idempotent_but_kind_pinned(self):
        reg = MetricsRegistry()
        c1 = reg.counter("x_total")
        assert reg.counter("x_total") is c1
        with pytest.raises(ValueError):
            reg.gauge("x_total")

    def test_thread_safety_under_concurrent_scrape(self):
        """The ISSUE 4 concurrency contract: writer threads (epoch tick
        / ingest) and scrape threads (HTTP GET /metrics) race on one
        registry; totals stay exact and rendering never throws."""
        reg = MetricsRegistry()
        c = reg.counter("writes_total", labelnames=("worker",))
        h = reg.histogram("vals", buckets=(0.25, 0.5, 0.75))
        n_writers, per_writer = 8, 2000
        errors = []
        stop = threading.Event()

        def writer(k):
            try:
                for i in range(per_writer):
                    c.inc(worker=str(k))
                    h.observe((i % 100) / 100.0)
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

        def scraper():
            try:
                while not stop.is_set():
                    from protocol_tpu.obs.export import prometheus_text

                    text = prometheus_text(reg)
                    assert "writes_total" in text
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

        scrapers = [threading.Thread(target=scraper) for _ in range(2)]
        writers = [threading.Thread(target=writer, args=(k,)) for k in range(n_writers)]
        for t in scrapers + writers:
            t.start()
        for t in writers:
            t.join()
        stop.set()
        for t in scrapers:
            t.join()
        assert errors == []
        for k in range(n_writers):
            assert c.value(worker=str(k)) == per_writer
        assert h.count() == n_writers * per_writer


# ---------------------------------------------------------------------------
# Prometheus exposition format
# ---------------------------------------------------------------------------

_LABEL_RE = r"[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\""  # value may escape \" \\ \n
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"  # metric name
    rf"(\{{{_LABEL_RE}(,{_LABEL_RE})*\}})?"
    r" (-?[0-9.]+(e[+-]?[0-9]+)?|\+Inf|-Inf|NaN)$"
)


def _parse_prometheus(text: str) -> dict:
    """Minimal exposition-format parser: every non-comment line must be
    a well-formed sample; returns {sample_name_with_labels: value}."""
    samples = {}
    for line in text.strip().splitlines():
        if line.startswith("#"):
            assert re.match(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* ", line), line
            continue
        assert _SAMPLE_RE.match(line), f"unparseable sample line: {line!r}"
        name_labels, value = line.rsplit(" ", 1)
        samples[name_labels] = float(value)
    return samples


class TestPrometheusExport:
    def test_text_format_parses(self):
        reg = MetricsRegistry()
        reg.counter("a_total", "with help").inc(3)
        reg.counter("b_total", "labelled", labelnames=("reason",)).inc(
            reason='we"ird\nvalue'
        )
        reg.gauge("g", "a gauge").set(1.25)
        reg.histogram("h", "a histogram", buckets=(0.1, 1.0)).observe(0.3)
        from protocol_tpu.obs.export import prometheus_text

        samples = _parse_prometheus(prometheus_text(reg))
        assert samples["a_total"] == 3
        assert samples["g"] == 1.25
        assert samples['h_bucket{le="0.1"}'] == 0
        assert samples['h_bucket{le="1"}'] == 1
        assert samples['h_bucket{le="+Inf"}'] == 1
        assert samples["h_count"] == 1

    def test_histogram_buckets_cumulative(self):
        reg = MetricsRegistry()
        h = reg.histogram("h", buckets=(1.0, 2.0, 4.0))
        for v in (0.5, 1.5, 3.0, 100.0):
            h.observe(v)
        from protocol_tpu.obs.export import prometheus_text

        samples = _parse_prometheus(prometheus_text(reg))
        counts = [
            samples[f'h_bucket{{le="{b}"}}'] for b in ("1", "2", "4", "+Inf")
        ]
        assert counts == sorted(counts)  # monotone non-decreasing
        assert counts[-1] == samples["h_count"] == 4

    def test_global_registry_renders(self):
        _parse_prometheus(prometheus_text())
        assert "eigentrust_epochs_total" in metrics_json()


# ---------------------------------------------------------------------------
# Node endpoints
# ---------------------------------------------------------------------------


def _ticked_manager(backend="tpu-sparse"):
    """A manager with one full epoch of work driven under the epoch
    trace root, exactly as Node._epoch_tick does."""
    m = Manager(ManagerConfig(prover="commitment", backend=backend))
    m.generate_initial_attestations()
    with TRACER.epoch(4):
        with TRACER.span("prove"):
            m.calculate_proofs(Epoch(4))
        m.converge_epoch(Epoch(4), alpha=0.1)
    return m


class TestEndpoints:
    def test_metrics_endpoint_prometheus_parses(self):
        METRICS.reset()
        m = _ticked_manager()
        status, body = handle_request("GET", "/metrics", m)
        assert status == 200
        samples = _parse_prometheus(body)
        assert samples["eigentrust_graph_peers"] == 5
        assert samples["eigentrust_convergence_iterations"] >= 1

    def test_residual_histogram_length_equals_iterations(self):
        METRICS.reset()
        m = _ticked_manager()
        result = m.cached_results[Epoch(4)]
        status, body = handle_request("GET", "/metrics", m)
        samples = _parse_prometheus(body)
        assert samples["eigentrust_convergence_residual_count"] == result.iterations
        assert len(result.residuals) == result.iterations

    def test_trace_endpoint_span_tree_nesting(self):
        m = _ticked_manager()
        status, body = handle_request("GET", "/trace/4", m)
        assert status == 200
        tree = json.loads(body)
        assert tree["name"] == "epoch_tick"
        names = [c["name"] for c in tree["children"]]
        assert names[0] == "prove"
        assert "build_graph" in names and "converge" in names
        prove_children = [c["name"] for c in tree["children"][0]["children"]]
        assert prove_children == ["power_iterate", "circuit_check", "snark"]

    def test_trace_latest_and_errors(self):
        m = _ticked_manager()
        status, body = handle_request("GET", "/trace/latest", m)
        assert status == 200 and json.loads(body)["name"] == "epoch_tick"
        status, _ = handle_request("GET", "/trace/notanint", m)
        assert status == 400
        status, body = handle_request("GET", "/trace/123456789", m)
        assert status == 404 and "no trace" in json.loads(body)["error"]

    def test_status_lists_traced_epochs(self):
        m = _ticked_manager()
        status, body = handle_request("GET", "/status", m)
        assert 4 in json.loads(body)["traced_epochs"]

    def test_metrics_content_type_over_socket(self):
        """Socket-level: /metrics must be served text/plain (Prometheus
        scrapers reject JSON content types)."""
        import asyncio

        from protocol_tpu.node.config import ProtocolConfig
        from protocol_tpu.node.server import Node

        async def scenario():
            cfg = ProtocolConfig(
                epoch_interval=3600, endpoint=((127, 0, 0, 1), 0),
                prover="commitment",
            )
            node = Node.from_config(cfg)
            await node.start()
            port = node._server.sockets[0].getsockname()[1]
            reader, writer = await asyncio.open_connection("127.0.0.1", port)
            writer.write(b"GET /metrics HTTP/1.1\r\nhost: x\r\n\r\n")
            await writer.drain()
            response = (await reader.read()).decode()
            writer.close()
            await node.stop()
            return response

        response = asyncio.run(scenario())
        head, _, body = response.partition("\r\n\r\n")
        assert "200 OK" in head
        assert "content-type: text/plain; version=0.0.4" in head
        _parse_prometheus(body)

    def test_bulk_ingest_rejection_reasons_counted(self):
        from protocol_tpu.crypto.eddsa import SecretKey, sign
        from tests.test_node import make_attestation

        METRICS.reset()
        good = make_attestation(0)
        bad_sig = make_attestation(1)
        bad_sig.sig = sign(SecretKey.random(), SecretKey.random().public(), 1)
        bad_sum = make_attestation(2, scores=[1, 0, 0, 0, 0])
        m = Manager()
        results = m.add_attestations_bulk([good, bad_sig, bad_sum])
        assert [r.accepted for r in results] == [True, False, False]
        assert obs_metrics.ATTESTATIONS_ACCEPTED.value() == 1
        assert obs_metrics.ATTESTATIONS_REJECTED.value(reason="bad-signature") == 1
        assert (
            obs_metrics.ATTESTATIONS_REJECTED.value(reason="non-conserving-scores")
            == 1
        )
        _, body = handle_request("GET", "/metrics", m)
        assert (
            'eigentrust_attestations_rejected_total{reason="bad-signature"} 1'
            in body
        )

    def test_checkpoint_counters(self, tmp_path):
        from protocol_tpu.node.checkpoint import CheckpointStore

        METRICS.reset()
        store = CheckpointStore(tmp_path)
        g = erdos_renyi(30, seed=2)
        store.save(Epoch(1), g)
        store.load_latest()
        assert obs_metrics.CHECKPOINT_SAVES.value() == 1
        assert obs_metrics.CHECKPOINT_RESTORES.value() == 1


# ---------------------------------------------------------------------------
# Hot-path contracts
# ---------------------------------------------------------------------------


class TestHotPathContracts:
    """The residual carry must not change the kernel access pattern,
    and instrumented convergence must be bit-identical."""

    BACKENDS = ("tpu-sparse", "tpu-csr", "tpu-windowed", "tpu-sharded")

    @pytest.mark.parametrize("name", BACKENDS)
    def test_instrumented_bit_identical_under_transfer_guard(self, name):
        g = erdos_renyi(150, avg_degree=5.0, seed=3)
        backend_on = get_backend(name)
        backend_off = get_backend(name)
        with jax.transfer_guard("disallow"):
            on = backend_on.converge(g, alpha=0.1, tol=1e-9, max_iter=30)
            off = backend_off.converge(
                g, alpha=0.1, tol=1e-9, max_iter=30, record_residuals=False
            )
        assert np.array_equal(on.scores, off.scores)  # bit-identical
        assert off.residuals is None
        assert len(on.residuals) == on.iterations == off.iterations
        # The trajectory's last entry is the reported final residual.
        np.testing.assert_allclose(on.residuals[-1], on.residual, rtol=1e-6)

    def test_residual_carry_adds_no_gathers_or_callbacks(self):
        """ISSUE 4 satellite: KERNEL_INVARIANTS budgets are unchanged
        with the residual-carry step — the full converge jaxpr has the
        same gather count, zero scatters, and zero callbacks with the
        history carry enabled."""
        import jax.numpy as jnp

        from protocol_tpu.analysis.jaxpr_walk import (
            CALLBACK_PRIMITIVES,
            SCATTER_PRIMITIVES,
            collect_primitives,
        )
        from protocol_tpu.ops.sparse import converge_csr
        from protocol_tpu.trust.graph import TrustGraph

        g = erdos_renyi(100, avg_degree=4.0, seed=5).drop_self_edges()
        w, dangling = g.row_normalized()
        gs = TrustGraph(g.n, g.src, g.dst, w, g.pre_trusted).sorted_by_dst()
        p = g.pre_trust_vector()
        args = (
            jnp.asarray(gs.src),
            jnp.asarray(gs.row_ptr_by_dst()),
            jnp.asarray(gs.weight),
            jnp.asarray(p),
            jnp.asarray(p),
            jnp.asarray(dangling.astype(np.float32)),
        )

        def counts(record):
            jaxpr = jax.make_jaxpr(
                lambda *a: converge_csr(
                    a[0], a[1], a[2], a[3], a[4], a[5],
                    alpha=0.1, tol=1e-6, max_iter=8,
                    record_residuals=record,
                )
            )(*args)
            return (
                len(collect_primitives(jaxpr, {"gather"})),
                len(collect_primitives(jaxpr, SCATTER_PRIMITIVES)),
                len(collect_primitives(jaxpr, CALLBACK_PRIMITIVES)),
            )

        gathers_off, scatters_off, callbacks_off = counts(False)
        gathers_on, scatters_on, callbacks_on = counts(True)
        assert gathers_on == gathers_off
        assert scatters_on == scatters_off == 0
        assert callbacks_on == callbacks_off == 0

    def test_trace_store_read_does_not_touch_device(self):
        """Serving /trace is a host-side dict copy: no jax arrays are
        reachable from the serialized tree."""
        m = _ticked_manager()
        tree = TRACER.get_trace(4)

        def walk(node):
            assert isinstance(node["name"], str)
            for k, v in node.get("attrs", {}).items():
                assert isinstance(v, (str, int, float, bool, type(None))), (k, v)
            for child in node["children"]:
                walk(child)

        walk(tree)


# ---------------------------------------------------------------------------
# Logging integration
# ---------------------------------------------------------------------------


class TestConfigureLogging:
    def _fresh_root(self):
        root = logging.getLogger()
        saved = (root.handlers[:], root.level)
        root.handlers[:] = []
        return root, saved

    def _restore(self, root, saved):
        root.handlers[:] = saved[0]
        root.setLevel(saved[1])

    def test_installs_handler_on_pristine_root(self):
        root, saved = self._fresh_root()
        try:
            configure_logging()
            assert len(root.handlers) == 1
            handler = root.handlers[0]
            assert any(isinstance(f, SpanContextFilter) for f in handler.filters)
            # The format resolves: a record through the handler must not
            # raise on the %(epoch)s / %(span)s columns.
            record = logging.LogRecord(
                "x", logging.INFO, __file__, 1, "hello", (), None
            )
            for f in handler.filters:
                f.filter(record)
            assert "epoch=-" in handler.format(record)
        finally:
            self._restore(root, saved)

    def test_respects_existing_root_handler(self):
        root, saved = self._fresh_root()
        try:
            mine = logging.StreamHandler()
            fmt = logging.Formatter("%(message)s")
            mine.setFormatter(fmt)
            root.addHandler(mine)
            configure_logging()
            configure_logging()  # idempotent
            assert root.handlers == [mine]  # no second handler
            assert mine.formatter is fmt  # formatter untouched
            # ...but the span filter was attached exactly once.
            filters = [f for f in mine.filters if isinstance(f, SpanContextFilter)]
            assert len(filters) == 1
        finally:
            self._restore(root, saved)

    def test_records_carry_span_context(self):
        record_holder = {}

        class Capture(logging.Handler):
            def emit(self, record):
                record_holder["r"] = record

        logger = logging.getLogger("protocol_tpu.test_obs")
        handler = Capture()
        handler.addFilter(SpanContextFilter())
        logger.addHandler(handler)
        try:
            with TRACER.epoch(11):
                with TRACER.span("prove"):
                    logger.warning("inside")
            r = record_holder["r"]
            assert r.epoch == 11 and r.span == "prove" and r.span_id > 0
        finally:
            logger.removeHandler(handler)


# ---------------------------------------------------------------------------
# Bench parity
# ---------------------------------------------------------------------------


class TestBenchPhases:
    def test_headline_embeds_span_phase_timings(self):
        """BENCH_*.json parity (ISSUE 4 CI satellite): the headline
        entry embeds span-derived phase timings under the same names
        the node's /trace reports."""
        import bench

        entry = bench.headline_entry(
            iters=2, backend="tpu-csr", n_peers=1024, n_edges=4096
        )
        assert entry["phases"].keys() == {"converge"}
        assert entry["phases"]["converge"] >= 0
        windowed = bench.headline_entry(
            iters=2, backend="tpu-windowed", n_peers=2048, n_edges=8192
        )
        assert set(windowed["phases"]) == {"plan", "converge"}


class TestProfileSession:
    def test_noop_without_dir(self):
        from protocol_tpu.obs import profile_session

        with profile_session(None):
            pass

    def test_writes_profile_artifacts(self, tmp_path):
        from protocol_tpu.obs import profile_session

        import jax.numpy as jnp

        with profile_session(str(tmp_path / "prof")):
            jnp.asarray(np.ones(8, np.float32)).sum().block_until_ready()
        assert any((tmp_path / "prof").rglob("*")), "no profiler output written"


# ---------------------------------------------------------------------------
# Exposition hardening (ISSUE 6 satellite)
# ---------------------------------------------------------------------------


def _unescape_label(value: str) -> str:
    """Inverse of the exposition-format label escaping."""
    out, i = [], 0
    while i < len(value):
        c = value[i]
        if c == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            out.append({"n": "\n", '"': '"', "\\": "\\"}.get(nxt, "\\" + nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


class TestExpositionHardening:
    def test_hostile_reason_label_round_trips(self):
        """A reason label containing every escapable character must
        render as a parseable sample whose unescaped value recovers
        the original string byte-for-byte."""
        hostile = 'quote:" backslash:\\ newline:\nend'
        reg = MetricsRegistry()
        reg.counter("rej_total", "rejections", labelnames=("reason",)).inc(
            reason=hostile
        )
        from protocol_tpu.obs.export import prometheus_text

        text = prometheus_text(reg)
        samples = _parse_prometheus(text)  # every line must stay well-formed
        (label_line,) = [k for k in samples if k.startswith("rej_total{")]
        m = re.match(r'rej_total\{reason="(.*)"\}', label_line)
        assert m is not None
        assert _unescape_label(m.group(1)) == hostile
        assert samples[label_line] == 1

    def test_help_text_escaped(self):
        reg = MetricsRegistry()
        reg.gauge("g", "first line\nsecond \\ line").set(1)
        from protocol_tpu.obs.export import prometheus_text

        text = prometheus_text(reg)
        (help_line,) = [
            line for line in text.splitlines() if line.startswith("# HELP g ")
        ]
        assert help_line == "# HELP g first line\\nsecond \\\\ line"
        _parse_prometheus(text)

    def test_content_type_version(self):
        from protocol_tpu.obs.export import PROMETHEUS_CONTENT_TYPE

        assert PROMETHEUS_CONTENT_TYPE.startswith("text/plain; version=0.0.4")


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_ring_is_bounded_and_tail_ordered(self):
        from protocol_tpu.obs.journal import FlightRecorder

        rec = FlightRecorder(max_events=64)
        for i in range(500):
            rec.record("tick", i=i)
        assert len(rec) == 64
        tail = rec.tail(10)
        assert [e["i"] for e in tail] == list(range(490, 500))
        assert all(e["kind"] == "tick" for e in tail)
        seqs = [e["seq"] for e in rec.tail()]
        assert seqs == sorted(seqs)

    def test_batched_writer_lands_events_on_disk(self, tmp_path):
        from protocol_tpu.obs.journal import FlightRecorder

        path = tmp_path / "j.jsonl"
        rec = FlightRecorder(max_events=128).configure(path)
        for i in range(20):
            rec.record("span", name=f"s{i}", duration_s=0.1)
        rec.close()
        lines = [json.loads(x) for x in path.read_text().splitlines()]
        assert [e["name"] for e in lines] == [f"s{i}" for i in range(20)]

    def test_rotation_keeps_recent_window(self, tmp_path):
        from protocol_tpu.obs.journal import FlightRecorder

        path = tmp_path / "j.jsonl"
        rec = FlightRecorder(max_events=50, max_bytes=2000).configure(path)
        for i in range(400):
            rec.record("tick", i=i)
        rec.close()
        assert path.stat().st_size < 10_000  # bounded, not 400 lines' worth
        lines = [json.loads(x) for x in path.read_text().splitlines()]
        assert lines  # the recent window survived
        assert lines[-1]["i"] == 399

    def test_dump_writes_ring_plus_marker(self, tmp_path):
        from protocol_tpu.obs.journal import FlightRecorder

        rec = FlightRecorder(max_events=32)
        for i in range(5):
            rec.record("tick", i=i)
        out = rec.dump(tmp_path / "post" / "mortem.jsonl", reason="test")
        lines = [json.loads(x) for x in out.read_text().splitlines()]
        assert len(lines) == 6
        assert lines[-1]["kind"] == "journal-dump"
        assert lines[-1]["reason"] == "test" and lines[-1]["events"] == 5

    def test_record_never_raises_on_unserializable(self, tmp_path):
        from protocol_tpu.obs.journal import FlightRecorder

        rec = FlightRecorder().configure(tmp_path / "j.jsonl")
        rec.record("weird", obj=object())  # json falls back to str()
        rec.close()
        line = json.loads((tmp_path / "j.jsonl").read_text().splitlines()[0])
        assert "object object" in line["obj"]

    def test_span_close_feeds_global_journal(self):
        from protocol_tpu.obs import JOURNAL

        before = len(JOURNAL.tail())
        with TRACER.span("journal_unit_phase"):
            pass
        events = JOURNAL.tail()
        assert len(events) > before
        assert any(
            e["kind"] == "span" and e.get("name") == "journal_unit_phase"
            for e in events
        )


# ---------------------------------------------------------------------------
# Watchers: recompiles, memory watermarks, drift
# ---------------------------------------------------------------------------


class TestRecompileTrackerUnit:
    def test_snapshot_observe_counts_misses(self):
        import jax.numpy as jnp

        from protocol_tpu.obs.watchers import RecompileTracker

        tracker = RecompileTracker()

        @jax.jit
        def f(x):
            return x * 2

        tracker.register("f", f)
        f(jnp.ones(3))
        snap = tracker.snapshot()
        assert tracker.observe(snap) == {}  # no new shapes
        f(jnp.ones(7))  # fresh shape -> one compile
        assert tracker.observe(snap) == {"f": 1}

    def test_non_jit_objects_are_ignored(self):
        from protocol_tpu.obs.watchers import RecompileTracker

        tracker = RecompileTracker()
        tracker.register("not_jit", lambda x: x)
        assert tracker.registered() == []


class TestMemoryWatermarks:
    def test_disables_itself_without_allocator_stats(self):
        from protocol_tpu.obs.watchers import MemoryWatermarkWatcher

        w = MemoryWatermarkWatcher()
        with TRACER.span("mem_probe") as sp:
            w.on_open(sp)
        # CPU devices report no memory_stats: the watcher must neither
        # leave snapshot attrs behind nor claim a delta.
        if jax.local_devices()[0].memory_stats() is None:
            assert w._enabled is False
            assert "dev_mem_delta_bytes" not in sp.attrs
            assert "_mem_open_bytes" not in sp.attrs

    def test_records_delta_with_fake_stats(self):
        from protocol_tpu.obs.watchers import MemoryWatermarkWatcher

        class Fake(MemoryWatermarkWatcher):
            def __init__(self):
                super().__init__()
                self.now = 1000

            def _bytes_in_use(self):
                return (self.now, self.now + 7)

        w = Fake()
        with TRACER.span("mem_fake") as sp:
            w.on_open(sp)
            w.now = 1500
            w.on_close(sp)
        assert sp.attrs["dev_mem_delta_bytes"] == 500
        assert sp.attrs["dev_mem_peak_bytes"] == 1507
        assert "_mem_open_bytes" not in sp.attrs
        assert obs_metrics.DEVICE_MEMORY_DELTA.value(phase="mem_fake") == 500


class TestScoreDriftMonitor:
    def test_first_epoch_has_no_drift_then_l1_linf(self):
        from protocol_tpu.obs.watchers import ScoreDriftMonitor

        mon = ScoreDriftMonitor(top_k=2)
        first = mon.observe(1, [10, 11, 12], [0.5, 0.3, 0.2])
        assert first["l1"] is None and first["top_movers"] == []
        second = mon.observe(2, [10, 11, 13], [0.4, 0.35, 0.25])
        assert abs(second["l1"] - 0.15) < 1e-12
        assert abs(second["linf"] - 0.1) < 1e-12
        assert second["joined"] == 1 and second["departed"] == 1
        movers = second["top_movers"]
        assert movers[0]["peer_hash"] == hex(10)
        assert abs(movers[0]["delta"] + 0.1) < 1e-12
        assert mon.last()["epoch"] == 2

    def test_residual_stall_detection(self):
        from protocol_tpu.obs.watchers import ScoreDriftMonitor

        mon = ScoreDriftMonitor()
        before = obs_metrics.RESIDUAL_STALLS.value()
        ok = mon.observe(1, [1], [1.0], residuals=[0.5, 0.4, 0.41, 0.2])
        assert ok["residual_increases"] == 1 and not ok["stalled"]
        bad = mon.observe(2, [1], [1.0], residuals=[0.5, 0.6, 0.4, 0.55])
        assert bad["residual_increases"] == 2 and bad["stalled"]
        assert obs_metrics.RESIDUAL_STALLS.value() == before + 1


# ---------------------------------------------------------------------------
# New node endpoints: /scores/drift and /debug/flight
# ---------------------------------------------------------------------------


class TestDeepAttributionEndpoints:
    def test_drift_endpoint_after_tick(self):
        from protocol_tpu.obs.watchers import DRIFT

        DRIFT.reset()
        m = _ticked_manager()
        status, body = handle_request("GET", "/scores/drift", m)
        assert status == 200
        drift = json.loads(body)
        assert drift["epoch"] == 4
        assert drift["peers"] == 5
        assert "stalled" in drift and "top_movers" in drift

    def test_flight_endpoint_serves_jsonl_tail(self):
        m = _ticked_manager()
        status, body = handle_request("GET", "/debug/flight", m)
        assert status == 200
        events = [json.loads(line) for line in body.splitlines() if line]
        assert events, "flight recorder empty after a full tick"
        kinds = {e["kind"] for e in events}
        assert "span" in kinds
        status, limited = handle_request("GET", "/debug/flight?n=3", m)
        assert status == 200
        assert len(limited.splitlines()) == 3
        status, _ = handle_request("GET", "/debug/flight?n=bogus", m)
        assert status == 400

    def test_flight_tail_replays_ingest_rejection(self):
        from protocol_tpu.crypto.eddsa import SecretKey, sign
        from protocol_tpu.obs import JOURNAL
        from tests.test_node import make_attestation

        bad_sig = make_attestation(1)
        bad_sig.sig = sign(SecretKey.random(), SecretKey.random().public(), 1)
        m = Manager()
        m.add_attestations_bulk([bad_sig])
        rejects = [
            e for e in JOURNAL.tail() if e["kind"] == "ingest-reject"
        ]
        assert rejects and rejects[-1]["reason"] == "bad-signature"


# ---------------------------------------------------------------------------
# Prover-internal spans (deep attribution)
# ---------------------------------------------------------------------------


class TestProverSubSpans:
    def test_attach_closed_hangs_child_under_current_span(self):
        tracer = Tracer()
        with tracer.epoch(21):
            with tracer.span("snark"):
                sp = tracer.attach_closed("msm", 0.125, calls=7)
                assert sp is not None
        tree = tracer.get_trace(21)
        (snark,) = tree["children"]
        (msm,) = snark["children"]
        assert msm["name"] == "msm"
        assert msm["duration_s"] == 0.125
        assert msm["attrs"]["calls"] == 7
        assert msm["start_offset_s"] >= 0

    def test_attach_closed_without_open_span_is_noop(self):
        tracer = Tracer()
        assert tracer.attach_closed("msm", 1.0) is None

    def test_plonk_prove_attributes_engine_and_stage_time(self):
        """The acceptance shape: snark -> named prover sub-spans with
        call counts, summing to (nearly) the whole snark span."""
        from protocol_tpu.zk import native as zk_native, plonk
        from tests.test_plonk import _mul_add_circuit

        cs = _mul_add_circuit()
        pk = plonk.compile_circuit(cs)
        with TRACER.epoch(31):
            with TRACER.span("prove"):
                with TRACER.span("snark"):
                    proof = plonk.prove(pk, cs, [17], seed=b"t")
        assert plonk.verify(pk.vk, [17], proof)
        tree = TRACER.get_trace(31)
        snark = tree["children"][0]["children"][0]
        by_name = {c["name"]: c for c in snark["children"]}
        expected = {"witness_gen", "commit", "quotient", "open", "transcript"}
        if zk_native.available():
            expected |= {"msm", "ntt"}
        assert expected <= set(by_name), sorted(by_name)
        assert len(by_name) >= 4
        for child in by_name.values():
            assert child["duration_s"] >= 0
            assert child["attrs"]["calls"] >= 1
        covered = sum(c["duration_s"] for c in snark["children"])
        assert covered <= snark["duration_s"] * 1.05  # disjoint, no double count
        assert covered >= snark["duration_s"] * 0.5  # attribution is substantial

    def test_native_phase_stats_accumulate_and_reset(self):
        from protocol_tpu.zk import native as zk_native

        if not zk_native.available():
            pytest.skip("native zk runtime unavailable")
        zk_native.reset_phase_stats()
        before = zk_native.phase_stats()
        assert before["msm"] == {"calls": 0, "seconds": 0.0}
        root = pow(5, (zk_native.R - 1) >> 2, zk_native.R)
        zk_native.ntt([1, 2, 3, 4], root)
        after = zk_native.phase_stats()
        assert after["ntt"]["calls"] == 1
        assert after["ntt"]["seconds"] >= 0
        delta = zk_native.phase_delta(before, after)
        assert delta["ntt"]["calls"] == 1 and delta["msm"]["calls"] == 0
