"""Admission-plane tests (ISSUE 7): sharded dedup, rate limits, the
verify worker pool (including crash recovery), the IngestPlane
pipeline, and the node's POST /attestation route with 429 shed.

Adversarial acceptance coverage: replay of an already-accepted
attestation, out-of-order nonces, rate-limit exhaustion followed by
token refill, and worker-crash recovery (the pool respawns; an
in-flight batch is retried or rejected with a distinct reason code —
never silently dropped)."""

import asyncio
import threading
import time
from concurrent.futures.process import BrokenProcessPool

import pytest

from protocol_tpu.crypto import (
    calculate_message_hash,
    group_pks_hash,
    message_hash_batch,
)
from protocol_tpu.crypto.eddsa import sign
from protocol_tpu.ingest import IngestPlane, IngestPlaneConfig, ShardedDedupCache
from protocol_tpu.ingest.plane import SHED_REASON
from protocol_tpu.ingest.ratelimit import AdmissionPolicy, RateLimitConfig
from protocol_tpu.ingest.workers import (
    CRASH_MARKER,
    VerifyCrashed,
    VerifyPool,
    verify_batch,
)
from protocol_tpu.node.attestation import Attestation, AttestationData
from protocol_tpu.node.bootstrap import FIXED_SET, keyset_from_raw
from protocol_tpu.node.manager import IngestResult, Manager, ManagerConfig
from protocol_tpu.obs import metrics as obs_metrics

SKS, PKS = keyset_from_raw(FIXED_SET)
GROUP_HASH = group_pks_hash(PKS)


def make_att(i: int, sender: int = 0, bad_sig: bool = False) -> Attestation:
    """Unique validly-signed attestation #i (scores sum to SCALE)."""
    d = i % 190
    scores = [200 + d, 200 - d, 200, 200, 200]
    _, msgs = calculate_message_hash(PKS, [scores])
    sig = sign(SKS[sender], PKS[sender], msgs[0] + (1 if bad_sig else 0))
    return Attestation(sig=sig, pk=PKS[sender], neighbours=list(PKS), scores=scores)


def work_item(att: Attestation):
    return (
        att.sig.big_r.x,
        att.sig.big_r.y,
        att.sig.s,
        att.pk.point.x,
        att.pk.point.y,
        tuple(att.scores),
    )


def fresh_manager() -> Manager:
    return Manager(ManagerConfig(prover="commitment"))


def open_plane(manager=None, **kw) -> IngestPlane:
    defaults = dict(
        workers=0,
        batch_size=8,
        rate=RateLimitConfig(rate=1e6, burst=1e6),
    )
    defaults.update(kw)
    return IngestPlane(manager or fresh_manager(), IngestPlaneConfig(**defaults))


class TestMessageHashBatch:
    def test_parity_with_reference_path(self):
        rows = [[200] * 5, [100, 300, 200, 150, 250], [999, 1, 0, 0, 0]]
        ph, ref = calculate_message_hash(PKS, rows)
        assert ph == GROUP_HASH
        assert message_hash_batch(GROUP_HASH, rows) == ref

    def test_multi_chunk_rows_match_sponge(self):
        # Rows wider than the sponge width take two absorb rounds.
        from protocol_tpu.crypto import PoseidonSponge, permute

        rows = [[i * 7 + j for j in range(7)] for i in range(3)]
        expected = []
        for row in rows:
            sponge = PoseidonSponge()
            sponge.update(row)
            expected.append(permute([GROUP_HASH, sponge.squeeze(), 0, 0, 0])[0])
        assert message_hash_batch(GROUP_HASH, rows) == expected


class TestShardedDedup:
    def test_duplicate_rejected(self):
        cache = ShardedDedupCache(n_shards=4)
        sender = (1, 2)
        assert cache.admit(sender, b"d1") is None
        assert cache.admit(sender, b"d1") == "duplicate"
        assert cache.admit(sender, b"d2") is None

    def test_nonce_monotonic(self):
        cache = ShardedDedupCache()
        sender = (3, 4)
        assert cache.admit(sender, b"a", nonce=5) is None
        # Out-of-order and replayed nonces both die as stale.
        assert cache.admit(sender, b"b", nonce=5) == "stale-nonce"
        assert cache.admit(sender, b"c", nonce=4) == "stale-nonce"
        assert cache.admit(sender, b"d", nonce=6) is None
        # A nonce-less submission from the same sender still dedups by
        # digest only.
        assert cache.admit(sender, b"e") is None

    def test_epoch_rotation_forgets_after_two_epochs(self):
        cache = ShardedDedupCache()
        sender = (5, 6)
        assert cache.admit(sender, b"x") is None
        cache.rotate_all()
        assert cache.admit(sender, b"x") == "duplicate"  # previous gen
        cache.rotate_all()
        cache.rotate_all()
        assert cache.admit(sender, b"x") is None  # aged out

    def test_overflow_rotates_bounded(self):
        cache = ShardedDedupCache(n_shards=1, hashes_per_shard=8)
        sender = (7, 8)
        for i in range(64):
            cache.admit(sender, bytes([i]))
        assert len(cache) <= 16  # two generations of 8


class TestAdmissionPolicy:
    def test_exhaustion_then_refill(self):
        clock = [0.0]
        policy = AdmissionPolicy(
            RateLimitConfig(rate=10.0, burst=3.0), clock=lambda: clock[0]
        )
        sender = (1, 1)
        assert [policy.check(sender) for _ in range(3)] == [None] * 3
        assert policy.check(sender) == "rate-limited"
        # Refill: 0.2s at 10/s = 2 tokens.
        clock[0] += 0.2
        assert policy.check(sender) is None
        assert policy.check(sender) is None
        assert policy.check(sender) == "rate-limited"

    def test_whitelist_bypass(self):
        sender = (2, 2)
        policy = AdmissionPolicy(
            RateLimitConfig(rate=1.0, burst=1.0, whitelist=frozenset({sender}))
        )
        assert all(policy.check(sender) is None for _ in range(50))

    def test_spam_score_from_rejection_history(self):
        clock = [0.0]
        policy = AdmissionPolicy(
            RateLimitConfig(rate=1e6, burst=1e6, spam_threshold=2.0),
            clock=lambda: clock[0],
        )
        sender = (3, 3)
        assert policy.check(sender) is None
        for _ in range(20):  # downstream verdicts: all garbage
            policy.record_outcome(sender, False)
        assert policy.score(sender) > 2.0
        assert policy.check(sender) == "spam-score"


class TestVerifyPool:
    def test_inline_verdicts(self):
        good, bad = make_att(1), make_att(2, bad_sig=True)
        assert verify_batch(GROUP_HASH, [work_item(good), work_item(bad)]) == [
            True,
            False,
        ]

    def test_pooled_verdicts_and_crash_recovery(self):
        good, bad = make_att(3), make_att(4, bad_sig=True)
        pool = VerifyPool(workers=1)
        try:
            assert pool.verify(GROUP_HASH, [work_item(good), work_item(bad)]) == [
                True,
                False,
            ]
            restarts0 = obs_metrics.INGEST_WORKER_RESTARTS.value()
            # A batch whose worker dies on every attempt must come back
            # as VerifyCrashed (the caller rejects it with a reason
            # code), never hang or vanish.
            with pytest.raises(VerifyCrashed):
                pool.verify(GROUP_HASH, [work_item(good), CRASH_MARKER])
            assert obs_metrics.INGEST_WORKER_RESTARTS.value() > restarts0
            # The pool respawned: the next batch verifies normally.
            assert pool.verify(GROUP_HASH, [work_item(good)]) == [True]
        finally:
            pool.close()

    def test_crash_retry_succeeds_on_respawned_pool(self):
        """First attempt dies (broken executor), the retry lands on the
        rebuilt pool — the in-flight batch is retried, not dropped."""

        class FlakyExecutor:
            def submit(self, fn, *args):
                raise BrokenProcessPool("worker died")

            def shutdown(self, wait=True, cancel_futures=False):
                pass

        pool = VerifyPool(workers=0)  # inline fallback after restart
        pool._executor = FlakyExecutor()
        retried0 = obs_metrics.INGEST_VERIFY_BATCHES.value(outcome="retried")
        try:
            good = make_att(5)
            # Attempt 1 hits the flaky executor; _restart drops it back
            # to inline (workers=0 -> _make unused because executor is
            # cleared only via generation bump) — emulate by patching
            # _make to return None-equivalent inline path.
            pool._make = lambda: None
            assert pool.verify(GROUP_HASH, [work_item(good)]) == [True]
            assert (
                obs_metrics.INGEST_VERIFY_BATCHES.value(outcome="retried")
                > retried0
            )
        finally:
            pool.close()


class TestIngestPlane:
    def test_accept_replay_badsig_and_nonces(self):
        manager = fresh_manager()
        with open_plane(manager) as plane:
            futs = [plane.submit(make_att(i, sender=i % 5)) for i in range(6)]
            replay = plane.submit(make_att(2, sender=2))
            bad = plane.submit(make_att(40, bad_sig=True))
            n5 = plane.submit(make_att(50, sender=1), nonce=5)
            stale = plane.submit(make_att(51, sender=1), nonce=4)
            assert plane.drain(30)
            assert all(f.result().accepted for f in futs)
            assert replay.result().reason == "duplicate"
            assert bad.result().reason == "bad-signature"
            assert n5.result().accepted
            assert stale.result().reason == "stale-nonce"
            # Accepted attestations landed in the manager's cache.
            assert len(manager.attestations) == 5
            stats = plane.stats()
            assert stats["accepted"] == 7 and stats["pending"] == 0

    def test_structural_rejects_never_reach_verify(self):
        manager = fresh_manager()
        with open_plane(manager) as plane:
            calls = []
            original = plane.pool.verify
            plane.pool.verify = lambda *a: (calls.append(1), original(*a))[1]
            att = make_att(1)
            outsider = Attestation(
                sig=att.sig,
                pk=att.pk,
                neighbours=list(reversed(att.neighbours)),
                scores=att.scores,
            )
            fut = plane.submit(outsider)
            assert plane.drain(30)
            assert fut.result().reason == "group-mismatch"
            assert not calls  # rejected before any signature work

    def test_rate_exhaustion_then_refill_through_plane(self):
        clock = [0.0]
        manager = fresh_manager()
        with open_plane(
            manager, rate=RateLimitConfig(rate=10.0, burst=2.0)
        ) as plane:
            plane.policy = AdmissionPolicy(
                RateLimitConfig(rate=10.0, burst=2.0), clock=lambda: clock[0]
            )
            futs = [plane.submit(make_att(i)) for i in range(4)]
            assert plane.drain(30)
            verdicts = [f.result() for f in futs]
            assert sum(v.accepted for v in verdicts) == 2
            assert {v.reason for v in verdicts if not v.accepted} == {
                "rate-limited"
            }
            clock[0] += 1.0  # refill 10 tokens (capped at burst=2)
            futs = [plane.submit(make_att(100 + i)) for i in range(2)]
            assert plane.drain(30)
            assert all(f.result().accepted for f in futs)

    def test_full_queue_sheds_with_reason(self):
        manager = fresh_manager()
        hold = threading.Event()
        with open_plane(
            manager, submit_queue_max=1, batch_queue_max=1, batch_size=1
        ) as plane:
            plane.pool.verify = lambda *a: (hold.wait(10), [True])[1]
            futs = [plane.submit(make_att(i)) for i in range(12)]
            time.sleep(0.2)  # let the pipeline wedge against the hold
            shed = [
                f for f in futs if f.done() and f.result().reason == SHED_REASON
            ]
            assert shed, "bounded intake never shed under a wedged verifier"
            assert plane.shed == len(shed)
            assert (
                obs_metrics.INGEST_SHED.value(stage="submit") >= len(shed)
            )
            hold.set()
            assert plane.drain(30)

    def test_worker_crash_rejects_with_reason_never_drops(self):
        manager = fresh_manager()
        with open_plane(manager) as plane:
            def crashed(*a):
                raise VerifyCrashed("worker died twice")

            plane.pool.verify = crashed
            futs = [plane.submit(make_att(i)) for i in range(3)]
            assert plane.drain(30)
            assert [f.result().reason for f in futs] == ["verify-crashed"] * 3
            assert plane.stats()["pending"] == 0

    def test_epoch_rotation_reopens_dedup(self):
        manager = fresh_manager()
        with open_plane(manager) as plane:
            att = make_att(7)
            assert plane.submit(att).result(10).accepted
            assert plane.submit(att).result(10).reason == "duplicate"
            plane.advance_epoch()
            plane.advance_epoch()
            assert plane.submit(att).result(10).accepted

    def test_close_resolves_pending_futures(self):
        manager = fresh_manager()
        hold = threading.Event()
        plane = open_plane(manager, batch_size=1)
        plane.start()
        plane.pool.verify = lambda *a: (hold.wait(10), [True])[1]
        futs = [plane.submit(make_att(i)) for i in range(4)]
        hold.set()
        plane.close(drain=False)
        for f in futs:
            assert f.result(timeout=10) is not None  # never left hanging


class TestManagerUniformIngestResult:
    def test_single_item_matches_bulk_shape(self):
        m = fresh_manager()
        ok = m.add_attestation(make_att(1))
        assert isinstance(ok, IngestResult) and ok.accepted
        bad = m.add_attestation(make_att(2, bad_sig=True))
        assert (bad.accepted, bad.reason) == (False, "bad-signature")
        att = make_att(3)
        att.neighbours = list(reversed(att.neighbours))
        assert m.add_attestation(att).reason == "group-mismatch"
        # Identical verdict objects from the bulk path.
        assert m.add_attestations_bulk([make_att(4)])[0].accepted

    def test_apply_verified_skips_checks(self):
        m = fresh_manager()
        att = make_att(5)
        assert m.apply_verified(att).accepted
        assert m.attestations[att.pk.hash()] is att


class TestServerIngestRoute:
    @staticmethod
    async def _post(port, body, path="/attestation"):
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(
            f"POST {path} HTTP/1.1\r\nhost: t\r\n"
            f"content-length: {len(body)}\r\n\r\n".encode() + body
        )
        await writer.drain()
        response = (await reader.read()).decode()
        writer.close()
        head, _, payload = response.partition("\r\n\r\n")
        return int(head.split()[1]), payload

    def test_post_accept_replay_and_shed(self):
        from protocol_tpu.node.config import ProtocolConfig
        from protocol_tpu.node.server import Node

        async def scenario():
            cfg = ProtocolConfig(
                epoch_interval=3600,
                endpoint=((127, 0, 0, 1), 0),
                prover="commitment",
            )
            node = Node.from_config(cfg)
            await node.start()
            port = node._server.sockets[0].getsockname()[1]
            payload = AttestationData.from_attestation(make_att(11)).to_bytes()
            first = await self._post(port, payload)
            replay = await self._post(port, payload)
            garbage = await self._post(port, b"\x00" * 31)
            # Wedge the verifier and flood a 1-slot queue: the bounded
            # intake must answer 429, not queue without bound.  The
            # flood runs concurrently (queued verdicts only resolve
            # once the verifier is released).
            hold = threading.Event()
            node._ingest.pool.verify = lambda ph, items: (
                hold.wait(10),
                [True] * len(items),
            )[1]
            node._ingest._submit_queue.maxsize = 1
            node._ingest._batch_queue.maxsize = 1
            flood_task = asyncio.gather(
                *[
                    self._post(
                        port,
                        AttestationData.from_attestation(
                            make_att(20 + i, sender=i % 5)
                        ).to_bytes(),
                    )
                    for i in range(8)
                ]
            )
            await asyncio.sleep(0.5)
            hold.set()
            floods = await flood_task
            await node.stop()
            return first, replay, garbage, floods

        first, replay, garbage, floods = asyncio.run(scenario())
        assert first[0] == 200 and '"accepted": true' in first[1]
        assert replay[0] == 400 and "duplicate" in replay[1]
        assert garbage[0] == 400 and "malformed-payload" in garbage[1]
        assert any(status == 429 for status, _ in floods), floods
        for status, body in floods:
            assert status in (200, 400, 429, 500), (status, body)
