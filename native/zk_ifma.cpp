// 8-way AVX-512IFMA radix-52 Montgomery engine for Bn254 Fr.
//
// The reference's prover inherits halo2's tuned Rust field backend
// (circuit/src/utils.rs:259-281 create_proof); this is the rebuild's
// host-side analog: the batch-parallel proving loops (NTT butterflies,
// gate-program evaluation over the extended coset, vector mul /
// scale-add) run eight field elements per instruction via
// vpmadd52{lu,hu}q.
//
// Representation: five 52-bit limbs (radix 2^52, Montgomery R = 2^260),
// SoA in blocks of eight lanes: block b = five consecutive __m512i,
// limb l at index 5*b + l.  Values are lazy in [0, 32p) with limbs kept
// < 2^52 by a carry propagation after every op (vpmadd52 reads only
// bits 51:0 of its operands).  Bound bookkeeping, in units of p
// (p ~ 2^254, 2^260 = 64p):
//   mul:   out < p + in_a*in_b*p/64      (in_a*in_b <= 256 required)
//   add:   out = a + b
//   sub<K>: out = a + K                  (requires b < K*p)
//   normalize (mul by R mod p): out < 1.5p for in < 32p
// Entry points convert canonical 4x64 limbs in/out with a final exact
// reduction, so callers never see the lazy domain.
//
// Runtime-gated: zk_runtime.cpp dispatches here only when
// zk_ifma_available() returns 1.

#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <vector>

#if defined(__x86_64__)
#include <immintrin.h>
#define ZK_IFMA_BUILD 1
#endif

#include "constants.h"
#include "zk_common.h"

extern "C" int64_t zk_ifma_available() {
#ifdef ZK_IFMA_BUILD
    __builtin_cpu_init();
    return __builtin_cpu_supports("avx512f") &&
           __builtin_cpu_supports("avx512vl") &&
           __builtin_cpu_supports("avx512dq") &&
           __builtin_cpu_supports("avx512ifma");
#else
    return 0;
#endif
}

#ifdef ZK_IFMA_BUILD

namespace {

constexpr uint64_t MASK52 = (1ULL << 52) - 1;
typedef unsigned __int128 u128;

// ---------------------------------------------------------------------
// Scalar radix-52 arithmetic (setup: constants, twiddle tables).

struct N52 {
    uint64_t l[5];
};

inline N52 to52(const uint64_t a[4]) {
    N52 r;
    r.l[0] = a[0] & MASK52;
    r.l[1] = ((a[0] >> 52) | (a[1] << 12)) & MASK52;
    r.l[2] = ((a[1] >> 40) | (a[2] << 24)) & MASK52;
    r.l[3] = ((a[2] >> 28) | (a[3] << 36)) & MASK52;
    r.l[4] = a[3] >> 16;
    return r;
}

inline void from52(uint64_t out[4], const N52 &a) {
    out[0] = a.l[0] | (a.l[1] << 52);
    out[1] = (a.l[1] >> 12) | (a.l[2] << 40);
    out[2] = (a.l[2] >> 24) | (a.l[3] << 28);
    out[3] = (a.l[3] >> 36) | (a.l[4] << 16);
}

inline int cmp256(const uint64_t a[4], const uint64_t b[4]) {
    for (int i = 3; i >= 0; --i) {
        if (a[i] != b[i]) return a[i] < b[i] ? -1 : 1;
    }
    return 0;
}

inline void sub256(uint64_t a[4], const uint64_t b[4]) {
    u128 borrow = 0;
    for (int i = 0; i < 4; ++i) {
        u128 d = (u128)a[i] - b[i] - borrow;
        a[i] = (uint64_t)d;
        borrow = (d >> 64) ? 1 : 0;
    }
}

inline void dbl_mod(uint64_t a[4]) {
    uint64_t hi = a[3] >> 63;
    for (int i = 3; i > 0; --i) a[i] = (a[i] << 1) | (a[i - 1] >> 63);
    a[0] <<= 1;
    if (hi || cmp256(a, FR_P) >= 0) sub256(a, FR_P);
}

struct Consts {
    N52 p;
    N52 r2;        // 2^520 mod p: canonical -> mont52 factor
    N52 one_mont;  // 2^260 mod p
    uint64_t pinv52;
};

Consts make_consts() {
    Consts c;
    c.p = to52(FR_P);
    uint64_t acc[4] = {1, 0, 0, 0};
    for (int i = 0; i < 520; ++i) dbl_mod(acc);
    c.r2 = to52(acc);
    uint64_t one[4] = {1, 0, 0, 0};
    for (int i = 0; i < 260; ++i) dbl_mod(one);
    c.one_mont = to52(one);
    uint64_t p0 = FR_P[0];
    uint64_t inv = 1;
    for (int i = 0; i < 6; ++i) inv *= 2 - p0 * inv;
    c.pinv52 = (0 - inv) & MASK52;
    return c;
}

const Consts &CC() {
    static const Consts c = make_consts();
    return c;
}

// Scalar Montgomery-52 product with full reduction to [0, p).
N52 s52_mul(const N52 &a, const N52 &b) {
    const Consts &c = CC();
    u128 t[6] = {0, 0, 0, 0, 0, 0};
    for (int i = 0; i < 5; ++i) {
        for (int j = 0; j < 5; ++j) {
            u128 prod = (u128)a.l[i] * b.l[j];
            t[j] += prod & MASK52;
            t[j + 1] += (uint64_t)(prod >> 52);
        }
        uint64_t y = ((uint64_t)t[0] * c.pinv52) & MASK52;
        for (int j = 0; j < 5; ++j) {
            u128 prod = (u128)y * c.p.l[j];
            t[j] += prod & MASK52;
            t[j + 1] += (uint64_t)(prod >> 52);
        }
        t[0] >>= 52;
        t[0] += t[1];
        for (int j = 1; j < 5; ++j) t[j] = t[j + 1];
        t[5] = 0;
    }
    N52 r;
    u128 carry = 0;
    for (int i = 0; i < 5; ++i) {
        u128 v = t[i] + carry;
        r.l[i] = (uint64_t)v & MASK52;
        carry = v >> 52;
    }
    // Exact reduce (value < 2p here).
    uint64_t c4[4];
    from52(c4, r);
    if (cmp256(c4, FR_P) >= 0) {
        sub256(c4, FR_P);
        return to52(c4);
    }
    return r;
}

// canonical -> Montgomery-52, fully reduced.
inline N52 s52_to_mont(const uint64_t a[4]) { return s52_mul(to52(a), CC().r2); }

inline N52 s52_from_mont_n52(const N52 &a) {
    N52 one = {{1, 0, 0, 0, 0}};
    return s52_mul(a, one);
}

// ---------------------------------------------------------------------
// Vector core.

#define ZK_TGT __attribute__((target("avx512f,avx512vl,avx512dq,avx512ifma")))

struct V8 {
    __m512i l[5];
};

ZK_TGT inline V8 v8_zero() {
    V8 r;
    for (int i = 0; i < 5; ++i) r.l[i] = _mm512_setzero_si512();
    return r;
}

ZK_TGT inline V8 v8_bcast(const N52 &a) {
    V8 r;
    for (int i = 0; i < 5; ++i) r.l[i] = _mm512_set1_epi64((long long)a.l[i]);
    return r;
}

// Unsigned carry propagation: limbs -> [0, 2^52), value unchanged.
ZK_TGT inline void v8_carry(V8 &a) {
    const __m512i mask = _mm512_set1_epi64((long long)MASK52);
    for (int i = 0; i < 4; ++i) {
        __m512i c = _mm512_srli_epi64(a.l[i], 52);
        a.l[i] = _mm512_and_si512(a.l[i], mask);
        a.l[i + 1] = _mm512_add_epi64(a.l[i + 1], c);
    }
}

// Signed carry propagation (for subtraction; borrows ride as negative
// carries, the total value is non-negative by the caller's invariant).
ZK_TGT inline void v8_carry_signed(V8 &a) {
    const __m512i mask = _mm512_set1_epi64((long long)MASK52);
    for (int i = 0; i < 4; ++i) {
        __m512i c = _mm512_srai_epi64(a.l[i], 52);
        a.l[i] = _mm512_and_si512(a.l[i], mask);
        a.l[i + 1] = _mm512_add_epi64(a.l[i + 1], c);
    }
}

// Montgomery product a*b/2^260; out < p + (a/p)*(b/p)*p/64.
ZK_TGT inline V8 v8_mul(const V8 &a, const V8 &b) {
    const Consts &c = CC();
    const __m512i zero = _mm512_setzero_si512();
    __m512i p0 = _mm512_set1_epi64((long long)c.p.l[0]);
    __m512i p1 = _mm512_set1_epi64((long long)c.p.l[1]);
    __m512i p2 = _mm512_set1_epi64((long long)c.p.l[2]);
    __m512i p3 = _mm512_set1_epi64((long long)c.p.l[3]);
    __m512i p4 = _mm512_set1_epi64((long long)c.p.l[4]);
    __m512i pinv = _mm512_set1_epi64((long long)c.pinv52);
    __m512i t0 = zero, t1 = zero, t2 = zero, t3 = zero, t4 = zero, t5 = zero;
    for (int i = 0; i < 5; ++i) {
        __m512i ai = a.l[i];
        t0 = _mm512_madd52lo_epu64(t0, ai, b.l[0]);
        t1 = _mm512_madd52lo_epu64(t1, ai, b.l[1]);
        t2 = _mm512_madd52lo_epu64(t2, ai, b.l[2]);
        t3 = _mm512_madd52lo_epu64(t3, ai, b.l[3]);
        t4 = _mm512_madd52lo_epu64(t4, ai, b.l[4]);
        t1 = _mm512_madd52hi_epu64(t1, ai, b.l[0]);
        t2 = _mm512_madd52hi_epu64(t2, ai, b.l[1]);
        t3 = _mm512_madd52hi_epu64(t3, ai, b.l[2]);
        t4 = _mm512_madd52hi_epu64(t4, ai, b.l[3]);
        t5 = _mm512_madd52hi_epu64(t5, ai, b.l[4]);
        __m512i y = _mm512_madd52lo_epu64(zero, t0, pinv);
        t0 = _mm512_madd52lo_epu64(t0, y, p0);
        t1 = _mm512_madd52lo_epu64(t1, y, p1);
        t2 = _mm512_madd52lo_epu64(t2, y, p2);
        t3 = _mm512_madd52lo_epu64(t3, y, p3);
        t4 = _mm512_madd52lo_epu64(t4, y, p4);
        t1 = _mm512_madd52hi_epu64(t1, y, p0);
        t2 = _mm512_madd52hi_epu64(t2, y, p1);
        t3 = _mm512_madd52hi_epu64(t3, y, p2);
        t4 = _mm512_madd52hi_epu64(t4, y, p3);
        t5 = _mm512_madd52hi_epu64(t5, y, p4);
        __m512i carry = _mm512_srli_epi64(t0, 52);
        t0 = _mm512_add_epi64(t1, carry);
        t1 = t2;
        t2 = t3;
        t3 = t4;
        t4 = t5;
        t5 = zero;
    }
    V8 r;
    r.l[0] = t0;
    r.l[1] = t1;
    r.l[2] = t2;
    r.l[3] = t3;
    r.l[4] = t4;
    v8_carry(r);
    return r;
}

ZK_TGT inline V8 v8_add(const V8 &a, const V8 &b) {
    V8 r;
    for (int i = 0; i < 5; ++i) r.l[i] = _mm512_add_epi64(a.l[i], b.l[i]);
    v8_carry(r);
    return r;
}

// a - b + K*p; requires b < K*p.
template <int K>
ZK_TGT inline V8 v8_sub(const V8 &a, const V8 &b) {
    const Consts &c = CC();
    V8 r;
    for (int i = 0; i < 5; ++i) {
        __m512i kp = _mm512_set1_epi64((long long)(c.p.l[i] * (uint64_t)K));
        r.l[i] = _mm512_sub_epi64(_mm512_add_epi64(a.l[i], kp), b.l[i]);
    }
    // K*p per-limb products stay < 2^57 for K <= 16; signed carries fix
    // both the scaled-limb overflow and subtraction borrows.
    v8_carry_signed(r);
    return r;
}

// Reduce the lazy bound: x -> x mod p + <1.5p, staying in the
// Montgomery domain (multiply by R mod p).
ZK_TGT inline V8 v8_normalize(const V8 &a) { return v8_mul(a, v8_bcast(CC().one_mont)); }

// Exact canonical value: leave Montgomery domain, then one conditional
// subtract (input < 32p).
ZK_TGT inline V8 v8_to_std_reduced(const V8 &a) {
    N52 one = {{1, 0, 0, 0, 0}};
    V8 y = v8_mul(a, v8_bcast(one));  // < p + 32/64 p < 2p
    const Consts &c = CC();
    V8 d;
    for (int i = 0; i < 5; ++i) {
        __m512i p = _mm512_set1_epi64((long long)c.p.l[i]);
        d.l[i] = _mm512_sub_epi64(y.l[i], p);
    }
    v8_carry_signed(d);
    // top limb of d negative => y < p => keep y.
    __mmask8 neg = _mm512_cmplt_epi64_mask(d.l[4], _mm512_setzero_si512());
    V8 r;
    for (int i = 0; i < 5; ++i) r.l[i] = _mm512_mask_blend_epi64(neg, d.l[i], y.l[i]);
    return r;
}

// ---------------------------------------------------------------------
// Canonical (m,4) row-major <-> 52-SoA blocks, 8 rows at a time.

// Transpose 8 rows x 4 u64 -> 4 vectors (one per 64-bit limb).
ZK_TGT inline void load_tr8(const uint64_t *src, __m512i out[4]) {
    __m512i z0 = _mm512_loadu_si512(src);
    __m512i z1 = _mm512_loadu_si512(src + 8);
    __m512i z2 = _mm512_loadu_si512(src + 16);
    __m512i z3 = _mm512_loadu_si512(src + 24);
    const __m512i ia = _mm512_setr_epi64(0, 4, 8, 12, 1, 5, 9, 13);
    const __m512i ib = _mm512_setr_epi64(2, 6, 10, 14, 3, 7, 11, 15);
    __m512i u0 = _mm512_permutex2var_epi64(z0, ia, z1);  // r0..r3 limb0 | limb1
    __m512i u1 = _mm512_permutex2var_epi64(z2, ia, z3);  // r4..r7 limb0 | limb1
    __m512i v0 = _mm512_permutex2var_epi64(z0, ib, z1);  // r0..r3 limb2 | limb3
    __m512i v1 = _mm512_permutex2var_epi64(z2, ib, z3);
    const __m512i lo = _mm512_setr_epi64(0, 1, 2, 3, 8, 9, 10, 11);
    const __m512i hi = _mm512_setr_epi64(4, 5, 6, 7, 12, 13, 14, 15);
    out[0] = _mm512_permutex2var_epi64(u0, lo, u1);
    out[1] = _mm512_permutex2var_epi64(u0, hi, u1);
    out[2] = _mm512_permutex2var_epi64(v0, lo, v1);
    out[3] = _mm512_permutex2var_epi64(v0, hi, v1);
}

ZK_TGT inline void store_tr8(uint64_t *dst, const __m512i in[4]) {
    const __m512i lo = _mm512_setr_epi64(0, 1, 2, 3, 8, 9, 10, 11);
    const __m512i hi = _mm512_setr_epi64(4, 5, 6, 7, 12, 13, 14, 15);
    __m512i u0 = _mm512_permutex2var_epi64(in[0], lo, in[1]);  // r0..r3 l0|l1
    __m512i u1 = _mm512_permutex2var_epi64(in[0], hi, in[1]);  // r4..r7 l0|l1
    __m512i v0 = _mm512_permutex2var_epi64(in[2], lo, in[3]);
    __m512i v1 = _mm512_permutex2var_epi64(in[2], hi, in[3]);
    const __m512i ia = _mm512_setr_epi64(0, 4, 8, 12, 1, 5, 9, 13);
    const __m512i ib = _mm512_setr_epi64(2, 6, 10, 14, 3, 7, 11, 15);
    _mm512_storeu_si512(dst, _mm512_permutex2var_epi64(u0, ia, v0));
    _mm512_storeu_si512(dst + 8, _mm512_permutex2var_epi64(u0, ib, v0));
    _mm512_storeu_si512(dst + 16, _mm512_permutex2var_epi64(u1, ia, v1));
    _mm512_storeu_si512(dst + 24, _mm512_permutex2var_epi64(u1, ib, v1));
}

// 4x64 vectors -> 5x52 vectors.
ZK_TGT inline V8 radix52(const __m512i L[4]) {
    const __m512i mask = _mm512_set1_epi64((long long)MASK52);
    V8 r;
    r.l[0] = _mm512_and_si512(L[0], mask);
    r.l[1] = _mm512_and_si512(
        _mm512_or_si512(_mm512_srli_epi64(L[0], 52), _mm512_slli_epi64(L[1], 12)),
        mask);
    r.l[2] = _mm512_and_si512(
        _mm512_or_si512(_mm512_srli_epi64(L[1], 40), _mm512_slli_epi64(L[2], 24)),
        mask);
    r.l[3] = _mm512_and_si512(
        _mm512_or_si512(_mm512_srli_epi64(L[2], 28), _mm512_slli_epi64(L[3], 36)),
        mask);
    r.l[4] = _mm512_srli_epi64(L[3], 16);
    return r;
}

ZK_TGT inline void radix64(const V8 &a, __m512i L[4]) {
    L[0] = _mm512_or_si512(a.l[0], _mm512_slli_epi64(a.l[1], 52));
    L[1] = _mm512_or_si512(_mm512_srli_epi64(a.l[1], 12), _mm512_slli_epi64(a.l[2], 40));
    L[2] = _mm512_or_si512(_mm512_srli_epi64(a.l[2], 24), _mm512_slli_epi64(a.l[3], 28));
    L[3] = _mm512_or_si512(_mm512_srli_epi64(a.l[3], 36), _mm512_slli_epi64(a.l[4], 16));
}

// Load 8 canonical rows -> Montgomery-52 (bound < 1.5p).
ZK_TGT inline V8 v8_load_mont(const uint64_t *src, const V8 &r2v) {
    __m512i L[4];
    load_tr8(src, L);
    return v8_mul(radix52(L), r2v);
}

// Store 8 lazy values -> canonical rows.
ZK_TGT inline void v8_store_std(uint64_t *dst, const V8 &a) {
    __m512i L[4];
    radix64(v8_to_std_reduced(a), L);
    store_tr8(dst, L);
}

// Store a *standard-domain* value < 2p: one conditional subtract, no
// Montgomery conversion.
ZK_TGT inline void v8_store_plain2p(uint64_t *dst, const V8 &a) {
    const Consts &c = CC();
    V8 d;
    for (int i = 0; i < 5; ++i) {
        __m512i p = _mm512_set1_epi64((long long)c.p.l[i]);
        d.l[i] = _mm512_sub_epi64(a.l[i], p);
    }
    v8_carry_signed(d);
    __mmask8 neg = _mm512_cmplt_epi64_mask(d.l[4], _mm512_setzero_si512());
    V8 r;
    for (int i = 0; i < 5; ++i) r.l[i] = _mm512_mask_blend_epi64(neg, d.l[i], a.l[i]);
    __m512i L[4];
    radix64(r, L);
    store_tr8(dst, L);
}

// ---------------------------------------------------------------------
// NTT.

struct StageTables {
    // Per-stage twiddles for len >= 16 stages, 52-SoA, exactly reduced:
    // stage s holds half(s) entries (half = len/2, len = 16 << s).
    std::vector<std::vector<V8>> big;
    // Lane-constant twiddle vectors for len = 2, 4, 8.
    V8 tw2, tw4, tw8;
    V8 ninv_mont;  // n^-1 in Montgomery-52 (inverse transforms)
};

ZK_TGT V8 pack_lanes(const N52 v[8]) {
    V8 r;
    alignas(64) uint64_t buf[8];
    for (int limb = 0; limb < 5; ++limb) {
        for (int l = 0; l < 8; ++l) buf[l] = v[l].l[limb];
        r.l[limb] = _mm512_load_si512(buf);
    }
    return r;
}

ZK_TGT StageTables make_tables(int64_t n, const uint64_t *root_canon) {
    StageTables st;
    N52 root = s52_to_mont(root_canon);
    // tw[i] = root^i for i < n/2 (Montgomery-52, exact).
    std::vector<N52> tw(n / 2);
    N52 one = CC().one_mont;
    tw[0] = one;
    for (int64_t i = 1; i < n / 2; ++i) tw[i] = s52_mul(tw[i - 1], root);

    // Small stages: len=2 twiddle is 1; len=4 lanes use j in {0,1} with
    // step n/4; len=8 lanes j in {0..3} with step n/8.
    N52 lanes2[8], lanes4[8], lanes8[8];
    for (int l = 0; l < 8; ++l) {
        lanes2[l] = tw[0];
        int j4 = l & 1;  // within len=4 group: lanes {0,1}=low, {2,3}=high; j = l & 1
        lanes4[l] = tw[(int64_t)j4 * (n / 4)];
        int j8 = l & 3;
        lanes8[l] = tw[(int64_t)j8 * (n / 8)];
    }
    st.tw2 = pack_lanes(lanes2);
    st.tw4 = pack_lanes(lanes4);
    st.tw8 = pack_lanes(lanes8);

    for (int64_t len = 16; len <= n; len <<= 1) {
        int64_t half = len >> 1, step = n / len;
        std::vector<V8> stage(half / 8);
        alignas(64) uint64_t buf[8];
        for (int64_t j0 = 0; j0 < half; j0 += 8) {
            V8 v;
            for (int limb = 0; limb < 5; ++limb) {
                for (int l = 0; l < 8; ++l) buf[l] = tw[(j0 + l) * step].l[limb];
                v.l[limb] = _mm512_load_si512(buf);
            }
            stage[j0 / 8] = v;
        }
        st.big.push_back(std::move(stage));
    }

    // n^-1 mod p in Montgomery-52: (n in mont)^(p-2) is overkill — use
    // Fermat via square-and-multiply on the scalar path.
    {
        uint64_t n4[4] = {(uint64_t)n, 0, 0, 0};
        N52 nm = s52_to_mont(n4);
        // exponent p-2
        uint64_t e[4];
        memcpy(e, FR_P, 32);
        // subtract 2
        uint64_t two[4] = {2, 0, 0, 0};
        sub256(e, two);
        N52 acc = one;
        for (int bit = 253; bit >= 0; --bit) {
            acc = s52_mul(acc, acc);
            if ((e[bit / 64] >> (bit % 64)) & 1) acc = s52_mul(acc, nm);
        }
        st.ninv_mont = v8_bcast(acc);
    }
    return st;
}

struct TableKey {
    int64_t n;
    uint64_t r0, r1, r2, r3;
    bool operator<(const TableKey &o) const {
        if (n != o.n) return n < o.n;
        if (r0 != o.r0) return r0 < o.r0;
        if (r1 != o.r1) return r1 < o.r1;
        if (r2 != o.r2) return r2 < o.r2;
        return r3 < o.r3;
    }
};

ZK_TGT const StageTables &tables_for(int64_t n, const uint64_t *root) {
    // ctypes releases the GIL, so concurrent zk_ntt calls can race on
    // this cache — serialize the lookup (table build is one-time).
    static std::mutex mu;
    static std::map<TableKey, StageTables> cache;
    std::lock_guard<std::mutex> lock(mu);
    TableKey k{n, root[0], root[1], root[2], root[3]};
    auto it = cache.find(k);
    if (it == cache.end()) it = cache.emplace(k, make_tables(n, root)).first;
    return it->second;
}

inline int64_t bitrev(int64_t x, int bits) {
    int64_t r = 0;
    for (int i = 0; i < bits; ++i) {
        r = (r << 1) | (x & 1);
        x >>= 1;
    }
    return r;
}

// Butterfly on whole blocks: (A, B) -> (A + tw*B, A - tw*B).
ZK_TGT inline void bfly(V8 &a, V8 &b, const V8 &tw) {
    V8 t = v8_mul(tw, b);  // tw exact (<p), b < 16p  =>  t < p + 16p/64 < 2p
    V8 anew = v8_add(a, t);
    b = v8_sub<2>(a, t);
    a = anew;
}

// Small-stage butterfly inside one block: partner lane = lane ^ half.
template <int HALF>
ZK_TGT inline V8 bfly_small(const V8 &x, const V8 &tw) {
    constexpr __mmask8 HI = (HALF == 1) ? 0xAA : (HALF == 2) ? 0xCC : 0xF0;
    V8 xp, z, w, t, r;
    __m512i idx;
    if (HALF == 1)
        idx = _mm512_setr_epi64(1, 0, 3, 2, 5, 4, 7, 6);
    else if (HALF == 2)
        idx = _mm512_setr_epi64(2, 3, 0, 1, 6, 7, 4, 5);
    else
        idx = _mm512_setr_epi64(4, 5, 6, 7, 0, 1, 2, 3);
    for (int i = 0; i < 5; ++i) xp.l[i] = _mm512_permutexvar_epi64(idx, x.l[i]);
    // z: the high-half operand aligned to every lane; w: the low-half.
    for (int i = 0; i < 5; ++i) {
        z.l[i] = _mm512_mask_blend_epi64(HI, xp.l[i], x.l[i]);
        w.l[i] = _mm512_mask_blend_epi64(HI, x.l[i], xp.l[i]);
    }
    t = v8_mul(tw, z);
    V8 sum = v8_add(w, t);
    V8 diff = v8_sub<2>(w, t);
    for (int i = 0; i < 5; ++i) r.l[i] = _mm512_mask_blend_epi64(HI, sum.l[i], diff.l[i]);
    return r;
}

ZK_TGT void ifma_ntt_impl(uint64_t *data, int64_t n, const uint64_t *root_canon,
                          int inverse) {
    const StageTables &st = tables_for(n, root_canon);
    int bits = 0;
    while ((1LL << bits) < n) ++bits;

    int64_t nb = n / 8;
    std::vector<V8> buf(nb);
    V8 r2v = v8_bcast(CC().r2);
    // Pack with bit-reversed row reads; convert to Montgomery-52.
    {
        alignas(64) uint64_t rows[32];
        for (int64_t b = 0; b < nb; ++b) {
            for (int l = 0; l < 8; ++l) {
                int64_t src = bitrev(8 * b + l, bits);
                memcpy(rows + 4 * l, data + 4 * src, 32);
            }
            __m512i L[4];
            load_tr8(rows, L);
            buf[b] = v8_mul(radix52(L), r2v);  // < 1.5p
        }
    }

    // Small stages (in-block).  Bounds: start < 1.5p; each stage adds
    // at most max(t, 2p) => < 2p growth per stage.
    if (n >= 2) {
        for (int64_t b = 0; b < nb; ++b) buf[b] = bfly_small<1>(buf[b], st.tw2);
    }
    if (n >= 4) {
        for (int64_t b = 0; b < nb; ++b) buf[b] = bfly_small<2>(buf[b], st.tw4);
    }
    if (n >= 8) {
        for (int64_t b = 0; b < nb; ++b) buf[b] = bfly_small<4>(buf[b], st.tw8);
    }

    // Big stages.  Normalize the whole array every 6 stages to keep the
    // lazy bound under 16p (growth <= 2p per stage from ~2p base).
    int since_norm = 0;
    int sidx = 0;
    for (int64_t len = 16; len <= n; len <<= 1, ++sidx) {
        int64_t half = len >> 1;
        const std::vector<V8> &tws = st.big[sidx];
        for (int64_t start = 0; start < n; start += len) {
            for (int64_t j = 0; j < half; j += 8) {
                int64_t ia = (start + j) / 8, ib = (start + j + half) / 8;
                bfly(buf[ia], buf[ib], tws[j / 8]);
            }
        }
        if (++since_norm == 6 && len < n) {
            for (int64_t b = 0; b < nb; ++b) buf[b] = v8_normalize(buf[b]);
            since_norm = 0;
        }
    }

    if (inverse) {
        for (int64_t b = 0; b < nb; ++b) buf[b] = v8_mul(buf[b], st.ninv_mont);
    }

    for (int64_t b = 0; b < nb; ++b) v8_store_std(data + 32 * b, buf[b]);
}

// ---------------------------------------------------------------------
// Gate-program evaluation (stack machine, 8 points per step).
//
// Columns arrive as canonical (m,4) arrays; rotations index blocks
// directly because rot*rot_stride is a multiple of 8 (checked by the
// dispatcher).  Bounds are tracked per stack slot in units of p and
// operands normalized when a multiply would exceed the lazy window.

ZK_TGT int64_t ifma_eval_impl(int64_t m, int64_t n_cols,
                              const uint64_t *const *cols, int64_t rot_stride,
                              const int64_t *code, int64_t code_len,
                              const uint64_t *consts, int64_t n_consts,
                              uint64_t *out) {
    const int STACK = ZK_EVAL_STACK_DEPTH;
    int64_t mb = m / 8;
    // Pre-convert columns to Montgomery-52 SoA.
    std::vector<std::vector<V8>> mcols(n_cols);
    V8 r2v = v8_bcast(CC().r2);
    for (int64_t ci = 0; ci < n_cols; ++ci) {
        mcols[ci].resize(mb);
        const uint64_t *src = cols[ci];
        for (int64_t b = 0; b < mb; ++b) mcols[ci][b] = v8_load_mont(src + 32 * b, r2v);
    }
    std::vector<V8> cmont(n_consts ? n_consts : 1);
    for (int64_t i = 0; i < n_consts; ++i) cmont[i] = v8_bcast(s52_to_mont(consts + 4 * i));

#pragma omp parallel
    {
    std::vector<V8> stack(STACK);
    std::vector<int> bound(STACK);
#pragma omp for schedule(static)
    for (int64_t b = 0; b < mb; ++b) {
        int sp = 0;
        for (int64_t pc = 0; pc < code_len;) {
            int64_t op = code[pc++];
            switch (op) {
            case 0: {
                int64_t col = code[pc++];
                int64_t rot = code[pc++];
                int64_t blk = (b + rot * rot_stride / 8) % mb;
                if (blk < 0) blk += mb;
                stack[sp] = mcols[col][blk];
                bound[sp++] = 2;
                break;
            }
            case 1:
                stack[sp] = cmont[code[pc++]];
                bound[sp++] = 1;
                break;
            case 2:
                --sp;
                if (bound[sp - 1] + bound[sp] > 30) {
                    stack[sp - 1] = v8_normalize(stack[sp - 1]);
                    bound[sp - 1] = 2;
                    if (bound[sp] > 15) {
                        stack[sp] = v8_normalize(stack[sp]);
                        bound[sp] = 2;
                    }
                }
                stack[sp - 1] = v8_add(stack[sp - 1], stack[sp]);
                bound[sp - 1] += bound[sp];
                break;
            case 3:
                --sp;
                if (bound[sp] > 8) {
                    stack[sp] = v8_normalize(stack[sp]);
                    bound[sp] = 2;
                }
                if (bound[sp - 1] > 22) {
                    stack[sp - 1] = v8_normalize(stack[sp - 1]);
                    bound[sp - 1] = 2;
                }
                stack[sp - 1] = v8_sub<8>(stack[sp - 1], stack[sp]);
                bound[sp - 1] += 8;
                break;
            case 4:
                --sp;
                if (bound[sp - 1] > 16) {
                    stack[sp - 1] = v8_normalize(stack[sp - 1]);
                    bound[sp - 1] = 2;
                }
                if (bound[sp] > 16) {
                    stack[sp] = v8_normalize(stack[sp]);
                    bound[sp] = 2;
                }
                stack[sp - 1] = v8_mul(stack[sp - 1], stack[sp]);
                // out < p + ba*bb*p/64, ba*bb <= 256 => < 5p
                bound[sp - 1] = 1 + (bound[sp - 1] * bound[sp] + 63) / 64;
                break;
            case 5:
                if (bound[sp - 1] > 8) {
                    stack[sp - 1] = v8_normalize(stack[sp - 1]);
                    bound[sp - 1] = 2;
                }
                stack[sp - 1] = v8_sub<8>(v8_zero(), stack[sp - 1]);
                bound[sp - 1] = 8;
                break;
            }
        }
        v8_store_std(out + 32 * b, stack[0]);
    }
    }
    return 0;
}

// ---------------------------------------------------------------------
// Vector mul / scale-add.

ZK_TGT void ifma_vec_mul_impl(const uint64_t *a, const uint64_t *b, uint64_t *out,
                              int64_t n) {
    V8 r2v = v8_bcast(CC().r2);
#pragma omp parallel for schedule(static) if (n >= 65536)
    for (int64_t blk = 0; blk < n / 8; ++blk) {
        V8 x = v8_load_mont(a + 32 * blk, r2v);
        V8 y = v8_load_mont(b + 32 * blk, r2v);
        v8_store_std(out + 32 * blk, v8_mul(x, y));
    }
}

ZK_TGT void ifma_scale_add_impl(uint64_t *acc, const uint64_t *p, const uint64_t *s,
                                int64_t n) {
    V8 r2v = v8_bcast(CC().r2);
    V8 sv = v8_bcast(s52_to_mont(s));
    for (int64_t blk = 0; blk < n / 8; ++blk) {
        V8 x = v8_load_mont(p + 32 * blk, r2v);
        V8 prod = v8_mul(x, sv);  // < p + 1.5*1*p/64 < 2p
        __m512i L[4];
        load_tr8(acc + 32 * blk, L);
        V8 a = radix52(L);  // canonical, < p: plain (non-Montgomery) value
        // prod is Montgomery; convert to std (exact, < p) before the
        // canonical add, then store with a plain reduction — the sum is
        // standard-domain, so no Montgomery factor must be applied.
        V8 pstd = v8_to_std_reduced(prod);
        v8_store_plain2p(acc + 32 * blk, v8_add(a, pstd));
    }
}

}  // namespace

// ---------------------------------------------------------------------
// C entry points (called from zk_runtime.cpp dispatchers).

extern "C" {

void ifma_ntt(uint64_t *data, int64_t n, const uint64_t *root_canon, int inverse) {
    ifma_ntt_impl(data, n, root_canon, inverse);
}

int64_t ifma_eval_program(int64_t m, int64_t n_cols, const uint64_t *const *cols,
                          int64_t rot_stride, const int64_t *code, int64_t code_len,
                          const uint64_t *consts, int64_t n_consts, uint64_t *out) {
    return ifma_eval_impl(m, n_cols, cols, rot_stride, code, code_len, consts,
                          n_consts, out);
}

void ifma_vec_mul(const uint64_t *a, const uint64_t *b, uint64_t *out, int64_t n) {
    ifma_vec_mul_impl(a, b, out, n);
}

void ifma_scale_add(uint64_t *acc, const uint64_t *p, const uint64_t *s, int64_t n) {
    ifma_scale_add_impl(acc, p, s, n);
}

}  // extern "C"

#endif  // ZK_IFMA_BUILD
