// zk_runtime: C++ proving runtime for the KZG/PLONK stack.
//
// The reference's prover is halo2's Rust backend (create_proof,
// circuit/src/utils.rs:259-281): its hot loops are NTTs over Fr,
// multi-scalar multiplications over G1, and evaluating the combined
// gate polynomial over an extended coset domain.  This library is the
// native engine for those three loops; Python (protocol_tpu.zk)
// orchestrates the protocol and keeps a pure fallback for environments
// without a compiler.
//
// ABI: canonical little-endian 4x64-bit limbs everywhere; G1 points as
// 8 limbs (x, y affine; (0,0) = identity).  Montgomery conversion is
// internal.
//
// Build: make -C native libzk_runtime.so

#include "constants.h"
#include "zk_common.h"

#include <atomic>
#include <cassert>
#include <chrono>
#include <cstdint>
#include <cstring>
#include <vector>

typedef unsigned __int128 u128;

// ---------------------------------------------------------------------
// Phase timer table (deep attribution).
//
// Every public entry point accumulates its wall-clock into one of a
// fixed set of phases so the Python prover can attribute SNARK time to
// the engine loops (msm / ntt / gate_eval / field_ops / srs) without
// per-call ctypes overhead: the table is a handful of relaxed atomics,
// read out once per prove via zk_phase_stats().  Timing wraps whole
// extern-C calls on the calling thread (OpenMP workers inside a call
// are covered by the caller's interval), so concurrent Python threads
// accumulate independently and correctly.

enum ZkPhase { PH_MSM = 0, PH_NTT, PH_GATE_EVAL, PH_FIELD_OPS, PH_SRS, PH_COUNT };

static std::atomic<int64_t> g_phase_calls[PH_COUNT];
static std::atomic<int64_t> g_phase_ns[PH_COUNT];

struct PhaseTimer {
    ZkPhase phase;
    std::chrono::steady_clock::time_point t0;
    explicit PhaseTimer(ZkPhase p) : phase(p), t0(std::chrono::steady_clock::now()) {}
    ~PhaseTimer() {
        int64_t ns = std::chrono::duration_cast<std::chrono::nanoseconds>(
                         std::chrono::steady_clock::now() - t0)
                         .count();
        g_phase_calls[phase].fetch_add(1, std::memory_order_relaxed);
        g_phase_ns[phase].fetch_add(ns, std::memory_order_relaxed);
    }
};

extern "C" {

int64_t zk_phase_count() { return PH_COUNT; }

// out: PH_COUNT x 2 int64 (calls, nanoseconds), phase-enum order
// (msm, ntt, gate_eval, field_ops, srs).
void zk_phase_stats(int64_t *out) {
    for (int p = 0; p < PH_COUNT; ++p) {
        out[2 * p] = g_phase_calls[p].load(std::memory_order_relaxed);
        out[2 * p + 1] = g_phase_ns[p].load(std::memory_order_relaxed);
    }
}

void zk_phase_reset() {
    for (int p = 0; p < PH_COUNT; ++p) {
        g_phase_calls[p].store(0, std::memory_order_relaxed);
        g_phase_ns[p].store(0, std::memory_order_relaxed);
    }
}

}  // extern "C"

// ---------------------------------------------------------------------
// Generic 4-limb Montgomery field.

struct FrP {
    static const uint64_t *mod() { return FR_P; }
    static const uint64_t *r2() { return FR_R2; }
    static const uint64_t *one() { return FR_ONE_MONT; }
    static uint64_t pinv() { return FR_P_INV_NEG; }
};

struct FqP {
    static const uint64_t *mod() { return FQ_P; }
    static const uint64_t *r2() { return FQ_R2; }
    static const uint64_t *one() { return FQ_ONE_MONT; }
    static uint64_t pinv() { return FQ_P_INV_NEG; }
};

template <typename P> struct Fp {
    uint64_t l[4];

    static inline bool geq_p(const uint64_t a[4]) {
        const uint64_t *m = P::mod();
        for (int i = 3; i >= 0; --i) {
            if (a[i] != m[i]) return a[i] > m[i];
        }
        return true;
    }

    static inline void sub_p(uint64_t a[4]) {
        const uint64_t *m = P::mod();
        u128 borrow = 0;
        for (int i = 0; i < 4; ++i) {
            u128 d = (u128)a[i] - m[i] - borrow;
            a[i] = (uint64_t)d;
            borrow = (d >> 64) ? 1 : 0;
        }
    }

    static inline void add(Fp &out, const Fp &a, const Fp &b) {
        u128 carry = 0;
        for (int i = 0; i < 4; ++i) {
            u128 s = (u128)a.l[i] + b.l[i] + carry;
            out.l[i] = (uint64_t)s;
            carry = s >> 64;
        }
        if (carry || geq_p(out.l)) sub_p(out.l);
    }

    static inline void sub(Fp &out, const Fp &a, const Fp &b) {
        const uint64_t *m = P::mod();
        u128 borrow = 0;
        for (int i = 0; i < 4; ++i) {
            u128 d = (u128)a.l[i] - b.l[i] - borrow;
            out.l[i] = (uint64_t)d;
            borrow = (d >> 64) ? 1 : 0;
        }
        if (borrow) {
            u128 carry = 0;
            for (int i = 0; i < 4; ++i) {
                u128 s = (u128)out.l[i] + m[i] + carry;
                out.l[i] = (uint64_t)s;
                carry = s >> 64;
            }
        }
    }

    static inline void neg(Fp &out, const Fp &a) {
        Fp zero;
        memset(zero.l, 0, 32);
        sub(out, zero, a);
    }

    // Montgomery CIOS multiplication with the "no-carry" optimization
    // (valid because the modulus' top word < 2^63 - 1, as for both
    // Bn254 fields): the running total never overflows 5 words, so the
    // 6-word temp and its carry juggling disappear.
    static inline __attribute__((always_inline)) void mul(Fp &out, const Fp &a, const Fp &b) {
        const uint64_t *m = P::mod();
        const uint64_t pinv = P::pinv();
        uint64_t t[4] = {0, 0, 0, 0};
        for (int i = 0; i < 4; ++i) {
            u128 cur = (u128)t[0] + (u128)a.l[i] * b.l[0];
            uint64_t c0 = (uint64_t)(cur >> 64);
            uint64_t mm = (uint64_t)cur * pinv;
            u128 red = ((u128)(uint64_t)cur + (u128)mm * m[0]) >> 64;
            for (int j = 1; j < 4; ++j) {
                cur = (u128)t[j] + (u128)a.l[i] * b.l[j] + c0;
                c0 = (uint64_t)(cur >> 64);
                red += (u128)(uint64_t)cur + (u128)mm * m[j];
                t[j - 1] = (uint64_t)red;
                red >>= 64;
            }
            t[3] = (uint64_t)(red + c0);
        }
        memcpy(out.l, t, 32);
        if (geq_p(out.l)) sub_p(out.l);
    }

    static inline void sqr(Fp &out, const Fp &a) { mul(out, a, a); }

    static inline bool is_zero(const Fp &a) {
        return !(a.l[0] | a.l[1] | a.l[2] | a.l[3]);
    }

    static inline bool eq(const Fp &a, const Fp &b) { return !memcmp(a.l, b.l, 32); }

    static void to_mont(Fp &out, const uint64_t canon[4]) {
        Fp a, r2;
        memcpy(a.l, canon, 32);
        memcpy(r2.l, P::r2(), 32);
        mul(out, a, r2);
    }

    static void from_mont(uint64_t canon[4], const Fp &a) {
        Fp one = {{1, 0, 0, 0}};
        Fp res;
        mul(res, a, one);
        memcpy(canon, res.l, 32);
    }

    static void set_one(Fp &out) { memcpy(out.l, P::one(), 32); }
    static void set_zero(Fp &out) { memset(out.l, 0, 32); }

    // out = a^e for a canonical 4-limb exponent (square-and-multiply).
    static void pow(Fp &out, const Fp &a, const uint64_t e[4]) {
        Fp result, base = a;
        set_one(result);
        for (int limb = 0; limb < 4; ++limb) {
            uint64_t bits = e[limb];
            for (int i = 0; i < 64; ++i) {
                if ((limb * 64 + i) >= 254 && !bits) break;
                if (bits & 1) mul(result, result, base);
                sqr(base, base);
                bits >>= 1;
            }
        }
        out = result;
    }

    // out = a^(p-2) = a^-1 (a != 0).
    static void inv(Fp &out, const Fp &a) {
        uint64_t e[4];
        memcpy(e, P::mod(), 32);
        // p - 2 (p is odd and > 2, no borrow past limb 0 unless l0 < 2)
        if (e[0] >= 2) {
            e[0] -= 2;
        } else {
            u128 borrow = 2;
            for (int i = 0; i < 4; ++i) {
                u128 d = (u128)e[i] - borrow;
                e[i] = (uint64_t)d;
                borrow = (d >> 64) ? 1 : 0;
            }
        }
        pow(out, a, e);
    }
};

typedef Fp<FrP> FrF;
typedef Fp<FqP> FqF;

// ---------------------------------------------------------------------
// NTT over Fr (radix-2, in-place, bit-reversed ordering internally).

static void bit_reverse_permute(FrF *data, int64_t n) {
    int log_n = 0;
    while ((1LL << log_n) < n) ++log_n;
    for (int64_t i = 0; i < n; ++i) {
        int64_t rev = 0;
        for (int b = 0; b < log_n; ++b) {
            if (i & (1LL << b)) rev |= 1LL << (log_n - 1 - b);
        }
        if (i < rev) {
            FrF tmp = data[i];
            data[i] = data[rev];
            data[rev] = tmp;
        }
    }
}

extern "C" {

int64_t zk_abi_version() { return 4; }

// AVX-512IFMA engine (zk_ifma.cpp), dispatched at runtime.
extern "C" {
int64_t zk_ifma_available();
#if defined(__x86_64__)
void ifma_ntt(uint64_t *data, int64_t n, const uint64_t *root_canon, int inverse);
int64_t ifma_eval_program(int64_t m, int64_t n_cols, const uint64_t *const *cols,
                          int64_t rot_stride, const int64_t *code, int64_t code_len,
                          const uint64_t *consts, int64_t n_consts, uint64_t *out);
void ifma_vec_mul(const uint64_t *a, const uint64_t *b, uint64_t *out, int64_t n);
void ifma_scale_add(uint64_t *acc, const uint64_t *p, const uint64_t *s, int64_t n);
#endif
}

static inline bool use_ifma() {
    static const bool ok = zk_ifma_available() != 0;
    return ok;
}

// In-place NTT of `data` (n x 4 canonical limbs).  `root_canon` must be
// a primitive n-th root of unity (pass the inverse root for the inverse
// transform; inverse=1 additionally scales by n^-1).
void zk_ntt(uint64_t *data, int64_t n, const uint64_t *root_canon, int inverse) {
    PhaseTimer _pt((PH_NTT));
#if defined(__x86_64__)
    if (use_ifma() && n >= 16) {
        ifma_ntt(data, n, root_canon, inverse);
        return;
    }
#endif
    std::vector<FrF> buf(n);
    for (int64_t i = 0; i < n; ++i) FrF::to_mont(buf[i], data + 4 * i);

    FrF root;
    FrF::to_mont(root, root_canon);

    // Twiddle table: root^0 .. root^(n/2-1).
    std::vector<FrF> tw(n / 2);
    if (n >= 2) {
        FrF::set_one(tw[0]);
        for (int64_t i = 1; i < n / 2; ++i) FrF::mul(tw[i], tw[i - 1], root);
    }

    bit_reverse_permute(buf.data(), n);

    for (int64_t len = 2; len <= n; len <<= 1) {
        int64_t half = len >> 1;
        int64_t step = n / len;
#pragma omp parallel for schedule(static) if (n >= 4096)
        for (int64_t start = 0; start < n; start += len) {
            for (int64_t j = 0; j < half; ++j) {
                FrF u = buf[start + j];
                FrF t;
                FrF::mul(t, buf[start + j + half], tw[j * step]);
                FrF::add(buf[start + j], u, t);
                FrF::sub(buf[start + j + half], u, t);
            }
        }
    }

    if (inverse) {
        // n^-1: n fits in one limb for any practical domain.
        FrF n_f = {{(uint64_t)n, 0, 0, 0}}, n_mont, n_inv;
        FrF r2;
        memcpy(r2.l, FrP::r2(), 32);
        FrF::mul(n_mont, n_f, r2);
        FrF::inv(n_inv, n_mont);
        for (int64_t i = 0; i < n; ++i) FrF::mul(buf[i], buf[i], n_inv);
    }

    for (int64_t i = 0; i < n; ++i) FrF::from_mont(data + 4 * i, buf[i]);
}

void zk_vec_mul(const uint64_t *a, const uint64_t *b, uint64_t *out, int64_t n) {
    PhaseTimer _pt((PH_FIELD_OPS));
#if defined(__x86_64__)
    if (use_ifma() && n >= 8) {
        int64_t head = n & ~7LL;
        ifma_vec_mul(a, b, out, head);
        a += 4 * head;
        b += 4 * head;
        out += 4 * head;
        n -= head;
        if (!n) return;
    }
#endif
#pragma omp parallel for schedule(static) if (n >= 4096)
    for (int64_t i = 0; i < n; ++i) {
        FrF x, y, z;
        FrF::to_mont(x, a + 4 * i);
        FrF::to_mont(y, b + 4 * i);
        FrF::mul(z, x, y);
        FrF::from_mont(out + 4 * i, z);
    }
}

// out[i] = base^i (canonical limbs) for i in [0, n).
void zk_powers(const uint64_t *base_canon, int64_t n, uint64_t *out) {
    PhaseTimer _pt((PH_FIELD_OPS));
    FrF base, acc;
    FrF::to_mont(base, base_canon);
    FrF::set_one(acc);
    for (int64_t i = 0; i < n; ++i) {
        FrF::from_mont(out + 4 * i, acc);
        FrF::mul(acc, acc, base);
    }
}

// acc[i] += s * p[i] for i in [0, n) — the round-5 linear combination.
// acc/p are canonical; the product is computed in Montgomery form and
// converted back before the canonical add.
void zk_scale_add(uint64_t *acc, const uint64_t *p, const uint64_t *s_canon, int64_t n) {
    PhaseTimer _pt((PH_FIELD_OPS));
#if defined(__x86_64__)
    if (use_ifma() && n >= 8) {
        int64_t head = n & ~7LL;
        ifma_scale_add(acc, p, s_canon, head);
        acc += 4 * head;
        p += 4 * head;
        n -= head;
        if (!n) return;
    }
#endif
    FrF s;
    FrF::to_mont(s, s_canon);
    for (int64_t i = 0; i < n; ++i) {
        FrF x, a, zf, sum;
        FrF::to_mont(x, p + 4 * i);
        FrF::mul(zf, x, s);
        FrF::from_mont(zf.l, zf);
        memcpy(a.l, acc + 4 * i, 32);
        FrF::add(sum, a, zf);
        memcpy(acc + 4 * i, sum.l, 32);
    }
}

// Horner evaluation of an n-coefficient polynomial at x (all canonical).
void zk_poly_eval(const uint64_t *coeffs, int64_t n, const uint64_t *x_canon,
                  uint64_t *out) {
    PhaseTimer _pt((PH_FIELD_OPS));
    FrF x, acc;
    FrF::to_mont(x, x_canon);
    FrF::set_zero(acc);
    for (int64_t i = n - 1; i >= 0; --i) {
        FrF c, t;
        FrF::to_mont(c, coeffs + 4 * i);
        FrF::mul(t, acc, x);
        FrF::add(acc, t, c);
    }
    FrF::from_mont(out, acc);
}

// Synthetic division: out (n-1 coeffs) = (p - y) / (X - z); the caller
// guarantees p(z) == y so the remainder vanishes.
void zk_div_linear(const uint64_t *coeffs, int64_t n, const uint64_t *z_canon,
                   uint64_t *out) {
    PhaseTimer _pt((PH_FIELD_OPS));
    FrF z, rem;
    FrF::to_mont(z, z_canon);
    FrF::set_zero(rem);
    for (int64_t i = n - 1; i >= 1; --i) {
        FrF c, t;
        FrF::to_mont(c, coeffs + 4 * i);
        FrF::mul(t, rem, z);
        // out[i-1] = c + rem*z ... building from the top down:
        FrF::add(rem, t, c);
        FrF::from_mont(out + 4 * (i - 1), rem);
    }
}

void zk_vec_add(const uint64_t *a, const uint64_t *b, uint64_t *out, int64_t n) {
    PhaseTimer _pt((PH_FIELD_OPS));
#pragma omp parallel for schedule(static) if (n >= 4096)
    for (int64_t i = 0; i < n; ++i) {
        // canonical add/sub don't need the Montgomery domain
        FrF x, y, z;
        memcpy(x.l, a + 4 * i, 32);
        memcpy(y.l, b + 4 * i, 32);
        FrF::add(z, x, y);
        memcpy(out + 4 * i, z.l, 32);
    }
}

void zk_vec_sub(const uint64_t *a, const uint64_t *b, uint64_t *out, int64_t n) {
    PhaseTimer _pt((PH_FIELD_OPS));
#pragma omp parallel for schedule(static) if (n >= 4096)
    for (int64_t i = 0; i < n; ++i) {
        FrF x, y, z;
        memcpy(x.l, a + 4 * i, 32);
        memcpy(y.l, b + 4 * i, 32);
        FrF::sub(z, x, y);
        memcpy(out + 4 * i, z.l, 32);
    }
}

// Batch modular inverse (Montgomery trick); zeros invert to zero.
void zk_batch_inv(const uint64_t *a, uint64_t *out, int64_t n) {
    PhaseTimer _pt((PH_FIELD_OPS));
    std::vector<FrF> vals(n), prefix(n);
    FrF acc;
    FrF::set_one(acc);
    for (int64_t i = 0; i < n; ++i) {
        FrF::to_mont(vals[i], a + 4 * i);
        prefix[i] = acc;
        if (!FrF::is_zero(vals[i])) FrF::mul(acc, acc, vals[i]);
    }
    FrF inv_all;
    FrF::inv(inv_all, acc);
    for (int64_t i = n - 1; i >= 0; --i) {
        if (FrF::is_zero(vals[i])) {
            memset(out + 4 * i, 0, 32);
            continue;
        }
        FrF res;
        FrF::mul(res, inv_all, prefix[i]);
        FrF::from_mont(out + 4 * i, res);
        FrF::mul(inv_all, inv_all, vals[i]);
    }
}

}  // extern "C"

// ---------------------------------------------------------------------
// G1 (short Weierstrass y^2 = x^3 + 3 over Fq), Jacobian coordinates.

struct G1J {
    FqF x, y, z;  // z == 0 -> identity
};

static inline bool g1_is_identity(const G1J &p) { return FqF::is_zero(p.z); }

static void g1_set_identity(G1J &p) {
    memset(&p, 0, sizeof(p));
    FqF::set_one(p.x);
    FqF::set_one(p.y);
}

// dbl-2009-l (a = 0).
static void g1_double(G1J &out, const G1J &p) {
    if (g1_is_identity(p) || FqF::is_zero(p.y)) {
        g1_set_identity(out);
        return;
    }
    FqF a, b, c, d, e, f, t, t2;
    FqF::sqr(a, p.x);                  // A = X^2
    FqF::sqr(b, p.y);                  // B = Y^2
    FqF::sqr(c, b);                    // C = B^2
    FqF::add(t, p.x, b);               // X+B
    FqF::sqr(t, t);                    // (X+B)^2
    FqF::sub(t, t, a);                 //  - A
    FqF::sub(t, t, c);                 //  - C
    FqF::add(d, t, t);                 // D = 2 * ...
    FqF::add(e, a, a);                 // E = 3A
    FqF::add(e, e, a);
    FqF::sqr(f, e);                    // F = E^2
    G1J res;
    FqF::add(t, d, d);                 // 2D
    FqF::sub(res.x, f, t);             // X3 = F - 2D
    FqF::sub(t, d, res.x);             // D - X3
    FqF::mul(t, e, t);                 // E(D - X3)
    FqF::add(t2, c, c);                // 8C
    FqF::add(t2, t2, t2);
    FqF::add(t2, t2, t2);
    FqF::sub(res.y, t, t2);            // Y3
    FqF::mul(t, p.y, p.z);             // YZ
    FqF::add(res.z, t, t);             // Z3 = 2YZ
    out = res;
}

// Mixed addition with affine q (madd-2007-bl, a = 0).
static void g1_add_affine(G1J &out, const G1J &p, const FqF &qx, const FqF &qy) {
    if (g1_is_identity(p)) {
        out.x = qx;
        out.y = qy;
        FqF::set_one(out.z);
        return;
    }
    FqF z1z1, u2, s2, h, hh, i, j, r, v, t, t2;
    FqF::sqr(z1z1, p.z);
    FqF::mul(u2, qx, z1z1);
    FqF::mul(s2, qy, p.z);
    FqF::mul(s2, s2, z1z1);
    FqF::sub(h, u2, p.x);
    FqF::sub(r, s2, p.y);
    if (FqF::is_zero(h)) {
        if (FqF::is_zero(r)) {
            g1_double(out, p);
            return;
        }
        g1_set_identity(out);
        return;
    }
    FqF::add(r, r, r);                 // r = 2(S2 - Y1)
    FqF::sqr(hh, h);
    FqF::add(i, hh, hh);               // I = 4 HH
    FqF::add(i, i, i);
    FqF::mul(j, h, i);                 // J = H I
    FqF::mul(v, p.x, i);               // V = X1 I
    G1J res;
    FqF::sqr(t, r);
    FqF::sub(t, t, j);
    FqF::add(t2, v, v);
    FqF::sub(res.x, t, t2);            // X3 = r^2 - J - 2V
    FqF::sub(t, v, res.x);
    FqF::mul(t, r, t);
    FqF::mul(t2, p.y, j);
    FqF::add(t2, t2, t2);
    FqF::sub(res.y, t, t2);            // Y3 = r(V-X3) - 2 Y1 J
    FqF::add(t, p.z, h);
    FqF::sqr(t, t);
    FqF::sub(t, t, z1z1);
    FqF::sub(res.z, t, hh);            // Z3 = (Z1+H)^2 - Z1Z1 - HH
    out = res;
}

// Full Jacobian addition (add-2007-bl, a = 0).
static void g1_add(G1J &out, const G1J &p, const G1J &q) {
    if (g1_is_identity(p)) {
        out = q;
        return;
    }
    if (g1_is_identity(q)) {
        out = p;
        return;
    }
    FqF z1z1, z2z2, u1, u2, s1, s2, h, i, j, r, v, t, t2;
    FqF::sqr(z1z1, p.z);
    FqF::sqr(z2z2, q.z);
    FqF::mul(u1, p.x, z2z2);
    FqF::mul(u2, q.x, z1z1);
    FqF::mul(s1, p.y, q.z);
    FqF::mul(s1, s1, z2z2);
    FqF::mul(s2, q.y, p.z);
    FqF::mul(s2, s2, z1z1);
    FqF::sub(h, u2, u1);
    FqF::sub(r, s2, s1);
    if (FqF::is_zero(h)) {
        if (FqF::is_zero(r)) {
            g1_double(out, p);
            return;
        }
        g1_set_identity(out);
        return;
    }
    FqF::add(t, h, h);
    FqF::sqr(i, t);                    // I = (2H)^2
    FqF::mul(j, h, i);
    FqF::add(r, r, r);                 // r = 2(S2-S1)
    FqF::mul(v, u1, i);
    G1J res;
    FqF::sqr(t, r);
    FqF::sub(t, t, j);
    FqF::add(t2, v, v);
    FqF::sub(res.x, t, t2);
    FqF::sub(t, v, res.x);
    FqF::mul(t, r, t);
    FqF::mul(t2, s1, j);
    FqF::add(t2, t2, t2);
    FqF::sub(res.y, t, t2);
    FqF::add(t, p.z, q.z);
    FqF::sqr(t, t);
    FqF::sub(t, t, z1z1);
    FqF::sub(t, t, z2z2);
    FqF::mul(res.z, t, h);
    out = res;
}

static void g1_to_affine(uint64_t out[8], const G1J &p) {
    if (g1_is_identity(p)) {
        memset(out, 0, 64);
        return;
    }
    FqF zinv, zinv2, zinv3, ax, ay;
    FqF::inv(zinv, p.z);
    FqF::sqr(zinv2, zinv);
    FqF::mul(zinv3, zinv2, zinv);
    FqF::mul(ax, p.x, zinv2);
    FqF::mul(ay, p.y, zinv3);
    FqF::from_mont(out, ax);
    FqF::from_mont(out + 4, ay);
}

extern "C" {

// Pippenger MSM with signed digits and batch-affine bucket
// accumulation: scalars n x 4, points n x 8 (affine canonical), out 8.
//
// Signed c-bit digits halve the bucket count (digit in
// [-2^(c-1), 2^(c-1)], negative digits add the negated point), and
// bucket accumulation batches independent affine additions behind one
// shared field inversion (Montgomery trick), ~6.5 Fq muls per add
// instead of ~15 for a Jacobian mixed add.  Rounds whose batch is too
// small to amortize the inversion fall back to mixed adds into shadow
// Jacobian buckets.
void zk_msm(const uint64_t *scalars, const uint64_t *points, int64_t n, uint64_t *out) {
    PhaseTimer _pt((PH_MSM));
    if (n == 0) {
        memset(out, 0, 64);
        return;
    }
    // Window size heuristic (signed buckets: 2^(c-1) per window);
    // reachable range is [5, 16].
    int c;
    {
        int64_t m = n;
        int logn = 0;
        while (m > 1) {
            m >>= 1;
            ++logn;
        }
        c = logn > 9 ? logn - 3 : 5;
        if (c > 16) c = 16;
    }
    int n_windows = (254 + c) / c;  // +1 window absorbs the signed carry
    int64_t n_buckets = 1LL << (c - 1);

    // Convert points to Montgomery once.
    std::vector<FqF> px(n), py(n);
    std::vector<bool> is_id(n);
    for (int64_t i = 0; i < n; ++i) {
        FqF::to_mont(px[i], points + 8 * i);
        FqF::to_mont(py[i], points + 8 * i + 4);
        is_id[i] = !(points[8 * i] | points[8 * i + 1] | points[8 * i + 2] |
                     points[8 * i + 3] | points[8 * i + 4] | points[8 * i + 5] |
                     points[8 * i + 6] | points[8 * i + 7]);
    }

    // Signed digit decomposition, all windows at once: digits[w*n + i].
    std::vector<int32_t> digits((int64_t)n_windows * n, 0);
    for (int64_t i = 0; i < n; ++i) {
        if (is_id[i]) continue;
        int carry = 0;
        for (int w = 0; w < n_windows; ++w) {
            int shift = w * c;
            int limb = shift / 64, off = shift % 64;
            uint64_t raw = limb < 4 ? scalars[4 * i + limb] >> off : 0;
            if (off && limb < 3) raw |= scalars[4 * i + limb + 1] << (64 - off);
            raw = (raw & ((1ULL << c) - 1)) + carry;
            if (raw > (uint64_t)n_buckets) {
                digits[(int64_t)w * n + i] = (int32_t)raw - (1 << c);
                carry = 1;
            } else {
                digits[(int64_t)w * n + i] = (int32_t)raw;
                carry = 0;
            }
        }
    }

    std::vector<G1J> window_sums(n_windows);

#pragma omp parallel for schedule(dynamic)
    for (int w = 0; w < n_windows; ++w) {
        const int32_t *dg = digits.data() + (int64_t)w * n;
        // Counting sort point indices by |digit| bucket.
        std::vector<int32_t> counts(n_buckets + 1, 0);
        for (int64_t i = 0; i < n; ++i) {
            if (dg[i]) ++counts[(dg[i] < 0 ? -dg[i] : dg[i]) - 1];
        }
        std::vector<int32_t> offs(n_buckets + 1, 0);
        int32_t maxcount = 0;
        for (int64_t b = 1; b <= n_buckets; ++b) {
            offs[b] = offs[b - 1] + counts[b - 1];
            if (counts[b - 1] > maxcount) maxcount = counts[b - 1];
        }
        std::vector<int32_t> order(offs[n_buckets]);
        {
            std::vector<int32_t> cur(offs.begin(), offs.end() - 1);
            for (int64_t i = 0; i < n; ++i) {
                if (dg[i]) order[cur[(dg[i] < 0 ? -dg[i] : dg[i]) - 1]++] = (int32_t)i;
            }
        }

        // Affine buckets (occupied flag) + Jacobian shadow for sparse
        // rounds and doubling/cancellation edge cases.
        std::vector<FqF> bx(n_buckets), by(n_buckets);
        std::vector<uint8_t> occ(n_buckets, 0);
        std::vector<G1J> shadow(n_buckets);
        std::vector<uint8_t> shadow_used(n_buckets, 0);

        // Per-round scratch for the batched affine additions.
        std::vector<int32_t> badd;       // bucket indices with a real add
        std::vector<FqF> nx, ny, denom, pref, dinv, lam;
        badd.reserve(n_buckets);

        for (int32_t r = 0; r < maxcount; ++r) {
            // Collect this round's (bucket, point) pairs.
            badd.clear();
            nx.clear();
            ny.clear();
            for (int64_t b = 0; b < n_buckets; ++b) {
                if (counts[b] <= r) continue;
                int32_t i = order[offs[b] + r];
                FqF qy = py[i];
                if (dg[i] < 0) FqF::neg(qy, qy);
                if (!occ[b]) {
                    bx[b] = px[i];
                    by[b] = qy;
                    occ[b] = 1;
                    continue;
                }
                badd.push_back((int32_t)b);
                nx.push_back(px[i]);
                ny.push_back(qy);
            }
            size_t m = badd.size();
            if (m == 0) continue;
            if (m < 16) {
                // Too few to amortize the inversion: mixed adds into the
                // Jacobian shadow buckets.
                for (size_t j = 0; j < m; ++j) {
                    int32_t b = badd[j];
                    if (!shadow_used[b]) {
                        g1_set_identity(shadow[b]);
                        shadow_used[b] = 1;
                    }
                    g1_add_affine(shadow[b], shadow[b], nx[j], ny[j]);
                }
                continue;
            }
            // Batched affine addition: denom = x2 - x1, or 2*y1 for a
            // doubling; cancellations route through the shadow path.
            denom.resize(m);
            pref.resize(m);
            std::vector<uint8_t> kind(m);  // 0 add, 1 double, 2 skip
            for (size_t j = 0; j < m; ++j) {
                int32_t b = badd[j];
                FqF dx;
                FqF::sub(dx, nx[j], bx[b]);
                if (FqF::is_zero(dx)) {
                    FqF sy;
                    FqF::add(sy, ny[j], by[b]);
                    if (FqF::is_zero(sy)) {
                        // P + (-P): bucket empties.
                        occ[b] = 0;
                        kind[j] = 2;
                        FqF::set_one(denom[j]);
                        continue;
                    }
                    kind[j] = 1;
                    FqF::add(denom[j], by[b], by[b]);  // 2y
                    continue;
                }
                kind[j] = 0;
                denom[j] = dx;
            }
            // Montgomery batch inversion over denom[].
            FqF acc;
            FqF::set_one(acc);
            for (size_t j = 0; j < m; ++j) {
                pref[j] = acc;
                FqF::mul(acc, acc, denom[j]);
            }
            FqF inv_all;
            FqF::inv(inv_all, acc);
            // The field mul is latency-bound on dependent chains (78
            // cycles) but ~18 cycles at 4-way ILP, so the application
            // runs in stage passes whose iterations are independent —
            // the out-of-order core overlaps adjacent elements.  Only
            // the dinv sweep keeps a (2-mul) serial chain.
            dinv.resize(m);
            lam.resize(m);
            for (size_t j = m; j-- > 0;) {
                FqF::mul(dinv[j], inv_all, pref[j]);
                FqF::mul(inv_all, inv_all, denom[j]);
            }
            for (size_t j = 0; j < m; ++j) {
                int32_t b = badd[j];
                if (kind[j] == 2) continue;
                if (kind[j] == 1) {
                    // lambda = 3 x^2 / 2y
                    FqF x2, num;
                    FqF::sqr(x2, bx[b]);
                    FqF::add(num, x2, x2);
                    FqF::add(num, num, x2);
                    FqF::mul(lam[j], num, dinv[j]);
                } else {
                    FqF dy;
                    FqF::sub(dy, ny[j], by[b]);
                    FqF::mul(lam[j], dy, dinv[j]);
                }
            }
            for (size_t j = 0; j < m; ++j) {
                int32_t b = badd[j];
                if (kind[j] == 2) continue;
                FqF l2, x3, y3, t;
                FqF::sqr(l2, lam[j]);
                FqF::sub(x3, l2, bx[b]);
                FqF::sub(x3, x3, (kind[j] == 1) ? bx[b] : nx[j]);
                FqF::sub(t, bx[b], x3);
                FqF::mul(y3, lam[j], t);
                FqF::sub(y3, y3, by[b]);
                bx[b] = x3;
                by[b] = y3;
            }
        }

        // Running-sum reduction: sum_b (b+1) * bucket[b], folding the
        // Jacobian shadows in as we pass each bucket.  Split into four
        // contiguous segments whose running sums are independent chains
        // (the point-add latency is ~4x its throughput, so interleaving
        // four chains in one loop body lets the core overlap them):
        //   partial = sum_s part_s + sum_s (s*L)*acc_s,  L = B/4.
        const int64_t L = n_buckets / 4;
        // The segment math needs 4 | n_buckets and L a power of two
        // (guaranteed by n_buckets = 2^(c-1), c >= 5); fail loudly if a
        // future window heuristic breaks that.
        assert((n_buckets & 3) == 0 && (L & (L - 1)) == 0);
        G1J accs[4], parts[4];
        for (int s = 0; s < 4; ++s) {
            g1_set_identity(accs[s]);
            g1_set_identity(parts[s]);
        }
        for (int64_t i = L - 1; i >= 0; --i) {
            for (int s = 0; s < 4; ++s) {
                int64_t b = s * L + i;
                if (occ[b]) g1_add_affine(accs[s], accs[s], bx[b], by[b]);
                if (shadow_used[b]) g1_add(accs[s], accs[s], shadow[b]);
                g1_add(parts[s], parts[s], accs[s]);
            }
        }
        G1J partial = parts[0];
        for (int s = 1; s < 4; ++s) {
            g1_add(partial, partial, parts[s]);
            // (s*L)*acc_s: s*acc_s by repeated addition, then L doublings.
            G1J t = accs[s];
            for (int k = 1; k < s; ++k) g1_add(t, t, accs[s]);
            for (int64_t l = L; l > 1; l >>= 1) g1_double(t, t);
            g1_add(partial, partial, t);
        }
        window_sums[w] = partial;
    }

    G1J total;
    g1_set_identity(total);
    for (int w = n_windows - 1; w >= 0; --w) {
        for (int bit = 0; bit < c; ++bit) g1_double(total, total);
        g1_add(total, total, window_sums[w]);
    }
    g1_to_affine(out, total);
}

// SRS ladder: out[i] = tau^i * G1 for i < n (generator (1, 2)).
void zk_srs_powers(const uint64_t *tau, int64_t n, uint64_t *out) {
    PhaseTimer _pt((PH_SRS));
    // Scalar ladder in Fr.
    std::vector<FrF> scal(n);
    FrF t, acc;
    FrF::to_mont(t, tau);
    FrF::set_one(acc);
    for (int64_t i = 0; i < n; ++i) {
        scal[i] = acc;
        FrF::mul(acc, acc, t);
    }
    uint64_t gen[8] = {1, 0, 0, 0, 2, 0, 0, 0};
    FqF gx, gy;
    FqF::to_mont(gx, gen);
    FqF::to_mont(gy, gen + 4);

#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        uint64_t e[4];
        FrF::from_mont(e, scal[i]);
        G1J r;
        g1_set_identity(r);
        // MSB-first double-and-add.
        for (int bit = 253; bit >= 0; --bit) {
            g1_double(r, r);
            if ((e[bit / 64] >> (bit % 64)) & 1) g1_add_affine(r, r, gx, gy);
        }
        g1_to_affine(out + 8 * i, r);
    }
}

// ---------------------------------------------------------------------
// Gate-program evaluator.
//
// Stack machine over Fr evaluated at every point of a domain: columns
// are (n_cols x m x 4) canonical values; rotations index as
// (i + rot * rot_stride) mod m.  Opcodes (flat int64 stream):
//   0 col rot   push columns[col] at rotation rot
//   1 idx       push consts[idx]
//   2           add
//   3           sub
//   4           mul
//   5           neg
// Output: m x 4 canonical.

static const int ZK_EVAL_STACK = ZK_EVAL_STACK_DEPTH;

// Pre-pass: simulate stack depth and bounds-check every operand so a
// malformed program can't overflow the per-thread stack or index out of
// cols/consts.  Returns the final stack depth, or -1 if invalid.
static int zk_validate_program(int64_t n_cols, const int64_t *code,
                               int64_t code_len, int64_t n_consts) {
    int sp = 0;
    for (int64_t pc = 0; pc < code_len;) {
        int64_t op = code[pc++];
        switch (op) {
        case 0:
            if (pc + 2 > code_len) return -1;
            if (code[pc] < 0 || code[pc] >= n_cols) return -1;
            pc += 2;
            if (++sp > ZK_EVAL_STACK) return -1;
            break;
        case 1:
            if (pc + 1 > code_len) return -1;
            if (code[pc] < 0 || code[pc] >= n_consts) return -1;
            pc += 1;
            if (++sp > ZK_EVAL_STACK) return -1;
            break;
        case 2:
        case 3:
        case 4:
            if (sp < 2) return -1;
            --sp;
            break;
        case 5:
            if (sp < 1) return -1;
            break;
        default:
            return -1;
        }
    }
    return sp;
}

int64_t zk_eval_program2(int64_t m, int64_t n_cols, const uint64_t *const *cols,
                         int64_t rot_stride, const int64_t *code, int64_t code_len,
                         const uint64_t *consts, int64_t n_consts, uint64_t *out);

// Stacked-tensor variant kept for ABI continuity: builds the pointer
// table over the (n_cols, m, 4) tensor and delegates.
int64_t zk_eval_program(int64_t m, int64_t n_cols, const uint64_t *cols,
                        int64_t rot_stride, const int64_t *code, int64_t code_len,
                        const uint64_t *consts, int64_t n_consts, uint64_t *out) {
    std::vector<const uint64_t *> ptrs(n_cols);
    for (int64_t c = 0; c < n_cols; ++c) ptrs[c] = cols + 4 * c * m;
    return zk_eval_program2(m, n_cols, ptrs.data(), rot_stride, code, code_len,
                            consts, n_consts, out);
}

// Pointer-table variant: columns as separate (m,4) arrays (no Python
// np.stack copy), AVX-512IFMA fast path when rotation offsets stay
// 8-aligned (rot_stride % 8 == 0, the k>=11 production shape).
int64_t zk_eval_program2(int64_t m, int64_t n_cols, const uint64_t *const *cols,
                         int64_t rot_stride, const int64_t *code, int64_t code_len,
                         const uint64_t *consts, int64_t n_consts, uint64_t *out) {
    PhaseTimer _pt((PH_GATE_EVAL));
    if (zk_validate_program(n_cols, code, code_len, n_consts) != 1) return -1;
#if defined(__x86_64__)
    if (use_ifma() && m % 8 == 0 && rot_stride % 8 == 0) {
        return ifma_eval_program(m, n_cols, cols, rot_stride, code, code_len,
                                 consts, n_consts, out);
    }
#endif
    std::vector<FrF> cmont(n_consts);
    for (int64_t i = 0; i < n_consts; ++i) FrF::to_mont(cmont[i], consts + 4 * i);

#pragma omp parallel
    {
        std::vector<FrF> stack(ZK_EVAL_STACK);
#pragma omp for schedule(static)
        for (int64_t i = 0; i < m; ++i) {
            int sp = 0;
            for (int64_t pc = 0; pc < code_len;) {
                int64_t op = code[pc++];
                switch (op) {
                case 0: {
                    int64_t col = code[pc++];
                    int64_t rot = code[pc++];
                    int64_t idx = (i + rot * rot_stride) % m;
                    if (idx < 0) idx += m;
                    FrF::to_mont(stack[sp++], cols[col] + 4 * idx);
                    break;
                }
                case 1:
                    stack[sp++] = cmont[code[pc++]];
                    break;
                case 2:
                    --sp;
                    FrF::add(stack[sp - 1], stack[sp - 1], stack[sp]);
                    break;
                case 3:
                    --sp;
                    FrF::sub(stack[sp - 1], stack[sp - 1], stack[sp]);
                    break;
                case 4:
                    --sp;
                    FrF::mul(stack[sp - 1], stack[sp - 1], stack[sp]);
                    break;
                case 5:
                    FrF::neg(stack[sp - 1], stack[sp - 1]);
                    break;
                }
            }
            FrF::from_mont(out + 4 * i, stack[0]);
        }
    }
    return 0;
}

}  // extern "C"
