// Shared constants between zk_runtime.cpp and zk_ifma.cpp.
#pragma once

// Maximum stack depth of the gate-program interpreter.  The validator
// admits programs up to this bound, so every interpreter (scalar and
// IFMA) must allocate exactly this many slots.
#define ZK_EVAL_STACK_DEPTH 160
