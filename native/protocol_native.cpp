// protocol_native: C++ crypto runtime for the attestation ingest path.
//
// The reference's node is native Rust end-to-end; here the Python node
// delegates its hot loops — batch EdDSA verification and batch Poseidon
// public-key hashing (one verify + N+2 hashes per ingested attestation,
// server/src/manager/mod.rs:95-138) — to this library via ctypes.
//
// Field arithmetic: Bn254 Fr in Montgomery form, 4x64-bit limbs, CIOS
// multiplication with __uint128_t.  Constants are generated from the
// golden-vector-validated Python layer (tools/gen_native_constants.py).
//
// Build: make -C native   (produces libprotocol_native.so)

#include "constants.h"

#include <cstdint>
#include <cstring>
#include <vector>

typedef unsigned __int128 u128;

struct Fr {
    uint64_t l[4];
};

static inline bool geq_p(const uint64_t a[4]) {
    for (int i = 3; i >= 0; --i) {
        if (a[i] != FR_P[i]) return a[i] > FR_P[i];
    }
    return true;  // equal
}

static inline void sub_p(uint64_t a[4]) {
    u128 borrow = 0;
    for (int i = 0; i < 4; ++i) {
        u128 d = (u128)a[i] - FR_P[i] - borrow;
        a[i] = (uint64_t)d;
        borrow = (d >> 64) ? 1 : 0;
    }
}

static inline void fr_add(Fr &out, const Fr &a, const Fr &b) {
    u128 carry = 0;
    for (int i = 0; i < 4; ++i) {
        u128 s = (u128)a.l[i] + b.l[i] + carry;
        out.l[i] = (uint64_t)s;
        carry = s >> 64;
    }
    if (carry || geq_p(out.l)) sub_p(out.l);
}

static inline void fr_sub(Fr &out, const Fr &a, const Fr &b) {
    u128 borrow = 0;
    for (int i = 0; i < 4; ++i) {
        u128 d = (u128)a.l[i] - b.l[i] - borrow;
        out.l[i] = (uint64_t)d;
        borrow = (d >> 64) ? 1 : 0;
    }
    if (borrow) {
        u128 carry = 0;
        for (int i = 0; i < 4; ++i) {
            u128 s = (u128)out.l[i] + FR_P[i] + carry;
            out.l[i] = (uint64_t)s;
            carry = s >> 64;
        }
    }
}

// Montgomery CIOS multiplication: out = a * b * R^-1 mod p.
static void fr_mul(Fr &out, const Fr &a, const Fr &b) {
    uint64_t t[6] = {0, 0, 0, 0, 0, 0};
    for (int i = 0; i < 4; ++i) {
        // t += a[i] * b
        u128 carry = 0;
        for (int j = 0; j < 4; ++j) {
            u128 cur = (u128)t[j] + (u128)a.l[i] * b.l[j] + carry;
            t[j] = (uint64_t)cur;
            carry = cur >> 64;
        }
        u128 cur = (u128)t[4] + carry;
        t[4] = (uint64_t)cur;
        t[5] = (uint64_t)(cur >> 64);

        // m = t[0] * p' mod 2^64;  t += m * p;  t >>= 64
        uint64_t m = t[0] * FR_P_INV_NEG;
        carry = ((u128)t[0] + (u128)m * FR_P[0]) >> 64;
        for (int j = 1; j < 4; ++j) {
            u128 c2 = (u128)t[j] + (u128)m * FR_P[j] + carry;
            t[j - 1] = (uint64_t)c2;
            carry = c2 >> 64;
        }
        cur = (u128)t[4] + carry;
        t[3] = (uint64_t)cur;
        t[4] = t[5] + (uint64_t)(cur >> 64);
        t[5] = 0;
    }
    memcpy(out.l, t, 32);
    if (t[4] || geq_p(out.l)) sub_p(out.l);
}

static inline void fr_sqr(Fr &out, const Fr &a) { fr_mul(out, a, a); }

static const Fr FR_ZERO = {{0, 0, 0, 0}};

static inline void fr_set(Fr &out, const uint64_t v[4]) { memcpy(out.l, v, 32); }

static inline bool fr_is_zero(const Fr &a) {
    return !(a.l[0] | a.l[1] | a.l[2] | a.l[3]);
}

static inline bool fr_eq(const Fr &a, const Fr &b) {
    return !memcmp(a.l, b.l, 32);
}

// canonical (non-Montgomery) comparison a <= b
static inline bool limbs_le(const uint64_t a[4], const uint64_t b[4]) {
    for (int i = 3; i >= 0; --i) {
        if (a[i] != b[i]) return a[i] < b[i];
    }
    return true;
}

static void fr_to_mont(Fr &out, const uint64_t canon[4]) {
    Fr a, r2;
    fr_set(a, canon);
    fr_set(r2, FR_R2);
    fr_mul(out, a, r2);
}

static void fr_from_mont(uint64_t canon[4], const Fr &a) {
    Fr one = {{1, 0, 0, 0}};
    Fr res;
    fr_mul(res, a, one);
    memcpy(canon, res.l, 32);
}

// ---------------------------------------------------------------------
// Poseidon 5x5 (Hades), Montgomery domain.

static inline void sbox5(Fr &x) {
    Fr x2, x4;
    fr_sqr(x2, x);
    fr_sqr(x4, x2);
    fr_mul(x, x4, x);
}

static void poseidon_permute(Fr state[5]) {
    const int half_full = POSEIDON_FULL_ROUNDS / 2;
    const int total = POSEIDON_FULL_ROUNDS + POSEIDON_PARTIAL_ROUNDS;
    Fr rc, next[5], prod;
    int idx = 0;
    for (int round = 0; round < total; ++round) {
        bool full = round < half_full || round >= half_full + POSEIDON_PARTIAL_ROUNDS;
        for (int j = 0; j < 5; ++j) {
            fr_set(rc, POSEIDON_RC_MONT[idx + j]);
            fr_add(state[j], state[j], rc);
        }
        idx += 5;
        if (full) {
            for (int j = 0; j < 5; ++j) sbox5(state[j]);
        } else {
            sbox5(state[0]);
        }
        for (int i = 0; i < 5; ++i) {
            next[i] = FR_ZERO;
            for (int j = 0; j < 5; ++j) {
                Fr mij;
                fr_set(mij, POSEIDON_MDS_MONT[i][j]);
                fr_mul(prod, mij, state[j]);
                fr_add(next[i], next[i], prod);
            }
        }
        memcpy(state, next, sizeof(next));
    }
}

// ---------------------------------------------------------------------
// BabyJubJub projective arithmetic, Montgomery domain.

struct Pt {
    Fr x, y, z;
};

static inline __attribute__((always_inline)) void pt_double(Pt &out, const Pt &p) {
    // dbl-2008-bbjlp
    Fr b, c, d, e, f, h, j, t, ca;
    fr_add(t, p.x, p.y);
    fr_sqr(b, t);
    fr_sqr(c, p.x);
    fr_sqr(d, p.y);
    Fr a_const;
    fr_set(a_const, BJJ_A_MONT);
    fr_mul(e, a_const, c);
    fr_add(f, e, d);
    fr_sqr(h, p.z);
    fr_add(t, h, h);
    fr_sub(j, f, t);
    fr_sub(t, b, c);
    fr_sub(t, t, d);
    fr_mul(out.x, t, j);
    fr_sub(ca, e, d);
    fr_mul(out.y, f, ca);
    fr_mul(out.z, f, j);
}

static inline __attribute__((always_inline)) void pt_add(Pt &out, const Pt &p, const Pt &q) {
    // add-2008-bbjlp
    Fr a, b, c, d, e, f, g, t, u, v;
    fr_mul(a, p.z, q.z);
    fr_sqr(b, a);
    fr_mul(c, p.x, q.x);
    fr_mul(d, p.y, q.y);
    Fr d_const, a_const;
    fr_set(d_const, BJJ_D_MONT);
    fr_set(a_const, BJJ_A_MONT);
    fr_mul(e, d_const, c);
    fr_mul(e, e, d);
    fr_sub(f, b, e);
    fr_add(g, b, e);
    fr_add(t, p.x, p.y);
    fr_add(u, q.x, q.y);
    fr_mul(v, t, u);
    fr_sub(v, v, c);
    fr_sub(v, v, d);
    fr_mul(t, a, f);
    fr_mul(out.x, t, v);
    fr_mul(t, a_const, c);
    fr_sub(t, d, t);
    fr_mul(u, a, g);
    fr_mul(out.y, u, t);
    fr_mul(out.z, f, g);
}

// Four independent double-and-add chains interleaved in one loop: the
// field mul is latency-bound (~78 cycles dependent vs ~18 at 4-way ILP,
// PERF.md), so running four signatures' scalar muls side by side lets
// the out-of-order core overlap their chains.
static void pt_mul_scalar4(Pt out[4], const Pt base[4], const uint64_t *scalars[4]) {
    Pt r[4], e[4], tmp;
    for (int k = 0; k < 4; ++k) {
        r[k].x = FR_ZERO;
        fr_set(r[k].y, FR_ONE_MONT);
        fr_set(r[k].z, FR_ONE_MONT);
        e[k] = base[k];
    }
    for (int i = 0; i < 256; ++i) {
        for (int k = 0; k < 4; ++k) {
            if ((scalars[k][i / 64] >> (i % 64)) & 1) {
                pt_add(tmp, r[k], e[k]);
                r[k] = tmp;
            }
        }
        for (int k = 0; k < 4; ++k) {
            pt_double(tmp, e[k]);
            e[k] = tmp;
        }
    }
    for (int k = 0; k < 4; ++k) out[k] = r[k];
}

// projective equality: x1*z2 == x2*z1 && y1*z2 == y2*z1
static bool pt_eq_affine(const Pt &p, const Pt &q) {
    Fr a, b;
    fr_mul(a, p.x, q.z);
    fr_mul(b, q.x, p.z);
    if (!fr_eq(a, b)) return false;
    fr_mul(a, p.y, q.z);
    fr_mul(b, q.y, p.z);
    return fr_eq(a, b);
}

// ---------------------------------------------------------------------
// Exported batch API.  All field inputs/outputs are canonical 4x64-limb
// little-endian arrays (matching Fr::to_bytes layout as u64 views).

extern "C" {

// Batch width-5 Poseidon: inputs (n, 5, 4) u64 canonical; outputs the
// full final state (n, 5, 4).  The if-clause keeps tiny batches (the
// per-attestation ingest path calls with n in the single digits) off
// the thread-team fork, the same guard pattern as zk_runtime.cpp's
// NTT/vector loops.
void poseidon5_permute_batch(const uint64_t *inputs, uint64_t *outputs, int64_t n) {
#pragma omp parallel for schedule(static) if (n >= 16)
    for (int64_t k = 0; k < n; ++k) {
        Fr state[5];
        for (int j = 0; j < 5; ++j) fr_to_mont(state[j], inputs + (k * 5 + j) * 4);
        poseidon_permute(state);
        for (int j = 0; j < 5; ++j) fr_from_mont(outputs + (k * 5 + j) * 4, state[j]);
    }
}

// Batch pk-hash: Poseidon(x, y, 0, 0, 0)[0]  (manager/mod.rs:101-120).
void pk_hash_batch(const uint64_t *xs, const uint64_t *ys, uint64_t *out, int64_t n) {
#pragma omp parallel for schedule(static) if (n >= 16)
    for (int64_t k = 0; k < n; ++k) {
        Fr state[5];
        fr_to_mont(state[0], xs + k * 4);
        fr_to_mont(state[1], ys + k * 4);
        state[2] = FR_ZERO;
        state[3] = FR_ZERO;
        state[4] = FR_ZERO;
        poseidon_permute(state);
        fr_from_mont(out + k * 4, state[0]);
    }
}

// Batch EdDSA verification (eddsa/native.rs:130-147): arrays of
// canonical limbs; writes 1/0 per signature.
void eddsa_verify_batch(const uint64_t *rx, const uint64_t *ry, const uint64_t *s,
                        const uint64_t *pkx, const uint64_t *pky,
                        const uint64_t *msg, uint8_t *ok, int64_t n) {
    Pt b8;
    fr_set(b8.x, BJJ_B8_X_MONT);
    fr_set(b8.y, BJJ_B8_Y_MONT);
    fr_set(b8.z, FR_ONE_MONT);

    // One parallel region for both phases — the pragma pattern
    // zk_runtime.cpp's gate evaluator uses (one team, work-shared
    // loops) — so the team forks once per batch, not once per phase;
    // the implicit barrier after the hash loop orders m_hash against
    // the scalar-mul reads.  The if-clause keeps the n=1 per-ingest
    // verify path serial (no fork on the event-loop hot path).
    std::vector<uint64_t> m_hash(n * 4);
    std::vector<uint8_t> s_ok(n);
    static const uint64_t DUMMY[4] = {1, 0, 0, 0};
#pragma omp parallel if (n >= 4)
    {
    // Per-signature message hashes first (cheap next to the curve ops).
#pragma omp for schedule(static)
    for (int64_t k = 0; k < n; ++k) {
        s_ok[k] = limbs_le(s + k * 4, BJJ_SUBORDER) ? 1 : 0;
        Fr state[5];
        fr_to_mont(state[0], rx + k * 4);
        fr_to_mont(state[1], ry + k * 4);
        fr_to_mont(state[2], pkx + k * 4);
        fr_to_mont(state[3], pky + k * 4);
        fr_to_mont(state[4], msg + k * 4);
        poseidon_permute(state);
        fr_from_mont(m_hash.data() + k * 4, state[0]);
    }

    // Scalar muls four signatures at a time: lanes [0..3] hold B8*s and
    // PK*m_hash for two signatures each, so every group of 4 lanes
    // completes two signatures.  Rejected-s slots run with a dummy
    // scalar and are overwritten below.  Dynamic schedule: adversarial
    // batches make group cost bimodal (dummy-only groups skip).
#pragma omp for schedule(dynamic, 8)
    for (int64_t g = 0; g < (n + 1) / 2; ++g) {
        int64_t k0 = 2 * g, k1 = 2 * g + 1;
        bool have1 = k1 < n;
        Pt bases[4];
        const uint64_t *scalars[4];
        // Range-rejected slots already have ok=0: dummy out BOTH of
        // their lanes so adversarial batches reject nearly free, and
        // skip the group entirely when no live signature remains.
        if (!s_ok[k0]) ok[k0] = 0;
        if (have1 && !s_ok[k1]) ok[k1] = 0;
        if (!s_ok[k0] && (!have1 || !s_ok[k1])) continue;
        bases[0] = b8;
        scalars[0] = s_ok[k0] ? s + k0 * 4 : DUMMY;
        if (s_ok[k0]) {
            fr_to_mont(bases[1].x, pkx + k0 * 4);
            fr_to_mont(bases[1].y, pky + k0 * 4);
            fr_set(bases[1].z, FR_ONE_MONT);
            scalars[1] = m_hash.data() + k0 * 4;
        } else {
            bases[1] = b8;
            scalars[1] = DUMMY;
        }
        if (have1 && s_ok[k1]) {
            bases[2] = b8;
            scalars[2] = s + k1 * 4;
            fr_to_mont(bases[3].x, pkx + k1 * 4);
            fr_to_mont(bases[3].y, pky + k1 * 4);
            fr_set(bases[3].z, FR_ONE_MONT);
            scalars[3] = m_hash.data() + k1 * 4;
        } else {
            bases[2] = b8;
            scalars[2] = DUMMY;
            bases[3] = b8;
            scalars[3] = DUMMY;
        }
        Pt res[4];
        pt_mul_scalar4(res, bases, scalars);
        for (int j = 0; j < (have1 ? 2 : 1); ++j) {
            int64_t k = 2 * g + j;
            if (!s_ok[k]) {
                ok[k] = 0;
                continue;
            }
            Pt r, cr;
            fr_to_mont(r.x, rx + k * 4);
            fr_to_mont(r.y, ry + k * 4);
            fr_set(r.z, FR_ONE_MONT);
            pt_add(cr, r, res[2 * j + 1]);
            ok[k] = pt_eq_affine(cr, res[2 * j]) ? 1 : 0;
        }
    }
    }  // omp parallel
}

// Library self-check hook (parity with Python golden vectors is tested
// from pytest).
int64_t protocol_native_abi_version() { return 1; }
}
