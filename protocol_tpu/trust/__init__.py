"""Trust computation: exact native kernels and the TrustBackend registry.

The native kernels reproduce the reference semantics bit-exactly in the
Bn254 field (circuit/src/circuit.rs::native, circuit/src/native.rs::
EigenTrustSet); the JAX backends in ``protocol_tpu.ops`` /
``protocol_tpu.parallel`` compute the same dynamics in floating point at
scale.  ``backend.get_backend`` selects between them.
"""

from .backend import ConvergenceResult, TrustBackend, get_backend  # noqa: F401
from .graph import TrustGraph  # noqa: F401
from .native import EigenTrustSet, Opinion, power_iterate  # noqa: F401
