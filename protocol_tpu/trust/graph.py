"""The local-trust graph assembled from attestations.

The reference's "graph" is an N×N dense ops matrix gathered from the
attestation cache (server/src/manager/mod.rs:182-188).  At TPU scale the
graph is edge-list COO: ``src`` scored ``dst`` with weight ``w``.  This
module owns host-side assembly and normalization; device kernels consume
the arrays it produces.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class TrustGraph:
    """A weighted directed trust graph.

    ``src/dst/weight`` are parallel COO arrays; ``pre_trusted`` flags the
    seed set used for the pre-trust vector p (the scaled analog of the
    reference's fixed bootstrap set, server/src/manager/mod.rs:40-61).
    """

    n: int
    src: np.ndarray  # int32 (nnz,)
    dst: np.ndarray  # int32 (nnz,)
    weight: np.ndarray  # float32 (nnz,)
    pre_trusted: np.ndarray | None = None  # bool (n,)

    def __post_init__(self):
        self.src = np.asarray(self.src, dtype=np.int32)
        self.dst = np.asarray(self.dst, dtype=np.int32)
        self.weight = np.asarray(self.weight, dtype=np.float32)
        assert self.src.shape == self.dst.shape == self.weight.shape

    @property
    def nnz(self) -> int:
        return int(self.src.shape[0])

    @classmethod
    def from_dense(cls, ops: np.ndarray, pre_trusted=None) -> "TrustGraph":
        ops = np.asarray(ops, dtype=np.float64)
        src, dst = np.nonzero(ops)
        return cls(
            n=ops.shape[0],
            src=src.astype(np.int32),
            dst=dst.astype(np.int32),
            weight=ops[src, dst].astype(np.float32),
            pre_trusted=pre_trusted,
        )

    def to_dense(self) -> np.ndarray:
        ops = np.zeros((self.n, self.n), dtype=np.float64)
        np.add.at(ops, (self.src, self.dst), self.weight.astype(np.float64))
        return ops

    def drop_self_edges(self) -> "TrustGraph":
        """EigenTrust nullifies self-scores (native.rs:183-191)."""
        keep = self.src != self.dst
        return TrustGraph(
            self.n, self.src[keep], self.dst[keep], self.weight[keep], self.pre_trusted
        )

    def row_normalized(self) -> tuple[np.ndarray, np.ndarray]:
        """Return ``(normalized weights, dangling mask)``.

        Weights are divided by their row (sender) sum — the local-trust
        normalization ``c_ij = s_ij / Σ_j s_ij`` of the EigenTrust paper
        and of the set kernel's credit distribution (native.rs:89-102).
        Rows with no positive mass are *dangling*; device kernels
        redistribute their rank to the pre-trust vector.
        """
        sums = np.zeros(self.n, dtype=np.float64)
        np.add.at(sums, self.src, self.weight.astype(np.float64))
        dangling = sums <= 0
        safe = np.where(dangling, 1.0, sums)
        w = (self.weight.astype(np.float64) / safe[self.src]).astype(np.float32)
        return w, dangling

    def pre_trust_vector(self) -> np.ndarray:
        """p: uniform over the pre-trusted set, or uniform over all peers
        when no seed set is designated."""
        if self.pre_trusted is None or not self.pre_trusted.any():
            return np.full(self.n, 1.0 / self.n, dtype=np.float32)
        p = self.pre_trusted.astype(np.float64)
        return (p / p.sum()).astype(np.float32)

    def sorted_by_dst(self) -> "TrustGraph":
        """Sort edges by destination — enables ``segment_sum`` with
        ``indices_are_sorted=True`` on TPU (no random-scatter path)."""
        order = np.argsort(self.dst, kind="stable")
        return TrustGraph(
            self.n, self.src[order], self.dst[order], self.weight[order], self.pre_trusted
        )

    def row_ptr_by_dst(self) -> np.ndarray:
        """CSC-style pointers over dst-sorted edges: ``row_ptr[j] ..
        row_ptr[j+1]`` is the edge range whose destination is j.  Feeds
        the cumsum SpMV formulation (gather-only, no scatter)."""
        return np.searchsorted(self.dst, np.arange(self.n + 1), side="left").astype(
            np.int32
        )
