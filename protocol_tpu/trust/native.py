"""Exact (field / rational) trust kernels — the parity reference for
every TPU backend.

Two kernels, mirroring the reference's two designs:

- ``power_iterate`` — the stateless kernel the server actually runs and
  the ZK circuit constrains (circuit/src/circuit.rs:425-470 ``native()``):
  I iterations of ``new_s[i] = Σ_j ops[j][i]·s[j]`` over the Bn254 field,
  then unscale by ``SCALE^-I``.
- ``EigenTrustSet`` — the richer set-managed kernel
  (circuit/src/native.rs:37-234): dynamic membership, per-peer signed
  opinions, ``filter_peers`` nullification/redistribution, credit
  normalization, fixed-iteration convergence.

The set kernel computes in exact rationals (``fractions.Fraction``) with a
``to_field`` mapping p/q ↦ p·q⁻¹ mod r; the field image of the rational
result equals the reference's in-field computation because every reference
division is a field inversion of a value that is the image of a nonzero
rational.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from fractions import Fraction

from ..crypto import field
from ..crypto.eddsa import PublicKey, Signature


def power_iterate(
    initial: list[int], ops: list[list[int]], num_iter: int, scale: int
) -> list[int]:
    """Field-exact power iteration (circuit/src/circuit.rs:425-470).

    ``ops[i][j]`` is peer i's (scaled integer) score for peer j; rows are
    expected to sum to ``scale`` so total score is conserved.  Returns the
    unscaled field elements — equal to the true integer scores whenever
    the integer result is divisible by ``scale**num_iter``.
    """
    n = len(initial)
    assert len(ops) == n and all(len(row) == n for row in ops)
    s = [x % field.MODULUS for x in initial]
    for _ in range(num_iter):
        s = [
            sum(ops[j][i] * s[j] for j in range(n)) % field.MODULUS
            for i in range(n)
        ]
    inv_scale = field.inv(pow(scale, num_iter, field.MODULUS))
    return [field.mul(x, inv_scale) for x in s]


def power_iterate_rational(
    initial: list[int], ops: list[list[int]], num_iter: int, scale: int
) -> list[Fraction]:
    """The same dynamics over exact rationals — the mathematical ground
    truth the floating-point TPU kernels approximate."""
    n = len(initial)
    s = [Fraction(x) for x in initial]
    for _ in range(num_iter):
        s = [sum(Fraction(ops[j][i]) * s[j] for j in range(n)) for i in range(n)]
    return [x / scale**num_iter for x in s]


def fraction_to_field(x: Fraction) -> int:
    """Map p/q into Fr as p·q⁻¹ mod r."""
    return field.mul(x.numerator % field.MODULUS, field.inv(x.denominator % field.MODULUS))


@dataclass
class Opinion:
    """A signed score vector from one peer (circuit/src/native.rs:13-35)."""

    sig: Signature
    message_hash: int
    scores: list[tuple[PublicKey, int]]

    @classmethod
    def empty(cls, num_neighbours: int) -> "Opinion":
        return cls(
            sig=Signature.new(0, 0, 0),
            message_hash=0,
            scores=[(PublicKey.null(), 0)] * num_neighbours,
        )


@dataclass
class EigenTrustSet:
    """Set-managed EigenTrust (circuit/src/native.rs::EigenTrustSet).

    Unlike the reference's compile-time constants (NUM_NEIGHBOURS=6,
    NUM_ITERATIONS=20, native.rs:9-11), set size / iteration count /
    initial score are runtime parameters (SURVEY.md §5 config note).
    """

    num_neighbours: int = 6
    num_iterations: int = 20
    initial_score: int = 1000
    set: list[tuple[PublicKey, int]] = dc_field(default_factory=list)
    ops: dict[PublicKey, Opinion] = dc_field(default_factory=dict)

    def __post_init__(self):
        if not self.set:
            self.set = [(PublicKey.null(), 0)] * self.num_neighbours

    def add_member(self, pk: PublicKey) -> None:
        positions = [i for i, (x, _) in enumerate(self.set) if x == pk]
        assert not positions, "member already in the set"
        free = [i for i, (x, _) in enumerate(self.set) if x.is_null()]
        index = free[0]  # IndexError if full, like the reference's unwrap
        self.set[index] = (pk, self.initial_score)

    def remove_member(self, pk: PublicKey) -> None:
        positions = [i for i, (x, _) in enumerate(self.set) if x == pk]
        assert positions, "member not in the set"
        self.set[positions[0]] = (PublicKey.null(), 0)
        self.ops.pop(pk, None)

    def update_op(self, from_pk: PublicKey, op: Opinion) -> None:
        assert any(x == from_pk for x, _ in self.set), "unknown sender"
        self.ops[from_pk] = op

    def filter_peers(
        self,
    ) -> tuple[list[tuple[PublicKey, int]], dict[PublicKey, Opinion]]:
        """Nullify invalid/self/absent scores and evenly redistribute
        all-zero opinions (circuit/src/native.rs:146-234)."""
        n = self.num_neighbours
        filtered_set = list(self.set)
        filtered_ops: dict[PublicKey, Opinion] = {}

        for i in range(n):
            pk_i, _ = filtered_set[i]
            if pk_i.is_null():
                continue

            op = self.ops.get(pk_i, Opinion.empty(n))
            scores = list(op.scores)

            for j in range(n):
                set_pk_j, _ = filtered_set[j]
                op_pk_j, op_score_j = scores[j]

                is_diff = set_pk_j != op_pk_j
                is_null = set_pk_j.is_null()
                is_self = set_pk_j == pk_i

                if is_diff or is_null or is_self:
                    op_score_j = 0
                if is_diff:
                    op_pk_j = set_pk_j
                scores[j] = (op_pk_j, op_score_j)

            if sum(score for _, score in scores) == 0:
                for j in range(n):
                    pk_j, _ = scores[j]
                    if pk_j != pk_i and not pk_j.is_null():
                        scores[j] = (pk_j, 1)

            filtered_ops[pk_i] = Opinion(op.sig, op.message_hash, scores)

        return filtered_set, filtered_ops

    def converge_rational(self) -> list[Fraction]:
        """Exact-rational convergence (circuit/src/native.rs:83-144).

        Raises ZeroDivisionError for a lone peer with an all-null opinion
        (the reference's ``invert().unwrap()`` panic) and AssertionError
        below 2 valid peers, in the reference's order.
        """
        n = self.num_neighbours
        filtered_set, filtered_ops = self.filter_peers()

        # Normalize each valid peer's opinion: distribute its credits
        # proportionally to its (filtered) scores.
        normalized: dict[PublicKey, list[Fraction]] = {}
        for pk, credits in filtered_set:
            if pk.is_null():
                continue
            scores = filtered_ops[pk].scores
            total = sum(score for _, score in scores)
            if total == 0:
                raise ZeroDivisionError("opinion sum is zero")  # invert(0)
            normalized[pk] = [Fraction(score * credits, total) for _, score in scores]

        valid_peers = sum(1 for pk, _ in filtered_set if not pk.is_null())
        assert valid_peers >= 2, "Insufficient peers for calculation!"

        s = [Fraction(credits) for _, credits in filtered_set]
        zero_row = [Fraction(0)] * n
        for _ in range(self.num_iterations):
            rows = [
                normalized.get(filtered_set[i][0], zero_row) if not filtered_set[i][0].is_null() else zero_row
                for i in range(n)
            ]
            s = [sum(rows[j][i] * s[j] for j in range(n)) for i in range(n)]
        return s

    def converge(self) -> list[int]:
        """Field image of the rational convergence — matches the
        reference's in-field result."""
        return [fraction_to_field(x) for x in self.converge_rational()]

    def to_arrays(self):
        """Bridge to the vectorized kernels: ``(ops, match, valid,
        credits)`` numpy arrays aligned to set order, consumed by
        ``protocol_tpu.ops.dense.filter_and_normalize``.

        ``ops[i, j]`` is peer i's raw (pre-filter) score for slot j;
        ``match[i, j]`` whether the opinion's j-th pk equals set slot
        j's pk (mismatches are nullified by the kernel exactly like
        filter_peers does).
        """
        import numpy as np

        n = self.num_neighbours
        ops = np.zeros((n, n), dtype=np.float64)
        match = np.zeros((n, n), dtype=bool)
        valid = np.array([not pk.is_null() for pk, _ in self.set])
        credits = np.array([score for _, score in self.set], dtype=np.float64)
        for i, (pk_i, _) in enumerate(self.set):
            if pk_i.is_null():
                continue
            op = self.ops.get(pk_i, Opinion.empty(n))
            for j in range(n):
                op_pk, score = op.scores[j]
                ops[i, j] = float(score)
                match[i, j] = op_pk == self.set[j][0]
        return ops, match, valid, credits
