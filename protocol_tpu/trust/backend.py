"""TrustBackend — the pluggable execution backend for trust convergence.

The north-star design: the node selects how the epoch's convergence runs
(BASELINE.json: "native-cpu | tpu-pjrt"), generalized here to six
backends along the scaling ladder:

- ``native-cpu``    exact field/rational math (parity with the reference)
- ``tpu-dense``     jit'd dense matmul power iteration (≤ ~10k peers)
- ``tpu-sparse``    COO segment-sum SpMV, single device
- ``tpu-csr``       gather-only CSR/compensated-cumsum SpMV (scatter-free)
- ``tpu-windowed``  fused fixed-slot pipeline: windowed Pallas gather from
  a VMEM-resident score table + static bucket→dst bridge (PERF.md §7).
  Needs a static graph layout (the one-time ``WindowPlan``, reusable
  across epochs/reboots while the graph fingerprint holds) and a score
  table that fits VMEM as one window set (≤ 4 MB ⇒ ≤ ~1M peers f32).
  Prefer ``tpu-csr`` when the graph churns every epoch (plan cost is
  then per-epoch), when N exceeds the VMEM table cap, or on toolchains
  where Mosaic is unavailable.
- ``tpu-sharded``   edge-sharded convergence + psum over a device mesh.
  Two per-shard kernels (``parallel/sharded.py::SHARDED_KERNELS``),
  selected with a ``:<kernel>`` suffix on the backend name:
  ``tpu-sharded:tpu-csr`` (default — per-shard CSR ``rowsum_sorted``
  via clipped row pointers) and ``tpu-sharded:tpu-windowed`` (the fused
  fixed-slot pipeline partitioned by window rows, PERF.md §8 — the
  multi-chip path that keeps the 50× windowed gather).

All float backends compute the damped EigenTrust fixed point over the
row-normalized graph; ``native-cpu`` additionally reproduces the
reference's field semantics for the proof layer.
"""

from __future__ import annotations

from dataclasses import dataclass
from fractions import Fraction

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.dense import converge_dense
from ..ops.gather_window import (
    PLAN_VERSION,
    WindowPlan,
    build_window_plan,
    converge_windowed,
    graph_fingerprint,
    try_plan_delta,
)
from ..obs import TRACER
from ..obs.journal import JOURNAL
from ..obs.metrics import PLAN_OUTCOMES, PLAN_REBUILDS, PLAN_REUSES
from ..obs.watchers import RECOMPILES
from ..ops.sparse import converge_csr, converge_sparse
from .graph import TrustGraph

# Register the jit'd converge entry points with the recompile tracker
# (obs/watchers.py): the epoch path brackets each converge with a
# cache-size snapshot, so every fresh XLA compilation is counted on
# eigentrust_jit_recompiles_total{fn} — and a steady-state delta epoch
# that recompiles (breaking PR 5's stable-shape guarantee) is flagged.
# Registration reads nothing from the device; it only keeps a
# reference for later _cache_size() reads at host boundaries.
RECOMPILES.register("converge_dense", converge_dense)
RECOMPILES.register("converge_sparse", converge_sparse)
RECOMPILES.register("converge_csr", converge_csr)
RECOMPILES.register("converge_windowed", converge_windowed)


@dataclass
class ConvergenceResult:
    """Scores plus convergence metadata — the aux bundle every backend
    returns so the node can export convergence health without touching
    device state again."""

    scores: np.ndarray  # (n,) float64, L1-normalized global trust
    iterations: int
    residual: float
    backend: str
    #: Per-iteration L1 residual trajectory (length == ``iterations``),
    #: captured device-side in the while-loop carry on the jax backends
    #: and fetched once after convergence.  None when the caller opted
    #: out (``record_residuals=False``).  The chunked ``tpu-dense``
    #: backend records one residual per host-checked chunk instead (its
    #: loop is host-driven between compiled scan chunks).
    residuals: np.ndarray | None = None

    def scaled(self, total: float) -> np.ndarray:
        """Rescale to reference-style score units (e.g. N·INITIAL_SCORE
        so a uniform result reads 1000 per peer)."""
        return self.scores * total


def _history(hist, iterations: int) -> np.ndarray:
    """The one post-convergence fetch of the device-side residual
    carry, sliced to the iterations actually run."""
    return np.asarray(hist, dtype=np.float64)[: int(iterations)]


def _initial_vector(t0, p: np.ndarray) -> np.ndarray:
    """Resolve the iteration's starting vector: the caller's warm-start
    ``t0`` (the previous epoch's fixed point, remapped over
    joined/departed peers) L1-renormalized, or the pre-trust vector
    ``p`` — the cold start — when ``t0`` is absent, mis-shaped, or
    degenerate.  A near-fixed-point start is pure initial-carry data:
    the step function, and therefore the pinned kernel budgets, are
    untouched (PERF.md §11)."""
    if t0 is None:
        return p
    t0 = np.asarray(t0, dtype=np.float32).reshape(-1)
    if t0.shape != p.shape or not np.isfinite(t0).all():
        return p
    # Converged score vectors carry ±1-ulp negative dust on zero-score
    # peers (compensated-sum differencing); clip rather than reject.
    t0 = np.maximum(t0, 0.0)
    s = float(t0.sum())
    if not np.isfinite(s) or s <= 0:
        return p
    return t0 / np.float32(s)


class TrustBackend:
    name = "abstract"

    def converge(
        self,
        graph: TrustGraph,
        *,
        alpha: float = 0.0,
        tol: float = 1e-6,
        max_iter: int = 50,
        record_residuals: bool = True,
        t0: np.ndarray | None = None,
    ) -> ConvergenceResult:
        raise NotImplementedError


class NativeCPUBackend(TrustBackend):
    """Exact rational dense power iteration — small sets only.

    With ``alpha=0`` and ``max_iter=I`` this is the reference kernel
    modulo normalization: it iterates the row-normalized matrix exactly
    like ``native()`` iterates the SCALE-summing ops matrix
    (circuit/src/circuit.rs:434-454), with dangling rows redirected to
    the pre-trust vector.
    """

    name = "native-cpu"

    def converge(self, graph, *, alpha=0.0, tol=1e-6, max_iter=50,
                 record_residuals=True, t0=None):
        g = graph.drop_self_edges()
        dense = g.to_dense()
        n = g.n
        # Exact pre-trust vector (the float pre_trust_vector() is this
        # same distribution rounded to f32).
        if graph.pre_trusted is not None and graph.pre_trusted.any():
            cnt = int(graph.pre_trusted.sum())
            p = [
                Fraction(1, cnt) if graph.pre_trusted[i] else Fraction(0)
                for i in range(n)
            ]
        else:
            p = [Fraction(1, n)] * n
        # Exact rational row-normalized matrix with dangling → p.
        rows: list[list[Fraction]] = []
        row_sums = dense.sum(axis=1)
        for i in range(n):
            if row_sums[i] <= 0:
                rows.append([p[j] for j in range(n)])
            else:
                s = Fraction(row_sums[i])
                rows.append([Fraction(dense[i][j]) / s for j in range(n)])
        a = Fraction(alpha).limit_denominator(10**9)
        # Warm start: rationalize the seed exactly like alpha; the
        # fixed point is start-independent, only the path shortens.
        pf = np.array([float(x) for x in p], dtype=np.float32)
        start = _initial_vector(t0, pf)
        if start is pf:
            t = list(p)
        else:
            raw = [Fraction(float(x)).limit_denominator(10**12) for x in start]
            s = sum(raw)
            t = [x / s for x in raw] if s > 0 else list(p)
        it = 0
        resid = Fraction(0)
        history: list[float] = []
        for it in range(1, max_iter + 1):
            new_t = [
                (1 - a) * sum(rows[j][i] * t[j] for j in range(n)) + a * p[i]
                for i in range(n)
            ]
            resid = sum(abs(x - y) for x, y in zip(new_t, t))
            if record_residuals:
                history.append(float(resid))
            t = new_t
            if tol > 0 and resid < tol:
                break
        return ConvergenceResult(
            scores=np.array([float(x) for x in t], dtype=np.float64),
            iterations=it,
            residual=float(resid),
            backend=self.name,
            residuals=np.array(history) if record_residuals else None,
        )


class DenseJaxBackend(TrustBackend):
    name = "tpu-dense"

    def converge(self, graph, *, alpha=0.0, tol=1e-6, max_iter=50,
                 record_residuals=True, t0=None):
        g = graph.drop_self_edges()
        dense = g.to_dense().astype(np.float32)
        row_sums = dense.sum(axis=1)
        p = graph.pre_trust_vector().astype(np.float32)
        dangling = row_sums <= 0
        norm = np.where(dangling[:, None], p[None, :], dense / np.where(dangling, 1.0, row_sums)[:, None])
        m = (1.0 - alpha) * norm.T + alpha * np.outer(p, np.ones(g.n, np.float32))
        t = jnp.asarray(_initial_vector(t0, p))
        m = jnp.asarray(m.astype(np.float32))
        it = 0
        resid = np.inf
        history: list[float] = []
        # Fixed-size scan chunks with host-side residual checks between
        # chunks: keeps the hot loop compiled while honoring tol.  The
        # residual trajectory is therefore chunk-granular here (one
        # entry per host check), unlike the while-loop backends.
        chunk = 8 if tol > 0 else max_iter
        while it < max_iter:
            steps = min(chunk, max_iter - it)
            t_new = converge_dense(m, t, steps)
            t_new = t_new / jnp.sum(t_new)
            resid = float(jnp.sum(jnp.abs(t_new - t)))
            if record_residuals:
                history.append(resid)
            t = t_new
            it += steps
            if tol > 0 and resid < tol:
                break
        return ConvergenceResult(
            scores=np.asarray(t, dtype=np.float64),
            iterations=it,
            residual=resid,
            backend=self.name,
            residuals=np.array(history) if record_residuals else None,
        )


class SparseJaxBackend(TrustBackend):
    name = "tpu-sparse"

    def converge(self, graph, *, alpha=0.0, tol=1e-6, max_iter=50,
                 record_residuals=True, t0=None):
        g = graph.drop_self_edges()
        w, dangling = g.row_normalized()
        g = TrustGraph(g.n, g.src, g.dst, w, graph.pre_trusted).sorted_by_dst()
        p = graph.pre_trust_vector()
        with TRACER.span("converge", backend=self.name):
            out = converge_sparse(
                jnp.asarray(g.src),
                jnp.asarray(g.dst),
                jnp.asarray(g.weight),
                jnp.asarray(_initial_vector(t0, p)),
                jnp.asarray(p),
                jnp.asarray(dangling.astype(np.float32)),
                n=g.n,
                alpha=jax.device_put(np.float32(alpha)),
                tol=tol,
                max_iter=max_iter,
                record_residuals=record_residuals,
            )
        t, it, resid = out[:3]
        return ConvergenceResult(
            scores=np.asarray(t, dtype=np.float64),
            iterations=int(it),
            residual=float(resid),
            backend=self.name,
            residuals=_history(out[3], it) if record_residuals else None,
        )


class CsrJaxBackend(TrustBackend):
    """Gather-only CSR/cumsum SpMV — the TPU-friendly formulation
    (scatter-free; see ops.sparse.power_step_csr)."""

    name = "tpu-csr"

    def converge(self, graph, *, alpha=0.0, tol=1e-6, max_iter=50,
                 record_residuals=True, t0=None):
        g = graph.drop_self_edges()
        w, dangling = g.row_normalized()
        g = TrustGraph(g.n, g.src, g.dst, w, graph.pre_trusted).sorted_by_dst()
        p = graph.pre_trust_vector()
        with TRACER.span("converge", backend=self.name):
            out = converge_csr(
                jnp.asarray(g.src),
                jnp.asarray(g.row_ptr_by_dst()),
                jnp.asarray(g.weight),
                jnp.asarray(_initial_vector(t0, p)),
                jnp.asarray(p),
                jnp.asarray(dangling.astype(np.float32)),
                alpha=jax.device_put(np.float32(alpha)),
                tol=tol,
                max_iter=max_iter,
                record_residuals=record_residuals,
            )
        t, it, resid = out[:3]
        return ConvergenceResult(
            scores=np.asarray(t, dtype=np.float64),
            iterations=int(it),
            residual=float(resid),
            backend=self.name,
            residuals=_history(out[3], it) if record_residuals else None,
        )


class WindowedJaxBackend(TrustBackend):
    """Fused fixed-slot pipeline (PERF.md §7): windowed Pallas
    gather-multiply from a VMEM-resident score table + a static
    bucket→dst bridge, so the per-iteration device step performs no
    O(E) random gather.

    The one-time ``WindowPlan`` (host bucketing + reduction layout) is
    cached on the instance and revalidated by graph fingerprint, so
    repeated epochs over a stable graph — and reboots that restore the
    plan from a checkpoint — skip construction entirely.  On a
    fingerprint miss with a churn hint (``delta_rows``: the source
    peers whose out-edges changed since the cached plan's graph), the
    plan is delta-updated in place of a full rebuild (PERF.md §11).
    """

    name = "tpu-windowed"

    def __init__(self, plan: WindowPlan | None = None, interpret: bool | None = None):
        #: Candidate plan to reuse (e.g. checkpoint-restored); replaced
        #: when its fingerprint doesn't match the converged graph.
        self.plan = plan
        #: Pallas interpret mode; default: interpret off real TPUs only
        #: (CPU test runs exercise the identical lowered computation).
        self.interpret = interpret
        #: The plan the last converge actually used (for persistence).
        self.last_plan: WindowPlan | None = plan
        #: Churn hint for the NEXT converge: ids of every source peer
        #: whose out-edges changed since ``plan``'s graph (a superset is
        #: fine).  Consumed (reset to None) by the converge; when the
        #: fingerprint misses and the hint is present, the plan is
        #: delta-updated instead of rebuilt.
        self.delta_rows: np.ndarray | None = None

    def _resolve_plan(self, g, w, fp: str) -> WindowPlan:
        """Reuse, delta-update, or rebuild the cached plan for the
        normalized graph; counts the outcome on the plan metrics.
        Delta application is host-side, strictly before any device
        dispatch (graftlint's plan-mutation-in-converge rule pins the
        converse)."""
        plan, rows = self.plan, self.delta_rows
        self.delta_rows = None
        valid = plan is not None and getattr(plan, "version", 0) == PLAN_VERSION
        if valid and plan.fingerprint == fp:
            PLAN_REUSES.inc()
            PLAN_OUTCOMES.inc(outcome="reuse")
            JOURNAL.record("plan", outcome="reuse", backend=self.name)
            return plan
        if valid and rows is not None:
            with TRACER.span("plan", backend=self.name, reason="delta"):
                delta = try_plan_delta(
                    plan, g.src, g.dst, w, n=g.n, rows=rows, fingerprint=fp
                )
            if delta is not None:
                PLAN_OUTCOMES.inc(outcome="delta")
                JOURNAL.record(
                    "plan", outcome="delta", backend=self.name, rows=int(rows.size)
                )
                return delta
        reason = "cold" if plan is None else (
            "stale-layout" if not valid else "fingerprint-miss"
        )
        with TRACER.span("plan", backend=self.name, reason=reason):
            plan = build_window_plan(g.src, g.dst, w, n=g.n)
        PLAN_REBUILDS.inc()
        PLAN_OUTCOMES.inc(outcome="rebuild")
        JOURNAL.record("plan", outcome="rebuild", backend=self.name, reason=reason)
        return plan

    def converge(self, graph, *, alpha=0.0, tol=1e-6, max_iter=50,
                 record_residuals=True, t0=None):
        g = graph.drop_self_edges()
        w, dangling = g.row_normalized()
        fp = graph_fingerprint(g.n, g.src, g.dst, w)
        plan = self._resolve_plan(g, w, fp)
        self.plan = plan
        self.last_plan = plan
        p = graph.pre_trust_vector()
        interpret = (
            self.interpret
            if self.interpret is not None
            else jax.default_backend() != "tpu"
        )
        with TRACER.span("converge", backend=self.name):
            out = converge_windowed(
                *plan.device_args(),
                jnp.asarray(_initial_vector(t0, p)),
                jnp.asarray(p),
                jnp.asarray(dangling.astype(np.float32)),
                n_rows=plan.n_rows,
                table_entries=plan.table_entries,
                alpha=jax.device_put(np.float32(alpha)),
                tol=tol,
                max_iter=max_iter,
                interpret=interpret,
                record_residuals=record_residuals,
            )
        t, it, resid = out[:3]
        return ConvergenceResult(
            scores=np.asarray(t, dtype=np.float64),
            iterations=int(it),
            residual=float(resid),
            backend=self.name,
            residuals=_history(out[3], it) if record_residuals else None,
        )


class ShardedJaxBackend(TrustBackend):
    """Mesh-sharded convergence, kernel-selectable
    (``parallel/sharded.py::SHARDED_KERNELS``): ``tpu-csr`` shards the
    edge list, ``tpu-windowed`` shards the fused-pipeline window rows
    and keeps the one-time ``WindowPlan`` cached/revalidated exactly
    like the single-device windowed backend (``plan``/``last_plan``
    carry it to and from the node's checkpoint store)."""

    name = "tpu-sharded"

    def __init__(self, mesh=None, kernel: str = "tpu-csr"):
        from ..parallel.sharded import SHARDED_KERNELS

        if kernel not in SHARDED_KERNELS:
            raise ValueError(
                f"unknown sharded kernel {kernel!r}; "
                f"available: {sorted(SHARDED_KERNELS)}"
            )
        self.mesh = mesh
        self.kernel = kernel
        #: Candidate WindowPlan to reuse (tpu-windowed kernel only).
        self.plan: WindowPlan | None = None
        #: The plan the last converge actually used (for persistence).
        self.last_plan: WindowPlan | None = None
        #: Churn hint consumed by the next converge — same contract as
        #: ``WindowedJaxBackend.delta_rows``.
        self.delta_rows: np.ndarray | None = None

    def converge(self, graph, *, alpha=0.0, tol=1e-6, max_iter=50,
                 record_residuals=True, t0=None):
        from ..parallel.mesh import default_mesh
        from ..parallel.sharded import (
            ShardedTrustProblem,
            ShardedWindowPlan,
            converge_sharded,
        )

        mesh = self.mesh if self.mesh is not None else default_mesh()
        name = (
            self.name if self.kernel == "tpu-csr" else f"{self.name}:{self.kernel}"
        )
        problem: ShardedTrustProblem | ShardedWindowPlan
        if self.kernel == "tpu-windowed":
            candidate, rows = self.plan, self.delta_rows
            self.delta_rows = None
            with TRACER.span("plan", backend=name):
                swp = ShardedWindowPlan.build(
                    graph, mesh, plan=candidate, delta_rows=rows
                )
            if swp.plan_outcome == "reuse":
                PLAN_REUSES.inc()
            elif swp.plan_outcome == "rebuild":
                PLAN_REBUILDS.inc()
            PLAN_OUTCOMES.inc(outcome=swp.plan_outcome)
            JOURNAL.record("plan", outcome=swp.plan_outcome, backend=name)
            self.plan = self.last_plan = swp.plan
            problem = swp
        else:
            problem = ShardedTrustProblem.build(graph, mesh)
        start = (
            None if t0 is None else _initial_vector(t0, graph.pre_trust_vector())
        )
        with TRACER.span("converge", backend=name):
            out = converge_sharded(
                problem, alpha=alpha, tol=tol, max_iter=max_iter,
                record_residuals=record_residuals, t0=start,
            )
        t, it, resid = out[:3]
        return ConvergenceResult(
            scores=np.asarray(t, dtype=np.float64),
            iterations=it,
            residual=resid,
            backend=name,
            residuals=_history(out[3], it) if record_residuals else None,
        )


_BACKENDS = {
    "native-cpu": NativeCPUBackend,
    "tpu-dense": DenseJaxBackend,
    "tpu-sparse": SparseJaxBackend,
    "tpu-csr": CsrJaxBackend,
    "tpu-windowed": WindowedJaxBackend,
    "tpu-sharded": ShardedJaxBackend,
}


def registered_backends() -> list[str]:
    """Every constructible backend name, composites expanded — the
    enumeration the static analyzer hard-gates (each name must carry a
    ``KERNEL_INVARIANTS`` declaration next to its kernel AND a
    ``COMM_INVARIANTS`` declaration for graftlint pass 8, or
    ``python -m protocol_tpu.analysis`` fails the lint wall: an
    unregistered kernel budget is ``undeclared-backend``, an
    unregistered comm budget is ``undeclared-comm-budget`` — same
    policy, same gate).  Plain ``tpu-sharded`` is the
    ``tpu-sharded:tpu-csr`` composite."""
    from ..parallel.sharded import SHARDED_KERNELS

    names: list[str] = []
    for base in _BACKENDS:
        if base == "tpu-sharded":
            names.extend(f"{base}:{kernel}" for kernel in sorted(SHARDED_KERNELS))
        else:
            names.append(base)
    return names


def get_backend(name: str, **kwargs) -> TrustBackend:
    """Construct a backend by ladder name.  ``tpu-sharded`` accepts a
    per-shard kernel suffix — ``tpu-sharded:tpu-windowed`` — so config
    strings (ManagerConfig.backend / ProtocolConfig.trust_backend) can
    select the sharded kernel without code."""
    base, _, kernel = name.partition(":")
    if kernel:
        if base != "tpu-sharded":
            raise ValueError(
                f"unknown trust backend {name!r}; only tpu-sharded takes a "
                f":<kernel> suffix (available: {sorted(_BACKENDS)})"
            )
        kwargs.setdefault("kernel", kernel)
    try:
        return _BACKENDS[base](**kwargs)
    except KeyError:
        raise ValueError(
            f"unknown trust backend {name!r}; available: {sorted(_BACKENDS)}"
        ) from None
