"""Sorted-segment helpers shared across the jit backends.

The trust plane's sorted SpMV (:mod:`.sparse`) and the proving plane's
Pippenger bucket accumulation (:mod:`..zk.graft.pippenger`) reduce the same
shape of problem: values carrying sorted integer ids, folded per id.
These helpers are the id-side machinery — run-end masks and the
segmented block-carry scan — kept dtype/monoid-agnostic so the EC
group fold and a float rowsum can ride the identical index logic.

All functions are shape-polymorphic jax and safe to call inside jit.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp


def run_end_mask(ids: jnp.ndarray) -> jnp.ndarray:
    """(..., n) sorted ids -> bool mask marking the LAST lane of every
    run of equal ids.  The final lane is always a run end (the wrapped
    ``roll`` comparison would otherwise drop it when all ids match)."""
    n = ids.shape[-1]
    nxt = jnp.roll(ids, -1, axis=-1)
    last = jnp.arange(n) == n - 1
    return (ids != nxt) | last


def block_boundary_flags(ids_blocked: jnp.ndarray) -> jnp.ndarray:
    """(..., nblocks, B) sorted ids -> (..., nblocks) bool: True when
    the block contains an internal run boundary.  Sortedness makes the
    test O(1) per block: first == last implies the whole block is one
    run."""
    return ids_blocked[..., 0] != ids_blocked[..., -1]


def segmented_carry_scan(
    values,
    flags: jnp.ndarray,
    combine: Callable,
    axis: int = -1,
):
    """Segmented inclusive Hillis–Steele scan over ``axis``.

    Computes ``C[b] = values[b] if flags[b] else combine(C[b-1],
    values[b])`` in ``log2(n)`` rounds — the cross-block carry pass of
    a two-level segmented fold (block-local fold first, then this over
    the block tails, exactly the hierarchical shape ``rowsum_sorted``
    uses for its compensated cumsum).  ``combine(left, right)`` must be
    associative; ``values`` may have trailing payload dims beyond
    ``flags`` (they are broadcast on the mask).
    """
    axis = axis % flags.ndim
    n = flags.shape[axis]
    lane = jnp.arange(n).reshape((n,) + (1,) * (flags.ndim - 1 - axis))
    extra = values.ndim - flags.ndim
    s = 1
    while s < n:
        v_shift = jnp.roll(values, s, axis=axis)
        f_shift = jnp.roll(flags, s, axis=axis)
        active = (lane >= s) & ~flags
        values = jnp.where(
            active.reshape(active.shape + (1,) * extra),
            combine(v_shift, values),
            values,
        )
        flags = flags | ((lane >= s) & f_shift)
        s <<= 1
    return values
