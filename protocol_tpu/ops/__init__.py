"""jit'd JAX kernels for trust convergence: dense, set-semantics, sparse."""

from .dense import converge_dense, filter_and_normalize, set_converge_dense  # noqa: F401
from .sparse import converge_sparse, power_step_coo  # noqa: F401
