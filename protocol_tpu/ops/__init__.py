"""jit'd JAX kernels for trust convergence: dense, set-semantics, sparse,
and the fused windowed (fixed-slot) pipeline."""

from .dense import converge_dense, filter_and_normalize, set_converge_dense  # noqa: F401
from .gather_window import (  # noqa: F401
    PLAN_VERSION,
    WindowPlan,
    bridge_partials,
    bucket_by_window,
    build_window_plan,
    converge_windowed,
    gather_windowed,
    power_step_windowed,
    windowed_ct,
)
from .sparse import converge_csr, converge_sparse, power_step_coo, power_step_csr  # noqa: F401
