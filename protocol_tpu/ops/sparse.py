"""Sparse trust kernels: COO transpose-SpMV power iteration with
pre-trust damping.

The scaled generalization of the reference's 5×5 loop (SURVEY.md §5
"long-context" note): the local-trust matrix C is an edge list, and one
power step is

    t' = (1−α)·(Cᵀt + (Σ_{i dangling} t_i)·p) + α·p

— the EigenTrust paper's damped iteration, where p is the pre-trust
vector and dangling rows (peers with no valid outgoing scores) donate
their mass to p, the at-scale analog of filter_peers' redistribution
(circuit/src/native.rs:200-228).

TPU-first design notes: edges are pre-sorted by destination so the
gather-multiply-reduce lowers to ``segment_sum`` with
``indices_are_sorted=True`` (sequential HBM traffic, no random scatter);
the iteration runs under ``lax.while_loop`` with an L1 residual bound so
convergence detection happens on-device (no host sync per step).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def power_step_coo(
    src: jax.Array,
    dst: jax.Array,
    w: jax.Array,
    t: jax.Array,
    p: jax.Array,
    dangling: jax.Array,
    alpha: jax.Array | float,
    *,
    n: int,
    sorted_by_dst: bool = True,
) -> jax.Array:
    """One damped transpose-SpMV step (edge arrays may be zero-padded:
    pad edges with w=0)."""
    contrib = w * t[src]
    ct = jax.ops.segment_sum(
        contrib, dst, num_segments=n, indices_are_sorted=sorted_by_dst
    )
    dangling_mass = jnp.sum(t * dangling)
    t_new = (1.0 - alpha) * (ct + dangling_mass * p) + alpha * p
    # L1 renorm guards against floating-point drift over many iterations.
    return t_new / jnp.sum(t_new)


@partial(jax.jit, static_argnames=("n", "tol", "max_iter", "sorted_by_dst"))
def converge_sparse(
    src: jax.Array,
    dst: jax.Array,
    w: jax.Array,
    t0: jax.Array,
    p: jax.Array,
    dangling: jax.Array,
    *,
    n: int,
    alpha: jax.Array | float = 0.1,
    tol: float = 1e-6,
    max_iter: int = 50,
    sorted_by_dst: bool = True,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Iterate to an L1 fixed point; returns ``(t, iterations,
    residual)``.  ``tol <= 0`` runs exactly ``max_iter`` steps (the
    benchmarking mode — fixed work, no early exit).  ``alpha`` is a
    traced operand so damping sweeps reuse one compiled kernel."""

    def cond(state):
        t, prev, it = state
        resid = jnp.sum(jnp.abs(t - prev))
        return (it < max_iter) & ((it == 0) | (resid > tol))

    def body(state):
        t, _, it = state
        t_new = power_step_coo(
            src, dst, w, t, p, dangling, alpha, n=n, sorted_by_dst=sorted_by_dst
        )
        return (t_new, t, it + 1)

    init = (t0, jnp.full_like(t0, jnp.inf), jnp.array(0, jnp.int32))
    if tol <= 0:
        t, prev, it = lax.fori_loop(
            0, max_iter, lambda _, s: body(s), init
        )
    else:
        t, prev, it = lax.while_loop(cond, body, init)
    return t, it, jnp.sum(jnp.abs(t - prev))
