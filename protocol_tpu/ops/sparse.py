"""Sparse trust kernels: COO transpose-SpMV power iteration with
pre-trust damping.

The scaled generalization of the reference's 5×5 loop (SURVEY.md §5
"long-context" note): the local-trust matrix C is an edge list, and one
power step is

    t' = (1−α)·(Cᵀt + (Σ_{i dangling} t_i)·p) + α·p

— the EigenTrust paper's damped iteration, where p is the pre-trust
vector and dangling rows (peers with no valid outgoing scores) donate
their mass to p, the at-scale analog of filter_peers' redistribution
(circuit/src/native.rs:200-228).

TPU-first design notes: edges are pre-sorted by destination so the
gather-multiply-reduce lowers to ``segment_sum`` with
``indices_are_sorted=True`` (sequential HBM traffic, no random scatter);
the iteration runs under ``lax.while_loop`` with an L1 residual bound so
convergence detection happens on-device (no host sync per step).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..analysis.budget import (
    CommBudget,
    GatherBudget,
    KernelBudget,
    MemBudget,
    declare,
    declare_comm,
    declare_mem,
)


def _compensated_cumsum(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Inclusive prefix sum in double-single (hi, lo) arithmetic via
    ``lax.associative_scan`` with a TwoSum combiner.

    A plain f32 cumsum over tens of millions of edges accumulates
    O(eps·√E) absolute error, which then cancels catastrophically when
    differencing at row pointers for small rows.  Tracking the rounding
    error in a second f32 lane recovers ~f64 accuracy while staying in
    the TPU's fast vector path (device f64 is emulated and slow)."""

    def two_sum(a, b):
        a_hi, a_lo = a
        b_hi, b_lo = b
        s = a_hi + b_hi
        bb = s - a_hi
        err = (a_hi - (s - bb)) + (b_hi - bb)
        return s, a_lo + b_lo + err

    hi, lo = lax.associative_scan(two_sum, (x, jnp.zeros_like(x)))
    return hi, lo


#: Edges per cumsum block in the hierarchical row-sum.  Within-block
#: prefix sums run as one vectorized cumsum along the minor axis; only
#: the ~E/2048 block totals need the compensated scan.
_ROWSUM_BLOCK = 2048


def _ds_add(ah, al, bh, bl):
    """Double-single addition (TwoSum + renormalize)."""
    s = ah + bh
    v = s - ah
    e = (ah - (s - v)) + (bh - v)
    e = e + al + bl
    hi = s + e
    lo = e - (hi - s)
    return hi, lo


def _ds_cumsum_axis1(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Inclusive double-single prefix sum along axis 1 via Hillis-Steele
    (log2(B) shifted vector adds — no sequential scan anywhere)."""
    hi, lo = x, jnp.zeros_like(x)
    b = x.shape[1]
    shift = 1
    while shift < b:
        sh = jnp.pad(hi, ((0, 0), (shift, 0)))[:, :-shift]
        sl = jnp.pad(lo, ((0, 0), (shift, 0)))[:, :-shift]
        hi, lo = _ds_add(hi, lo, sh, sl)
        shift <<= 1
    return hi, lo


def rowsum_sorted(contrib: jax.Array, row_ptr: jax.Array) -> jax.Array:
    """Per-row sums of dst-sorted edge contributions via a hierarchical
    double-single prefix sum: ``out[j] = sum(contrib[row_ptr[j] :
    row_ptr[j+1]])``.

    TPU scatter (what ``segment_sum`` lowers to) serializes on
    destination indices even when they are sorted.  Measured on the
    v5e at full bench scale (1M peers / 50M edges, 40 iters,
    PERF.md §1): the end-to-end COO
    segment_sum convergence runs 42.4 s vs 17.9 s for this cumsum
    formulation (2.4×); the op-level gap is larger at smaller scales
    (7.5× end-to-end at 200K peers / 10M edges).  Within each
    2048-edge block the prefix runs as a
    Hillis-Steele scan in (hi, lo) compensated arithmetic (vectorized
    over all blocks at once); block totals get the TwoSum
    ``associative_scan``; row sums difference the hi/lo lanes
    separately so the hi cancellation stays exact (Sterbenz) and the
    residual lives in lo."""
    e = contrib.shape[0]
    b = _ROWSUM_BLOCK
    n_blocks = -(-e // b)
    # jnp.pad, not zeros().at[:e].set(): the update-slice form lowers
    # to a real XLA scatter, which would break the scatter-free budget
    # the analyzer pins on the CSR/windowed steps (analysis/budget.py).
    padded = jnp.pad(contrib, (0, n_blocks * b - e))
    wh, wl = _ds_cumsum_axis1(padded.reshape(n_blocks, b))
    hi_in, lo_in = _compensated_cumsum(wh[:, -1] + wl[:, -1])
    # Exclusive block prefixes.
    zero = jnp.zeros(1, contrib.dtype)
    bhi = jnp.concatenate([zero, hi_in[:-1]])
    blo = jnp.concatenate([zero, lo_in[:-1]])
    # Inclusive prefix at index i-1 for every row pointer (i=0 -> 0).
    i = row_ptr - 1
    blk = jnp.clip(i // b, 0, n_blocks - 1)
    off = jnp.clip(i % b, 0, b - 1)
    ph, pl = _ds_add(bhi[blk], blo[blk], wh[blk, off], wl[blk, off])
    ph = jnp.where(i < 0, 0.0, ph)
    pl = jnp.where(i < 0, 0.0, pl)
    return (ph[1:] - ph[:-1]) + (pl[1:] - pl[:-1])


def power_step_csr(
    src: jax.Array,
    row_ptr: jax.Array,
    w: jax.Array,
    t: jax.Array,
    p: jax.Array,
    dangling: jax.Array,
    alpha: jax.Array | float,
) -> jax.Array:
    """One damped step in the gather-only CSR formulation:
    ``cᵀt[j] = rowsum_sorted(w · t[src], row_ptr)`` — the fast path for
    dst-sorted edge lists (no scatter anywhere)."""
    ct = rowsum_sorted(w * t[src], row_ptr)
    dangling_mass = jnp.sum(t * dangling)
    t_new = (1.0 - alpha) * (ct + dangling_mass * p) + alpha * p
    return t_new / jnp.sum(t_new)


def run_power_iteration(
    step_fn, t0: jax.Array, *, tol: float, max_iter: int,
    record_residuals: bool = False,
):
    """Shared on-device convergence driver: iterate ``step_fn`` under
    while_loop until the L1 residual drops below ``tol`` (or fori_loop
    for exactly ``max_iter`` fixed steps when ``tol <= 0``, the
    benchmark mode).  Used by every sparse/sharded convergence kernel so
    early-exit semantics can't drift between formulations.

    With ``record_residuals`` the loop additionally carries a
    ``(max_iter,)`` residual-history vector and writes each iteration's
    L1 residual into it *device-side* (``lax.dynamic_update_slice`` on
    the carry — not a scatter, not a callback, no host sync; the
    telemetry contract ``tests/test_obs.py`` pins against the jaxpr),
    returning ``(t, iterations, residual, history)``; callers fetch the
    history ONCE after convergence and slice ``history[:iterations]``.
    The score arithmetic is the identical op sequence either way, so
    instrumented and uninstrumented runs are bit-identical."""

    def cond(state):
        it = state[2]
        if record_residuals:
            # The body already reduced this iteration's residual for
            # the history write; reuse the carried scalar instead of
            # re-reducing — identical value, one O(n) pass per
            # iteration either way.
            resid = state[4]
        else:
            t, prev = state[0], state[1]
            resid = jnp.sum(jnp.abs(t - prev))
        return (it < max_iter) & ((it == 0) | (resid > tol))

    def body(state):
        t, _, it = state[:3]
        t_new = step_fn(t)
        if not record_residuals:
            return (t_new, t, it + 1)
        resid = jnp.sum(jnp.abs(t_new - t))
        hist = lax.dynamic_update_index_in_dim(state[3], resid, it, 0)
        return (t_new, t, it + 1, hist, resid)

    init = (t0, jnp.full_like(t0, jnp.inf), jnp.array(0, jnp.int32))
    if record_residuals:
        init = init + (
            jnp.zeros(max_iter, t0.dtype),
            jnp.array(jnp.inf, t0.dtype),
        )
    if tol <= 0:
        out = lax.fori_loop(0, max_iter, lambda _, s: body(s), init)
    else:
        out = lax.while_loop(cond, body, init)
    t, prev, it = out[:3]
    if record_residuals:
        return t, it, out[4], out[3]
    return t, it, jnp.sum(jnp.abs(t - prev))


@partial(
    jax.jit,
    static_argnames=("tol", "max_iter", "record_residuals"),
    donate_argnames=("t0",),
)
def converge_csr(
    src: jax.Array,
    row_ptr: jax.Array,
    w: jax.Array,
    t0: jax.Array,
    p: jax.Array,
    dangling: jax.Array,
    *,
    alpha: jax.Array | float = 0.1,
    tol: float = 1e-6,
    max_iter: int = 50,
    record_residuals: bool = False,
) -> tuple[jax.Array, ...]:
    """CSR/cumsum analog of ``converge_sparse``.  ``t0`` is donated:
    the iteration consumes the initial vector in place (4 MB saved at
    the 1M-peer shape), so callers must pass a fresh buffer.
    ``record_residuals`` appends the device-side residual history to
    the returned tuple (see ``run_power_iteration``)."""
    return run_power_iteration(
        lambda t: power_step_csr(src, row_ptr, w, t, p, dangling, alpha),
        t0,
        tol=tol,
        max_iter=max_iter,
        record_residuals=record_residuals,
    )


def power_step_coo(
    src: jax.Array,
    dst: jax.Array,
    w: jax.Array,
    t: jax.Array,
    p: jax.Array,
    dangling: jax.Array,
    alpha: jax.Array | float,
    *,
    n: int,
    sorted_by_dst: bool = True,
) -> jax.Array:
    """One damped transpose-SpMV step (edge arrays may be zero-padded:
    pad edges with w=0)."""
    contrib = w * t[src]
    ct = jax.ops.segment_sum(
        contrib, dst, num_segments=n, indices_are_sorted=sorted_by_dst
    )
    dangling_mass = jnp.sum(t * dangling)
    t_new = (1.0 - alpha) * (ct + dangling_mass * p) + alpha * p
    # L1 renorm guards against floating-point drift over many iterations.
    return t_new / jnp.sum(t_new)


@partial(
    jax.jit,
    static_argnames=("n", "tol", "max_iter", "sorted_by_dst", "record_residuals"),
    donate_argnames=("t0",),
)
def converge_sparse(
    src: jax.Array,
    dst: jax.Array,
    w: jax.Array,
    t0: jax.Array,
    p: jax.Array,
    dangling: jax.Array,
    *,
    n: int,
    alpha: jax.Array | float = 0.1,
    tol: float = 1e-6,
    max_iter: int = 50,
    sorted_by_dst: bool = True,
    record_residuals: bool = False,
) -> tuple[jax.Array, ...]:
    """Iterate to an L1 fixed point; returns ``(t, iterations,
    residual)``.  ``tol <= 0`` runs exactly ``max_iter`` steps (the
    benchmarking mode — fixed work, no early exit).  ``alpha`` is a
    traced operand so damping sweeps reuse one compiled kernel.
    ``t0`` is donated — pass a fresh buffer.  ``record_residuals``
    appends the device-side residual history to the returned tuple."""
    return run_power_iteration(
        lambda t: power_step_coo(
            src, dst, w, t, p, dangling, alpha, n=n, sorted_by_dst=sorted_by_dst
        ),
        t0,
        tol=tol,
        max_iter=max_iter,
        record_residuals=record_residuals,
    )


# ---------------------------------------------------------------------------
# Pinned kernel invariants (PERF.md §9) — checked per step by
# `python -m protocol_tpu.analysis` against the traced jaxpr.
# ---------------------------------------------------------------------------

#: COO segment-sum step: one random ``t[src]`` gather; the scatter-add
#: is the formulation (segment_sum) and is capped at exactly one.
declare(
    KernelBudget(
        backend="tpu-sparse",
        max_random_gathers=1,
        max_scatters=1,
        gather_budgets=(GatherBudget(dim="edges", max_total=1, max_random=1),),
        donated_args=("t0",),
        notes="segment_sum SpMV: 1 random E-gather + 1 sorted scatter-add",
    )
)

#: Gather-only CSR/cumsum step: one random ``t[src]`` gather plus the
#: four (n+1)-sized block-prefix lookups of ``rowsum_sorted`` — and no
#: scatter anywhere (the formulation's reason to exist; PERF.md §1
#: measured segment_sum 2.4× slower end-to-end at the bench shape).
declare(
    KernelBudget(
        backend="tpu-csr",
        max_random_gathers=5,
        max_scatters=0,
        gather_budgets=(GatherBudget(dim="edges", max_total=1, max_random=1),),
        donated_args=("t0",),
        notes="scatter-free CSR: 1 random E-gather + 4 rowsum pointer reads",
    )
)

# -- communication budgets (PERF.md §15, graftlint pass 8) ------------------
# Single-device steps: zero collectives, zero host round-trips, and the
# t0 donation of the jit entry must survive into the compiled module's
# input_output_alias table (a dropped alias doubles peak HBM at 1M
# peers and ships silently — the jaxpr cannot see it).

declare_comm(
    CommBudget(
        backend="tpu-sparse",
        donated_args=("t0",),
        notes="single-device segment-sum loop: no wire, no host traffic",
    )
)

declare_comm(
    CommBudget(
        backend="tpu-csr",
        donated_args=("t0",),
        notes="single-device CSR/cumsum loop: no wire, no host traffic",
    )
)

# -- memory budgets (PERF.md §19, graftlint pass 12) ------------------------
# Measured from the buffer assignment at the analyzer's compile scale
# (N=1024/E=4073); the committed slack is below a 4 B/edge live
# temporary, pinned by test.  The single-device kernels' E-sized
# working vectors (the ``w * t[src]`` contribution stream and its
# reduction passes) are declared in the transient_n coefficient: at
# the pinned compile scale E ≈ 4N, and the slack test keeps the
# coefficient honest — a SECOND edge-sized live buffer busts it.

declare_mem(
    MemBudget(
        backend="tpu-sparse",
        resident_edge_bytes=12.0,  # src + dst + w
        resident_n=12.0,  # t0 + p + dangling
        resident_const=4096.0,
        transient_n=36.0,  # contribution stream + segment_sum passes
        transient_const=8192.0,
        donated_args=("t0",),
        notes="segment-sum SpMV: COO triplet resident, E-working set in "
        "the scatter-add loop",
    )
)

declare_mem(
    MemBudget(
        backend="tpu-csr",
        resident_edge_bytes=8.0,  # src + w (row_ptr rides resident_n)
        resident_n=16.0,  # t0 + p + dangling + (n+1) row pointers
        resident_const=4096.0,
        transient_n=445.0,  # contribution stream + cumsum scan levels
        transient_const=8192.0,
        donated_args=("t0",),
        notes="scatter-free CSR: the compensated-cumsum rowsum streams "
        "log-depth scan levels over the contribution vector (E ~ 4N at "
        "the pinned scale)",
    )
)
