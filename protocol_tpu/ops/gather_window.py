"""Windowed Pallas gather: score-table lookups from VMEM using only the
Mosaic primitives that compile on this toolchain.

PERF.md §1 establishes that XLA's TPU gather runs at ~7 cycles/element
(386 ms per 50M-edge iteration, 86 % of the bench step) and that
Mosaic's general cross-vreg dynamic gather crashes the compiler.  What
*does* compile: dynamic sublane slicing of a VMEM ref, range-8 sublane
`take_along_axis`, range-128 lane `take_along_axis`, broadcasts, and
selects.  This kernel composes exactly those into a windowed gather:

- Host side (`bucket_by_window`, one-time per graph): edges are
  grouped so every 1024-edge vreg-row shares one 1024-entry window of
  the table (`src // 1024`); rows are padded with window-local zeros
  and a weight mask.
- Kernel side (`gather_windowed`): the 4 MB score table lives in VMEM
  as (8192, 128); per vreg-row the kernel dynamic-slices the (8, 128)
  window and resolves the 1024 local indices with an 8-way
  broadcast/lane-gather/select chain (~30 vreg ops per 1024 edges).

The output is in *bucket order*, not dst order — PERF.md §1 documents
why that prevents fusing this kernel into the full CSR pipeline (the
rowsum needs dst order and the bridging permutation is itself a random
gather).  The kernel stands as the best-achievable custom gather on
this toolchain, and becomes directly usable if a future Mosaic fixes
cross-vreg `dynamic_gather` (then the bucketing constraint drops).

Correctness is validated in interpret mode on CPU (tests); wall-clock
on the real chip is queued on TPU availability (PERF.md §6).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

#: Window width in table entries: one (8, 128) VMEM tile.
WINDOW = 1024
#: Edges per vreg-row (must equal WINDOW for the two-step resolve).
ROW = 1024
#: Vreg-rows per grid step.
BLOCK_ROWS = 64


def bucket_by_window(src: np.ndarray, w: np.ndarray, table_size: int | None = None) -> dict:
    """Group edges so each 1024-edge vreg-row shares one src window.

    Returns arrays shaped for ``gather_windowed`` plus the mapping back
    to input edges: for the k-th edge of the window-sorted order,
    ``contrib_input[order[k]] = contrib_bucketed[out_pos[k]]`` —
    ``out_pos`` accounts for the per-window padding, which carries
    weight 0.
    """
    e = src.shape[0]
    if e == 0:
        raise ValueError("no edges to bucket")
    if table_size is not None and (
        int(src.min()) < 0 or int(src.max()) >= table_size
    ):
        # Out-of-range (or negative) indices would be silently clamped
        # by the kernel's dynamic slice into a wrong but in-bounds
        # window; must survive python -O, so no assert.
        raise ValueError("src index outside [0, table_size)")
    window = src.astype(np.int64) // WINDOW
    order = np.argsort(window, kind="stable").astype(np.int64)
    sorted_win = window[order]
    # Rows per window bucket, each padded to a full vreg-row.
    uniq, counts = np.unique(sorted_win, return_counts=True)
    rows_per = -(-counts // ROW)
    total_rows = int(rows_per.sum())
    # Pad to the grid's block granularity.
    total_rows = -(-total_rows // BLOCK_ROWS) * BLOCK_ROWS
    local = np.zeros(total_rows * ROW, np.int32)
    weight = np.zeros(total_rows * ROW, np.float32)
    out_pos = np.zeros(e, np.int64)  # bucketed position of input edge order[k]
    wid = np.zeros(total_rows, np.int32)
    row = 0
    off = 0
    for u, c in zip(uniq, counts):
        idx = order[off : off + c]
        base = row * ROW
        local[base : base + c] = (src[idx] % WINDOW).astype(np.int32)
        weight[base : base + c] = w[idx]
        out_pos[off : off + c] = base + np.arange(c)
        nrows = -(-c // ROW)
        wid[row : row + nrows] = u
        row += nrows
        off += c
    return {
        "local": local.reshape(total_rows * 8, 128),
        "weight": weight.reshape(total_rows * 8, 128),
        "wid": wid,
        "order": order,
        "out_pos": out_pos,
        "n_rows": total_rows,
    }


def _kernel(wid_ref, t_ref, local_ref, w_ref, out_ref):
    """One grid step: BLOCK_ROWS vreg-rows of 1024 edges each.

    ``wid_ref`` is the scalar-prefetch ref (SMEM) of the FULL wid
    array — dynamic-slice starts must come from scalar memory, not a
    VMEM vector load, to lower on Mosaic."""
    blk = pl.program_id(0)
    for v in range(BLOCK_ROWS):
        wid = wid_ref[blk * BLOCK_ROWS + v]
        win = t_ref[pl.ds(wid * 8, 8), :]  # (8,128) window slice
        lidx = local_ref[pl.ds(v * 8, 8), :]
        sub = lidx // 128
        lane = lidx % 128
        acc = jnp.zeros((8, 128), jnp.float32)
        for k in range(8):
            rowk = jnp.broadcast_to(win[k : k + 1, :], (8, 128))
            g = jnp.take_along_axis(rowk, lane, axis=1)
            acc = jnp.where(sub == k, g, acc)
        out_ref[pl.ds(v * 8, 8), :] = acc * w_ref[pl.ds(v * 8, 8), :]


@partial(jax.jit, static_argnames=("n_rows", "interpret"))
def gather_windowed(
    wid: jax.Array,
    table: jax.Array,
    local: jax.Array,
    weight: jax.Array,
    *,
    n_rows: int,
    interpret: bool = False,
) -> jax.Array:
    """``out[r, j] = weight[r, j] * table[wid[r//8]*1024 + local[r, j]]``
    with the table resident in VMEM as (8192, 128)."""
    assert table.size % WINDOW == 0
    assert n_rows % BLOCK_ROWS == 0, (
        f"n_rows must be a multiple of {BLOCK_ROWS} (bucket_by_window pads "
        "to this); a partial trailing block would be silently unwritten"
    )
    t2d = table.reshape(-1, 128)
    n_blocks = n_rows // BLOCK_ROWS
    from jax.experimental.pallas import tpu as pltpu

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec(t2d.shape, lambda i, wid_ref: (0, 0)),
            pl.BlockSpec((BLOCK_ROWS * 8, 128), lambda i, wid_ref: (i, 0)),
            pl.BlockSpec((BLOCK_ROWS * 8, 128), lambda i, wid_ref: (i, 0)),
        ],
        out_specs=pl.BlockSpec((BLOCK_ROWS * 8, 128), lambda i, wid_ref: (i, 0)),
    )
    return pl.pallas_call(
        _kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_rows * 8, 128), jnp.float32),
        interpret=interpret,
    )(wid, t2d, local, weight)
