"""Windowed Pallas gather and the fused fixed-slot pipeline built on it.

PERF.md §1 establishes that XLA's TPU gather runs at ~7 cycles/element
(386 ms per 50M-edge iteration, 86 % of the bench step) and that
Mosaic's general cross-vreg dynamic gather crashes the compiler.  What
*does* compile: dynamic sublane slicing of a VMEM ref, range-8 sublane
`take_along_axis`, range-128 lane `take_along_axis`, broadcasts, and
selects.  This module composes exactly those into a windowed gather and
— new in PERF.md §7 — a full power step that consumes it:

- Host side (`bucket_by_window`, one-time per graph): edges are
  grouped so every 1024-edge vreg-row shares one 1024-entry window of
  the table (`src // 1024`); rows are padded with window-local zeros
  and a weight mask.  The loop-free formulation (argsort + cumulative
  counts) buckets 50M edges in seconds, not the ~34 s of the original
  per-window Python loop (PERF.md §6).
- Kernel side (`gather_windowed`): the ≤4 MB score table lives in VMEM
  as (rows, 128); per vreg-row the kernel dynamic-slices the (8, 128)
  window and resolves the 1024 local indices with an 8-way
  broadcast/lane-gather/select chain (~30 vreg ops per 1024 edges).
- Bridge side (`power_step_windowed`, PERF.md §7-8): the kernel output
  is in *bucket order*, not the dst order the rowsum needs.  Bridging
  per-edge would itself be an O(E) random gather (the circularity that
  stalled PERF.md §1).  Instead `bucket_by_window` additionally sorts
  each window's edges by dst and emits a static single-pass reduction
  plan (`bridge_partials`): the (hi, lo) lanes of the row-local
  compensated prefix sum are interleaved into one (slots, 2) array so a
  single 2-wide slice gather at the run *ends* — in bucket order, where
  the end slots are strictly increasing, so the read streams — fetches
  both lanes of every boundary at once; each run's start prefix is just
  the *previous gathered element* (runs are consecutive within a
  vreg-row), so the differencing is a shift, not a second gather; and
  the one host-precomputed dst permutation of the resulting
  ``n_segments`` partials is the only data-randomly-addressed pass per
  iteration.  Random volume: 1× n_segments (was 4× — hi/lo at both
  boundaries — before the interleave, PERF.md §7 open variable), with
  ``n_segments <= min(E, n_windows · N)``, which the hub-heavy bench
  graph compresses far below E (the plan records the measured ratio).

Correctness is validated in interpret mode on CPU (tests); per-op
wall-clock on the real chip is in PERF.md §6 and the fused-pipeline
projection in §7.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ..analysis.budget import (
    CommBudget,
    GatherBudget,
    KernelBudget,
    MemBudget,
    declare,
    declare_comm,
    declare_mem,
)
from .sparse import _ds_cumsum_axis1, rowsum_sorted, run_power_iteration

try:
    # The C two-pass kernel underneath scipy's COO→CSR conversion; the
    # coo_matrix wrapper around it re-validates indices with two extra
    # O(E) passes (~0.5 s at 50M edges on the bench host).
    from scipy.sparse import _sparsetools as _scipy_sparsetools
except ImportError:  # pragma: no cover - bench/prod images carry scipy
    _scipy_sparsetools = None

#: Window width in table entries: one (8, 128) VMEM tile.
WINDOW = 1024
#: Edges per vreg-row (must equal WINDOW for the two-step resolve).
ROW = 1024
#: Vreg-rows per grid step.
BLOCK_ROWS = 64


#: log2(WINDOW): window ids and window-local indices are shifts/masks.
_WIN_BITS = 10


def _counting_sort(
    key: np.ndarray, n_keys: int, payload: np.ndarray | None = None
) -> tuple[np.ndarray, np.ndarray, np.ndarray | None]:
    """Stable counting sort by a small-domain non-negative integer key:
    returns ``(order, counts, sorted_payload)`` where ``order`` is the
    ``argsort(key, kind="stable")`` permutation and ``counts`` the
    per-key histogram.

    numpy's stable argsort costs ~8-10 s at 50M elements on the bench
    host — most of the old 34 s bucketing loop's replacement budget.
    scipy's COO→CSR conversion is the same counting sort as a two-pass
    C loop, O(E + n_keys): rows are the keys, columns the positions, so
    the CSR column indices come out key-grouped in stable position
    order, and the CSR data array carries ``payload`` through the sort
    without a separate O(E) random gather.  Falls back to numpy where
    scipy is missing.
    """
    e = key.shape[0]
    coo_tocsr = getattr(_scipy_sparsetools, "coo_tocsr", None)
    if coo_tocsr is None or e >= 2**31 or n_keys >= 2**31:  # pragma: no cover
        order = np.argsort(key, kind="stable")
        counts = np.bincount(key, minlength=n_keys)
        return order, counts, None if payload is None else payload[order]
    data = (
        np.ascontiguousarray(payload)
        if payload is not None
        else np.empty(e, np.int8)
    )
    key = np.ascontiguousarray(key, dtype=np.int32)
    indptr = np.empty(n_keys + 1, np.int32)
    order = np.empty(e, np.int32)
    sorted_data = np.empty(e, data.dtype)
    coo_tocsr(
        n_keys, e, e, key, np.arange(e, dtype=np.int32), data,
        indptr, order, sorted_data,
    )
    return order, np.diff(indptr), sorted_data if payload is not None else None


def _pack_lanes(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Pack two equal-length 4-byte arrays into one int64 array
    (bit-preserving), so one counting-sort pass carries both payloads
    at once instead of paying two O(E) permutations."""
    lanes = np.empty((a.shape[0], 2), np.int32)
    lanes[:, 0] = a if a.dtype == np.int32 else a.view(np.int32)
    lanes[:, 1] = b if b.dtype == np.int32 else b.view(np.int32)
    return lanes.view(np.int64)[:, 0]


def _unpack_lanes(packed: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Bit-exact int32 lane views of a ``_pack_lanes`` array."""
    v = packed.view(np.int32).reshape(-1, 2)
    return v[:, 0], v[:, 1]


def bucket_by_window(
    src: np.ndarray,
    w: np.ndarray,
    table_size: int | None = None,
    *,
    dst: np.ndarray | None = None,
    n_dst: int | None = None,
    spare_rows: int | None = 0,
) -> dict:
    """Group edges so each 1024-edge vreg-row shares one src window.

    Returns arrays shaped for ``gather_windowed`` plus the mapping back
    to input edges: for the k-th edge of the window-sorted order,
    ``contrib_input[order[k]] = contrib_bucketed[out_pos[k]]`` —
    ``out_pos`` accounts for the per-window padding, which carries
    weight 0.

    With ``dst`` (and ``n_dst``) given, edges are additionally sorted by
    destination *within* each window and the dict gains the static
    single-pass reduction plan (PERF.md §7-8): ``seg_end`` flat end
    slots of every per-(vreg-row, dst) run in *bucket order* (strictly
    increasing — the boundary read streams), ``seg_first`` flagging
    row-leading runs (whose start prefix is an exact zero),
    ``seg_perm`` the bucket→dst permutation of the run partials, and
    ``dst_ptr`` delimiting each destination's runs in permuted order —
    everything ``power_step_windowed`` needs to reduce bucket-order
    contributions to a dense Cᵀt with one n_segments-sized random pass.

    Fully vectorized: stable counting sorts (scipy COO→CSR, O(E)) plus
    cumulative-count placement — the previous per-window Python loop
    was ~34 s at 50M edges; this formulation is bounded by the sort's
    payload movement (<5 s measured, PERF.md §7).

    ``spare_rows`` reserves that many zero-weight vreg-rows past the
    packed data (on top of the BLOCK_ROWS grid rounding) — headroom
    ``WindowPlan.apply_delta`` allocates overflow rows from (and where
    the inert segment-table pads end), so a window outgrowing its
    original padding doesn't force a full rebuild (PERF.md §11).
    None sizes it adaptively: one grid block or ~6% of the data rows,
    whichever is larger.
    """
    e = src.shape[0]
    if e == 0:
        raise ValueError("no edges to bucket")
    src = np.asarray(src, dtype=np.int32)
    w = np.asarray(w, dtype=np.float32)
    smin, smax = int(src.min()), int(src.max())
    if smin < 0 or (table_size is not None and smax >= table_size):
        # Out-of-range (or negative) indices would be silently clamped
        # by the kernel's dynamic slice into a wrong but in-bounds
        # window; must survive python -O, so no assert.
        raise ValueError("src index outside [0, table_size)")
    n_src = table_size if table_size is not None else smax + 1
    n_windows = -(-n_src // WINDOW)

    if dst is None:
        o1, s1, w1, d1 = None, src, w, None
    else:
        if n_dst is None:
            raise ValueError("n_dst is required when dst is given")
        if int(dst.min()) < 0 or int(dst.max()) >= n_dst:
            raise ValueError("dst index outside [0, n_dst)")
        dst = np.asarray(dst, dtype=np.int32)
        # Within-window dst order = one stable counting sort by window
        # over a dst-sorted edge sequence.  The node/bench graphs arrive
        # dst-sorted (``TrustGraph.sorted_by_dst``), so the usual cost
        # is a single O(E) pass; unsorted input pays one extra
        # dst-keyed pass (LSD radix), with (src, w) riding the payload
        # lanes so no separate O(E) random gathers are needed.
        if np.any(dst[1:] < dst[:-1]):
            o1, dst_counts, packed = _counting_sort(
                dst, n_dst, payload=_pack_lanes(src, w)
            )
            if packed is None:  # pragma: no cover - numpy fallback
                s1, w1 = src[o1], w[o1]
            else:
                s1, w1raw = _unpack_lanes(packed)
                w1 = w1raw.view(np.float32)
            d1 = np.repeat(np.arange(n_dst, dtype=np.int32), dst_counts)
        else:
            o1, s1, w1, d1 = None, src, w, dst
    # The one window-keyed counting sort.  The small key domain
    # (E/1024 windows) matters: the placement pass advances one write
    # pointer per key, so with ~1000 keys the writes stream (measured
    # ~6× faster than a src-keyed pass whose 1M pointers scatter every
    # write to a cold cache line).  (local, w) ride the payload lanes;
    # ``order`` is the CSR column indices, for free.
    window = s1 >> _WIN_BITS
    order, counts, data = _counting_sort(
        window, n_windows, payload=_pack_lanes(s1 & (WINDOW - 1), w1)
    )
    if data is None:  # pragma: no cover - numpy fallback
        local_sorted = (s1 & (WINDOW - 1))[order]
        w_sorted = w1[order]
    else:
        local_sorted, wraw = _unpack_lanes(data)
        w_sorted = wraw.view(np.float32)
    ds = d1[order] if d1 is not None else None
    if o1 is not None:
        order = o1[order]

    # Rows per window, each padded to a full vreg-row; grid padded to
    # block granularity.  Windows with no edges contribute zero rows.
    rows_per = -(-counts // ROW)
    row_offset = np.concatenate([[0], np.cumsum(rows_per)]).astype(np.int64)
    n_data_rows = int(row_offset[-1])
    if spare_rows is None:
        spare_rows = max(BLOCK_ROWS, n_data_rows // 16)
    total_rows = -(-(n_data_rows + spare_rows) // BLOCK_ROWS) * BLOCK_ROWS
    # Flat slot of each window-sorted edge: consecutive within its
    # window, starting at the window's first (fresh) vreg-row.  One
    # repeat over the per-window pad shift; the scatter below is
    # monotonic (sorted destinations), so it streams.  int32 throughout:
    # slot count < 2³¹ is already implied by the int32 edge arrays, and
    # the narrower lanes halve this pass's memory traffic (measured 6×
    # on the bench host).
    win_off = np.concatenate([[0], np.cumsum(counts)[:-1]])
    out_pos = np.repeat(
        (row_offset[:-1] * ROW - win_off).astype(np.int32), counts
    ) + np.arange(e, dtype=np.int32)
    local = np.zeros(total_rows * ROW, np.int32)
    weight = np.zeros(total_rows * ROW, np.float32)
    local[out_pos] = local_sorted
    weight[out_pos] = w_sorted
    wid = np.zeros(total_rows, np.int32)
    wid[:n_data_rows] = np.repeat(np.arange(n_windows, dtype=np.int32), rows_per)
    result = {
        "local": local.reshape(total_rows * 8, 128),
        "weight": weight.reshape(total_rows * 8, 128),
        "wid": wid,
        "order": order,
        "out_pos": out_pos,
        "n_rows": total_rows,
        "n_data_rows": n_data_rows,
        "row_offset": row_offset,
    }
    if ds is None:
        return result

    # -- static single-pass reduction plan (PERF.md §7-8) ---------------
    # Segments are maximal same-dst slot runs within one vreg-row: edges
    # are dst-sorted inside each window and packed into consecutive
    # slots, so a run breaks only at a dst change or a row boundary (a
    # window change always starts a fresh row, so it needs no term).
    brk = np.empty(e, bool)
    brk[0] = True
    brk[1:] = (ds[1:] != ds[:-1]) | (out_pos[1:] & (ROW - 1) == 0)
    end_mask = np.empty(e, bool)
    end_mask[-1] = True
    end_mask[:-1] = brk[1:]
    seg_dst = ds[brk]
    # The boundary table stays in BUCKET order: run end slots are then
    # strictly increasing, so the device's one boundary gather reads
    # monotonically (streams) instead of jumping dst-to-dst through the
    # prefix-sum array.  A run's start prefix is the previous run's end
    # prefix (runs are consecutive within a row) — an on-device shift —
    # except at row-leading runs, where it is an exact zero.
    seg_end = np.ascontiguousarray(out_pos[end_mask])
    seg_first = np.ascontiguousarray(out_pos[brk] & (ROW - 1) == 0)
    # Host-side dst sort of the segment table becomes a single stored
    # permutation: the device applies it once to the n_segments run
    # partials — the only data-randomly-addressed pass per iteration.
    seg_perm, seg_counts, _ = _counting_sort(seg_dst, n_dst)
    dst_ptr = np.zeros(n_dst + 1, np.int64)
    np.cumsum(seg_counts, out=dst_ptr[1:])
    result.update(
        seg_end=seg_end,
        seg_first=seg_first,
        seg_perm=seg_perm.astype(np.int32, copy=False),
        dst_ptr=dst_ptr.astype(np.int32),
        seg_dst=np.ascontiguousarray(seg_dst, dtype=np.int32),
        n_segments=int(seg_dst.shape[0]),
    )
    return result


def _kernel(wid_ref, t_ref, local_ref, w_ref, out_ref):
    """One grid step: BLOCK_ROWS vreg-rows of 1024 edges each.

    ``wid_ref`` is the scalar-prefetch ref (SMEM) of the FULL wid
    array — dynamic-slice starts must come from scalar memory, not a
    VMEM vector load, to lower on Mosaic."""
    blk = pl.program_id(0)
    for v in range(BLOCK_ROWS):
        wid = wid_ref[blk * BLOCK_ROWS + v]
        win = t_ref[pl.ds(wid * 8, 8), :]  # (8,128) window slice
        lidx = local_ref[pl.ds(v * 8, 8), :]
        sub = lidx // 128
        lane = lidx % 128
        acc = jnp.zeros((8, 128), jnp.float32)
        for k in range(8):
            rowk = jnp.broadcast_to(win[k : k + 1, :], (8, 128))
            g = jnp.take_along_axis(rowk, lane, axis=1)
            acc = jnp.where(sub == k, g, acc)
        out_ref[pl.ds(v * 8, 8), :] = acc * w_ref[pl.ds(v * 8, 8), :]


@partial(jax.jit, static_argnames=("n_rows", "interpret"))
def gather_windowed(
    wid: jax.Array,
    table: jax.Array,
    local: jax.Array,
    weight: jax.Array,
    *,
    n_rows: int,
    interpret: bool = False,
) -> jax.Array:
    """``out[r, j] = weight[r, j] * table[wid[r//8]*1024 + local[r, j]]``
    with the table resident in VMEM as (rows, 128)."""
    assert table.size % WINDOW == 0
    assert n_rows % BLOCK_ROWS == 0, (
        f"n_rows must be a multiple of {BLOCK_ROWS} (bucket_by_window pads "
        "to this); a partial trailing block would be silently unwritten"
    )
    t2d = table.reshape(-1, 128)
    n_blocks = n_rows // BLOCK_ROWS
    from jax.experimental.pallas import tpu as pltpu

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec(t2d.shape, lambda i, wid_ref: (0, 0)),
            pl.BlockSpec((BLOCK_ROWS * 8, 128), lambda i, wid_ref: (i, 0)),
            pl.BlockSpec((BLOCK_ROWS * 8, 128), lambda i, wid_ref: (i, 0)),
        ],
        out_specs=pl.BlockSpec((BLOCK_ROWS * 8, 128), lambda i, wid_ref: (i, 0)),
    )
    return pl.pallas_call(
        _kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_rows * 8, 128), jnp.float32),
        interpret=interpret,
    )(wid, t2d, local, weight)


# ---------------------------------------------------------------------------
# The fused fixed-slot pipeline (PERF.md §7)
# ---------------------------------------------------------------------------


#: WindowPlan on-disk/in-memory layout version.  v1 stored dst-sorted
#: ``seg_start``/``seg_end`` boundary pairs (4 random gathers per
#: iteration); v2 is the interleaved single-pass layout (bucket-order
#: ``seg_end`` + row-leading mask + folded dst permutation, PERF.md §8);
#: v3 adds the host-side delta-update bookkeeping (bucket-order
#: ``seg_dst``, per-window ``row_offset``, the live-row watermark, and
#: the fingerprint lineage chain, PERF.md §11).  Checkpoint-restored
#: plans of any other version are discarded and rebuilt — the same
#: path a fingerprint mismatch takes.
PLAN_VERSION = 3

#: Ancestor fingerprints a delta-updated plan remembers (checkpoint
#: forensics: how many epochs of churn separate this layout from its
#: last from-scratch build).
LINEAGE_DEPTH = 16

#: Device segment tables are padded to a multiple of this, with at
#: least SEG_HEADROOM free entries, so per-epoch deltas that grow the
#: run count slightly keep every device array shape — and therefore
#: the compiled convergence kernel — stable.  Pad runs are inert: they
#: end in the zero-weight spare tail (partial ≡ 0) and the dst
#: permutation parks them beyond ``dst_ptr[n]``, so ``rowsum_sorted``
#: never differences them into any destination (the same trick the
#: sharded partition uses for its per-shard padding).
SEG_QUANTUM = 1024
SEG_HEADROOM = 256


class PlanDeltaError(ValueError):
    """The requested delta cannot be applied to this plan (peer set
    shrank, a deleted edge is absent, or the overflow headroom is
    exhausted) — callers fall back to a full ``build_window_plan``."""


def _pad_segment_tables(
    seg_end: np.ndarray,
    seg_first: np.ndarray,
    seg_dst: np.ndarray,
    *,
    capacity: int,
    n: int,
    n_rows: int,
    n_data_rows: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Pad the live bucket-order run tables to ``capacity`` device
    entries and fold the dst sort: pad runs end at the topmost
    zero-weight spare slots (strictly above every live run, so the
    boundary read stays sorted and their partials are exact zeros) and
    carry sentinel dst ``n``, which the counting sort parks beyond
    ``dst_ptr[n]`` — never reduced into any destination.  Returns
    ``(seg_end, seg_first, seg_perm, dst_ptr)`` at device capacity."""
    s = int(seg_end.shape[0])
    pad = capacity - s
    if pad < 0 or pad > (n_rows - n_data_rows) * ROW:
        raise PlanDeltaError(
            f"segment capacity {capacity} does not fit the spare-slot headroom"
        )
    total_slots = n_rows * ROW
    end = np.concatenate(
        [
            seg_end.astype(np.int64),
            np.arange(total_slots - pad, total_slots, dtype=np.int64),
        ]
    )
    first = np.concatenate([seg_first.astype(bool), np.ones(pad, bool)])
    key = np.concatenate([seg_dst.astype(np.int64), np.full(pad, n, np.int64)])
    perm, counts, _ = _counting_sort(np.ascontiguousarray(key, np.int32), n + 1)
    dst_ptr = np.zeros(n + 1, np.int64)
    np.cumsum(counts[:n], out=dst_ptr[1:])
    return (
        end.astype(np.int32),
        first,
        np.asarray(perm, np.int32),
        dst_ptr.astype(np.int32),
    )


def _segment_capacity(s: int, max_pad_slots: int) -> int:
    """Quantized device capacity for ``s`` live runs: proportional
    growth headroom (churn fragments hub runs into singletons, so the
    live count drifts up by roughly the per-epoch rewire count —
    ~12.5% absorbs several epochs between regrowths), rounded to
    SEG_QUANTUM, clamped to the spare-tail slots actually available
    for pad runs."""
    slack = max(SEG_HEADROOM, s // 8)
    return min(-(-(s + slack) // SEG_QUANTUM) * SEG_QUANTUM, s + max_pad_slots)


@dataclass
class WindowPlan:
    """Static per-graph layout for the fused windowed power step.

    Built once on the host (``build_window_plan``), reused every
    iteration and across epochs while the graph fingerprint matches;
    persisted by ``node/checkpoint.py`` so a node reboot doesn't re-pay
    construction.  Small per-epoch edge churn is folded in by
    ``apply_delta`` (touched windows repacked in place, everything else
    shared) instead of a full rebuild — the ``lineage`` chain records
    the ancestor fingerprints of such delta-updated plans.
    ``order``/``out_pos`` map bucket slots back to input edges — needed
    only by tests and diagnostics, so checkpoints omit them
    (``to_arrays(core_only=True)``); delta-updated plans drop them.
    """

    n: int  # peers (dense output length)
    n_rows: int  # padded vreg-rows
    table_entries: int  # score table padded to a WINDOW multiple
    n_segments: int  # per-(row, dst) runs crossing the bridge
    n_data_rows: int  # live vreg-rows (original packing + delta overflow)
    n_edges: int  # live edges encoded (delta-integrity tripwire)
    wid: np.ndarray  # (n_rows,) int32 window id per vreg-row
    local: np.ndarray  # (n_rows*8, 128) int32 window-local indices
    weight: np.ndarray  # (n_rows*8, 128) f32 slot weights (0 = padding)
    seg_end: np.ndarray  # (S,) int32 last slot of each run, bucket order
    seg_first: np.ndarray  # (S,) bool run is row-leading (start prefix = 0)
    seg_perm: np.ndarray  # (S,) int32 bucket→dst permutation of partials
    dst_ptr: np.ndarray  # (n+1,) int32 run range per destination
    seg_dst: np.ndarray  # (S,) int32 run destination, bucket order (host-side)
    row_offset: np.ndarray  # (n_windows+1,) int64 original rows per window
    fingerprint: str  # graph identity for safe reuse
    version: int = PLAN_VERSION  # layout version (see PLAN_VERSION)
    #: Fingerprints of the plans this one was delta-derived from,
    #: oldest first, capped at LINEAGE_DEPTH; empty for a from-scratch
    #: build.  Persisted with checkpoints (delta provenance).
    lineage: tuple[str, ...] = ()
    order: np.ndarray | None = None  # (E,) bucket position k ← edge order[k]
    out_pos: np.ndarray | None = None  # (E,) slot of edge order[k]

    #: Device operands, in ``converge_windowed`` order — exactly what
    #: crosses the host→HBM boundary.
    _CORE = ("wid", "local", "weight", "seg_end", "seg_first", "seg_perm", "dst_ptr")
    #: Host-only bookkeeping for ``apply_delta`` (persisted, never
    #: shipped to the device).
    _HOST = ("seg_dst", "row_offset")
    _META = ("n", "n_rows", "table_entries", "n_segments", "n_data_rows", "n_edges")

    @property
    def compression(self) -> float:
        """Edge contributions per bridge partial (E / n_segments) —
        how much the run-level reduction shrinks the random-access
        volume vs a per-edge bucket→dst permutation."""
        return self.n_edges / max(self.n_segments, 1)

    @property
    def seg_capacity(self) -> int:
        """Device length of the segment tables: ``n_segments`` live
        runs plus inert pad runs (shape-stability headroom for
        ``apply_delta`` — see SEG_QUANTUM)."""
        return int(self.seg_end.shape[0])

    def device_args(self) -> tuple:
        """Core arrays as device arrays, in ``converge_windowed`` order."""
        return tuple(jnp.asarray(getattr(self, k)) for k in self._CORE)

    def to_arrays(self, *, core_only: bool = True) -> dict:
        """npz-ready mapping (checkpoint format)."""
        out = {k: np.int64(getattr(self, k)) for k in self._META}
        out["version"] = np.int64(self.version)
        out["fingerprint"] = np.bytes_(self.fingerprint.encode())
        out["lineage"] = np.array(list(self.lineage), dtype="S64")
        for k in self._CORE + self._HOST:
            out[k] = getattr(self, k)
        if not core_only and self.order is not None:
            out["order"] = self.order
            out["out_pos"] = self.out_pos
        return out

    @classmethod
    def from_arrays(cls, z) -> "WindowPlan":
        """Rehydrate a persisted plan; raises ``ValueError`` on a stale
        layout version (pre-v2 plans lack ``version`` entirely) so
        callers fall back to a rebuild instead of feeding the device
        mis-shaped boundary arrays."""
        version = int(z["version"]) if "version" in z else 1
        if version != PLAN_VERSION:
            raise ValueError(
                f"window plan layout v{version} is stale (current v{PLAN_VERSION}); rebuild"
            )
        return cls(
            **{k: int(z[k]) for k in cls._META},
            **{k: np.asarray(z[k]) for k in cls._CORE + cls._HOST},
            fingerprint=bytes(z["fingerprint"]).decode(),
            version=version,
            lineage=tuple(bytes(x).decode() for x in z["lineage"])
            if "lineage" in z
            else (),
            order=np.asarray(z["order"]) if "order" in z else None,
            out_pos=np.asarray(z["out_pos"]) if "out_pos" in z else None,
        )

    # -- delta updates (PERF.md §11) ------------------------------------

    def _window_vreg_rows(self, window: int) -> np.ndarray:
        """Live vreg-rows carrying ``window``'s slots, ascending: the
        original contiguous block plus any delta-appended overflow rows
        (overflow lives past ``row_offset[-1]``, identified by wid)."""
        n_orig = int(self.row_offset[-1])
        if window + 1 < len(self.row_offset):
            rows = np.arange(
                self.row_offset[window], self.row_offset[window + 1], dtype=np.int64
            )
        else:
            rows = np.empty(0, np.int64)
        if self.n_data_rows > n_orig:
            tail = np.arange(n_orig, self.n_data_rows, dtype=np.int64)
            rows = np.concatenate([rows, tail[self.wid[tail] == window]])
        return rows

    def _segments_of_rows(self, rows: np.ndarray) -> np.ndarray:
        """Indices into the bucket-order segment table of every run
        living in ``rows`` — seg_end is strictly increasing, so each
        row's runs are one searchsorted slice."""
        end = self.seg_end.astype(np.int64)
        lo = np.searchsorted(end, rows * ROW, side="left")
        hi = np.searchsorted(end, (rows + 1) * ROW - 1, side="right")
        parts = [np.arange(a, b, dtype=np.int64) for a, b in zip(lo, hi) if b > a]
        if not parts:
            return np.empty(0, np.int64)
        return np.concatenate(parts)

    def _edges_of_segments(
        self, idx: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Recover ``(src, dst, w)`` of the edges inside the given runs
        by expanding each run's slot range — the inverse of the packing
        ``bucket_by_window`` performed."""
        if idx.size == 0:
            z = np.empty(0, np.int32)
            return z, z, np.empty(0, np.float32)
        end = self.seg_end.astype(np.int64)
        start = np.where(
            self.seg_first[idx],
            (end[idx] // ROW) * ROW,
            end[np.maximum(idx, 1) - 1] + 1,
        )
        lens = end[idx] - start + 1
        total = int(lens.sum())
        run_of = np.repeat(np.cumsum(lens) - lens, lens)
        slots = np.repeat(start, lens) + (np.arange(total, dtype=np.int64) - run_of)
        dst = np.repeat(self.seg_dst[idx], lens)
        rows = slots // ROW
        src = (
            self.wid[rows].astype(np.int64) * WINDOW
            + self.local.reshape(-1)[slots]
        ).astype(np.int32)
        return src, dst, self.weight.reshape(-1)[slots]

    def recovered_edges(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """The full ``(src, dst, w)`` edge list this plan encodes, in
        slot (bucket) order — the layout-semantics ground truth the
        delta property tests compare against a from-scratch rebuild."""
        return self._edges_of_segments(np.arange(self.n_segments, dtype=np.int64))

    def apply_delta(
        self,
        inserts: tuple[np.ndarray, np.ndarray, np.ndarray] | None,
        deletes: tuple[np.ndarray, np.ndarray] | None,
        *,
        n: int | None = None,
        fingerprint: str,
    ) -> "WindowPlan":
        """Incrementally fold an edge delta into the layout, returning a
        NEW plan (arrays are copied where touched, shared elsewhere —
        the old plan stays valid for the in-flight epoch).

        ``inserts`` is ``(src, dst, w)`` of edges to add (normalized
        weights), ``deletes`` is ``(src, dst)`` of edges to remove; ``n``
        grows the peer set (new peers join with no plan presence until
        an insert names them).  Host-side cost: O(Δ log Δ) sorting over
        the delta plus a repack of the touched windows' slots, then two
        streaming O(S) passes (segment-table splice + the dst counting
        sort behind ``seg_perm``/``dst_ptr``) — far below the full
        rebuild's O(E) counting sorts.  The result's ``fingerprint`` is
        the caller-supplied identity of the post-delta graph and the
        predecessor chain lands in ``lineage``.

        Raises :class:`PlanDeltaError` when the delta cannot be folded
        (peer set shrank, a deleted edge is absent, or a window outgrew
        the spare-row headroom) — callers fall back to
        ``build_window_plan``.
        """
        empty_i = (np.empty(0, np.int32),) * 2 + (np.empty(0, np.float32),)
        ins_src, ins_dst, ins_w = (
            tuple(np.asarray(a) for a in inserts) if inserts is not None else empty_i
        )
        del_src, del_dst = (
            tuple(np.asarray(a, np.int64) for a in deletes)
            if deletes is not None
            else (np.empty(0, np.int64),) * 2
        )
        ins_src = np.asarray(ins_src, np.int64)
        ins_dst = np.asarray(ins_dst, np.int64)
        ins_w = np.asarray(ins_w, np.float32)
        n_new = self.n if n is None else int(n)
        if n_new < self.n:
            raise PlanDeltaError("peer set shrank; rebuild the plan")
        for a in (ins_src, ins_dst, del_src, del_dst):
            if a.size and (int(a.min()) < 0 or int(a.max()) >= n_new):
                raise PlanDeltaError("delta edge index outside [0, n)")
        table_entries = -(-n_new // WINDOW) * WINDOW
        n_windows = table_entries // WINDOW
        row_offset = self.row_offset
        if n_windows + 1 > len(row_offset):
            # New windows own no original rows; overflow allocation
            # below serves them like any outgrown window.
            row_offset = np.concatenate(
                [
                    row_offset,
                    np.full(n_windows + 1 - len(row_offset), row_offset[-1], np.int64),
                ]
            )

        touched = np.unique(np.concatenate([ins_src, del_src]) >> _WIN_BITS)
        wid = self.wid.copy()
        local = self.local.reshape(-1).copy()
        weight = self.weight.reshape(-1).copy()
        n_data_rows = self.n_data_rows

        # Segments whose rows stay untouched survive verbatim; the
        # touched windows' runs are rebuilt below.  Only live runs
        # participate — the inert device pads are regenerated at exit.
        row_window = wid.astype(np.int64).copy()
        row_window[self.n_data_rows :] = -1
        end_live = self.seg_end.astype(np.int64)[: self.n_segments]
        first_live = self.seg_first[: self.n_segments]
        seg_win = row_window[end_live // ROW]
        keep = ~np.isin(seg_win, touched)
        new_end: list[np.ndarray] = [end_live[keep]]
        new_first: list[np.ndarray] = [first_live[keep]]
        new_dst: list[np.ndarray] = [self.seg_dst.astype(np.int64)[keep]]

        iw = ins_src >> _WIN_BITS
        dw = del_src >> _WIN_BITS
        for w in touched.tolist():
            rows_w = self._window_vreg_rows(int(w))
            osrc, odst, ow = self._edges_of_segments(self._segments_of_rows(rows_w))
            # Delete by (src, dst) identity; duplicate edges are a
            # multiset — each delete consumes one instance.
            dm = dw == w
            if dm.any():
                okey = osrc.astype(np.int64) << 32 | odst.astype(np.int64)
                dkey = np.sort(del_src[dm] << 32 | del_dst[dm])
                order = np.argsort(okey, kind="stable")
                sk = okey[order]
                pos = np.searchsorted(sk, dkey, side="left")
                # The i-th duplicate of a delete key consumes the i-th
                # plan instance of that edge.
                grp = np.concatenate([[True], dkey[1:] != dkey[:-1]])
                first = np.nonzero(grp)[0][np.cumsum(grp) - 1]
                take = pos + (np.arange(len(dkey)) - first)
                if take.size and (
                    int(take.max()) >= len(sk) or not (sk[take] == dkey).all()
                ):
                    raise PlanDeltaError("delete names an edge absent from the plan")
                drop = np.zeros(len(okey), bool)
                drop[order[take]] = True
                osrc, odst, ow = osrc[~drop], odst[~drop], ow[~drop]
            im = iw == w
            if im.any():
                osrc = np.concatenate([osrc, ins_src[im].astype(np.int32)])
                odst = np.concatenate([odst, ins_dst[im].astype(np.int32)])
                ow = np.concatenate([ow, ins_w[im]])
            count = osrc.shape[0]
            # Zero the window's slots, then repack dst-sorted from the
            # first row — the run differencing needs gap-free packing.
            if rows_w.size:
                slots_w = (rows_w[:, None] * ROW + np.arange(ROW)[None, :]).reshape(-1)
                local[slots_w] = 0
                weight[slots_w] = 0.0
            if count > rows_w.size * ROW:
                extra = -(-(count - rows_w.size * ROW) // ROW)
                if n_data_rows + extra > self.n_rows:
                    raise PlanDeltaError(
                        f"window {w} outgrew the spare-row headroom; rebuild"
                    )
                grown = np.arange(n_data_rows, n_data_rows + extra, dtype=np.int64)
                wid[grown] = w
                n_data_rows += extra
                rows_w = np.concatenate([rows_w, grown])
            if count == 0:
                continue
            order = np.argsort(odst, kind="stable")
            d = odst[order].astype(np.int64)
            slots = rows_w[np.arange(count) // ROW] * ROW + np.arange(count) % ROW
            local[slots] = (osrc[order] & (WINDOW - 1)).astype(np.int32)
            weight[slots] = ow[order]
            lead = np.arange(count) % ROW == 0
            brk = np.empty(count, bool)
            brk[0] = True
            brk[1:] = (d[1:] != d[:-1]) | lead[1:]
            endm = np.empty(count, bool)
            endm[-1] = True
            endm[:-1] = brk[1:]
            new_end.append(slots[endm])
            new_first.append(lead[brk])
            new_dst.append(d[brk])

        all_end = np.concatenate(new_end)
        order = np.argsort(all_end, kind="stable")
        live_end = all_end[order]
        if live_end.size > 1 and not (np.diff(live_end) > 0).all():
            raise AssertionError("delta produced overlapping runs (plan bug)")
        live_first = np.concatenate(new_first)[order]
        seg_dst = np.concatenate(new_dst)[order].astype(np.int32)
        # Keep the device capacity (and so every array shape + the
        # compiled kernel) whenever the new run count still fits; grow
        # by whole quanta otherwise — one recompile, then stable again.
        s_new = int(seg_dst.shape[0])
        max_pad = (self.n_rows - n_data_rows) * ROW
        capacity = self.seg_capacity
        if s_new > capacity or capacity - s_new > max_pad:
            capacity = _segment_capacity(s_new, max_pad)
        seg_end, seg_first, seg_perm, dst_ptr = _pad_segment_tables(
            live_end,
            live_first,
            seg_dst,
            capacity=capacity,
            n=n_new,
            n_rows=self.n_rows,
            n_data_rows=n_data_rows,
        )
        return WindowPlan(
            n=n_new,
            n_rows=self.n_rows,
            table_entries=table_entries,
            n_segments=int(seg_dst.shape[0]),
            n_data_rows=n_data_rows,
            n_edges=self.n_edges - int(del_src.size) + int(ins_src.size),
            wid=wid,
            local=local.reshape(self.local.shape),
            weight=weight.reshape(self.weight.shape),
            seg_end=seg_end.astype(np.int32),
            seg_first=seg_first,
            seg_perm=seg_perm.astype(np.int32, copy=False),
            dst_ptr=dst_ptr.astype(np.int32),
            seg_dst=seg_dst,
            row_offset=row_offset,
            fingerprint=fingerprint,
            lineage=(self.lineage + (self.fingerprint,))[-LINEAGE_DEPTH:],
        )

    def replace_rows(
        self,
        rows: np.ndarray,
        new_src: np.ndarray,
        new_dst: np.ndarray,
        new_w: np.ndarray,
        *,
        n: int | None = None,
        fingerprint: str,
    ) -> "WindowPlan":
        """Replace every out-edge of the given source peers with the
        supplied (normalized) edges — the natural delta unit, because
        row normalization makes any change to a peer's attestation
        rewrite that peer's whole out-row.  Deletes are recovered from
        the plan itself, so callers need no copy of the previous edge
        list.  Raises :class:`PlanDeltaError` like ``apply_delta``."""
        rows = np.unique(np.asarray(rows, np.int64))
        new_src = np.asarray(new_src, np.int64)
        if new_src.size and not np.isin(new_src, rows).all():
            raise PlanDeltaError("replacement edge outside the replaced rows")
        parts = [
            self._edges_of_segments(
                self._segments_of_rows(self._window_vreg_rows(int(w)))
            )
            for w in np.unique(rows >> _WIN_BITS).tolist()
        ]
        if parts:
            osrc = np.concatenate([p[0] for p in parts])
            odst = np.concatenate([p[1] for p in parts])
            m = np.isin(osrc.astype(np.int64), rows)
            deletes = (osrc[m], odst[m])
        else:
            deletes = None
        return self.apply_delta(
            (new_src, new_dst, new_w), deletes, n=n, fingerprint=fingerprint
        )


def graph_fingerprint(n: int, src: np.ndarray, dst: np.ndarray, w: np.ndarray) -> str:
    """Cheap identity for plan-reuse validation: exact (n, nnz) plus a
    sha1 over strided samples of the edge arrays (hashing all 600 MB at
    bench scale would cost a meaningful fraction of plan construction;
    a strided digest catches every realistic graph change)."""
    h = hashlib.sha1()
    h.update(np.asarray([n, src.shape[0]], np.int64).tobytes())
    stride = max(1, src.shape[0] // (1 << 20))
    for a in (src, dst, w):
        h.update(np.ascontiguousarray(a[::stride]).tobytes())
    return h.hexdigest()


def build_window_plan(
    src: np.ndarray,
    dst: np.ndarray,
    w: np.ndarray,
    *,
    n: int,
    spare_rows: int | None = None,
) -> WindowPlan:
    """One-time host construction of the fused-pipeline layout for a
    row-normalized, self-edge-free edge list.  ``spare_rows`` of
    zero-weight tail headroom (adaptive by default: one grid block or
    ~6% of the data rows) lets ``apply_delta`` absorb window growth —
    and segment-table fragmentation — across epochs without a rebuild
    or a device-shape change."""
    b = bucket_by_window(
        src, w, table_size=n, dst=dst, n_dst=n, spare_rows=spare_rows
    )
    # Device segment tables at quantized capacity: the inert pads give
    # apply_delta shape-stability headroom (no recompile per epoch).
    max_pad = (b["n_rows"] - b["n_data_rows"]) * ROW
    seg_end, seg_first, seg_perm, dst_ptr = _pad_segment_tables(
        b["seg_end"],
        b["seg_first"],
        b["seg_dst"],
        capacity=_segment_capacity(b["n_segments"], max_pad),
        n=n,
        n_rows=b["n_rows"],
        n_data_rows=b["n_data_rows"],
    )
    return WindowPlan(
        n=n,
        n_rows=b["n_rows"],
        table_entries=-(-n // WINDOW) * WINDOW,
        n_segments=b["n_segments"],
        n_data_rows=b["n_data_rows"],
        n_edges=int(src.shape[0]),
        wid=b["wid"],
        local=b["local"],
        weight=b["weight"],
        seg_end=seg_end,
        seg_first=seg_first,
        seg_perm=seg_perm,
        dst_ptr=dst_ptr,
        seg_dst=b["seg_dst"],
        row_offset=b["row_offset"],
        fingerprint=graph_fingerprint(n, src, dst, w),
        order=b["order"],
        out_pos=b["out_pos"],
    )


def try_plan_delta(
    plan: WindowPlan,
    src: np.ndarray,
    dst: np.ndarray,
    w: np.ndarray,
    *,
    n: int,
    rows: np.ndarray,
    fingerprint: str,
) -> WindowPlan | None:
    """Fold per-epoch churn into a cached plan: replace the out-edges of
    the hinted ``rows`` (every source peer whose attestation changed
    since the plan's graph — row normalization rewrites exactly those
    rows) with their slice of the new normalized edge list
    ``(src, dst, w)``.  Returns the delta-updated plan, or None when the
    delta cannot be applied (overflow, shrink), when it would not pay
    (churn spread over too many windows — past the measured crossover
    a full rebuild's vectorized counting sorts beat the per-window
    repack, PERF.md §11), or when it fails the edge-count tripwire (a
    stale/incomplete ``rows`` hint would stamp the new fingerprint
    onto a layout that doesn't encode the new graph — in that case the
    caller must rebuild).
    """
    rows = np.unique(np.asarray(rows, np.int64))
    if rows.size == 0:
        return None
    # Delta-vs-rebuild crossover: the repack loop costs ~constant per
    # touched window while the rebuild is one vectorized O(E) pass, so
    # window-spread churn (every window touched) runs ~5x SLOWER as a
    # delta.  The measured crossover sits near a quarter of the data
    # windows; the 64-window floor keeps small graphs (few windows
    # total, trivially all touched) on the delta path where the
    # absolute cost is noise.
    data_windows = max(1, int(np.count_nonzero(np.diff(plan.row_offset))))
    touched_windows = int(np.unique(rows >> _WIN_BITS).size)
    if touched_windows > max(64, data_windows // 4):
        return None
    mask = np.isin(src, rows.astype(src.dtype))
    try:
        new_plan = plan.replace_rows(
            rows, src[mask], dst[mask], w[mask], n=n, fingerprint=fingerprint
        )
    except PlanDeltaError:
        return None
    if new_plan.n_edges != src.shape[0]:
        # The hint missed a changed row: the delta edge count disagrees
        # with the target graph.  Never serve a mislabeled layout.
        return None
    return new_plan


def partition_delta(
    rows: np.ndarray | None,
    src: np.ndarray,
    dst: np.ndarray,
    w: np.ndarray,
    owner: np.ndarray,
    host: int,
) -> tuple[np.ndarray | None, np.ndarray, np.ndarray, np.ndarray]:
    """Clip a churn hint and a row-normalized edge list to one pod
    host's partition (``owner[i]`` = host owning source peer ``i``,
    from ``parallel.partition.HostPartition``).

    Edges are owned by their **source** peer, so a dirty row (one
    sender's rewritten out-edges) is dirty on exactly one host: the
    returned ``owned_rows`` feed :func:`try_plan_delta` against that
    host's *local* plan, and hosts owning none of the churn keep their
    plan verbatim — steady-state churn never forces a cross-host
    rebuild.  Returns ``(owned_rows, local_src, local_dst, local_w)``;
    ``owned_rows`` is None when the caller passed no hint (forcing
    fingerprint-only revalidation, same contract as the global path).
    """
    owner = np.asarray(owner)
    mask = owner[src] == host
    owned_rows = None
    if rows is not None:
        rows = np.unique(np.asarray(rows, np.int64))
        owned_rows = rows[owner[rows] == host]
    return owned_rows, src[mask], dst[mask], w[mask]


def bridge_partials(
    hi: jax.Array,
    lo: jax.Array,
    seg_end: jax.Array,
    seg_first: jax.Array,
    seg_perm: jax.Array,
) -> jax.Array:
    """Reduce the flattened row-local (hi, lo) prefix lanes to
    dst-sorted per-(row, dst) run partials in a single pass (PERF.md
    §8): one 2-wide slice gather at the bucket-order run ends (strictly
    increasing indices — the read streams, and XLA is told so), an
    adjacent-element shift for each run's start prefix (runs are
    consecutive within a vreg-row; row-leading runs read an exact
    zero), and the one host-precomputed dst permutation — the only
    n_segments-sized random access per iteration."""
    cum2 = jnp.stack([hi, lo], axis=-1)
    ends = cum2.at[seg_end].get(indices_are_sorted=True, unique_indices=True)
    eh, el = ends[:, 0], ends[:, 1]
    zero = jnp.zeros(1, eh.dtype)
    prev_h = jnp.where(seg_first, 0.0, jnp.concatenate([zero, eh[:-1]]))
    prev_l = jnp.where(seg_first, 0.0, jnp.concatenate([zero, el[:-1]]))
    # Difference hi/lo lanes separately so the hi cancellation stays
    # exact (Sterbenz), matching rowsum_sorted's row differencing.
    partial = (eh - prev_h) + (el - prev_l)
    return partial[seg_perm]


def windowed_ct(
    wid: jax.Array,
    local: jax.Array,
    weight: jax.Array,
    seg_end: jax.Array,
    seg_first: jax.Array,
    seg_perm: jax.Array,
    dst_ptr: jax.Array,
    t: jax.Array,
    *,
    n_rows: int,
    table_entries: int,
    interpret: bool = False,
) -> jax.Array:
    """Dense Cᵀt over the plan's slot set — the fused pipeline minus
    damping.  Shared verbatim by the single-device step and the
    per-shard step under ``shard_map`` (``parallel/sharded.py``), where
    the plan arrays cover one shard's rows/runs and the returned vector
    is that shard's partial product (completed by ``lax.psum``):

    1. windowed Pallas gather-multiply from the VMEM-resident score
       table (bucket order — no random access, PERF.md §6: 7.9 ms at
       50M edges);
    2. row-local double-single prefix sum over the (n_rows, 1024) slot
       matrix (sequential vector work, the ``_ds_cumsum`` machinery);
    3. ``bridge_partials``: run partials out of the interleaved prefix
       lanes — one streaming boundary read + one n_segments random
       permutation (PERF.md §8; was 4 random gathers);
    4. ``rowsum_sorted`` over the dst-delimited partials → dense Cᵀt.
    """
    n = t.shape[0]
    table = jnp.pad(t, (0, table_entries - n))
    out = gather_windowed(
        wid, table, local, weight, n_rows=n_rows, interpret=interpret
    )
    hi, lo = _ds_cumsum_axis1(out.reshape(n_rows, ROW))
    partial = bridge_partials(
        hi.reshape(-1), lo.reshape(-1), seg_end, seg_first, seg_perm
    )
    return rowsum_sorted(partial, dst_ptr)


def power_step_windowed(
    wid: jax.Array,
    local: jax.Array,
    weight: jax.Array,
    seg_end: jax.Array,
    seg_first: jax.Array,
    seg_perm: jax.Array,
    dst_ptr: jax.Array,
    t: jax.Array,
    p: jax.Array,
    dangling: jax.Array,
    alpha: jax.Array | float,
    *,
    n_rows: int,
    table_entries: int,
    interpret: bool = False,
) -> jax.Array:
    """One damped step of the fused fixed-slot pipeline: ``windowed_ct``
    then the shared damping + dangling redistribution + L1 renorm."""
    ct = windowed_ct(
        wid,
        local,
        weight,
        seg_end,
        seg_first,
        seg_perm,
        dst_ptr,
        t,
        n_rows=n_rows,
        table_entries=table_entries,
        interpret=interpret,
    )
    dangling_mass = jnp.sum(t * dangling)
    t_new = (1.0 - alpha) * (ct + dangling_mass * p) + alpha * p
    return t_new / jnp.sum(t_new)


@partial(
    jax.jit,
    static_argnames=(
        "n_rows", "table_entries", "tol", "max_iter", "interpret",
        "record_residuals",
    ),
    donate_argnames=("t0",),
)
def converge_windowed(
    wid: jax.Array,
    local: jax.Array,
    weight: jax.Array,
    seg_end: jax.Array,
    seg_first: jax.Array,
    seg_perm: jax.Array,
    dst_ptr: jax.Array,
    t0: jax.Array,
    p: jax.Array,
    dangling: jax.Array,
    *,
    n_rows: int,
    table_entries: int,
    alpha: jax.Array | float = 0.1,
    tol: float = 1e-6,
    max_iter: int = 50,
    interpret: bool = False,
    record_residuals: bool = False,
) -> tuple[jax.Array, ...]:
    """Fused-pipeline analog of ``converge_csr`` — same shared
    ``run_power_iteration`` driver, so early-exit semantics can't drift
    between formulations.  ``t0`` is donated (pass a fresh buffer);
    the plan arrays are not — they are reused across epochs.
    ``record_residuals`` appends the device-side residual history to
    the returned tuple (the telemetry path; no host sync, no new
    gathers — see ``run_power_iteration``)."""
    return run_power_iteration(
        lambda t: power_step_windowed(
            wid,
            local,
            weight,
            seg_end,
            seg_first,
            seg_perm,
            dst_ptr,
            t,
            p,
            dangling,
            alpha,
            n_rows=n_rows,
            table_entries=table_entries,
            interpret=interpret,
        ),
        t0,
        tol=tol,
        max_iter=max_iter,
        record_residuals=record_residuals,
    )


# ---------------------------------------------------------------------------
# Pinned kernel invariants (PERF.md §9) — checked per step by
# `python -m protocol_tpu.analysis` against the traced jaxpr.
# ---------------------------------------------------------------------------

#: The tentpole contract of the fused fixed-slot pipeline (PERF.md §8):
#: exactly two n_segments-sized gathers per step — the streaming
#: sorted+unique (S, 2) boundary read and ONE random dst permutation —
#: plus the four (n+1)-sized rowsum pointer reads; no scatter; the
#: windowed Pallas kernel must actually be present (gathers inside its
#: interpret body are excluded from the counts: on the real chip they
#: are Mosaic codegen, not XLA gathers).
declare(
    KernelBudget(
        backend="tpu-windowed",
        max_random_gathers=5,
        max_scatters=0,
        require_primitives=("pallas_call",),
        gather_budgets=(
            GatherBudget(
                dim="n_segments", max_total=2, max_random=1, boundary_sorted=True
            ),
        ),
        donated_args=("t0",),
        notes=(
            "fused pipeline: 1 random n_segments pass (dst perm), "
            "streaming 2-wide boundary read, 4 rowsum pointer reads"
        ),
    )
)

#: Single-device fused pipeline (graftlint pass 8): zero collectives,
#: zero host round-trips — the Pallas windowed gather is VMEM-local by
#: construction — and the t0 donation must survive into the compiled
#: module's input_output_alias table (PERF.md §15).
declare_comm(
    CommBudget(
        backend="tpu-windowed",
        donated_args=("t0",),
        notes="single-device fused pipeline: no wire, no host traffic",
    )
)

#: Peak-HBM budget (graftlint pass 12, PERF.md §19).  Resident: the
#: plan's vreg-row tables — ``local`` + ``weight`` at (8, 128) slots x
#: 4 B each plus ``wid`` = 8196 B/row — the bucket-order segment table
#: (seg_end + seg_first + seg_perm = 9 B/run), the (n+1) dst pointers
#: and the three f32[N] vectors.  Transient: the Pallas INTERPRET
#: compile (the analyzer's CPU mesh) re-expresses the Mosaic kernel as
#: XLA ops, so its VMEM-analog scratch shows up as ~12 row-table
#: copies in the temp arena (``transient_rows`` = 12 x 8192); on the
#: real chip that scratch is VMEM, not HBM.  Rows are a plan-layout
#: dimension (1024 edge slots each) — there is still no per-edge
#: transient coefficient, and the committed slack is below a 4 B/edge
#: temporary (pinned by test).
declare_mem(
    MemBudget(
        backend="tpu-windowed",
        resident_rows=8196.0,
        resident_segments=9.0,
        resident_n=16.0,
        resident_const=4096.0,
        transient_rows=98304.0,
        transient_n=36.0,
        transient_segments=9.0,
        transient_const=4096.0,
        donated_args=("t0",),
        notes="fused pipeline: plan tables resident; interpret-mode "
        "kernel scratch ~12x the 8 KB row tables",
    )
)
