"""Dense jit'd trust kernels.

The TPU image of the reference's two native kernels:

- ``converge_dense`` ↔ circuit/src/circuit.rs:425-470 ``native()``:
  repeated ``opsᵀ·s`` as an MXU matmul under ``lax.scan``.  Operates on
  *row-normalized* matrices so floating point stays bounded; the field
  kernel's unscale-by-SCALE^I is algebraically the same normalization.
- ``set_converge_dense`` + ``filter_and_normalize`` ↔
  circuit/src/native.rs:83-234: the EigenTrustSet filter/redistribute/
  normalize semantics re-derived as data-parallel masks (no per-peer
  Python control flow — everything is `where`-select so XLA fuses it).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..analysis.budget import (
    CommBudget,
    KernelBudget,
    MemBudget,
    declare,
    declare_comm,
    declare_mem,
)


@partial(jax.jit, static_argnames=("num_iter",))
def converge_dense(ops_t: jax.Array, s0: jax.Array, num_iter: int) -> jax.Array:
    """``num_iter`` power-iteration steps ``s ← ops_t @ s``.

    ``ops_t`` is the transposed local-trust matrix (so the contraction is
    a single matmul on the MXU); pass a column-stochastic matrix and a
    normalized ``s0`` for bounded dynamics.
    """

    def step(s, _):
        return ops_t @ s, None

    s, _ = lax.scan(step, s0, None, length=num_iter)
    return s


@jax.jit
def filter_and_normalize(
    ops: jax.Array, match: jax.Array, set_valid: jax.Array
) -> jax.Array:
    """Vectorized ``filter_peers`` + credit normalization
    (circuit/src/native.rs:146-234, 89-102), returning a row-stochastic
    matrix (zero rows for invalid peers).

    - ``ops[i, j]``: peer i's score for set slot j (already aligned to
      set order by the caller; a mismatched slot has ``match[i, j] =
      False``).
    - ``match[i, j]``: the opinion's j-th public key equals set slot j's.
    - ``set_valid[i]``: slot i holds a real (non-null) member.

    Nullification: score kept only when the pk matches, the target slot
    is valid, and it is not a self-score.  All-zero rows of valid peers
    redistribute evenly over the other valid slots.  Rows are then
    normalized to sum to 1 (the per-credit share; multiply by credits for
    reference-scale values).
    """
    n = ops.shape[0]
    eye = jnp.eye(n, dtype=bool)
    valid_row = set_valid[:, None]
    valid_col = set_valid[None, :]

    keep = match & valid_col & ~eye & valid_row
    a = jnp.where(keep, ops, 0.0)

    row_sum = a.sum(axis=1)
    redistribute = (row_sum == 0.0) & set_valid
    fallback = valid_col & ~eye & valid_row
    a = jnp.where(redistribute[:, None] & fallback, 1.0, a)

    row_sum = a.sum(axis=1)
    safe = jnp.where(row_sum == 0.0, 1.0, row_sum)
    return a / safe[:, None]


@partial(jax.jit, static_argnames=("num_iter",))
def set_converge_dense(
    stochastic: jax.Array, credits: jax.Array, num_iter: int
) -> jax.Array:
    """EigenTrustSet convergence on a row-stochastic filtered matrix.

    The reference iterates ``s ← Mᵀ s`` where M = diag(credits)·S with S
    row-stochastic (native.rs:111-133), so raw scores grow by a factor of
    INITIAL_SCORE per iteration.  On the valid subspace diag(credits) is
    INITIAL_SCORE·Identity, hence the reference's raw result equals this
    function's output times ``INITIAL_SCORE^num_iter`` (tests check
    against the exact rational kernel).
    """
    total = credits.sum()
    s0 = credits / total

    def step(s, _):
        return stochastic.T @ s, None

    s, _ = lax.scan(step, s0, None, length=num_iter)
    return s * total


# ---------------------------------------------------------------------------
# Pinned kernel invariants (PERF.md §9) — checked per step by
# `python -m protocol_tpu.analysis` against the traced jaxpr.
# ---------------------------------------------------------------------------

#: Dense power iteration: pure MXU matmuls — no gather, no scatter, no
#: collective; ``dot_general`` must survive any rewrite (losing it
#: means the contraction fell off the MXU).
declare(
    KernelBudget(
        backend="tpu-dense",
        max_random_gathers=0,
        max_scatters=0,
        require_primitives=("dot_general",),
        notes="matmul-only power step under lax.scan",
    )
)

#: Single-device matmul chunk: the compiled module must contain zero
#: collectives and zero host round-trips (graftlint pass 8; the
#: host-side tol check between scan chunks lives OUTSIDE the jit).
#: ``converge_dense`` takes no donated seed — the chunked driver
#: re-feeds ``t`` itself.
declare_comm(
    CommBudget(
        backend="tpu-dense",
        notes="single-device scan chunk: no wire, no host traffic",
    )
)

#: Peak-HBM budget (graftlint pass 12, PERF.md §19).  Resident: the
#: dense operator matrix (4 B/entry, dims report entries as "edges")
#: plus the f32[N] seed.  Transient: the scan chunk ping-pongs one
#: f32[N] score vector — nothing else stays live.  No donation: the
#: chunked driver re-feeds ``t`` itself.
declare_mem(
    MemBudget(
        backend="tpu-dense",
        resident_edge_bytes=4.0,
        resident_n=4.0,
        resident_const=4096.0,
        transient_n=8.0,
        transient_const=4096.0,
        notes="matmul scan chunk: matrix + seed resident, one f32[N] "
        "carry transient",
    )
)
