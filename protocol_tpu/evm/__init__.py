"""In-process EVM for contract-level verification.

The analog of the reference's use of an embedded revm executor to
deploy and exercise its generated Yul PLONK verifier without a chain
(circuit/src/verifier/mod.rs:117-134 ``evm_verify``, client
deploy/call utils client/src/utils.rs:60-116): a compact interpreter
covering the execution profile of verifier contracts — 256-bit stack
machine, memory, calldata, KECCAK256, the Bn254 precompiles (ecAdd,
ecMul, pairing) plus modexp, and Istanbul-flavoured gas metering so
verification cost is measurable.
"""

from .machine import EVM, Precompiles, Receipt, asm, op

__all__ = ["EVM", "Precompiles", "Receipt", "asm", "op"]
