"""A compact EVM interpreter with Bn254 precompiles and gas metering.

Covers the opcode/precompile profile that generated PLONK verifier
contracts use (and small glue contracts like EtVerifierWrapper.sol):
arithmetic incl. ADDMOD/MULMOD, comparisons, bit ops, KECCAK256,
calldata/memory/returndata, control flow, STATICCALL into other
contracts and precompiles 0x05 (modexp), 0x06 (ecAdd), 0x07 (ecMul),
0x08 (pairing).  Gas follows Istanbul numbers for the metered subset —
close enough that reported verification gas is meaningful, which is all
the reference's dbg!(gas_used) provides (verifier/mod.rs:123-130).

No state trie, no value transfer: contracts are deployed
either as raw runtime code or by executing creation code.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field

from ..crypto.keccak import keccak256

U256 = (1 << 256) - 1
_SIGN_BIT = 1 << 255

#: Bn254 base field / curve order for the precompiles.
_FQ = 21888242871839275222246405745257275088696311157297823662689037894645226208583
_FR = 21888242871839275222246405745257275088548364400416034343698204186575808495617


class EvmError(Exception):
    pass


class OutOfGas(EvmError):
    pass


# ---------------------------------------------------------------------------
# Assembler helpers
# ---------------------------------------------------------------------------

_OPCODES = {
    "STOP": 0x00, "ADD": 0x01, "MUL": 0x02, "SUB": 0x03, "DIV": 0x04,
    "SDIV": 0x05, "MOD": 0x06, "SMOD": 0x07, "ADDMOD": 0x08, "MULMOD": 0x09,
    "EXP": 0x0A, "SIGNEXTEND": 0x0B,
    "LT": 0x10, "GT": 0x11, "SLT": 0x12, "SGT": 0x13, "EQ": 0x14,
    "ISZERO": 0x15, "AND": 0x16, "OR": 0x17, "XOR": 0x18, "NOT": 0x19,
    "BYTE": 0x1A, "SHL": 0x1B, "SHR": 0x1C, "SAR": 0x1D,
    "KECCAK256": 0x20,
    "ADDRESS": 0x30, "CALLER": 0x33, "CALLVALUE": 0x34,
    "CALLDATALOAD": 0x35, "CALLDATASIZE": 0x36, "CALLDATACOPY": 0x37,
    "CODESIZE": 0x38, "CODECOPY": 0x39,
    "RETURNDATASIZE": 0x3D, "RETURNDATACOPY": 0x3E, "EXTCODESIZE": 0x3B,
    "POP": 0x50, "MLOAD": 0x51, "MSTORE": 0x52, "MSTORE8": 0x53,
    "SLOAD": 0x54, "SSTORE": 0x55, "JUMP": 0x56, "JUMPI": 0x57,
    "PC": 0x58, "MSIZE": 0x59, "GAS": 0x5A, "JUMPDEST": 0x5B,
    "PUSH0": 0x5F,
    "RETURN": 0xF3, "STATICCALL": 0xFA, "REVERT": 0xFD, "INVALID": 0xFE,
}
for _i in range(1, 33):
    _OPCODES[f"PUSH{_i}"] = 0x5F + _i
for _i in range(1, 17):
    _OPCODES[f"DUP{_i}"] = 0x7F + _i
    _OPCODES[f"SWAP{_i}"] = 0x8F + _i

_OPNAMES = {v: k for k, v in _OPCODES.items()}


def op(name: str) -> int:
    return _OPCODES[name]


def asm(*items) -> bytes:
    """Tiny assembler: strings are opcodes; ints become minimal PUSHes;
    ("label", name) defines a JUMPDEST, ("ref", name) a 2-byte push of
    its offset (two-pass)."""
    # Pass 1: layout.
    code: list = []
    labels: dict[str, int] = {}
    pos = 0
    for it in items:
        if isinstance(it, tuple) and it[0] == "label":
            labels[it[1]] = pos
            code.append(("op", 0x5B))
            pos += 1
        elif isinstance(it, tuple) and it[0] == "ref":
            code.append(it)
            pos += 4  # PUSH3 + 3 bytes (verifier contracts exceed 64KB)
        elif isinstance(it, str):
            code.append(("op", _OPCODES[it]))
            pos += 1
        elif isinstance(it, int):
            if it == 0:
                code.append(("op", 0x5F))
                pos += 1
            else:
                blen = max(1, (it.bit_length() + 7) // 8)
                code.append(("push", it, blen))
                pos += 1 + blen
        elif isinstance(it, bytes):
            code.append(("raw", it))
            pos += len(it)
        else:  # pragma: no cover
            raise TypeError(f"bad asm item {it!r}")
    # Pass 2: emit.
    out = bytearray()
    for it in code:
        if it[0] == "op":
            out.append(it[1])
        elif it[0] == "push":
            out.append(0x5F + it[2])
            out += it[1].to_bytes(it[2], "big")
        elif it[0] == "raw":
            out += it[1]
        else:  # ref
            out.append(0x62)  # PUSH3
            out += labels[it[1]].to_bytes(3, "big")
    return bytes(out)


# ---------------------------------------------------------------------------
# Precompiles
# ---------------------------------------------------------------------------


class Precompiles:
    """0x05 modexp, 0x06 ecAdd, 0x07 ecMul, 0x08 ecPairing (Istanbul
    gas), implemented over the framework's own Bn254 stack."""

    #: Istanbul static costs, shared by the pre-check and the
    #: implementations so a repricing can't drift between them.
    EC_ADD_GAS = 150
    EC_MUL_GAS = 6000
    PAIRING_BASE_GAS = 45000
    PAIRING_PER_PAIR_GAS = 34000

    @staticmethod
    def pairing_gas(data: bytes) -> int:
        return Precompiles.PAIRING_BASE_GAS + Precompiles.PAIRING_PER_PAIR_GAS * (
            len(data) // 192
        )

    @staticmethod
    def run(addr: int, data: bytes, gas_limit: int | None = None) -> tuple[bool, bytes, int]:
        """-> (success, returndata, gas_cost).  When ``gas_limit`` is
        given, the cost is computed and checked *before* any expensive
        work, so hostile inputs can't burn CPU they haven't paid for."""
        if addr == 0x05:
            return Precompiles._modexp(data, gas_limit)
        if addr == 0x06:
            if gas_limit is not None and gas_limit < Precompiles.EC_ADD_GAS:
                return False, b"", Precompiles.EC_ADD_GAS
            return Precompiles._ec_add(data)
        if addr == 0x07:
            if gas_limit is not None and gas_limit < Precompiles.EC_MUL_GAS:
                return False, b"", Precompiles.EC_MUL_GAS
            return Precompiles._ec_mul(data)
        if addr == 0x08:
            if gas_limit is not None and gas_limit < Precompiles.pairing_gas(data):
                return False, b"", Precompiles.pairing_gas(data)
            return Precompiles._pairing(data)
        raise EvmError(f"unsupported precompile {addr:#x}")

    @staticmethod
    def _word(data: bytes, i: int) -> int:
        chunk = data[32 * i : 32 * i + 32]
        return int.from_bytes(chunk.ljust(32, b"\0"), "big")

    @staticmethod
    def _modexp(data: bytes, gas_limit: int | None = None):
        blen = Precompiles._word(data, 0)
        elen = Precompiles._word(data, 1)
        mlen = Precompiles._word(data, 2)
        if max(blen, elen, mlen) > 1024:
            # Failing precompile: the call handler consumes the forwarded gas.
            return False, b"", 0
        body = data[96:].ljust(blen + elen + mlen, b"\0")
        # EIP-2565 gas, computed from the lengths + exponent head before
        # the pow runs so unpaid work never executes.
        e_head = int.from_bytes(body[blen : blen + 32].ljust(32, b"\0")[: min(elen, 32)], "big")
        words = (max(blen, mlen) + 7) // 8
        mult = words * words
        adj = (
            max(e_head.bit_length() - 1, 0)
            if elen <= 32
            else 8 * (elen - 32) + max(e_head.bit_length() - 1, 0)
        )
        gas = max(200, mult * max(adj, 1) // 3)
        if gas_limit is not None and gas > gas_limit:
            return False, b"", gas
        b = int.from_bytes(body[:blen], "big")
        e = int.from_bytes(body[blen : blen + elen], "big")
        m = int.from_bytes(body[blen + elen : blen + elen + mlen], "big")
        out = pow(b, e, m) if m else 0
        return True, out.to_bytes(mlen, "big") if mlen else b"", gas

    @staticmethod
    def _g1(data: bytes, off_words: int):
        from ..zk.bn254 import G1, is_on_curve

        x = Precompiles._word(data, off_words)
        y = Precompiles._word(data, off_words + 1)
        if x >= _FQ or y >= _FQ:
            raise EvmError("ec point coordinate out of range")
        p = G1(x, y)
        if not is_on_curve(p):
            raise EvmError("ec point not on curve")
        return p

    @staticmethod
    def _ec_add(data: bytes):
        try:
            a = Precompiles._g1(data, 0)
            b = Precompiles._g1(data, 2)
        except EvmError:
            return False, b"", Precompiles.EC_ADD_GAS
        c = a.add(b)
        return True, c.x.to_bytes(32, "big") + c.y.to_bytes(32, "big"), Precompiles.EC_ADD_GAS

    @staticmethod
    def _ec_mul(data: bytes):
        try:
            a = Precompiles._g1(data, 0)
        except EvmError:
            return False, b"", Precompiles.EC_MUL_GAS
        s = Precompiles._word(data, 2)
        c = a.mul(s % _FR) if s else a.mul(0)
        return True, c.x.to_bytes(32, "big") + c.y.to_bytes(32, "big"), Precompiles.EC_MUL_GAS

    @staticmethod
    def _pairing(data: bytes):
        from ..zk.bn254 import G1
        from ..zk.fields import FQ2, G2, g2_in_subgroup, g2_is_on_curve, pairing_check

        if len(data) % 192 != 0:
            return False, b"", Precompiles.PAIRING_BASE_GAS
        n = len(data) // 192
        gas = Precompiles.pairing_gas(data)
        pairs = []
        for i in range(n):
            base = 6 * i
            try:
                p = Precompiles._g1(data, base)
            except EvmError:
                return False, b"", gas
            # EVM ABI: G2 as (x_imag, x_real, y_imag, y_real).
            xi, xr = Precompiles._word(data, base + 2), Precompiles._word(data, base + 3)
            yi, yr = Precompiles._word(data, base + 4), Precompiles._word(data, base + 5)
            if max(xi, xr, yi, yr) >= _FQ:
                return False, b"", gas
            q = G2(FQ2([xr, xi]), FQ2([yr, yi]))
            is_zero_q = xi == xr == yi == yr == 0
            if not is_zero_q and not (g2_is_on_curve(q) and g2_in_subgroup(q)):
                return False, b"", gas
            if p.is_identity() or is_zero_q:
                continue  # e(O, Q) = e(P, O) = 1
            pairs.append((p, q))
        ok = pairing_check(pairs) if pairs else True
        return True, (1 if ok else 0).to_bytes(32, "big"), gas


# ---------------------------------------------------------------------------
# Interpreter
# ---------------------------------------------------------------------------


@dataclass
class Log:
    """One emitted event: address, up to four topics, data blob."""

    address: int
    topics: list
    data: bytes


@dataclass
class Receipt:
    success: bool
    returndata: bytes
    gas_used: int
    error: str | None = None
    logs: list = dc_field(default_factory=list)


_GAS = {  # metered subset (Istanbul-ish)
    0x00: 0, 0x01: 3, 0x02: 5, 0x03: 3, 0x04: 5, 0x05: 5, 0x06: 5, 0x07: 5,
    0x08: 8, 0x09: 8, 0x0A: 10, 0x0B: 5,
    0x10: 3, 0x11: 3, 0x12: 3, 0x13: 3, 0x14: 3, 0x15: 3, 0x16: 3, 0x17: 3,
    0x18: 3, 0x19: 3, 0x1A: 3, 0x1B: 3, 0x1C: 3, 0x1D: 3,
    0x30: 2, 0x33: 2, 0x34: 2, 0x35: 3, 0x36: 2, 0x38: 2,
    0x3D: 2, 0x50: 2, 0x51: 3, 0x52: 3, 0x53: 3,
    0x54: 800, 0x55: 20000, 0x56: 8, 0x57: 10, 0x58: 2, 0x59: 2, 0x5A: 2,
    0x5B: 1, 0x5F: 2,
    0xF3: 0, 0xFD: 0,
    0xA0: 375, 0xA1: 750, 0xA2: 1125, 0xA3: 1500, 0xA4: 1875,
}


class EVM:
    """Single-shot executor over an in-memory contract map."""

    def __init__(self):
        self.code: dict[int, bytes] = {}
        self.storage: dict[int, dict[int, int]] = {}
        self._next_addr = 0x1000

    # -- deployment -----------------------------------------------------

    def deploy_runtime(self, runtime: bytes) -> int:
        """Install runtime bytecode directly (the reference deploys its
        Yul verifier's compiled runtime the same way, utils.rs:90-103)."""
        addr = self._next_addr
        self._next_addr += 1
        self.code[addr] = bytes(runtime)
        return addr

    def deploy(self, creation: bytes, gas: int = 30_000_000) -> int:
        """Execute creation code; the returned body becomes runtime."""
        r = self._execute(creation, b"", gas, depth=0, self_addr=0)
        if not r.success:
            raise EvmError(f"constructor reverted: {r.error or r.returndata.hex()}")
        return self.deploy_runtime(r.returndata)

    # -- calls ----------------------------------------------------------

    #: Default msg.sender when none is given (a recognizable dummy).
    DEFAULT_CALLER = 0xCA11E5

    def call(
        self, addr: int, calldata: bytes, gas: int = 30_000_000, caller: int | None = None
    ) -> Receipt:
        code = self.code.get(addr)
        if code is None:
            raise EvmError(f"no contract at {addr:#x}")
        return self._execute(
            code, bytes(calldata), gas, depth=0, self_addr=addr,
            caller=self.DEFAULT_CALLER if caller is None else caller,
        )

    # -- core loop ------------------------------------------------------

    def _execute(
        self,
        code: bytes,
        calldata: bytes,
        gas: int,
        depth: int,
        self_addr: int,
        static: bool = False,
        caller: int | None = None,
    ) -> Receipt:
        if depth > 8:
            return Receipt(False, b"", 0, "call depth exceeded")
        if caller is None:
            caller = self.DEFAULT_CALLER
        logs: list[Log] = []
        stack: list[int] = []
        mem = bytearray()
        ret_buf = b""
        pc = 0
        gas_left = gas
        jumpdests = _jumpdests(code)
        store = self.storage.setdefault(self_addr, {})

        def use(n: int):
            nonlocal gas_left
            gas_left -= n
            if gas_left < 0:
                raise OutOfGas(f"out of gas at pc={pc}")

        def mem_expand(end: int):
            if end <= len(mem):
                return
            new_words = (end + 31) // 32
            old_words = (len(mem) + 31) // 32
            cost = (3 * new_words + new_words * new_words // 512) - (
                3 * old_words + old_words * old_words // 512
            )
            use(cost)
            mem.extend(b"\0" * (new_words * 32 - len(mem)))

        def mread(off: int, size: int) -> bytes:
            if size == 0:
                return b""
            mem_expand(off + size)
            return bytes(mem[off : off + size])

        def mwrite(off: int, data: bytes):
            if not data:
                return
            mem_expand(off + len(data))
            mem[off : off + len(data)] = data

        def push(v: int):
            if len(stack) >= 1024:
                raise EvmError("stack overflow")
            stack.append(v & U256)

        def pop() -> int:
            if not stack:
                raise EvmError("stack underflow")
            return stack.pop()

        try:
            while pc < len(code):
                opcode = code[pc]
                base = _GAS.get(opcode)
                if base is None and not (0x60 <= opcode <= 0x9F) and opcode not in (
                    0x20,
                    0x37,
                    0x39,
                    0x3B,
                    0x3E,
                    0xFA,
                ):
                    raise EvmError(f"invalid opcode {opcode:#04x} at pc={pc}")
                if base is not None:
                    use(base)
                elif 0x60 <= opcode <= 0x9F:
                    use(3)

                if opcode == 0x00:  # STOP
                    return Receipt(True, b"", gas - gas_left, logs=logs)
                elif opcode == 0x01:
                    push(pop() + pop())
                elif opcode == 0x02:
                    push(pop() * pop())
                elif opcode == 0x03:
                    a, b = pop(), pop()
                    push(a - b)
                elif opcode == 0x04:
                    a, b = pop(), pop()
                    push(a // b if b else 0)
                elif opcode == 0x05:  # SDIV
                    a, b = _sgn(pop()), _sgn(pop())
                    push(0 if b == 0 else abs(a) // abs(b) * (1 if (a < 0) == (b < 0) else -1))
                elif opcode == 0x06:
                    a, b = pop(), pop()
                    push(a % b if b else 0)
                elif opcode == 0x07:  # SMOD
                    a, b = _sgn(pop()), _sgn(pop())
                    push(0 if b == 0 else (abs(a) % abs(b)) * (1 if a >= 0 else -1))
                elif opcode == 0x08:  # ADDMOD
                    a, b, m = pop(), pop(), pop()
                    push((a + b) % m if m else 0)
                elif opcode == 0x09:  # MULMOD
                    a, b, m = pop(), pop(), pop()
                    push((a * b) % m if m else 0)
                elif opcode == 0x0A:  # EXP
                    a, b = pop(), pop()
                    use(50 * max(1, (b.bit_length() + 7) // 8) - 0 if b else 0)
                    push(pow(a, b, 1 << 256))
                elif opcode == 0x0B:  # SIGNEXTEND
                    k, v = pop(), pop()
                    if k < 31:
                        bit = 8 * (k + 1) - 1
                        if v & (1 << bit):
                            v |= U256 ^ ((1 << (bit + 1)) - 1)
                        else:
                            v &= (1 << (bit + 1)) - 1
                    push(v)
                elif opcode == 0x10:
                    push(1 if pop() < pop() else 0)
                elif opcode == 0x11:
                    push(1 if pop() > pop() else 0)
                elif opcode == 0x12:
                    push(1 if _sgn(pop()) < _sgn(pop()) else 0)
                elif opcode == 0x13:
                    push(1 if _sgn(pop()) > _sgn(pop()) else 0)
                elif opcode == 0x14:
                    push(1 if pop() == pop() else 0)
                elif opcode == 0x15:
                    push(1 if pop() == 0 else 0)
                elif opcode == 0x16:
                    push(pop() & pop())
                elif opcode == 0x17:
                    push(pop() | pop())
                elif opcode == 0x18:
                    push(pop() ^ pop())
                elif opcode == 0x19:
                    push(pop() ^ U256)
                elif opcode == 0x1A:  # BYTE
                    i, v = pop(), pop()
                    push((v >> (8 * (31 - i))) & 0xFF if i < 32 else 0)
                elif opcode == 0x1B:  # SHL
                    s, v = pop(), pop()
                    push(v << s if s < 256 else 0)
                elif opcode == 0x1C:  # SHR
                    s, v = pop(), pop()
                    push(v >> s if s < 256 else 0)
                elif opcode == 0x1D:  # SAR
                    s, v = pop(), _sgn(pop())
                    push((v >> s) if s < 256 else (0 if v >= 0 else U256))
                elif opcode == 0x20:  # KECCAK256
                    off, size = pop(), pop()
                    use(30 + 6 * ((size + 31) // 32))
                    push(int.from_bytes(keccak256(mread(off, size)), "big"))
                elif opcode == 0x30:
                    push(self_addr)
                elif opcode == 0x33:
                    push(caller)
                elif opcode == 0x34:
                    push(0)
                elif opcode == 0x35:  # CALLDATALOAD
                    off = pop()
                    push(int.from_bytes(calldata[off : off + 32].ljust(32, b"\0"), "big"))
                elif opcode == 0x36:
                    push(len(calldata))
                elif opcode == 0x37:  # CALLDATACOPY
                    dst, src, size = pop(), pop(), pop()
                    use(3 + 3 * ((size + 31) // 32))
                    mwrite(dst, calldata[src : src + size].ljust(size, b"\0"))
                elif opcode == 0x38:
                    push(len(code))
                elif opcode == 0x39:  # CODECOPY
                    dst, src, size = pop(), pop(), pop()
                    use(3 + 3 * ((size + 31) // 32))
                    mwrite(dst, code[src : src + size].ljust(size, b"\0"))
                elif opcode == 0x3B:  # EXTCODESIZE
                    use(700)
                    push(len(self.code.get(pop(), b"")))
                elif opcode == 0x3D:
                    push(len(ret_buf))
                elif opcode == 0x3E:  # RETURNDATACOPY
                    dst, src, size = pop(), pop(), pop()
                    use(3 + 3 * ((size + 31) // 32))
                    if src + size > len(ret_buf):
                        raise EvmError("returndatacopy out of bounds")
                    mwrite(dst, ret_buf[src : src + size])
                elif opcode == 0x50:
                    pop()
                elif opcode == 0x51:  # MLOAD
                    off = pop()
                    push(int.from_bytes(mread(off, 32), "big"))
                elif opcode == 0x52:  # MSTORE
                    off, v = pop(), pop()
                    mwrite(off, v.to_bytes(32, "big"))
                elif opcode == 0x53:  # MSTORE8
                    off, v = pop(), pop()
                    mwrite(off, bytes([v & 0xFF]))
                elif opcode == 0x54:  # SLOAD
                    push(store.get(pop(), 0))
                elif opcode == 0x55:  # SSTORE
                    if static:
                        raise EvmError("state modification in static context")
                    k, v = pop(), pop()
                    store[k] = v
                elif opcode == 0x56:  # JUMP
                    pc = pop()
                    if pc not in jumpdests:
                        raise EvmError(f"bad jump target {pc}")
                    continue
                elif opcode == 0x57:  # JUMPI
                    dst, cond = pop(), pop()
                    if cond:
                        pc = dst
                        if pc not in jumpdests:
                            raise EvmError(f"bad jump target {pc}")
                        continue
                elif opcode == 0x58:
                    push(pc)
                elif opcode == 0x59:
                    push(len(mem))
                elif opcode == 0x5A:
                    push(max(gas_left, 0))
                elif opcode == 0x5B:
                    pass  # JUMPDEST
                elif opcode == 0x5F:
                    push(0)
                elif 0x60 <= opcode <= 0x7F:  # PUSH1..32
                    nbytes = opcode - 0x5F
                    push(int.from_bytes(code[pc + 1 : pc + 1 + nbytes].ljust(nbytes, b"\0"), "big"))
                    pc += nbytes
                elif 0x80 <= opcode <= 0x8F:  # DUP
                    i = opcode - 0x7F
                    if len(stack) < i:
                        raise EvmError("stack underflow")
                    push(stack[-i])
                elif 0x90 <= opcode <= 0x9F:  # SWAP
                    i = opcode - 0x8F
                    if len(stack) < i + 1:
                        raise EvmError("stack underflow")
                    stack[-1], stack[-1 - i] = stack[-1 - i], stack[-1]
                elif 0xA0 <= opcode <= 0xA4:  # LOG0..LOG4
                    if static:
                        raise EvmError("log in static context")
                    off, size = pop(), pop()
                    use(8 * size)
                    topics = [pop() for _ in range(opcode - 0xA0)]
                    logs.append(Log(self_addr, topics, mread(off, size)))
                elif opcode == 0xF3:  # RETURN
                    off, size = pop(), pop()
                    return Receipt(True, mread(off, size), gas - gas_left, logs=logs)
                elif opcode == 0xFA:  # STATICCALL
                    use(700)
                    call_gas, to, in_off, in_size, out_off, out_size = (
                        pop(),
                        pop(),
                        pop(),
                        pop(),
                        pop(),
                        pop(),
                    )
                    data = mread(in_off, in_size)
                    sub_gas = min(call_gas, max(gas_left - gas_left // 64, 0))
                    if 1 <= to <= 0x09:
                        ok, out, pgas = Precompiles.run(to, data, sub_gas)
                        # Real EVM: a failing precompile (or one whose cost
                        # exceeds the forwarded gas) consumes the forwarded
                        # gas and the call fails; success pays metered cost.
                        if ok and pgas <= sub_gas:
                            use(pgas)
                        else:
                            ok, out = False, b""
                            use(sub_gas)
                    elif to in self.code:
                        r = self._execute(
                            self.code[to], data, sub_gas, depth + 1, to,
                            static=True, caller=self_addr,
                        )
                        use(r.gas_used)
                        ok, out = r.success, r.returndata
                    else:
                        ok, out = True, b""  # call to empty account
                    ret_buf = out
                    mwrite(out_off, out[:out_size].ljust(min(out_size, len(out)), b"\0"))
                    push(1 if ok else 0)
                elif opcode == 0xFD:  # REVERT
                    off, size = pop(), pop()
                    return Receipt(False, mread(off, size), gas - gas_left, "revert")
                elif opcode == 0xFE:
                    raise EvmError("invalid opcode 0xfe")
                else:  # pragma: no cover
                    raise EvmError(f"unhandled opcode {opcode:#04x}")
                pc += 1
            return Receipt(True, b"", gas - gas_left, logs=logs)
        except OutOfGas as e:
            return Receipt(False, b"", gas, str(e))
        except EvmError as e:
            return Receipt(False, b"", gas - max(gas_left, 0), str(e))


def _sgn(v: int) -> int:
    return v - (1 << 256) if v & _SIGN_BIT else v


def _jumpdests(code: bytes) -> set[int]:
    out = set()
    pc = 0
    while pc < len(code):
        opcode = code[pc]
        if opcode == 0x5B:
            out.add(pc)
        if 0x60 <= opcode <= 0x7F:
            pc += opcode - 0x5F
        pc += 1
    return out
