"""In-process development chain — the rebuild's Anvil analog.

The reference's chain-integration tests spawn a real Anvil devnet
in-process (client/src/lib.rs:185-221, client/src/utils.rs:169-206);
this image ships no Ethereum node and no web3, but the repo has its own
EVM (evm/machine.py), so the dev chain runs on that: deploy contracts,
send transactions with a real ``msg.sender``, collect event logs per
block, and answer the narrow JSON-RPC-shaped queries the node's event
source needs (eth_blockNumber / eth_getLogs).

Ships a hand-assembled AttestationStation runtime with the reference
registry's exact external surface — ``attest(AttestationData[])``
batches under selector 0x5eb5ea10 emitting
``AttestationCreated(address indexed, address indexed, bytes32 indexed,
bytes)`` (contracts/AttestationStation.sol; the event log is the
protocol's entire transport, SURVEY.md L5).  Storage keeps one word
per (creator, about, key): keccak(val) at the Solidity-shaped nested
mapping slot — a documented deviation (the protocol never reads the
getter; nodes replay events).
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field

from ..crypto.keccak import keccak256
from .machine import EVM, Receipt

#: keccak("AttestationCreated(address,address,bytes32,bytes)")
ATTESTATION_CREATED_TOPIC = int.from_bytes(
    keccak256(b"AttestationCreated(address,address,bytes32,bytes)"), "big"
)
ATTEST_SELECTOR = 0x5EB5EA10  # reference att_station.rs:54


# ---------------------------------------------------------------------------
# Minimal assembler
# ---------------------------------------------------------------------------

_OPS = {
    "STOP": 0x00, "ADD": 0x01, "MUL": 0x02, "SUB": 0x03, "DIV": 0x04,
    "LT": 0x10, "GT": 0x11, "EQ": 0x14, "ISZERO": 0x15, "AND": 0x16,
    "SHL": 0x1B, "SHR": 0x1C, "KECCAK256": 0x20, "CALLER": 0x33,
    "CALLDATALOAD": 0x35, "CALLDATASIZE": 0x36, "CALLDATACOPY": 0x37,
    "EXTCODESIZE": 0x3B, "GAS": 0x5A,
    "POP": 0x50, "MLOAD": 0x51, "MSTORE": 0x52, "SLOAD": 0x54,
    "SSTORE": 0x55, "JUMP": 0x56, "JUMPI": 0x57, "JUMPDEST": 0x5B,
    "LOG2": 0xA2, "LOG4": 0xA4, "STATICCALL": 0xFA,
    "RETURN": 0xF3, "REVERT": 0xFD,
    "DUP1": 0x80, "DUP2": 0x81, "DUP3": 0x82, "DUP4": 0x83, "DUP5": 0x84,
    "SWAP1": 0x90, "SWAP2": 0x91, "SWAP3": 0x92,
}


def assemble(items: list) -> bytes:
    """Tiny two-pass assembler: ints become minimal PUSHes, strings are
    opcodes, ("label", name) marks a JUMPDEST, ("ref", name) pushes its
    address (2 bytes)."""
    # Pass 1: layout.
    size = 0
    labels: dict[str, int] = {}
    for it in items:
        if isinstance(it, tuple) and it[0] == "label":
            labels[it[1]] = size
            size += 1  # JUMPDEST
        elif isinstance(it, tuple) and it[0] == "ref":
            size += 3  # PUSH2 xx xx
        elif isinstance(it, int):
            n = max(1, (it.bit_length() + 7) // 8)
            size += 1 + n
        else:
            size += 1
    out = bytearray()
    for it in items:
        if isinstance(it, tuple) and it[0] == "label":
            out.append(0x5B)
        elif isinstance(it, tuple) and it[0] == "ref":
            out.append(0x61)
            out += labels[it[1]].to_bytes(2, "big")
        elif isinstance(it, int):
            n = max(1, (it.bit_length() + 7) // 8)
            out.append(0x5F + n)
            out += it.to_bytes(n, "big")
        else:
            out.append(_OPS[it])
    return bytes(out)


def attestation_station_runtime() -> bytes:
    """The AttestationStation runtime, assembled directly (no solc in
    the image).  Memory map: 0x00..0x40 scratch for slot hashing,
    0x40 event-data ABI head (offset word), 0x60 val length, 0x80+ val
    bytes."""
    a: list = []
    E = a.extend

    # selector check: calldataload(0) >> 224 == ATTEST_SELECTOR
    E([0, "CALLDATALOAD", 224, "SHR", ATTEST_SELECTOR, "EQ", ("ref", "ok"), "JUMPI"])
    E([0, 0, "REVERT", ("label", "ok")])
    # arr = 4 + calldataload(4)  (absolute offset of the length word)
    E([4, "CALLDATALOAD", 4, "ADD"])          # stack: [arr]
    # i = 0
    E([0])                                     # stack: [arr, i]

    E([("label", "loop")])
    # if i >= n: done    (n = calldataload(arr))
    E(["DUP1", "DUP3", "CALLDATALOAD", "GT", "ISZERO", ("ref", "done"), "JUMPI"])
    # elem = arr + 32 + calldataload(arr + 32 + 32*i)
    E(["DUP1", 32, "MUL", "DUP3", "ADD", 32, "ADD"])       # [arr, i, p] p = arr+32+32i
    E(["CALLDATALOAD", "DUP3", "ADD", 32, "ADD"])          # [arr, i, elem]
    # about = calldataload(elem); key = calldataload(elem+32)
    # val_off = elem + calldataload(elem+64); val_len = calldataload(val_off)
    E(["DUP1", 64, "ADD", "CALLDATALOAD", "DUP2", "ADD"])  # [arr, i, elem, vptr]
    E(["DUP1", "CALLDATALOAD"])                            # [arr, i, elem, vptr, vlen]
    # memory: 0x40 <- 0x20 (abi head), 0x60 <- vlen, 0x80.. <- val bytes
    E([0x20, 0x40, "MSTORE"])
    E(["DUP1", 0x60, "MSTORE"])
    E(["DUP1", "DUP3", 32, "ADD", 0x80, "CALLDATACOPY"])   # calldatacopy(0x80, vptr+32, vlen)
    # zero the ABI padding (a shorter val must not leak the previous
    # iteration's bytes): mstore(0x80+vlen, 0)
    E([0, "DUP2", 0x80, "ADD", "MSTORE"])
    # event data size = 0x40 + ceil32(vlen)   (DIV pops numerator first)
    E([32, "DUP2", 31, "ADD", "DIV", 32, "MUL", 0x40, "ADD"])  # [.., vptr, vlen, dsize]
    # topics: key, about, caller, sig  (LOG4 pops topics in order t1..t4
    # after off/size: off, size, t1(sig), t2(creator), t3(about), t4(key))
    E(["DUP4", 32, "ADD", "CALLDATALOAD"])                 # key   [.., dsize, key]
    E(["DUP5", "CALLDATALOAD"])                            # about [.., dsize, key, about]
    E(["CALLER"])                                          # [.., dsize, key, about, caller]
    E([ATTESTATION_CREATED_TOPIC])                         # [.., key, about, caller, sig]
    E(["DUP5", 0x40, "LOG4"])                              # log4(0x40, dsize, sig, caller, about, key)
    # storage: slot = keccak(key ++ keccak(about ++ keccak(caller ++ 0)));
    # elem sits 4th from the top throughout ([.., elem, vptr, vlen, dsize]).
    E(["CALLER", 0x00, "MSTORE", 0, 0x20, "MSTORE", 64, 0x00, "KECCAK256"])
    E([0x20, "MSTORE", "DUP4", "CALLDATALOAD", 0x00, "MSTORE", 64, 0x00, "KECCAK256"])
    E([0x20, "MSTORE", "DUP4", 32, "ADD", "CALLDATALOAD", 0x00, "MSTORE", 64, 0x00, "KECCAK256"])
    # value word = keccak(val bytes)
    E(["SWAP1", "POP"])                                    # drop dsize: [arr, i, elem, vptr, vlen, slot]
    E(["SWAP1", 0x80, "KECCAK256"])                        # keccak(mem[0x80:0x80+vlen]) -> [.., vptr? ...]
    E(["SWAP1", "SSTORE"])                                 # sstore(slot, hash)
    # pop vptr, elem; i += 1
    E(["POP", "POP"])
    E([1, "ADD"])                                          # [arr, i+1]
    E([("ref", "loop"), "JUMP"])

    E([("label", "done"), "STOP"])
    return assemble(a)


#: keccak4("verify(uint256[5],bytes)") — EtVerifierWrapper.sol:26-28.
VERIFY_SELECTOR = int.from_bytes(
    keccak256(b"verify(uint256[5],bytes)")[:4], "big"
)
#: keccak("Verified(address)") — EtVerifierWrapper.sol:20.
VERIFIED_TOPIC = int.from_bytes(keccak256(b"Verified(address)"), "big")


def et_wrapper_runtime(verifier_addr: int) -> bytes:
    """EtVerifierWrapper runtime (contracts/EtVerifierWrapper.sol),
    assembled: ``verify(uint256[5] pubIns, bytes proof)`` unpacks its
    ABI calldata, staticcalls the raw verifier with the packed
    ``pubIns ‖ proof`` payload, reverts when the verifier rejects, and
    emits ``Verified(msg.sender)``."""
    a: list = []
    E = a.extend
    E([0, "CALLDATALOAD", 224, "SHR", VERIFY_SELECTOR, "EQ", ("ref", "sel"), "JUMPI"])
    E([0, 0, "REVERT", ("label", "sel")])
    # verifier.code.length == 0 -> VerifierMissing (plain revert here)
    E([verifier_addr, "EXTCODESIZE", ("ref", "present"), "JUMPI"])
    E([0, 0, "REVERT", ("label", "present")])
    # mem[0:160] = pubIns; proof tail follows
    E([160, 4, 0, "CALLDATACOPY"])
    # boff = calldataload(164); plen = calldataload(4+boff)
    E([164, "CALLDATALOAD", 4, "ADD"])                      # [pptr] (abs len word)
    E(["DUP1", "CALLDATALOAD"])                             # [pptr, plen]
    E(["DUP1", "DUP3", 32, "ADD", 160, "CALLDATACOPY"])     # copy(160, pptr+32, plen)
    # staticcall(gas, verifier, 0, 160+plen, 0, 0)
    E([0, 0, "DUP3", 160, "ADD", 0, verifier_addr, "GAS", "STATICCALL"])
    E([("ref", "ok"), "JUMPI"])
    E([0, 0, "REVERT", ("label", "ok")])
    E(["CALLER", VERIFIED_TOPIC, 0, 0, "LOG2"])
    E(["STOP"])
    return assemble(a)


# ---------------------------------------------------------------------------
# The chain
# ---------------------------------------------------------------------------


@dataclass
class ChainLog:
    """A mined log: machine.Log plus chain coordinates (the
    eth_getLogs response shape the event source consumes)."""

    address: int
    topics: list[int]
    data: bytes
    block_number: int
    tx_index: int


@dataclass
class DevChain:
    """Blocks are one-transaction: every successful transact() mines."""

    evm: EVM = dc_field(default_factory=EVM)
    block_number: int = 0
    logs: list[ChainLog] = dc_field(default_factory=list)

    def deploy_runtime(self, runtime: bytes) -> int:
        addr = self.evm.deploy_runtime(runtime)
        self.block_number += 1
        return addr

    def deploy_attestation_station(self) -> int:
        return self.deploy_runtime(attestation_station_runtime())

    def transact(self, to: int, data: bytes, sender: int) -> Receipt:
        r = self.evm.call(to, data, caller=sender)
        if r.success:
            self.block_number += 1
            for i, log in enumerate(r.logs):
                self.logs.append(
                    ChainLog(
                        address=log.address,
                        topics=log.topics,
                        data=log.data,
                        block_number=self.block_number,
                        tx_index=i,
                    )
                )
        return r

    def call(self, to: int, data: bytes) -> Receipt:
        """eth_call semantics: runs on ephemeral state — storage writes
        are rolled back and no block is mined."""
        snapshot = {a: dict(s) for a, s in self.evm.storage.items()}
        try:
            return self.evm.call(to, data)
        finally:
            self.evm.storage = snapshot

    # -- the JSON-RPC-shaped surface the event source needs -------------

    def eth_block_number(self) -> int:
        return self.block_number

    def eth_get_logs(
        self,
        address: int | None = None,
        from_block: int = 0,
        to_block: int | None = None,
        topic0: int | None = None,
    ) -> list[ChainLog]:
        hi = self.block_number if to_block is None else to_block
        return [
            lg
            for lg in self.logs
            if from_block <= lg.block_number <= hi
            and (address is None or lg.address == address)
            and (topic0 is None or (lg.topics and lg.topics[0] == topic0))
        ]


def _word(x) -> bytes:
    """One ABI word from an int, bytes32, or 0x-hex address string."""
    if isinstance(x, bytes):
        return x.rjust(32, b"\0")
    if isinstance(x, str):
        return bytes.fromhex(x.removeprefix("0x")).rjust(32, b"\0")
    return int(x).to_bytes(32, "big")


def encode_attest_batch(batch: list[tuple]) -> bytes:
    """The canonical ``attest((address,bytes32,bytes)[])`` argument
    encoding (no selector) for (about, key, val) triples — shared by
    the dev chain tests and the client's chain submission so the ABI
    layout has exactly one definition (att_station.rs:54 parity)."""
    n = len(batch)
    body = _word(n)
    offsets = []
    elems = []
    off = 32 * n
    for about, key, val in batch:
        offsets.append(off)
        pad = (-len(val)) % 32
        elem = (
            _word(about) + _word(key) + _word(0x60) + _word(len(val)) + val + b"\0" * pad
        )
        elems.append(elem)
        off += len(elem)
    body += b"".join(_word(o) for o in offsets) + b"".join(elems)
    return _word(0x20) + body


def encode_attest_calldata(batch: list[tuple]) -> bytes:
    """Selector-prefixed attest() calldata for the dev chain."""
    return ATTEST_SELECTOR.to_bytes(4, "big") + encode_attest_batch(batch)
